"""Minimal asyncio HTTP/1.1 client for INTERNAL hops between this
build's own servers (s3 -> filer, filer -> master/volume).

The gateway hot path chains three asyncio services on one box; a
full-featured client (aiohttp ~125us/call measured, sync `requests`
worse plus a thread hop) pays for cookies, redirects, chunked decode,
multidicts and timer machinery that server-to-server calls between our
own processes never use. This pool speaks exactly the subset those
servers emit — Content-Length-framed HTTP/1.1 over keep-alive
connections — for ~4x less per-call overhead.

The reference leans on compiled gRPC for the same internal hops
(filer_server_handlers_write_autochunk.go -> AssignVolume ->
volume upload); this is the asyncio-native answer. NOT for talking to
arbitrary external endpoints — cloud sinks/remotes keep their real
clients.
"""
from __future__ import annotations

import asyncio
import json as _json
import time as _time
import urllib.parse

from ..utils import faults, retry, tracing


class RequestError(OSError):
    """Transport failure with enough context for the retry layer.

    ``progress`` — at least one response byte arrived (the server may
    have executed the request; never blind-replay).  ``timed_out`` —
    the attempt hit its timeout (same "can't prove it didn't run"
    reasoning).  A failure with neither flag is connection-level: the
    request provably never ran and is safe to replay.
    """

    def __init__(self, msg: str, *, progress: bool = False,
                 timed_out: bool = False):
        super().__init__(msg)
        self.progress = progress
        self.timed_out = timed_out

    @property
    def conn_failure(self) -> bool:
        return not self.progress and not self.timed_out


class Response:
    """requests-shaped view: .status_code / .content / .text / .json()
    / .headers (case-insensitive get via lowercase keys)."""
    __slots__ = ("status_code", "content", "_headers")

    def __init__(self, status: int, content: bytes,
                 headers: dict[str, str]):
        self.status_code = status
        self.content = content
        self._headers = headers  # keys lowercased at parse time

    @property
    def text(self) -> str:
        return self.content.decode("utf-8", "replace")

    def json(self):
        return _json.loads(self.content)

    @property
    def headers(self) -> "Response._CI":
        return Response._CI(self._headers)

    class _CI:
        __slots__ = ("_d",)

        def __init__(self, d):
            self._d = d

        def get(self, k, default=None):
            return self._d.get(k.lower(), default)

        def __contains__(self, k):
            return k.lower() in self._d

        def __getitem__(self, k):
            return self._d[k.lower()]

        def items(self):
            return self._d.items()


class HttpPool:
    """Keep-alive connection pool, one per event loop consumer."""

    def __init__(self, timeout: float = 120.0, per_host: int = 32):
        self.timeout = timeout
        self.per_host = per_host
        self._idle: dict[tuple[str, int], list] = {}

    async def _connect(self, host: str, port: int):
        reader, writer = await asyncio.open_connection(host, port)
        sock = writer.get_extra_info("socket")
        if sock is not None:
            import socket as _s

            sock.setsockopt(_s.IPPROTO_TCP, _s.TCP_NODELAY, 1)
        return reader, writer

    def _put_idle(self, key, conn) -> None:
        pool = self._idle.setdefault(key, [])
        if len(pool) < self.per_host:
            pool.append(conn)
        else:
            conn[1].close()

    async def request(self, method: str, url: str, *,
                      params: dict | None = None,
                      headers: dict | None = None,
                      data: bytes | None = None,
                      json=None,
                      idempotent: bool | None = None) -> Response:
        """One logical call: RetryPolicy loop (capped exp backoff, full
        jitter) around single attempts, consulting the peer's circuit
        breaker, carrying the ambient deadline on X-Sw-Deadline, and
        recorded as a client span when called under an active trace.

        ``idempotent`` marks non-GET internal calls that are safe to
        replay (e.g. an assign, a lookup POST); unmarked writes only
        retry when the failure proves the request never ran (connection
        -level error with zero response bytes, or a 503 carrying
        X-Sw-Retryable)."""
        peer = urllib.parse.urlsplit(url).netloc
        breaker = retry.breaker_for(peer)
        pol = retry.policy()
        last_exc: Exception | None = None
        resp: Response | None = None
        for attempt in range(pol.max_attempts):
            if attempt:
                await asyncio.sleep(pol.backoff(attempt))
            retry.check_deadline()
            if not breaker.allow():
                raise retry.BreakerOpenError(peer, breaker.retry_after())
            try:
                await faults.async_hook("fastclient", method)
                resp = await self._traced(method, url, peer,
                                          params=params, headers=headers,
                                          data=data, json=json)
            except faults.FaultInjected as e:
                # injected before any bytes moved: replayable by design,
                # but NOT a real peer failure — don't poison the breaker
                # (a held half-open probe slot is handed back, though)
                breaker.release_probe()
                last_exc = e
                if pol.should_retry(attempt, method, idempotent=idempotent,
                                    conn_failure=True):
                    continue
                raise
            except RequestError as e:
                last_exc = e
                if e.conn_failure:
                    breaker.record_failure()
                else:
                    # progress/timeout: outcome unproven — settle a held
                    # probe back to open instead of leaking the slot
                    breaker.probe_inconclusive()
                if pol.should_retry(attempt, method, idempotent=idempotent,
                                    conn_failure=e.conn_failure):
                    continue
                raise
            breaker.record_success()
            retryable = (resp.status_code == 503 and
                         retry.RETRYABLE_HEADER.lower() in resp._headers)
            if retryable or resp.status_code in (502, 503, 504):
                if pol.should_retry(attempt, method, idempotent=idempotent,
                                    status=resp.status_code,
                                    retryable_response=retryable):
                    continue
            return resp
        if resp is not None:
            return resp
        raise last_exc  # type: ignore[misc]

    async def _traced(self, method: str, url: str, peer: str, *,
                      params, headers, data, json) -> Response:
        hdrs = dict(headers or {})
        retry.inject(hdrs)
        if tracing.current() is None:
            return await self._request(method, url, params=params,
                                       headers=hdrs, data=data,
                                       json=json)
        with tracing.span(f"{method} {peer}", kind="client",
                          peer=peer) as rec:
            tracing.inject(hdrs)
            resp = await self._request(method, url, params=params,
                                       headers=hdrs, data=data,
                                       json=json)
            rec["status"] = str(resp.status_code)
            return resp

    async def _request(self, method: str, url: str, *,
                       params: dict | None = None,
                       headers: dict | None = None,
                       data: bytes | None = None,
                       json=None) -> Response:
        """Retries on a dead keep-alive conn only when
        no response byte arrived AND the failure was connection-level —
        once bytes show up (or on a timeout, where we can't prove they
        didn't) the server may have executed the request, so retrying a
        non-idempotent internal call could apply it twice."""
        parts = urllib.parse.urlsplit(url)
        host = parts.hostname or "127.0.0.1"
        port = parts.port or 80
        path = parts.path or "/"
        query = parts.query
        if params:
            extra = urllib.parse.urlencode(params)
            query = f"{query}&{extra}" if query else extra
        if query:
            path = f"{path}?{query}"
        body = data if data is not None else b""
        hdrs = dict(headers or {})
        if json is not None:
            body = _json.dumps(json).encode()
            hdrs.setdefault("Content-Type", "application/json")
        head = (f"{method} {path} HTTP/1.1\r\n"
                f"Host: {parts.netloc}\r\n"
                f"Content-Length: {len(body)}\r\n")
        for k, v in hdrs.items():
            head += f"{k}: {v}\r\n"
        # large payloads (streamed-PUT chunk uploads) ship as a second
        # write instead of being concatenated into one blob: the head+
        # body copy measured as a full extra memcpy of every 8MB chunk
        # on the filer streaming path
        if len(body) > (256 << 10):
            blob = (head.encode() + b"\r\n", body)
        else:
            blob = (head.encode() + b"\r\n" + body,)
        key = (host, port)
        # one attempt's wire budget: the pool timeout clipped to what
        # is left of the overall deadline the edge minted — tracked as
        # an ABSOLUTE deadline so the stale-conn drain loop below can't
        # grant each dial/roundtrip a fresh full budget and overrun the
        # remaining deadline several-fold
        budget = self.timeout
        rem = retry.remaining()
        if rem is not None:
            if rem <= 0:
                raise retry.DeadlineExceeded(f"{method} {url}")
            budget = min(budget, rem)
        attempt_deadline = _time.monotonic() + budget
        last: Exception | None = None
        saw_progress = False
        timed_out = False
        # every pooled conn may be stale after an idle gap longer than
        # the server keepalive: drain through them and ALWAYS end on a
        # freshly-dialed attempt before declaring failure
        for _ in range(self.per_host + 1):
            timeout = attempt_deadline - _time.monotonic()
            if timeout <= 0:
                if retry.expired():
                    raise retry.DeadlineExceeded(f"{method} {url}")
                break  # attempt budget spent — report the last failure
            pool = self._idle.get(key)
            fresh = not pool
            if pool:
                conn = pool.pop()
            else:
                # a refused/timed-out dial is the canonical replayable
                # failure (zero request bytes sent) AND the breaker's
                # trip signal — surface it as such, not as a raw OSError
                try:
                    conn = await asyncio.wait_for(
                        self._connect(host, port), timeout)
                except (OSError, asyncio.TimeoutError) as e:
                    raise RequestError(
                        f"fastclient {method} {url}: connect: {e!r}") from e
            progress = [False]  # set once any response byte is read
            try:
                return await asyncio.wait_for(
                    self._roundtrip(conn, key, blob, method, progress),
                    timeout)
            except (OSError, asyncio.IncompleteReadError,
                    asyncio.LimitOverrunError, asyncio.TimeoutError,
                    ValueError) as e:
                conn[1].close()
                last = e
                saw_progress = progress[0]
                timed_out = isinstance(e, asyncio.TimeoutError)
                if progress[0] or isinstance(
                        e, (asyncio.TimeoutError,
                            # an oversized head means bytes DID arrive
                            asyncio.LimitOverrunError)):
                    break  # server may have executed it — never re-send
                if fresh:
                    break  # a brand-new conn failing is a real error
        raise RequestError(f"fastclient {method} {url}: {last}",
                           progress=saw_progress or isinstance(
                               last, asyncio.LimitOverrunError),
                           timed_out=timed_out)

    async def _roundtrip(self, conn, key, blob: tuple,
                         method: str, progress: list) -> Response:
        reader, writer = conn
        for part in blob:
            writer.write(part)
        await writer.drain()
        # response head
        try:
            raw = await reader.readuntil(b"\r\n\r\n")
        except asyncio.IncompleteReadError as e:
            if e.partial:
                progress[0] = True
            raise
        progress[0] = True
        lines = raw.decode("latin-1").split("\r\n")
        status = int(lines[0].split(" ", 2)[1])
        headers: dict[str, str] = {}
        for line in lines[1:]:
            if not line:
                continue
            k, _, v = line.partition(":")
            headers[k.strip().lower()] = v.strip()
        te = headers.get("transfer-encoding", "")
        if method == "HEAD" or status in (204, 304) or status < 200:
            # bodyless by protocol (a HEAD's Content-Length describes
            # the body it does NOT send)
            body = b""
            te = ""
        elif "chunked" in te:
            # our servers CL-frame everything; decode chunked anyway so
            # an unexpected streamed response degrades, not corrupts
            chunks = []
            while True:
                size_line = await reader.readuntil(b"\r\n")
                size = int(size_line.strip().split(b";")[0], 16)
                if size == 0:
                    await reader.readuntil(b"\r\n")
                    break
                chunks.append(await reader.readexactly(size))
                await reader.readexactly(2)
            body = b"".join(chunks)
        else:
            cl = headers.get("content-length")
            if cl is None:
                raise ValueError("response without framing")
            body = await reader.readexactly(int(cl))
        if headers.get("connection", "").lower() == "close":
            writer.close()
        else:
            self._put_idle(key, conn)
        return Response(status, body, headers)

    async def close(self) -> None:
        for pool in self._idle.values():
            for _r, w in pool:
                w.close()
        self._idle.clear()
