"""Span pusher: ships finished spans to the master's trace collector.

Every server (volume, filer, S3, WebDAV) registers one SpanPusher as a
tracing sink; finished span records land in a bounded queue and a
daemon thread batches them to ``POST /cluster/traces/push`` on the
master over the shared pooled client (so pushes ride the same
breaker/retry/deadline layer as all other internal hops — and produce
no spans of their own, since the pusher thread carries no trace
context).

Head sampling happens here, at enqueue time, via the deterministic
per-trace verdict in `utils.tracing.sample_decision`: every process
reaches the same keep/drop decision for a given trace-id, so a sampled
trace arrives complete from all hops. A sampled-out span is *skipped*,
not dropped — ``trace_spans_dropped_total`` counts only real loss
(queue overflow / push give-up), so zero drops at any sample rate
means the collector saw everything it was meant to see.

One tail-sampling exception: spans slower than ``-trace.slowThreshold``
are pushed even when head sampling drops their trace (counted by
``trace_push_tail_kept_total``), so a 1% sample rate still surfaces
every slow outlier.
"""
from __future__ import annotations

import threading
from collections import deque

from ..utils import glog, metrics, retry, tracing

BATCH_SIZE = 128          # spans per push
FLUSH_INTERVAL = 2.0      # seconds between pushes when below BATCH_SIZE
QUEUE_MAX = 4096          # bounded backlog while the master is away


def master_from_filer(filer_url: str, timeout: float = 5.0) -> str:
    """Resolve the master address from a filer's /status (the S3 and
    WebDAV gateways only know their filer)."""
    from . import httpclient

    r = httpclient.session().get(
        filer_url.rstrip("/") + "/status", timeout=timeout)
    r.raise_for_status()
    m = str(r.json().get("master", ""))
    if not m:
        raise ValueError(f"no master in {filer_url}/status")
    if not m.startswith("http"):
        m = "http://" + m
    return m


class SpanPusher:
    """Batches finished spans from the tracing ring to the master.

    ``master_url`` may be a string or a zero-arg callable resolved on
    every flush (gateways re-resolve through their filer so a master
    failover doesn't orphan the pusher).
    """

    def __init__(self, master_url, service: str, instance: str, *,
                 batch_size: int = BATCH_SIZE,
                 interval: float = FLUSH_INTERVAL,
                 queue_max: int = QUEUE_MAX):
        self._master_url = master_url
        self.service = service
        self.instance = instance
        self.batch_size = max(1, int(batch_size))
        self.interval = float(interval)
        self.queue_max = max(self.batch_size, int(queue_max))
        self._q: deque = deque()
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._dropped_unreported = 0

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        tracing.add_sink(self._enqueue)
        self._thread = threading.Thread(
            target=self._loop, name=f"span-push-{self.service}",
            daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        """Unregister the sink, flush what's queued, join the thread.
        Idempotent; safe to call before start()."""
        tracing.remove_sink(self._enqueue)
        thread = self._thread
        self._thread = None
        self._stop.set()
        self._wake.set()
        if thread is not None:
            thread.join(timeout)

    # -- sink -----------------------------------------------------------

    def _enqueue(self, rec: dict) -> None:
        if not tracing.sample_decision(rec.get("trace_id", "")):
            # keep-if-slow tail pass: a span over -trace.slowThreshold
            # is pushed even when head sampling dropped its trace, so
            # low sample rates still surface every slow outlier (the
            # rest of that trace stays sampled out — the collector gets
            # a partial trace, flagged by the counter)
            thresh = tracing.slow_threshold()
            try:
                duration = float(rec.get("duration") or 0.0)
            except (TypeError, ValueError):
                duration = 0.0
            if thresh <= 0 or duration < thresh:
                return  # sampled out everywhere — not a drop
            metrics.counter_add("trace_push_tail_kept_total", 1)
        with self._lock:
            if len(self._q) >= self.queue_max:
                self._q.popleft()
                self._dropped_unreported += 1
                metrics.counter_add("trace_spans_dropped_total", 1)
            self._q.append(rec)
            full = len(self._q) >= self.batch_size
        if full:
            self._wake.set()

    # -- push loop ------------------------------------------------------

    def _resolve(self) -> str:
        url = self._master_url
        if callable(url):
            url = url()
        return str(url).rstrip("/")

    def _take_batch(self) -> tuple[list[dict], int]:
        with self._lock:
            n = min(len(self._q), self.batch_size)
            batch = [self._q.popleft() for _ in range(n)]
            dropped = self._dropped_unreported
            self._dropped_unreported = 0
        return batch, dropped

    def _requeue(self, batch: list[dict], dropped: int) -> None:
        with self._lock:
            self._dropped_unreported += dropped
            for rec in reversed(batch):
                if len(self._q) >= self.queue_max:
                    self._dropped_unreported += 1
                    metrics.counter_add("trace_spans_dropped_total", 1)
                    break
                self._q.appendleft(rec)

    def _push(self, batch: list[dict], dropped: int) -> bool:
        from . import httpclient

        try:
            url = self._resolve()
        except Exception:
            return False
        payload = {"instance": self.instance, "service": self.service,
                   "spans": batch, "dropped": dropped}
        try:
            r = httpclient.session().post(
                url + "/cluster/traces/push", json=payload,
                timeout=(5.0, 10.0))
        except retry.BreakerOpenError:
            return False
        except Exception as e:
            glog.v(2, "span push to %s failed: %s", url, e)
            return False
        if r.status_code >= 300:
            return False
        metrics.counter_add("trace_spans_pushed_total", len(batch))
        return True

    def flush(self) -> bool:
        """One push attempt; failed batches requeue (bounded). -> did
        everything queued at entry get delivered."""
        ok = True
        while True:
            batch, dropped = self._take_batch()
            if not batch and not dropped:
                return ok
            if not self._push(batch, dropped):
                self._requeue(batch, dropped)
                return False

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.interval)
            self._wake.clear()
            self.flush()
        self.flush()  # final drain on shutdown
