"""HTTP/WebSocket RPC substrate.

The reference talks gRPC (control) + HTTP (data) over DCN
(/root/reference/weed/pb/*.proto). This build keeps the same process
topology but speaks JSON-over-HTTP for control verbs and WebSockets for
the three long-lived streams (heartbeat master.proto:10, KeepConnected
:12, metadata subscribe filer.proto:57-60) — idiomatic for the asyncio
server stack, zero codegen, and debuggable with curl. Data bytes ride
plain HTTP exactly like the reference.
"""
from __future__ import annotations

import asyncio
import json
import threading
from typing import Any, Awaitable, Callable

from aiohttp import web


def json_ok(data: Any = None, **extra) -> web.Response:
    body = dict(data or {})
    body.update(extra)
    return web.json_response(body)


def json_error(msg: str, status: int = 400) -> web.Response:
    return web.json_response({"error": msg}, status=status)


class ServerThread:
    """Run an aiohttp app on its own event loop in a daemon thread —
    lets a whole cluster (master + volumes + filer + s3) live in one
    process for tests and `weed server`-style combined startup."""

    def __init__(self, app_factory: Callable[[], Awaitable[web.Application]]
                 | web.Application, host: str = "127.0.0.1", port: int = 0,
                 ssl_context=None):
        self._app_factory = app_factory
        self.host = host
        self.port = port
        self.ssl_context = ssl_context
        self.loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._started = threading.Event()
        self._runner: web.AppRunner | None = None
        self.app: web.Application | None = None

    def start(self) -> "ServerThread":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=30):
            raise TimeoutError("server failed to start")
        return self

    def _run(self) -> None:
        self.loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self.loop)
        self.loop.run_until_complete(self._serve())
        self.loop.run_forever()

    async def _serve(self) -> None:
        app = self._app_factory
        if not isinstance(app, web.Application):
            app = await app()
        self.app = app
        # bound shutdown: a lingering client connection (e.g. a
        # subscriber websocket) must not stall process exit.
        # access_log=None: even a level-suppressed access logger costs
        # a logging call per request — glog -v is the observability
        # path here, like the reference's glog
        self._runner = web.AppRunner(app, shutdown_timeout=2.0,
                                     access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port,
                           ssl_context=self.ssl_context)
        await site.start()
        # resolve ephemeral port
        server = site._server
        if server and server.sockets:
            self.port = server.sockets[0].getsockname()[1]
        self._started.set()

    @property
    def url(self) -> str:
        scheme = "https" if self.ssl_context is not None else "http"
        return f"{scheme}://{self.host}:{self.port}"

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def call_soon(self, coro) -> None:
        asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self) -> None:
        if self.loop is None:
            return

        async def _shutdown():
            if self._runner is not None:
                await self._runner.cleanup()
            self.loop.stop()

        asyncio.run_coroutine_threadsafe(_shutdown(), self.loop)
        if self._thread is not None:
            self._thread.join(timeout=10)


def run_apps_forever(servers: list[ServerThread]) -> None:
    import time

    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        for s in servers:
            s.stop()


def parse_json_body(text: str) -> dict:
    try:
        v = json.loads(text) if text else {}
    except json.JSONDecodeError as e:
        raise ValueError(f"bad json body: {e}") from e
    if not isinstance(v, dict):
        raise ValueError("json body must be an object")
    return v


def debug_index_factory(service: str, endpoints: dict[str, str]):
    """GET /debug — one self-describing index of a server's debug
    surface, so operators need no tribal knowledge of paths. Every
    server registers this with its own {path: one-line description}
    map; ?format=text renders a plain listing for terminals."""
    listing = dict(sorted(endpoints.items()))

    async def handle(request: web.Request) -> web.Response:
        if request.query.get("format") == "text":
            width = max(len(p) for p in listing)
            lines = [f"{service} debug endpoints:"] + [
                f"  {path.ljust(width)}  {desc}"
                for path, desc in listing.items()]
            return web.Response(text="\n".join(lines) + "\n",
                                content_type="text/plain")
        return web.json_response({"service": service,
                                  "endpoints": listing})
    return handle
