"""Shared pooled HTTP client for sync (requests-based) call sites.

The reference reuses net/http's connection pool everywhere
(util/http_client pooling); bare `requests.get` opens and tears down a
TCP connection per call, which dominated the data-plane benchmark
(assign+upload+read all paid a fresh handshake). One Session per
thread (requests Sessions aren't documented thread-safe) with a wide
urllib3 pool gives keep-alive across all client verbs.

Failure handling routes through utils/retry.py: connect failures obey
the shared RetryPolicy (full-jitter backoff) instead of urllib3's bare
``max_retries=1`` int, every request carries the ambient deadline on
X-Sw-Deadline, every peer consults its circuit breaker, and every call
gets an explicit timeout (DEFAULT_TIMEOUT unless the caller passes
one) — an untimed sync call in a server thread pool is how one dead
peer wedges the whole pool.
"""
from __future__ import annotations

import os
import threading
import urllib.parse

import requests

from ..utils import faults, retry, tracing

_local = threading.local()

# applied when a call site passes no timeout; (connect, read) so a
# black-holed peer fails in seconds while long reads still stream
DEFAULT_TIMEOUT = (5.0, 60.0)


def _is_connect_failure(exc: Exception) -> bool:
    """Did this requests.ConnectionError happen before any request
    byte left (dial refused / unreachable / connect timeout)?  urllib3
    folds both connect-phase and mid-stream failures into the same
    requests exception type, so classify by the wrapped reason."""
    seen = set()
    e: BaseException | None = exc
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        name = type(e).__name__
        if name in ("NewConnectionError", "ConnectTimeoutError"):
            return True
        if isinstance(e, ConnectionRefusedError):
            return True
        # MaxRetryError keeps the reason as an attribute, not a cause
        nxt = getattr(e, "reason", None)
        e = nxt if isinstance(nxt, BaseException) else \
            (e.__cause__ or e.__context__)
    return "Connection refused" in str(exc)


class TracingSession(requests.Session):
    """Session that joins the active trace and the fault-tolerance
    layer: each request records a client span (when a trace context is
    set — contextvars survive the sync call sites in
    operation/verbs.py and the servers' thread-pool hops via
    asyncio.to_thread), carries traceparent + X-Sw-Deadline
    downstream, consults the peer's circuit breaker, and retries
    connection-level failures per the shared RetryPolicy."""

    def request(self, method, url, **kw):  # type: ignore[override]
        if kw.get("timeout") is None:
            kw["timeout"] = DEFAULT_TIMEOUT
        rem = retry.remaining()
        if rem is not None:
            if rem <= 0:
                raise retry.DeadlineExceeded(f"{method} {url}")
            to = kw["timeout"]
            if isinstance(to, tuple):
                kw["timeout"] = (min(to[0], rem), min(to[1], rem))
            else:
                kw["timeout"] = min(to, rem)
        headers = dict(kw.get("headers") or {})
        retry.inject(headers)
        kw["headers"] = headers
        if tracing.current() is None:
            return self._retrying(method, url, **kw)
        peer = urllib.parse.urlsplit(url).netloc
        with tracing.span(f"{method} {peer}", kind="client",
                          peer=peer) as rec:
            tracing.inject(headers)
            resp = self._retrying(method, url, **kw)
            rec["status"] = str(resp.status_code)
            return resp

    def _retrying(self, method, url, **kw):
        """RetryPolicy loop around single sends.  Only provably-unsent
        requests replay: requests.ConnectionError from urllib3 means
        the transport failed before a response line (urllib3 raises
        ProtocolError for mid-response drops, which surfaces the same
        way — so non-idempotent methods additionally require the
        breaker-style 503 + X-Sw-Retryable attestation to replay)."""
        import time as _time

        peer = urllib.parse.urlsplit(url).netloc
        breaker = retry.breaker_for(peer)
        pol = retry.policy()
        last_exc: Exception | None = None
        resp = None
        for attempt in range(pol.max_attempts):
            if attempt:
                _time.sleep(pol.backoff(attempt))
            retry.check_deadline()
            if not breaker.allow():
                raise retry.BreakerOpenError(peer, breaker.retry_after())
            try:
                faults.sync_hook("httpclient", method)
                resp = super().request(method, url, **kw)
            except faults.FaultInjected as e:
                # the fault fired before any bytes moved: the peer was
                # never contacted, so hand a held probe slot back
                breaker.release_probe()
                last_exc = e
                if pol.should_retry(attempt, method, conn_failure=True):
                    continue
                raise
            except requests.exceptions.ConnectionError as e:
                # connect-phase failures (refused/unreachable/connect
                # timeout) provably never sent the request — replayable
                # and the breaker's trip signal; a mid-stream drop is
                # neither (the server may have executed the request)
                connect_phase = _is_connect_failure(e)
                if connect_phase:
                    breaker.record_failure()
                else:
                    # unproven outcome: a held probe must still settle
                    breaker.probe_inconclusive()
                last_exc = e
                if pol.should_retry(attempt, method,
                                    conn_failure=connect_phase):
                    continue
                raise
            except requests.exceptions.Timeout:
                # can't prove the server didn't execute it: no replay —
                # but settle a held probe so the slot never leaks
                breaker.probe_inconclusive()
                raise
            breaker.record_success()
            retryable = (resp.status_code == 503 and
                         retry.RETRYABLE_HEADER in resp.headers)
            if retryable or resp.status_code in (502, 503, 504):
                if pol.should_retry(attempt, method,
                                    status=resp.status_code,
                                    retryable_response=retryable):
                    # drain the abandoned response back to the pool:
                    # stream=True call sites would otherwise leak one
                    # pooled urllib3 conn per retried attempt, exactly
                    # under the degraded conditions retries fire
                    resp.close()
                    resp = None
                    continue
            return resp
        if resp is not None:
            return resp
        raise last_exc  # type: ignore[misc]


def session() -> requests.Session:
    s = getattr(_local, "session", None)
    if s is None:
        s = TracingSession()
        # cluster-internal traffic: skip the per-request proxy-env
        # scan (getproxies_environment walked os.environ on EVERY
        # call — ~15% of client CPU in the write benchmark).
        # trust_env=False would also drop REQUESTS_CA_BUNDLE, which the
        # TLS story relies on — resolve it once here instead.
        s.trust_env = False
        ca = os.environ.get("REQUESTS_CA_BUNDLE") or \
            os.environ.get("CURL_CA_BUNDLE")
        if ca:
            s.verify = ca
        # connect-retry now lives in TracingSession._retrying (shared
        # RetryPolicy, jittered); urllib3's own Retry stays disabled so
        # a request is never re-sent below the policy's visibility
        adapter = requests.adapters.HTTPAdapter(
            pool_connections=32, pool_maxsize=32, max_retries=0)
        s.mount("http://", adapter)
        s.mount("https://", adapter)
        _local.session = s
    return s
