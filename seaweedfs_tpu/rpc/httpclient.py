"""Shared pooled HTTP client for sync (requests-based) call sites.

The reference reuses net/http's connection pool everywhere
(util/http_client pooling); bare `requests.get` opens and tears down a
TCP connection per call, which dominated the data-plane benchmark
(assign+upload+read all paid a fresh handshake). One Session per
thread (requests Sessions aren't documented thread-safe) with a wide
urllib3 pool gives keep-alive across all client verbs.
"""
from __future__ import annotations

import os
import threading
import urllib.parse

import requests

from ..utils import tracing

_local = threading.local()


class TracingSession(requests.Session):
    """Session that joins the active trace: when a trace context is set
    (contextvars survive the sync call sites in operation/verbs.py and
    the servers' thread-pool hops via asyncio.to_thread), each request
    records a client span and carries its traceparent downstream.
    Outside a trace this adds nothing — no header, no span."""

    def request(self, method, url, **kw):  # type: ignore[override]
        if tracing.current() is None:
            return super().request(method, url, **kw)
        peer = urllib.parse.urlsplit(url).netloc
        with tracing.span(f"{method} {peer}", kind="client",
                          peer=peer) as rec:
            headers = dict(kw.get("headers") or {})
            tracing.inject(headers)
            kw["headers"] = headers
            resp = super().request(method, url, **kw)
            rec["status"] = str(resp.status_code)
            return resp


def session() -> requests.Session:
    s = getattr(_local, "session", None)
    if s is None:
        s = TracingSession()
        # cluster-internal traffic: skip the per-request proxy-env
        # scan (getproxies_environment walked os.environ on EVERY
        # call — ~15% of client CPU in the write benchmark).
        # trust_env=False would also drop REQUESTS_CA_BUNDLE, which the
        # TLS story relies on — resolve it once here instead.
        s.trust_env = False
        ca = os.environ.get("REQUESTS_CA_BUNDLE") or \
            os.environ.get("CURL_CA_BUNDLE")
        if ca:
            s.verify = ca
        # max_retries as an int retries CONNECT failures only (requests
        # builds Retry(n, read=False)), so a request is never sent
        # twice; it papers over transient refused/reset on dial.
        adapter = requests.adapters.HTTPAdapter(
            pool_connections=32, pool_maxsize=32, max_retries=1)
        s.mount("http://", adapter)
        s.mount("https://", adapter)
        _local.session = s
    return s
