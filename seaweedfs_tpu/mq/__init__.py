"""Message queue: brokers, partitioned topics, pub/sub.

Equivalent of /root/reference/weed/mq/ (broker_server.go:32-45,
broker_grpc_pub.go, broker_grpc_sub.go, mq.proto): brokers register in
cluster membership under their own node type, topic configuration and
segment data live in the filer (so brokers are stateless and
restartable), publishers hash keys onto partitions, subscribers replay
from any offset then follow the live tail. The reference marks the
subsystem WIP; the shape here mirrors its architecture with an HTTP
transport.
"""
from .broker import BrokerServer, Topic

__all__ = ["BrokerServer", "Topic"]
