"""Message-queue broker server.

Equivalent of /root/reference/weed/mq/broker/ (broker_server.go,
broker_grpc_pub.go, broker_grpc_sub.go, broker_grpc_configure.go):

- topics are `namespace/name` with a fixed partition count; their
  config is a JSON file in the filer at /topics/<ns>/<name>/topic.conf
  (the reference stores topic.conf via filer too, broker_grpc_configure)
- publish hashes the record key onto a partition (sticky round-robin
  for empty keys) and appends to that partition's log
- partition logs live in the filer as segment files
  /topics/<ns>/<name>/p<k>/seg-<firstOffset> (flushed by size/age, the
  reference's log_buffer flush), with the unflushed tail in broker
  memory — a broker restart replays offsets from the filer
- subscribe streams records from `offset` onward: flushed segments
  first, then the live in-memory tail (long-poll)

Records are JSON: {"o": offset, "ts": ns, "k": key, "v": value}; values
are base64 when not valid UTF-8.
"""
from __future__ import annotations

import asyncio
import base64
import hashlib
import json
import time

import aiohttp
from aiohttp import web

TOPICS_DIR = "/topics"
SEG_FLUSH_RECORDS = 256
SEG_FLUSH_BYTES = 1 << 20
SEG_FLUSH_AGE = 1.0  # seconds


def _enc_value(v: bytes) -> dict:
    try:
        return {"v": v.decode("utf-8")}
    except UnicodeDecodeError:
        return {"v64": base64.b64encode(v).decode()}


def _dec_value(d: dict) -> bytes:
    if "v64" in d:
        return base64.b64decode(d["v64"])
    return d.get("v", "").encode()


class Partition:
    """One partition's log: flushed segments in the filer + memory tail."""

    def __init__(self, dirpath: str, idx: int):
        self.dir = f"{dirpath}/p{idx}"
        self.idx = idx
        self.tail: list[dict] = []      # unflushed records
        self.tail_base = 0              # offset of tail[0]
        self.next_offset = 0
        self.tail_bytes = 0
        self.last_flush = time.monotonic()
        self.lock = asyncio.Lock()
        self.new_data = asyncio.Event()


class Topic:
    def __init__(self, namespace: str, name: str, partitions: int = 4):
        self.namespace = namespace
        self.name = name
        self.partitions = partitions

    @property
    def dir(self) -> str:
        return f"{TOPICS_DIR}/{self.namespace}/{self.name}"

    def conf(self) -> dict:
        return {"namespace": self.namespace, "name": self.name,
                "partitions": self.partitions}


class BrokerServer:
    def __init__(self, filer_url: str, master_url: str = "",
                 announce_pulse: float = 3.0):
        self.filer_url = filer_url.rstrip("/")
        self.master_url = master_url.rstrip("/")
        self.announce_pulse = announce_pulse
        self.address = ""  # set by the runner after the socket binds
        self.topics: dict[tuple[str, str], Topic] = {}
        self.parts: dict[tuple[str, str, int], Partition] = {}
        self._rr = 0
        self._member_task = None
        self._flush_task = None
        self.app = self._build_app()
        self.app.on_startup.append(self._on_startup)
        self.app.on_cleanup.append(self._on_cleanup)

    # -- plumbing -------------------------------------------------------
    def _build_app(self) -> web.Application:
        @web.middleware
        async def error_mw(request, handler):
            try:
                return await handler(request)
            except web.HTTPException:
                raise
            except (json.JSONDecodeError, KeyError, ValueError) as e:
                return web.json_response(
                    {"error": f"bad request: {e}"}, status=400)

        app = web.Application(middlewares=[error_mw])
        app.add_routes([
            web.get("/status", self.handle_status),
            web.get("/topics", self.handle_list_topics),
            web.post("/topics/{ns}/{topic}", self.handle_configure),
            web.get("/topics/{ns}/{topic}", self.handle_describe),
            web.delete("/topics/{ns}/{topic}", self.handle_delete),
            web.post("/topics/{ns}/{topic}/publish",
                     self.handle_publish),
            web.get("/topics/{ns}/{topic}/subscribe",
                    self.handle_subscribe),
        ])
        return app

    async def _on_startup(self, app) -> None:
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=30))
        await self._load_topics()
        self._flush_task = asyncio.create_task(self._flush_loop())
        if self.master_url:
            self._member_task = asyncio.create_task(
                self._membership_loop())

    async def _on_cleanup(self, app) -> None:
        for task in (self._flush_task, self._member_task):
            if task is not None:
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, Exception):
                    pass
        # flush every dirty partition so a clean shutdown loses nothing
        for key, part in list(self.parts.items()):
            topic = self.topics.get(key[:2])
            if topic and part.tail:
                try:
                    await self._flush_partition(part)
                except Exception:
                    pass
        await self._session.close()

    async def _membership_loop(self) -> None:
        """Register as a broker in cluster membership
        (broker_server.go:32 keepConnectedToMaster)."""
        while not self.address:
            await asyncio.sleep(0.02)
        try:
            while True:
                try:
                    async with self._session.post(
                            f"{self.master_url}/cluster/announce",
                            json={"address": self.address,
                                  "type": "broker"},
                            allow_redirects=True) as resp:
                        await resp.read()
                except Exception:
                    pass
                await asyncio.sleep(self.announce_pulse)
        except asyncio.CancelledError:
            # deregister so shell commands don't route to a dead broker
            # for the membership TTL window
            try:
                async with self._session.post(
                        f"{self.master_url}/cluster/announce",
                        json={"address": self.address, "type": "broker",
                              "leave": True},
                        allow_redirects=True) as resp:
                    await resp.read()
            except Exception:
                pass
            raise

    # -- filer IO -------------------------------------------------------
    async def _filer(self, method: str, path: str, **kw):
        return await self._session.request(
            method, f"{self.filer_url}{path}", **kw)

    async def _load_topics(self) -> None:
        """Rehydrate topic registry + partition offsets from the filer
        (stateless broker restart)."""
        for ns in await self._list_dir(TOPICS_DIR):
            if not ns["is_dir"]:
                continue
            ns_name = ns["name"]
            for tp in await self._list_dir(f"{TOPICS_DIR}/{ns_name}"):
                if not tp["is_dir"]:
                    continue
                resp = await self._filer(
                    "GET", f"{TOPICS_DIR}/{ns_name}/{tp['name']}"
                           f"/topic.conf")
                if resp.status != 200:
                    continue
                conf = json.loads(await resp.read())
                topic = Topic(conf["namespace"], conf["name"],
                              conf.get("partitions", 4))
                self.topics[(topic.namespace, topic.name)] = topic
                for i in range(topic.partitions):
                    part = await self._open_partition(topic, i)
                    self.parts[(topic.namespace, topic.name, i)] = part

    async def _list_dir(self, path: str) -> list[dict]:
        resp = await self._filer("GET", path,
                                 headers={"Accept": "application/json"})
        if resp.status != 200:
            return []
        body = await resp.json()
        out = []
        for e in body.get("entries", []):
            name = e["full_path"].rstrip("/").rsplit("/", 1)[-1]
            out.append({"name": name,
                        "is_dir": bool(e.get("mode", 0) & 0o40000)})
        return out

    async def _open_partition(self, topic: Topic, idx: int) -> Partition:
        part = Partition(topic.dir, idx)
        segs = await self._segments(part)
        if segs:
            # next offset = last segment's first offset + its records.
            # A failed read here must NOT fall through to offset 0 —
            # the broker would re-ack duplicate offsets and overwrite
            # the first flushed segment on the next flush.
            resp = await self._filer("GET",
                                     f"{part.dir}/seg-{segs[-1]:020d}")
            if resp.status != 200:
                raise IOError(
                    f"cannot recover offsets for {part.dir}: segment "
                    f"seg-{segs[-1]:020d} read failed "
                    f"({resp.status})")
            n = sum(1 for line in (await resp.read()).splitlines()
                    if line.strip())
            part.next_offset = segs[-1] + n
        part.tail_base = part.next_offset
        return part

    async def _segments(self, part: Partition) -> list[int]:
        """Sorted first-offsets of flushed segment files."""
        segs = []
        for e in await self._list_dir(part.dir):
            if e["name"].startswith("seg-"):
                try:
                    segs.append(int(e["name"][4:]))
                except ValueError:
                    continue
        return sorted(segs)

    async def _flush_partition(self, part: Partition) -> None:
        # records stay in the tail until the segment write is durable:
        # removing them first would open a window where a subscriber
        # sees neither the tail copy nor the (in-flight) segment and
        # silently skips offsets. Duplicates across tail+segment are
        # harmless — subscribe filters by offset.
        async with part.lock:
            if not part.tail:
                return
            records = list(part.tail)
            base = part.tail_base
        body = "\n".join(json.dumps(r, separators=(",", ":"))
                         for r in records) + "\n"
        resp = await self._filer("POST", f"{part.dir}/seg-{base:020d}",
                                 data=body.encode())
        await resp.release()
        if resp.status >= 300:
            raise IOError(f"segment flush failed: {resp.status}")
        async with part.lock:
            del part.tail[:len(records)]
            part.tail_base = base + len(records)
            part.tail_bytes = sum(
                len(r.get("v", r.get("v64", ""))) + len(r["k"]) + 32
                for r in part.tail)
            part.last_flush = time.monotonic()

    async def _flush_loop(self) -> None:
        while True:
            await asyncio.sleep(SEG_FLUSH_AGE / 2)
            now = time.monotonic()
            for part in list(self.parts.values()):
                try:
                    if part.tail and (
                            now - part.last_flush >= SEG_FLUSH_AGE
                            or len(part.tail) >= SEG_FLUSH_RECORDS
                            or part.tail_bytes >= SEG_FLUSH_BYTES):
                        await self._flush_partition(part)
                except asyncio.CancelledError:
                    return
                except Exception:
                    continue  # filer hiccup: retry next tick

    # -- handlers -------------------------------------------------------
    def _topic(self, req: web.Request) -> Topic:
        key = (req.match_info["ns"], req.match_info["topic"])
        topic = self.topics.get(key)
        if topic is None:
            raise web.HTTPNotFound(
                text=json.dumps({"error": f"no topic {key[0]}/{key[1]}"}),
                content_type="application/json")
        return topic

    async def handle_status(self, req: web.Request) -> web.Response:
        return web.json_response(
            {"filer": self.filer_url, "topics": len(self.topics)})

    async def handle_list_topics(self, req: web.Request) -> web.Response:
        return web.json_response(
            {"topics": [t.conf() for t in self.topics.values()]})

    async def handle_configure(self, req: web.Request) -> web.Response:
        """ConfigureTopic (broker_grpc_configure.go): create or resize."""
        ns, name = req.match_info["ns"], req.match_info["topic"]
        body = await req.json() if req.can_read_body else {}
        partitions = int(body.get("partitions", 4))
        if partitions < 1:
            raise ValueError("partitions must be >= 1")
        existing = self.topics.get((ns, name))
        if existing is not None and existing.partitions > partitions:
            return web.json_response(
                {"error": "cannot shrink partitions"}, status=409)
        topic = Topic(ns, name, partitions)
        resp = await self._filer(
            "POST", f"{topic.dir}/topic.conf",
            data=json.dumps(topic.conf()).encode())
        await resp.release()
        if resp.status >= 300:
            return web.json_response(
                {"error": f"filer: {resp.status}"}, status=502)
        self.topics[(ns, name)] = topic
        for i in range(partitions):
            if (ns, name, i) not in self.parts:
                self.parts[(ns, name, i)] = await self._open_partition(
                    topic, i)
        return web.json_response(topic.conf(), status=201)

    async def handle_describe(self, req: web.Request) -> web.Response:
        topic = self._topic(req)
        parts = []
        for i in range(topic.partitions):
            part = self.parts[(topic.namespace, topic.name, i)]
            parts.append({"partition": i,
                          "next_offset": part.next_offset})
        return web.json_response({**topic.conf(), "state": parts})

    async def handle_delete(self, req: web.Request) -> web.Response:
        topic = self._topic(req)
        resp = await self._filer("DELETE", topic.dir,
                                 params={"recursive": "true"})
        await resp.release()
        del self.topics[(topic.namespace, topic.name)]
        for i in range(topic.partitions):
            self.parts.pop((topic.namespace, topic.name, i), None)
        return web.json_response({}, status=204)

    async def handle_publish(self, req: web.Request) -> web.Response:
        """Publish one record or a batch (broker_grpc_pub.go). Body:
        {"key": ..., "value": ...} or {"records": [...]}."""
        topic = self._topic(req)
        body = await req.json()
        if "records" in body:
            # an explicitly-empty batch is a no-op, NOT a single
            # publish of the envelope (`or [body]` treated [] as
            # missing and acked a phantom empty record)
            records = body["records"]
            if not isinstance(records, list):
                return web.json_response(
                    {"error": "records must be a list"}, status=400)
        else:
            records = [body]
        out = []
        for rec in records:
            key = rec.get("key", "")
            if "value64" in rec:
                value = base64.b64decode(rec["value64"])
            else:
                value = rec.get("value", "")
                if isinstance(value, str):
                    value = value.encode()
            if key:
                pidx = int(hashlib.md5(key.encode()).hexdigest(),
                           16) % topic.partitions
            else:
                self._rr += 1
                pidx = self._rr % topic.partitions
            part = self.parts[(topic.namespace, topic.name, pidx)]
            async with part.lock:
                record = {"o": part.next_offset, "ts": time.time_ns(),
                          "k": key, **_enc_value(value)}
                part.tail.append(record)
                part.tail_bytes += len(value) + len(key) + 32
                part.next_offset += 1
                part.new_data.set()
                part.new_data = asyncio.Event()
            out.append({"partition": pidx, "offset": record["o"]})
        return web.json_response({"acks": out})

    async def handle_subscribe(self, req: web.Request) \
            -> web.StreamResponse:
        """Stream records from `offset` on one partition; replays
        flushed segments then follows the live tail until idle for
        `idle_timeout` seconds (broker_grpc_sub.go)."""
        topic = self._topic(req)
        pidx = int(req.query.get("partition", "0"))
        if not 0 <= pidx < topic.partitions:
            raise ValueError(f"partition {pidx} out of range")
        offset = int(req.query.get("offset", "0"))
        idle_timeout = float(req.query.get("idle_timeout", "5"))
        limit = int(req.query.get("limit", "0"))
        part = self.parts[(topic.namespace, topic.name, pidx)]
        resp = web.StreamResponse()
        resp.content_type = "application/x-ndjson"
        await resp.prepare(req)
        sent = 0

        async def send(rec: dict) -> bool:
            nonlocal offset, sent
            if rec["o"] < offset:
                return True
            await resp.write(
                (json.dumps(rec, separators=(",", ":")) + "\n").encode())
            offset = rec["o"] + 1
            sent += 1
            return not limit or sent < limit

        # 1. replay flushed segments that may contain >= offset
        for first in await self._segments(part):
            async with part.lock:
                tail_base = part.tail_base
            if first >= tail_base:
                break  # re-flushed after we read; tail covers it
            r = await self._filer("GET", f"{part.dir}/seg-{first:020d}")
            if r.status != 200:
                continue
            for line in (await r.read()).splitlines():
                if not line.strip():
                    continue
                if not await send(json.loads(line)):
                    await resp.write_eof()
                    return resp
        # 2. live tail + follow
        while True:
            async with part.lock:
                pending = [r for r in part.tail if r["o"] >= offset]
                waiter = part.new_data
                # records between segment replay and the tail may have
                # been flushed while we replayed: fetch those segments
                gap = offset < part.tail_base and not pending
            if gap:
                import bisect

                segs = await self._segments(part)
                # the segment holding `offset` is the last one starting
                # at or before it (segments have no fixed record count)
                idx = max(0, bisect.bisect_right(segs, offset) - 1)
                for first in segs[idx:]:
                    r = await self._filer("GET",
                                          f"{part.dir}/seg-{first:020d}")
                    if r.status != 200:
                        continue
                    for line in (await r.read()).splitlines():
                        if line.strip() and \
                                not await send(json.loads(line)):
                            await resp.write_eof()
                            return resp
                if offset < part.tail_base:
                    # nothing more on disk either: records were lost or
                    # compacted away; skip forward rather than spin
                    offset = part.tail_base
                continue
            for rec in pending:
                if not await send(rec):
                    await resp.write_eof()
                    return resp
            try:
                await asyncio.wait_for(waiter.wait(), idle_timeout)
            except asyncio.TimeoutError:
                break
        await resp.write_eof()
        return resp
