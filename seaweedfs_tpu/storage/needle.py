"""Needle record format — the unit of storage in a volume .dat file.

Byte-compatible with the reference's Version2/Version3 layouts
(/root/reference/weed/storage/needle/needle_write.go:20-110,
needle_read.go:15-23,198-210):

    header:  cookie(4) id(8 BE) size(4 BE)
    body:    data_size(4) data flags(1)
             [name_size(1) name] [mime_size(1) mime]
             [last_modified(5 BE)] [ttl(2)] [pairs_size(2) pairs]
    tail:    crc32c(4 BE raw) [append_at_ns(8 BE), v3 only] padding to 8

`size` covers the body only; a body of size 0 (data_size absent) is an
empty/tombstone record. Padding length is the reference's exact quirk:
8 - (total % 8), i.e. a full 8 bytes when already aligned.

CRC is Castagnoli (crc32c) over the raw data bytes, stored big-endian as
the raw sum (the legacy `.Value()` transform is accepted on read for
compatibility, needle_read.go:76-80).
"""
from __future__ import annotations

import struct
from dataclasses import dataclass, field

import google_crc32c

from . import types as t

VERSION2 = 2
VERSION3 = 3
CURRENT_VERSION = VERSION3

CHECKSUM_SIZE = 4
LAST_MODIFIED_BYTES = 5
TTL_BYTES = 2

FLAG_IS_COMPRESSED = 0x01
FLAG_HAS_NAME = 0x02
FLAG_HAS_MIME = 0x04
FLAG_HAS_LAST_MODIFIED = 0x08
FLAG_HAS_TTL = 0x10
FLAG_HAS_PAIRS = 0x20
FLAG_IS_CHUNK_MANIFEST = 0x80


def crc32c(data: bytes, initial: int = 0) -> int:
    return google_crc32c.extend(initial, data) if initial else \
        google_crc32c.value(data)


def legacy_crc_value(c: int) -> int:
    """Deprecated on-disk transform still accepted on read
    (needle/crc.go:26-28)."""
    return (((c >> 15) | (c << 17)) + 0xA282EAD8) & 0xFFFFFFFF


def padding_length(size: int, version: int = CURRENT_VERSION) -> int:
    total = t.NEEDLE_HEADER_SIZE + size + CHECKSUM_SIZE
    if version == VERSION3:
        total += t.TIMESTAMP_SIZE
    return t.NEEDLE_PADDING - (total % t.NEEDLE_PADDING)


def body_length(size: int, version: int = CURRENT_VERSION) -> int:
    n = size + CHECKSUM_SIZE + padding_length(size, version)
    if version == VERSION3:
        n += t.TIMESTAMP_SIZE
    return n


def disk_size(size: int, version: int = CURRENT_VERSION) -> int:
    """Total on-disk record bytes (GetActualSize, needle_read.go:206)."""
    return t.NEEDLE_HEADER_SIZE + body_length(size, version)


@dataclass
class Needle:
    id: int = 0
    cookie: int = 0
    data: bytes = b""
    name: bytes = b""
    mime: bytes = b""
    pairs: bytes = b""
    flags: int = 0
    last_modified: int = 0     # unix seconds, 5 bytes stored
    ttl: bytes = b"\x00\x00"   # (count, unit) stored pair
    checksum: int = 0
    append_at_ns: int = 0
    size: int = field(default=0, init=False)  # body size, set on write/read

    # -- flag helpers -------------------------------------------------
    def has(self, flag: int) -> bool:
        return bool(self.flags & flag)

    def set_flag(self, flag: int, on: bool = True) -> None:
        if on:
            self.flags |= flag
        else:
            self.flags &= ~flag

    @property
    def is_compressed(self) -> bool:
        return self.has(FLAG_IS_COMPRESSED)

    @property
    def is_chunk_manifest(self) -> bool:
        return self.has(FLAG_IS_CHUNK_MANIFEST)

    # -- serialization ------------------------------------------------
    def _computed_size(self) -> int:
        if not self.data:
            return 0
        size = 4 + len(self.data) + 1
        if self.flags & FLAG_HAS_NAME and self.name:
            size += 1 + min(len(self.name), 255)
        if self.flags & FLAG_HAS_MIME and self.mime:
            size += 1 + len(self.mime)
        if self.flags & FLAG_HAS_LAST_MODIFIED:
            size += LAST_MODIFIED_BYTES
        if self.flags & FLAG_HAS_TTL:
            size += TTL_BYTES
        if self.flags & FLAG_HAS_PAIRS and self.pairs:
            size += 2 + len(self.pairs)
        return size

    def to_bytes(self, version: int = CURRENT_VERSION) -> bytes:
        """Full padded on-disk record."""
        if version not in (VERSION2, VERSION3):
            raise ValueError(f"unsupported needle version {version}")
        if len(self.mime) > 255:
            raise ValueError(
                f"mime too long ({len(self.mime)} bytes, max 255)")
        if len(self.pairs) > 0xFFFF:
            raise ValueError(
                f"pairs too long ({len(self.pairs)} bytes, max 65535)")
        if len(self.data) > 0xFFFFFFFF - 1024:
            raise ValueError("needle data exceeds 4GB limit")
        # auto-set presence flags from populated fields
        if self.name:
            self.flags |= FLAG_HAS_NAME
        if self.mime:
            self.flags |= FLAG_HAS_MIME
        if self.last_modified:
            self.flags |= FLAG_HAS_LAST_MODIFIED
        if self.ttl != b"\x00\x00":
            self.flags |= FLAG_HAS_TTL
        if self.pairs:
            self.flags |= FLAG_HAS_PAIRS

        self.size = self._computed_size()
        self.checksum = crc32c(self.data) if self.data else 0

        out = bytearray()
        out += struct.pack(">IQ", self.cookie, self.id)
        out += struct.pack(">I", t.size_to_u32(self.size))
        if self.size:
            out += struct.pack(">I", len(self.data))
            out += self.data
            out.append(self.flags & 0xFF)
            if self.flags & FLAG_HAS_NAME and self.name:
                name = self.name[:255]
                out.append(len(name))
                out += name
            if self.flags & FLAG_HAS_MIME and self.mime:
                out.append(len(self.mime))
                out += self.mime
            if self.flags & FLAG_HAS_LAST_MODIFIED:
                out += self.last_modified.to_bytes(8, "big")[-LAST_MODIFIED_BYTES:]
            if self.flags & FLAG_HAS_TTL:
                out += self.ttl[:TTL_BYTES]
            if self.flags & FLAG_HAS_PAIRS and self.pairs:
                out += struct.pack(">H", len(self.pairs))
                out += self.pairs
        out += struct.pack(">I", self.checksum)
        if version == VERSION3:
            out += struct.pack(">Q", self.append_at_ns)
        out += b"\x00" * padding_length(self.size, version)
        return bytes(out)

    @classmethod
    def from_bytes(cls, blob: bytes, version: int = CURRENT_VERSION,
                   verify_crc: bool = True) -> "Needle":
        """Parse a full on-disk record (header + body)."""
        n = cls()
        cookie, nid, size_u32 = struct.unpack_from(">IQI", blob, 0)
        n.cookie, n.id = cookie, nid
        size = t.u32_to_size(size_u32)
        n.size = size
        if size <= 0:
            return n
        body = blob[t.NEEDLE_HEADER_SIZE:t.NEEDLE_HEADER_SIZE + size]
        try:
            n._parse_body(body)
        except (IndexError, struct.error) as e:
            # a flipped length byte must read as corruption, not crash
            raise ValueError(f"corrupt needle body: {e}") from e
        stored_crc = struct.unpack_from(
            ">I", blob, t.NEEDLE_HEADER_SIZE + size)[0]
        if verify_crc and n.data:
            actual = crc32c(n.data)
            if stored_crc not in (actual, legacy_crc_value(actual)):
                raise ValueError("CRC error: data on disk corrupted")
            n.checksum = actual
        if version == VERSION3 and len(blob) >= t.NEEDLE_HEADER_SIZE + size + 12:
            n.append_at_ns = struct.unpack_from(
                ">Q", blob, t.NEEDLE_HEADER_SIZE + size + 4)[0]
        return n

    def _parse_body(self, body: bytes) -> None:
        (data_size,) = struct.unpack_from(">I", body, 0)
        idx = 4
        self.data = body[idx:idx + data_size]
        self._parse_meta(body, idx + data_size)

    def _parse_meta(self, body: bytes, idx: int) -> None:
        """Parse the post-data fields ([flags][name][mime][lm][ttl]
        [pairs]) starting at `idx`. Split out so the streaming read
        path can parse metadata from a small tail pread without the
        data bytes in memory."""
        self.flags = body[idx]
        idx += 1
        if self.flags & FLAG_HAS_NAME:
            ln = body[idx]
            idx += 1
            self.name = body[idx:idx + ln]
            idx += ln
        if self.flags & FLAG_HAS_MIME:
            lm = body[idx]
            idx += 1
            self.mime = body[idx:idx + lm]
            idx += lm
        if self.flags & FLAG_HAS_LAST_MODIFIED:
            self.last_modified = int.from_bytes(
                body[idx:idx + LAST_MODIFIED_BYTES], "big")
            idx += LAST_MODIFIED_BYTES
        if self.flags & FLAG_HAS_TTL:
            self.ttl = body[idx:idx + TTL_BYTES]
            idx += TTL_BYTES
        if self.flags & FLAG_HAS_PAIRS:
            (lp,) = struct.unpack_from(">H", body, idx)
            idx += 2
            self.pairs = body[idx:idx + lp]
            idx += lp

    def etag(self) -> str:
        return f"{self.checksum:08x}"


def whole_records_prefix(data, version: int = CURRENT_VERSION) -> int:
    """Length of the longest prefix of `data` (bytes or bytearray) that
    is whole needle records — the framing rule for record streams
    (incremental copy / tail), which carry no explicit framing because
    records self-describe via their headers."""
    off = 0
    while off + t.NEEDLE_HEADER_SIZE <= len(data):
        _, _, size_u32 = struct.unpack_from(">IQI", data, off)
        nsize = max(t.u32_to_size(size_u32), 0)
        disk = disk_size(nsize, version)
        if off + disk > len(data):
            break
        off += disk
    return off
