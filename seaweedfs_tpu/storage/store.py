"""Store: the per-server registry of disk locations, volumes and EC
volumes — the engine behind every volume-server handler.

Equivalent of /root/reference/weed/storage/store.go (WriteVolumeNeedle
:386, ReadVolumeNeedle :410, CollectHeartbeat :249) and store_ec.go (EC
mount/read/delete incl. the degraded-read ladder: local shard -> remote
shard fetch -> on-the-fly reconstruction from >= k shards,
store_ec.go:199-393). Remote fetch is injected as a callback so the
transport lives in the server layer.
"""
from __future__ import annotations

import os
from typing import Callable, Iterable

import numpy as np

from ..ec import geometry as geo
from ..ec.backend import ReedSolomon
from ..ec.backend import cpu_backend_name as ec_cpu_backend
from ..ec.encoder import rebuild_ec_files, write_ec_files, write_sorted_ecx
from ..ec.volume import EcVolume
from . import needle as ndl
from . import types as t
from .disk_location import DiskLocation
from .needle import Needle
from .super_block import ReplicaPlacement

# fetch(vid, shard_id, offset, size) -> bytes | None
RemoteShardReader = Callable[[int, int, int, int], "bytes | None"]

# fan-out fetch(vid, candidate_sids, offset, size, need, deadline_s)
# -> {sid: bytes}; returns as soon as `need` shards arrive (first-k-wins)
RemoteShardsFetcher = Callable[[int, list, int, int, int, float],
                               "dict[int, bytes]"]


class Store:
    def __init__(self, dirnames: Iterable[str], ip: str = "localhost",
                 port: int = 8080, public_url: str = "",
                 ec_backend: str = "auto",
                 needle_map_kind: str = "memory"):
        self.locations = [
            DiskLocation(d, needle_map_kind=needle_map_kind)
            for d in dirnames]
        self.ip = ip
        self.port = port
        self.public_url = public_url or f"{ip}:{port}"
        self.ec_backend = ec_backend
        self.ec_volumes: dict[int, EcVolume] = {}
        self.remote_shard_reader: RemoteShardReader | None = None
        self.remote_shards_fetcher: RemoteShardsFetcher | None = None
        # wall-clock budget for one degraded read's remote fan-out: a
        # single hung peer must not stall the read ladder indefinitely
        # (the reference bounds this with per-rpc contexts,
        # store_ec.go:349-393)
        self.ec_read_deadline = 10.0
        self._rs = ReedSolomon(geo.DATA_SHARDS, geo.PARITY_SHARDS,
                               backend=ec_backend)
        for loc in self.locations:
            loc.load_existing()
            for vid, entry in loc.ec_shards.items():
                ecv = EcVolume(loc.dir, entry.collection, vid)
                for sid in entry.shard_ids:
                    ecv.mount_shard(sid)
                self.ec_volumes[vid] = ecv

    # -- volume management --------------------------------------------
    def find_volume(self, vid: int):
        for loc in self.locations:
            v = loc.volumes.get(vid)
            if v is not None:
                return v
        return None

    def has_volume(self, vid: int) -> bool:
        return self.find_volume(vid) is not None

    def needle_size(self, vid: int, needle_id: int) -> int:
        """Cheap O(1) size estimate from the needle map (no disk IO);
        0 when unknown — feeds in-flight download accounting."""
        v = self.find_volume(vid)
        if v is None:
            return 0
        loc = v.nm.get(needle_id)
        return int(loc[1]) if loc else 0

    def add_volume(self, vid: int, collection: str = "",
                   replication: str = "000", ttl: bytes = b"\x00\x00"):
        if self.find_volume(vid) is not None:
            raise FileExistsError(f"volume {vid} already exists")
        loc = min(self.locations, key=lambda l: l.volume_count)
        return loc.new_volume(
            collection, vid,
            replica_placement=ReplicaPlacement.parse(replication), ttl=ttl)

    def delete_volume(self, vid: int) -> None:
        for loc in self.locations:
            if vid in loc.volumes:
                loc.delete_volume(vid)
                return
        raise KeyError(f"volume {vid} not found")

    def mark_readonly(self, vid: int, read_only: bool = True) -> None:
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        v.read_only = read_only

    def unmount_volume(self, vid: int) -> None:
        """Close a volume and drop it from memory, keeping its files on
        disk (volume_grpc_admin.go VolumeUnmount). It disappears from the
        next heartbeat; `mount_volume` brings it back."""
        for loc in self.locations:
            v = loc.volumes.get(vid)
            if v is not None:
                v.close()
                del loc.volumes[vid]
                return
        raise KeyError(f"volume {vid} not found")

    def mount_volume(self, vid: int) -> None:
        """Reload an unmounted volume from its on-disk .dat/.idx
        (volume_grpc_admin.go VolumeMount)."""
        if self.find_volume(vid) is not None:
            return
        for loc in self.locations:
            if loc.try_load_volume(vid):
                return
        raise KeyError(f"volume {vid} has no files on disk")

    def read_raw_needle(self, vid: int, key: int) -> bytes:
        """Serialized on-disk record of one live needle — the transfer
        unit of volume.check.disk's needle-level replica sync."""
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        n = v.read_needle(key)
        return n.to_bytes(v.version)

    def append_raw_needle(self, vid: int, blob: bytes,
                          force: bool = False) -> int:
        """Append a record produced by `read_raw_needle` on a peer
        replica. Skips keys that are already live unless `force` (the
        content-divergence repair, where the newer record must win)."""
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        n = Needle.from_bytes(blob, v.version)
        if not force and v.nm.get(n.id) is not None:
            return n.id
        v.append_needle(n)
        return n.id

    def needle_ids(self, vid: int) -> tuple[list[tuple[int, int]],
                                            list[int]]:
        """(live (needle_id, size) pairs, deleted needle_ids) of a local
        volume or EC volume — feeds volume.fsck / volume.check.disk
        (command_volume_fsck.go). Deleted ids matter: replica sync must
        propagate tombstones, never resurrect from a stale live copy."""
        v = self.find_volume(vid)
        if v is not None:
            return ([(key, size) for key, _, size in v.nm.live_items()],
                    sorted(v.nm.deleted_keys()))
        ecv = self.ec_volumes.get(vid)
        if ecv is not None:
            return ecv.live_needle_ids(), sorted(ecv.deleted)
        raise KeyError(f"volume {vid} not found")

    # -- needle IO ------------------------------------------------------
    def write_needle(self, vid: int, n: Needle) -> tuple[int, int]:
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        return v.append_needle(n)

    def read_needle(self, vid: int, needle_id: int,
                    cookie: int | None = None,
                    read_deleted: bool = False) -> Needle:
        v = self.find_volume(vid)
        if v is not None:
            return v.read_needle(needle_id, cookie,
                                 read_deleted=read_deleted)
        if vid in self.ec_volumes:
            return self.read_ec_needle(vid, needle_id, cookie)
        raise KeyError(f"volume {vid} not found")

    def delete_needle(self, vid: int, needle_id: int) -> int:
        v = self.find_volume(vid)
        if v is not None:
            return v.delete_needle(needle_id)
        if vid in self.ec_volumes:
            self.ec_volumes[vid].delete_needle(needle_id)
            return 0
        raise KeyError(f"volume {vid} not found")

    # -- EC lifecycle ---------------------------------------------------
    def generate_ec_shards(self, vid: int, codec: str = "") -> None:
        """VolumeEcShardsGenerate (volume_grpc_erasure_coding.go:38):
        .dat -> shard files + .ecx, using the configured codec backend.
        `codec` ("k.m") selects a wide code (beyond-reference tier)."""
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        v.sync()
        base = v.file_name()
        write_ec_files(base, backend=self.ec_backend, codec=codec)
        write_sorted_ecx(base)

    def rebuild_ec_shards(self, vid: int) -> list[int]:
        """VolumeEcShardsRebuild (:84): regenerate missing local shards."""
        base = self._ec_base(vid)
        if base is None:
            raise KeyError(f"ec volume {vid} not found")
        return rebuild_ec_files(base, backend=self.ec_backend)

    def mount_ec_shards(self, vid: int, collection: str,
                        shard_ids: Iterable[int]) -> None:
        ecv = self.ec_volumes.get(vid)
        if ecv is None:
            loc = self._loc_with_ec_files(vid, collection)
            ecv = EcVolume(loc.dir, collection, vid)
            self.ec_volumes[vid] = ecv
        for sid in shard_ids:
            ecv.mount_shard(sid)
            for loc in self.locations:
                if loc.dir == ecv.dir:
                    loc.add_ec_shard(collection, vid, sid)

    def unmount_ec_shards(self, vid: int, shard_ids: Iterable[int]) -> None:
        ecv = self.ec_volumes.get(vid)
        if ecv is None:
            return
        for sid in shard_ids:
            ecv.unmount_shard(sid)
        if not ecv.shards:
            self.ec_volumes.pop(vid, None)

    def delete_ec_shards(self, vid: int,
                         shard_ids: Iterable[int] | None = None) -> None:
        ids = set(shard_ids) if shard_ids is not None else None
        self.unmount_ec_shards(vid, ids or range(geo.MAX_SHARD_COUNT))
        for loc in self.locations:
            loc.remove_ec_shards(vid, ids)

    def _ec_base(self, vid: int) -> str | None:
        for loc in self.locations:
            entry = loc.ec_shards.get(vid)
            if entry is not None:
                return entry.base_name(loc.dir)
            # also look for shard files not yet registered
            v = loc.volumes.get(vid)
            if v is not None and os.path.exists(
                    v.file_name() + geo.shard_ext(0)):
                return v.file_name()
        ecv = self.ec_volumes.get(vid)
        return ecv.base_name() if ecv is not None else None

    def _loc_with_ec_files(self, vid: int, collection: str) -> DiskLocation:
        for loc in self.locations:
            name = f"{collection}_{vid}" if collection else str(vid)
            for sid in range(geo.MAX_SHARD_COUNT):
                if os.path.exists(os.path.join(
                        loc.dir, name + geo.shard_ext(sid))):
                    return loc
        return self.locations[0]

    # -- EC degraded read ladder ----------------------------------------
    def read_ec_needle(self, vid: int, needle_id: int,
                       cookie: int | None = None) -> Needle:
        """ReadEcShardNeedle (store_ec.go:136): locate via .ecx, read each
        interval locally, else via remote fetch, else reconstruct."""
        ecv = self.ec_volumes.get(vid)
        if ecv is None:
            raise KeyError(f"ec volume {vid} not found")
        intervals, size = ecv.needle_intervals(needle_id)
        blob = b"".join(self._read_interval(ecv, iv) for iv in intervals)
        n = Needle.from_bytes(blob)
        if n.size != size:
            raise ValueError(f"size mismatch: ecx {size} vs disk {n.size}")
        if cookie is not None and n.cookie != cookie:
            raise PermissionError("cookie mismatch")
        return n

    def _read_interval(self, ecv: EcVolume, iv: geo.Interval) -> bytes:
        data = ecv.read_interval_local(iv)
        if data is not None:
            return data
        sid, off = iv.to_shard_and_offset()
        if self.remote_shards_fetcher is not None:
            # direct fetch of the owning shard gets only a SLICE of the
            # read budget: if its holder is hung, the remaining budget
            # must still cover the reconstruction fan-out (the old
            # ladder burned the whole deadline on this hop first)
            got = self.remote_shards_fetcher(
                ecv.vid, [sid], off, iv.size, 1,
                min(2.0, self.ec_read_deadline * 0.25))
            if sid in got:
                return got[sid]
        elif self.remote_shard_reader is not None:
            data = self.remote_shard_reader(ecv.vid, sid, off, iv.size)
            if data is not None:
                return data
        return self._reconstruct_interval(ecv, sid, off, iv.size)

    def _reconstruct_interval(self, ecv: EcVolume, missing_sid: int,
                              offset: int, size: int) -> bytes:
        """recoverOneRemoteEcShardInterval (store_ec.go:339): gather the
        same byte range from >= k other shards and reconstruct.

        Local shards are read first (cheap); the remaining need is
        fanned out CONCURRENTLY to every remote candidate via
        remote_shards_fetcher, first-k-wins under ec_read_deadline —
        the reference fans out one goroutine per shard the same way
        (store_ec.go:349-393); a serial walk would pay ≥10 sequential
        RTTs and a single hung peer would stall the read forever."""
        rows: dict[int, np.ndarray] = {}
        candidates: list[int] = []
        for sid in range(ecv.total):
            if sid == missing_sid:
                continue
            shard = ecv.shards.get(sid)
            if shard is not None and len(rows) < ecv.k:
                rows[sid] = np.frombuffer(
                    shard.read_at(offset, size), dtype=np.uint8)
            elif shard is None:
                candidates.append(sid)
        need = ecv.k - len(rows)
        if need > 0 and candidates:
            if self.remote_shards_fetcher is not None:
                got = self.remote_shards_fetcher(
                    ecv.vid, candidates, offset, size, need,
                    self.ec_read_deadline)
                for sid, data in got.items():
                    rows[sid] = np.frombuffer(data, dtype=np.uint8)
            elif self.remote_shard_reader is not None:
                # legacy serial fallback (tools / tests without a server)
                for sid in candidates:
                    if len(rows) >= ecv.k:
                        break
                    data = self.remote_shard_reader(
                        ecv.vid, sid, offset, size)
                    if data is not None:
                        rows[sid] = np.frombuffer(data, dtype=np.uint8)
        if len(rows) < ecv.k:
            raise IOError(
                f"cannot reconstruct shard {missing_sid} of volume "
                f"{ecv.vid}: only {len(rows)} shards reachable")
        rec = self._rs_for(ecv, interval=True).reconstruct(
            rows, [missing_sid])
        return rec[missing_sid].tobytes()

    def _rs_for(self, ecv: EcVolume, *,
                interval: bool = False) -> ReedSolomon:
        """Per-codec ReedSolomon, cached — wide-code volumes carry their
        own (k, m) from the .vif sidecar.

        interval=True pins the CPU codec (native/numpy) regardless of
        the configured device backend: a single-needle degraded read
        reconstructs a few KB on a GET's critical path, where a device
        dispatch (jit compile + host<->device DMA, measured ~1.6s cold)
        is pure latency with zero throughput payoff.  Whole-volume
        encode/rebuild keeps the configured backend — that's where the
        device's bandwidth actually wins."""
        backend = ec_cpu_backend() if interval else self.ec_backend
        if not interval and \
                (ecv.k, ecv.m) == (geo.DATA_SHARDS, geo.PARITY_SHARDS):
            return self._rs
        cache = getattr(self, "_rs_cache", None)
        if cache is None:
            cache = self._rs_cache = {}
        rs = cache.get((ecv.k, ecv.m, backend))
        if rs is None:
            rs = cache[(ecv.k, ecv.m, backend)] = ReedSolomon(
                ecv.k, ecv.m, backend=backend)
        return rs

    # -- heartbeat -------------------------------------------------------
    def collect_heartbeat(self) -> dict:
        """CollectHeartbeat (store.go:249): full volume + EC shard report
        for the master."""
        volumes = []
        for loc in self.locations:
            for vid, v in loc.volumes.items():
                volumes.append({
                    "id": vid,
                    "collection": v.collection,
                    "size": v.content_size(),
                    "file_count": v.nm.file_count,
                    "delete_count": v.nm.deleted_count,
                    "deleted_bytes": v.nm.deleted_bytes,
                    "read_only": v.read_only,
                    "replica_placement":
                        str(v.super_block.replica_placement),
                    "ttl": list(v.super_block.ttl),
                    "version": v.version,
                    # volume-TTL expiry decisions need the last write
                    # time (volume ttl, needle/volume_ttl.go)
                    "modified_at": v.modified_at_second(),
                })
        ec_shards = [
            {"id": vid, "collection": ecv.collection,
             "shard_bits": ecv.shard_bits().bits,
             "codec": geo.codec_name(ecv.k, ecv.m)
             if (ecv.k, ecv.m) != (geo.DATA_SHARDS, geo.PARITY_SHARDS)
             else ""}
            for vid, ecv in self.ec_volumes.items()
        ]
        return {
            "ip": self.ip, "port": self.port, "public_url": self.public_url,
            "max_volume_count": sum(l.max_volumes for l in self.locations),
            "volumes": volumes, "ec_shards": ec_shards,
        }

    def close(self) -> None:
        for loc in self.locations:
            loc.close()
        for ecv in self.ec_volumes.values():
            ecv.close()
