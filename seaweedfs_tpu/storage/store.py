"""Store: the per-server registry of disk locations, volumes and EC
volumes — the engine behind every volume-server handler.

Equivalent of /root/reference/weed/storage/store.go (WriteVolumeNeedle
:386, ReadVolumeNeedle :410, CollectHeartbeat :249) and store_ec.go (EC
mount/read/delete incl. the degraded-read ladder: local shard -> remote
shard fetch -> on-the-fly reconstruction from >= k shards,
store_ec.go:199-393). Remote fetch is injected as a callback so the
transport lives in the server layer.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Iterable

import numpy as np

from ..ec import geometry as geo
from ..ec.backend import ReedSolomon
from ..ec.backend import cpu_backend_name as ec_cpu_backend
from ..ec.encoder import rebuild_ec_files, write_ec_files, write_sorted_ecx
from ..ec.volume import EcVolume
from ..utils import sketch as _sketch
from . import needle as ndl
from . import types as t
from .disk_location import DiskLocation
from .needle import Needle
from .super_block import ReplicaPlacement

# fetch(vid, shard_id, offset, size) -> bytes | None
RemoteShardReader = Callable[[int, int, int, int], "bytes | None"]

# fan-out fetch(vid, candidate_sids, offset, size, need, deadline_s)
# -> {sid: bytes}; returns as soon as `need` shards arrive (first-k-wins)
RemoteShardsFetcher = Callable[[int, list, int, int, int, float],
                               "dict[int, bytes]"]

# byte-rate shaping hook for bulk tier movement: fn(n_bytes) blocks
# until the bytes are admitted (volume server wires the "tier" bucket)
TierThrottle = Callable[[int], None]


def tier_shard_key(collection: str, vid: int, sid: int) -> str:
    """Deterministic remote object key for one offloaded EC shard.
    Determinism is the no-duplicate-objects guarantee: a transition
    retried after a crash overwrites the same key instead of minting a
    new object."""
    return f"tier-ec/{collection or 'default'}/{vid}/{sid:02d}.ec"


# one remote client per distinct config, process-wide (clients are
# stateless wrappers; S3 ones hold a signing-key cache worth sharing)
_remote_clients: dict[str, object] = {}
_remote_lock = threading.Lock()


def remote_client_for(conf: dict):
    from ..remote_storage.client import make_client

    key = json.dumps(conf, sort_keys=True)
    with _remote_lock:
        c = _remote_clients.get(key)
        if c is None:
            c = _remote_clients[key] = make_client(conf)
        return c


class Store:
    def __init__(self, dirnames: Iterable[str], ip: str = "localhost",
                 port: int = 8080, public_url: str = "",
                 ec_backend: str = "auto",
                 needle_map_kind: str = "memory"):
        self.locations = [
            DiskLocation(d, needle_map_kind=needle_map_kind)
            for d in dirnames]
        self.ip = ip
        self.port = port
        self.public_url = public_url or f"{ip}:{port}"
        self.ec_backend = ec_backend
        self.ec_volumes: dict[int, EcVolume] = {}
        self.remote_shard_reader: RemoteShardReader | None = None
        self.remote_shards_fetcher: RemoteShardsFetcher | None = None
        # wall-clock budget for one degraded read's remote fan-out: a
        # single hung peer must not stall the read ladder indefinitely
        # (the reference bounds this with per-rpc contexts,
        # store_ec.go:349-393)
        self.ec_read_deadline = 10.0
        self._rs = ReedSolomon(geo.DATA_SHARDS, geo.PARITY_SHARDS,
                               backend=ec_backend)
        # per-volume heat: last read/write wall time + cumulative
        # counts, reported in heartbeats so the master's tiering
        # controller can age volumes by real access, not just write
        # mtime
        self._heat: dict[int, dict] = {}
        self._heat_lock = threading.Lock()
        # per-volume workload sketches (read/write inter-access gaps +
        # request sizes) behind the same short lock; compact encodings
        # ride the heartbeat `workload` key when telemetry is enabled
        self._wl: dict[int, dict] = {}
        # node-level foreground byte-rate accounting: current-second
        # tally, last completed second, all-time per-second peak — the
        # repair-cap advisor's headroom inputs
        self._bps_sec = 0
        self._bps_cur = 0
        self._bps_last = 0
        self._bps_peak = 0
        for loc in self.locations:
            loc.load_existing()
            for vid, entry in loc.ec_shards.items():
                ecv = EcVolume(loc.dir, entry.collection, vid)
                for sid in entry.shard_ids:
                    if os.path.exists(
                            ecv.base_name() + geo.shard_ext(sid)):
                        ecv.mount_shard(sid)
                self.ec_volumes[vid] = ecv
                # shards offloaded to the cold tier re-mount
                # remote-backed from the manifest (restart survival)
                try:
                    self._mount_manifest_shards(ecv)
                except Exception as e:
                    loc.load_errors.append(
                        (vid, f"remote shards: {type(e).__name__}: {e}"))

    # -- volume management --------------------------------------------
    def find_volume(self, vid: int):
        for loc in self.locations:
            v = loc.volumes.get(vid)
            if v is not None:
                return v
        return None

    def has_volume(self, vid: int) -> bool:
        return self.find_volume(vid) is not None

    def needle_size(self, vid: int, needle_id: int) -> int:
        """Cheap O(1) size estimate from the needle map (no disk IO);
        0 when unknown — feeds in-flight download accounting."""
        v = self.find_volume(vid)
        if v is None:
            return 0
        loc = v.nm.get(needle_id)
        return int(loc[1]) if loc else 0

    def add_volume(self, vid: int, collection: str = "",
                   replication: str = "000", ttl: bytes = b"\x00\x00"):
        if self.find_volume(vid) is not None:
            raise FileExistsError(f"volume {vid} already exists")
        loc = min(self.locations, key=lambda l: l.volume_count)
        return loc.new_volume(
            collection, vid,
            replica_placement=ReplicaPlacement.parse(replication), ttl=ttl)

    def delete_volume(self, vid: int) -> None:
        for loc in self.locations:
            if vid in loc.volumes:
                loc.delete_volume(vid)
                return
        raise KeyError(f"volume {vid} not found")

    def mark_readonly(self, vid: int, read_only: bool = True) -> None:
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        v.read_only = read_only

    def unmount_volume(self, vid: int) -> None:
        """Close a volume and drop it from memory, keeping its files on
        disk (volume_grpc_admin.go VolumeUnmount). It disappears from the
        next heartbeat; `mount_volume` brings it back."""
        for loc in self.locations:
            v = loc.volumes.get(vid)
            if v is not None:
                v.close()
                del loc.volumes[vid]
                return
        raise KeyError(f"volume {vid} not found")

    def mount_volume(self, vid: int) -> None:
        """Reload an unmounted volume from its on-disk .dat/.idx
        (volume_grpc_admin.go VolumeMount)."""
        if self.find_volume(vid) is not None:
            return
        for loc in self.locations:
            if loc.try_load_volume(vid):
                return
        raise KeyError(f"volume {vid} has no files on disk")

    def read_raw_needle(self, vid: int, key: int) -> bytes:
        """Serialized on-disk record of one live needle — the transfer
        unit of volume.check.disk's needle-level replica sync."""
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        n = v.read_needle(key)
        return n.to_bytes(v.version)

    def append_raw_needle(self, vid: int, blob: bytes,
                          force: bool = False) -> int:
        """Append a record produced by `read_raw_needle` on a peer
        replica. Skips keys that are already live unless `force` (the
        content-divergence repair, where the newer record must win)."""
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        n = Needle.from_bytes(blob, v.version)
        if not force and v.nm.get(n.id) is not None:
            return n.id
        v.append_needle(n)
        return n.id

    def needle_ids(self, vid: int) -> tuple[list[tuple[int, int]],
                                            list[int]]:
        """(live (needle_id, size) pairs, deleted needle_ids) of a local
        volume or EC volume — feeds volume.fsck / volume.check.disk
        (command_volume_fsck.go). Deleted ids matter: replica sync must
        propagate tombstones, never resurrect from a stale live copy."""
        v = self.find_volume(vid)
        if v is not None:
            return ([(key, size) for key, _, size in v.nm.live_items()],
                    sorted(v.nm.deleted_keys()))
        ecv = self.ec_volumes.get(vid)
        if ecv is not None:
            return ecv.live_needle_ids(), sorted(ecv.deleted)
        raise KeyError(f"volume {vid} not found")

    # -- needle IO ------------------------------------------------------
    def write_needle(self, vid: int, n: Needle) -> tuple[int, int]:
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        res = v.append_needle(n)
        self.record_write(vid, nbytes=res[1])
        return res

    def read_needle(self, vid: int, needle_id: int,
                    cookie: int | None = None,
                    read_deleted: bool = False) -> Needle:
        v = self.find_volume(vid)
        if v is not None:
            out = v.read_needle(needle_id, cookie,
                                read_deleted=read_deleted)
            self.record_read(vid, nbytes=out.size)
            return out
        if vid in self.ec_volumes:
            return self.read_ec_needle(vid, needle_id, cookie)
        raise KeyError(f"volume {vid} not found")

    @staticmethod
    def _new_heat() -> dict:
        return {"last_read_at": 0.0, "read_count": 0,
                "last_write_at": 0.0, "write_count": 0}

    def _wl_for(self, vid: int) -> dict:
        # caller holds _heat_lock; rg/wg = read/write inter-access
        # gaps, rs/ws = read/write request sizes
        wl = self._wl.get(vid)
        if wl is None:
            wl = self._wl[vid] = {k: _sketch.windowed()
                                  for k in ("rg", "rs", "wg", "ws")}
        return wl

    def _account_bytes(self, nbytes: int, now: float) -> None:
        # caller holds _heat_lock
        sec = int(now)
        if sec != self._bps_sec:
            if self._bps_sec:
                self._bps_last = self._bps_cur
                if self._bps_cur > self._bps_peak:
                    self._bps_peak = self._bps_cur
            self._bps_sec = sec
            self._bps_cur = 0
        if nbytes > 0:
            self._bps_cur += int(nbytes)

    def record_read(self, vid: int, nbytes: int = 0) -> None:
        """Heat accounting for one serving read of a volume — cheap
        enough for the GET hot path (dict store under a short lock).
        With telemetry on, also sketches the inter-read gap and the
        needle size into the volume's sliding-window histograms."""
        now = time.time()
        tele = _sketch.enabled()
        with self._heat_lock:
            h = self._heat.get(vid)
            if h is None:
                h = self._heat[vid] = self._new_heat()
            prev = h["last_read_at"]
            h["last_read_at"] = now
            h["read_count"] += 1
            if tele:
                wl = self._wl_for(vid)
                if prev:
                    wl["rg"].record(now - prev, now)
                if nbytes > 0:
                    wl["rs"].record(nbytes, now)
                self._account_bytes(nbytes, now)

    def record_write(self, vid: int, nbytes: int = 0) -> None:
        """Write-side twin of record_read, tapped from write_needle."""
        now = time.time()
        tele = _sketch.enabled()
        with self._heat_lock:
            h = self._heat.get(vid)
            if h is None:
                h = self._heat[vid] = self._new_heat()
            prev = h["last_write_at"]
            h["last_write_at"] = now
            h["write_count"] += 1
            if tele:
                wl = self._wl_for(vid)
                if prev:
                    wl["wg"].record(now - prev, now)
                if nbytes > 0:
                    wl["ws"].record(nbytes, now)
                self._account_bytes(nbytes, now)

    def volume_heat(self, vid: int) -> dict:
        with self._heat_lock:
            h = self._heat.get(vid)
            return dict(h) if h else self._new_heat()

    def workload_payload(self, now: float | None = None) -> dict:
        """Compact per-volume sketch encodings + node byte rates for
        the heartbeat `workload` key (empty sketches are skipped so an
        idle node costs a few bytes)."""
        now = time.time() if now is None else now
        with self._heat_lock:
            vols = {}
            for vid, wl in self._wl.items():
                enc = {k: s.to_dict(now) for k, s in wl.items()}
                enc = {k: d for k, d in enc.items() if d.get("n")}
                if enc:
                    vols[str(vid)] = enc
            # fg_bps: the most recent complete-or-partial second's
            # foreground bytes, 0 when the node has gone idle. The
            # roll in _account_bytes only happens on the NEXT record,
            # so a just-ended second still sits in _bps_cur here.
            sec = int(now)
            if sec == self._bps_sec:
                fg = max(self._bps_cur, self._bps_last)
            elif sec - self._bps_sec == 1:
                fg = self._bps_cur  # that full second just ended
            else:
                fg = 0
            # _bps_cur is always a valid single-second tally, even if
            # the roll hasn't folded it into _bps_peak yet — a burst
            # must count toward the peak before the next request lands
            return {"alpha": _sketch.alpha(), "volumes": vols,
                    "fg_bps": fg,
                    "peak_bps": max(self._bps_peak, self._bps_cur)}

    def delete_needle(self, vid: int, needle_id: int) -> int:
        v = self.find_volume(vid)
        if v is not None:
            return v.delete_needle(needle_id)
        if vid in self.ec_volumes:
            self.ec_volumes[vid].delete_needle(needle_id)
            return 0
        raise KeyError(f"volume {vid} not found")

    # -- EC lifecycle ---------------------------------------------------
    def generate_ec_shards(self, vid: int, codec: str = "") -> None:
        """VolumeEcShardsGenerate (volume_grpc_erasure_coding.go:38):
        .dat -> shard files + .ecx, using the configured codec backend.
        `codec` ("k.m") selects a wide code (beyond-reference tier)."""
        v = self.find_volume(vid)
        if v is None:
            raise KeyError(f"volume {vid} not found")
        v.sync()
        base = v.file_name()
        write_ec_files(base, backend=self.ec_backend, codec=codec)
        write_sorted_ecx(base)

    def rebuild_ec_shards(self, vid: int) -> list[int]:
        """VolumeEcShardsRebuild (:84): regenerate missing local shards."""
        base = self._ec_base(vid)
        if base is None:
            raise KeyError(f"ec volume {vid} not found")
        return rebuild_ec_files(base, backend=self.ec_backend)

    def mount_ec_shards(self, vid: int, collection: str,
                        shard_ids: Iterable[int]) -> None:
        ecv = self.ec_volumes.get(vid)
        if ecv is None:
            loc = self._loc_with_ec_files(vid, collection)
            ecv = EcVolume(loc.dir, collection, vid)
            self.ec_volumes[vid] = ecv
        for sid in shard_ids:
            ecv.mount_shard(sid)
            for loc in self.locations:
                if loc.dir == ecv.dir:
                    loc.add_ec_shard(collection, vid, sid)

    def unmount_ec_shards(self, vid: int, shard_ids: Iterable[int]) -> None:
        ecv = self.ec_volumes.get(vid)
        if ecv is None:
            return
        for sid in shard_ids:
            ecv.unmount_shard(sid)
        if not ecv.shards:
            self.ec_volumes.pop(vid, None)

    def delete_ec_shards(self, vid: int,
                         shard_ids: Iterable[int] | None = None) -> None:
        ids = set(shard_ids) if shard_ids is not None else None
        self.unmount_ec_shards(vid, ids or range(geo.MAX_SHARD_COUNT))
        for loc in self.locations:
            loc.remove_ec_shards(vid, ids)

    def _ec_base(self, vid: int) -> str | None:
        for loc in self.locations:
            entry = loc.ec_shards.get(vid)
            if entry is not None:
                return entry.base_name(loc.dir)
            # also look for shard files not yet registered
            v = loc.volumes.get(vid)
            if v is not None and os.path.exists(
                    v.file_name() + geo.shard_ext(0)):
                return v.file_name()
        ecv = self.ec_volumes.get(vid)
        return ecv.base_name() if ecv is not None else None

    def _loc_with_ec_files(self, vid: int, collection: str) -> DiskLocation:
        for loc in self.locations:
            name = f"{collection}_{vid}" if collection else str(vid)
            for sid in range(geo.MAX_SHARD_COUNT):
                if os.path.exists(os.path.join(
                        loc.dir, name + geo.shard_ext(sid))):
                    return loc
        return self.locations[0]

    # -- EC degraded read ladder ----------------------------------------
    def read_ec_needle(self, vid: int, needle_id: int,
                       cookie: int | None = None) -> Needle:
        """ReadEcShardNeedle (store_ec.go:136): locate via .ecx, read each
        interval locally, else via remote fetch, else reconstruct."""
        ecv = self.ec_volumes.get(vid)
        if ecv is None:
            raise KeyError(f"ec volume {vid} not found")
        intervals, size = ecv.needle_intervals(needle_id)
        blob = b"".join(self._read_interval(ecv, iv) for iv in intervals)
        n = Needle.from_bytes(blob)
        if n.size != size:
            raise ValueError(f"size mismatch: ecx {size} vs disk {n.size}")
        if cookie is not None and n.cookie != cookie:
            raise PermissionError("cookie mismatch")
        self.record_read(vid, nbytes=n.size)
        return n

    def _read_interval(self, ecv: EcVolume, iv: geo.Interval) -> bytes:
        data = ecv.read_interval_local(iv)
        if data is not None:
            return data
        sid, off = iv.to_shard_and_offset()
        if self.remote_shards_fetcher is not None:
            # direct fetch of the owning shard gets only a SLICE of the
            # read budget: if its holder is hung, the remaining budget
            # must still cover the reconstruction fan-out (the old
            # ladder burned the whole deadline on this hop first)
            got = self.remote_shards_fetcher(
                ecv.vid, [sid], off, iv.size, 1,
                min(2.0, self.ec_read_deadline * 0.25))
            if sid in got:
                return got[sid]
        elif self.remote_shard_reader is not None:
            data = self.remote_shard_reader(ecv.vid, sid, off, iv.size)
            if data is not None:
                return data
        return self._reconstruct_interval(ecv, sid, off, iv.size)

    def _reconstruct_interval(self, ecv: EcVolume, missing_sid: int,
                              offset: int, size: int) -> bytes:
        """recoverOneRemoteEcShardInterval (store_ec.go:339): gather the
        same byte range from >= k other shards and reconstruct.

        Local shards are read first (cheap); the remaining need is
        fanned out CONCURRENTLY to every remote candidate via
        remote_shards_fetcher, first-k-wins under ec_read_deadline —
        the reference fans out one goroutine per shard the same way
        (store_ec.go:349-393); a serial walk would pay ≥10 sequential
        RTTs and a single hung peer would stall the read forever.

        Structured codes first consult their repair plan: an LRC heals
        a single lost shard from its locality group (fan-in k/l), so
        the ladder reads a handful of shards instead of k. The generic
        gather below stays as the fallback for multi-loss and for plan
        shards that turn out unreachable — it collects shards until
        their encode rows reach GF(256) rank k, NOT until k shards are
        in hand: structured codes carry dependent rows (an LRC local
        parity is the XOR of its group), so a first-k-by-count set can
        be rank-deficient while independent shards sit reachable."""
        if not ecv.code.is_rs:
            data = self._reconstruct_planned(ecv, missing_sid, offset,
                                             size)
            if data is not None:
                return data
        code = ecv.code
        rows: dict[int, np.ndarray] = {}
        span: list[int] = []   # shard ids backing rows; full-rank by invariant

        def grows(sid: int) -> bool:
            # for RS any <= k distinct shards are independent, so rank
            # is the count and the matrix check is skipped
            if len(span) >= ecv.k:
                return False
            if code.is_rs:
                return True
            from ..ops import rs_matrix

            return rs_matrix.rank_of(code, span + [sid]) > len(span)

        candidates: list[int] = []
        for sid in range(ecv.total):
            if sid == missing_sid:
                continue
            shard = ecv.shards.get(sid)
            if shard is None:
                candidates.append(sid)
            elif grows(sid):
                rows[sid] = np.frombuffer(
                    shard.read_at(offset, size), dtype=np.uint8)
                span.append(sid)
        while len(span) < ecv.k and candidates:
            need = ecv.k - len(span)
            got: dict[int, bytes] = {}
            if self.remote_shards_fetcher is not None:
                got = self.remote_shards_fetcher(
                    ecv.vid, candidates, offset, size, need,
                    self.ec_read_deadline)
            elif self.remote_shard_reader is not None:
                # legacy serial fallback (tools / tests without a server)
                for sid in list(candidates):
                    if len(got) >= need:
                        break
                    candidates.remove(sid)  # tried: never re-asked
                    data = self.remote_shard_reader(
                        ecv.vid, sid, offset, size)
                    if data is not None:
                        got[sid] = data
            if not got:
                break
            for sid in sorted(got):
                if grows(sid):
                    rows[sid] = np.frombuffer(got[sid], dtype=np.uint8)
                    span.append(sid)
            # responders that didn't grow the span are dropped from the
            # candidate list so the retry round asks for NEW shards
            candidates = [s for s in candidates if s not in got]
        if len(span) < ecv.k:
            raise IOError(
                f"cannot reconstruct shard {missing_sid} of volume "
                f"{ecv.vid}: only {len(rows)} shards reachable")
        rec = self._rs_for(ecv, interval=True).reconstruct(
            rows, [missing_sid])
        return rec[missing_sid].tobytes()

    def _reconstruct_planned(self, ecv: EcVolume, missing_sid: int,
                             offset: int, size: int) -> bytes | None:
        """Repair-plan fast path: read exactly the code's planned
        fan-in for this single loss (the locality group for an LRC
        data/local shard). Returns None — falling back to the generic
        >= k ladder — when the plan doesn't beat k reads or one of its
        shards is unreachable."""
        plan = ecv.code.repair_plan(
            [missing_sid],
            [s for s in range(ecv.total) if s != missing_sid])
        if plan is None or plan.fanin >= ecv.k:
            return None
        rows: dict[int, np.ndarray] = {}
        remote: list[int] = []
        for sid in plan.reads:
            shard = ecv.shards.get(sid)
            if shard is not None:
                rows[sid] = np.frombuffer(
                    shard.read_at(offset, size), dtype=np.uint8)
            else:
                remote.append(sid)
        if remote:
            if self.remote_shards_fetcher is not None:
                got = self.remote_shards_fetcher(
                    ecv.vid, remote, offset, size, len(remote),
                    self.ec_read_deadline)
                for sid, data in got.items():
                    rows[sid] = np.frombuffer(data, dtype=np.uint8)
            elif self.remote_shard_reader is not None:
                for sid in remote:
                    data = self.remote_shard_reader(
                        ecv.vid, sid, offset, size)
                    if data is not None:
                        rows[sid] = np.frombuffer(data, dtype=np.uint8)
        if set(rows) != set(plan.reads):
            return None
        rec = self._rs_for(ecv, interval=True).reconstruct(
            rows, [missing_sid])
        return rec[missing_sid].tobytes()

    def _rs_for(self, ecv: EcVolume, *,
                interval: bool = False) -> ReedSolomon:
        """Per-codec ReedSolomon, cached — wide-code volumes carry their
        own (k, m) from the .vif sidecar.

        interval=True pins the CPU codec (native/numpy) regardless of
        the configured device backend: a single-needle degraded read
        reconstructs a few KB on a GET's critical path, where a device
        dispatch (jit compile + host<->device DMA, measured ~1.6s cold)
        is pure latency with zero throughput payoff.  Whole-volume
        encode/rebuild keeps the configured backend — that's where the
        device's bandwidth actually wins."""
        backend = ec_cpu_backend() if interval else self.ec_backend
        # a real EcVolume carries .code from the .vif sidecar; bare
        # (k, m) stand-ins fall back to the plain RS family
        code = getattr(ecv, "code", None) or \
            geo.parse_code("%d.%d" % (ecv.k, ecv.m))
        if not interval and code.is_rs and \
                (ecv.k, ecv.m) == (geo.DATA_SHARDS, geo.PARITY_SHARDS):
            return self._rs
        cache = getattr(self, "_rs_cache", None)
        if cache is None:
            cache = self._rs_cache = {}
        rs = cache.get((code.spec, backend))
        if rs is None:
            rs = cache[(code.spec, backend)] = ReedSolomon(
                ecv.k, ecv.m, backend=backend, code=code)
        return rs

    # -- cold-tier offload / recall (remote_storage clients) -------------
    def _manifest_path(self, ecv: EcVolume) -> str:
        return ecv.base_name() + ".rsm"

    def _load_manifest(self, ecv: EcVolume) -> dict | None:
        try:
            with open(self._manifest_path(ecv), encoding="utf-8") as f:
                return json.load(f)
        except FileNotFoundError:
            return None

    def _save_manifest(self, ecv: EcVolume, man: dict) -> None:
        """Atomic write: a crash mid-offload must leave either the old
        or the new shard inventory, never a torn one."""
        path = self._manifest_path(ecv)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(man, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _mount_manifest_shards(self, ecv: EcVolume) -> None:
        """Re-mount remote-backed shards recorded in the manifest
        (volume-server restart while the volume is cold)."""
        man = self._load_manifest(ecv)
        if man is None:
            return
        client = remote_client_for(man["remote"])
        for sid_s, ent in man.get("shards", {}).items():
            sid = int(sid_s)
            prev = ecv.shards.get(sid)
            if prev is not None and not prev.remote:
                continue  # local file won a race with the manifest
            ecv.mount_remote_shard(sid, ent["key"], int(ent["size"]),
                                   client.read_file)

    def tier_offload_ec(self, vid: int, remote_conf: dict,
                        throttle: TierThrottle | None = None) -> dict:
        """Move this server's local shards of one EC volume to a
        remote tier; reads keep working through the remote-backed
        shard objects (degraded-read guard intact). Idempotent: shards
        already offloaded are skipped, keys are deterministic, and the
        manifest is persisted after every shard — a crash mid-offload
        resumes without duplicate remote objects or lost bytes."""
        ecv = self.ec_volumes.get(vid)
        if ecv is None:
            raise KeyError(f"ec volume {vid} not found")
        client = remote_client_for(remote_conf)
        man = self._load_manifest(ecv) or {
            "volume": vid, "collection": ecv.collection,
            "remote": remote_conf, "shards": {}}
        moved = 0
        offloaded: list[int] = []
        for sid in sorted(ecv.shards):
            shard = ecv.shards[sid]
            if shard.remote:
                continue  # already cold (resume after crash)
            key = tier_shard_key(ecv.collection, vid, sid)
            size = shard.size
            if throttle is not None:
                throttle(size)
            data = shard.read_at(0, size)
            client.write_file(key, data)
            # manifest BEFORE deleting the local file: worst case after
            # a crash is a re-upload over the same key, never data loss
            man["shards"][str(sid)] = {"key": key, "size": size}
            self._save_manifest(ecv, man)
            path = getattr(shard, "path", "")
            ecv.mount_remote_shard(sid, key, size, client.read_file)
            if path:
                try:
                    os.remove(path)
                except FileNotFoundError:
                    pass
            moved += size
            offloaded.append(sid)
        return {"volume": vid, "moved_bytes": moved,
                "offloaded": offloaded,
                "remote_shards": sorted(int(s) for s in man["shards"])}

    def tier_recall_ec(self, vid: int,
                       throttle: TierThrottle | None = None,
                       delete_remote: bool = True) -> dict:
        """Bring this server's offloaded shards back to local disk.
        Idempotent mirror of tier_offload_ec: already-local shards are
        skipped, downloads land via tmp+rename, and the remote objects
        plus manifest are removed only once every shard is local."""
        ecv = self.ec_volumes.get(vid)
        if ecv is None:
            raise KeyError(f"ec volume {vid} not found")
        man = self._load_manifest(ecv)
        if man is None:
            return {"volume": vid, "moved_bytes": 0, "recalled": []}
        client = remote_client_for(man["remote"])
        base = ecv.base_name()
        moved = 0
        recalled: list[int] = []
        for sid_s, ent in sorted(man.get("shards", {}).items()):
            sid = int(sid_s)
            shard = ecv.shards.get(sid)
            if shard is not None and not shard.remote:
                continue  # already recalled (resume after crash)
            size = int(ent["size"])
            if throttle is not None:
                throttle(size)
            data = client.read_file(ent["key"])
            path = base + geo.shard_ext(sid)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            if shard is not None:
                ecv.unmount_shard(sid)
            ecv.mount_shard(sid)
            for loc in self.locations:
                if loc.dir == ecv.dir:
                    loc.add_ec_shard(ecv.collection, vid, sid)
            moved += len(data)
            recalled.append(sid)
        if delete_remote:
            for ent in man.get("shards", {}).values():
                client.delete_file(ent["key"])
        try:
            os.remove(self._manifest_path(ecv))
        except FileNotFoundError:
            pass
        return {"volume": vid, "moved_bytes": moved,
                "recalled": recalled}

    def ec_remote_shards(self, vid: int) -> list[int]:
        ecv = self.ec_volumes.get(vid)
        if ecv is None:
            return []
        return sorted(sid for sid, s in ecv.shards.items() if s.remote)

    # -- heartbeat -------------------------------------------------------
    def collect_heartbeat(self) -> dict:
        """CollectHeartbeat (store.go:249): full volume + EC shard report
        for the master."""
        volumes = []
        for loc in self.locations:
            for vid, v in loc.volumes.items():
                volumes.append({
                    "id": vid,
                    "collection": v.collection,
                    "size": v.content_size(),
                    "file_count": v.nm.file_count,
                    "delete_count": v.nm.deleted_count,
                    "deleted_bytes": v.nm.deleted_bytes,
                    "read_only": v.read_only,
                    "replica_placement":
                        str(v.super_block.replica_placement),
                    "ttl": list(v.super_block.ttl),
                    "version": v.version,
                    # volume-TTL expiry decisions need the last write
                    # time (volume ttl, needle/volume_ttl.go)
                    "modified_at": v.modified_at_second(),
                    # heat signals for the master's tiering controller
                    **self.volume_heat(vid),
                })
        ec_shards = [
            {"id": vid, "collection": ecv.collection,
             "shard_bits": ecv.shard_bits().bits,
             # the .vif spec string, NOT a (k, m)-derived name: an LRC
             # can share RS(10,4)'s geometry (lrc-10.2.2) yet be a
             # different code, and the master's registry drives repair
             # planning for structured codes
             "codec": ecv.codec,
             # tiering: are this node's shards offloaded to the remote
             # tier, and how hot is the EC volume still being read
             "remote": bool(ecv.shards) and
             all(s.remote for s in ecv.shards.values()),
             **self.volume_heat(vid)}
            for vid, ecv in self.ec_volumes.items()
        ]
        hb = {
            "ip": self.ip, "port": self.port, "public_url": self.public_url,
            "max_volume_count": sum(l.max_volumes for l in self.locations),
            "volumes": volumes, "ec_shards": ec_shards,
        }
        if _sketch.enabled():
            # compact sketch encodings for the master's workload
            # aggregator; unknown keys are ignored by older masters
            hb["workload"] = self.workload_payload()
        return hb

    def close(self) -> None:
        for loc in self.locations:
            loc.close()
        for ecv in self.ec_volumes.values():
            ecv.close()
