"""Volume-file storage backends.

Mirrors the reference's plugin pattern (/root/reference/weed/storage/
backend/backend.go:15-45): a `StorageFile` is the random-access byte
store a volume's .dat lives on; factories are registered by type string so
tiered backends (s3, memory, ...) can be added without touching the
engine.
"""
from __future__ import annotations

import os
from typing import Callable, Protocol


class StorageFile(Protocol):
    def read_at(self, size: int, offset: int) -> bytes: ...
    def write_at(self, data: bytes, offset: int) -> int: ...
    def append(self, data: bytes) -> int: ...
    def truncate(self, size: int) -> None: ...
    def size(self) -> int: ...
    def sync(self) -> None: ...
    def close(self) -> None: ...
    @property
    def name(self) -> str: ...


class DiskFile:
    """Local-disk backend (backend/disk_file.go equivalent)."""

    def __init__(self, path: str, create: bool = False):
        mode = "r+b" if os.path.exists(path) else ("w+b" if create else None)
        if mode is None:
            raise FileNotFoundError(path)
        self._f = open(path, mode)
        self._path = path

    @property
    def name(self) -> str:
        return self._path

    def read_at(self, size: int, offset: int) -> bytes:
        self._f.seek(offset)
        return self._f.read(size)

    def write_at(self, data: bytes, offset: int) -> int:
        self._f.seek(offset)
        return self._f.write(data)

    def append(self, data: bytes) -> int:
        self._f.seek(0, os.SEEK_END)
        offset = self._f.tell()
        self._f.write(data)
        return offset

    def truncate(self, size: int) -> None:
        self._f.truncate(size)

    def size(self) -> int:
        self._f.flush()
        return os.fstat(self._f.fileno()).st_size

    def flush(self) -> None:
        """Userspace buffer -> OS (no fsync)."""
        self._f.flush()

    def sync(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        try:
            self._f.flush()
        finally:
            self._f.close()


class MemoryFile:
    """In-memory backend for tests and the memory_map analogue."""

    def __init__(self, name: str = "<memory>"):
        self._buf = bytearray()
        self._name = name

    @property
    def name(self) -> str:
        return self._name

    def read_at(self, size: int, offset: int) -> bytes:
        return bytes(self._buf[offset:offset + size])

    def write_at(self, data: bytes, offset: int) -> int:
        end = offset + len(data)
        if end > len(self._buf):
            self._buf.extend(b"\x00" * (end - len(self._buf)))
        self._buf[offset:end] = data
        return len(data)

    def append(self, data: bytes) -> int:
        offset = len(self._buf)
        self._buf.extend(data)
        return offset

    def truncate(self, size: int) -> None:
        del self._buf[size:]

    def size(self) -> int:
        return len(self._buf)

    def flush(self) -> None:
        pass

    def sync(self) -> None:
        pass

    def close(self) -> None:
        pass


_factories: dict[str, Callable[..., StorageFile]] = {
    "disk": DiskFile,
    "memory": MemoryFile,
}


def register(name: str, factory: Callable[..., StorageFile]) -> None:
    _factories[name] = factory


def create(kind: str, *args, **kwargs) -> StorageFile:
    try:
        return _factories[kind](*args, **kwargs)
    except KeyError:
        raise KeyError(f"unknown storage backend {kind!r}; "
                       f"known: {sorted(_factories)}") from None
