"""Volume-file storage backends.

Mirrors the reference's plugin pattern (/root/reference/weed/storage/
backend/backend.go:15-45): a `StorageFile` is the random-access byte
store a volume's .dat lives on; factories are registered by type string so
tiered backends (s3, memory, ...) can be added without touching the
engine.
"""
from __future__ import annotations

import os
import threading
from typing import Callable, Protocol


class StorageFile(Protocol):
    def read_at(self, size: int, offset: int) -> bytes: ...
    def write_at(self, data: bytes, offset: int) -> int: ...
    def append(self, data: bytes) -> int: ...
    def truncate(self, size: int) -> None: ...
    def size(self) -> int: ...
    def sync(self) -> None: ...
    def close(self) -> None: ...
    @property
    def name(self) -> str: ...


class DiskFile:
    """Local-disk backend (backend/disk_file.go equivalent)."""

    remote = False  # reads are page-cache, not network

    def __init__(self, path: str, create: bool = False):
        mode = "r+b" if os.path.exists(path) else ("w+b" if create else None)
        if mode is None:
            raise FileNotFoundError(path)
        self._f = open(path, mode)
        self._path = path
        # one lock per file: streaming readers (tail/incremental copy/
        # plain GETs) run in worker threads concurrently with appends;
        # an unguarded seek+write pair could land a record at a reader's
        # offset and destroy live data. Reads use pread so they never
        # move the shared file position.
        self._lock = threading.RLock()

    @property
    def name(self) -> str:
        return self._path

    def read_at(self, size: int, offset: int) -> bytes:
        # flush needs the lock (it touches the buffered writer); the
        # pread itself doesn't move the shared position, so the actual
        # disk read runs unlocked and GETs stay concurrent. The fd is
        # dup'ed under the lock: a bare cached fd number could be
        # closed by a concurrent compact commit and REUSED for the new
        # file, silently serving wrong bytes — the dup stays pinned to
        # the old file until we close it.
        with self._lock:
            self._f.flush()
            fd = os.dup(self._f.fileno())
        try:
            return os.pread(fd, size, offset)
        finally:
            os.close(fd)

    def write_at(self, data: bytes, offset: int) -> int:
        with self._lock:
            self._f.seek(offset)
            return self._f.write(data)

    def append(self, data: bytes) -> int:
        with self._lock:
            self._f.seek(0, os.SEEK_END)
            offset = self._f.tell()
            self._f.write(data)
            return offset

    def truncate(self, size: int) -> None:
        with self._lock:
            self._f.flush()
            self._f.truncate(size)

    def size(self) -> int:
        with self._lock:
            self._f.flush()
            return os.fstat(self._f.fileno()).st_size

    def flush(self) -> None:
        """Userspace buffer -> OS (no fsync)."""
        with self._lock:
            self._f.flush()

    def sync(self) -> None:
        with self._lock:
            self._f.flush()
            os.fsync(self._f.fileno())

    def datasync(self) -> None:
        """flush + fdatasync: forces the data and the size metadata
        needed to retrieve it, skipping the mtime journal ordering —
        ~3x cheaper than fsync on ext4 appends, which is what the
        group-commit batch flush amortizes."""
        with self._lock:
            self._f.flush()
            os.fdatasync(self._f.fileno())

    def close(self) -> None:
        with self._lock:
            try:
                self._f.flush()
            finally:
                self._f.close()


class MemoryFile:
    remote = False

    """In-memory backend for tests and the memory_map analogue."""

    def __init__(self, name: str = "<memory>"):
        self._buf = bytearray()
        self._name = name

    @property
    def name(self) -> str:
        return self._name

    def read_at(self, size: int, offset: int) -> bytes:
        return bytes(self._buf[offset:offset + size])

    def write_at(self, data: bytes, offset: int) -> int:
        end = offset + len(data)
        if end > len(self._buf):
            self._buf.extend(b"\x00" * (end - len(self._buf)))
        self._buf[offset:end] = data
        return len(data)

    def append(self, data: bytes) -> int:
        offset = len(self._buf)
        self._buf.extend(data)
        return offset

    def truncate(self, size: int) -> None:
        del self._buf[size:]

    def size(self) -> int:
        return len(self._buf)

    def flush(self) -> None:
        pass

    def sync(self) -> None:
        pass

    def close(self) -> None:
        pass


class S3RangeFile:
    """Read-only view of a tiered volume's .dat living in an
    S3-compatible bucket (backend/s3_backend/s3_backend.go
    S3BackendStorageFile): reads become ranged GETs; writes are
    forbidden — tiered volumes are read-only by construction
    (shell/command_volume_tier_upload.go marks them so first)."""

    remote = True  # every read is a network round trip

    def __init__(self, storage: "S3BackendStorage", key: str, size: int):
        self._storage = storage
        self._key = key
        self._size = size

    @property
    def name(self) -> str:
        return f"s3://{self._storage.bucket}/{self._key}"

    def read_at(self, size: int, offset: int) -> bytes:
        if offset >= self._size or size <= 0:
            return b""
        end = min(offset + size, self._size) - 1
        return self._storage.get_range(self._key, offset, end)

    def write_at(self, data: bytes, offset: int) -> int:
        raise PermissionError("tiered volume is read-only")

    def append(self, data: bytes) -> int:
        raise PermissionError("tiered volume is read-only")

    def truncate(self, size: int) -> None:
        raise PermissionError("tiered volume is read-only")

    def size(self) -> int:
        return self._size

    def flush(self) -> None:
        pass

    def sync(self) -> None:
        pass

    def close(self) -> None:
        pass


class S3BackendStorage:
    """One configured S3-compatible tier destination
    (backend/s3_backend/s3_backend.go S3BackendStorage): uploads a
    volume's .dat as one object, serves ranged reads back, deletes on
    un-tier. HTTP mechanics live in the shared s3.client.S3Client."""

    def __init__(self, id: str = "default", prefix: str = "", **conf):
        from ..s3.client import S3Client
        self.id = id
        self.prefix = prefix.strip("/")
        self._c = S3Client(**conf)
        self.bucket = self._c.bucket

    @property
    def name(self) -> str:
        return f"s3.{self.id}"

    def object_key(self, filename: str) -> str:
        base = os.path.basename(filename)
        return f"{self.prefix}/{base}" if self.prefix else base

    def upload_file(self, f: StorageFile, key: str,
                    chunk: int = 64 << 20) -> int:
        """Move the .dat into the bucket; small files in one signed
        PUT, larger ones as a streamed PUT (the reference
        multipart-uploads via s3manager)."""
        total = f.size()
        if total <= chunk:
            self._c.put_object(key, f.read_at(total, 0))
            return total

        class _R:
            off = 0

            def read(self, n: int) -> bytes:
                blob = f.read_at(min(n, chunk), self.off)
                self.off += len(blob)
                return blob

        return self._c.put_stream(key, _R(), total)

    def get_range(self, key: str, start: int, end: int) -> bytes:
        return self._c.get_object(key, offset=start,
                                  size=end - start + 1)

    def download_to(self, key: str, dest_path: str) -> int:
        return self._c.download_to(key, dest_path)

    def delete(self, key: str) -> None:
        self._c.delete_object(key)

    def open_file(self, key: str, size: int) -> S3RangeFile:
        return S3RangeFile(self, key, size)


class MmapFile:
    remote = False

    """Memory-mapped volume file backend — the counterpart of the
    reference's memory_map backend (storage/backend/memory_map/, the
    `-memoryMapLimitMB` path): reads come straight out of the mapping,
    appends extend the file and remap. Best for read-heavy volumes
    whose working set fits the page cache."""

    # appends extend the backing file in GROW steps so a remap happens
    # once per megabyte, not once per record; the file is trimmed back
    # to the logical size on close. (After a crash the grow padding
    # survives as trailing zeros — the volume load scan walks them as
    # empty tombstones, same as any torn tail.)
    GROW = 1 << 20

    def __init__(self, path: str, create: bool = False):
        import mmap as _mmap

        mode = "r+b" if os.path.exists(path) else ("w+b" if create else None)
        if mode is None:
            raise FileNotFoundError(path)
        self._f = open(path, mode)
        self._path = path
        self._lock = threading.RLock()
        self._size = os.path.getsize(path)    # logical bytes
        self._mapped = self._size             # physical/mapped bytes
        self._mmap_mod = _mmap
        self._map = None
        self._remap()

    def _remap(self) -> None:
        if self._map is not None:
            self._map.close()
            self._map = None
        if self._mapped > 0:
            self._f.flush()
            self._map = self._mmap_mod.mmap(
                self._f.fileno(), self._mapped,
                access=self._mmap_mod.ACCESS_WRITE)

    @property
    def name(self) -> str:
        return self._path

    def read_at(self, size: int, offset: int) -> bytes:
        with self._lock:
            if offset >= self._size:
                return b""
            end = min(offset + size, self._size)
            return bytes(self._map[offset:end])

    def write_at(self, data: bytes, offset: int) -> int:
        with self._lock:
            end = offset + len(data)
            if end > self._mapped:
                grown = ((end + self.GROW - 1) // self.GROW) * self.GROW
                self._f.truncate(grown)
                self._mapped = grown
                self._remap()
            self._map[offset:end] = data
            self._size = max(self._size, end)
            return len(data)

    def append(self, data: bytes) -> int:
        with self._lock:
            offset = self._size
            self.write_at(data, offset)
            return offset

    def truncate(self, size: int) -> None:
        with self._lock:
            self._f.truncate(size)
            self._size = size
            self._mapped = size
            self._remap()

    def size(self) -> int:
        with self._lock:
            return self._size

    def flush(self) -> None:
        # mapped stores are already visible through the fd; nothing
        # buffered in userspace to push (DiskFile flushes its writer)
        pass

    def sync(self) -> None:
        with self._lock:
            if self._map is not None:
                self._map.flush()
            os.fsync(self._f.fileno())

    def close(self) -> None:
        with self._lock:
            if self._map is not None:
                self._map.close()
                self._map = None
            # drop the grow padding so the on-disk file ends at the
            # logical size (plain DiskFile can reopen it verbatim)
            try:
                self._f.truncate(self._size)
            except OSError:
                pass
            self._f.close()


class RcloneFile:
    """Placeholder for the rclone backend (backend/rclone_backend/):
    needs the rclone binary, which this environment does not ship.
    Marked unavailable so `create()` fails fast at construction with a
    clear error instead of a bare NotImplementedError at use time; a
    build that bundles rclone re-registers a real factory via
    `register("rclone", ...)`."""

    available = False
    unavailable_reason = ("needs the rclone binary on PATH, which this "
                          "build does not ship; tier to s3 instead "
                          "(backend 's3')")

    def __init__(self, *a, **kw):
        raise RuntimeError(
            f"backend 'rclone' not available in this build: "
            f"{self.unavailable_reason}")


_factories: dict[str, Callable[..., StorageFile]] = {
    "disk": DiskFile,
    "memory": MemoryFile,
    "mmap": MmapFile,
    "rclone": RcloneFile,
}

# configured tier destinations keyed "type.id" ("s3.default"), the
# BackendStorages registry of backend.go:44
_storages: dict[str, S3BackendStorage] = {}


def register(name: str, factory: Callable[..., StorageFile]) -> None:
    _factories[name] = factory


def create(kind: str, *args, **kwargs) -> StorageFile:
    try:
        factory = _factories[kind]
    except KeyError:
        raise KeyError(f"unknown storage backend {kind!r}; "
                       f"known: {sorted(_factories)}") from None
    if not getattr(factory, "available", True):
        # fail fast at construction, before any volume state exists
        raise RuntimeError(
            f"backend {kind!r} not available in this build: "
            f"{getattr(factory, 'unavailable_reason', 'unavailable')}")
    return factory(*args, **kwargs)


def configure_storage(name: str, **conf) -> S3BackendStorage:
    """Configure a tier destination; `name` is "s3.<id>"
    (LoadConfiguration, backend.go:50-70)."""
    btype, _, bid = name.partition(".")
    if btype != "s3":
        raise KeyError(f"unknown backend storage type {btype!r}")
    s = S3BackendStorage(id=bid or "default", **conf)
    _storages[s.name] = s
    return s


def get_storage(name: str) -> S3BackendStorage:
    try:
        return _storages[name]
    except KeyError:
        raise KeyError(f"backend storage {name!r} not configured; "
                       f"known: {sorted(_storages)}") from None


def storage_names() -> list[str]:
    return sorted(_storages)
