"""VolumeInfo `.vif` sidecar: per-volume metadata surviving restarts.

Equivalent of /root/reference/weed/storage/volume_info/volume_info.go
(SaveVolumeInfo / MaybeLoadVolumeInfo) persisting the protobuf
`VolumeInfo{files: []RemoteFile, version}` (volume_server.proto). Here
the sidecar is JSON — same role: it records which storage backend holds
the volume's `.dat` once it has been tiered off local disk
(weed/storage/backend/s3_backend), so a restarted server reopens the
remote copy instead of concluding the volume is gone.
"""
from __future__ import annotations

import json
import os
from dataclasses import asdict, dataclass, field


@dataclass
class RemoteFile:
    """One remote copy of the volume's .dat (pb.RemoteFile)."""

    backend_type: str = "s3"
    backend_id: str = "default"
    key: str = ""
    file_size: int = 0
    modified_time: int = 0

    @property
    def backend_name(self) -> str:
        """Registry key, e.g. "s3.default" (backend.go:42 registries)."""
        return f"{self.backend_type}.{self.backend_id}"


@dataclass
class VolumeInfo:
    version: int = 3
    replication: str = ""
    files: list[RemoteFile] = field(default_factory=list)
    # EC codec of this volume's shard set, "k.m" (empty = RS(10,4)
    # default). Beyond-reference: wide codes for cold collections.
    ec_codec: str = ""

    def remote_file(self) -> RemoteFile | None:
        return self.files[0] if self.files else None


def save_volume_info(path: str, vi: VolumeInfo) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(asdict(vi), f, indent=1)
    os.replace(tmp, path)


def maybe_load_volume_info(path: str) -> VolumeInfo | None:
    try:
        with open(path) as f:
            raw = json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None
    return VolumeInfo(
        version=raw.get("version", 3),
        replication=raw.get("replication", ""),
        files=[RemoteFile(**rf) for rf in raw.get("files", [])],
        ec_codec=raw.get("ec_codec", ""))
