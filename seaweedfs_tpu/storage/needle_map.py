"""In-memory needle maps.

The reference keeps three index-persistence strategies (memory / leveldb /
sorted-file, weed/storage/needle_map*.go) over a compact sharded map
(needle_map/compact_map.go:28). Here the core map is a python dict over
vectorized numpy loads — idiomatic and fast enough for the control plane;
the batched scrub/EC paths never touch it per-needle, they consume whole
index columns (storage/idx.py).

MemDb mirrors needle_map/memdb.go: an insert-ordered map with an
ascending-key visit used to produce sorted .ecx files
(/root/reference/weed/storage/erasure_coding/ec_encoder.go:27-55).
"""
from __future__ import annotations

import os
from typing import Callable, Iterator

import numpy as np

from . import idx as idxmod
from . import types as t


class NeedleMap:
    """Live per-volume map: key -> (offset, size), with accounting
    mirroring the reference's mapMetric (file/deleted counts and bytes)."""

    def __init__(self) -> None:
        self._m: dict[int, tuple[int, int]] = {}
        self.file_count = 0
        self.deleted_count = 0
        self.file_bytes = 0
        self.deleted_bytes = 0
        self.max_key = 0

    def __len__(self) -> int:
        return len(self._m)

    def get(self, key: int) -> tuple[int, int] | None:
        """-> (stored offset, size) for live needles, else None."""
        v = self._m.get(key)
        if v is None or t.size_is_deleted(v[1]):
            return None
        return v

    def put(self, key: int, offset: int, size: int) -> None:
        old = self._m.get(key)
        if old is not None and t.size_is_valid(old[1]):
            self.deleted_count += 1
            self.deleted_bytes += old[1]
            self.file_count -= 1
            self.file_bytes -= old[1]
        self._m[key] = (offset, size)
        if t.size_is_valid(size):
            self.file_count += 1
            self.file_bytes += size
        self.max_key = max(self.max_key, key)

    def delete(self, key: int) -> int:
        """Mark deleted; returns reclaimed bytes (0 if absent)."""
        old = self._m.get(key)
        if old is None or not t.size_is_valid(old[1]):
            return 0
        self._m[key] = (old[0], t.TOMBSTONE_SIZE)
        self.deleted_count += 1
        self.deleted_bytes += old[1]
        self.file_count -= 1
        self.file_bytes -= old[1]
        return old[1]

    def items(self) -> Iterator[tuple[int, int, int]]:
        for k, (off, size) in self._m.items():
            yield k, off, size

    def live_items(self) -> Iterator[tuple[int, int, int]]:
        for k, (off, size) in self._m.items():
            if t.size_is_valid(size):
                yield k, off, size

    def deleted_keys(self) -> Iterator[int]:
        """Keys with a tombstone — the delete half of the replica-sync
        census (volume.check.disk must propagate deletes, not resurrect
        the stale live copy)."""
        for k, (_off, size) in self._m.items():
            if t.size_is_deleted(size):
                yield k


def load_needle_map(idx_path: str) -> NeedleMap:
    """Replay an .idx log into a live map (needle_map_memory.go
    LoadCompactNeedleMap equivalent): later entries win; tombstones
    (size<0 or offset==0&&size==0 per reference semantics) delete."""
    nm = NeedleMap()
    if not os.path.exists(idx_path):
        return nm
    arr = idxmod.read_index(idx_path)
    for rec in arr:
        key = int(rec["key"])
        off = int(rec["offset"])
        size = t.u32_to_size(int(rec["size"]))
        if off > 0 and t.size_is_valid(size):
            nm.put(key, off, size)
        else:
            nm.delete(key)
    return nm


class MemDb:
    """Sorted-visit map used for .ecx generation and idx compaction."""

    def __init__(self) -> None:
        self._m: dict[int, tuple[int, int]] = {}

    def set(self, key: int, offset: int, size: int) -> None:
        self._m[key] = (offset, size)

    def delete(self, key: int) -> None:
        self._m.pop(key, None)

    def get(self, key: int) -> tuple[int, int] | None:
        return self._m.get(key)

    def __len__(self) -> int:
        return len(self._m)

    def ascending_visit(self, fn: Callable[[int, int, int], None]) -> None:
        for key in sorted(self._m):
            off, size = self._m[key]
            fn(key, off, size)

    def load_from_idx(self, idx_path: str) -> None:
        """Replay .idx: valid entries set, tombstones remove
        (needle_map/memdb.go LoadFromIdx semantics)."""
        arr = idxmod.read_index(idx_path)
        for rec in arr:
            key = int(rec["key"])
            off = int(rec["offset"])
            size = t.u32_to_size(int(rec["size"]))
            if off == 0 or t.size_is_deleted(size):
                self._m.pop(key, None)
            else:
                self._m[key] = (off, size)

    def save_to_idx(self, idx_path: str) -> None:
        keys = sorted(self._m)
        arr = np.empty(len(keys), dtype=idxmod.IDX_DTYPE)
        for i, k in enumerate(keys):
            off, size = self._m[k]
            arr[i] = (k, off, t.size_to_u32(size))
        idxmod.write_index(idx_path, arr)
