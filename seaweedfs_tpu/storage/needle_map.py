"""In-memory needle maps.

The reference keeps three index-persistence strategies (memory / leveldb /
sorted-file, weed/storage/needle_map*.go) over a compact sharded map
(needle_map/compact_map.go:28). Here the core map is a python dict over
vectorized numpy loads — idiomatic and fast enough for the control plane;
the batched scrub/EC paths never touch it per-needle, they consume whole
index columns (storage/idx.py).

MemDb mirrors needle_map/memdb.go: an insert-ordered map with an
ascending-key visit used to produce sorted .ecx files
(/root/reference/weed/storage/erasure_coding/ec_encoder.go:27-55).
"""
from __future__ import annotations

import os
from typing import Callable, Iterator

import numpy as np

from . import idx as idxmod
from . import types as t

OFFSET_DTYPE = np.uint32 if t.OFFSET_SIZE == 4 else np.uint64


class NeedleMap:
    """Live per-volume map: key -> (offset, size), with accounting
    mirroring the reference's mapMetric (file/deleted counts and bytes)."""

    def __init__(self) -> None:
        self._m: dict[int, tuple[int, int]] = {}
        self.file_count = 0
        self.deleted_count = 0
        self.file_bytes = 0
        self.deleted_bytes = 0
        self.max_key = 0

    def __len__(self) -> int:
        return len(self._m)

    def get(self, key: int) -> tuple[int, int] | None:
        """-> (stored offset, size) for live needles, else None."""
        v = self._m.get(key)
        if v is None or t.size_is_deleted(v[1]):
            return None
        return v

    def get_any(self, key: int) -> tuple[int, int] | None:
        """Raw entry including tombstones (size<0) — the
        ?readDeleted=true read path (volume_read.go:29)."""
        return self._m.get(key)

    def put(self, key: int, offset: int, size: int) -> None:
        old = self._m.get(key)
        if old is not None and t.size_is_valid(old[1]):
            self.deleted_count += 1
            self.deleted_bytes += old[1]
            self.file_count -= 1
            self.file_bytes -= old[1]
        self._m[key] = (offset, size)
        if t.size_is_valid(size):
            self.file_count += 1
            self.file_bytes += size
        self.max_key = max(self.max_key, key)

    def delete(self, key: int) -> int:
        """Mark deleted; returns reclaimed bytes (0 if absent)."""
        old = self._m.get(key)
        if old is None or not t.size_is_valid(old[1]):
            return 0
        self._m[key] = (old[0], t.TOMBSTONE_SIZE)
        self.deleted_count += 1
        self.deleted_bytes += old[1]
        self.file_count -= 1
        self.file_bytes -= old[1]
        return old[1]

    def items(self) -> Iterator[tuple[int, int, int]]:
        for k, (off, size) in self._m.items():
            yield k, off, size

    def live_items(self) -> Iterator[tuple[int, int, int]]:
        for k, (off, size) in self._m.items():
            if t.size_is_valid(size):
                yield k, off, size

    def deleted_keys(self) -> Iterator[int]:
        """Keys with a tombstone — the delete half of the replica-sync
        census (volume.check.disk must propagate deletes, not resurrect
        the stale live copy)."""
        for k, (_off, size) in self._m.items():
            if t.size_is_deleted(size):
                yield k


def new_needle_map(kind: str = "memory", idx_path: str = ""):
    """Fresh, empty map of the configured strategy — rebuild paths must
    honor the kind too, or a compact-configured node falls back to the
    dict map's ~6x memory after crash recovery."""
    if kind == "compact":
        return CompactNeedleMap()
    if kind == "btree":
        if not idx_path:
            raise ValueError("btree needle map needs the idx path")
        nm = BtreeNeedleMap(idx_path)
        nm.clear()
        return nm
    if kind != "memory":
        raise ValueError(f"unknown needle map kind {kind!r}")
    return NeedleMap()


def load_needle_map(idx_path: str, kind: str = "memory"):
    """Replay an .idx log into a live map (needle_map_memory.go
    LoadCompactNeedleMap equivalent): later entries win; tombstones
    (size<0 or offset==0&&size==0 per reference semantics) delete.
    kind selects the strategy: "memory" (dict), "compact" (sorted
    numpy array, needle_map_kind in store.go:57), or "btree" (on-disk
    sqlite sidecar — the reference's -index=leveldb analog)."""
    if kind == "compact":
        return load_compact_needle_map(idx_path)
    if kind == "btree":
        return load_btree_needle_map(idx_path)
    if kind != "memory":
        raise ValueError(f"unknown needle map kind {kind!r}")
    nm = new_needle_map(kind)
    if not os.path.exists(idx_path):
        return nm
    arr = idxmod.read_index(idx_path)
    for rec in arr:
        key = int(rec["key"])
        off = int(rec["offset"])
        size = t.u32_to_size(int(rec["size"]))
        if off > 0 and t.size_is_valid(size):
            nm.put(key, off, size)
        else:
            nm.delete(key)
    return nm


class MemDb:
    """Sorted-visit map used for .ecx generation and idx compaction."""

    def __init__(self) -> None:
        self._m: dict[int, tuple[int, int]] = {}

    def set(self, key: int, offset: int, size: int) -> None:
        self._m[key] = (offset, size)

    def delete(self, key: int) -> None:
        self._m.pop(key, None)

    def get(self, key: int) -> tuple[int, int] | None:
        return self._m.get(key)

    def __len__(self) -> int:
        return len(self._m)

    def ascending_visit(self, fn: Callable[[int, int, int], None]) -> None:
        for key in sorted(self._m):
            off, size = self._m[key]
            fn(key, off, size)

    def load_from_idx(self, idx_path: str) -> None:
        """Replay .idx: valid entries set, tombstones remove
        (needle_map/memdb.go LoadFromIdx semantics)."""
        arr = idxmod.read_index(idx_path)
        for rec in arr:
            key = int(rec["key"])
            off = int(rec["offset"])
            size = t.u32_to_size(int(rec["size"]))
            if off == 0 or t.size_is_deleted(size):
                self._m.pop(key, None)
            else:
                self._m[key] = (off, size)

    def save_to_idx(self, idx_path: str) -> None:
        keys = sorted(self._m)
        arr = np.empty(len(keys), dtype=idxmod.IDX_DTYPE)
        for i, k in enumerate(keys):
            off, size = self._m[k]
            arr[i] = (k, off, t.size_to_u32(size))
        idxmod.write_index(idx_path, arr)


class CompactNeedleMap:
    """Memory-frugal needle map: the loaded index is a sorted numpy
    structured array (16 bytes/needle, the compact_map.go:28 goal —
    a python dict burns ~100 bytes/needle) probed by binary search,
    with a small dict overlay for writes since load. The overlay is
    merged into the array when it grows past OVERLAY_LIMIT, keeping
    lookups O(log n) and memory O(n * 16B).

    Same surface and metric fields as NeedleMap; selected per volume
    with needle_map_kind="compact" (needle_map_kind, store.go:57).
    """

    OVERLAY_LIMIT = 8192

    def __init__(self) -> None:
        self._keys = np.empty(0, dtype=np.uint64)
        # u32 holds 4-byte offsets; the 5BytesOffset variant needs
        # u64 or offsets past 32GB would silently truncate mod 2^32
        self._offsets = np.empty(0, dtype=OFFSET_DTYPE)
        self._sizes = np.empty(0, dtype=np.int64)  # -1 = tombstone
        self._overlay: dict[int, tuple[int, int]] = {}
        self.file_count = 0
        self.deleted_count = 0
        self.file_bytes = 0
        self.deleted_bytes = 0
        self.max_key = 0

    def __len__(self) -> int:
        base = len(self._keys)
        novel = sum(1 for k in self._overlay
                    if not self._base_has(k))
        return base + novel

    def _base_has(self, key: int) -> bool:
        i = int(np.searchsorted(self._keys, np.uint64(key)))
        return i < len(self._keys) and int(self._keys[i]) == key

    def _base_get(self, key: int) -> tuple[int, int] | None:
        i = int(np.searchsorted(self._keys, np.uint64(key)))
        if i < len(self._keys) and int(self._keys[i]) == key:
            return int(self._offsets[i]), int(self._sizes[i])
        return None

    def _lookup(self, key: int) -> tuple[int, int] | None:
        if key in self._overlay:
            return self._overlay[key]
        return self._base_get(key)

    def get(self, key: int) -> tuple[int, int] | None:
        v = self._lookup(key)
        if v is None or t.size_is_deleted(v[1]):
            return None
        return v

    def get_any(self, key: int) -> tuple[int, int] | None:
        """Raw entry including tombstones (readDeleted path)."""
        return self._lookup(key)

    def put(self, key: int, offset: int, size: int) -> None:
        old = self._lookup(key)
        if old is not None and t.size_is_valid(old[1]):
            self.deleted_count += 1
            self.deleted_bytes += old[1]
            self.file_count -= 1
            self.file_bytes -= old[1]
        self._overlay[key] = (offset, size)
        if t.size_is_valid(size):
            self.file_count += 1
            self.file_bytes += size
        self.max_key = max(self.max_key, key)
        self._maybe_merge()

    def delete(self, key: int) -> int:
        old = self._lookup(key)
        if old is None or not t.size_is_valid(old[1]):
            return 0
        self._overlay[key] = (old[0], t.TOMBSTONE_SIZE)
        self.deleted_count += 1
        self.deleted_bytes += old[1]
        self.file_count -= 1
        self.file_bytes -= old[1]
        self._maybe_merge()
        return old[1]

    def _maybe_merge(self) -> None:
        if len(self._overlay) >= self.OVERLAY_LIMIT:
            self.merge_overlay()

    def merge_overlay(self) -> None:
        if not self._overlay:
            return
        ok = np.fromiter(self._overlay.keys(), dtype=np.uint64,
                         count=len(self._overlay))
        ov = np.array([v for v in self._overlay.values()],
                      dtype=np.int64).reshape(-1, 2)
        keys = np.concatenate([self._keys, ok])
        offsets = np.concatenate([self._offsets,
                                  ov[:, 0].astype(OFFSET_DTYPE)])
        sizes = np.concatenate([self._sizes, ov[:, 1]])
        # stable sort + keep the LAST occurrence of each key (overlay
        # entries were appended after the base, so they win)
        order = np.argsort(keys, kind="stable")
        keys, offsets, sizes = keys[order], offsets[order], sizes[order]
        keep = np.ones(len(keys), dtype=bool)
        keep[:-1] = keys[:-1] != keys[1:]
        self._keys = keys[keep]
        self._offsets = offsets[keep]
        self._sizes = sizes[keep]
        self._overlay = {}

    def items(self) -> Iterator[tuple[int, int, int]]:
        self.merge_overlay()
        for i in range(len(self._keys)):
            yield (int(self._keys[i]), int(self._offsets[i]),
                   int(self._sizes[i]))

    def live_items(self) -> Iterator[tuple[int, int, int]]:
        for k, off, size in self.items():
            if t.size_is_valid(size):
                yield k, off, size

    def deleted_keys(self) -> Iterator[int]:
        for k, _off, size in self.items():
            if t.size_is_deleted(size):
                yield k


def load_compact_needle_map(idx_path: str) -> CompactNeedleMap:
    """Vectorized .idx replay into a CompactNeedleMap: one structured
    read, later-entries-win dedupe and metric computation all as numpy
    column ops (the TPU-idiomatic version of
    needle_map_memory.go LoadCompactNeedleMap)."""
    nm = CompactNeedleMap()
    if not os.path.exists(idx_path):
        return nm
    arr = idxmod.read_index(idx_path)
    if len(arr) == 0:
        return nm
    keys = arr["key"].astype(np.uint64)
    offsets = arr["offset"].astype(OFFSET_DTYPE)
    sizes = arr["size"].astype(np.int64)
    sizes = np.where(sizes >= 0x80000000, sizes - (1 << 32), sizes)
    # tombstone rows delete; size-0 rows count as deletes too, exactly
    # like the memory loader's `off > 0 and size_is_valid(size)` test —
    # the two kinds must produce identical live-sets from one .idx
    dead = (offsets == 0) | (sizes <= 0)
    sizes = np.where(dead, np.int64(t.TOMBSTONE_SIZE), sizes)
    # later entries win: stable sort by key keeps append order within
    # a key; take each key's last row
    order = np.argsort(keys, kind="stable")
    keys, offsets, sizes = keys[order], offsets[order], sizes[order]
    keep = np.ones(len(keys), dtype=bool)
    keep[:-1] = keys[:-1] != keys[1:]
    # count a key as "deleted" only if its final row is a tombstone;
    # overwritten intermediate rows add to deleted_bytes like the
    # incremental path does
    shadowed_sizes = sizes[~keep]
    nm._keys = keys[keep]
    nm._offsets = offsets[keep]
    nm._sizes = sizes[keep]
    live = nm._sizes >= 0
    nm.file_count = int(np.count_nonzero(live))
    nm.file_bytes = int(nm._sizes[live].sum())
    # every shadowed live row was ended by exactly one overwrite or
    # tombstone — the same events the incremental path counts
    shadowed_live = shadowed_sizes[shadowed_sizes >= 0]
    nm.deleted_count = int(len(shadowed_live))
    nm.deleted_bytes = int(shadowed_live.sum())
    nm.max_key = int(nm._keys[-1]) if len(nm._keys) else 0
    return nm


class BtreeNeedleMap:
    """On-disk needle index: the reference's third strategy
    (needle_map_leveldb.go, `-index=leveldb`) for servers whose needle
    maps don't fit RAM. sqlite's B-tree plays the leveldb role — O(log
    n) key probes with O(1) resident memory; only the map METRICS
    (file/deleted counts and bytes, mapMetric) live in RAM.

    Startup rides a watermark like the reference's
    (needle_map_leveldb.go:70 levelDbWrite watermark): the sidecar
    remembers how many .idx bytes it reflects; reopening replays only
    the .idx TAIL past the watermark (later-wins, idempotent), and a
    truncated .idx (vacuum commit) triggers a full rebuild.
    """

    def __init__(self, idx_path: str):
        import sqlite3

        self.db_path = idx_path + ".bdb"
        self._db = sqlite3.connect(self.db_path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=OFF")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS needles ("
            "key INTEGER PRIMARY KEY, offset INTEGER, size INTEGER)")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS meta (k TEXT PRIMARY KEY, v)")
        self._lock = __import__("threading").RLock()
        self._dirty = 0
        self.file_count = 0
        self.deleted_count = 0
        self.file_bytes = 0
        self.deleted_bytes = 0
        self.max_key = 0
        self._load_metrics()

    # -- metrics persistence (mapMetric analog) -------------------------
    METRIC_KEYS = ("file_count", "deleted_count", "file_bytes",
                   "deleted_bytes", "max_key")

    def _load_metrics(self) -> None:
        rows = dict(self._db.execute("SELECT k, v FROM meta"))
        for k in self.METRIC_KEYS:
            setattr(self, k, int(rows.get(k, 0)))

    def _save_metrics(self) -> None:
        self._db.executemany(
            "INSERT OR REPLACE INTO meta (k, v) VALUES (?, ?)",
            [(k, getattr(self, k)) for k in self.METRIC_KEYS])

    def watermark(self) -> int:
        # sqlite connections are not safe for unsynchronized concurrent
        # use even with check_same_thread=False
        with self._lock:
            row = self._db.execute(
                "SELECT v FROM meta WHERE k='idx_bytes'").fetchone()
        return int(row[0]) if row else 0

    def set_watermark(self, idx_bytes: int) -> None:
        with self._lock:
            self._save_metrics()
            self._db.execute(
                "INSERT OR REPLACE INTO meta (k, v) VALUES "
                "('idx_bytes', ?)", (idx_bytes,))
            self._db.commit()
            self._dirty = 0

    def clear(self) -> None:
        with self._lock:
            self._db.execute("DELETE FROM needles")
            self._db.execute("DELETE FROM meta")
            for k in self.METRIC_KEYS:
                setattr(self, k, 0)
            self._db.commit()

    # -- signed-size storage: rows keep tombstones (size<0) so the
    # deleted-keys census works without the .idx
    def _lookup(self, key: int) -> tuple[int, int] | None:
        row = self._db.execute(
            "SELECT offset, size FROM needles WHERE key=?",
            (key,)).fetchone()
        return (int(row[0]), int(row[1])) if row else None

    def __len__(self) -> int:
        with self._lock:
            return int(self._db.execute(
                "SELECT COUNT(*) FROM needles").fetchone()[0])

    def get(self, key: int) -> tuple[int, int] | None:
        import sqlite3

        try:
            with self._lock:
                v = self._lookup(key)
        except sqlite3.ProgrammingError as e:
            # a vacuum commit closed this map object under a concurrent
            # unlocked reader; OSError routes the caller into the
            # locked retry, which re-reads the volume's NEW map
            raise OSError(f"needle map closed: {e}") from e
        if v is None or t.size_is_deleted(v[1]):
            return None
        return v

    def get_any(self, key: int) -> tuple[int, int] | None:
        """Raw row including tombstones (readDeleted path)."""
        with self._lock:
            return self._lookup(key)

    # no standalone commit cadence here: transaction sizing is owned by
    # the group-commit scheduler (storage/commit.py), whose batch close
    # calls sync()/set_watermark so idx durability matches .dat acks
    def put(self, key: int, offset: int, size: int) -> None:
        with self._lock:
            old = self._lookup(key)
            if old == (offset, size):
                # identical row: watermark-tail replay after a crash
                # re-applies committed puts — counting them as
                # overwrites would inflate deleted_count/bytes
                return
            if old is not None and t.size_is_valid(old[1]):
                self.deleted_count += 1
                self.deleted_bytes += old[1]
                self.file_count -= 1
                self.file_bytes -= old[1]
            self._db.execute(
                "INSERT OR REPLACE INTO needles (key, offset, size) "
                "VALUES (?, ?, ?)", (key, offset, size))
            if t.size_is_valid(size):
                self.file_count += 1
                self.file_bytes += size
            self.max_key = max(self.max_key, key)
            self._dirty += 1

    def delete(self, key: int) -> int:
        with self._lock:
            old = self._lookup(key)
            if old is None or not t.size_is_valid(old[1]):
                return 0
            self._db.execute(
                "UPDATE needles SET size=? WHERE key=?",
                (t.TOMBSTONE_SIZE, key))
            self.deleted_count += 1
            self.deleted_bytes += old[1]
            self.file_count -= 1
            self.file_bytes -= old[1]
            self._dirty += 1
            return old[1]

    def recount_live(self) -> None:
        """Recompute file_count/file_bytes from the rows (one SQL
        aggregate, no Python materialization) — used after a tail
        replay, where interleaved crash windows can drift the
        incremental counters."""
        with self._lock:
            row = self._db.execute(
                "SELECT COUNT(*), COALESCE(SUM(size), 0) FROM needles "
                "WHERE size >= 0").fetchone()
            self.file_count, self.file_bytes = int(row[0]), int(row[1])
            row = self._db.execute(
                "SELECT COALESCE(MAX(key), 0) FROM needles").fetchone()
            self.max_key = max(self.max_key, int(row[0]))

    ITEMS_BATCH = 4096

    def items(self) -> Iterator[tuple[int, int, int]]:
        # keyset pagination, NOT fetchall: this map exists for volumes
        # whose index doesn't fit RAM — scrub/compact iteration must
        # stay O(batch) resident
        with self._lock:
            self._db.commit()
        last = -1
        while True:
            with self._lock:
                rows = self._db.execute(
                    "SELECT key, offset, size FROM needles "
                    "WHERE key > ? ORDER BY key LIMIT ?",
                    (last, self.ITEMS_BATCH)).fetchall()
            if not rows:
                return
            for k, off, size in rows:
                yield int(k), int(off), int(size)
            last = int(rows[-1][0])

    def live_items(self) -> Iterator[tuple[int, int, int]]:
        for k, off, size in self.items():
            if t.size_is_valid(size):
                yield k, off, size

    def deleted_keys(self) -> Iterator[int]:
        for k, _off, size in self.items():
            if t.size_is_deleted(size):
                yield k

    def sync(self) -> None:
        with self._lock:
            self._db.commit()
            self._dirty = 0

    def close(self) -> None:
        with self._lock:
            try:
                self._save_metrics()
                self._db.commit()
                self._db.close()
            except Exception:
                pass


def load_btree_needle_map(idx_path: str) -> BtreeNeedleMap:
    """Open the .bdb sidecar and catch up from the .idx log tail past
    the watermark (full rebuild when the .idx shrank, i.e. a vacuum
    rewrote it). A corrupt sidecar (synchronous=OFF allows it after an
    OS crash) is dropped and rebuilt from the intact .idx, never fatal."""
    import sqlite3

    try:
        nm = BtreeNeedleMap(idx_path)
        mark = nm.watermark()
    except sqlite3.DatabaseError:
        drop_btree_sidecar(idx_path)
        nm = BtreeNeedleMap(idx_path)
        mark = 0
    idx_size = os.path.getsize(idx_path) if os.path.exists(idx_path) \
        else 0
    if mark > idx_size:
        nm.clear()  # idx rewritten shorter (vacuum commit): rebuild
        mark = 0
    if mark < idx_size:
        entry = t.NEEDLE_MAP_ENTRY_SIZE
        mark -= mark % entry  # torn tail of a previous run
        with open(idx_path, "rb") as f:
            f.seek(mark)
            blob = f.read(idx_size - mark)
        arr = idxmod.parse_index_bytes(blob)
        for rec in arr:
            key = int(rec["key"])
            off = int(rec["offset"])
            size = t.u32_to_size(int(rec["size"]))
            if off > 0 and t.size_is_valid(size):
                nm.put(key, off, size)
            else:
                nm.delete(key)
        # an unclean shutdown means the tail was replayed over rows the
        # db may already hold: idempotent re-application keeps the ROWS
        # right but cannot reconstruct overwrite/delete counters (the
        # original sizes are gone from the rows). The .idx has the full
        # history — recompute ALL metrics from it exactly, the same way
        # the compact loader does (garbage_ratio feeds vacuum decisions
        # and must not drift down).
        full = idxmod.read_index(idx_path)
        if len(full):
            import numpy as np

            keys = full["key"].astype(np.uint64)
            sizes = full["size"].astype(np.int64)
            sizes = np.where(sizes >= 0x80000000, sizes - (1 << 32),
                             sizes)
            offs = full["offset"].astype(np.uint64)
            dead = (offs == 0) | (sizes <= 0)
            sizes = np.where(dead, np.int64(t.TOMBSTONE_SIZE), sizes)
            order = np.argsort(keys, kind="stable")
            keys_s, sizes_s = keys[order], sizes[order]
            keep = np.ones(len(keys_s), dtype=bool)
            keep[:-1] = keys_s[:-1] != keys_s[1:]
            shadowed = sizes_s[~keep]
            shadowed_live = shadowed[shadowed >= 0]
            nm.deleted_count = int(len(shadowed_live))
            nm.deleted_bytes = int(shadowed_live.sum())
        nm.recount_live()
    nm.set_watermark(idx_size)
    return nm


def drop_btree_sidecar(idx_path: str) -> None:
    """Remove the .bdb sidecar (and WAL files) so the next open does a
    full rebuild — required whenever the .idx is REWRITTEN rather than
    appended (vacuum commit, index rebuild): the size-only watermark
    cannot detect same-size reordered content."""
    for suffix in (".bdb", ".bdb-wal", ".bdb-shm"):
        try:
            os.remove(idx_path + suffix)
        except FileNotFoundError:
            pass
