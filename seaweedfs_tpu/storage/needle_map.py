"""In-memory needle maps.

The reference keeps three index-persistence strategies (memory / leveldb /
sorted-file, weed/storage/needle_map*.go) over a compact sharded map
(needle_map/compact_map.go:28). Here the core map is a python dict over
vectorized numpy loads — idiomatic and fast enough for the control plane;
the batched scrub/EC paths never touch it per-needle, they consume whole
index columns (storage/idx.py).

MemDb mirrors needle_map/memdb.go: an insert-ordered map with an
ascending-key visit used to produce sorted .ecx files
(/root/reference/weed/storage/erasure_coding/ec_encoder.go:27-55).
"""
from __future__ import annotations

import os
from typing import Callable, Iterator

import numpy as np

from . import idx as idxmod
from . import types as t

OFFSET_DTYPE = np.uint32 if t.OFFSET_SIZE == 4 else np.uint64


class NeedleMap:
    """Live per-volume map: key -> (offset, size), with accounting
    mirroring the reference's mapMetric (file/deleted counts and bytes)."""

    def __init__(self) -> None:
        self._m: dict[int, tuple[int, int]] = {}
        self.file_count = 0
        self.deleted_count = 0
        self.file_bytes = 0
        self.deleted_bytes = 0
        self.max_key = 0

    def __len__(self) -> int:
        return len(self._m)

    def get(self, key: int) -> tuple[int, int] | None:
        """-> (stored offset, size) for live needles, else None."""
        v = self._m.get(key)
        if v is None or t.size_is_deleted(v[1]):
            return None
        return v

    def put(self, key: int, offset: int, size: int) -> None:
        old = self._m.get(key)
        if old is not None and t.size_is_valid(old[1]):
            self.deleted_count += 1
            self.deleted_bytes += old[1]
            self.file_count -= 1
            self.file_bytes -= old[1]
        self._m[key] = (offset, size)
        if t.size_is_valid(size):
            self.file_count += 1
            self.file_bytes += size
        self.max_key = max(self.max_key, key)

    def delete(self, key: int) -> int:
        """Mark deleted; returns reclaimed bytes (0 if absent)."""
        old = self._m.get(key)
        if old is None or not t.size_is_valid(old[1]):
            return 0
        self._m[key] = (old[0], t.TOMBSTONE_SIZE)
        self.deleted_count += 1
        self.deleted_bytes += old[1]
        self.file_count -= 1
        self.file_bytes -= old[1]
        return old[1]

    def items(self) -> Iterator[tuple[int, int, int]]:
        for k, (off, size) in self._m.items():
            yield k, off, size

    def live_items(self) -> Iterator[tuple[int, int, int]]:
        for k, (off, size) in self._m.items():
            if t.size_is_valid(size):
                yield k, off, size

    def deleted_keys(self) -> Iterator[int]:
        """Keys with a tombstone — the delete half of the replica-sync
        census (volume.check.disk must propagate deletes, not resurrect
        the stale live copy)."""
        for k, (_off, size) in self._m.items():
            if t.size_is_deleted(size):
                yield k


def new_needle_map(kind: str = "memory"):
    """Fresh, empty map of the configured strategy — rebuild paths must
    honor the kind too, or a compact-configured node falls back to the
    dict map's ~6x memory after crash recovery."""
    if kind == "compact":
        return CompactNeedleMap()
    if kind != "memory":
        raise ValueError(f"unknown needle map kind {kind!r}")
    return NeedleMap()


def load_needle_map(idx_path: str, kind: str = "memory"):
    """Replay an .idx log into a live map (needle_map_memory.go
    LoadCompactNeedleMap equivalent): later entries win; tombstones
    (size<0 or offset==0&&size==0 per reference semantics) delete.
    kind selects the strategy: "memory" (dict) or "compact" (sorted
    numpy array, needle_map_kind in store.go:57)."""
    if kind == "compact":
        return load_compact_needle_map(idx_path)
    if kind != "memory":
        raise ValueError(f"unknown needle map kind {kind!r}")
    nm = new_needle_map(kind)
    if not os.path.exists(idx_path):
        return nm
    arr = idxmod.read_index(idx_path)
    for rec in arr:
        key = int(rec["key"])
        off = int(rec["offset"])
        size = t.u32_to_size(int(rec["size"]))
        if off > 0 and t.size_is_valid(size):
            nm.put(key, off, size)
        else:
            nm.delete(key)
    return nm


class MemDb:
    """Sorted-visit map used for .ecx generation and idx compaction."""

    def __init__(self) -> None:
        self._m: dict[int, tuple[int, int]] = {}

    def set(self, key: int, offset: int, size: int) -> None:
        self._m[key] = (offset, size)

    def delete(self, key: int) -> None:
        self._m.pop(key, None)

    def get(self, key: int) -> tuple[int, int] | None:
        return self._m.get(key)

    def __len__(self) -> int:
        return len(self._m)

    def ascending_visit(self, fn: Callable[[int, int, int], None]) -> None:
        for key in sorted(self._m):
            off, size = self._m[key]
            fn(key, off, size)

    def load_from_idx(self, idx_path: str) -> None:
        """Replay .idx: valid entries set, tombstones remove
        (needle_map/memdb.go LoadFromIdx semantics)."""
        arr = idxmod.read_index(idx_path)
        for rec in arr:
            key = int(rec["key"])
            off = int(rec["offset"])
            size = t.u32_to_size(int(rec["size"]))
            if off == 0 or t.size_is_deleted(size):
                self._m.pop(key, None)
            else:
                self._m[key] = (off, size)

    def save_to_idx(self, idx_path: str) -> None:
        keys = sorted(self._m)
        arr = np.empty(len(keys), dtype=idxmod.IDX_DTYPE)
        for i, k in enumerate(keys):
            off, size = self._m[k]
            arr[i] = (k, off, t.size_to_u32(size))
        idxmod.write_index(idx_path, arr)


class CompactNeedleMap:
    """Memory-frugal needle map: the loaded index is a sorted numpy
    structured array (16 bytes/needle, the compact_map.go:28 goal —
    a python dict burns ~100 bytes/needle) probed by binary search,
    with a small dict overlay for writes since load. The overlay is
    merged into the array when it grows past OVERLAY_LIMIT, keeping
    lookups O(log n) and memory O(n * 16B).

    Same surface and metric fields as NeedleMap; selected per volume
    with needle_map_kind="compact" (needle_map_kind, store.go:57).
    """

    OVERLAY_LIMIT = 8192

    def __init__(self) -> None:
        self._keys = np.empty(0, dtype=np.uint64)
        # u32 holds 4-byte offsets; the 5BytesOffset variant needs
        # u64 or offsets past 32GB would silently truncate mod 2^32
        self._offsets = np.empty(0, dtype=OFFSET_DTYPE)
        self._sizes = np.empty(0, dtype=np.int64)  # -1 = tombstone
        self._overlay: dict[int, tuple[int, int]] = {}
        self.file_count = 0
        self.deleted_count = 0
        self.file_bytes = 0
        self.deleted_bytes = 0
        self.max_key = 0

    def __len__(self) -> int:
        base = len(self._keys)
        novel = sum(1 for k in self._overlay
                    if not self._base_has(k))
        return base + novel

    def _base_has(self, key: int) -> bool:
        i = int(np.searchsorted(self._keys, np.uint64(key)))
        return i < len(self._keys) and int(self._keys[i]) == key

    def _base_get(self, key: int) -> tuple[int, int] | None:
        i = int(np.searchsorted(self._keys, np.uint64(key)))
        if i < len(self._keys) and int(self._keys[i]) == key:
            return int(self._offsets[i]), int(self._sizes[i])
        return None

    def _lookup(self, key: int) -> tuple[int, int] | None:
        if key in self._overlay:
            return self._overlay[key]
        return self._base_get(key)

    def get(self, key: int) -> tuple[int, int] | None:
        v = self._lookup(key)
        if v is None or t.size_is_deleted(v[1]):
            return None
        return v

    def put(self, key: int, offset: int, size: int) -> None:
        old = self._lookup(key)
        if old is not None and t.size_is_valid(old[1]):
            self.deleted_count += 1
            self.deleted_bytes += old[1]
            self.file_count -= 1
            self.file_bytes -= old[1]
        self._overlay[key] = (offset, size)
        if t.size_is_valid(size):
            self.file_count += 1
            self.file_bytes += size
        self.max_key = max(self.max_key, key)
        self._maybe_merge()

    def delete(self, key: int) -> int:
        old = self._lookup(key)
        if old is None or not t.size_is_valid(old[1]):
            return 0
        self._overlay[key] = (old[0], t.TOMBSTONE_SIZE)
        self.deleted_count += 1
        self.deleted_bytes += old[1]
        self.file_count -= 1
        self.file_bytes -= old[1]
        self._maybe_merge()
        return old[1]

    def _maybe_merge(self) -> None:
        if len(self._overlay) >= self.OVERLAY_LIMIT:
            self.merge_overlay()

    def merge_overlay(self) -> None:
        if not self._overlay:
            return
        ok = np.fromiter(self._overlay.keys(), dtype=np.uint64,
                         count=len(self._overlay))
        ov = np.array([v for v in self._overlay.values()],
                      dtype=np.int64).reshape(-1, 2)
        keys = np.concatenate([self._keys, ok])
        offsets = np.concatenate([self._offsets,
                                  ov[:, 0].astype(OFFSET_DTYPE)])
        sizes = np.concatenate([self._sizes, ov[:, 1]])
        # stable sort + keep the LAST occurrence of each key (overlay
        # entries were appended after the base, so they win)
        order = np.argsort(keys, kind="stable")
        keys, offsets, sizes = keys[order], offsets[order], sizes[order]
        keep = np.ones(len(keys), dtype=bool)
        keep[:-1] = keys[:-1] != keys[1:]
        self._keys = keys[keep]
        self._offsets = offsets[keep]
        self._sizes = sizes[keep]
        self._overlay = {}

    def items(self) -> Iterator[tuple[int, int, int]]:
        self.merge_overlay()
        for i in range(len(self._keys)):
            yield (int(self._keys[i]), int(self._offsets[i]),
                   int(self._sizes[i]))

    def live_items(self) -> Iterator[tuple[int, int, int]]:
        for k, off, size in self.items():
            if t.size_is_valid(size):
                yield k, off, size

    def deleted_keys(self) -> Iterator[int]:
        for k, _off, size in self.items():
            if t.size_is_deleted(size):
                yield k


def load_compact_needle_map(idx_path: str) -> CompactNeedleMap:
    """Vectorized .idx replay into a CompactNeedleMap: one structured
    read, later-entries-win dedupe and metric computation all as numpy
    column ops (the TPU-idiomatic version of
    needle_map_memory.go LoadCompactNeedleMap)."""
    nm = CompactNeedleMap()
    if not os.path.exists(idx_path):
        return nm
    arr = idxmod.read_index(idx_path)
    if len(arr) == 0:
        return nm
    keys = arr["key"].astype(np.uint64)
    offsets = arr["offset"].astype(OFFSET_DTYPE)
    sizes = arr["size"].astype(np.int64)
    sizes = np.where(sizes >= 0x80000000, sizes - (1 << 32), sizes)
    # tombstone rows delete; size-0 rows count as deletes too, exactly
    # like the memory loader's `off > 0 and size_is_valid(size)` test —
    # the two kinds must produce identical live-sets from one .idx
    dead = (offsets == 0) | (sizes <= 0)
    sizes = np.where(dead, np.int64(t.TOMBSTONE_SIZE), sizes)
    # later entries win: stable sort by key keeps append order within
    # a key; take each key's last row
    order = np.argsort(keys, kind="stable")
    keys, offsets, sizes = keys[order], offsets[order], sizes[order]
    keep = np.ones(len(keys), dtype=bool)
    keep[:-1] = keys[:-1] != keys[1:]
    # count a key as "deleted" only if its final row is a tombstone;
    # overwritten intermediate rows add to deleted_bytes like the
    # incremental path does
    shadowed_sizes = sizes[~keep]
    nm._keys = keys[keep]
    nm._offsets = offsets[keep]
    nm._sizes = sizes[keep]
    live = nm._sizes >= 0
    nm.file_count = int(np.count_nonzero(live))
    nm.file_bytes = int(nm._sizes[live].sum())
    # every shadowed live row was ended by exactly one overwrite or
    # tombstone — the same events the incremental path counts
    shadowed_live = shadowed_sizes[shadowed_sizes >= 0]
    nm.deleted_count = int(len(shadowed_live))
    nm.deleted_bytes = int(shadowed_live.sum())
    nm.max_key = int(nm._keys[-1]) if len(nm._keys) else 0
    return nm
