""".idx / .ecx index file IO: flat arrays of 16-byte (key, offset, size)
entries, big-endian.

Reference: /root/reference/weed/storage/idx/walk.go:12,45. Unlike the
row-at-a-time Go walker, reads are vectorized through a numpy structured
dtype — the whole index becomes three columns in one shot, which is also
the layout the TPU scrub pipeline wants.
"""
from __future__ import annotations

import os
from typing import Callable, Iterator

import numpy as np

from . import types as t

IDX_DTYPE = np.dtype([("key", ">u8"), ("offset", ">u4"), ("size", ">u4")])
assert IDX_DTYPE.itemsize == t.NEEDLE_MAP_ENTRY_SIZE


def read_index(path: str) -> np.ndarray:
    """Whole index file -> structured array (key, offset, size-u32)."""
    size = os.path.getsize(path)
    usable = (size // t.NEEDLE_MAP_ENTRY_SIZE) * t.NEEDLE_MAP_ENTRY_SIZE
    with open(path, "rb") as f:
        buf = f.read(usable)
    return np.frombuffer(buf, dtype=IDX_DTYPE)


def write_index(path: str, entries: np.ndarray) -> None:
    with open(path, "wb") as f:
        f.write(np.ascontiguousarray(entries, dtype=IDX_DTYPE).tobytes())


def append_entry(f, key: int, offset: int, size: int) -> None:
    """Append one entry to an open binary file object."""
    f.write(t.NeedleValue(key, offset, size).to_bytes())


def walk(path: str, fn: Callable[[int, int, int], None],
         start_from: int = 0) -> None:
    """Visit (key, offset, signed size) for each entry in file order."""
    arr = read_index(path)
    for rec in arr[start_from:]:
        fn(int(rec["key"]), int(rec["offset"]), t.u32_to_size(int(rec["size"])))


def iter_entries(path: str) -> Iterator[t.NeedleValue]:
    arr = read_index(path)
    for rec in arr:
        yield t.NeedleValue(int(rec["key"]), int(rec["offset"]),
                            t.u32_to_size(int(rec["size"])))
