""".idx / .ecx index file IO: flat arrays of 16-byte (key, offset, size)
entries, big-endian.

Reference: /root/reference/weed/storage/idx/walk.go:12,45. Unlike the
row-at-a-time Go walker, reads are vectorized through a numpy structured
dtype — the whole index becomes three columns in one shot, which is also
the layout the TPU scrub pipeline wants.
"""
from __future__ import annotations

import os
from typing import Callable, Iterator

import numpy as np

from . import types as t

if t.OFFSET_SIZE == 4:
    # logical layout == disk layout
    IDX_DTYPE = np.dtype([("key", ">u8"), ("offset", ">u4"),
                          ("size", ">u4")])
    _RAW_DTYPE = IDX_DTYPE
else:
    # 5BytesOffset variant (offset_5bytes.go): on disk the offset is
    # 4 BE lower bytes then 1 high byte; in memory a uniform u8 column
    IDX_DTYPE = np.dtype([("key", ">u8"), ("offset", ">u8"),
                          ("size", ">u4")])
    _RAW_DTYPE = np.dtype([("key", ">u8"), ("off_lo", ">u4"),
                           ("off_hi", "u1"), ("size", ">u4")])
assert _RAW_DTYPE.itemsize == t.NEEDLE_MAP_ENTRY_SIZE


def parse_index_bytes(buf: bytes) -> np.ndarray:
    """Raw index bytes -> structured array (key, offset, size-u32)."""
    usable = (len(buf) // t.NEEDLE_MAP_ENTRY_SIZE) * \
        t.NEEDLE_MAP_ENTRY_SIZE
    raw = np.frombuffer(buf[:usable], dtype=_RAW_DTYPE)
    if _RAW_DTYPE is IDX_DTYPE:
        return raw
    arr = np.empty(len(raw), dtype=IDX_DTYPE)
    arr["key"] = raw["key"]
    arr["offset"] = (raw["off_hi"].astype(np.uint64) << 32) | \
        raw["off_lo"].astype(np.uint64)
    arr["size"] = raw["size"]
    return arr


def read_index(path: str) -> np.ndarray:
    """Whole index file -> structured array (key, offset, size-u32)."""
    with open(path, "rb") as f:
        buf = f.read()
    return parse_index_bytes(buf)


def write_index(path: str, entries: np.ndarray) -> None:
    entries = np.ascontiguousarray(entries, dtype=IDX_DTYPE)
    if _RAW_DTYPE is not IDX_DTYPE:
        raw = np.empty(len(entries), dtype=_RAW_DTYPE)
        raw["key"] = entries["key"]
        raw["off_lo"] = entries["offset"] & 0xFFFFFFFF
        raw["off_hi"] = entries["offset"] >> 32
        raw["size"] = entries["size"]
        entries = raw
    with open(path, "wb") as f:
        f.write(entries.tobytes())


def append_entry(f, key: int, offset: int, size: int) -> None:
    """Append one entry to an open binary file object."""
    f.write(t.NeedleValue(key, offset, size).to_bytes())


def walk(path: str, fn: Callable[[int, int, int], None],
         start_from: int = 0) -> None:
    """Visit (key, offset, signed size) for each entry in file order."""
    arr = read_index(path)
    for rec in arr[start_from:]:
        fn(int(rec["key"]), int(rec["offset"]), t.u32_to_size(int(rec["size"])))


def iter_entries(path: str) -> Iterator[t.NeedleValue]:
    arr = read_index(path)
    for rec in arr:
        yield t.NeedleValue(int(rec["key"]), int(rec["offset"]),
                            t.u32_to_size(int(rec["size"])))
