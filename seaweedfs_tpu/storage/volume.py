"""Volume: one append-only needle log (.dat) plus its index (.idx).

Engine equivalent of /root/reference/weed/storage/volume*.go — append
(volume_write.go:123 writeNeedle2), read (volume_read.go:19 readNeedle),
delete-as-tombstone, load with torn-tail integrity check
(volume_checking.go:17), and two-phase vacuum compaction
(volume_vacuum.go:67 Compact2 / :102 CommitCompact).

Differences from the reference are deliberate simplifications, not
omissions: no async write queue (the server layer batches), and the
needle map is the dict-based storage.needle_map.NeedleMap.
"""
from __future__ import annotations

import os
import struct
import threading
import time

from . import backend as bk
from . import idx as idxmod
from . import needle as ndl
from . import needle_map as nmap
from . import types as t
from . import volume_info as vinfo
from .super_block import ReplicaPlacement, SuperBlock


class Volume:
    def __init__(self, dirname: str, collection: str, vid: int,
                 replica_placement: ReplicaPlacement | None = None,
                 ttl: bytes = b"\x00\x00", create: bool = False,
                 backend_kind: str = "disk",
                 needle_map_kind: str = "memory"):
        self.dir = dirname
        self.collection = collection
        self.vid = vid
        self.needle_map_kind = needle_map_kind
        # native data-plane delegation (native/dataplane.py): while set,
        # the C++ library is the single authority for this volume's
        # needle map, .dat tail and .idx log — every mutation below
        # routes through it instead of touching the files directly
        self.delegate = None
        self.read_only = False
        self._backend_kind = backend_kind
        # serializes mutations (append/delete/raw-append) against each
        # other and against compact's snapshot + commit phases — the
        # reference's per-volume write lock around Compact2/CommitCompact
        self.write_lock = threading.RLock()
        base = self.file_name()
        exists = os.path.exists(base + ".dat")
        self.volume_info = vinfo.maybe_load_volume_info(base + ".vif")
        remote = self.volume_info.remote_file() if self.volume_info else None
        if remote is not None and not exists:
            # .dat tiered off to a backend storage: open the remote copy
            # (disk_location.go loadVolumeInfo → s3 BackendStorageFile)
            storage = bk.get_storage(remote.backend_name)
            self.dat = storage.open_file(remote.key, remote.file_size)
            self.read_only = True
        elif remote is not None:
            # tiered with keepLocalDatFile: serve from the local copy
            # but stay read-only — appends would silently diverge from
            # the remote object recorded in the .vif
            self.dat = bk.DiskFile(base + ".dat")
            self.read_only = True
        elif backend_kind in ("disk", "mmap"):
            self.dat = bk.create(backend_kind, base + ".dat",
                                 create=create or not exists)
        else:
            self.dat = bk.create(backend_kind, base + ".dat")
        if (exists or remote is not None) and self.dat.size() >= 8:
            self.super_block = self._read_super_block()
        else:
            self.super_block = SuperBlock(
                replica_placement=replica_placement or ReplicaPlacement(),
                ttl=ttl)
            self.dat.write_at(self.super_block.to_bytes(), 0)
            self.dat.sync()
        self.nm = nmap.load_needle_map(base + ".idx",
                                       kind=needle_map_kind)
        self._idx_f = open(base + ".idx", "ab")
        self.last_append_at_ns = 0
        if exists:
            self.check_integrity()
            self.last_append_at_ns = self._recover_last_append_at_ns()

    # -- native data-plane delegation ----------------------------------
    @property
    def read_only(self) -> bool:
        return self._read_only

    @read_only.setter
    def read_only(self, value: bool) -> None:
        self._read_only = bool(value)
        if self.delegate is not None:
            self.delegate.set_readonly(self.vid, self._read_only)

    @property
    def last_append_at_ns(self) -> int:
        if self.delegate is not None:
            return self.delegate.stats(self.vid)["last_append_ns"]
        return self._last_append_at_ns

    @last_append_at_ns.setter
    def last_append_at_ns(self, value: int) -> None:
        self._last_append_at_ns = int(value)

    def attach_native(self, dp) -> bool:
        """Hand the hot path to the native data plane. Only plain local
        disk volumes qualify — remote/tiered and mmap stay Python.
        Returns True when attached."""
        if self.delegate is not None:
            return True
        if self._backend_kind != "disk" or not isinstance(
                self.dat, bk.DiskFile):
            return False
        base = self.file_name()
        with self.write_lock:
            self.dat.flush()
            self._idx_f.flush()
            from ..native.dataplane import NativeNeedleMap

            dp.attach(self.vid, base + ".dat", base + ".idx",
                      self.version, self.read_only,
                      self.super_block.replica_placement.copy_count > 1,
                      self.dat.size(), self._last_append_at_ns)
            if hasattr(self.nm, "close"):
                self.nm.close()  # btree: persists its watermark
            self.nm = NativeNeedleMap(dp, self.vid)
            self.delegate = dp
        return True

    def detach_native(self, reload_map: bool = True) -> None:
        """Take the volume back from the native plane (vacuum, tier,
        EC, unmount all need exclusive Python ownership)."""
        if self.delegate is None:
            return
        base = self.file_name()
        with self.write_lock:
            dp = self.delegate
            self.delegate = None
            _tail, last_ns = dp.detach(self.vid)
            self._last_append_at_ns = max(self._last_append_at_ns,
                                          last_ns)
            # reopen the .idx append handle at the true EOF and rebuild
            # the Python map from the .idx log (the btree sidecar's
            # watermark catch-up consumes exactly the natively appended
            # tail)
            self._idx_f.close()
            self._idx_f = open(base + ".idx", "ab")
            if reload_map:
                self.nm = nmap.load_needle_map(
                    base + ".idx", kind=self.needle_map_kind)
            else:
                self.nm = nmap.new_needle_map(
                    self.needle_map_kind, idx_path=base + ".idx") \
                    if self.needle_map_kind != "btree" else \
                    nmap.NeedleMap()

    # -- naming --------------------------------------------------------
    def file_name(self) -> str:
        name = f"{self.collection}_{self.vid}" if self.collection else \
            str(self.vid)
        return os.path.join(self.dir, name)

    # -- super block ---------------------------------------------------
    def _read_super_block(self) -> SuperBlock:
        head = self.dat.read_at(64 << 10, 0)
        return SuperBlock.from_bytes(head)

    # -- write path ----------------------------------------------------
    def append_needle(self, n: ndl.Needle) -> tuple[int, int]:
        """Append; returns (byte offset, body size). Pads .dat so offsets
        stay 8-aligned (reference appends already-padded records)."""
        if self.read_only:
            raise PermissionError(f"volume {self.vid} is read only")
        with self.write_lock:
            return self._append_needle_locked(n)

    def _append_needle_locked(self, n: ndl.Needle) -> tuple[int, int]:
        if not n.append_at_ns:
            # wall clock, not monotonic: append_at_ns orders records
            # ACROSS restarts for incremental sync (volume_backup.go);
            # the max() guard keeps it strictly increasing regardless
            n.append_at_ns = max(time.time_ns(),
                                 self.last_append_at_ns + 1)
        self.last_append_at_ns = n.append_at_ns
        blob = n.to_bytes(self.version)
        if self.delegate is not None:
            # native plane owns the tail, map and .idx for this volume
            offset = self.delegate.append(self.vid, blob, n.id, n.size,
                                          n.append_at_ns)
            return offset, n.size
        offset = self.dat.append(blob)
        if offset % t.NEEDLE_PADDING:
            # torn previous write: realign (reference truncates on load)
            pad = t.NEEDLE_PADDING - offset % t.NEEDLE_PADDING
            raise IOError(f".dat misaligned by {pad} bytes")
        # data reaches the OS before the index entry does — the recovery
        # path assumes index entries never point past .dat EOF
        self.dat.flush()
        stored = t.actual_to_offset(offset)
        self.nm.put(n.id, stored, n.size)
        idxmod.append_entry(self._idx_f, n.id, stored, n.size)
        self._idx_f.flush()
        return offset, n.size

    def delete_needle(self, needle_id: int) -> int:
        """Tombstone a needle; returns reclaimed data size (0 if absent).
        Appends an empty needle to .dat and a tombstone .idx entry, as the
        reference does (volume_write.go deleteNeedle2)."""
        if self.read_only:
            raise PermissionError(f"volume {self.vid} is read only")
        with self.write_lock:
            tomb = ndl.Needle(id=needle_id)
            tomb.append_at_ns = max(time.time_ns(),
                                    self.last_append_at_ns + 1)
            if self.delegate is not None:
                # the native side checks existence, appends the
                # tombstone record + .idx entry atomically
                return self.delegate.delete(self.vid, needle_id,
                                            tomb.to_bytes(self.version),
                                            tomb.append_at_ns)
            existing = self.nm.get(needle_id)
            if existing is None:
                return 0
            self.last_append_at_ns = tomb.append_at_ns
            self.dat.append(tomb.to_bytes(self.version))
            reclaimed = self.nm.delete(needle_id)
            idxmod.append_entry(self._idx_f, needle_id, 0,
                                t.TOMBSTONE_SIZE)
            self._idx_f.flush()
            return reclaimed

    # -- read path -----------------------------------------------------
    def read_needle(self, needle_id: int, cookie: int | None = None,
                    read_deleted: bool = False) -> ndl.Needle:
        try:
            return self._read_needle_once(needle_id, cookie, read_deleted)
        except PermissionError:
            raise  # cookie mismatch is definitive, never retry-worthy
        except (ValueError, OSError, struct.error):
            # a vacuum commit can swap .dat/.idx under an unlocked
            # reader (closed file, or stale offsets against the new
            # file). The commit holds write_lock through the swap, so
            # one retry serialized behind it reads consistent state;
            # a repeat failure is real corruption and propagates.
            with self.write_lock:
                return self._read_needle_once(needle_id, cookie,
                                              read_deleted)

    def _read_needle_once(self, needle_id: int,
                          cookie: int | None = None,
                          read_deleted: bool = False) -> ndl.Needle:
        loc = self.nm.get(needle_id)
        if loc is None and read_deleted:
            # ?readDeleted=true (volume_read.go:29): the tombstoned
            # map entry keeps the ORIGINAL offset until vacuum/reload;
            # the magnitude lives in the needle's own header on disk
            raw = getattr(self.nm, "get_any", lambda _k: None)(needle_id)
            # offset 0 = superblock, never needle data: a tombstone
            # REloaded from .idx carries offset 0 (append_entry writes
            # it that way), so post-restart the original offset is
            # genuinely unknown and the read must 404, not decode the
            # superblock as a needle header
            if raw is not None and raw[0] != 0 \
                    and t.size_is_deleted(raw[1]):
                hdr_off = t.offset_to_actual(raw[0])
                hdr = self.dat.read_at(t.NEEDLE_HEADER_SIZE, hdr_off)
                if len(hdr) == t.NEEDLE_HEADER_SIZE:
                    disk_sz = t.u32_to_size(
                        struct.unpack_from(">I", hdr, 12)[0])
                    if t.size_is_valid(disk_sz):
                        loc = (raw[0], disk_sz)
        if loc is None:
            raise KeyError(f"needle {needle_id} not found")
        stored_offset, size = loc
        offset = t.offset_to_actual(stored_offset)
        blob = self.dat.read_at(ndl.disk_size(size, self.version), offset)
        n = ndl.Needle.from_bytes(blob, self.version)
        if n.id != needle_id:
            # a stale offset after a vacuum swap can land on a DIFFERENT
            # valid record of the same size — without this check the
            # wrong needle's data would be served silently
            raise ValueError(
                f"needle id mismatch: want {needle_id} got {n.id}")
        if n.size != size:
            raise ValueError(
                f"size mismatch: index {size} vs disk {n.size}")
        if cookie is not None and n.cookie != cookie:
            raise PermissionError("cookie mismatch")
        return n

    def read_needle_streamed(self, needle_id: int,
                             cookie: int | None = None):
        """Open a big needle for WINDOWED serving without materializing
        its data (the reference's streamed read path — PagedReadLimit,
        volume_read.go:41 AttemptMetaOnly + paged ReadNeedleDataInto):
        two small preads fetch the header and the post-data metadata;
        -> (meta_needle_with_empty_data, data_size, reader) where
        reader(off, ln) preads the data span [off, off+ln).

        The reader captures THIS DiskFile handle: a concurrent vacuum
        commit swaps in a new file but the old fd keeps serving a
        consistent snapshot until it is closed.
        """
        loc = self.nm.get(needle_id)
        if loc is None:
            raise KeyError(f"needle {needle_id} not found")
        stored_offset, size = loc
        offset = t.offset_to_actual(stored_offset)
        dat = self.dat
        head = dat.read_at(t.NEEDLE_HEADER_SIZE + 4, offset)
        if len(head) < t.NEEDLE_HEADER_SIZE + 4:
            raise ValueError("needle header truncated")
        ck, nid, size_u32, data_size = struct.unpack(">IQII", head)
        if nid != needle_id:
            raise ValueError(
                f"needle id mismatch: want {needle_id} got {nid}")
        if t.u32_to_size(size_u32) != size:
            raise ValueError(f"size mismatch: index {size} vs "
                             f"disk {t.u32_to_size(size_u32)}")
        if cookie is not None and ck != cookie:
            raise PermissionError("cookie mismatch")
        if data_size + 5 > size:
            raise ValueError("corrupt needle: data_size exceeds body")
        n = ndl.Needle(id=nid, cookie=ck)
        n.size = size
        data_off = offset + t.NEEDLE_HEADER_SIZE + 4
        # post-data tail: [flags][name][mime][lm][ttl][pairs][crc]...
        tail_len = size - 4 - data_size + 4  # meta + stored crc
        tail = dat.read_at(tail_len, data_off + data_size)
        try:
            n._parse_meta(tail, 0)
        except (IndexError, struct.error) as e:
            raise ValueError(f"corrupt needle meta: {e}") from e
        # the stored crc IS the etag; streaming can't re-verify the
        # payload before bytes go out, and the reference's paged path
        # does exactly this (needle_read_page.go:75 sets Checksum to
        # the RAW stored value, while the materialized read normalizes
        # to the computed crc) — so a legacy-transform .dat shows the
        # same streamed-vs-small etag split there too
        if len(tail) >= 4:
            n.checksum = struct.unpack_from(">I", tail, len(tail) - 4)[0]

        def reader(off: int, ln: int) -> bytes:
            return dat.read_at(ln, data_off + off)

        return n, data_size, reader

    # -- maintenance ---------------------------------------------------
    @property
    def version(self) -> int:
        return self.super_block.version

    def content_size(self) -> int:
        return self.dat.size()

    def garbage_ratio(self) -> float:
        used = self.nm.file_bytes + self.nm.deleted_bytes
        return (self.nm.deleted_bytes / used) if used else 0.0

    def check_integrity(self) -> None:
        """Crash recovery on load (CheckAndFixVolumeDataIntegrity,
        volume_checking.go:17, extended for group commit):

        1. truncate a torn .dat tail to the 8-byte record grid;
        2. torn-BATCH tail: a group-commit window can die mid-flush
           (kill between a batch's appends), leaving CRC-good records
           and then a partial one beyond the last indexed record. Walk
           that unindexed tail, REPLAY every CRC-clean record into the
           needle map + .idx (the batch committer fsyncs only the
           .dat — acked idx entries are regained right here), and cut
           the .dat at the first corrupt one — the torn batch suffix
           drops as one unit while every record before the cut
           survives bit-for-bit. Batch-mode acks release only after
           the covering .dat fsync, so an acked needle always sits
           below the cut and is re-indexed, never dropped;
        3. drop index entries pointing at/past the .dat EOF (idx flushed
           ahead of an unwritten data record);
        4. spot-check the last live entry parses with the right id — a
           mismatch means the whole index is stale (e.g. torn compact
           commit) and is rebuilt by scanning the .dat.
        """
        size = self.dat.size()
        aligned = size - (size % t.NEEDLE_PADDING)
        if aligned != size:
            self.dat.truncate(aligned)
            size = aligned
        anchor = self.super_block.block_size
        for key, off, sz in self.nm.live_items():
            end = t.offset_to_actual(off) + ndl.disk_size(sz, self.version)
            if end <= size:
                anchor = max(anchor, end)
        cut = self._recover_tail(anchor, size)
        if cut is not None:
            self.dat.truncate(cut)
            size = cut
        stale = []
        last = None
        for key, off, sz in self.nm.live_items():
            end = t.offset_to_actual(off) + ndl.disk_size(sz, self.version)
            if end > size:
                stale.append(key)
            elif last is None or off > last[1]:
                last = (key, off, sz)
        consistent = not stale
        if consistent and last is None and \
                size > self.super_block.block_size:
            consistent = False  # data present but index knows nothing
        if consistent and last is not None:
            key, off, sz = last
            try:
                blob = self.dat.read_at(
                    ndl.disk_size(sz, self.version), t.offset_to_actual(off))
                n = ndl.Needle.from_bytes(blob, self.version)
                if n.id != key or n.size != sz:
                    consistent = False
            except Exception:
                consistent = False
        if not consistent:
            self.rebuild_index()

    def _recover_tail(self, offset: int, size: int) -> int | None:
        """Walk .dat records in [offset, size) verifying each parses
        CRC-clean (tombstones have no payload and pass trivially), and
        REPLAY every sound record into the needle map + .idx. The .idx
        appends in the same order as the .dat under the write lock, so
        an idx loss is always a suffix: the batch committer fsyncs only
        the .dat and relies on this replay to regain the covering idx
        entries after a crash. The anchor is a safe underestimate
        (live-entry maximum), so already-indexed records re-apply
        idempotently — the nm state check skips their idx re-append to
        keep clean reloads byte-stable.
        -> the byte offset of the first bad/partial record — the
        torn-batch truncation cut — or None when the tail is sound."""
        while offset + t.NEEDLE_HEADER_SIZE <= size:
            try:
                head = self.dat.read_at(t.NEEDLE_HEADER_SIZE, offset)
                _, nid, size_u32 = struct.unpack(">IQI", head)
                nsize = max(t.u32_to_size(size_u32), 0)
                disk = ndl.disk_size(nsize, self.version)
                if offset + disk > size:
                    return offset  # partial record: torn mid-append
                blob = self.dat.read_at(disk, offset)
                ndl.Needle.from_bytes(blob, self.version)
            except Exception:
                return offset
            stored = t.actual_to_offset(offset)
            if nsize > 0:
                if self.nm.get(nid) != (stored, nsize):
                    self.nm.put(nid, stored, nsize)
                    idxmod.append_entry(self._idx_f, nid, stored, nsize)
            elif self.nm.get(nid) is not None:
                try:
                    self.nm.delete(nid)
                except KeyError:
                    pass
                else:
                    idxmod.append_entry(self._idx_f, nid, 0,
                                        t.TOMBSTONE_SIZE)
            offset += disk
        if offset != size:
            return offset  # sub-header residue on the record grid
        return None

    def rebuild_index(self) -> None:
        """Offline .idx reconstruction by scanning the .dat — the
        `weed fix` tool (command/fix.go:24-40) as an engine method, also
        the recovery path for a torn compact commit. Uses the native
        C++ record walker when available (the scan itself drops from
        seconds to milliseconds on large volumes; end-to-end ~2x since
        the needle-map replay dominates); the Python loop below is the
        always-works fallback and the semantic reference."""
        base = self.file_name()
        if self._rebuild_index_native(base):
            return
        self._idx_f.close()
        if hasattr(self.nm, "close"):
            self.nm.close()
        self.nm = nmap.new_needle_map(self.needle_map_kind,
                                      idx_path=base + ".idx")
        with open(base + ".idx", "wb") as idxf:
            offset = self.super_block.block_size
            size = self.dat.size()
            while offset + t.NEEDLE_HEADER_SIZE <= size:
                head = self.dat.read_at(t.NEEDLE_HEADER_SIZE, offset)
                _, nid, size_u32 = struct.unpack(">IQI", head)
                nsize = t.u32_to_size(size_u32)
                if nsize < 0:
                    nsize = 0
                disk = ndl.disk_size(nsize, self.version)
                if offset + disk > size:
                    self.dat.truncate(offset)
                    break
                stored = t.actual_to_offset(offset)
                if nsize > 0:
                    self.nm.put(nid, stored, nsize)
                    idxmod.append_entry(idxf, nid, stored, nsize)
                else:
                    self.nm.delete(nid)
                    idxmod.append_entry(idxf, nid, 0, t.TOMBSTONE_SIZE)
                offset += disk
        self._idx_f = open(base + ".idx", "ab")

    def scrub(self, limit: int = 0) -> dict:
        """Verify every live needle end-to-end: disk read, size check,
        CRC32C (needle.from_bytes raises on mismatch). The per-volume
        arm of cluster scrub (BASELINE config #5); the EC arm is the
        shell's ec.verify parity check. `limit` bounds the record
        count (0 = all)."""
        checked = 0
        bad: list[dict] = []
        with self.write_lock:  # stable snapshot vs concurrent puts
            snapshot = list(self.nm.live_items())
        for key, _off, _size in snapshot:
            if limit and checked >= limit:
                break
            checked += 1
            try:
                self.read_needle(key)
            except (ValueError, IOError, KeyError, struct.error):
                # A needle legitimately deleted — or a vacuum commit
                # swapping the .dat mid-read — is not corruption. The
                # retry must run under write_lock: the commit holds it
                # through the .dat close/replace/reopen, so the locked
                # retry is serialized after the swap and reads the
                # fresh map + file instead of a torn pair.
                with self.write_lock:
                    if self.nm.get(key) is None:
                        continue
                    try:
                        self.read_needle(key)
                    except (ValueError, IOError, KeyError,
                            struct.error) as e2:
                        bad.append({"id": key, "error": str(e2)})
        return {"volume": self.vid, "checked": checked, "bad": bad}

    def _rebuild_index_native(self, base: str) -> bool:
        """C++ fast path of rebuild_index: bulk-scan the .dat, write
        the .idx vectorized, reload the map through the standard
        loader. Returns False when the native library or a scannable
        file isn't available (caller falls back to the Python walk)."""
        import numpy as np

        from .. import native

        path = self.dat.name
        if not native.available() or not os.path.exists(path):
            return False
        try:
            lib_ok = native.load() is not None
        except Exception:
            return False
        if not lib_ok:
            return False
        self.dat.flush()
        size = self.dat.size()
        start = self.super_block.block_size
        if size <= start:
            ids = offs = sizes = np.empty(0, dtype=np.int64)
            end = size
        else:
            dat = np.memmap(path, dtype=np.uint8, mode="r", shape=(size,))
            ids, offs, sizes, end = native.dat_scan(
                dat, start, self.version)
            del dat
        if end < size:
            self.dat.truncate(end)  # torn tail after the last record
        self._idx_f.close()
        arr = np.empty(len(ids), dtype=idxmod.IDX_DTYPE)
        live = sizes > 0
        arr["key"] = ids
        arr["offset"] = np.where(live, offs // t.NEEDLE_PADDING, 0)
        arr["size"] = np.where(live, sizes.astype(np.int64),
                               t.size_to_u32(t.TOMBSTONE_SIZE))
        idxmod.write_index(base + ".idx", arr)
        if hasattr(self.nm, "close"):
            self.nm.close()
        if self.needle_map_kind == "btree":
            # the .idx was rewritten wholesale: a stale sidecar with a
            # coincidentally-equal watermark would serve wrong offsets
            nmap.drop_btree_sidecar(base + ".idx")
        self.nm = nmap.load_needle_map(base + ".idx",
                                       self.needle_map_kind)
        self._idx_f = open(base + ".idx", "ab")
        return True

    # -- incremental sync (volume_backup.go, volume_grpc_copy_incremental.go)
    def _walk_records(self, start: int, end: int | None = None):
        """Yield (offset, needle_id, size, disk_size) for every record
        (live or tombstone) from byte offset `start` to `end` (EOF by
        default), stopping at a torn tail."""
        offset = start
        if end is None:
            end = self.dat.size()
        while offset + t.NEEDLE_HEADER_SIZE <= end:
            head = self.dat.read_at(t.NEEDLE_HEADER_SIZE, offset)
            _, nid, size_u32 = struct.unpack(">IQI", head)
            nsize = max(t.u32_to_size(size_u32), 0)
            disk = ndl.disk_size(nsize, self.version)
            if offset + disk > end:
                return
            yield offset, nid, nsize, disk
            offset += disk

    def _append_at_ns_at(self, offset: int, nsize: int) -> int:
        """Read a record's append_at_ns stamp (v3 tail field)."""
        if self.version != ndl.VERSION3:
            return 0
        pos = offset + t.NEEDLE_HEADER_SIZE + nsize + ndl.CHECKSUM_SIZE
        raw = self.dat.read_at(8, pos)
        return struct.unpack(">Q", raw)[0] if len(raw) == 8 else 0

    def _recover_last_append_at_ns(self) -> int:
        """Stamp of the last record on disk. Starts the scan at the
        newest live offset the index knows (one vectorized idx read)
        so only trailing tombstones are walked record-by-record."""
        base = self.file_name()
        start = self.super_block.block_size
        try:
            entries = idxmod.read_index(base + ".idx")
            live = entries[entries["offset"] != 0]  # tombstones store 0
            if len(live):
                start = max(start,
                            int(live["offset"].max()) * t.NEEDLE_PADDING)
        except (OSError, ValueError):
            pass
        last = (0, 0)
        for offset, _nid, nsize, _disk in self._walk_records(start):
            last = (offset, nsize)
        return self._append_at_ns_at(*last) if last != (0, 0) else 0

    def offset_for_append_at_ns(self, since_ns: int) -> int:
        """Byte offset of the first record appended strictly after
        `since_ns` (EOF when none) — the reference's
        BinarySearchByAppendAtNs. Stamps are strictly increasing and
        the .idx file is in append order, so a binary search over the
        live index entries lands next to the answer; a short forward
        scan from there covers interleaved tombstone records (which
        have no index offset to probe)."""
        start = self.super_block.block_size
        if since_ns <= 0:
            return start
        if self.version == ndl.VERSION3:
            try:
                entries = idxmod.read_index(self.file_name() + ".idx")
                live = entries[entries["offset"] != 0]
            except (OSError, ValueError):
                live = ()
            if len(live):
                offsets = live["offset"].astype("int64") * t.NEEDLE_PADDING
                sizes = live["size"].astype("int64")
                lo, hi, best = 0, len(live) - 1, -1
                while lo <= hi:
                    mid = (lo + hi) // 2
                    stamp = self._append_at_ns_at(
                        int(offsets[mid]), int(sizes[mid]))
                    if stamp <= since_ns:
                        best, lo = mid, mid + 1
                    else:
                        hi = mid - 1
                if best >= 0:
                    start = int(offsets[best]) + ndl.disk_size(
                        int(sizes[best]), self.version)
        for offset, _nid, nsize, _disk in self._walk_records(start):
            if self._append_at_ns_at(offset, nsize) > since_ns:
                return offset
        return self.dat.size()

    def read_segment(self, offset: int, limit: int = 1 << 20) -> bytes:
        return self.dat.read_at(min(limit, self.dat.size() - offset),
                                offset)

    def append_raw_segment(self, data: bytes) -> int:
        """Append already-encoded records (an incremental-copy stream)
        and index them; returns the number of records applied. Only
        whole records are appended — a trailing partial record is an
        error, the transport must frame on record boundaries."""
        if self.read_only:
            raise PermissionError(f"volume {self.vid} is read only")
        if self.delegate is not None:
            raise RuntimeError(
                f"volume {self.vid} is natively attached; detach "
                "before applying raw segments")
        # the write lock spans append AND the error-path truncate: a
        # concurrent client write landing right after this segment
        # would otherwise be chopped off by truncate(end) (its index
        # entry left pointing past EOF)
        with self.write_lock:
            start = self.dat.append(data)
            self.dat.flush()
            applied = 0
            end = start
            for offset, nid, nsize, disk in self._walk_records(
                    start, start + len(data)):
                stored = t.actual_to_offset(offset)
                if nsize > 0:
                    self.nm.put(nid, stored, nsize)
                    idxmod.append_entry(self._idx_f, nid, stored, nsize)
                else:
                    self.nm.delete(nid)
                    idxmod.append_entry(self._idx_f, nid, 0,
                                        t.TOMBSTONE_SIZE)
                self.last_append_at_ns = max(
                    self.last_append_at_ns,
                    self._append_at_ns_at(offset, nsize))
                applied += 1
                end = offset + disk
            self._idx_f.flush()
            if end != start + len(data):
                self.dat.truncate(end)
                raise IOError(
                    f"incremental segment ends mid-record at {end}; "
                    f"{start + len(data) - end} trailing bytes dropped")
            return applied

    def modified_at_second(self) -> int:
        """Unix seconds of the last write, falling back to the .dat
        file mtime when no stamped record exists yet — a TTL volume
        that was assigned but never written must still age out
        (reference initializes lastModifiedTsSeconds from file mtime)."""
        if self.last_append_at_ns:
            return self.last_append_at_ns // 1_000_000_000
        try:
            return int(os.path.getmtime(self.file_name() + ".dat"))
        except OSError:
            return 0

    def sync_status(self) -> dict:
        """Volume state for sync negotiation (VolumeSyncStatusResponse,
        volume_server.proto)."""
        return {"volume": self.vid,
                "tail_offset": self.dat.size(),
                "compact_revision": self.super_block.compaction_revision,
                "last_append_at_ns": self.last_append_at_ns,
                "read_only": self.read_only}

    # -- tiering -------------------------------------------------------
    @property
    def is_remote(self) -> bool:
        """True when the .dat lives on a backend storage (tiered)."""
        return isinstance(self.dat, bk.S3RangeFile)

    def tier_upload(self, storage: "bk.S3BackendStorage",
                    keep_local: bool = False) -> vinfo.RemoteFile:
        """Move the .dat to a backend storage and record it in .vif
        (VolumeTierMoveDatToRemote, volume_grpc_tier_upload.go;
        shell command_volume_tier_upload.go). The volume becomes
        read-only; the .idx stays local."""
        if self.is_remote or (self.volume_info and
                              self.volume_info.remote_file()):
            raise ValueError(f"volume {self.vid} is already tiered")
        self.detach_native()  # the .dat is about to be closed/removed
        base = self.file_name()
        was_read_only = self.read_only
        self.read_only = True
        self.sync()
        key = storage.object_key(base + ".dat")
        try:
            size = storage.upload_file(self.dat, key)
        except Exception:
            # a failed upload must not wedge the volume read-only
            self.read_only = was_read_only
            raise
        rf = vinfo.RemoteFile(
            backend_type="s3", backend_id=storage.id, key=key,
            file_size=size, modified_time=int(time.time()))
        self._adopt_remote(rf, keep_local, storage)
        return rf

    def tier_adopt(self, rf: vinfo.RemoteFile, keep_local: bool = False) \
            -> None:
        """Record an already-uploaded remote copy in the .vif and drop
        the local .dat — used by replicas after one of them did the
        actual upload, so an N-replica tier.upload transfers the bytes
        once, not N times."""
        if self.is_remote:
            raise ValueError(f"volume {self.vid} is already tiered")
        self.detach_native()
        self.read_only = True
        self.sync()
        self._adopt_remote(rf, keep_local, bk.get_storage(rf.backend_name))

    def _adopt_remote(self, rf: vinfo.RemoteFile, keep_local: bool,
                      storage: "bk.S3BackendStorage") -> None:
        base = self.file_name()
        self.volume_info = vinfo.VolumeInfo(
            version=self.version,
            replication=str(self.super_block.replica_placement),
            files=[rf])
        vinfo.save_volume_info(base + ".vif", self.volume_info)
        if not keep_local:
            self.dat.close()
            os.remove(base + ".dat")
            self.dat = storage.open_file(rf.key, rf.file_size)

    def tier_download(self, delete_remote: bool = True) -> None:
        """Bring a tiered .dat back to local disk
        (VolumeTierMoveDatFromRemote, volume_grpc_tier_download.go)."""
        remote = self.volume_info.remote_file() if self.volume_info else None
        if remote is None:
            raise ValueError(f"volume {self.vid} is not tiered")
        storage = bk.get_storage(remote.backend_name)
        base = self.file_name()
        if not os.path.exists(base + ".dat"):
            storage.download_to(remote.key, base + ".dat")
        self.dat.close()
        self.dat = bk.DiskFile(base + ".dat")
        self.volume_info = None
        try:
            os.remove(base + ".vif")
        except FileNotFoundError:
            pass
        if delete_remote:
            storage.delete(remote.key)
        self.read_only = False

    def compact(self) -> None:
        """Two-phase vacuum: write surviving live needles to .cpd/.cpx,
        then atomically swap (Compact2 + CommitCompact,
        volume_vacuum.go:67,102)."""
        if self.is_remote:
            raise PermissionError(
                f"volume {self.vid} is tiered; download before compacting")
        if self.delegate is not None:
            raise RuntimeError(
                f"volume {self.vid} is natively attached; detach "
                "before compacting")
        base = self.file_name()
        cpd, cpx = base + ".cpd", base + ".cpx"
        new_sb = SuperBlock(
            version=self.super_block.version,
            replica_placement=self.super_block.replica_placement,
            ttl=self.super_block.ttl,
            compaction_revision=(self.super_block.compaction_revision + 1)
            & 0xFFFF)
        with self.write_lock:
            # snapshot under the write lock: a concurrent put would
            # otherwise mutate the dict mid-iteration, and the idx
            # watermark must match the item set exactly
            items = sorted(self.nm.live_items(), key=lambda kv: kv[1])
            self._idx_f.flush()
            idx_snapshot = os.path.getsize(base + ".idx")
        with open(cpd, "wb") as datf, open(cpx, "wb") as idxf:
            datf.write(new_sb.to_bytes())
            write_offset = datf.tell()
            for key, stored_off, size in items:
                blob = self.dat.read_at(
                    ndl.disk_size(size, self.version),
                    t.offset_to_actual(stored_off))
                datf.write(blob)
                idxmod.append_entry(
                    idxf, key, t.actual_to_offset(write_offset), size)
                write_offset += len(blob)
        self._commit_compact(cpd, cpx, idx_snapshot)

    def _commit_compact(self, cpd: str, cpx: str,
                        idx_snapshot: int) -> None:
        """Swap in the compacted files, first replaying every index
        entry appended since the snapshot (writes and tombstones that
        raced the compaction) into them (CommitCompact makeupDiff,
        volume_vacuum.go:200). Holds the write lock so nothing lands
        between the replay and the swap."""
        base = self.file_name()
        with self.write_lock:
            self._idx_f.flush()
            with open(base + ".idx", "rb") as f:
                f.seek(idx_snapshot)
                delta = f.read()
            if delta:
                with open(cpd, "ab") as datf, open(cpx, "ab") as idxf:
                    write_offset = os.path.getsize(cpd)
                    step = t.NEEDLE_MAP_ENTRY_SIZE
                    for i in range(0, len(delta) - step + 1, step):
                        nv = t.NeedleValue.from_bytes(delta[i:i + step])
                        if t.size_is_valid(nv.size) and nv.offset > 0:
                            blob = self.dat.read_at(
                                ndl.disk_size(nv.size, self.version),
                                t.offset_to_actual(nv.offset))
                            datf.write(blob)
                            idxmod.append_entry(
                                idxf, nv.key,
                                t.actual_to_offset(write_offset),
                                nv.size)
                            write_offset += len(blob)
                        else:
                            idxmod.append_entry(idxf, nv.key, 0,
                                                t.TOMBSTONE_SIZE)
            self.dat.close()
            self._idx_f.close()
            if self.needle_map_kind == "btree":
                # drop the sidecar BEFORE the .idx swap: a crash in
                # between leaves no sidecar (full rebuild next open)
                # instead of a stale one whose size-only watermark
                # could coincidentally match the rewritten .idx
                nmap.drop_btree_sidecar(base + ".idx")
            os.replace(cpd, base + ".dat")
            os.replace(cpx, base + ".idx")
            # reopen with the volume's configured local backend so an
            # mmap volume stays mmap after its first vacuum
            if self._backend_kind in ("disk", "mmap"):
                self.dat = bk.create(self._backend_kind, base + ".dat")
            else:
                self.dat = bk.DiskFile(base + ".dat")
            self.super_block = self._read_super_block()
            if hasattr(self.nm, "close"):
                self.nm.close()
            self.nm = nmap.load_needle_map(base + ".idx",
                                           kind=self.needle_map_kind)
            self._idx_f = open(base + ".idx", "ab")

    def commit_batch(self, durable: bool) -> None:
        """One group-commit step (storage/commit.py committer thread).

        durable=True fsyncs the .dat ONLY — one journal commit per
        batch, not two. The .idx is flushed to userspace but rides the
        page cache: acked idx entries are recoverable from the fsynced
        .dat via check_integrity's tail replay (the .idx appends in
        .dat order, so any loss is a suffix the replay regains).
        durable=False is the buffered-mode hygiene commit that
        replaced the needle map's COMMIT_EVERY cadence: flush the .idx
        and commit the btree transaction (userspace durability, no
        fsync). Takes no lock, same contract as sync() — the committer
        serializes behind vacuum swaps."""
        if durable:
            dat = self.dat
            (dat.datasync if hasattr(dat, "datasync") else dat.sync)()
            if self.delegate is None:
                self._idx_f.flush()
                if hasattr(self.nm, "set_watermark"):
                    self.nm.set_watermark(self._idx_f.tell())
            return
        if self.delegate is not None:
            return  # native appends are unbuffered pwrites already
        self._idx_f.flush()
        if hasattr(self.nm, "set_watermark"):
            self.nm.set_watermark(self._idx_f.tell())

    def sync(self) -> None:
        self.dat.sync()
        if self.delegate is not None:
            # native writes are unbuffered pwrites; fsync the .idx
            # through our own handle to the same file
            os.fsync(self._idx_f.fileno())
            return
        self._idx_f.flush()
        os.fsync(self._idx_f.fileno())
        if hasattr(self.nm, "set_watermark"):
            # btree sidecar: remember how much .idx the committed db
            # reflects, so reopen replays only the tail past it
            self.nm.set_watermark(self._idx_f.tell())

    def close(self) -> None:
        self.detach_native(reload_map=False)
        try:
            self.sync()
        finally:
            self.dat.close()
            self._idx_f.close()
            if hasattr(self.nm, "close"):
                self.nm.close()

    def destroy(self) -> None:
        remote = self.volume_info.remote_file() if self.volume_info else None
        self.close()
        if remote is not None:
            try:
                bk.get_storage(remote.backend_name).delete(remote.key)
            except KeyError:
                pass  # backend no longer configured; leave the object
        base = self.file_name()
        exts = [".dat", ".idx"]
        # ec.encode deletes the source volume AFTER generating shards:
        # the .vif now carries the shard set's codec record and must
        # survive as long as any shard file does
        from ..ec import geometry as _geo

        if not any(os.path.exists(base + _geo.shard_ext(i))
                   for i in range(_geo.MAX_SHARD_COUNT)):
            exts.append(".vif")
        for ext in exts:
            try:
                os.remove(base + ext)
            except FileNotFoundError:
                pass
        # a leftover sidecar would poison a future same-vid volume
        # copied in from a peer (its watermark could pass the size check)
        nmap.drop_btree_sidecar(base + ".idx")
