"""Group-commit scheduler: fsync-coalesced durable acks.

One fsync can durably land hundreds of appends, because concurrent
needle writes share a contiguous .dat extent (the Haystack layout's
whole point) — the same amortization argument arXiv 1709.05365 makes
for online-EC write handling. Writers enqueue a ticket per append; a
single committer thread closes the open batch window when either
``max_delay`` elapses or ``max_bytes`` accumulate, issues ONE
``flush + fsync`` per dirty volume, and only then releases the
tickets. The ack contract is the scheduler's ``durability`` mode:

======== ==========================================================
buffered ack after the userspace append (today's semantics; batches
         still close, replacing the needle map's old COMMIT_EVERY
         cadence, but without fsync)
batch    ack only after the covering batch fsync — fsync-durable at
         ~1 fsync/batch instead of ~1 fsync/write
sync     per-write fsync oracle (the caller fsyncs inline; the
         scheduler only keeps the idx/btree commit cadence)
======== ==========================================================

Lock discipline (enforced by analysis/rules/lock_discipline.py): the
committer NEVER fsyncs while holding any lock — not its own condition
and not the volume write lock. The queue snapshot happens under the
condition variable, the fsync happens after release; Volume.sync()
itself takes no lock (vacuum swaps are survived by the one-retry
below, exactly like the unlocked read path).
"""
from __future__ import annotations

import threading
import time

from ..utils import sketch as _sketch
from ..utils.metrics import counter_add, histogram_observe

DURABILITY_MODES = ("buffered", "batch", "sync")

# buffered mode has no ack waiting on the window, so the batch close
# exists only for idx/btree commit hygiene — stretch tiny windows out
# to a saner cadence instead of spinning the committer at 0.5ms
_BUFFERED_FLOOR = 0.025


class CommitTicket:
    """One enqueued append waiting for its covering batch commit."""

    __slots__ = ("volume", "nbytes", "enqueued_at", "error", "_event",
                 "_future", "_loop", "queue_seconds", "fsync_seconds")

    def __init__(self, volume, nbytes: int, loop=None):
        self.volume = volume
        self.nbytes = nbytes
        self.enqueued_at = time.monotonic()
        self.error: Exception | None = None
        self.queue_seconds = 0.0
        self.fsync_seconds = 0.0
        self._loop = loop
        if loop is not None:
            self._future = loop.create_future()
            self._event = None
        else:
            self._future = None
            self._event = threading.Event()

    def _release(self) -> None:
        if self._event is not None:
            self._event.set()
            return
        loop, fut = self._loop, self._future

        def _set() -> None:
            if not fut.done():
                fut.set_result(None)

        try:
            loop.call_soon_threadsafe(_set)
        except RuntimeError:
            pass  # loop already closed; nothing is awaiting

    def wait(self, timeout: float | None = None) -> bool:
        """Synchronous wait (thread writers / tests)."""
        return self._event.wait(timeout)

    def __await__(self):
        return self._future.__await__()


class CommitScheduler:
    """Per-volume-server group-commit pipeline (one committer thread)."""

    def __init__(self, durability: str = "buffered",
                 max_delay: float = 0.002, max_bytes: int = 4 << 20):
        if durability not in DURABILITY_MODES:
            raise ValueError(
                f"durability must be one of {DURABILITY_MODES}, "
                f"got {durability!r}")
        self.durability = durability
        self.max_delay = float(max_delay)
        self.max_bytes = int(max_bytes)
        self._cond = threading.Condition()
        self._queue: list[CommitTicket] = []
        self._queue_bytes = 0
        self._window_opened: float | None = None
        self._stopping = False
        self._thread: threading.Thread | None = None
        # counters for /debug/commit (all monotonic, guarded by _cond)
        self.batches = 0
        self.commits = 0          # tickets released
        self.fsyncs = 0
        self.commit_errors = 0
        self._size_sketch = _sketch.windowed()
        self._bytes_sketch = _sketch.windowed()

    # -- writer side ---------------------------------------------------
    def submit(self, volume, nbytes: int, loop=None) -> CommitTicket:
        """Enqueue an already-appended write; the returned ticket
        releases after the covering batch commit (await it from async
        code, ``wait()`` from threads)."""
        t = CommitTicket(volume, nbytes, loop=loop)
        with self._cond:
            if self._stopping:
                raise RuntimeError("commit scheduler stopped")
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="commit-scheduler", daemon=True)
                self._thread.start()
            self._queue.append(t)
            self._queue_bytes += nbytes
            if self._window_opened is None:
                self._window_opened = t.enqueued_at
            self._cond.notify()
        return t

    # -- committer side ------------------------------------------------
    def _window(self) -> float:
        if self.durability == "batch":
            return self.max_delay
        return max(self.max_delay, _BUFFERED_FLOOR)

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stopping:
                    self._cond.wait()
                if not self._queue and self._stopping:
                    return
                # adaptive window: close at max_delay after the first
                # enqueue, or immediately once max_bytes piled up
                window = self._window()
                while not self._stopping:
                    elapsed = time.monotonic() - self._window_opened
                    if elapsed >= window or \
                            self._queue_bytes >= self.max_bytes:
                        break
                    self._cond.wait(window - elapsed)
                batch = self._queue
                nbytes = self._queue_bytes
                self._queue = []
                self._queue_bytes = 0
                self._window_opened = None
            # lock released: all blocking IO happens out here
            self._commit(batch, nbytes)
            with self._cond:
                if self._stopping and not self._queue:
                    return

    def _commit(self, batch: list[CommitTicket], nbytes: int) -> None:
        now = time.monotonic()
        for t in batch:
            t.queue_seconds = now - t.enqueued_at
            histogram_observe("write_commit_seconds", t.queue_seconds,
                              {"stage": "queue"})
        volumes: dict[int, object] = {}
        for t in batch:
            volumes[id(t.volume)] = t.volume
        durable = self.durability != "buffered"
        t0 = time.monotonic()
        errors: dict[int, Exception] = {}
        for key, v in volumes.items():
            try:
                self._commit_volume(v, durable)
            except Exception as e:  # pragma: no cover - disk failure
                errors[key] = e
        fsync_s = time.monotonic() - t0
        histogram_observe("write_commit_seconds", fsync_s,
                          {"stage": "fsync"})
        now = time.monotonic()
        with self._cond:
            self.batches += 1
            self.commits += len(batch)
            if durable:
                self.fsyncs += len(volumes)
            self.commit_errors += len(errors)
            self._size_sketch.record(len(batch), now)
            self._bytes_sketch.record(nbytes, now)
        counter_add("write_commit_batches_total", 1)
        if durable:
            counter_add("write_commit_fsyncs_total", len(volumes))
        for t in batch:
            t.fsync_seconds = fsync_s
            t.error = errors.get(id(t.volume))
            t._release()

    @staticmethod
    def _commit_volume(v, durable: bool) -> None:
        try:
            v.commit_batch(durable)
        except (ValueError, OSError):
            # a vacuum commit can swap .dat/.idx under us (sync takes
            # no lock by design). Serialize behind the swap by taking
            # the write lock EMPTY, then retry on the fresh handles —
            # the fsync itself must never run under the volume write
            # lock (lock_discipline commit-fsync contract).
            with v.write_lock:
                pass
            v.commit_batch(durable)

    # -- lifecycle / introspection -------------------------------------
    def flush(self, timeout: float = 5.0) -> None:
        """Block until everything currently enqueued has committed."""
        with self._cond:
            pending = list(self._queue)
            self._cond.notify()
        deadline = time.monotonic() + timeout
        for t in pending:
            t.wait(max(0.0, deadline - time.monotonic()))

    def stop(self) -> None:
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
            thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)

    def snapshot(self) -> dict:
        """/debug/commit payload: mode, live window, counters."""
        now = time.monotonic()
        with self._cond:
            opened = self._window_opened
            return {
                "durability": self.durability,
                "max_delay_seconds": self.max_delay,
                "max_bytes": self.max_bytes,
                "queue_depth": len(self._queue),
                "queue_bytes": self._queue_bytes,
                "window_open_seconds": (now - opened)
                if opened is not None else None,
                "batches": self.batches,
                "commits": self.commits,
                "fsyncs": self.fsyncs,
                "commit_errors": self.commit_errors,
                "batch_size": self._size_sketch.merged(now).summary(),
                "batch_bytes": self._bytes_sketch.merged(now).summary(),
            }
