"""DiskLocation: one data directory holding volume files and EC shards.

Equivalent of /root/reference/weed/storage/disk_location.go and
disk_location_ec.go: scan a directory, load `<collection_>?<vid>.dat/.idx`
volumes and `.ecXX`/`.ecx` shard sets, expose free-space checks.
"""
from __future__ import annotations

import os
import re
import shutil
from dataclasses import dataclass, field

from ..ec import geometry as geo
from .volume import Volume

_VOL_RE = re.compile(r"^(?:(?P<col>.+)_)?(?P<vid>\d+)\.(?:dat|vif)$")
_EC_RE = re.compile(r"^(?:(?P<col>.+)_)?(?P<vid>\d+)\.ec(?P<shard>\d{2})$")
# remote-shard manifest: shards of this EC volume whose bytes were
# offloaded to a cold remote tier (storage/store.py tier_offload_ec)
_RSM_RE = re.compile(r"^(?:(?P<col>.+)_)?(?P<vid>\d+)\.rsm$")


def parse_volume_filename(name: str) -> tuple[str, int] | None:
    """Recognise a volume by its .dat — or by a .vif sidecar alone,
    which marks a tiered volume whose .dat lives on a backend storage
    (disk_location.go loadVolumeInfo)."""
    m = _VOL_RE.match(name)
    if not m:
        return None
    return (m.group("col") or "", int(m.group("vid")))


def parse_ec_filename(name: str) -> tuple[str, int, int] | None:
    m = _EC_RE.match(name)
    if not m:
        return None
    return (m.group("col") or "", int(m.group("vid")), int(m.group("shard")))


@dataclass
class EcShardSet:
    """Shards of one EC volume present at this location."""

    collection: str
    vid: int
    shard_ids: set[int] = field(default_factory=set)

    def base_name(self, dirname: str) -> str:
        name = f"{self.collection}_{self.vid}" if self.collection else \
            str(self.vid)
        return os.path.join(dirname, name)


class DiskLocation:
    def __init__(self, dirname: str, max_volumes: int = 8,
                 disk_type: str = "hdd",
                 needle_map_kind: str = "memory"):
        self.dir = dirname
        self.needle_map_kind = needle_map_kind
        self.max_volumes = max_volumes
        self.disk_type = disk_type
        self.volumes: dict[int, Volume] = {}
        self.ec_shards: dict[int, EcShardSet] = {}
        self.load_errors: list[tuple[int, str]] = []
        os.makedirs(dirname, exist_ok=True)

    def load_existing(self) -> None:
        """Scan the dir; one unloadable volume (e.g. a tiered .vif whose
        backend storage isn't configured on this process yet) must not
        abort the whole location — it is recorded in `load_errors` and
        skipped, like the reference logging and continuing per volume
        (disk_location.go concurrentLoadingVolumes)."""
        self.load_errors: list[tuple[int, str]] = []
        for name in sorted(os.listdir(self.dir)):
            v = parse_volume_filename(name)
            if v is not None:
                col, vid = v
                if vid not in self.volumes:
                    try:
                        self.volumes[vid] = Volume(
                            self.dir, col, vid,
                            needle_map_kind=self.needle_map_kind)
                    except Exception as e:
                        self.load_errors.append((vid, f"{type(e).__name__}: {e}"))
                continue
            e = parse_ec_filename(name)
            if e is not None:
                col, vid, shard = e
                entry = self.ec_shards.setdefault(vid, EcShardSet(col, vid))
                entry.shard_ids.add(shard)
                continue
            r = _RSM_RE.match(name)
            if r is not None:
                # offloaded shards: registered so the store re-mounts
                # them remote-backed after a restart (tier recall needs
                # the EC volume to stay served while its bytes are cold)
                col, vid = r.group("col") or "", int(r.group("vid"))
                entry = self.ec_shards.setdefault(vid, EcShardSet(col, vid))
                try:
                    import json as _json

                    with open(os.path.join(self.dir, name),
                              encoding="utf-8") as f:
                        man = _json.load(f)
                    entry.shard_ids.update(
                        int(s) for s in man.get("shards", {}))
                except Exception as ex:
                    self.load_errors.append(
                        (vid, f"rsm manifest: {type(ex).__name__}: {ex}"))

    def try_load_volume(self, vid: int) -> bool:
        """Load one volume's on-disk files if present (VolumeMount)."""
        if vid in self.volumes:
            return True
        for name in os.listdir(self.dir):
            v = parse_volume_filename(name)
            if v is not None and v[1] == vid:
                self.volumes[vid] = Volume(
                    self.dir, v[0], vid,
                    needle_map_kind=self.needle_map_kind)
                return True
        return False

    def new_volume(self, collection: str, vid: int, **kw) -> Volume:
        if vid in self.volumes:
            raise FileExistsError(f"volume {vid} already exists")
        kw.setdefault('needle_map_kind', self.needle_map_kind)
        v = Volume(self.dir, collection, vid, create=True, **kw)
        self.volumes[vid] = v
        return v

    def delete_volume(self, vid: int) -> None:
        v = self.volumes.pop(vid, None)
        if v is not None:
            v.destroy()

    def base_name(self, collection: str, vid: int) -> str:
        name = f"{collection}_{vid}" if collection else str(vid)
        return os.path.join(self.dir, name)

    def add_ec_shard(self, collection: str, vid: int, shard_id: int) -> None:
        entry = self.ec_shards.setdefault(vid, EcShardSet(collection, vid))
        entry.shard_ids.add(shard_id)

    def remove_ec_shards(self, vid: int,
                         shard_ids: set[int] | None = None) -> None:
        entry = self.ec_shards.get(vid)
        if entry is None:
            return
        ids = shard_ids if shard_ids is not None else set(entry.shard_ids)
        base = entry.base_name(self.dir)
        for sid in ids:
            entry.shard_ids.discard(sid)
            try:
                os.remove(base + geo.shard_ext(sid))
            except FileNotFoundError:
                pass
        if not entry.shard_ids:
            self.ec_shards.pop(vid, None)
            # drop the codec sidecar with the last shard — unless a
            # normal volume still owns the base (its tiering record
            # lives in the same .vif)
            if not os.path.exists(base + ".dat"):
                try:
                    os.remove(base + ".vif")
                except FileNotFoundError:
                    pass
            for ext in (".ecx", ".ecj", ".rsm"):
                try:
                    os.remove(base + ext)
                except FileNotFoundError:
                    pass

    def free_space_bytes(self) -> int:
        return shutil.disk_usage(self.dir).free

    @property
    def volume_count(self) -> int:
        return len(self.volumes)

    def close(self) -> None:
        for v in self.volumes.values():
            v.close()
        self.volumes.clear()
