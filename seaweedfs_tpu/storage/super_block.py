"""Volume super block: the 8-byte header of every .dat file.

Byte-compatible with /root/reference/weed/storage/super_block/
super_block.go:16-23: [version, replica placement byte, ttl(2),
compaction revision(2 BE), extra size(2 BE)] (+ optional protobuf extra,
which we keep as opaque bytes).
"""
from __future__ import annotations

import struct
from dataclasses import dataclass, field

SUPER_BLOCK_SIZE = 8


@dataclass(frozen=True)
class ReplicaPlacement:
    """xyz-digit placement: x=other DCs, y=other racks, z=other servers
    in-rack (replica_placement.go:8-31)."""

    diff_dc: int = 0
    diff_rack: int = 0
    same_rack: int = 0

    @classmethod
    def parse(cls, s: str) -> "ReplicaPlacement":
        s = (s or "000").rjust(3, "0")
        d = [int(c) for c in s]
        if any(not 0 <= c <= 2 for c in d):
            raise ValueError(f"unknown replication type {s!r}")
        return cls(diff_dc=d[0], diff_rack=d[1], same_rack=d[2])

    @classmethod
    def from_byte(cls, b: int) -> "ReplicaPlacement":
        return cls.parse(f"{b:03d}")

    def to_byte(self) -> int:
        return self.diff_dc * 100 + self.diff_rack * 10 + self.same_rack

    def __str__(self) -> str:
        return f"{self.diff_dc}{self.diff_rack}{self.same_rack}"

    @property
    def copy_count(self) -> int:
        return self.diff_dc + self.diff_rack + self.same_rack + 1


@dataclass
class SuperBlock:
    version: int = 3
    replica_placement: ReplicaPlacement = field(default_factory=ReplicaPlacement)
    ttl: bytes = b"\x00\x00"
    compaction_revision: int = 0
    extra: bytes = b""

    def to_bytes(self) -> bytes:
        header = struct.pack(
            ">BB2sHH", self.version, self.replica_placement.to_byte(),
            self.ttl[:2].ljust(2, b"\x00"), self.compaction_revision,
            len(self.extra))
        return header + self.extra

    @property
    def block_size(self) -> int:
        return SUPER_BLOCK_SIZE + (len(self.extra) if self.version >= 2 else 0)

    @classmethod
    def from_bytes(cls, header: bytes) -> "SuperBlock":
        if len(header) < SUPER_BLOCK_SIZE:
            raise ValueError("super block truncated")
        version, rp_byte, ttl, rev, extra_size = struct.unpack_from(
            ">BB2sHH", header, 0)
        sb = cls(version=version,
                 replica_placement=ReplicaPlacement.from_byte(rp_byte),
                 ttl=ttl, compaction_revision=rev)
        if extra_size:
            sb.extra = header[SUPER_BLOCK_SIZE:SUPER_BLOCK_SIZE + extra_size]
        return sb

    @classmethod
    def read_from(cls, f) -> "SuperBlock":
        pos = f.tell()
        f.seek(0)
        head = f.read(SUPER_BLOCK_SIZE)
        if len(head) < SUPER_BLOCK_SIZE:
            f.seek(pos)
            raise ValueError("super block truncated")
        extra_size = struct.unpack_from(">H", head, 6)[0]
        extra = f.read(extra_size) if extra_size else b""
        f.seek(pos)
        return cls.from_bytes(head + extra)
