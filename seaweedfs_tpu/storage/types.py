"""Core storage value types and on-disk constants.

Byte-compatible with the reference formats (so fixtures and tools
interoperate): /root/reference/weed/storage/types/needle_types.go:33-40 and
offset_4bytes.go:14-17 / offset_5bytes.go:14-17. Offsets are stored in
units of NEEDLE_PADDING (8 bytes); the default 4-byte big-endian form
gives a 32GB max volume. Setting WEED_5BYTES_OFFSET=1 in the
environment selects the reference's `5BytesOffset` build-tag variant:
17-byte index entries whose offset is 4 BE lower bytes followed by one
high byte (offset_5bytes.go OffsetToBytes order), raising the ceiling
to 8TiB volumes (the reference's large-disk limit). Like the build tag, the choice is process-wide and
must match the files on disk. Sizes are int32 with -1 as the tombstone
marker.
"""
from __future__ import annotations

import os as _os
from dataclasses import dataclass

NEEDLE_ID_SIZE = 8
OFFSET_SIZE = 5 if _os.environ.get("WEED_5BYTES_OFFSET") == "1" else 4
SIZE_SIZE = 4
COOKIE_SIZE = 4
NEEDLE_PADDING = 8
NEEDLE_HEADER_SIZE = COOKIE_SIZE + NEEDLE_ID_SIZE + SIZE_SIZE  # 16
NEEDLE_MAP_ENTRY_SIZE = NEEDLE_ID_SIZE + OFFSET_SIZE + SIZE_SIZE  # 16 / 17
TIMESTAMP_SIZE = 8
TOMBSTONE_SIZE = -1  # Size value marking a deleted needle
# 32GB with 4-byte padded offsets; 8TiB with 5
MAX_VOLUME_SIZE = NEEDLE_PADDING * (1 << (8 * OFFSET_SIZE))


def offset_to_disk_bytes(offset: int) -> bytes:
    """Stored (padded-unit) offset -> its on-disk index encoding."""
    if OFFSET_SIZE == 4:
        return offset.to_bytes(4, "big")
    return (offset & 0xFFFFFFFF).to_bytes(4, "big") + \
        bytes([offset >> 32])


def disk_bytes_to_offset(b: bytes) -> int:
    if OFFSET_SIZE == 4:
        return int.from_bytes(b[:4], "big")
    return (b[4] << 32) | int.from_bytes(b[:4], "big")

SIZE_MASK = 0xFFFFFFFF


def size_is_deleted(size: int) -> bool:
    return size < 0 or size == TOMBSTONE_SIZE


def size_is_valid(size: int) -> bool:
    return size > 0 and size != TOMBSTONE_SIZE


def size_to_u32(size: int) -> int:
    return size & SIZE_MASK


def u32_to_size(u: int) -> int:
    """Stored uint32 -> signed Size."""
    return u - (1 << 32) if u & 0x80000000 else u


def offset_to_actual(stored: int) -> int:
    """Stored (padded-unit) offset -> byte offset in the volume file."""
    return stored * NEEDLE_PADDING


def actual_to_offset(byte_offset: int) -> int:
    if byte_offset % NEEDLE_PADDING:
        raise ValueError(f"offset {byte_offset} not {NEEDLE_PADDING}-aligned")
    stored = byte_offset // NEEDLE_PADDING
    if stored >= 1 << (8 * OFFSET_SIZE):
        raise ValueError(f"offset {byte_offset} exceeds max volume size")
    return stored


@dataclass(frozen=True)
class NeedleValue:
    """One needle-map entry: (key, stored offset, size)."""

    key: int          # NeedleId, uint64
    offset: int       # stored units of NEEDLE_PADDING
    size: int         # signed; TOMBSTONE_SIZE or negative = deleted

    def to_bytes(self) -> bytes:
        return (self.key.to_bytes(NEEDLE_ID_SIZE, "big")
                + offset_to_disk_bytes(self.offset)
                + size_to_u32(self.size).to_bytes(SIZE_SIZE, "big"))

    @classmethod
    def from_bytes(cls, b: bytes) -> "NeedleValue":
        key = int.from_bytes(b[:8], "big")
        offset = disk_bytes_to_offset(b[8:8 + OFFSET_SIZE])
        size = u32_to_size(int.from_bytes(
            b[8 + OFFSET_SIZE:8 + OFFSET_SIZE + SIZE_SIZE], "big"))
        return cls(key, offset, size)


def format_file_id(volume_id: int, key: int, cookie: int) -> str:
    """'vid,khexchex' — reference fid string (needle/file_id.go)."""
    return f"{volume_id},{key:x}{cookie:08x}"


def parse_file_id(fid: str) -> tuple[int, int, int]:
    """fid string -> (volume_id, key, cookie). A `_N` suffix adds N to
    the key (needle.go ParsePath:121-141) — that's how clients address
    the extra slots of an `assign?count=N` batch: fid, fid_1, ...,
    fid_{N-1}."""
    vid_s, _, rest = fid.partition(",")
    delta = 0
    if "_" in rest:
        rest, _, delta_s = rest.rpartition("_")
        try:
            delta = int(delta_s)
        except ValueError:
            raise ValueError(f"bad file id delta {fid!r}") from None
    if not rest or len(rest) <= 8:
        raise ValueError(f"bad file id {fid!r}")
    volume_id = int(vid_s)
    key = int(rest[:-8], 16) + delta
    cookie = int(rest[-8:], 16)
    return volume_id, key, cookie
