"""Reed-Solomon coding matrices over GF(256).

Uses the systematic-Vandermonde construction (Backblaze / klauspost
`buildMatrix` lineage — the default of the reference's codec dependency,
/root/reference/go.mod:62): rows of a Vandermonde matrix are made systematic
by right-multiplying with the inverse of its top k x k square, so shards
0..k-1 are the data bytes verbatim and shards k..n-1 are parity.

All matrices are small ((k+m) x k, k+m <= 256) host-side numpy uint8.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from functools import lru_cache

import numpy as np

from . import gf256


def vandermonde(rows: int, cols: int) -> np.ndarray:
    """v[r, c] = r ** c in GF(256). Any k of the rows are independent."""
    v = np.zeros((rows, cols), dtype=np.uint8)
    for r in range(rows):
        for c in range(cols):
            v[r, c] = gf256.gf_pow(r, c)
    return v


@lru_cache(maxsize=64)
def _encode_matrix_cached(data_shards: int, parity_shards: int) -> bytes:
    total = data_shards + parity_shards
    if total > gf256.FIELD:
        raise ValueError("data+parity shards must be <= 256")
    vm = vandermonde(total, data_shards)
    top_inv = gf256.mat_inv(vm[:data_shards, :data_shards])
    m = gf256.mat_mul(vm, top_inv)
    return m.tobytes()


def encode_matrix(data_shards: int, parity_shards: int) -> np.ndarray:
    """The (k+m) x k systematic encode matrix: identity on top, parity
    coefficient rows below."""
    total = data_shards + parity_shards
    raw = _encode_matrix_cached(data_shards, parity_shards)
    return np.frombuffer(raw, dtype=np.uint8).reshape(total, data_shards).copy()


def parity_rows(data_shards: int, parity_shards: int) -> np.ndarray:
    """Just the m x k parity coefficient block."""
    return encode_matrix(data_shards, parity_shards)[data_shards:, :]


# ---------------------------------------------------------------------------
# Inversion cache: repair storms re-invert the same surviving-set matrix
# ---------------------------------------------------------------------------

INVERSION_CACHE_MAX = 512

_inv_cache: "OrderedDict[tuple, bytes]" = OrderedDict()
_inv_lock = threading.Lock()


def _count_inv(outcome: str) -> None:
    try:
        from ..utils import metrics

        metrics.counter_add("rs_matrix_inversion_cache_total", 1,
                            {"outcome": outcome})
    except Exception:  # pragma: no cover - metrics must never fatal
        pass


def _cached_inverse(key: tuple, sub: np.ndarray) -> np.ndarray:
    """LRU-cached gf256.mat_inv keyed by the surviving-shard set (plus
    the code identity): a repair storm over one loss pattern hits the
    same k x k inversion on every stripe chunk."""
    with _inv_lock:
        raw = _inv_cache.get(key)
        if raw is not None:
            _inv_cache.move_to_end(key)
    if raw is not None:
        _count_inv("hit")
        n = sub.shape[0]
        return np.frombuffer(raw, dtype=np.uint8).reshape(n, n).copy()
    inv = gf256.mat_inv(sub)
    _count_inv("miss")
    with _inv_lock:
        _inv_cache[key] = inv.tobytes()
        while len(_inv_cache) > INVERSION_CACHE_MAX:
            _inv_cache.popitem(last=False)
    return inv


def inversion_cache_info() -> dict:
    return {"entries": len(_inv_cache), "max": INVERSION_CACHE_MAX}


def reconstruction_matrix(
    data_shards: int,
    parity_shards: int,
    present: list[int],
) -> tuple[np.ndarray, list[int]]:
    """Matrix recovering ALL k+m shards from k present ones.

    `present` lists >= k available shard indices (0..k+m-1); the first k of
    them (sorted) are used as inputs. Returns (R, input_shard_ids) with
        all_shards = R @ stack(shards[i] for i in input_shard_ids)
    R is (k+m) x k; rows for the input shards are unit vectors.
    """
    k = data_shards
    present = sorted(set(present))
    if len(present) < k:
        raise ValueError(
            f"need >= {k} shards to reconstruct, have {len(present)}")
    inputs = present[:k]
    enc = encode_matrix(data_shards, parity_shards)
    sub = enc[inputs, :]                      # (k, k): inputs = sub @ data
    data_from_inputs = _cached_inverse(
        ("rs", k, parity_shards, tuple(inputs)), sub)
    return gf256.mat_mul(enc, data_from_inputs), inputs


def recovery_rows(
    data_shards: int,
    parity_shards: int,
    present: list[int],
    missing: list[int],
) -> tuple[np.ndarray, list[int]]:
    """Rows of the reconstruction matrix for `missing` shards only.

    Returns (matrix of shape (len(missing), k), input_shard_ids) where
        missing_shards = matrix @ stack(shards[i] for i in input_shard_ids)
    """
    full, inputs = reconstruction_matrix(data_shards, parity_shards, present)
    return full[missing, :].copy(), inputs


# ---------------------------------------------------------------------------
# Code-family matrices: a code is (encode matrix, locality groups,
# repair plan) — ec/geometry.CodeConfig carries the structure, this
# module builds the GF(256) matrices behind it.
# ---------------------------------------------------------------------------

@lru_cache(maxsize=64)
def _encode_matrix_for_cached(spec: str, k: int, n_local: int,
                              n_global: int) -> bytes:
    total = k + n_local + n_global
    if not n_local:  # plain RS
        return _encode_matrix_cached(k, n_global)
    enc = np.zeros((total, k), dtype=np.uint8)
    enc[:k] = np.eye(k, dtype=np.uint8)
    gs = k // n_local
    for i in range(n_local):
        enc[k + i, i * gs:(i + 1) * gs] = 1
    # Global rows: the LAST g systematic-Vandermonde parity rows of
    # RS(k, locals+globals). The first klauspost parity row is the
    # all-ones XOR row — exactly the sum of the local-group rows — so
    # taking rows [locals:] keeps the stack independent of the locals.
    pr = parity_rows(k, n_local + n_global)
    enc[k + n_local:] = pr[n_local:]
    return enc.tobytes()


def encode_matrix_for(code) -> np.ndarray:
    """(total, k) systematic encode matrix of a geometry.CodeConfig:
    identity on top; for LRC, local XOR indicator rows then global
    Vandermonde rows; for RS, the classic parity block."""
    raw = _encode_matrix_for_cached(code.spec, code.k, code.n_local,
                                    code.n_global)
    return np.frombuffer(raw, dtype=np.uint8).reshape(
        code.total, code.k).copy()


def parity_rows_for(code) -> np.ndarray:
    """The (m, k) parity coefficient block of a code's encode matrix."""
    return encode_matrix_for(code)[code.k:, :]


def _gf_eliminate(rows: np.ndarray) -> tuple[np.ndarray, list[int]]:
    """Row-reduce over GF(256) -> (reduced rows, pivot column list)."""
    work = np.array(rows, dtype=np.uint8)
    r, c = work.shape
    pivots: list[int] = []
    row = 0
    for col in range(c):
        if row >= r:
            break
        piv = None
        for rr in range(row, r):
            if work[rr, col]:
                piv = rr
                break
        if piv is None:
            continue
        if piv != row:
            work[[row, piv]] = work[[piv, row]]
        work[row] = gf256.MUL_TABLE[gf256.INV[work[row, col]], work[row]]
        for rr in range(r):
            if rr != row and work[rr, col]:
                work[rr] ^= gf256.MUL_TABLE[int(work[rr, col]), work[row]]
        pivots.append(col)
        row += 1
    return work, pivots


def rank_of(code, present: list[int]) -> int:
    """GF(256) rank of the encode-matrix rows of `present` shards —
    the honest recoverability check (LRC local-parity rows are
    linearly dependent with their group, so counting survivors lies)."""
    enc = encode_matrix_for(code)
    rows = enc[[s for s in sorted(set(present)) if 0 <= s < code.total]]
    if not len(rows):
        return 0
    _, pivots = _gf_eliminate(rows)
    return len(pivots)


def gf_solve(a: np.ndarray, b: np.ndarray) -> np.ndarray | None:
    """Solve A @ X = B over GF(256) (A: (r, c), B: (r, t)) -> X (c, t),
    free variables zeroed; None when inconsistent."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    r, c = a.shape
    t = b.shape[1]
    work, pivots = _gf_eliminate(np.concatenate([a, b], axis=1))
    x = np.zeros((c, t), dtype=np.uint8)
    for row, col in enumerate(pivots):
        if col >= c:      # pivot landed in the B block: inconsistent
            return None
        x[col] = work[row, c:]
    # consistency: rows below the last pivot must be all-zero in B too
    for row in range(len(pivots), r):
        if work[row, c:].any():
            return None
    return x


def solve_inputs(code, available: list[int], missing: list[int],
                 prefer: list[int] | None = None) -> list[int] | None:
    """Greedy minimal-ish input set: the smallest prefix of available
    shards (preferred readers first, then data, locals, globals) whose
    encode rows span every missing shard's row. None = unrecoverable.
    Rows that do not grow the span are never added — a dependent local
    parity costs a read without buying information."""
    enc = encode_matrix_for(code)
    avail = [s for s in sorted(set(available))
             if 0 <= s < code.total and s not in set(missing)]
    prefer = [s for s in (prefer or []) if s in set(avail)]
    ordered = prefer + [s for s in avail if s not in set(prefer)]
    targets = enc[sorted(set(missing))]
    chosen: list[int] = []
    for sid in ordered:
        trial = chosen + [sid]
        basis, pivots = _gf_eliminate(enc[trial])
        if len(pivots) == len(chosen):  # dependent row: skip
            continue
        chosen = trial
        if gf_solve(enc[chosen].T, targets.T) is not None:
            return chosen
    return None


def recovery_rows_for(code, present: list[int], missing: list[int]
                      ) -> tuple[np.ndarray, list[int]]:
    """Code-aware recovery_rows: (matrix (len(missing), fanin),
    input_shard_ids) with
        missing = matrix @ stack(shards[i] for i in input_shard_ids)
    For RS this is the classic k-input inversion (cached); for LRC the
    input set follows the code's repair plan — a single group loss
    reads group_size shards, not k."""
    if code.is_rs:
        return recovery_rows(code.k, code.m, present, missing)
    missing = sorted(set(int(s) for s in missing))
    plan = code.repair_plan(missing, present)
    if plan is None:
        raise ValueError(
            f"code {code.spec}: shards {missing} unrecoverable from "
            f"{sorted(set(present))}")
    inputs = list(plan.reads)
    key = (code.spec, tuple(inputs), tuple(missing))
    with _inv_lock:
        raw = _inv_cache.get(key)
        if raw is not None:
            _inv_cache.move_to_end(key)
    if raw is not None:
        _count_inv("hit")
        rows = np.frombuffer(raw, dtype=np.uint8).reshape(
            len(missing), len(inputs)).copy()
        return rows, inputs
    enc = encode_matrix_for(code)
    x = gf_solve(enc[inputs].T, enc[missing].T)
    if x is None:  # plan said solvable; matrices disagree -> bug guard
        raise ValueError(
            f"code {code.spec}: no solution for {missing} from {inputs}")
    rows = np.ascontiguousarray(x.T)
    _count_inv("miss")
    with _inv_lock:
        _inv_cache[key] = rows.tobytes()
        while len(_inv_cache) > INVERSION_CACHE_MAX:
            _inv_cache.popitem(last=False)
    return rows, inputs
