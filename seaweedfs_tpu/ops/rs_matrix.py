"""Reed-Solomon coding matrices over GF(256).

Uses the systematic-Vandermonde construction (Backblaze / klauspost
`buildMatrix` lineage — the default of the reference's codec dependency,
/root/reference/go.mod:62): rows of a Vandermonde matrix are made systematic
by right-multiplying with the inverse of its top k x k square, so shards
0..k-1 are the data bytes verbatim and shards k..n-1 are parity.

All matrices are small ((k+m) x k, k+m <= 256) host-side numpy uint8.
"""
from __future__ import annotations

from functools import lru_cache

import numpy as np

from . import gf256


def vandermonde(rows: int, cols: int) -> np.ndarray:
    """v[r, c] = r ** c in GF(256). Any k of the rows are independent."""
    v = np.zeros((rows, cols), dtype=np.uint8)
    for r in range(rows):
        for c in range(cols):
            v[r, c] = gf256.gf_pow(r, c)
    return v


@lru_cache(maxsize=64)
def _encode_matrix_cached(data_shards: int, parity_shards: int) -> bytes:
    total = data_shards + parity_shards
    if total > gf256.FIELD:
        raise ValueError("data+parity shards must be <= 256")
    vm = vandermonde(total, data_shards)
    top_inv = gf256.mat_inv(vm[:data_shards, :data_shards])
    m = gf256.mat_mul(vm, top_inv)
    return m.tobytes()


def encode_matrix(data_shards: int, parity_shards: int) -> np.ndarray:
    """The (k+m) x k systematic encode matrix: identity on top, parity
    coefficient rows below."""
    total = data_shards + parity_shards
    raw = _encode_matrix_cached(data_shards, parity_shards)
    return np.frombuffer(raw, dtype=np.uint8).reshape(total, data_shards).copy()


def parity_rows(data_shards: int, parity_shards: int) -> np.ndarray:
    """Just the m x k parity coefficient block."""
    return encode_matrix(data_shards, parity_shards)[data_shards:, :]


def reconstruction_matrix(
    data_shards: int,
    parity_shards: int,
    present: list[int],
) -> tuple[np.ndarray, list[int]]:
    """Matrix recovering ALL k+m shards from k present ones.

    `present` lists >= k available shard indices (0..k+m-1); the first k of
    them (sorted) are used as inputs. Returns (R, input_shard_ids) with
        all_shards = R @ stack(shards[i] for i in input_shard_ids)
    R is (k+m) x k; rows for the input shards are unit vectors.
    """
    k = data_shards
    present = sorted(set(present))
    if len(present) < k:
        raise ValueError(
            f"need >= {k} shards to reconstruct, have {len(present)}")
    inputs = present[:k]
    enc = encode_matrix(data_shards, parity_shards)
    sub = enc[inputs, :]                      # (k, k): inputs = sub @ data
    data_from_inputs = gf256.mat_inv(sub)     # (k, k): data = inv @ inputs
    return gf256.mat_mul(enc, data_from_inputs), inputs


def recovery_rows(
    data_shards: int,
    parity_shards: int,
    present: list[int],
    missing: list[int],
) -> tuple[np.ndarray, list[int]]:
    """Rows of the reconstruction matrix for `missing` shards only.

    Returns (matrix of shape (len(missing), k), input_shard_ids) where
        missing_shards = matrix @ stack(shards[i] for i in input_shard_ids)
    """
    full, inputs = reconstruction_matrix(data_shards, parity_shards, present)
    return full[missing, :].copy(), inputs
