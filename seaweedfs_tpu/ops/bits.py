"""Shared jax bit-plane pack/unpack — the device-side counterpart of
gf256.unpack_bits/pack_bits (numpy).

Every TPU codec path (codec_jax, models.ec_pipeline, bench) MUST use
these two functions: the codecs have to stay bit-identical for shard
interoperability, and divergent hand-rolled copies of the shift/weights
transform are exactly how they'd drift apart.

Bit order: bit s of byte b lands at plane-row 8*i+s for shard-row i
(bit-minor), matching gf256.expand_to_bits block layout.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def unpack_bits_bf16(x: jax.Array) -> jax.Array:
    """(..., k, n) uint8 -> (..., 8k, n) bf16 0/1 bit-planes."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (x[..., :, None, :] >> shifts[None, :, None]) & 1
    shape = x.shape[:-2] + (x.shape[-2] * 8, x.shape[-1])
    return bits.reshape(shape).astype(jnp.bfloat16)


def pack_bits_uint8(bits: jax.Array) -> jax.Array:
    """(..., 8m, n) int 0/1 -> (..., m, n) uint8."""
    m8, n = bits.shape[-2], bits.shape[-1]
    b = bits.reshape(bits.shape[:-2] + (m8 // 8, 8, n)).astype(jnp.uint8)
    w = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))[None, :, None]
    return (b * w).sum(axis=-2, dtype=jnp.uint8)


def coded_matmul_bits(a_bits: jax.Array, shards: jax.Array) -> jax.Array:
    """The core codec op: (8m, 8k) bf16 bit-matrix x (k, n) uint8 shards
    -> (m, n) uint8, GF(256) coded matmul via GF(2) matmul on the MXU."""
    bits = unpack_bits_bf16(shards)
    acc = jax.lax.dot_general(
        a_bits, bits, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return pack_bits_uint8(acc.astype(jnp.int32) & 1)
