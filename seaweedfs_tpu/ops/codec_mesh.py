"""Mesh codec: the multichip dryrun promoted to a production backend.

`-ec.backend=mesh` runs the GF(256) bit-plane coded matmul sharded over
every local device: the (k, n) column block a caller hands any codec
backend is split into `vol` column segments (the data-parallel batch
axis of `parallel/mesh.py`) and each segment's columns shard over the
`col` (sequence-parallel) axis, so one jitted dispatch — compiled with
explicit `NamedSharding`s, the pjit pattern from SNIPPETS.md [1]–[3] —
keeps all chips busy. Encode and reconstruction are column-local, so
there are no collectives in the hot path and throughput scales
near-linearly with device count until the host↔device link is the wall
(which the mesh rows of `ec/probe.py` measure rather than assume).

Geometry comes from `parallel.mesh.make_mesh`: `{'vol': 4, 'col': 2}`
on 8 devices by default, overridable with `-ec.mesh.devices` /
`-ec.mesh.col` (env `SEAWEEDFS_TPU_EC_MESH_DEVICES` /
`SEAWEEDFS_TPU_EC_MESH_COL`). Wide codes (RS(28,4)+) are first-class:
the coefficient matrix is a runtime argument exactly as in the
single-chip codec, so `ec.encode -codec=28.4` volumes ride the same
compiled kernel shape and amortize the per-byte transfer cost over
2.8x more data bytes per parity byte.

The streaming entry point mirrors `JaxCodec.coded_matmul_stream`: a
depth-N staged pipeline (upload thread committing the sharded
device_put, kernel, drain thread gathering the result) with the same
ec_codec_stage_seconds{stage,backend="mesh"} attribution.
"""
from __future__ import annotations

import time as _time
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import metrics
from . import gf256, schedule

# Per-vol-segment column widths are padded up to power-of-two buckets
# (>= this) so repeated uneven blocks share a handful of XLA compiles,
# mirroring JaxCodec._pad_width.
BUCKET_MIN = 256


def _mesh_kernel(a_bits: jax.Array, stripes: jax.Array) -> jax.Array:
    """(8m, 8k) bf16 bit-matrix x (vol, k, w) uint8 -> (vol, m, w)
    uint8. Batch and column dims are embarrassingly parallel, so with
    stripes sharded (vol -> 'vol', w -> 'col') every device computes
    its slice locally — no collectives."""
    from .bits import pack_bits_uint8, unpack_bits_bf16

    bits = unpack_bits_bf16(stripes)                    # (vol, 8k, w)
    acc = jnp.einsum("st,btn->bsn", a_bits, bits,
                     preferred_element_type=jnp.float32)
    return pack_bits_uint8(acc.astype(jnp.int32) & 1)


def _mesh_sched_kernel(program, stripes: jax.Array) -> jax.Array:
    """Scheduled twin of _mesh_kernel: the CSE-optimized XOR program
    (ops/schedule.Program, static) over uint8 bit-planes, batched over
    the vol axis. Elementwise per column, so it composes with the same
    (vol, col) NamedSharding with no collectives."""
    from .bits import pack_bits_uint8

    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (stripes[:, :, None, :] >> shifts[None, None, :, None]) & 1
    bits = bits.reshape(stripes.shape[0], stripes.shape[1] * 8,
                        stripes.shape[2])               # (vol, 8k, w)
    pool = [bits[:, i, :] for i in range(program.n_in)]
    for _, a, b in program.ops:
        pool.append(pool[a] ^ pool[b])
    zero = jnp.zeros_like(pool[0])
    rows = jnp.stack([pool[v] if v >= 0 else zero
                      for v in program.outputs], axis=1)  # (vol, 8m, w)
    return pack_bits_uint8(rows)


class MeshCodec:
    """Coded-matmul backend sharded over the local (vol, col) mesh."""

    name = "mesh"

    BITMAT_CACHE_MAX = 256

    def __init__(self, mesh=None, bucket_min: int = BUCKET_MIN):
        from ..parallel import mesh as pmesh

        if mesh is None:
            n_devices, col = pmesh.mesh_config()
            mesh = pmesh.make_mesh(n_devices, col)
        self.mesh = mesh
        self.vol, self.col = (int(x) for x in mesh.devices.shape)
        self.n_devices = int(mesh.devices.size)
        self.bucket_min = max(1, int(bucket_min))
        self._data_sh = pmesh.stripe_sharding(mesh)
        self._repl = pmesh.replicated(mesh)
        self._bitmats: "OrderedDict[bytes, jax.Array]" = OrderedDict()
        self._fn = None
        self._fn_meas = None
        self._sched_fns: "OrderedDict[object, object]" = OrderedDict()
        self._chooser = schedule.Chooser()
        self._donate = mesh.devices.flat[0].platform != "cpu"
        metrics.gauge_set("ec_mesh_devices", self.n_devices)
        metrics.gauge_set("ec_mesh_vol", self.vol)
        metrics.gauge_set("ec_mesh_col", self.col)

    # -- introspection --------------------------------------------------

    def describe(self) -> dict:
        from ..parallel import mesh as pmesh

        return pmesh.describe(self.mesh)

    # -- compiled step / coefficient cache ------------------------------

    def _step(self):
        if self._fn is None:
            self._fn = jax.jit(
                _mesh_kernel,
                in_shardings=(self._repl, self._data_sh),
                out_shardings=self._data_sh,
                donate_argnums=(1,) if self._donate else ())
        return self._fn

    def _sched_step(self, plan):
        """Compiled scheduled kernel for one program (static arg);
        bounded cache — one entry per distinct coefficient matrix."""
        fn = self._sched_fns.get(plan)
        if fn is None:
            fn = jax.jit(
                _mesh_sched_kernel, static_argnums=(0,),
                in_shardings=(self._data_sh,),
                out_shardings=self._data_sh)
            self._sched_fns[plan] = fn
            if len(self._sched_fns) > self.BITMAT_CACHE_MAX:
                self._sched_fns.popitem(last=False)
        else:
            self._sched_fns.move_to_end(plan)
        return fn

    def _plan_for(self, coef: np.ndarray, nbytes: int):
        """Measured scheduled-vs-dense choice for this (matrix, size
        bucket) — same protocol as JaxCodec._plan_for, against the
        sharded kernels: the verdict is keyed by the sample's own byte
        size and measured on a background thread, serving the dense
        kernel until it lands."""
        k = coef.shape[1]
        w = self._seg_width(max(1, min(nbytes // max(1, k), 4 << 20)))
        sample_bytes = min(nbytes, self.vol * k * w)
        state: dict = {}

        def prep():
            if not state:
                rng = np.random.default_rng(0)
                batched = rng.integers(
                    0, 256, (self.vol, k, w), dtype=np.uint8)
                state["dev"] = self._h2d(batched)
                state["mats"] = self._coef_bits(coef)
                state["plan"] = schedule.plan_for(coef)

        def run_sched():
            prep()
            self._sched_step(state["plan"])(
                state["plan"], state["dev"]).block_until_ready()

        # measurement must not donate the shared sample buffer
        if self._fn_meas is None:
            self._fn_meas = jax.jit(
                _mesh_kernel,
                in_shardings=(self._repl, self._data_sh),
                out_shardings=self._data_sh)

        def run_dense():
            prep()
            self._fn_meas(state["mats"],
                          state["dev"]).block_until_ready()

        if self._chooser.use_scheduled(coef, sample_bytes, run_sched,
                                       run_dense, background=True):
            return schedule.plan_for(coef)
        return None

    def _kernel_call(self, mats, plan, dev):
        if plan is not None:
            return self._sched_step(plan)(plan, dev)
        return self._step()(mats, dev)

    def _coef_bits(self, coef: np.ndarray) -> jax.Array:
        key = coef.shape[0].to_bytes(2, "big") + coef.tobytes()
        bm = self._bitmats.get(key)
        if bm is None:
            bm = jax.device_put(
                jnp.asarray(gf256.expand_to_bits(coef),
                            dtype=jnp.bfloat16), self._repl)
            self._bitmats[key] = bm
            if len(self._bitmats) > self.BITMAT_CACHE_MAX:
                self._bitmats.popitem(last=False)
        else:
            self._bitmats.move_to_end(key)
        return bm

    # -- host-side layout -----------------------------------------------

    def _seg_width(self, n: int) -> int:
        """Per-vol-segment width for n columns: divides `col` (the
        NamedSharding requirement), bucket-padded to bound compiles."""
        grain = self.vol * self.col
        per = -(-n // grain) * self.col
        bucket = self.bucket_min
        while bucket < per:
            bucket <<= 1
        # re-round after bucketing: a non-power-of-two col axis must
        # still divide the padded width
        return -(-bucket // self.col) * self.col

    def _to_batched(self, shards: np.ndarray) -> tuple[np.ndarray, int]:
        """(k, n) -> (vol, k, per) with zero padding; segment v holds
        columns [v*per, (v+1)*per). Zero columns encode/reconstruct to
        zero columns, sliced off on the way back."""
        k, n = shards.shape
        per = self._seg_width(n)
        total = per * self.vol
        if total != n:
            padded = np.zeros((k, total), dtype=np.uint8)
            padded[:, :n] = shards
        else:
            padded = np.asarray(shards, dtype=np.uint8)
        return np.ascontiguousarray(
            padded.reshape(k, self.vol, per).transpose(1, 0, 2)), per

    def _from_batched(self, out: np.ndarray, n: int) -> np.ndarray:
        """(vol, m, per) device result -> (m, n) host block."""
        vol, m, per = out.shape
        res = out.transpose(1, 0, 2).reshape(m, vol * per)
        return np.ascontiguousarray(res[:, :n]) if vol * per != n \
            else res

    def _h2d(self, batched: np.ndarray) -> jax.Array:
        """Committed sharded placement: one device_put against the
        explicit NamedSharding scatters the host block across every
        device and pins it there."""
        return jax.device_put(batched, self._data_sh)

    # -- codec API ------------------------------------------------------

    def coded_matmul(self, coef: np.ndarray, shards) -> np.ndarray:
        coef = np.asarray(coef, dtype=np.uint8)
        m, k = coef.shape
        shards = np.asarray(shards, dtype=np.uint8)
        assert shards.ndim == 2 and shards.shape[0] == k, shards.shape
        n = shards.shape[1]
        if n == 0:
            return np.zeros((m, 0), dtype=np.uint8)
        plan = self._plan_for(coef, shards.nbytes)
        mats = self._coef_bits(coef)
        batched, _per = self._to_batched(shards)
        out = self._kernel_call(mats, plan, self._h2d(batched))
        return self._from_batched(np.asarray(out), n)

    def coded_matmul_stream(self, coef: np.ndarray, blocks,
                            depth: int = 2):
        """Depth-N staged pipeline over the mesh: while the drain
        thread gathers block j-1 from all devices, the devices run
        block j's sharded kernel and the upload thread scatters block
        j+1 — the same schedule as the single-chip feed, with the
        whole mesh behind each stage. Stages record
        ec_codec_stage_seconds{stage,backend="mesh"}."""
        from collections import deque
        from concurrent.futures import Future, ThreadPoolExecutor

        from .codec_jax import observe_stage

        coef = np.asarray(coef, dtype=np.uint8)
        m = coef.shape[0]
        mats = self._coef_bits(coef)
        depth = max(1, int(depth))
        backend = self.name
        # streams are bulk: one scheduled-vs-dense decision up front
        plan = self._plan_for(
            coef, coef.shape[1] * self.n_devices * (1 << 20))

        def upload(block: np.ndarray):
            t0 = _time.perf_counter()
            batched, _per = self._to_batched(block)
            dev = self._h2d(batched)
            dev.block_until_ready()
            t1 = _time.perf_counter()
            out = self._kernel_call(mats, plan, dev)
            observe_stage(backend, "h2d", t1 - t0)
            return out

        def drain(up_fut, n: int):
            out = up_fut.result()
            t0 = _time.perf_counter()
            out.block_until_ready()
            t1 = _time.perf_counter()
            arr = self._from_batched(np.asarray(out), n)
            t2 = _time.perf_counter()
            observe_stage(backend, "kernel", t1 - t0)
            observe_stage(backend, "d2h", t2 - t1)
            return arr, t2

        up_ex = ThreadPoolExecutor(1, thread_name_prefix="ecmesh-h2d")
        down_ex = ThreadPoolExecutor(1, thread_name_prefix="ecmesh-d2h")

        def finish(fut) -> np.ndarray:
            arr, t_done = fut.result()
            relay = _time.perf_counter() - t_done
            if relay > 0:
                observe_stage(backend, "relay", relay)
            return arr

        try:
            pending: deque = deque()
            it = iter(blocks)
            while True:
                t0 = _time.perf_counter()
                try:
                    block = next(it)
                except StopIteration:
                    break
                observe_stage(backend, "pread",
                              _time.perf_counter() - t0)
                block = np.asarray(block, dtype=np.uint8)
                if block.shape[1] == 0:
                    # empty block still rides the queue so ordering
                    # holds (same contract as JaxCodec's stream)
                    f: Future = Future()
                    f.set_result((np.zeros((m, 0), dtype=np.uint8),
                                  _time.perf_counter()))
                    pending.append(f)
                else:
                    up = up_ex.submit(upload, block)
                    pending.append(
                        down_ex.submit(drain, up, block.shape[1]))
                while len(pending) >= depth:
                    yield finish(pending.popleft())
            while pending:
                yield finish(pending.popleft())
        finally:
            up_ex.shutdown(wait=True, cancel_futures=True)
            down_ex.shutdown(wait=True, cancel_futures=True)
