"""Fused Pallas TPU kernel for the GF(256) coded matmul.

The XLA path (codec_jax / bits.coded_matmul_bits) materializes the
(8k, n) bf16 bit-plane expansion — 32x the input bytes of HBM write+
read traffic — so at scale it runs HBM-bound far below the MXU's
ceiling. This kernel keeps the whole unpack -> matmul -> pack chain in
VMEM per column tile: HBM sees only the (k, TN) uint8 reads and
(m, TN) uint8 writes.

Layout discipline (the first attempt died on this): Mosaic relayouts
across the sublane dimension — the interleaving reshape
(k, 8, n)->(8k, n) or strided sublane slicing — are catastrophically
slow. So the kernel never interleaves: the bit expansion CONCATENATES
the 8 shift masks along sublanes (plane-major order) and the
coefficient matrix's columns are permuted on the host to match
(plane_major_bit_matrix); the byte pack is itself a tiny matmul with
the power-of-two packing matrix P[i, 8i+b] = 2^b — exact in f32.

Bit/byte semantics are EXACTLY bits.coded_matmul_bits (golden tests
run identical vectors through both paths). Measured on the dev chip
through the axon relay, scan-chained pipelines put the fused kernel a
few percent ahead of the XLA path (21.6 vs 20.6 GB/s) with BOTH
saturating the relayed chip's effective HBM streaming (~30 GB/s
device-side — raw copy-through-kernel measures the same); the fused
kernel's 20x traffic reduction should open a real gap on direct-attach
hardware. Beware two measurement traps this file's history hit:
closing over the data array turns it into a multi-GB jit constant,
and a fori_loop over one slab gets hoisted as loop-invariant and
reports fantasy numbers — bench.py's scan-over-distinct-slabs is the
honest shape. Selected with -ec.backend=pallas.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

COL_TILE = 4096  # lanes per grid step


def _kernel(a_ref, p_ref, x_ref, o_ref):
    """a_ref: (8m, 8k) bf16 coefficient matrix with PLANE-MAJOR
    columns (see plane_major_bit_matrix); p_ref: (m, 8m) bf16 packing
    matrix; x_ref: (k, TN) uint8; o_ref: (m, TN) uint8.

    The bit expansion concatenates the 8 shift masks along sublanes
    (plane-major: all bit-0 rows, then bit-1 rows, ...) — concat is a
    cheap placement, unlike the interleaving (k,8,TN)->(8k,TN) reshape
    which forces a catastrophic sublane relayout."""
    x = x_ref[:, :].astype(jnp.int32)
    planes = [((x >> s) & 1).astype(jnp.bfloat16) for s in range(8)]
    bits = jnp.concatenate(planes, axis=0)  # (8k, TN) plane-major
    acc = jax.lax.dot_general(
        a_ref[:, :], bits, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    par = (acc.astype(jnp.int32) & 1).astype(jnp.bfloat16)  # (8m, TN)
    packed = jax.lax.dot_general(
        p_ref[:, :], par, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)  # exact: sums <= 255
    o_ref[:, :] = packed.astype(jnp.int32).astype(jnp.uint8)


def plane_major_bit_matrix(a_bits: np.ndarray | jax.Array) -> jax.Array:
    """(8m, 8k) bit-minor matrix -> (8m, 8k) with columns permuted to
    plane-major order: column s*k + j multiplies bit s of shard j
    (matching the kernel's concatenated expansion). Row order is
    untouched, so the packing matrix stays the same."""
    a = np.asarray(a_bits, dtype=np.float32)
    m8, k8 = a.shape
    k = k8 // 8
    perm = [8 * j + s for s in range(8) for j in range(k)]
    return jnp.asarray(a[:, perm], dtype=jnp.bfloat16)


def packing_matrix(m: int) -> jax.Array:
    """(m, 8m) P with P[i, 8i+b] = 2^b: packs bit rows back to bytes
    via one exact f32 matmul (bit-minor order, matching
    bits.pack_bits_uint8)."""
    p = np.zeros((m, 8 * m), dtype=np.float32)
    for i in range(m):
        for b in range(8):
            p[i, 8 * i + b] = float(1 << b)
    return jnp.asarray(p, dtype=jnp.bfloat16)


def _coded_matmul_pallas_pm_impl(a_pm: jax.Array, pack: jax.Array,
                                 shards: jax.Array,
                                 interpret: bool = False) -> jax.Array:
    """a_pm: (8m, 8k) bf16 plane-major coefficient matrix;
    pack: (m, 8m) bf16; shards: (k, n) uint8 with n % COL_TILE == 0
    -> (m, n) uint8."""
    from jax.experimental import pallas as pl

    m8, k8 = a_pm.shape
    k, n = shards.shape
    assert k8 == 8 * k and n % COL_TILE == 0, (a_pm.shape, shards.shape)
    m = m8 // 8
    return pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.uint8),
        grid=(n // COL_TILE,),
        in_specs=[
            pl.BlockSpec((m8, k8), lambda j: (0, 0)),
            pl.BlockSpec((m, m8), lambda j: (0, 0)),
            pl.BlockSpec((k, COL_TILE), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((m, COL_TILE), lambda j: (0, j)),
        interpret=interpret,
    )(a_pm, pack, shards)


coded_matmul_pallas_pm = jax.jit(_coded_matmul_pallas_pm_impl,
                                 static_argnames=("interpret",))
# pipeline variant: the uploaded block is dead after the kernel —
# donating it lets XLA recycle its HBM for in-flight staging buffers
coded_matmul_pallas_pm_donated = jax.jit(
    _coded_matmul_pallas_pm_impl, static_argnames=("interpret",),
    donate_argnums=(2,))


def coded_matmul_pallas(a_bits: jax.Array, shards: jax.Array,
                        interpret: bool = False) -> jax.Array:
    """Drop-in signature match for bits.coded_matmul_bits (a_bits is
    the bit-minor (8m, 8k) matrix); hot paths should precompute the
    plane-major matrix + packing matrix and call the _pm form."""
    a_pm = plane_major_bit_matrix(np.asarray(a_bits, dtype=np.float32))
    pack = packing_matrix(a_pm.shape[0] // 8)
    return coded_matmul_pallas_pm(a_pm, pack, shards,
                                  interpret=interpret)


def _make_pallas_codec_class():
    """Deferred so importing this module never pulls codec_jax/jax
    machinery at module import time (mirrors the lazy backend
    factories in ec/backend.py)."""
    from collections import OrderedDict

    from .codec_jax import JaxCodec

    class PallasCodec(JaxCodec):
        """Codec backend running the fused Pallas kernel
        (-ec.backend=pallas). Reuses JaxCodec's slabbing, committed
        H2D placement and the staged streaming pipeline; only the
        per-coefficient matrices, the column padding (COL_TILE
        multiples, applied host-side before H2D) and the kernel
        dispatch differ."""

        name = "pallas"

        def __init__(self, slab: int = 8 << 20):
            super().__init__(slab=slab)
            self._mats: "OrderedDict[bytes, tuple]" = OrderedDict()

        def _coef_bits(self, coef: np.ndarray):
            key = coef.shape[0].to_bytes(2, "big") + coef.tobytes()
            mats = self._mats.get(key)
            if mats is None:
                from . import gf256

                bits = gf256.expand_to_bits(coef)
                mats = (plane_major_bit_matrix(bits),
                        packing_matrix(coef.shape[0]))
                self._mats[key] = mats
                if len(self._mats) > self.BITMAT_CACHE_MAX:
                    self._mats.popitem(last=False)
            else:
                self._mats.move_to_end(key)
            return mats

        def _pad_width(self, n: int) -> int:
            # the kernel's grid walks COL_TILE lanes per step; padding
            # happens on the host (JaxCodec._split) so the device
            # never relayouts
            return n + (-n) % COL_TILE

        def _plan_for(self, coef, nbytes):
            # the fused kernel is already a bit-plane program executed
            # on-device; the scheduled XOR path never applies here
            return None

        def _run(self, mats, dev: jax.Array, plan=None) -> jax.Array:
            a_pm, pack = mats
            if self._donate is None:
                self._donate = jax.devices()[0].platform != "cpu"
            fn = (coded_matmul_pallas_pm_donated if self._donate
                  else coded_matmul_pallas_pm)
            return fn(a_pm, pack, dev)

    return PallasCodec


def PallasCodec(slab: int = 8 << 20):
    """Factory kept under the class's name for the backend registry."""
    return _make_pallas_codec_class()(slab=slab)
