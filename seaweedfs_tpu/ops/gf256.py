"""GF(2^8) arithmetic for Reed-Solomon coding.

Field: GF(256) with the primitive polynomial x^8+x^4+x^3+x^2+1 (0x11d) and
generator 2 — the same field used by the reference's codec dependency
(klauspost/reedsolomon, see /root/reference/weed/storage/erasure_coding/
ec_encoder.go:202 `reedsolomon.New`), so shard bytes are interoperable.

Two representations are maintained:

1. Byte-domain tables (EXP/LOG/MUL_TABLE) for host-side scalar math and the
   numpy CPU backend.
2. Bit-domain matrices: multiplication by a constant c is linear over
   GF(2)^8, i.e. an 8x8 bit-matrix M_c with
       M_c[s, t] = bit s of (c * 2^t).
   A whole m x k byte matrix then expands to an (8m x 8k) 0/1 matrix, and
   RS encode/reconstruct of k shards becomes ONE dense matmul over GF(2):
       parity_bits = (A_bits @ data_bits) mod 2
   which is exactly the shape of work the TPU MXU is built for (integer
   0/1 matmul accumulates exactly in bf16/f32 for k*8 <= 256 terms... and
   exactly in f32 always). This module builds those matrices; the batched
   device kernels live in codec_jax.py / codec_pallas.py.

Everything here is pure numpy + python ints; no jax imports (host-side).
"""
from __future__ import annotations

import numpy as np

GF_POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1
FIELD = 256
ORDER = 255  # multiplicative group order


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(ORDER):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= GF_POLY
    # duplicate so exp[(la + lb)] works without a mod for la+lb < 510
    for i in range(ORDER, 512):
        exp[i] = exp[i - ORDER]
    return exp, log


EXP, LOG = _build_tables()

# Full 256x256 product table: 64KB, used by the numpy CPU codec backend.
_a = np.arange(256)
_la = LOG[_a][:, None]
_lb = LOG[_a][None, :]
MUL_TABLE = EXP[(_la + _lb) % ORDER].astype(np.uint8)
MUL_TABLE[0, :] = 0
MUL_TABLE[:, 0] = 0
del _a, _la, _lb

# Multiplicative inverse table (INV[0] is undefined; left as 0).
INV = np.zeros(256, dtype=np.uint8)
INV[1:] = EXP[(ORDER - LOG[np.arange(1, 256)]) % ORDER]


def gf_mul(a: int, b: int) -> int:
    return int(MUL_TABLE[a & 0xFF, b & 0xFF])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("GF(256) division by zero")
    if a == 0:
        return 0
    return int(EXP[(LOG[a] - LOG[b]) % ORDER])


def gf_pow(a: int, n: int) -> int:
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(EXP[(int(LOG[a]) * n) % ORDER])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(256) inverse of zero")
    return int(INV[a])


# ---------------------------------------------------------------------------
# Matrix algebra over GF(256) (host side, small matrices: k, m <= ~32)
# ---------------------------------------------------------------------------

def mat_mul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """(r,n) @ (n,c) byte matrices over GF(256)."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    assert a.shape[1] == b.shape[0]
    # products[i,j,t] = a[i,t]*b[t,j]; xor-reduce over t
    prod = MUL_TABLE[a[:, None, :], b.T[None, :, :]]  # (r, c, n)
    return np.bitwise_xor.reduce(prod, axis=2)


def mat_inv(m: np.ndarray) -> np.ndarray:
    """Invert a square byte matrix over GF(256) by Gauss-Jordan.

    Raises ValueError if singular.
    """
    m = np.asarray(m, dtype=np.uint8)
    n = m.shape[0]
    assert m.shape == (n, n)
    work = np.concatenate([m.copy(), np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        # pivot
        if work[col, col] == 0:
            for r in range(col + 1, n):
                if work[r, col] != 0:
                    work[[col, r]] = work[[r, col]]
                    break
            else:
                raise ValueError("singular matrix over GF(256)")
        pivot = int(work[col, col])
        work[col] = MUL_TABLE[INV[pivot], work[col]]
        # eliminate other rows
        for r in range(n):
            if r != col and work[r, col] != 0:
                factor = int(work[r, col])
                work[r] ^= MUL_TABLE[factor, work[col]]
    return work[:, n:].copy()


def mat_identity(n: int) -> np.ndarray:
    return np.eye(n, dtype=np.uint8)


# ---------------------------------------------------------------------------
# Bit-matrix expansion: GF(256) linear maps -> GF(2) matrices
# ---------------------------------------------------------------------------

def _build_bitmats() -> np.ndarray:
    """BITMAT[c] is the 8x8 0/1 matrix of 'multiply by c':

        bits(c*x)[s] = XOR_t BITMAT[c][s,t] * bits(x)[t]

    Column t is the bit-decomposition of c * 2^t.
    """
    out = np.zeros((256, 8, 8), dtype=np.uint8)
    for c in range(256):
        for t in range(8):
            v = MUL_TABLE[c, 1 << t]
            for s in range(8):
                out[c, s, t] = (v >> s) & 1
    return out


BITMAT = _build_bitmats()


def expand_to_bits(m: np.ndarray) -> np.ndarray:
    """Expand an (r, c) byte matrix to the (8r, 8c) GF(2) matrix of the
    same linear map, acting on bit-minor-expanded vectors:

        y_bits[8*i + s] = XOR_{j,t} out[8i+s, 8j+t] * x_bits[8j+t]
    """
    m = np.asarray(m, dtype=np.uint8)
    r, c = m.shape
    blocks = BITMAT[m]                      # (r, c, 8, 8)
    out = blocks.transpose(0, 2, 1, 3).reshape(8 * r, 8 * c)
    return np.ascontiguousarray(out)


def pack_bits(bits: np.ndarray) -> np.ndarray:
    """(8r, n) 0/1 -> (r, n) uint8, bit s of row r taken from row 8r+s."""
    r8, n = bits.shape
    assert r8 % 8 == 0
    b = bits.reshape(r8 // 8, 8, n).astype(np.uint16)
    weights = (1 << np.arange(8, dtype=np.uint16))[None, :, None]
    return (b * weights).sum(axis=1).astype(np.uint8)


def unpack_bits(data: np.ndarray) -> np.ndarray:
    """(r, n) uint8 -> (8r, n) 0/1 uint8 (bit-minor)."""
    r, n = data.shape
    shifts = np.arange(8, dtype=np.uint8)[None, :, None]
    bits = (data[:, None, :] >> shifts) & 1
    return bits.reshape(8 * r, n)
