"""CPU reference codec: GF(256) coded matmul via 64KB product-table gathers.

This is the correctness baseline (and the AVX2-klauspost stand-in for
benchmarks) that the TPU backends must match bit-for-bit. Mirrors what the
reference's CPU codec does per stripe (/root/reference/weed/storage/
erasure_coding/ec_encoder.go:166-196 `encodeDataOneBatch` -> enc.Encode).
"""
from __future__ import annotations

import numpy as np

from . import gf256


def coded_matmul(coef: np.ndarray, shards: np.ndarray) -> np.ndarray:
    """out[i] = XOR_j coef[i,j] * shards[j]   (GF(256), byte-wise).

    coef: (m, k) uint8; shards: (k, n) uint8 -> (m, n) uint8.
    """
    coef = np.asarray(coef, dtype=np.uint8)
    shards = np.asarray(shards, dtype=np.uint8)
    m, k = coef.shape
    assert shards.shape[0] == k, (coef.shape, shards.shape)
    n = shards.shape[1]
    out = np.zeros((m, n), dtype=np.uint8)
    # plain fancy indexing: measured ~2x faster than np.take(out=...)
    # for 256-entry uint8 tables despite the per-term allocation
    for i in range(m):
        acc = out[i]
        for j in range(k):
            c = coef[i, j]
            if c == 0:
                continue
            if c == 1:
                acc ^= shards[j]
            else:
                acc ^= gf256.MUL_TABLE[c][shards[j]]
    return out


class NumpyCodec:
    name = "numpy"

    def coded_matmul(self, coef: np.ndarray, shards: np.ndarray) -> np.ndarray:
        return coded_matmul(coef, shards)
