"""`native` codec backend: the in-tree C++ SIMD kernel via ctypes.

The klauspost-equivalent CPU path (SURVEY.md section 2.1) — split-nibble
PSHUFB GF(256) multiply — wrapped in the CodecBackend protocol so
`-ec.backend=native` selects it through the registry (ec/backend.py).
"""
from __future__ import annotations

import numpy as np

from .. import native


class NativeCodec:
    name = "native"

    def __init__(self):
        native.load()  # build + bind eagerly so failures surface here

    def coded_matmul(self, coef: np.ndarray,
                     shards: np.ndarray) -> np.ndarray:
        return native.coded_matmul(coef, shards)
