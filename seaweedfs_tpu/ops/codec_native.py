"""`native` codec backend: the in-tree C++ SIMD kernel via ctypes.

The klauspost-equivalent CPU path (SURVEY.md section 2.1) — split-nibble
PSHUFB GF(256) multiply — wrapped in the CodecBackend protocol so
`-ec.backend=native` selects it through the registry (ec/backend.py).

Since the bit-matrix scheduling pass (ops/schedule.py) the backend has
a second kernel: the CSE-optimized XOR program run word-wide over
packed bit-planes (`gf256_scheduled_matmul`). Which kernel serves a
given (coefficient matrix, request size) is decided by measurement
(schedule.Chooser): both run once at first sight of a size bucket and
the winner is cached, so the scheduled path is never slower than the
dense one at any probed size. `SEAWEEDFS_TPU_EC_SCHEDULE=on|off` pins
the choice for tests and benches.
"""
from __future__ import annotations

import numpy as np

from .. import native
from . import schedule


class NativeCodec:
    name = "native"

    def __init__(self):
        native.load()  # build + bind eagerly so failures surface here
        self._chooser = schedule.Chooser()
        self._flat: dict[bytes, np.ndarray] = {}

    def _flattened(self, coef: np.ndarray) -> np.ndarray:
        key = schedule.coef_key(coef)
        flat = self._flat.get(key)
        if flat is None:
            flat = schedule.flatten(schedule.plan_for(coef))
            if len(self._flat) >= schedule.PLAN_CACHE_MAX:
                self._flat.clear()
            self._flat[key] = flat
        return flat

    def _scheduled(self, coef: np.ndarray,
                   shards: np.ndarray) -> np.ndarray:
        return native.scheduled_matmul(self._flattened(coef), shards,
                                       coef.shape[0])

    def coded_matmul(self, coef: np.ndarray,
                     shards: np.ndarray) -> np.ndarray:
        coef = np.asarray(coef, dtype=np.uint8)
        shards = np.asarray(shards, dtype=np.uint8)
        if shards.shape[1] and native.has_scheduled():
            # sample columns derive from a BYTE cap, and the verdict is
            # keyed by the sample's own size — the cached decision is
            # only ever one that was actually measured at that size
            cap = max(1, schedule.MEASURE_BYTES_MAX // shards.shape[0])
            sample = shards[:, :cap] if shards.shape[1] > cap \
                else shards
            if self._chooser.use_scheduled(
                    coef, sample.nbytes,
                    lambda: self._scheduled(coef, sample),
                    lambda: native.coded_matmul(coef, sample)):
                return self._scheduled(coef, shards)
        return native.coded_matmul(coef, shards)

    def schedule_snapshot(self) -> dict:
        return self._chooser.snapshot()
