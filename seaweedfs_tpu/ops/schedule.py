"""Bit-matrix scheduling for the GF(256) coded-matmul hot path.

A GF(256) coefficient matrix expands to an (8m x 8k) 0/1 matrix over
GF(2) (gf256.expand_to_bits); computing the coded matmul is then an
XOR program: output bit-plane i is the XOR of the input bit-planes
where the matrix has ones. The naive program costs popcount(B) - 8m
XORs; the classic program-optimization result (arXiv 2108.02692,
Paar-style greedy factoring) is that shared subexpressions cut that
substantially — RS parity matrices are dense and highly redundant.

This module builds the optimized program once per coefficient matrix:

  - `build_program(coef)` -> a hashable `Program` of (dst, a, b) XOR
    ops over a growing variable pool (inputs are vars [0, 8k)), plus
    the output variable per bit-plane row.
  - `apply_numpy(program, bits)` — the oracle executor tests compare
    against (and the reference semantics of the flattened op list).
  - `flatten(program)` — one int32 array the native C kernel consumes
    (gf256_codec.cc `gf256_scheduled_matmul`).
  - `plan_for(coef)` — bounded memo, shared by every backend so the
    CSE pass runs once per matrix per process.
  - `Chooser` — measured per-(matrix, size-bucket) selection between
    the scheduled kernel and the dense one, so the scheduled path is
    never slower than unscheduled at any probed size: both run once at
    first sight of a bucket, the winner is cached.

Everything here is host-side numpy + pure python; the jitted jax
executor lives in codec_jax (it needs jax), the C executor in
native/gf256_codec.cc.
"""
from __future__ import annotations

import os
import threading
import time as _time
from collections import OrderedDict
from dataclasses import dataclass, field

import numpy as np

from . import gf256

# below this many BYTES the dense kernels win on dispatch overhead
# alone; the chooser never even measures the scheduled path there
MIN_SCHED_BYTES = 64 << 10

# measurement sample cap: requests bigger than this are decided from a
# sample of at most this many bytes, and callers key the verdict by the
# SAMPLE's size (the size actually probed) so the never-slower-at-any-
# probed-size guarantee stays honest for large requests
MEASURE_BYTES_MAX = 4 << 20

_SCHED_ENV = "SEAWEEDFS_TPU_EC_SCHEDULE"  # auto (default) | on | off


def mode() -> str:
    v = os.environ.get(_SCHED_ENV, "auto").strip().lower()
    return v if v in ("auto", "on", "off") else "auto"


@dataclass(frozen=True)
class Program:
    """An XOR straight-line program over bit-plane variables.

    Vars [0, n_in) are the input planes (bit s of shard row j is var
    8j+s); op i defines var n_in+i as vars[a] ^ vars[b]. `outputs[r]`
    is the var holding output plane r, or -1 for an all-zero row.
    Hashable (static arg for jitted executors)."""

    n_in: int
    n_out: int
    ops: tuple[tuple[int, int, int], ...]
    outputs: tuple[int, ...]
    naive_xors: int

    @property
    def xors(self) -> int:
        return len(self.ops)

    @property
    def saving(self) -> float:
        """Fraction of naive XORs eliminated by the schedule."""
        if not self.naive_xors:
            return 0.0
        return 1.0 - self.xors / self.naive_xors


def build_program(coef: np.ndarray) -> Program:
    """CSE-schedule the XOR program of a byte coefficient matrix.

    Greedy pair factoring (Paar): while some variable pair co-occurs
    in >= 2 rows, hoist the most frequent pair into a fresh variable;
    then emit per-row XOR chains. Output is bit-identical with the
    dense GF(256) matmul by construction — the pass rewrites the
    program, never the shard byte layout.
    """
    coef = np.asarray(coef, dtype=np.uint8)
    bits = gf256.expand_to_bits(coef)          # (8m, 8k)
    n_out, n_in = bits.shape
    rows: list[set[int]] = [set(np.nonzero(bits[r])[0].tolist())
                            for r in range(n_out)]
    naive = sum(max(0, len(r) - 1) for r in rows)

    # pair -> count over all rows, maintained incrementally
    counts: dict[tuple[int, int], int] = {}

    def add_row_pairs(row: set[int], sign: int) -> None:
        mem = sorted(row)
        for i, a in enumerate(mem):
            for b in mem[i + 1:]:
                key = (a, b)
                c = counts.get(key, 0) + sign
                if c > 0:
                    counts[key] = c
                else:
                    counts.pop(key, None)

    for row in rows:
        add_row_pairs(row, +1)

    ops: list[tuple[int, int, int]] = []
    next_var = n_in
    while counts:
        (a, b), best = max(counts.items(), key=lambda kv: kv[1])
        if best < 2:
            break
        t = next_var
        next_var += 1
        ops.append((t, a, b))
        for row in rows:
            if a in row and b in row:
                add_row_pairs(row, -1)
                row.discard(a)
                row.discard(b)
                row.add(t)
                add_row_pairs(row, +1)

    outputs: list[int] = []
    for row in rows:
        mem = sorted(row)
        if not mem:
            outputs.append(-1)
            continue
        acc = mem[0]
        for v in mem[1:]:
            t = next_var
            next_var += 1
            ops.append((t, acc, v))
            acc = t
        outputs.append(acc)

    return Program(n_in, n_out, tuple(ops), tuple(outputs), naive)


def apply_numpy(program: Program, bits: np.ndarray) -> np.ndarray:
    """Oracle executor: (n_in, n) 0/1 planes -> (n_out, n) 0/1 planes.
    This IS the semantics of the flattened op list the C kernel runs;
    tests diff every other executor against it."""
    n = bits.shape[1]
    vars_: list[np.ndarray] = [bits[i] for i in range(program.n_in)]
    for _, a, b in program.ops:
        vars_.append(vars_[a] ^ vars_[b])
    out = np.zeros((program.n_out, n), dtype=bits.dtype)
    for r, v in enumerate(program.outputs):
        if v >= 0:
            out[r] = vars_[v]
    return out


def apply_bytes_numpy(program: Program, shards: np.ndarray) -> np.ndarray:
    """(k, n) uint8 shards -> (m, n) uint8 via unpack/XOR-program/pack
    — the byte-level oracle (must equal the dense GF(256) matmul)."""
    bits = gf256.unpack_bits(np.asarray(shards, dtype=np.uint8))
    return gf256.pack_bits(apply_numpy(program, bits))


def flatten(program: Program) -> np.ndarray:
    """One contiguous int32 array for the C kernel:
    [n_in, n_out, n_ops, (dst, a, b) * n_ops, outputs * n_out]."""
    head = [program.n_in, program.n_out, len(program.ops)]
    body = [v for op in program.ops for v in op]
    return np.asarray(head + body + list(program.outputs),
                      dtype=np.int32)


# ----------------------------------------------------------------------
# per-process plan memo (the CSE pass is O(ones^2)-ish; run it once
# per coefficient matrix, shared by every backend)
# ----------------------------------------------------------------------

PLAN_CACHE_MAX = 128
_plans: "OrderedDict[bytes, Program]" = OrderedDict()


def coef_key(coef: np.ndarray) -> bytes:
    coef = np.asarray(coef, dtype=np.uint8)
    return coef.shape[0].to_bytes(2, "big") + coef.tobytes()


def plan_for(coef: np.ndarray) -> Program:
    key = coef_key(coef)
    plan = _plans.get(key)
    if plan is None:
        plan = build_program(coef)
        _plans[key] = plan
        while len(_plans) > PLAN_CACHE_MAX:
            _plans.popitem(last=False)
    else:
        _plans.move_to_end(key)
    return plan


def summary_for(coef: np.ndarray) -> dict:
    plan = plan_for(coef)
    return {"naive_xors": plan.naive_xors, "scheduled_xors": plan.xors,
            "saving": round(plan.saving, 3)}


# ----------------------------------------------------------------------
# measured scheduled-vs-dense selection
# ----------------------------------------------------------------------

def _bucket(nbytes: int) -> int:
    return max(0, int(nbytes).bit_length() - 1)


@dataclass
class Chooser:
    """Per-backend winner table: (coef key, log2 size bucket) -> use
    scheduled? `auto` measures both paths once per bucket (after a
    warm call each, so jit/compile is not billed) and caches the
    winner — the guarantee that the scheduled kernel is never slower
    than the dense one at any probed size holds by construction;
    callers pass the nbytes of the sample they actually measure so the
    cached verdict is keyed by a probed size. `on`/`off`
    (SEAWEEDFS_TPU_EC_SCHEDULE) pin the answer for tests and benches.

    `background=True` moves the measurement off the caller's thread:
    the first sight of a (matrix, bucket) kicks a worker thread and
    serves the dense kernel until the verdict lands — device backends
    use this because their warm calls include an XLA compile that
    would otherwise stall the first live read/repair for seconds."""

    max_keys: int = 256
    _won: "OrderedDict[tuple[bytes, int], bool]" = field(
        default_factory=OrderedDict)
    _pending: set = field(default_factory=set)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def use_scheduled(self, coef: np.ndarray, nbytes: int,
                      run_sched, run_dense,
                      background: bool = False) -> bool:
        m = mode()
        if m == "off":
            return False
        if m == "on":
            return True
        if nbytes < MIN_SCHED_BYTES:
            return False
        plan = plan_for(coef)
        if plan.xors >= plan.naive_xors:
            return False
        key = (coef_key(coef), _bucket(nbytes))
        with self._lock:
            hit = self._won.get(key)
            if hit is not None:
                self._won.move_to_end(key)
                return hit
            if key in self._pending:
                return False  # measurement in flight: dense meanwhile
            self._pending.add(key)
        if background:
            # non-daemon ON PURPOSE: a daemon thread killed mid-XLA-
            # compile at interpreter shutdown aborts the process
            # (std::terminate); joining at exit costs at most one
            # compile and only when a measurement is in flight
            threading.Thread(
                target=self._measure, args=(key, run_sched, run_dense),
                name="ec-sched-measure", daemon=False).start()
            return False
        return self._measure(key, run_sched, run_dense)

    def _measure(self, key, run_sched, run_dense) -> bool:
        try:
            run_sched()  # warm: build/compile both paths off the clock
            run_dense()
            t0 = _time.perf_counter()
            run_sched()
            t_s = _time.perf_counter() - t0
            t0 = _time.perf_counter()
            run_dense()
            t_d = _time.perf_counter() - t0
            win = t_s < t_d
        except Exception:
            win = False
        with self._lock:
            self._pending.discard(key)
            self._won[key] = win
            while len(self._won) > self.max_keys:
                self._won.popitem(last=False)
        return win

    def snapshot(self) -> dict:
        with self._lock:
            wins = sum(1 for v in self._won.values() if v)
            return {"buckets": len(self._won), "scheduled_wins": wins,
                    "measuring": len(self._pending)}
