"""TPU codec: GF(256) coded matmul as a bit-plane matmul on the MXU.

The trick (SURVEY.md section 7 "GF(256) as MXU work"): multiplication by a
GF(256) constant is linear over GF(2)^8, so the whole m x k coefficient
matrix expands to an (8m x 8k) 0/1 matrix A_bits (gf256.expand_to_bits) and

    out_bytes = pack( (A_bits @ unpack(shards)) mod 2 )

where unpack turns (k, n) bytes into (8k, n) bit-planes. The matmul runs in
bf16 on the MXU with f32 accumulation — sums of 8k <= 2048 zeros/ones are
exact in f32 — and `mod 2` is a cheap elementwise op XLA fuses into the
epilogue. One compiled kernel serves encode AND any reconstruction: the
coefficient bit-matrix is a runtime argument, only shapes are static.

Equivalent of the reference's hot loops enc.Encode / enc.Reconstruct
(/root/reference/weed/storage/erasure_coding/ec_encoder.go:190,274), but
batched: callers collapse (batch, k, stripe) into (k, batch*stripe) columns
so thousands of stripes ride one dispatch.
"""
from __future__ import annotations

from collections import OrderedDict
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from . import gf256

# Column slab each jitted call processes; callers pad up to a multiple.
# 2 MiB columns x 8k bit-rows in bf16 keeps the working set well inside HBM
# while amortizing dispatch overhead.
DEFAULT_SLAB = 1 << 21


@partial(jax.jit, donate_argnums=())
def _bit_matmul(a_bits: jax.Array, shards: jax.Array) -> jax.Array:
    """a_bits: (8m, 8k) bf16 0/1; shards: (k, n) uint8 -> (m, n) uint8."""
    from .bits import coded_matmul_bits

    return coded_matmul_bits(a_bits, shards)


def bit_matrix(coef: np.ndarray) -> jax.Array:
    """Host byte matrix -> device bf16 bit-matrix (cacheable by caller)."""
    return jnp.asarray(gf256.expand_to_bits(coef), dtype=jnp.bfloat16)


class JaxCodec:
    """Coded-matmul backend running on the default jax device.

    Caches expanded coefficient bit-matrices keyed by the coefficient
    bytes, and pads the column count to `slab` multiples so XLA compiles a
    handful of shapes no matter the file size.
    """

    name = "jax"

    # bound the per-instance coefficient-matrix cache: reconstruction over
    # wide codes can see tens of thousands of distinct recovery matrices
    BITMAT_CACHE_MAX = 256

    def __init__(self, slab: int = DEFAULT_SLAB):
        self.slab = slab
        self._bitmats: "OrderedDict[bytes, jax.Array]" = OrderedDict()

    def _coef_bits(self, coef: np.ndarray) -> jax.Array:
        key = coef.shape[0].to_bytes(2, "big") + coef.tobytes()
        bm = self._bitmats.get(key)
        if bm is None:
            bm = bit_matrix(coef)
            self._bitmats[key] = bm
            if len(self._bitmats) > self.BITMAT_CACHE_MAX:
                self._bitmats.popitem(last=False)
        else:
            self._bitmats.move_to_end(key)
        return bm

    def coded_matmul(self, coef: np.ndarray, shards) -> np.ndarray:
        coef = np.asarray(coef, dtype=np.uint8)
        m, k = coef.shape
        shards = np.asarray(shards, dtype=np.uint8)
        assert shards.ndim == 2 and shards.shape[0] == k
        n = shards.shape[1]
        if n == 0:
            return np.zeros((m, 0), dtype=np.uint8)
        a_bits = self._coef_bits(coef)
        return _collect(self._dispatch(a_bits, shards))

    def _dispatch(self, a_bits, shards: np.ndarray) -> list:
        """Issue the async device calls for one (k, n) column block,
        slab-split and bucket-padded; returns [(device_array, width)]
        without forcing any transfer back."""
        n = shards.shape[1]
        slab = self.slab
        if n <= slab:
            # pad to power-of-two buckets (>=256) so XLA compiles at most
            # log2(slab/256) shapes for sub-slab calls
            padded = 256
            while padded < n:
                padded <<= 1
            padded = min(padded, slab)  # n <= slab, so padded >= n still
            return [(self._run(a_bits, _pad_cols(shards, padded)), n)]
        out = []
        for off in range(0, n, slab):
            chunk = shards[:, off:off + slab]
            w = chunk.shape[1]
            if w < slab:
                chunk = _pad_cols(chunk, slab)
            out.append((self._run(a_bits, chunk), w))
        return out

    def coded_matmul_stream(self, coef: np.ndarray, blocks,
                            depth: int = 2):
        """Streaming pipeline: for each (k, w) uint8 column block from
        the iterable `blocks`, yield the matching (m, w) result, in
        order. Up to `depth` blocks are in flight at once — the
        producer side issues H2D + compute (both asynchronous under
        jax's dispatch model) while a single fetch thread drains D2H —
        so on hardware with independent DMA engines the three stages
        overlap instead of serializing (the reference streams 256KB
        buffers through its CPU codec synchronously,
        ec_encoder.go:198-235; a device codec lives or dies by hiding
        the transfer latency).
        """
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor

        coef = np.asarray(coef, dtype=np.uint8)
        m = coef.shape[0]
        a_bits = self._coef_bits(coef)

        with ThreadPoolExecutor(1) as ex:
            pending: deque = deque()
            for block in blocks:
                block = np.asarray(block, dtype=np.uint8)
                if block.shape[1] == 0:
                    # empty result still rides the queue: yielding it
                    # directly would reorder it ahead of pending blocks
                    pending.append(ex.submit(
                        lambda: np.zeros((m, 0), dtype=np.uint8)))
                else:
                    pending.append(
                        ex.submit(_collect, self._dispatch(a_bits, block)))
                while len(pending) > depth:
                    yield pending.popleft().result()
            while pending:
                yield pending.popleft().result()

    def _run(self, a_bits: jax.Array, shards: np.ndarray) -> jax.Array:
        return _bit_matmul(a_bits, jnp.asarray(shards))


def _collect(devs: list) -> np.ndarray:
    """Force D2H on a _dispatch result and reassemble the (m, n) block
    (shared by the sync path and the streaming fetch thread)."""
    if len(devs) == 1:
        dev, w = devs[0]
        return np.asarray(dev)[:, :w]
    return np.concatenate(
        [np.asarray(dev)[:, :w] for dev, w in devs], axis=1)


def _pad_cols(arr: np.ndarray, n: int) -> np.ndarray:
    if arr.shape[1] == n:
        return arr
    out = np.zeros((arr.shape[0], n), dtype=arr.dtype)
    out[:, : arr.shape[1]] = arr
    return out
