"""TPU codec: GF(256) coded matmul as a bit-plane matmul on the MXU.

The trick (SURVEY.md section 7 "GF(256) as MXU work"): multiplication by a
GF(256) constant is linear over GF(2)^8, so the whole m x k coefficient
matrix expands to an (8m x 8k) 0/1 matrix A_bits (gf256.expand_to_bits) and

    out_bytes = pack( (A_bits @ unpack(shards)) mod 2 )

where unpack turns (k, n) bytes into (8k, n) bit-planes. The matmul runs in
bf16 on the MXU with f32 accumulation — sums of 8k <= 2048 zeros/ones are
exact in f32 — and `mod 2` is a cheap elementwise op XLA fuses into the
epilogue. One compiled kernel serves encode AND any reconstruction: the
coefficient bit-matrix is a runtime argument, only shapes are static.

Equivalent of the reference's hot loops enc.Encode / enc.Reconstruct
(/root/reference/weed/storage/erasure_coding/ec_encoder.go:190,274), but
batched: callers collapse (batch, k, stripe) into (k, batch*stripe) columns
so thousands of stripes ride one dispatch.
"""
from __future__ import annotations

from collections import OrderedDict
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from . import gf256

# Column slab each jitted call processes; callers pad up to a multiple.
# 2 MiB columns x 8k bit-rows in bf16 keeps the working set well inside HBM
# while amortizing dispatch overhead.
DEFAULT_SLAB = 1 << 21


@partial(jax.jit, donate_argnums=())
def _bit_matmul(a_bits: jax.Array, shards: jax.Array) -> jax.Array:
    """a_bits: (8m, 8k) bf16 0/1; shards: (k, n) uint8 -> (m, n) uint8."""
    from .bits import coded_matmul_bits

    return coded_matmul_bits(a_bits, shards)


def bit_matrix(coef: np.ndarray) -> jax.Array:
    """Host byte matrix -> device bf16 bit-matrix (cacheable by caller)."""
    return jnp.asarray(gf256.expand_to_bits(coef), dtype=jnp.bfloat16)


class JaxCodec:
    """Coded-matmul backend running on the default jax device.

    Caches expanded coefficient bit-matrices keyed by the coefficient
    bytes, and pads the column count to `slab` multiples so XLA compiles a
    handful of shapes no matter the file size.
    """

    name = "jax"

    # bound the per-instance coefficient-matrix cache: reconstruction over
    # wide codes can see tens of thousands of distinct recovery matrices
    BITMAT_CACHE_MAX = 256

    def __init__(self, slab: int = DEFAULT_SLAB):
        self.slab = slab
        self._bitmats: "OrderedDict[bytes, jax.Array]" = OrderedDict()

    def _coef_bits(self, coef: np.ndarray) -> jax.Array:
        key = coef.shape[0].to_bytes(2, "big") + coef.tobytes()
        bm = self._bitmats.get(key)
        if bm is None:
            bm = bit_matrix(coef)
            self._bitmats[key] = bm
            if len(self._bitmats) > self.BITMAT_CACHE_MAX:
                self._bitmats.popitem(last=False)
        else:
            self._bitmats.move_to_end(key)
        return bm

    def coded_matmul(self, coef: np.ndarray, shards) -> np.ndarray:
        coef = np.asarray(coef, dtype=np.uint8)
        m, k = coef.shape
        shards = np.asarray(shards, dtype=np.uint8)
        assert shards.ndim == 2 and shards.shape[0] == k
        n = shards.shape[1]
        if n == 0:
            return np.zeros((m, 0), dtype=np.uint8)
        a_bits = self._coef_bits(coef)
        slab = self.slab
        if n <= slab:
            # pad to power-of-two buckets (>=256) so XLA compiles at most
            # log2(slab/256) shapes for sub-slab calls
            padded = 256
            while padded < n:
                padded <<= 1
            padded = min(padded, slab)  # n <= slab, so padded >= n still
            out = self._run(a_bits, _pad_cols(shards, padded))
            return np.asarray(out)[:, :n]
        # dispatch all slabs asynchronously, then sync once at the end so
        # device compute overlaps host-side slicing/transfer
        pending: list[tuple[jax.Array, int]] = []
        for off in range(0, n, slab):
            chunk = shards[:, off:off + slab]
            w = chunk.shape[1]
            if w < slab:
                chunk = _pad_cols(chunk, slab)
            pending.append((self._run(a_bits, chunk), w))
        return np.concatenate(
            [np.asarray(dev)[:, :w] for dev, w in pending], axis=1)

    def _run(self, a_bits: jax.Array, shards: np.ndarray) -> jax.Array:
        return _bit_matmul(a_bits, jnp.asarray(shards))


def _pad_cols(arr: np.ndarray, n: int) -> np.ndarray:
    if arr.shape[1] == n:
        return arr
    out = np.zeros((arr.shape[0], n), dtype=arr.dtype)
    out[:, : arr.shape[1]] = arr
    return out
