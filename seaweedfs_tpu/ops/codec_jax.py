"""TPU codec: GF(256) coded matmul as a bit-plane matmul on the MXU.

The trick (SURVEY.md section 7 "GF(256) as MXU work"): multiplication by a
GF(256) constant is linear over GF(2)^8, so the whole m x k coefficient
matrix expands to an (8m x 8k) 0/1 matrix A_bits (gf256.expand_to_bits) and

    out_bytes = pack( (A_bits @ unpack(shards)) mod 2 )

where unpack turns (k, n) bytes into (8k, n) bit-planes. The matmul runs in
bf16 on the MXU with f32 accumulation — sums of 8k <= 2048 zeros/ones are
exact in f32 — and `mod 2` is a cheap elementwise op XLA fuses into the
epilogue. One compiled kernel serves encode AND any reconstruction: the
coefficient bit-matrix is a runtime argument, only shapes are static.

Equivalent of the reference's hot loops enc.Encode / enc.Reconstruct
(/root/reference/weed/storage/erasure_coding/ec_encoder.go:190,274), but
batched: callers collapse (batch, k, stripe) into (k, batch*stripe) columns
so thousands of stripes ride one dispatch.

The streaming entry point (coded_matmul_stream) is a depth-N staged
pipeline: a dedicated upload thread commits block k+1 to the device
(jax.device_put with an explicit SingleDeviceSharding, so placement is
decided once, not re-negotiated per call) while the device runs block
k's kernel and a dedicated drain thread reads block k-1 back. Input
device buffers are donated to the kernel on real accelerators so XLA
can reuse them for the bit-plane intermediate, and readback goes
through dlpack when the consumer and producer share an address space
(CPU devices: zero-copy). Every stage is timed into
ec_codec_stage_seconds{stage,backend} — pread (waiting on the block
source), h2d, kernel, d2h, and relay (finished results waiting for the
consumer) — which is what lets bench/VERDICT attribute the
encode-vs-ceiling gap instead of guessing.
"""
from __future__ import annotations

import time as _time
from collections import OrderedDict
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from . import gf256, schedule
from ..utils import metrics

# Column slab each jitted call processes; callers pad up to a multiple.
# 2 MiB columns x 8k bit-rows in bf16 keeps the working set well inside HBM
# while amortizing dispatch overhead.
DEFAULT_SLAB = 1 << 21


def _bit_matmul_body(a_bits: jax.Array, shards: jax.Array) -> jax.Array:
    from .bits import coded_matmul_bits

    return coded_matmul_bits(a_bits, shards)


# a_bits: (8m, 8k) bf16 0/1; shards: (k, n) uint8 -> (m, n) uint8.
_bit_matmul = jax.jit(_bit_matmul_body)
# pipeline variant: the device input block is dead after the kernel, so
# donating it lets XLA reuse the buffer for the (8k, n) bit-plane
# intermediate instead of allocating fresh HBM per in-flight block
_bit_matmul_donated = jax.jit(_bit_matmul_body, donate_argnums=(1,))


def _xor_matmul_body(program, shards: jax.Array) -> jax.Array:
    """The scheduled alternative to the MXU matmul: run the
    CSE-optimized XOR program (ops/schedule.Program, static) over
    uint8 bit-planes. Same byte semantics as coded_matmul_bits — the
    schedule rewrites the program, not the layout — so either kernel
    can serve any dispatch; which one runs is measured, not assumed."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (shards[:, None, :] >> shifts[None, :, None]) & 1
    bits = bits.reshape(shards.shape[0] * 8, shards.shape[1])
    pool = [bits[i] for i in range(program.n_in)]
    for _, a, b in program.ops:
        pool.append(pool[a] ^ pool[b])
    zero = jnp.zeros_like(bits[0])
    rows = jnp.stack([pool[v] if v >= 0 else zero
                      for v in program.outputs])
    from .bits import pack_bits_uint8

    return pack_bits_uint8(rows)


# program is hashable (frozen dataclass of tuples) -> valid static arg
_xor_matmul = jax.jit(_xor_matmul_body, static_argnums=(0,))


def observe_stage(backend: str, stage: str, seconds: float) -> None:
    """Per-stage feed timing (pread/h2d/kernel/d2h/relay) — the
    attribution VERDICT round 5 asked for. Lives next to
    ec_codec_seconds; one extra label dimension, one histogram per
    (stage, backend)."""
    metrics.histogram_observe("ec_codec_stage_seconds", seconds,
                              {"stage": stage, "backend": backend})


def bit_matrix(coef: np.ndarray) -> jax.Array:
    """Host byte matrix -> device bf16 bit-matrix (cacheable by caller)."""
    return jnp.asarray(gf256.expand_to_bits(coef), dtype=jnp.bfloat16)


class JaxCodec:
    """Coded-matmul backend running on the default jax device.

    Caches expanded coefficient bit-matrices keyed by the coefficient
    bytes, and pads the column count to `slab` multiples so XLA compiles a
    handful of shapes no matter the file size.
    """

    name = "jax"

    # bound the per-instance coefficient-matrix cache: reconstruction over
    # wide codes can see tens of thousands of distinct recovery matrices
    BITMAT_CACHE_MAX = 256

    def __init__(self, slab: int = DEFAULT_SLAB):
        self.slab = slab
        self._bitmats: "OrderedDict[bytes, jax.Array]" = OrderedDict()
        self._sharding = None
        self._donate: bool | None = None
        self._chooser = schedule.Chooser()

    def _coef_bits(self, coef: np.ndarray) -> jax.Array:
        key = coef.shape[0].to_bytes(2, "big") + coef.tobytes()
        bm = self._bitmats.get(key)
        if bm is None:
            bm = bit_matrix(coef)
            self._bitmats[key] = bm
            if len(self._bitmats) > self.BITMAT_CACHE_MAX:
                self._bitmats.popitem(last=False)
        else:
            self._bitmats.move_to_end(key)
        return bm

    # ------------------------------------------------------------------
    # placement / transfer / dispatch primitives (shared with PallasCodec)
    # ------------------------------------------------------------------
    def _placement(self):
        """Committed single-device placement: device_put against an
        explicit sharding starts the copy immediately and pins the
        array, so back-to-back uploads from the feed thread queue on
        the DMA engine instead of waiting for lazy placement."""
        if self._sharding is None:
            from jax.sharding import SingleDeviceSharding

            self._sharding = SingleDeviceSharding(jax.devices()[0])
        return self._sharding

    def _h2d(self, chunk: np.ndarray) -> jax.Array:
        return jax.device_put(chunk, self._placement())

    def _pad_width(self, n: int) -> int:
        """Pad sub-slab column counts to power-of-two buckets (>=256) so
        XLA compiles at most log2(slab/256) shapes for sub-slab calls."""
        padded = 256
        while padded < n:
            padded <<= 1
        return min(padded, max(self.slab, n))

    def _split(self, shards: np.ndarray) -> list[tuple[np.ndarray, int]]:
        """Host-side slab split + padding: [(padded_chunk, true_width)].
        Padding happens before H2D so the device never sees a shape it
        has to relayout."""
        n = shards.shape[1]
        slab = self.slab
        if n <= slab:
            return [(_pad_cols(shards, self._pad_width(n)), n)]
        out = []
        for off in range(0, n, slab):
            chunk = shards[:, off:off + slab]
            w = chunk.shape[1]
            out.append((_pad_cols(chunk, self._pad_width(w)), w))
        return out

    def _run(self, mats, dev: jax.Array, plan=None) -> jax.Array:
        """Dispatch the kernel on an already-on-device padded block:
        the scheduled XOR program when the chooser picked it for this
        (matrix, size), else the dense MXU bit-matmul."""
        if plan is not None:
            return _xor_matmul(plan, dev)
        if self._donate is None:
            # donation on the CPU backend logs an unusable-buffer
            # warning per call; only enable where it buys HBM reuse
            self._donate = jax.devices()[0].platform != "cpu"
        fn = _bit_matmul_donated if self._donate else _bit_matmul
        return fn(mats, dev)

    def _plan_for(self, coef: np.ndarray, nbytes: int):
        """The scheduled program when measurement says it beats the
        dense kernel at this (matrix, size bucket); None otherwise.
        Both candidates are timed once per bucket on a slab-width
        sample (after a warm/compile call each), and the verdict is
        keyed by the SAMPLE's byte size — never-slower at the probed
        size by construction, pinnable via SEAWEEDFS_TPU_EC_SCHEDULE.
        The measurement runs on a background thread (the warm calls
        include an XLA compile of the ~10^3-op unrolled XOR program,
        multi-second cold): first sight of a (matrix, bucket) serves
        the dense kernel immediately and upgrades once the verdict
        lands, so a live read/repair never pays the compile spike."""
        k = coef.shape[1]
        w = self._pad_width(
            min(max(1, nbytes // max(1, k)), self.slab))
        sample_bytes = min(nbytes, k * w)
        sample = None
        mats = None
        plan = None

        def prep():
            nonlocal sample, mats, plan
            if sample is None:
                rng = np.random.default_rng(0)
                chunk = rng.integers(0, 256, (k, w), dtype=np.uint8)
                sample = self._h2d(chunk)
                mats = self._coef_bits(coef)
                plan = schedule.plan_for(coef)

        def run_sched():
            prep()
            _xor_matmul(plan, sample).block_until_ready()

        def run_dense():
            prep()
            _bit_matmul(mats, sample).block_until_ready()

        if self._chooser.use_scheduled(coef, sample_bytes, run_sched,
                                       run_dense, background=True):
            return schedule.plan_for(coef)
        return None

    def _dispatch(self, mats, shards: np.ndarray, plan=None) -> list:
        """Issue the async device calls for one (k, n) column block,
        slab-split and bucket-padded; returns [(device_array, width)]
        without forcing any transfer back."""
        return [(self._run(mats, self._h2d(chunk), plan), w)
                for chunk, w in self._split(shards)]

    def coded_matmul(self, coef: np.ndarray, shards) -> np.ndarray:
        coef = np.asarray(coef, dtype=np.uint8)
        m, k = coef.shape
        shards = np.asarray(shards, dtype=np.uint8)
        assert shards.ndim == 2 and shards.shape[0] == k
        n = shards.shape[1]
        if n == 0:
            return np.zeros((m, 0), dtype=np.uint8)
        plan = self._plan_for(coef, shards.nbytes)
        mats = self._coef_bits(coef)
        return _collect(self._dispatch(mats, shards, plan))

    def coded_matmul_stream(self, coef: np.ndarray, blocks,
                            depth: int = 2):
        """Streaming pipeline: for each (k, w) uint8 column block from
        the iterable `blocks`, yield the matching (m, w) result, in
        order, with up to `depth` blocks in flight.

        Three stages on three threads so they genuinely overlap (the
        reference streams 256KB buffers through its CPU codec
        synchronously, ec_encoder.go:198-235; a device codec lives or
        dies by hiding transfer latency):

          caller thread   pread   next(blocks) + host pad/split
          upload thread   h2d     committed device_put, blocks until
                                  the copy lands, then issues the
                                  kernel (async under jax dispatch)
          drain thread    kernel  block_until_ready on the result
                          d2h     dlpack/np.asarray readback

        While the drain thread reads block k-1 back, the device runs
        block k's kernel and the upload thread pushes block k+1 — the
        double-buffered schedule at depth=2, deeper when asked. Each
        stage records ec_codec_stage_seconds{stage}; `relay` is the
        time a finished block waited for the consumer (writer
        backpressure + queue residence), so pread+h2d+kernel+d2h+relay
        accounts for the whole e2e gap versus the link ceiling.
        """
        from collections import deque
        from concurrent.futures import Future, ThreadPoolExecutor

        coef = np.asarray(coef, dtype=np.uint8)
        m = coef.shape[0]
        mats = self._coef_bits(coef)
        depth = max(1, int(depth))
        backend = self.name
        # streams are bulk: decide scheduled-vs-dense once at slab size
        plan = self._plan_for(coef, coef.shape[1] * self.slab)

        def upload(block: np.ndarray):
            t0 = _time.perf_counter()
            chunks = self._split(block)
            devs = [(self._h2d(chunk), w) for chunk, w in chunks]
            for d, _ in devs:
                # wait for the copies, not the compute: the h2d stage
                # time must be the transfer alone, and issuing the next
                # upload before the kernel keeps the DMA engine busy
                d.block_until_ready()
            t1 = _time.perf_counter()
            outs = [(self._run(mats, d, plan), w) for d, w in devs]
            observe_stage(backend, "h2d", t1 - t0)
            return outs

        def drain(up_fut):
            outs = up_fut.result()
            t0 = _time.perf_counter()
            for d, _ in outs:
                d.block_until_ready()
            t1 = _time.perf_counter()
            arr = _collect(outs)
            t2 = _time.perf_counter()
            observe_stage(backend, "kernel", t1 - t0)
            observe_stage(backend, "d2h", t2 - t1)
            return arr, t2

        up_ex = ThreadPoolExecutor(1, thread_name_prefix="ec-h2d")
        down_ex = ThreadPoolExecutor(1, thread_name_prefix="ec-d2h")

        def finish(fut) -> np.ndarray:
            arr, t_done = fut.result()
            relay = _time.perf_counter() - t_done
            if relay > 0:
                observe_stage(backend, "relay", relay)
            return arr

        try:
            pending: deque = deque()
            it = iter(blocks)
            while True:
                t0 = _time.perf_counter()
                try:
                    block = next(it)
                except StopIteration:
                    break
                observe_stage(backend, "pread",
                              _time.perf_counter() - t0)
                block = np.asarray(block, dtype=np.uint8)
                if block.shape[1] == 0:
                    # empty result still rides the queue: yielding it
                    # directly would reorder it ahead of pending blocks
                    f: Future = Future()
                    f.set_result((np.zeros((m, 0), dtype=np.uint8),
                                  _time.perf_counter()))
                    pending.append(f)
                else:
                    up = up_ex.submit(upload, block)
                    pending.append(down_ex.submit(drain, up))
                while len(pending) >= depth:
                    yield finish(pending.popleft())
            while pending:
                yield finish(pending.popleft())
        finally:
            # bounded: at most `depth` blocks in flight, and upload
            # tasks cannot deadlock on drain tasks, so waiting here
            # can't hang; cancel_futures covers generator early-close
            up_ex.shutdown(wait=True, cancel_futures=True)
            down_ex.shutdown(wait=True, cancel_futures=True)


def _readback(dev: jax.Array) -> np.ndarray:
    """D2H for one device result. dlpack first: on CPU devices (and
    any platform sharing the host address space) it aliases the device
    buffer instead of copying — the consumer only reads, so the
    read-only view is fine. Accelerators fall back to np.asarray."""
    try:
        return np.from_dlpack(dev)
    except Exception:
        return np.asarray(dev)


def _collect(devs: list) -> np.ndarray:
    """Force D2H on a _dispatch result and reassemble the (m, n) block
    (shared by the sync path and the streaming drain thread)."""
    if len(devs) == 1:
        dev, w = devs[0]
        out = _readback(dev)
        return out[:, :w] if out.shape[1] != w else out
    return np.concatenate(
        [_readback(dev)[:, :w] for dev, w in devs], axis=1)


def _pad_cols(arr: np.ndarray, n: int) -> np.ndarray:
    if arr.shape[1] == n:
        return arr
    out = np.zeros((arr.shape[0], n), dtype=arr.dtype)
    out[:, : arr.shape[1]] = arr
    return out
