"""Flagship pipeline: batched erasure-coding encode + scrub as one
jittable, mesh-shardable step.

This is the framework's "model": the computation the TPU sidecar runs in
steady state (BASELINE.json north_star) — thousands of stripes per
dispatch, RS(10,4) parity generation fused with the parity-consistency
scrub, sharded over a (vol, col) device mesh with psum aggregation.

The step takes a (batch, k, cols) uint8 stripe tensor and the parity
bit-matrix, and returns the (batch, m, cols) parity plus a global scrub
scalar (count of mismatched bytes vs a provided expected-parity tensor;
zero when clean). Encode-only callers pass expected=None logic via the
`encode_step` wrapper.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import gf256, rs_matrix


def parity_bit_matrix(k: int = 10, m: int = 4) -> np.ndarray:
    """Host-side (8m, 8k) 0/1 matrix for the systematic parity rows."""
    return gf256.expand_to_bits(rs_matrix.parity_rows(k, m))


def encode_batch(a_bits: jax.Array, stripes: jax.Array) -> jax.Array:
    """(batch, k, n) uint8 -> (batch, m, n) uint8 parity. Pure function,
    jit/shard_map-safe; batch and n dims are embarrassingly parallel."""
    from ..ops.bits import pack_bits_uint8, unpack_bits_bf16

    bits = unpack_bits_bf16(stripes)                      # (B, 8k, n)
    acc = jnp.einsum("st,btn->bsn", a_bits, bits,
                     preferred_element_type=jnp.float32)
    return pack_bits_uint8(acc.astype(jnp.int32) & 1)


def encode_scrub_step(a_bits: jax.Array, stripes: jax.Array,
                      expected_parity: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Full step: encode parity AND count bytes differing from
    expected_parity (the scrub check). Returns (parity, mismatches)."""
    parity = encode_batch(a_bits, stripes)
    mism = jnp.sum((parity != expected_parity).astype(jnp.int64))
    return parity, mism


def jitted_encode(k: int = 10, m: int = 4):
    """-> (fn, a_bits) with fn(a_bits, stripes) jitted."""
    a_bits = jnp.asarray(parity_bit_matrix(k, m), dtype=jnp.bfloat16)
    return jax.jit(encode_batch), a_bits


def sharded_encode_scrub(mesh, k: int = 10, m: int = 4):
    """The multi-chip training-step analogue: jit encode+scrub over a
    (vol, col) mesh. Stripes shard (batch->vol, cols->col); the scrub
    count all-reduces via the sharded sum (XLA inserts the psum).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import COL_AXIS, VOL_AXIS

    a_bits = jnp.asarray(parity_bit_matrix(k, m), dtype=jnp.bfloat16)
    data_sh = NamedSharding(mesh, P(VOL_AXIS, None, COL_AXIS))
    repl = NamedSharding(mesh, P())

    step = jax.jit(
        encode_scrub_step,
        in_shardings=(repl, data_sh, data_sh),
        out_shardings=(data_sh, repl),
    )
    return step, a_bits, data_sh


# ---------------------------------------------------------------------
# Host-feed pipeline (BASELINE configs #3 and #5)
#
# The jitted step above is device-side only; at volume scale the feed
# is the bottleneck. These entry points run the same depth-N staged
# pipeline as ops.codec_jax.JaxCodec.coded_matmul_stream — block j+1's
# H2D overlaps block j's kernel and block j-1's D2H — with the same
# per-stage ec_codec_stage_seconds observations, so Grafana attributes
# batched-encode and scrub time to pread/h2d/kernel/d2h/relay exactly
# like the codec path.
# ---------------------------------------------------------------------


def _staged_feed(blocks, upload, drain, depth: int, backend: str):
    """Shared pipeline skeleton: pread timing around the caller's
    generator, bounded deque of `depth` in-flight blocks, relay = time
    a finished result waited for the consumer. Yields drain results in
    input order."""
    import time
    from collections import deque
    from concurrent.futures import ThreadPoolExecutor

    from ..ops.codec_jax import observe_stage

    up_ex = ThreadPoolExecutor(max_workers=1,
                               thread_name_prefix="ecfeed-h2d")
    down_ex = ThreadPoolExecutor(max_workers=1,
                                 thread_name_prefix="ecfeed-d2h")
    pending: deque = deque()

    def finish(fut):
        host, t_done = fut.result()
        observe_stage(backend, "relay", time.perf_counter() - t_done)
        return host

    it = iter(blocks)
    try:
        while True:
            t0 = time.perf_counter()
            try:
                block = next(it)
            except StopIteration:
                break
            observe_stage(backend, "pread", time.perf_counter() - t0)
            pending.append(down_ex.submit(drain, up_ex.submit(upload,
                                                              block)))
            while len(pending) >= max(1, depth):
                yield finish(pending.popleft())
        while pending:
            yield finish(pending.popleft())
    finally:
        up_ex.shutdown(wait=True, cancel_futures=True)
        down_ex.shutdown(wait=True, cancel_futures=True)


def pipelined_encode_stream(stripe_blocks, k: int = 10, m: int = 4,
                            depth: int = 2, mesh=None):
    """Batched-encode feed (config #3: 64x1GB volumes through the
    sidecar). `stripe_blocks` yields (B, k, n) uint8 host arrays;
    yields (B, m, n) np.uint8 parity blocks in order, bit-identical to
    encode_batch on the same input.

    With `mesh` (a parallel.mesh (vol, col) mesh) each block is
    zero-padded to the mesh grain (pad_to_mesh), scattered with one
    sharded device_put (batch over vol, columns over col) and the
    jitted step runs on every device; outputs are trimmed back to the
    caller's shape, so uneven volume tails ride the mesh unchanged."""
    import time

    from jax.sharding import SingleDeviceSharding

    from ..ops.codec_jax import _readback, observe_stage

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.mesh import COL_AXIS, VOL_AXIS, pad_to_mesh

        a_bits = jnp.asarray(parity_bit_matrix(k, m), dtype=jnp.bfloat16)
        data_sh = NamedSharding(mesh, P(VOL_AXIS, None, COL_AXIS))
        repl = NamedSharding(mesh, P())
        fn = jax.jit(encode_batch, in_shardings=(repl, data_sh),
                     out_shardings=data_sh)
        a_bits = jax.device_put(a_bits, repl)
        sharding = data_sh
        backend = "ec_pipeline_mesh"
    else:
        fn, a_bits = jitted_encode(k, m)
        sharding = SingleDeviceSharding(jax.devices()[0])
        backend = "ec_pipeline"

    def upload(block):
        t0 = time.perf_counter()
        block = np.ascontiguousarray(block)
        orig = None
        if mesh is not None:
            block, orig = pad_to_mesh(block, mesh)
        dev = jax.device_put(block, sharding)
        jax.block_until_ready(dev)
        observe_stage(backend, "h2d", time.perf_counter() - t0)
        return fn(a_bits, dev), orig

    def drain(up_fut):
        out, orig = up_fut.result()
        t0 = time.perf_counter()
        jax.block_until_ready(out)
        t1 = time.perf_counter()
        observe_stage(backend, "kernel", t1 - t0)
        host = _readback(out)
        if orig is not None and (host.shape[0], host.shape[2]) != orig:
            host = np.ascontiguousarray(host[:orig[0], :, :orig[1]])
        t2 = time.perf_counter()
        observe_stage(backend, "d2h", t2 - t1)
        return host, t2

    yield from _staged_feed(stripe_blocks, upload, drain, depth,
                            backend)


def pipelined_scrub(pair_blocks, k: int = 10, m: int = 4,
                    depth: int = 2, mesh=None) -> tuple[int, int]:
    """Cluster-scrub feed (config #5: RS parity verify over a volume
    fleet). `pair_blocks` yields (stripes, expected_parity) uint8 host
    pairs; returns (total_mismatched_bytes, n_blocks). Only the int64
    scrub scalar crosses back over the link per block, so the feed
    stays H2D/kernel bound — the honest shape for a read-mostly scrub.

    With `mesh`, each pair is zero-padded to the mesh grain and both
    tensors scatter over (vol, col); padding stripes encode to zero
    parity and the padded expected parity is also zero, so the psum'd
    mismatch count is untouched — `volume.scrub -all` saturates every
    local device with no caller-visible shape constraints."""
    import time

    from jax.sharding import SingleDeviceSharding

    from ..ops.codec_jax import observe_stage

    if mesh is not None:
        from ..parallel.mesh import pad_to_mesh

        step, a_bits, data_sh = sharded_encode_scrub(mesh, k, m)
        sharding = data_sh
        backend = "ec_scrub_mesh"
    else:
        step = jax.jit(encode_scrub_step)
        a_bits = jnp.asarray(parity_bit_matrix(k, m),
                             dtype=jnp.bfloat16)
        sharding = SingleDeviceSharding(jax.devices()[0])
        backend = "ec_scrub"

    def upload(pair):
        stripes, expected = pair
        t0 = time.perf_counter()
        stripes = np.ascontiguousarray(stripes)
        expected = np.ascontiguousarray(expected)
        if mesh is not None:
            stripes, _ = pad_to_mesh(stripes, mesh)
            expected, _ = pad_to_mesh(expected, mesh)
        dev_s = jax.device_put(stripes, sharding)
        dev_e = jax.device_put(expected, sharding)
        jax.block_until_ready((dev_s, dev_e))
        observe_stage(backend, "h2d", time.perf_counter() - t0)
        return step(a_bits, dev_s, dev_e)

    def drain(up_fut):
        _parity, mism = up_fut.result()
        t0 = time.perf_counter()
        jax.block_until_ready(mism)
        t1 = time.perf_counter()
        observe_stage(backend, "kernel", t1 - t0)
        val = int(mism)
        t2 = time.perf_counter()
        observe_stage(backend, "d2h", t2 - t1)
        return val, t2

    total = 0
    n = 0
    for val in _staged_feed(pair_blocks, upload, drain, depth, backend):
        total += val
        n += 1
    return total, n


def rebuild_mesh(n_devices: int | None = None):
    """1-D mesh over the `shard` axis: device i holds shard-rows i*k/d
    .. (i+1)*k/d — the layout that mirrors storage reality, where each
    shard lives on a different server/chip."""
    from jax.sharding import Mesh

    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.array(devs[:n]), ("shard",))


def sharded_rebuild(mesh, k: int = 10, m: int = 4,
                    present: list[int] | None = None,
                    missing: list[int] | None = None):
    """Distributed reconstruction with shard rows spread across the
    mesh — the framework's ring/all-to-all sequence-parallel analogue.

    Each device holds a row block of the (8k, n) bit expansion (its
    local shards); it computes the partial parity counts its rows
    contribute, and a reduce-scatter ring (lax.psum_scatter over the
    `shard` axis — XLA lowers it onto ICI as a ring) leaves every
    device with the finished column slice of the rebuilt shards. The
    mod-2 fold happens after the ring: integer partial counts sum
    exactly in int32, and total_count & 1 == XOR.

    Returns (step, a_pm) where step(a_pm, shards_rowsharded) ->
    rebuilt bytes, column-sharded. shards input: (k, n) uint8 with k
    divisible by the mesh size; n divisible by 8*mesh size.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

    if present is None or missing is None:
        missing = list(range(m))
        present = list(range(m, k + m))[:k]
    coef, _ = rs_matrix.recovery_rows(k, len(missing), present, missing)
    a_bits = gf256.expand_to_bits(coef)  # (8m', 8k)
    d = mesh.devices.size
    # granularity is BIT rows: the (8k, n) expansion shards over
    # devices, so 8k (80 for RS(10,4)) must divide — device
    # boundaries may cut across a byte's bit-planes, which is fine
    # because the dot contracts all of them
    assert (8 * k) % d == 0, f"{8 * k} bit rows over {d} devices"

    def step(a, local_bits_rows):
        # a: full (8m', 8k) replicated; local rows: (8k/d, n)
        i = jax.lax.axis_index("shard")
        rows_per = a.shape[1] // d
        a_block = jax.lax.dynamic_slice(
            a, (0, i * rows_per), (a.shape[0], rows_per))
        partial = jax.lax.dot_general(
            a_block, local_bits_rows, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(jnp.int32)
        # reduce-scatter ring: sum partials, scatter columns
        total = jax.lax.psum_scatter(partial, "shard",
                                     scatter_dimension=1, tiled=True)
        return total & 1

    smapped = shard_map(
        step, mesh=mesh,
        in_specs=(P(), P("shard", None)),
        out_specs=P(None, "shard"))

    @jax.jit
    def rebuild(a, shards_u8):
        from ..ops.bits import pack_bits_uint8, unpack_bits_bf16

        bits = unpack_bits_bf16(shards_u8)       # (8k, n)
        out_bits = smapped(a, bits)              # (8m', n) col-sharded
        return pack_bits_uint8(out_bits)

    a_dev = jnp.asarray(a_bits, dtype=jnp.bfloat16)
    return rebuild, a_dev, coef
