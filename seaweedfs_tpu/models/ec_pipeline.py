"""Flagship pipeline: batched erasure-coding encode + scrub as one
jittable, mesh-shardable step.

This is the framework's "model": the computation the TPU sidecar runs in
steady state (BASELINE.json north_star) — thousands of stripes per
dispatch, RS(10,4) parity generation fused with the parity-consistency
scrub, sharded over a (vol, col) device mesh with psum aggregation.

The step takes a (batch, k, cols) uint8 stripe tensor and the parity
bit-matrix, and returns the (batch, m, cols) parity plus a global scrub
scalar (count of mismatched bytes vs a provided expected-parity tensor;
zero when clean). Encode-only callers pass expected=None logic via the
`encode_step` wrapper.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import gf256, rs_matrix


def parity_bit_matrix(k: int = 10, m: int = 4) -> np.ndarray:
    """Host-side (8m, 8k) 0/1 matrix for the systematic parity rows."""
    return gf256.expand_to_bits(rs_matrix.parity_rows(k, m))


def encode_batch(a_bits: jax.Array, stripes: jax.Array) -> jax.Array:
    """(batch, k, n) uint8 -> (batch, m, n) uint8 parity. Pure function,
    jit/shard_map-safe; batch and n dims are embarrassingly parallel."""
    from ..ops.bits import pack_bits_uint8, unpack_bits_bf16

    bits = unpack_bits_bf16(stripes)                      # (B, 8k, n)
    acc = jnp.einsum("st,btn->bsn", a_bits, bits,
                     preferred_element_type=jnp.float32)
    return pack_bits_uint8(acc.astype(jnp.int32) & 1)


def encode_scrub_step(a_bits: jax.Array, stripes: jax.Array,
                      expected_parity: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Full step: encode parity AND count bytes differing from
    expected_parity (the scrub check). Returns (parity, mismatches)."""
    parity = encode_batch(a_bits, stripes)
    mism = jnp.sum((parity != expected_parity).astype(jnp.int64))
    return parity, mism


def jitted_encode(k: int = 10, m: int = 4):
    """-> (fn, a_bits) with fn(a_bits, stripes) jitted."""
    a_bits = jnp.asarray(parity_bit_matrix(k, m), dtype=jnp.bfloat16)
    return jax.jit(encode_batch), a_bits


def sharded_encode_scrub(mesh, k: int = 10, m: int = 4):
    """The multi-chip training-step analogue: jit encode+scrub over a
    (vol, col) mesh. Stripes shard (batch->vol, cols->col); the scrub
    count all-reduces via the sharded sum (XLA inserts the psum).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import COL_AXIS, VOL_AXIS

    a_bits = jnp.asarray(parity_bit_matrix(k, m), dtype=jnp.bfloat16)
    data_sh = NamedSharding(mesh, P(VOL_AXIS, None, COL_AXIS))
    repl = NamedSharding(mesh, P())

    step = jax.jit(
        encode_scrub_step,
        in_shardings=(repl, data_sh, data_sh),
        out_shardings=(data_sh, repl),
    )
    return step, a_bits, data_sh
