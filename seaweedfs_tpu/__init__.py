"""seaweedfs_tpu — a TPU-native distributed object store framework.

A from-scratch rebuild of the capabilities of SeaweedFS (reference:
/root/reference, v3.57): Haystack-style needle volumes, replication,
RS(10,4) erasure coding, master/volume/filer architecture, S3 gateway,
admin shell, metadata event log — with the storage hot paths (Reed-Solomon
erasure coding encode/reconstruct, CRC32C scrub) re-expressed as batched
GF(256) bit-plane matrix multiplies on TPU via JAX/XLA/Pallas.

Layout:
    ops/        TPU compute primitives: GF(256) math, RS matrices,
                bit-plane matmul codecs (numpy / jax / pallas), crc32c
    ec/         erasure-coding subsystem: geometry, interval math,
                file-level encode/rebuild/decode, shard objects
    storage/    storage engine: needle format, needle map, volume,
                super block, idx files, store, disk backends
    master/     cluster control plane: topology, volume growth, assign
    filer/      namespace tier: entries, chunks, stores, event log
    server/     HTTP/RPC servers: master, volume, filer
    s3/         S3 gateway (V4 auth, multipart)
    shell/      admin shell commands (ec.encode, volume.balance, ...)
    wdclient/   client-side volume-location cache
    operation/  client SDK verbs (assign, upload, delete)
    rpc/        lightweight msgpack-over-HTTP rpc substrate
    parallel/   jax mesh/sharding helpers for the codec data plane
    models/     flagship pipelines exposed as jittable step functions
    utils/      config, logging, misc
"""

__version__ = "0.1.0"
