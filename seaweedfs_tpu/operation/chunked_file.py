"""Volume-level chunked files: a needle whose body is a JSON manifest
of sub-fids (reference: weed/operation/chunked_file.go ChunkManifest +
submit.go FilePart.Upload with maxMB). This is the pre-filer way of
storing files bigger than one volume entry: `weed upload -maxMB N`
splits the file into chunk needles and stores a manifest needle
flagged FLAG_IS_CHUNK_MANIFEST (set by POST ?cm=true,
needle_parse_upload.go:186); the volume server reassembles on GET and
cascades DELETE to the chunks. JSON keys mirror the reference's tags:
{"name","mime","size","chunks":[{"fid","offset","size"}]}.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field

from ..filer import FileChunk
from . import verbs


@dataclass
class ChunkInfo:
    fid: str
    offset: int
    size: int


@dataclass
class ChunkManifest:
    name: str = ""
    mime: str = ""
    size: int = 0
    chunks: list[ChunkInfo] = field(default_factory=list)

    def marshal(self) -> bytes:
        d: dict = {}
        if self.name:
            d["name"] = self.name
        if self.mime:
            d["mime"] = self.mime
        if self.size:
            d["size"] = self.size
        if self.chunks:
            d["chunks"] = [{"fid": c.fid, "offset": c.offset,
                            "size": c.size} for c in self.chunks]
        return json.dumps(d).encode()

    def as_file_chunks(self) -> list[FileChunk]:
        """The manifest's spans as filer FileChunks, so the one
        streaming reassembler (filer/stream_content) serves both the
        filer chunk model and these legacy volume manifests."""
        return [FileChunk(fid=c.fid, offset=c.offset, size=c.size,
                          mtime_ns=0)
                for c in sorted(self.chunks, key=lambda c: c.offset)]


def load_chunk_manifest(buffer: bytes,
                        is_compressed: bool = False) -> ChunkManifest:
    """chunked_file.go LoadChunkManifest."""
    if is_compressed:
        from ..utils import compression

        buffer = compression.ungzip(buffer)
    d = json.loads(buffer)
    return ChunkManifest(
        name=d.get("name", ""), mime=d.get("mime", ""),
        size=int(d.get("size", 0)),
        chunks=[ChunkInfo(fid=c["fid"], offset=int(c.get("offset", 0)),
                          size=int(c.get("size", 0)))
                for c in d.get("chunks", [])])


def delete_chunks(lookup_fid, manifest: ChunkManifest,
                  auth: str = "") -> list[str]:
    """Delete every chunk the manifest references; returns the fids
    that could not be deleted (chunked_file.go DeleteChunks — errors
    are reported, not fatal, so a half-deleted manifest can be retried)."""
    failed = []
    for c in manifest.chunks:
        try:
            verbs.delete(lookup_fid(c.fid), auth=auth)
        except (RuntimeError, LookupError, OSError):
            failed.append(c.fid)
    return failed


def upload_chunked(master_url: str, data_iter, total_size: int,
                   name: str, mime: str, chunk_size: int,
                   collection: str = "", replication: str = "",
                   ttl: str = "") -> tuple[str, int]:
    """submit.go FilePart.Upload (the maxMB>0 arm): assign + upload one
    needle per chunk_size span, then store the manifest at its own
    assigned fid with ?cm=true. Returns (manifest fid, stored size).
    On any chunk failure the already-uploaded chunks are deleted."""
    cm = ChunkManifest(name=name, mime=mime, size=total_size)
    try:
        offset = 0
        for piece in data_iter:
            a = verbs.assign(master_url, collection=collection,
                             replication=replication, ttl=ttl)
            verbs.upload(a, piece, name=f"{name}-{len(cm.chunks) + 1}",
                         auth=a.auth)
            cm.chunks.append(ChunkInfo(fid=a.fid, offset=offset,
                                       size=len(piece)))
            offset += len(piece)
        cm.size = offset
        a = verbs.assign(master_url, collection=collection,
                         replication=replication, ttl=ttl)
        url = f"http://{a.url}/{a.fid}?cm=true"
        verbs.upload(url, cm.marshal(), name=name,
                     mime="application/json", auth=a.auth)
        return a.fid, offset
    except Exception:
        from ..wdclient.client import MasterClient

        delete_chunks(MasterClient(master_url).lookup_file_id, cm)
        raise
