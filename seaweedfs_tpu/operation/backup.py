"""`backup` tool: keep a local replica of a volume up to date.

Equivalent of /root/reference/weed/command/backup.go +
weed/storage/volume_backup.go: locate the volume via the master, compare
sync status with the local copy, then either full-copy (.dat/.idx) or
incrementally append only the records written since the last run
(streamed from the source's append_at_ns watermark). Repeated runs are
cheap — the normal mode is a cron job pulling deltas.
"""
from __future__ import annotations

import os

from ..storage.volume import Volume
from ..rpc.httpclient import session


class BackupError(Exception):
    pass


def _locate(master_url: str, vid: int) -> str:
    r = session().get(f"{master_url}/dir/lookup",
                     params={"volumeId": vid}, timeout=30)
    try:
        body = r.json()
    except ValueError:  # proxy/html error bodies
        raise BackupError(
            f"volume {vid}: lookup returned {r.status_code}: "
            f"{r.text[:200]}")
    locs = body.get("locations", [])
    if r.status_code >= 300 or not locs:
        raise BackupError(
            f"volume {vid}: {body.get('error', 'no locations')}")
    return locs[0]["url"]


def backup_volume(master_url: str, vid: int, dest_dir: str,
                  collection: str = "") -> dict:
    """Pull volume `vid` into dest_dir; returns a summary dict."""
    master_url = master_url.rstrip("/")
    if not master_url.startswith("http"):
        master_url = f"http://{master_url}"
    source = _locate(master_url, vid)
    st = session().get(f"http://{source}/admin/volume_sync_status",
                      params={"volume": vid}, timeout=60)
    if st.status_code >= 300:
        raise BackupError(f"sync status from {source}: {st.text}")
    status = st.json()
    os.makedirs(dest_dir, exist_ok=True)

    name = f"{collection}_{vid}" if collection else str(vid)
    dat_path = os.path.join(dest_dir, name + ".dat")
    have_local = os.path.exists(dat_path)
    mode = "incremental"
    if have_local:
        local = Volume(dest_dir, collection, vid)
        # a vacuum on the source rewrote history; or the local copy is
        # somehow ahead (e.g. it was a live replica once) — start over
        if (local.super_block.compaction_revision
                != status["compact_revision"]
                or local.dat.size() > status["tail_offset"]):
            local.close()
            have_local = False
            mode = "full (revision/tail mismatch)"
        elif local.last_append_at_ns == 0 and len(local.nm) > 0:
            # a replica without stamps (v2 records) can't say where it
            # stopped — an "incremental" pull from 0 would re-append
            # the whole source on every run
            local.close()
            have_local = False
            mode = "full (no append stamps)"
    if not have_local:
        if os.path.exists(dat_path):
            os.remove(dat_path)
            idx = os.path.join(dest_dir, name + ".idx")
            if os.path.exists(idx):
                os.remove(idx)
        _full_copy(source, vid, collection, dest_dir, name)
        local = Volume(dest_dir, collection, vid)
        mode = mode if mode.startswith("full") else "full (new)"
        applied = local.nm.file_count
    else:
        applied = _incremental_copy(source, vid, local)
    out = {"volume": vid, "mode": mode, "records_applied": applied,
           "tail_offset": local.dat.size(),
           "last_append_at_ns": local.last_append_at_ns}
    local.close()
    return out


def _full_copy(source: str, vid: int, collection: str, dest_dir: str,
               name: str) -> None:
    for ext in (".dat", ".idx"):
        with session().get(f"http://{source}/admin/copy_file",
                          params={"volume": vid, "collection": collection,
                                  "ext": ext},
                          stream=True, timeout=600) as r:
            if r.status_code >= 300:
                raise BackupError(f"copy {ext} from {source}: "
                                  f"{r.status_code}")
            with open(os.path.join(dest_dir, name + ext), "wb") as f:
                for chunk in r.iter_content(1 << 20):
                    f.write(chunk)


def _incremental_copy(source: str, vid: int, local: Volume) -> int:
    """Stream the delta and append whole-record prefixes as they
    arrive — the delta after a long gap can be many GB and must not be
    buffered wholesale."""
    from ..storage import needle as ndl

    applied = 0
    buf = bytearray()
    with session().get(f"http://{source}/admin/volume_incremental_copy",
                      params={"volume": vid,
                              "since_ns": local.last_append_at_ns},
                      stream=True, timeout=600) as r:
        if r.status_code >= 300:
            raise BackupError(f"incremental copy from {source}: "
                              f"{r.status_code}")
        for chunk in r.iter_content(1 << 20):
            buf.extend(chunk)
            whole = ndl.whole_records_prefix(buf, local.version)
            if whole:
                applied += local.append_raw_segment(
                    bytes(memoryview(buf)[:whole]))
                del buf[:whole]
    if buf:
        raise BackupError(
            f"incremental stream from {source} ended mid-record "
            f"({len(buf)} trailing bytes); re-run to retry")
    return applied
