"""Offline volume file tools: fix, compact, export.

Equivalents of /root/reference/weed/command/fix.go (offline .idx
reconstruction by scanning the .dat), command/compact.go (offline
vacuum) and command/export.go (dump live needles out of a volume into a
tar archive). These operate directly on volume files with the server
stopped — the recovery toolbox when an index is corrupt or a server
won't start.
"""
from __future__ import annotations

import io
import os
import tarfile
import time

from ..storage import types as t
from ..storage.volume import Volume


def _require_dat(dirname: str, vid: int, collection: str) -> None:
    """Opening a Volume auto-creates missing files; an offline tool
    pointed at a wrong id must error, not fabricate an empty volume."""
    name = f"{collection}_{vid}" if collection else str(vid)
    dat = os.path.join(dirname, name + ".dat")
    if not os.path.exists(dat):
        raise FileNotFoundError(f"no volume file {dat}")


def fix_volume(dirname: str, vid: int, collection: str = "") -> dict:
    """Rebuild <vid>.idx from the .dat (command/fix.go:24-40)."""
    _require_dat(dirname, vid, collection)
    v = Volume(dirname, collection, vid)
    try:
        v.rebuild_index()
        return {"volume": vid, "records": v.nm.file_count,
                "idx": v.file_name() + ".idx"}
    finally:
        v.close()


def compact_volume(dirname: str, vid: int, collection: str = "") -> dict:
    """Offline vacuum: drop deleted/overwritten records
    (command/compact.go)."""
    _require_dat(dirname, vid, collection)
    v = Volume(dirname, collection, vid)
    try:
        before = v.dat.size()
        v.compact()
        return {"volume": vid, "before_bytes": before,
                "after_bytes": v.dat.size(), "records": v.nm.file_count}
    finally:
        v.close()


def export_volume(dirname: str, vid: int, out_tar: str,
                  collection: str = "", newer_than_ns: int = 0) -> dict:
    """Write every live needle to a tar archive, named by its stored
    file name when present else its hex id (command/export.go). Deleted
    records are skipped; `newer_than_ns` filters by append stamp."""
    _require_dat(dirname, vid, collection)
    v = Volume(dirname, collection, vid)
    count, total = 0, 0
    try:
        with tarfile.open(out_tar, "w") as tar:
            for offset, nid, nsize, _disk in v._walk_records(
                    v.super_block.block_size):
                if nsize <= 0:
                    continue
                loc = v.nm.get(nid)
                if loc is None or t.offset_to_actual(loc[0]) != offset:
                    continue  # overwritten or deleted later
                if newer_than_ns and v._append_at_ns_at(
                        offset, nsize) <= newer_than_ns:
                    continue
                n = v.read_needle(nid)
                name = n.name.decode("utf-8", "replace") if n.name \
                    else f"{nid:x}"
                if n.is_compressed and not name.endswith(".gz"):
                    name += ".gz"  # export.go:248 marks gzipped bodies
                info = tarfile.TarInfo(name=f"vol{vid}/{name}")
                info.size = len(n.data)
                info.mtime = n.last_modified or int(time.time())
                tar.addfile(info, io.BytesIO(n.data))
                count += 1
                total += len(n.data)
        return {"volume": vid, "files": count, "bytes": total,
                "tar": os.path.abspath(out_tar)}
    finally:
        v.close()


def see_dat(dirname: str, vid: int, collection: str = ""):
    """Yield one dict per record in .dat order — the unmaintained
    see_dat inspector: full needle decode (name/mime/flags/ttl),
    deleted records included. For spot-checking volume files."""
    from ..storage import needle as ndl

    _require_dat(dirname, vid, collection)
    v = Volume(dirname, collection, vid)
    try:
        import struct

        offset = v.super_block.block_size
        size = v.dat.size()
        while offset + t.NEEDLE_HEADER_SIZE <= size:
            head = v.dat.read_at(t.NEEDLE_HEADER_SIZE, offset)
            _, nid, size_u32 = struct.unpack(">IQI", head)
            nsize = t.u32_to_size(size_u32)
            disk = ndl.disk_size(max(nsize, 0), v.version)
            if offset + disk > size:
                break
            rec = {"offset": offset, "id": nid, "size": nsize,
                   "deleted": nsize <= 0}
            if nsize > 0:
                try:
                    n = ndl.Needle.from_bytes(
                        v.dat.read_at(disk, offset), v.version)
                    rec.update({
                        "cookie": n.cookie,
                        "name": n.name.decode("utf-8", "replace"),
                        "mime": n.mime.decode("utf-8", "replace"),
                        "data_bytes": len(n.data),
                        "flags": n.flags,
                        "last_modified": n.last_modified,
                        "crc_ok": True,
                    })
                except ValueError as e:
                    rec["crc_ok"] = False
                    rec["error"] = str(e)
            yield rec
            offset += disk
    finally:
        v.close()


def see_idx(dirname: str, vid: int, collection: str = ""):
    """Yield (key, offset, size) per .idx entry in file order — the
    unmaintained see_idx inspector."""
    from ..storage import idx as idxmod

    name = f"{collection}_{vid}" if collection else str(vid)
    path = os.path.join(dirname, name + ".idx")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no index file {path}")
    for e in idxmod.iter_entries(path):
        yield {"key": e.key, "offset": e.offset,
               "byte_offset": t.offset_to_actual(e.offset),
               "size": e.size, "deleted": e.size <= 0}
