"""Client SDK verbs: assign, upload, download, delete.

Equivalent of /root/reference/weed/operation/ (Assign
assign_file_id.go:141, upload_content.go, delete batch, lookup). Sync
`requests`-based — the client side is host code, not server asyncio.
"""
from __future__ import annotations

from dataclasses import dataclass, field

import requests

from ..rpc.httpclient import session
from ..utils import retry


@dataclass
class AssignResult:
    fid: str
    url: str
    public_url: str
    count: int = 1
    auth: str = ""
    replicas: list[dict] = field(default_factory=list)


def assign(master_url: str, count: int = 1, collection: str = "",
           replication: str = "", ttl: str = "",
           data_center: str = "", disk_type: str = "") -> AssignResult:
    params = {"count": count}
    if collection:
        params["collection"] = collection
    if replication:
        params["replication"] = replication
    if ttl:
        params["ttl"] = ttl
    if data_center:
        params["dataCenter"] = data_center
    if disk_type:
        params["disk"] = disk_type
    resp = session().get(f"{master_url.rstrip('/')}/dir/assign",
                        params=params, timeout=30)
    body = resp.json()
    if resp.status_code != 200 or "error" in body:
        raise RuntimeError(f"assign: {body.get('error', resp.status_code)}")
    return AssignResult(fid=body["fid"], url=body["url"],
                        public_url=body.get("publicUrl", body["url"]),
                        count=body.get("count", count),
                        auth=body.get("auth", ""),
                        replicas=body.get("replicas", []))


def upload(url_or_assign, data: bytes, name: str = "",
           mime: str = "", auth: str = "", ts: int = 0) -> dict:
    """Upload bytes to a volume server. Accepts an AssignResult or a full
    'http://host:port/fid' url."""
    if isinstance(url_or_assign, AssignResult):
        url = f"http://{url_or_assign.url}/{url_or_assign.fid}"
        auth = auth or url_or_assign.auth
    else:
        url = url_or_assign
    headers = {"Content-Type": mime or "application/octet-stream"}
    if auth:
        headers["Authorization"] = f"Bearer {auth}"
    params = {}
    if ts:
        params["ts"] = str(ts)
    if name:
        params["name"] = name
    # raw body, not multipart: the volume server accepts both
    # (needle_parse_upload.go does too), and multipart encode+parse
    # measured ~1ms/req of pure CPU on the 1KB write benchmark
    resp = session().post(url, data=data, headers=headers, params=params,
                         timeout=60)
    body = resp.json()
    if resp.status_code >= 300 or "error" in body:
        raise RuntimeError(f"upload: {body.get('error', resp.status_code)}")
    return body


def download(url: str, auth: str = "") -> bytes:
    headers = {"Authorization": f"Bearer {auth}"} if auth else {}
    resp = session().get(url, headers=headers, timeout=60)
    if resp.status_code != 200:
        raise RuntimeError(f"download {url}: {resp.status_code}")
    return resp.content


def delete(url: str, auth: str = "") -> None:
    headers = {"Authorization": f"Bearer {auth}"} if auth else {}
    resp = session().delete(url, headers=headers, timeout=30)
    if resp.status_code not in (200, 202, 404):
        raise RuntimeError(f"delete {url}: {resp.status_code}")


def upload_data(master_url: str, data: bytes, name: str = "",
                collection: str = "", replication: str = "",
                ttl: str = "", mime: str = "") -> str:
    """assign + upload in one call; returns the fid.

    Mints an overall deadline covering both hops (the SDK is its own
    gateway edge), so a slow assign eats into the upload's budget
    instead of each hop getting a fresh clock.
    """
    with retry.deadline_scope(budget=retry.EDGE_BUDGET):
        a = assign(master_url, collection=collection,
                   replication=replication, ttl=ttl)
        upload(a, data, name=name, mime=mime)
    return a.fid
