"""FTP gateway over the filer.

The reference ships only an experimental 81-line skeleton
(/root/reference/weed/ftpd/ftp_server.go wires ftpserverlib but is not
production-ready); this is a working stdlib implementation of the same
slot: a threaded RFC 959 server speaking passive-mode FTP, with every
file operation carried by the filer HTTP API (list/GET/POST/DELETE /
mkdir / mv.from — the same surface the WebDAV gateway rides).

Supported verbs: USER/PASS, SYST, FEAT, TYPE, PWD/CWD/CDUP, PASV/EPSV,
LIST/NLST, RETR, STOR, APPE, DELE, MKD, RMD, RNFR/RNTO, SIZE, MDTM,
REST (stream resume for RETR), NOOP, QUIT.
"""
from __future__ import annotations

import posixpath
import socket
import threading
import time

import requests

from ..filer.entry import entry_size as _entry_size


class FtpSession(threading.Thread):
    def __init__(self, server: "FtpServer", conn: socket.socket):
        super().__init__(daemon=True)
        self.srv = server
        self.conn = conn
        self.cwd = "/"
        self.user = ""
        self.authed = False
        self.binary = True
        self.rename_from = ""
        self.rest_offset = 0
        self._pasv: socket.socket | None = None

    # -- plumbing -------------------------------------------------------
    def reply(self, code: int, text: str) -> None:
        self.conn.sendall(f"{code} {text}\r\n".encode())

    def _abs(self, arg: str) -> str:
        path = arg if arg.startswith("/") else \
            posixpath.join(self.cwd, arg)
        norm = posixpath.normpath(path)
        root = self.srv.root.rstrip("/")
        return (root + norm) if norm != "/" else (root or "/")

    def _filer(self, method: str, path: str, **kw) -> requests.Response:
        from ..rpc.httpclient import session

        return session().request(method, f"{self.srv.filer_url}{path}",
                                 timeout=600, **kw)

    def _open_data(self) -> socket.socket:
        if self._pasv is None:
            raise ConnectionError("no PASV listener")
        self._pasv.settimeout(30)
        data, _ = self._pasv.accept()
        self._pasv.close()
        self._pasv = None
        return data

    # -- main loop ------------------------------------------------------
    def run(self) -> None:
        try:
            self.reply(220, "seaweedfs-tpu FTP gateway ready")
            buf = b""
            while True:
                while b"\r\n" not in buf:
                    chunk = self.conn.recv(4096)
                    if not chunk:
                        return
                    buf += chunk
                line, buf = buf.split(b"\r\n", 1)
                cmd, _, arg = line.decode("utf-8",
                                          "surrogateescape").partition(" ")
                cmd = cmd.upper()
                try:
                    if not self._dispatch(cmd, arg):
                        return
                except requests.RequestException:
                    self.reply(451, "filer request failed")
                except (ConnectionError, socket.timeout):
                    self.reply(425, "cannot open data connection")
        except OSError:
            pass
        finally:
            if self._pasv is not None:
                self._pasv.close()
            self.conn.close()

    # -- commands -------------------------------------------------------
    def _dispatch(self, cmd: str, arg: str) -> bool:
        if cmd == "QUIT":
            self.reply(221, "bye")
            return False
        if cmd == "USER":
            self.user = arg
            if self.srv.anonymous and arg in ("anonymous", "ftp"):
                self.authed = True
                self.reply(230, "anonymous login ok")
            else:
                self.reply(331, "password required")
            return True
        if cmd == "PASS":
            if self.srv.anonymous and self.user in ("anonymous", "ftp"):
                self.authed = True
                self.reply(230, "logged in")
            elif self.srv.users.get(self.user) == arg:
                self.authed = True
                self.reply(230, "logged in")
            else:
                self.reply(530, "login incorrect")
            return True
        if cmd in ("SYST",):
            self.reply(215, "UNIX Type: L8")
            return True
        if cmd == "FEAT":
            self.conn.sendall(
                b"211-Features:\r\n SIZE\r\n MDTM\r\n REST STREAM\r\n"
                b" EPSV\r\n UTF8\r\n211 End\r\n")
            return True
        if cmd == "NOOP":
            self.reply(200, "ok")
            return True
        if cmd == "TYPE":
            self.binary = arg.upper().startswith("I")
            self.reply(200, f"type set to {'I' if self.binary else 'A'}")
            return True
        if not self.authed:
            self.reply(530, "please login")
            return True
        handler = getattr(self, f"_cmd_{cmd.lower()}", None)
        if handler is None:
            self.reply(502, f"{cmd} not implemented")
            return True
        handler(arg)
        return True

    def _cmd_pwd(self, arg: str) -> None:
        self.reply(257, f'"{self.cwd}" is the current directory')

    def _cmd_cwd(self, arg: str) -> None:
        path = self._abs(arg or "/")
        if arg in ("/", "") or self._stat_dir(path):
            self.cwd = posixpath.normpath(
                arg if arg.startswith("/")
                else posixpath.join(self.cwd, arg))
            self.reply(250, "directory changed")
        else:
            self.reply(550, "no such directory")

    def _cmd_cdup(self, arg: str) -> None:
        self.cwd = posixpath.dirname(self.cwd.rstrip("/")) or "/"
        self.reply(250, "directory changed")

    def _stat_dir(self, path: str) -> bool:
        r = self._filer("GET", path, params={"meta": "1"})
        return r.status_code == 200 and \
            bool(r.json().get("mode", 0) & 0o40000)

    def _entry(self, path: str) -> dict | None:
        r = self._filer("GET", path, params={"meta": "1"})
        return r.json() if r.status_code == 200 else None

    def _cmd_pasv(self, arg: str) -> None:
        self._listen_pasv()
        # advertise the address the client already reached us on — a
        # wildcard bind (0.0.0.0) must never leak into the 227 reply
        ip = self.conn.getsockname()[0].replace(".", ",")
        port = self._pasv.getsockname()[1]
        self.reply(227, f"entering passive mode "
                        f"({ip},{port >> 8},{port & 0xFF})")

    def _cmd_epsv(self, arg: str) -> None:
        self._listen_pasv()
        port = self._pasv.getsockname()[1]
        self.reply(229, f"entering extended passive mode (|||{port}|)")

    def _listen_pasv(self) -> None:
        if self._pasv is not None:
            self._pasv.close()
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.srv.host, 0))
        s.listen(1)
        self._pasv = s

    def _list_entries(self, path: str) -> list[dict]:
        r = self._filer("GET", path or "/",
                        params={"limit": "10000"},
                        headers={"Accept": "application/json"})
        if r.status_code != 200:
            return []
        return r.json().get("entries", [])

    def _cmd_list(self, arg: str) -> None:
        path = self._abs(arg or ".") if not arg.startswith("-") \
            else self._abs(".")
        self.reply(150, "opening data connection")
        data = self._open_data()
        try:
            lines = []
            for e in self._list_entries(path):
                name = e["full_path"].rstrip("/").rpartition("/")[2]
                is_dir = bool(e.get("mode", 0) & 0o40000)
                size = _entry_size(e)
                mtime = time.strftime(
                    "%b %d %H:%M", time.localtime(e.get("mtime", 0)))
                kind = "d" if is_dir else "-"
                lines.append(f"{kind}rw-r--r-- 1 ftp ftp "
                             f"{size:>12} {mtime} {name}")
            data.sendall(("\r\n".join(lines) + "\r\n").encode()
                         if lines else b"")
        finally:
            data.close()
        self.reply(226, "transfer complete")

    def _cmd_nlst(self, arg: str) -> None:
        path = self._abs(arg or ".")
        self.reply(150, "opening data connection")
        data = self._open_data()
        try:
            names = [e["full_path"].rstrip("/").rpartition("/")[2]
                     for e in self._list_entries(path)]
            data.sendall(("\r\n".join(names) + "\r\n").encode()
                         if names else b"")
        finally:
            data.close()
        self.reply(226, "transfer complete")

    def _cmd_rest(self, arg: str) -> None:
        try:
            self.rest_offset = int(arg)
            self.reply(350, f"restarting at {self.rest_offset}")
        except ValueError:
            self.reply(501, "bad offset")

    def _cmd_retr(self, arg: str) -> None:
        path = self._abs(arg)
        headers = {}
        offset = self.rest_offset
        self.rest_offset = 0
        if offset:
            headers["Range"] = f"bytes={offset}-"
        r = self._filer("GET", path, headers=headers, stream=True)
        try:
            if r.status_code not in (200, 206):
                self.reply(550, "no such file")
                return
            self.reply(150, "opening data connection")
            data = self._open_data()
            try:
                for chunk in r.iter_content(256 << 10):
                    data.sendall(chunk)
            finally:
                data.close()
        finally:
            r.close()
        self.reply(226, "transfer complete")

    # spill uploads to disk past this; an FTP gateway's whole job is
    # large transfers, so the body must never have to fit in RAM
    SPOOL_MAX = 16 << 20

    def _store(self, arg: str, append: bool) -> None:
        import shutil
        import tempfile

        path = self._abs(arg)
        self.reply(150, "opening data connection")
        data = self._open_data()
        spool = tempfile.SpooledTemporaryFile(max_size=self.SPOOL_MAX)
        try:
            if append:
                # prefix with the existing content, streamed
                r = self._filer("GET", path, stream=True)
                try:
                    if r.status_code == 200:
                        shutil.copyfileobj(r.raw, spool, 256 << 10)
                finally:
                    r.close()
            while True:
                chunk = data.recv(256 << 10)
                if not chunk:
                    break
                spool.write(chunk)
            data.close()
            data = None
            spool.seek(0)
            # file-object body streams as chunked transfer encoding;
            # the filer's autochunk splits it into volume chunks
            self._filer("POST", path, data=spool).raise_for_status()
        finally:
            if data is not None:
                data.close()
            spool.close()
        self.reply(226, "transfer complete")

    def _cmd_stor(self, arg: str) -> None:
        self._store(arg, append=False)

    def _cmd_appe(self, arg: str) -> None:
        self._store(arg, append=True)

    def _cmd_dele(self, arg: str) -> None:
        r = self._filer("DELETE", self._abs(arg))
        if r.status_code in (200, 204):
            self.reply(250, "deleted")
        else:
            self.reply(550, "delete failed")

    def _cmd_rmd(self, arg: str) -> None:
        path = self._abs(arg)
        if not self._stat_dir(path):
            self.reply(550, "no such directory")
            return
        r = self._filer("DELETE", path + "/",
                        params={"recursive": "true"})
        if r.status_code in (200, 204):
            self.reply(250, "directory removed")
        else:
            self.reply(550, "rmd failed")

    def _cmd_mkd(self, arg: str) -> None:
        path = self._abs(arg)
        r = self._filer("PUT", path, params={"mkdir": "1"})
        if r.status_code < 300:
            self.reply(257, f'"{arg}" created')
        else:
            self.reply(550, "mkdir failed")

    def _cmd_rnfr(self, arg: str) -> None:
        if self._entry(self._abs(arg)) is None:
            self.reply(550, "no such file")
            return
        self.rename_from = self._abs(arg)
        self.reply(350, "ready for RNTO")

    def _cmd_rnto(self, arg: str) -> None:
        if not self.rename_from:
            self.reply(503, "RNFR first")
            return
        r = self._filer("PUT", self._abs(arg),
                        params={"mv.from": self.rename_from})
        self.rename_from = ""
        if r.status_code < 300:
            self.reply(250, "renamed")
        else:
            self.reply(550, "rename failed")

    def _cmd_size(self, arg: str) -> None:
        e = self._entry(self._abs(arg))
        if e is None or e.get("mode", 0) & 0o40000:
            self.reply(550, "no such file")
            return
        self.reply(213, str(_entry_size(e)))

    def _cmd_mdtm(self, arg: str) -> None:
        e = self._entry(self._abs(arg))
        if e is None:
            self.reply(550, "no such file")
            return
        self.reply(213, time.strftime("%Y%m%d%H%M%S",
                                      time.gmtime(e.get("mtime", 0))))


class FtpServer:
    """`seaweedfs_tpu ftp` — serve a filer directory over FTP."""

    def __init__(self, filer_url: str, port: int = 8021,
                 host: str = "127.0.0.1", root: str = "/",
                 users: dict[str, str] | None = None,
                 anonymous: bool = True):
        self.filer_url = filer_url.rstrip("/") \
            if filer_url.startswith("http") else f"http://{filer_url}"
        self.host = host
        self.port = port
        self.root = "/" + root.strip("/") if root.strip("/") else ""
        self.users = users or {}
        self.anonymous = anonymous and not self.users
        self._srv: socket.socket | None = None
        self._thread: threading.Thread | None = None
        self._stopping = False

    def start(self) -> "FtpServer":
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self.port))
        s.listen(16)
        self.port = s.getsockname()[1]
        self._srv = s
        self._thread = threading.Thread(target=self._accept, daemon=True)
        self._thread.start()
        return self

    def _accept(self) -> None:
        while not self._stopping:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            FtpSession(self, conn).start()

    def stop(self) -> None:
        self._stopping = True
        if self._srv is not None:
            self._srv.close()
