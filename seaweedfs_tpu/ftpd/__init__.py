"""FTP gateway — experimental stub, matching the reference's status.

The reference ships an 81-line experimental stub
(/root/reference/weed/ftpd/ftp_server.go) that wires an FTP library to
filer-backed file operations but is not production-wired into `weed
server`. This package holds the same slot: the option surface exists so
configs/scaffolds mention it, and `start()` explains the status instead
of half-working.
"""
from __future__ import annotations


class FtpServer:
    def __init__(self, filer_url: str, port: int = 8021):
        self.filer_url = filer_url.rstrip("/")
        self.port = port

    def start(self) -> None:
        raise NotImplementedError(
            "the FTP gateway is experimental and not yet implemented "
            "(the reference ships it as a stub too, weed/ftpd/"
            "ftp_server.go); use the S3, WebDAV or mount gateways")
