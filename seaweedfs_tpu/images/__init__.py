"""On-read image transforms.

Equivalent of /root/reference/weed/images/resizing.go (+ orientation
fix, orientation.go), hooked into the volume read path exactly where
the reference does it (volume_server_handlers_read.go:294-353): a GET
for an image fid may carry ?width=&height=&mode= and receives a resized
rendition; the stored bytes are untouched.

Modes (resizing.go Resized):
    ""     exact resize to width x height (single dimension keeps the
           aspect ratio)
    fit    largest resize that fits inside the box, ratio preserved
    fill   cover the box then center-crop to exactly width x height
"""
from __future__ import annotations

import io

_FORMATS = {"image/jpeg": "JPEG", "image/png": "PNG",
            "image/gif": "GIF", "image/webp": "WEBP",
            "image/bmp": "BMP"}


def is_image_mime(mime: str) -> bool:
    return mime.split(";")[0].strip().lower() in _FORMATS


def cropped(data: bytes, mime: str, x1: int, y1: int,
            x2: int, y2: int) -> bytes:
    """Crop to the (x1,y1)-(x2,y2) rectangle — the ?crop_x1=… GET
    params (volume_server_handlers_read.go:336 shouldCropImages +
    images/cropping.go Cropped; applied BEFORE any resize, like the
    reference). The reference crops png/jpeg/gif only and serves the
    original when the rectangle falls outside the image or the bytes
    don't decode; same here."""
    kind = mime.split(";")[0].strip().lower()
    if kind not in ("image/png", "image/jpeg", "image/gif"):
        return data
    try:
        from PIL import Image
    except ImportError:
        return data
    try:
        img = Image.open(io.BytesIO(data))
        img.load()
    except Exception:
        return data
    w, h = img.size
    if x2 > w or y2 > h:  # cropping.go:24 out-of-bounds -> original
        return data
    # clamp the origin into bounds: PIL pads negative coordinates
    # with black, the reference's crop intersects with the image
    x1, y1 = max(0, x1), max(0, y1)
    if x1 >= x2 or y1 >= y2:  # clamping emptied the box
        return data
    out = img.crop((x1, y1, x2, y2))
    fmt = _FORMATS[kind]
    if fmt == "JPEG" and out.mode not in ("RGB", "L"):
        out = out.convert("RGB")
    buf = io.BytesIO()
    out.save(buf, format=fmt)
    return buf.getvalue()


def resized(data: bytes, mime: str, width: int = 0, height: int = 0,
            mode: str = "") -> bytes:
    """Return a resized rendition of `data`, or the original bytes when
    no resize applies (no dims, undecodable, or already smaller the way
    the reference short-circuits NewImage errors)."""
    if width <= 0 and height <= 0:
        return data
    fmt = _FORMATS.get(mime.split(";")[0].strip().lower())
    if fmt is None:
        return data
    try:
        from PIL import Image, ImageOps
    except ImportError:  # stripped-down runtime: serve original bytes
        return data
    try:
        img = Image.open(io.BytesIO(data))
        img.load()
    except Exception:
        return data  # resizing.go: undecodable -> original bytes
    # camera EXIF orientation is honored before any geometry math
    # (images/orientation.go FixJpgOrientation)
    img = ImageOps.exif_transpose(img)
    w, h = img.size
    if width <= 0:
        width = max(1, round(w * height / h))
    if height <= 0:
        height = max(1, round(h * width / w))
    if mode == "fit":
        out = ImageOps.contain(img, (width, height))
    elif mode == "fill":
        out = ImageOps.fit(img, (width, height))
    else:
        out = img.resize((width, height))
    buf = io.BytesIO()
    save_kw = {}
    if fmt == "JPEG" and out.mode not in ("RGB", "L"):
        out = out.convert("RGB")
    if fmt == "GIF":
        save_kw["save_all"] = False
    out.save(buf, format=fmt, **save_kw)
    return buf.getvalue()
