"""Replicator: consume a filer's metadata event stream and mirror the
namespace into a sink, with resumable offsets.

Equivalent of /root/reference/weed/replication/replicator.go driven the
way command/filer_replicate.go drives it: subscribe to metadata events
under a path prefix, translate each event into sink calls, checkpoint
the last-applied ts_ns so restarts resume rather than recopy
(remote_storage/track_sync_offset.go's role, stored in the source
filer's KV).
"""
from __future__ import annotations

import asyncio
import json
import threading
from typing import Callable

import requests

from ..filer.entry import Entry
from .sink import ReplicationSink


class Replicator:
    def __init__(self, source_filer: str, sink: ReplicationSink,
                 path_prefix: str = "/", offset_key: str = "",
                 exclude_signature: int = 0):
        """exclude_signature: skip events already signed by this id —
        the active-active loop guard (filer_sync.go)."""
        self.source = source_filer.rstrip("/") \
            if source_filer.startswith("http") else \
            f"http://{source_filer}"
        self.sink = sink
        self.prefix = path_prefix.rstrip("/") or "/"
        self.offset_key = offset_key or \
            f"replication/{sink.name}/offset"
        self.exclude_signature = exclude_signature
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.applied = 0
        self.skipped = 0

    # -- offsets --------------------------------------------------------
    def _load_offset(self) -> int:
        try:
            r = requests.get(f"{self.source}/kv/{self.offset_key}",
                             timeout=5)
            if r.status_code == 200:
                return int(r.content)
        except (requests.RequestException, ValueError):
            pass
        return 0

    def _save_offset(self, ts_ns: int) -> None:
        try:
            requests.put(f"{self.source}/kv/{self.offset_key}",
                         data=str(ts_ns).encode(), timeout=5)
        except requests.RequestException:
            pass

    # -- the event pump -------------------------------------------------
    def start(self) -> None:
        self._stop.clear()
        self._loop = None
        self._task = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        # the pump blocks inside ws receive; cancel it from its loop or
        # the join would always ride out the full timeout
        loop, task = self._loop, self._task
        if loop is not None and task is not None and loop.is_running():
            loop.call_soon_threadsafe(task.cancel)
        if self._thread is not None:
            self._thread.join(timeout=10)

    def _run(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        self._task = self._loop.create_task(self._pump())
        try:
            self._loop.run_until_complete(self._task)
        except asyncio.CancelledError:
            pass
        finally:
            try:
                self._loop.run_until_complete(
                    self._loop.shutdown_asyncgens())
            finally:
                self._loop.close()

    async def _pump(self) -> None:
        import aiohttp

        while not self._stop.is_set():
            since = self._load_offset()
            url = self.source.replace("http", "ws", 1) + \
                "/ws/meta_subscribe"
            try:
                async with aiohttp.ClientSession() as sess:
                    async with sess.ws_connect(
                            url, params={"path_prefix": self.prefix,
                                         "since_ns": str(since)},
                            heartbeat=30) as ws:
                        async for msg in ws:
                            if self._stop.is_set():
                                return
                            if msg.type != aiohttp.WSMsgType.TEXT:
                                break
                            ev = json.loads(msg.data)
                            await asyncio.to_thread(self.apply, ev)
                            self._save_offset(ev["ts_ns"])
            except Exception:
                pass
            await asyncio.sleep(0.5)

    # -- event -> sink ---------------------------------------------------
    def _rel(self, full_path: str) -> str:
        if self.prefix != "/" and full_path.startswith(self.prefix):
            return full_path[len(self.prefix):] or "/"
        return full_path

    def _reader(self, full_path: str) -> Callable[[], bytes]:
        src = self.source

        def read() -> bytes:
            r = requests.get(f"{src}{full_path}", timeout=300)
            r.raise_for_status()
            return r.content

        return read

    def apply(self, ev: dict) -> None:
        """Route one metadata event to the sink
        (replicator.go Replicate)."""
        if self.exclude_signature and \
                self.exclude_signature in ev.get("signatures", []):
            self.skipped += 1
            return
        old, new = ev.get("old_entry"), ev.get("new_entry")
        if old is None and new is None:
            return
        if new is None:  # delete
            e = Entry.from_dict(old)
            self.sink.delete_entry(self._rel(e.full_path),
                                   e.is_directory)
        elif old is None:  # create
            e = Entry.from_dict(new)
            self.sink.create_entry(self._rel(e.full_path), e,
                                   self._reader(e.full_path))
        else:  # update / rename
            oe, ne = Entry.from_dict(old), Entry.from_dict(new)
            if oe.full_path != ne.full_path:
                self.sink.delete_entry(self._rel(oe.full_path),
                                       oe.is_directory)
            self.sink.update_entry(self._rel(ne.full_path), ne,
                                   self._reader(ne.full_path))
        self.applied += 1
