"""Replicator: consume a filer's metadata event stream and mirror the
namespace into a sink, with resumable offsets.

Equivalent of /root/reference/weed/replication/replicator.go driven the
way command/filer_replicate.go drives it: subscribe to metadata events
under a path prefix, translate each event into sink calls, checkpoint
the last-applied ts_ns so restarts resume rather than recopy
(remote_storage/track_sync_offset.go's role, stored in the source
filer's KV).
"""
from __future__ import annotations

from typing import Callable

import requests

from ..filer.entry import Entry
from ..rpc.meta_subscriber import MetaSubscriber
from .sink import ReplicationSink
from ..rpc.httpclient import session


class Replicator:
    def __init__(self, source_filer: str, sink: ReplicationSink,
                 path_prefix: str = "/", offset_key: str = "",
                 exclude_signature: int = 0):
        """exclude_signature: skip events already signed by this id —
        the active-active loop guard (filer_sync.go)."""
        self.source = source_filer.rstrip("/") \
            if source_filer.startswith("http") else \
            f"http://{source_filer}"
        self.sink = sink
        self.prefix = path_prefix.rstrip("/") or "/"
        self.offset_key = offset_key or \
            f"replication/{sink.name}/offset"
        self.exclude_signature = exclude_signature
        self._sub: MetaSubscriber | None = None
        self.applied = 0
        self.skipped = 0
        self.failed = 0  # poison events skipped after a failed apply

    # -- offsets --------------------------------------------------------
    def _load_offset(self) -> int:
        try:
            r = session().get(f"{self.source}/kv/{self.offset_key}",
                             timeout=5)
            if r.status_code == 200:
                return int(r.content)
        except (requests.RequestException, ValueError):
            pass
        return 0

    def _save_offset(self, ts_ns: int) -> None:
        try:
            session().put(f"{self.source}/kv/{self.offset_key}",
                         data=str(ts_ns).encode(), timeout=5)
        except requests.RequestException:
            pass

    # -- the event pump -------------------------------------------------
    def start(self) -> None:
        self._sub = MetaSubscriber(self.source, self.prefix,
                                   self._handle,
                                   since_fn=self._load_offset)
        self._sub.start()

    def stop(self) -> None:
        if self._sub is not None:
            self._sub.stop()
            self._sub = None

    def _handle(self, ev: dict) -> None:
        """One event, called off-loop by the subscriber pump."""
        try:
            self.apply(ev)
        except Exception:
            # poison event (e.g. create whose content is already deleted
            # at the source): count it and move on — replaying it forever
            # would wedge the stream behind it (a later event supersedes
            # it anyway)
            self.failed += 1
        self._save_offset(ev["ts_ns"])

    # -- event -> sink ---------------------------------------------------
    def _rel(self, full_path: str) -> str:
        if self.prefix != "/" and full_path.startswith(self.prefix):
            return full_path[len(self.prefix):] or "/"
        return full_path

    def _reader(self, full_path: str) -> Callable[[], bytes]:
        src = self.source

        def read() -> bytes:
            r = session().get(f"{src}{full_path}", timeout=300)
            r.raise_for_status()
            return r.content

        return read

    def apply(self, ev: dict) -> None:
        """Route one metadata event to the sink
        (replicator.go Replicate)."""
        if self.exclude_signature and \
                self.exclude_signature in ev.get("signatures", []):
            self.skipped += 1
            return
        old, new = ev.get("old_entry"), ev.get("new_entry")
        if old is None and new is None:
            return
        if new is None:  # delete
            e = Entry.from_dict(old)
            self.sink.delete_entry(self._rel(e.full_path),
                                   e.is_directory)
        elif old is None:  # create
            e = Entry.from_dict(new)
            self.sink.create_entry(self._rel(e.full_path), e,
                                   self._reader(e.full_path))
        else:  # update / rename
            oe, ne = Entry.from_dict(old), Entry.from_dict(new)
            if oe.full_path != ne.full_path:
                self.sink.delete_entry(self._rel(oe.full_path),
                                       oe.is_directory)
            self.sink.update_entry(self._rel(ne.full_path), ne,
                                   self._reader(ne.full_path))
        self.applied += 1
