"""Continuous filer metadata backup into a local store.

Equivalent of /root/reference/weed/command/filer_meta_backup.go: apply
the metadata event stream to a FilerStore (sqlite here), checkpointing
the last-applied event so restarts resume. The result is a queryable
point-in-time copy of the namespace (not the file bytes — that is the
data replication sinks' job).
"""
from __future__ import annotations

import asyncio
import json
import threading

import requests

from ..filer.entry import Entry
from ..filer.filerstore import make_store


class FilerMetaBackup:
    def __init__(self, source_filer: str, backup_path: str,
                 path_prefix: str = "/"):
        self.source = source_filer.rstrip("/") \
            if source_filer.startswith("http") else \
            f"http://{source_filer}"
        self.prefix = path_prefix
        self.store = make_store("sqlite", path=backup_path)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.applied = 0

    def _offset(self) -> int:
        v = self.store.kv_get("meta_backup/offset")
        return int(v) if v else 0

    def _save_offset(self, ts_ns: int) -> None:
        self.store.kv_put("meta_backup/offset", str(ts_ns).encode())

    def apply(self, ev: dict) -> None:
        old, new = ev.get("old_entry"), ev.get("new_entry")
        if new is None and old is not None:
            self.store.delete_entry(old["full_path"])
        elif new is not None:
            if old is not None and old["full_path"] != new["full_path"]:
                self.store.delete_entry(old["full_path"])
            self.store.insert_entry(Entry.from_dict(new))
        self.applied += 1

    def start(self) -> None:
        self._stop.clear()
        self._loop = None
        self._task = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        loop, task = self._loop, self._task
        if loop is not None and task is not None and loop.is_running():
            loop.call_soon_threadsafe(task.cancel)
        if self._thread is not None:
            self._thread.join(timeout=10)

    def _run(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        self._task = self._loop.create_task(self._pump())
        try:
            self._loop.run_until_complete(self._task)
        except asyncio.CancelledError:
            pass
        finally:
            try:
                self._loop.run_until_complete(
                    self._loop.shutdown_asyncgens())
            finally:
                self._loop.close()

    async def _pump(self) -> None:
        import aiohttp

        while not self._stop.is_set():
            url = self.source.replace("http", "ws", 1) + \
                "/ws/meta_subscribe"
            try:
                async with aiohttp.ClientSession() as sess:
                    async with sess.ws_connect(
                            url,
                            params={"path_prefix": self.prefix,
                                    "since_ns": str(self._offset())},
                            heartbeat=30) as ws:
                        async for msg in ws:
                            if self._stop.is_set():
                                return
                            if msg.type != aiohttp.WSMsgType.TEXT:
                                break
                            ev = json.loads(msg.data)
                            self.apply(ev)
                            self._save_offset(ev["ts_ns"])
            except Exception:
                pass
            await asyncio.sleep(0.5)

    # -- restore/query ---------------------------------------------------
    def find_entry(self, path: str) -> Entry | None:
        return self.store.find_entry(path)

    def list_entries(self, dirpath: str) -> list[Entry]:
        return self.store.list_directory_entries(dirpath)
