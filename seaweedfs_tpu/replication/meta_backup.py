"""Continuous filer metadata backup into a local store.

Equivalent of /root/reference/weed/command/filer_meta_backup.go: apply
the metadata event stream to a FilerStore (sqlite here), checkpointing
the last-applied event so restarts resume. The result is a queryable
point-in-time copy of the namespace (not the file bytes — that is the
data replication sinks' job).
"""
from __future__ import annotations

from ..filer.entry import Entry
from ..filer.filerstore import make_store
from ..rpc.meta_subscriber import MetaSubscriber


class FilerMetaBackup:
    def __init__(self, source_filer: str, backup_path: str,
                 path_prefix: str = "/"):
        self.source = source_filer.rstrip("/") \
            if source_filer.startswith("http") else \
            f"http://{source_filer}"
        self.prefix = path_prefix
        self.store = make_store("sqlite", path=backup_path)
        self._sub: MetaSubscriber | None = None
        self.applied = 0

    def _offset(self) -> int:
        v = self.store.kv_get("meta_backup/offset")
        return int(v) if v else 0

    def _save_offset(self, ts_ns: int) -> None:
        self.store.kv_put("meta_backup/offset", str(ts_ns).encode())

    def apply(self, ev: dict) -> None:
        old, new = ev.get("old_entry"), ev.get("new_entry")
        if new is None and old is not None:
            self.store.delete_entry(old["full_path"])
        elif new is not None:
            if old is not None and old["full_path"] != new["full_path"]:
                self.store.delete_entry(old["full_path"])
            self.store.insert_entry(Entry.from_dict(new))
        self.applied += 1

    def _handle(self, ev: dict) -> None:
        self.apply(ev)
        self._save_offset(ev["ts_ns"])

    def start(self) -> None:
        self._sub = MetaSubscriber(self.source, self.prefix,
                                   self._handle, since_fn=self._offset)
        self._sub.start()

    def stop(self) -> None:
        if self._sub is not None:
            self._sub.stop()
            self._sub = None

    # -- restore/query ---------------------------------------------------
    def find_entry(self, path: str) -> Entry | None:
        return self.store.find_entry(path)

    def list_entries(self, dirpath: str) -> list[Entry]:
        return self.store.list_directory_entries(dirpath)
