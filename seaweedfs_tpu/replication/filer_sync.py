"""Active-active filer <-> filer synchronization.

Equivalent of /root/reference/weed/command/filer_sync.go: each side
runs a replicator whose sink writes into the peer tagged with the
SOURCE filer's signature; events whose signature list already contains
the peer's signature are skipped, so an entry written on A and synced
to B does not bounce back to A (signature loop prevention,
filer_sync.go's clientId/signature dance).
"""
from __future__ import annotations

from .replicator import Replicator
from .sink import FilerSink
from ..rpc.httpclient import session


def _signature_of(filer_url: str) -> int:
    url = filer_url.rstrip("/") if filer_url.startswith("http") \
        else f"http://{filer_url}"
    return int(session().get(f"{url}/status",
                            timeout=10).json()["signature"])


class FilerSync:
    """Bidirectional (or one-way) sync between two filers."""

    def __init__(self, filer_a: str, filer_b: str,
                 path_prefix: str = "/", both_ways: bool = True):
        sig_a = _signature_of(filer_a)
        sig_b = _signature_of(filer_b)
        # A -> B: skip events B has already seen; tag writes into B
        # with A's signature so B's own events name A as origin
        self.a_to_b = Replicator(
            filer_a,
            FilerSink(filer_b, dest_path=path_prefix, signature=sig_a),
            path_prefix=path_prefix,
            offset_key=f"sync/{sig_b}/offset",
            exclude_signature=sig_b)
        self.b_to_a = Replicator(
            filer_b,
            FilerSink(filer_a, dest_path=path_prefix, signature=sig_b),
            path_prefix=path_prefix,
            offset_key=f"sync/{sig_a}/offset",
            exclude_signature=sig_a) if both_ways else None

    def start(self) -> None:
        self.a_to_b.start()
        if self.b_to_a is not None:
            self.b_to_a.start()

    def stop(self) -> None:
        self.a_to_b.stop()
        if self.b_to_a is not None:
            self.b_to_a.stop()
