from .replicator import Replicator
from .sink import FilerSink, LocalSink, S3Sink, make_sink

__all__ = ["Replicator", "FilerSink", "LocalSink", "S3Sink",
           "make_sink"]
