"""Replication sinks: where filer metadata events get mirrored.

Equivalent of /root/reference/weed/replication/sink/: filersink,
localsink, s3sink, plus gcssink/azuresink over the in-tree REST
remote clients and b2sink over the native B2 API — every cloud sink
speaks its real wire protocol, no SDKs. A sink receives entry
lifecycle callbacks; file content is provided by a reader callable so
sinks don't need to know the source's chunk layout.
"""
from __future__ import annotations

import os
from typing import Callable

import requests

from ..filer.entry import Entry
from ..rpc.httpclient import session

DataReader = Callable[[], bytes]


def _prefixed_key(prefix: str, path: str) -> str:
    """Object key for a filer path under an optional key prefix —
    shared by every flat-keyspace sink."""
    key = path.lstrip("/")
    return f"{prefix}/{key}" if prefix else key


class ReplicationSink:
    name = "base"

    def create_entry(self, path: str, entry: Entry,
                     read_data: DataReader) -> None:
        raise NotImplementedError

    def update_entry(self, path: str, entry: Entry,
                     read_data: DataReader) -> None:
        self.create_entry(path, entry, read_data)

    def delete_entry(self, path: str, is_directory: bool) -> None:
        raise NotImplementedError


class FilerSink(ReplicationSink):
    """Mirror into another filer over its HTTP API
    (replication/sink/filersink/)."""

    name = "filer"

    def __init__(self, filer_url: str, dest_path: str = "/",
                 signature: int = 0):
        self.filer_url = filer_url.rstrip("/") \
            if filer_url.startswith("http") else f"http://{filer_url}"
        self.dest = dest_path.rstrip("/")
        # signature of the SOURCE filer: carried on writes so the
        # target's events name the origin (active-active loop guard)
        self.signature = signature

    def _url(self, path: str) -> str:
        return f"{self.filer_url}{self.dest}{path}"

    def _params(self) -> dict:
        return {"signatures": str(self.signature)} if self.signature \
            else {}

    def create_entry(self, path: str, entry: Entry,
                     read_data: DataReader) -> None:
        if entry.is_directory:
            session().put(self._url(path),
                         params={"mkdir": "1", **self._params()},
                         timeout=30).raise_for_status()
            return
        params = self._params()
        r = session().put(self._url(path), data=read_data(),
                         params=params,
                         headers={"Content-Type": entry.mime or
                                  "application/octet-stream"},
                         timeout=300)
        r.raise_for_status()

    def delete_entry(self, path: str, is_directory: bool) -> None:
        params = {"recursive": "true", **self._params()}
        session().delete(self._url(path), params=params, timeout=60)


class LocalSink(ReplicationSink):
    """Mirror into a local directory (replication/sink/localsink/)."""

    name = "local"

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, path: str) -> str:
        return os.path.join(self.dir, path.lstrip("/"))

    def create_entry(self, path: str, entry: Entry,
                     read_data: DataReader) -> None:
        target = self._path(path)
        if entry.is_directory:
            os.makedirs(target, exist_ok=True)
            return
        os.makedirs(os.path.dirname(target), exist_ok=True)
        tmp = target + ".tmp"
        with open(tmp, "wb") as f:
            f.write(read_data())
        os.replace(tmp, target)

    def delete_entry(self, path: str, is_directory: bool) -> None:
        target = self._path(path)
        try:
            if is_directory:
                import shutil

                shutil.rmtree(target, ignore_errors=True)
            else:
                os.remove(target)
        except FileNotFoundError:
            pass


class S3Sink(ReplicationSink):
    """Mirror into an S3-compatible endpoint (replication/sink/s3sink/).
    Targets this build's own gateway or any endpoint that accepts
    anonymous/open PUTs; SigV4 credentials optional."""

    name = "s3"

    def __init__(self, endpoint: str, bucket: str, prefix: str = "",
                 access_key: str = "", secret_key: str = ""):
        self.endpoint = endpoint.rstrip("/") \
            if endpoint.startswith("http") else f"http://{endpoint}"
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self.access_key = access_key
        self.secret_key = secret_key

    def _key(self, path: str) -> str:
        return _prefixed_key(self.prefix, path)

    def _headers(self, method: str, url: str, payload: bytes) -> dict:
        if not self.access_key:
            return {}
        from ..s3.sigv4_client import sign_headers

        return sign_headers(method, url, self.access_key,
                            self.secret_key, payload)

    def create_entry(self, path: str, entry: Entry,
                     read_data: DataReader) -> None:
        if entry.is_directory:
            return  # keys are flat
        url = f"{self.endpoint}/{self.bucket}/{self._key(path)}"
        data = read_data()
        r = session().put(url, data=data,
                         headers=self._headers("PUT", url, data),
                         timeout=300)
        r.raise_for_status()

    def delete_entry(self, path: str, is_directory: bool) -> None:
        if is_directory:
            return
        url = f"{self.endpoint}/{self.bucket}/{self._key(path)}"
        session().delete(url, headers=self._headers("DELETE", url, b""),
                        timeout=60)


class _RemoteClientSink(ReplicationSink):
    """Sink over a RemoteStorageClient: GCS and Azure replicate
    through the same in-tree REST clients the remote-mount tier uses
    (gcs_storage_client.go / azure_storage_client.go are likewise
    shared by the reference's sinks)."""

    def __init__(self, client, prefix: str = ""):
        self._c = client
        self.prefix = prefix.strip("/")

    def _key(self, path: str) -> str:
        return _prefixed_key(self.prefix, path)

    def create_entry(self, path: str, entry: Entry,
                     read_data: DataReader) -> None:
        if entry.is_directory:
            return  # object keys are flat
        self._c.write_file(self._key(path), read_data())

    def delete_entry(self, path: str, is_directory: bool) -> None:
        if is_directory:
            return
        self._c.delete_file(self._key(path))


class GcsSink(_RemoteClientSink):
    """replication/sink/gcssink/gcs_sink.go:18 over the JSON API."""

    name = "gcs"

    def __init__(self, bucket: str, prefix: str = "", **conf):
        from ..remote_storage.gcs_client import GcsRemoteClient

        super().__init__(GcsRemoteClient(bucket=bucket, **conf), prefix)


class AzureSink(_RemoteClientSink):
    """replication/sink/azuresink/azure_sink.go:20 over Blob REST."""

    name = "azure"

    def __init__(self, container: str, prefix: str = "", **conf):
        from ..remote_storage.azure_client import AzureRemoteClient

        super().__init__(AzureRemoteClient(container=container, **conf),
                         prefix)


class B2Sink(ReplicationSink):
    """replication/sink/b2sink/b2_sink.go:17 over the native B2 API
    (b2_authorize_account / b2_get_upload_url / b2_upload_file /
    b2_hide_file) — no blazer SDK."""

    name = "b2"

    def __init__(self, bucket: str, key_id: str, application_key: str,
                 prefix: str = "",
                 api_base: str = "https://api.backblazeb2.com"):
        self.bucket_name = bucket
        self.prefix = prefix.strip("/")
        self._key_id = key_id
        self._app_key = application_key
        self._api_base = api_base.rstrip("/")
        self._sess = requests.Session()
        self._authorize()
        r = self._api("b2_list_buckets",
                      {"accountId": self.account_id,
                       "bucketName": bucket})
        buckets = r.json().get("buckets", [])
        if not buckets:
            raise KeyError(f"b2 bucket {bucket!r} not found")
        self.bucket_id = buckets[0]["bucketId"]

    def _authorize(self) -> None:
        r = self._sess.get(
            f"{self._api_base}/b2api/v2/b2_authorize_account",
            auth=(self._key_id, self._app_key), timeout=30)
        r.raise_for_status()
        d = r.json()
        self.api_url = d["apiUrl"].rstrip("/")
        self.token = d["authorizationToken"]
        self.account_id = d["accountId"]

    def _api(self, verb: str, body: dict) -> requests.Response:
        """POST a b2api call; a 401 re-authorizes once (B2 tokens
        expire within 24h — a long-running replicator must renew)."""
        for attempt in (0, 1):
            r = self._sess.post(
                f"{self.api_url}/b2api/v2/{verb}", json=body,
                headers={"Authorization": self.token}, timeout=60)
            if r.status_code == 401 and attempt == 0:
                self._authorize()
                continue
            return r
        return r

    def _key(self, path: str) -> str:
        return _prefixed_key(self.prefix, path)

    def create_entry(self, path: str, entry: Entry,
                     read_data: DataReader) -> None:
        if entry.is_directory:
            return
        import hashlib
        import urllib.parse

        data = read_data()
        # B2's documented contract: uploads ROUTINELY fail with 503
        # (pod busy) or 401 (expired upload token) and the client must
        # fetch a fresh upload URL and retry — blazer, which the
        # reference uses, does exactly this
        import time as _time

        for attempt in range(3):
            r = self._api("b2_get_upload_url",
                          {"bucketId": self.bucket_id})
            if r.status_code == 503 and attempt < 2:
                _time.sleep(0.2 * (attempt + 1))
                continue
            r.raise_for_status()
            up = r.json()
            r = self._sess.post(
                up["uploadUrl"], data=data, headers={
                    "Authorization": up["authorizationToken"],
                    "X-Bz-File-Name": urllib.parse.quote(
                        self._key(path)),
                    "Content-Type": entry.mime or "b2/x-auto",
                    "X-Bz-Content-Sha1": hashlib.sha1(data).hexdigest(),
                }, timeout=300)
            if r.status_code in (401, 503) and attempt < 2:
                _time.sleep(0.2 * (attempt + 1))
                continue
            r.raise_for_status()
            return

    def delete_entry(self, path: str, is_directory: bool) -> None:
        if is_directory:
            return
        r = self._api("b2_hide_file",
                      {"bucketId": self.bucket_id,
                       "fileName": self._key(path)})
        if r.status_code == 200:
            return
        try:
            code = r.json().get("code")
        except ValueError:  # non-JSON error body (proxy, LB)
            code = None
        if code not in ("no_such_file", "already_hidden"):
            r.raise_for_status()


def make_sink(kind: str, **kwargs) -> ReplicationSink:
    sinks = {"filer": FilerSink, "local": LocalSink, "s3": S3Sink,
             "gcs": GcsSink, "azure": AzureSink, "b2": B2Sink}
    if kind not in sinks:
        raise KeyError(f"unknown sink {kind!r}; have {sorted(sinks)}")
    return sinks[kind](**kwargs)
