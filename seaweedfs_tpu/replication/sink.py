"""Replication sinks: where filer metadata events get mirrored.

Equivalent of /root/reference/weed/replication/sink/ (filersink,
localsink, s3sink — the gcs/azure/b2 sinks are the same interface over
cloud SDKs not present in this environment, so they register as
unavailable rather than silently half-working). A sink receives entry
lifecycle callbacks; file content is provided by a reader callable so
sinks don't need to know the source's chunk layout.
"""
from __future__ import annotations

import os
from typing import Callable

import requests

from ..filer.entry import Entry

DataReader = Callable[[], bytes]


class ReplicationSink:
    name = "base"

    def create_entry(self, path: str, entry: Entry,
                     read_data: DataReader) -> None:
        raise NotImplementedError

    def update_entry(self, path: str, entry: Entry,
                     read_data: DataReader) -> None:
        self.create_entry(path, entry, read_data)

    def delete_entry(self, path: str, is_directory: bool) -> None:
        raise NotImplementedError


class FilerSink(ReplicationSink):
    """Mirror into another filer over its HTTP API
    (replication/sink/filersink/)."""

    name = "filer"

    def __init__(self, filer_url: str, dest_path: str = "/",
                 signature: int = 0):
        self.filer_url = filer_url.rstrip("/") \
            if filer_url.startswith("http") else f"http://{filer_url}"
        self.dest = dest_path.rstrip("/")
        # signature of the SOURCE filer: carried on writes so the
        # target's events name the origin (active-active loop guard)
        self.signature = signature

    def _url(self, path: str) -> str:
        return f"{self.filer_url}{self.dest}{path}"

    def _params(self) -> dict:
        return {"signatures": str(self.signature)} if self.signature \
            else {}

    def create_entry(self, path: str, entry: Entry,
                     read_data: DataReader) -> None:
        if entry.is_directory:
            requests.put(self._url(path),
                         params={"mkdir": "1", **self._params()},
                         timeout=30).raise_for_status()
            return
        params = self._params()
        r = requests.put(self._url(path), data=read_data(),
                         params=params,
                         headers={"Content-Type": entry.mime or
                                  "application/octet-stream"},
                         timeout=300)
        r.raise_for_status()

    def delete_entry(self, path: str, is_directory: bool) -> None:
        params = {"recursive": "true", **self._params()}
        requests.delete(self._url(path), params=params, timeout=60)


class LocalSink(ReplicationSink):
    """Mirror into a local directory (replication/sink/localsink/)."""

    name = "local"

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, path: str) -> str:
        return os.path.join(self.dir, path.lstrip("/"))

    def create_entry(self, path: str, entry: Entry,
                     read_data: DataReader) -> None:
        target = self._path(path)
        if entry.is_directory:
            os.makedirs(target, exist_ok=True)
            return
        os.makedirs(os.path.dirname(target), exist_ok=True)
        tmp = target + ".tmp"
        with open(tmp, "wb") as f:
            f.write(read_data())
        os.replace(tmp, target)

    def delete_entry(self, path: str, is_directory: bool) -> None:
        target = self._path(path)
        try:
            if is_directory:
                import shutil

                shutil.rmtree(target, ignore_errors=True)
            else:
                os.remove(target)
        except FileNotFoundError:
            pass


class S3Sink(ReplicationSink):
    """Mirror into an S3-compatible endpoint (replication/sink/s3sink/).
    Targets this build's own gateway or any endpoint that accepts
    anonymous/open PUTs; SigV4 credentials optional."""

    name = "s3"

    def __init__(self, endpoint: str, bucket: str, prefix: str = "",
                 access_key: str = "", secret_key: str = ""):
        self.endpoint = endpoint.rstrip("/") \
            if endpoint.startswith("http") else f"http://{endpoint}"
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self.access_key = access_key
        self.secret_key = secret_key

    def _key(self, path: str) -> str:
        key = path.lstrip("/")
        return f"{self.prefix}/{key}" if self.prefix else key

    def _headers(self, method: str, url: str, payload: bytes) -> dict:
        if not self.access_key:
            return {}
        from ..s3.sigv4_client import sign_headers

        return sign_headers(method, url, self.access_key,
                            self.secret_key, payload)

    def create_entry(self, path: str, entry: Entry,
                     read_data: DataReader) -> None:
        if entry.is_directory:
            return  # keys are flat
        url = f"{self.endpoint}/{self.bucket}/{self._key(path)}"
        data = read_data()
        r = requests.put(url, data=data,
                         headers=self._headers("PUT", url, data),
                         timeout=300)
        r.raise_for_status()

    def delete_entry(self, path: str, is_directory: bool) -> None:
        if is_directory:
            return
        url = f"{self.endpoint}/{self.bucket}/{self._key(path)}"
        requests.delete(url, headers=self._headers("DELETE", url, b""),
                        timeout=60)


def make_sink(kind: str, **kwargs) -> ReplicationSink:
    sinks = {"filer": FilerSink, "local": LocalSink, "s3": S3Sink}
    if kind not in sinks:
        raise KeyError(f"unknown sink {kind!r}; have {sorted(sinks)} "
                       "(gcs/azure/b2 need cloud SDKs absent here)")
    return sinks[kind](**kwargs)
