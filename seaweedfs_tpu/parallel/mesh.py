"""Device-mesh helpers for the codec data plane.

The TPU-native analogue of the reference's parallelism axes (SURVEY.md
section 2.10): stripes of independent volumes ride a `vol` (data-parallel)
mesh axis, and the columns of a stripe — the long-sequence dimension of
this domain — ride a `col` (sequence-parallel) axis. Encode/rebuild are
column-local so they scale linearly over ICI; scrub aggregation reduces
with psum collectives over both axes.
"""
from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

VOL_AXIS = "vol"
COL_AXIS = "col"

# production mesh shape knobs (-ec.mesh.devices / -ec.mesh.col set
# these; the MeshCodec reads them at construction)
DEVICES_ENV = "SEAWEEDFS_TPU_EC_MESH_DEVICES"
COL_ENV = "SEAWEEDFS_TPU_EC_MESH_COL"


def mesh_config() -> tuple[int | None, int | None]:
    """(n_devices, col_parallel) from the environment; None means the
    defaults (all local devices / the make_mesh heuristic). Garbage
    values are ignored, not fatal — a bad flag must not take down a
    volume server whose CPU codec still works."""
    def _positive_int(name: str) -> int | None:
        v = os.environ.get(name, "").strip()
        if not v:
            return None
        try:
            n = int(v)
        except ValueError:
            return None
        return n if n > 0 else None

    return _positive_int(DEVICES_ENV), _positive_int(COL_ENV)


def describe(mesh: Mesh) -> dict:
    """Operator-facing mesh geometry for /debug/ec and the probe
    fingerprint: device count, (vol, col) shape, platform."""
    vol, col = (int(x) for x in mesh.devices.shape)
    first = mesh.devices.flat[0]
    return {"devices": int(mesh.devices.size), "vol": vol, "col": col,
            "platform": getattr(first, "platform", "unknown")}


def make_mesh(n_devices: int | None = None,
              col_parallel: int | None = None) -> Mesh:
    """A (vol, col) mesh over the first n devices.

    col_parallel defaults to 2 when n is even and > 1 (so both axes are
    exercised), else 1.
    """
    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    if col_parallel is None:
        col_parallel = 2 if (n % 2 == 0 and n > 1) else 1
    if n % col_parallel:
        raise ValueError(f"{n} devices not divisible by col={col_parallel}")
    grid = np.array(devs[:n]).reshape(n // col_parallel, col_parallel)
    return Mesh(grid, (VOL_AXIS, COL_AXIS))


def stripe_sharding(mesh: Mesh) -> NamedSharding:
    """(batch, k, cols) stripes: batch over vol, cols over col."""
    return NamedSharding(mesh, P(VOL_AXIS, None, COL_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_mesh(arr: np.ndarray, mesh: Mesh, batch_axis: int = 0,
                col_axis: int = 2) -> tuple[np.ndarray, tuple[int, int]]:
    """Zero-pad (batch, k, cols)-shaped host data so both sharded dims
    divide the mesh — NamedSharding requires divisibility, and real
    volumes rarely oblige. Returns (padded, (orig_batch, orig_cols));
    callers slice outputs back with those. Zero stripes encode to zero
    parity, so padding never perturbs scrub results."""
    vol, col = mesh.devices.shape
    b, c = arr.shape[batch_axis], arr.shape[col_axis]
    pb = -(-b // vol) * vol
    pc = -(-c // col) * col
    if (pb, pc) == (b, c):
        return arr, (b, c)
    shape = list(arr.shape)
    shape[batch_axis], shape[col_axis] = pb, pc
    out = np.zeros(shape, dtype=arr.dtype)
    sl = [slice(None)] * arr.ndim
    sl[batch_axis], sl[col_axis] = slice(0, b), slice(0, c)
    out[tuple(sl)] = np.asarray(arr)
    return out, (b, c)
