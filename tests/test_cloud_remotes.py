"""GCS / Azure / B2 over raw REST: remote-storage clients, replication
sinks, and a fake-GCS remote.mount end to end. Reference slots:
/root/reference/weed/remote_storage/gcs/gcs_storage_client.go:21,
azure/azure_storage_client.go:23, replication/sink/gcssink/gcs_sink.go:18,
azuresink/azure_sink.go:20, b2sink/b2_sink.go:17.
"""
import json
import shutil
import subprocess

import pytest
import requests

from seaweedfs_tpu.filer.entry import Entry
from seaweedfs_tpu.remote_storage import make_client
from seaweedfs_tpu.replication.sink import make_sink

from .minicloud import MiniAzure, MiniB2, MiniGcs


@pytest.fixture(scope="module")
def gcs():
    s = MiniGcs()
    s.store.buckets["pics"] = {}
    yield s
    s.close()


@pytest.fixture(scope="module")
def azure():
    s = MiniAzure()
    s.store.buckets["pics"] = {}
    yield s
    s.close()


@pytest.fixture(scope="module")
def b2():
    s = MiniB2()
    s.store.buckets["pics"] = {}
    yield s
    s.close()


# -- clients ------------------------------------------------------------

CLIENT_CONFS = {
    "gcs": lambda s: {"type": "gcs", "bucket": "pics",
                      "endpoint": s.endpoint},
    "azure": lambda s: {"type": "azure", "account": s.account,
                        "key": s.key, "container": "pics",
                        "endpoint": s.endpoint},
}


@pytest.mark.parametrize("kind", ["gcs", "azure"])
def test_client_roundtrip(kind, request):
    server = request.getfixturevalue(kind)
    server.store.buckets["pics"].clear()
    c = make_client(CLIENT_CONFS[kind](server))
    c.write_file("a/b.txt", b"hello-cloud")
    assert c.read_file("a/b.txt") == b"hello-cloud"
    assert c.read_file("a/b.txt", offset=6, size=5) == b"cloud"
    assert c.head("a/b.txt").size == 11
    assert c.head("missing") is None
    c.write_file("a/c.txt", b"x")
    c.write_file("z.txt", b"y")
    assert [e.key for e in c.traverse()] == ["a/b.txt", "a/c.txt",
                                             "z.txt"]
    assert [e.key for e in c.traverse(prefix="a/")] == ["a/b.txt",
                                                        "a/c.txt"]
    assert "pics" in c.list_buckets()
    c.delete_file("a/b.txt")
    assert c.head("a/b.txt") is None
    c.delete_file("a/b.txt")  # idempotent


def test_azure_bad_key_rejected(azure):
    import base64

    c = make_client({"type": "azure", "account": azure.account,
                     "key": base64.b64encode(b"wrongkey").decode(),
                     "container": "pics", "endpoint": azure.endpoint})
    with pytest.raises(requests.HTTPError):
        c.write_file("x", b"y")


# -- RS256 (service-account JWT signing) --------------------------------

def test_rs256_matches_openssl(tmp_path):
    openssl = shutil.which("openssl")
    if not openssl:
        pytest.skip("no openssl binary")
    key_pem = tmp_path / "k.pem"
    subprocess.run([openssl, "genrsa", "-out", str(key_pem), "2048"],
                   check=True, capture_output=True)
    msg = b"header.payload"
    msg_f = tmp_path / "msg"
    msg_f.write_bytes(msg)
    expected = subprocess.run(
        [openssl, "dgst", "-sha256", "-sign", str(key_pem),
         str(msg_f)], check=True, capture_output=True).stdout

    from seaweedfs_tpu.utils import rs256

    assert rs256.sign(key_pem.read_text(), msg) == expected


# -- sinks --------------------------------------------------------------

def _file_entry(mime=""):
    return Entry(full_path="/docs/report.bin", mime=mime,
                 chunks=[])


@pytest.mark.parametrize("kind", ["gcs", "azure", "b2"])
def test_sink_create_update_delete(kind, request):
    server = request.getfixturevalue(kind)
    server.store.buckets["pics"].clear()
    if kind == "gcs":
        sink = make_sink("gcs", bucket="pics", prefix="backup",
                         endpoint=server.endpoint)
    elif kind == "azure":
        sink = make_sink("azure", container="pics", prefix="backup",
                         account=server.account, key=server.key,
                         endpoint=server.endpoint)
    else:
        sink = make_sink("b2", bucket="pics", prefix="backup",
                         key_id="kid", application_key="akey",
                         api_base=server.endpoint)
    sink.create_entry("/docs/report.bin", _file_entry(),
                      lambda: b"v1-bytes")
    assert server.store.buckets["pics"]["backup/docs/report.bin"][0] \
        == b"v1-bytes"
    sink.update_entry("/docs/report.bin", _file_entry(),
                      lambda: b"v2-bytes")
    assert server.store.buckets["pics"]["backup/docs/report.bin"][0] \
        == b"v2-bytes"
    # directories are flat no-ops
    sink.create_entry("/docs", Entry(full_path="/docs", mode=0o40755),
                      lambda: b"")
    sink.delete_entry("/docs/report.bin", is_directory=False)
    assert "backup/docs/report.bin" not in server.store.buckets["pics"]
    sink.delete_entry("/docs/report.bin", is_directory=False)  # gone ok


def test_b2_bad_credentials(b2):
    with pytest.raises(requests.HTTPError):
        make_sink("b2", bucket="pics", key_id="kid",
                  application_key="wrong", api_base=b2.endpoint)


# -- fake-GCS bucket mounted end to end ---------------------------------

@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    from seaweedfs_tpu.server.cluster import Cluster

    c = Cluster(str(tmp_path_factory.mktemp("gcs_mount")),
                n_volume_servers=1, volume_size_limit=8 << 20,
                with_filer=True)
    yield c
    c.stop()


def test_remote_mount_fake_gcs(cluster, gcs):
    from seaweedfs_tpu.shell.env import CommandEnv
    from seaweedfs_tpu.shell.repl import run_command

    gcs.store.buckets["pics"] = {}
    c = make_client({"type": "gcs", "bucket": "pics",
                     "endpoint": gcs.endpoint})
    c.write_file("photos/a.jpg", b"JPEG" * 100)
    c.write_file("readme.txt", b"top-level")

    env = CommandEnv(cluster.master_url, filer_url=cluster.filer_url)
    env.acquire_lock()
    try:
        out = run_command(
            env, f"remote.configure -name=gcloud -type=gcs "
                 f"-bucket=pics -endpoint={gcs.endpoint}")
        assert out == {"gcloud": "gcs"}
        out = run_command(env, "remote.mount -dir=/gcs -remote=gcloud")
        assert out["created"] == 2

        # read-through GET serves the cloud bytes via the JSON API
        r = requests.get(f"{cluster.filer_url}/gcs/photos/a.jpg")
        assert r.status_code == 200 and r.content == b"JPEG" * 100
        r = requests.get(f"{cluster.filer_url}/gcs/readme.txt",
                         headers={"Range": "bytes=4-8"})
        assert r.status_code == 206 and r.content == b"level"

        # cache then uncache round-trips through cluster chunks
        out = run_command(env, "remote.cache -dir=/gcs")
        assert out["cached"] == 2
        meta = requests.get(f"{cluster.filer_url}/gcs/photos/a.jpg",
                            params={"meta": "1"}).json()
        assert meta["chunks"]
        out = run_command(env, "remote.uncache -dir=/gcs")
        assert out["uncached"] == 2

        # upstream change picked up by meta sync
        c.write_file("new.bin", b"fresh")
        c.delete_file("readme.txt")
        out = run_command(env, "remote.meta.sync -dir=/gcs")
        assert out["created"] == 1 and out["removed"] == 1
        assert requests.get(
            f"{cluster.filer_url}/gcs/new.bin").content == b"fresh"
        run_command(env, "remote.unmount -dir=/gcs")
    finally:
        env.close()


def test_azure_shared_key_string_to_sign_vector():
    """Non-circular signature check: the string-to-sign for a fixed
    request is spelled out literally per the published SharedKey
    scheme (method, 11 standard headers with zero Content-Length
    blanked, canonicalized x-ms-* headers, /account/path + sorted
    query lines) and HMAC'd independently of the production code."""
    import base64 as b64
    import hashlib as hl
    import hmac as hm

    from seaweedfs_tpu.remote_storage.azure_client import \
        shared_key_signature

    key = b64.b64encode(b"0123456789abcdef").decode()
    headers = {"x-ms-date": "Thu, 30 Jul 2026 12:00:00 GMT",
               "x-ms-version": "2020-10-02",
               "x-ms-blob-type": "BlockBlob",
               "Content-Length": "0",
               "Range": "bytes=0-99"}
    query = {"restype": "container", "comp": "list", "prefix": ""}
    expected_sts = (
        "GET\n"        # method
        "\n\n"         # content-encoding, content-language
        "\n"           # content-length: "0" canonicalizes to empty
        "\n\n"         # content-md5, content-type
        "\n"           # date (always empty; x-ms-date rules)
        "\n\n\n\n"     # if-modified/match/none-match/unmodified
        "bytes=0-99\n"  # range
        "x-ms-blob-type:BlockBlob\n"
        "x-ms-date:Thu, 30 Jul 2026 12:00:00 GMT\n"
        "x-ms-version:2020-10-02\n"
        "/myacct/pics/a b.txt"
        "\ncomp:list\nprefix:\nrestype:container")
    mac = hm.new(b"0123456789abcdef", expected_sts.encode(),
                 hl.sha256).digest()
    expected = f"SharedKey myacct:{b64.b64encode(mac).decode()}"
    got = shared_key_signature("myacct", key, "GET", "/pics/a b.txt",
                               query, headers)
    assert got == expected


def test_b2_upload_retries_on_503(b2, monkeypatch):
    """B2's contract: uploads routinely 503; the sink must fetch a
    fresh upload URL and retry (what blazer does for the reference)."""
    from tests import minicloud

    b2.store.buckets["pics"].clear()
    sink = make_sink("b2", bucket="pics", key_id="kid",
                     application_key="akey", api_base=b2.endpoint)
    # first upload attempt answers 503, then the double recovers
    orig = minicloud._B2Handler.do_POST
    state = {"failed": False}

    def flaky(self):
        if self.path.startswith("/upload/") and not state["failed"]:
            state["failed"] = True
            # drain the body or the keep-alive connection desyncs
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            return self._json(503, {"code": "service_unavailable"})
        return orig(self)

    monkeypatch.setattr(minicloud._B2Handler, "do_POST", flaky)
    sink.create_entry("/r/x.bin", Entry(full_path="/r/x.bin"),
                      lambda: b"retried")
    assert state["failed"]
    assert b2.store.buckets["pics"]["r/x.bin"][0] == b"retried"
