"""Large-directory scaling (VERDICT r3 item 7, the redis3
kv_directory_children concern): a 100k-entry directory must page in
O(page) per listing call and absorb inserts at O(1)-ish cost.

weedkv (the embedded leveldb-class engine) gets the full 100k sweep;
the redis store gets a 20k sweep through the real RESP wire against
mini-redis (page fetches ride ONE MGET, not a GET per child).
"""
import time

import pytest

from seaweedfs_tpu.filer.entry import Entry
from seaweedfs_tpu.filer.filerstore import make_store

N_WEEDKV = 100_000
N_REDIS = 20_000
PAGE = 100


def _fill(store, n, dirpath="/big"):
    t0 = time.perf_counter()
    for i in range(n):
        store.insert_entry(Entry(full_path=f"{dirpath}/f{i:07d}"))
    return time.perf_counter() - t0


def _page_walk(store, n, dirpath="/big"):
    """Walk the whole directory page by page; returns (names_count,
    worst single-page seconds)."""
    seen = 0
    cursor = ""
    worst = 0.0
    while True:
        t0 = time.perf_counter()
        page = store.list_directory_entries(
            dirpath, start_from=cursor, inclusive=False, limit=PAGE)
        worst = max(worst, time.perf_counter() - t0)
        if not page:
            return seen, worst
        seen += len(page)
        cursor = page[-1].name


def test_weedkv_100k_directory(tmp_path):
    store = make_store("leveldb", path=str(tmp_path / "db"))
    try:
        fill_s = _fill(store, N_WEEDKV)
        # O(1)-ish inserts: 100k in well under a minute even on the
        # 1-core CI box (measured ~8s; 60s is the regression alarm)
        assert fill_s < 60, f"inserts took {fill_s:.1f}s"

        # single page from the MIDDLE of the keyspace: O(page), not
        # O(directory) — generous absolute bound, sharp vs the ~full
        # scan this would cost if paging re-filtered 100k entries
        t0 = time.perf_counter()
        page = store.list_directory_entries(
            "/big", start_from=f"f{N_WEEDKV // 2:07d}", inclusive=True,
            limit=PAGE)
        mid_s = time.perf_counter() - t0
        assert len(page) == PAGE
        assert page[0].name == f"f{N_WEEDKV // 2:07d}"
        assert mid_s < 0.25, f"mid-page listing took {mid_s * 1e3:.0f}ms"

        # prefix window deep in the directory
        pref = store.list_directory_entries("/big", prefix="f0099",
                                            limit=2000)
        assert len(pref) == 1000  # f0099000..f0099999

        # full pagination visits every entry exactly once
        seen, worst = _page_walk(store, N_WEEDKV)
        assert seen == N_WEEDKV
        assert worst < 0.25, f"worst page took {worst * 1e3:.0f}ms"

        # inserts stay cheap AFTER the directory is huge
        t0 = time.perf_counter()
        for i in range(1000):
            store.insert_entry(Entry(full_path=f"/big/zz{i:05d}"))
        tail_s = time.perf_counter() - t0
        assert tail_s < 2.0, f"late inserts took {tail_s:.2f}s"
    finally:
        store.close()


def test_redis_20k_directory():
    from .miniredis import MiniRedis

    srv = MiniRedis()  # serving from construction
    store = make_store("redis", port=srv.port)
    try:
        fill_s = _fill(store, N_REDIS)
        assert fill_s < 60, f"inserts took {fill_s:.1f}s"
        t0 = time.perf_counter()
        page = store.list_directory_entries(
            "/big", start_from=f"f{N_REDIS // 2:07d}", inclusive=True,
            limit=PAGE)
        mid_s = time.perf_counter() - t0
        assert len(page) == PAGE
        # one ZRANGEBYLEX + one MGET: two round trips per page
        assert mid_s < 0.25, f"mid-page listing took {mid_s * 1e3:.0f}ms"
        seen, worst = _page_walk(store, N_REDIS)
        assert seen == N_REDIS
        assert worst < 0.25, f"worst page took {worst * 1e3:.0f}ms"
    finally:
        store.close()
        srv.close()
