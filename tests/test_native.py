"""Native C++ kernel tests: GF(256) SIMD codec + CRC32C, bit-exact
against the numpy reference (the same golden contract every backend
must satisfy — SURVEY.md section 4 golden test).
"""
import numpy as np
import pytest

pytest.importorskip("ctypes")
nat = pytest.importorskip("seaweedfs_tpu.native")

if not nat.available():
    pytest.skip("no g++ and no prebuilt .so", allow_module_level=True)

from seaweedfs_tpu.ec.backend import ReedSolomon, get_backend
from seaweedfs_tpu.ops import codec_numpy


class TestGf256Kernel:
    @pytest.mark.parametrize("m,k,n", [
        (4, 10, 1), (4, 10, 15), (4, 10, 1024), (4, 10, 100_003),
        (14, 14, 4096), (1, 1, 33), (28, 4, 257),
    ])
    def test_matches_numpy(self, m, k, n):
        rng = np.random.default_rng(m * 1000 + n)
        coef = rng.integers(0, 256, (m, k)).astype(np.uint8)
        shards = rng.integers(0, 256, (k, n)).astype(np.uint8)
        assert np.array_equal(nat.coded_matmul(coef, shards),
                              codec_numpy.coded_matmul(coef, shards))

    def test_zero_and_identity_coefficients(self):
        shards = np.arange(30, dtype=np.uint8).reshape(3, 10)
        coef = np.array([[0, 0, 0], [1, 0, 0], [0, 1, 1]], dtype=np.uint8)
        out = nat.coded_matmul(coef, shards)
        assert np.array_equal(out[0], np.zeros(10, dtype=np.uint8))
        assert np.array_equal(out[1], shards[0])
        assert np.array_equal(out[2], shards[1] ^ shards[2])

    def test_simd_level_reported(self):
        assert nat.simd_level() in (0, 1, 2, 3)


class TestNativeBackendRegistry:
    def test_reed_solomon_round_trip(self):
        rs = ReedSolomon(10, 4, backend="native")
        rng = np.random.default_rng(7)
        data = rng.integers(0, 256, (10, 2048)).astype(np.uint8)
        parity = rs.encode(data)
        full = np.concatenate([data, parity])
        assert rs.verify(full)
        # lose 4 shards, rebuild
        present = {i: full[i] for i in range(14) if i not in (0, 3, 9, 12)}
        rec = rs.reconstruct(present)
        for sid in (0, 3, 9, 12):
            assert np.array_equal(rec[sid], full[sid]), sid

    def test_backend_matches_numpy_backend(self):
        rs_nat = ReedSolomon(10, 4, backend="native")
        rs_np = ReedSolomon(10, 4, backend="numpy")
        rng = np.random.default_rng(8)
        data = rng.integers(0, 256, (10, 999)).astype(np.uint8)
        assert np.array_equal(rs_nat.encode(data), rs_np.encode(data))

    def test_get_backend(self):
        assert get_backend("native").name == "native"


class TestCrc32c:
    def test_known_vector(self):
        assert nat.crc32c(b"123456789") == 0xE3069283

    def test_matches_google_crc32c(self):
        google_crc32c = pytest.importorskip("google_crc32c")
        rng = np.random.default_rng(9)
        data = rng.integers(0, 256, 100_001).astype(np.uint8).tobytes()
        assert nat.crc32c(data) == google_crc32c.value(data)

    def test_incremental(self):
        data = b"seaweedfs-tpu" * 1000
        whole = nat.crc32c(data)
        part = nat.crc32c(data[7000:], nat.crc32c(data[:7000]))
        assert part == whole

    def test_batch(self):
        rng = np.random.default_rng(10)
        rows = rng.integers(0, 256, (8, 513)).astype(np.uint8)
        crcs = nat.crc32c_batch(rows)
        for i in range(8):
            assert int(crcs[i]) == nat.crc32c(rows[i].tobytes())

    def test_empty(self):
        assert nat.crc32c(b"") == 0


class TestNativeDatScan:
    def test_rebuild_index_native_matches_python(self, tmp_path):
        import numpy as np

        from seaweedfs_tpu import native
        from seaweedfs_tpu.storage import idx as idxmod
        from seaweedfs_tpu.storage import needle as ndl
        from seaweedfs_tpu.storage.volume import Volume

        (tmp_path / "a").mkdir()
        v = Volume(str(tmp_path / "a"), "", 1, create=True)
        for i in range(50):
            v.append_needle(ndl.Needle(id=i + 1, cookie=i,
                                       data=bytes([i % 250]) * (i * 7)))
        for i in (3, 9, 30):
            v.delete_needle(i)
        v.close()
        import shutil
        shutil.copytree(str(tmp_path / "a"), str(tmp_path / "b"))

        va = Volume(str(tmp_path / "a"), "", 1)
        assert va._rebuild_index_native(va.file_name())  # native ran
        va.close()
        vb = Volume(str(tmp_path / "b"), "", 1)
        # force the pure-Python reference path
        orig = Volume._rebuild_index_native
        Volume._rebuild_index_native = lambda self, base: False
        try:
            vb.rebuild_index()
        finally:
            Volume._rebuild_index_native = orig
        vb.close()

        a = idxmod.read_index(str(tmp_path / "a" / "1.idx"))
        b = idxmod.read_index(str(tmp_path / "b" / "1.idx"))
        assert np.array_equal(a, b)

    def test_native_rebuild_truncates_torn_tail(self, tmp_path):
        from seaweedfs_tpu.storage import needle as ndl
        from seaweedfs_tpu.storage.volume import Volume

        v = Volume(str(tmp_path), "", 2, create=True)
        v.append_needle(ndl.Needle(id=1, cookie=1, data=b"whole"))
        v.close()
        dat = tmp_path / "2.dat"
        with open(dat, "ab") as f:
            f.write(b"\xde\xad\xbe")  # torn partial record
        v2 = Volume(str(tmp_path), "", 2)
        assert v2._rebuild_index_native(v2.file_name())
        assert v2.nm.file_count == 1
        assert v2.read_needle(1, cookie=1).data == b"whole"
        size_after = v2.dat.size()
        v2.close()
        import os
        assert os.path.getsize(dat) == size_after
        assert size_after % 8 == 0
