"""Workload-characterization telemetry plane end to end: the volume
store's heat taps and heartbeat payload (storage/store.py), the
gateway's tenant-demand sketches (utils/qos.py), the master-side
aggregator + recommend-only advisors (master/workload.py), the
/debug/workload + /debug-index + trace-alias endpoints
(server/master_server.py), and federation staleness/up gauges
(master/collector.py)."""
import time

import pytest
import requests

from seaweedfs_tpu.master.collector import MetricsFederator
from seaweedfs_tpu.master.workload import WorkloadAggregator
from seaweedfs_tpu.rpc.http import ServerThread
from seaweedfs_tpu.server.master_server import MasterServer
from seaweedfs_tpu.storage.store import Store
from seaweedfs_tpu.utils import metrics
from seaweedfs_tpu.utils import qos as _qos
from seaweedfs_tpu.utils import sketch as _sketch


@pytest.fixture(autouse=True)
def _telemetry_on():
    """Pin telemetry config; restore whatever the session had."""
    en, al, wi = _sketch.enabled(), _sketch.alpha(), _sketch.window()
    _sketch.configure(enabled=True, alpha=0.01, window=300.0)
    yield
    _sketch.configure(enabled=en, alpha=al, window=wi)


def _sk(values, alpha=0.01):
    s = _sketch.QuantileSketch(alpha=alpha)
    for v in values:
        s.record(v)
    return s.to_dict()


def _payload(gaps=(), sizes=(), fg=0.0, peak=0.0, vid="1"):
    kinds = {}
    if gaps:
        kinds["rg"] = _sk(gaps)
    if sizes:
        kinds["rs"] = _sk(sizes)
    return {"alpha": 0.01, "volumes": {vid: kinds} if kinds else {},
            "fg_bps": fg, "peak_bps": peak}


class _FakeFederator:
    def __init__(self, texts=()):
        import threading
        self._lock = threading.Lock()
        self._scraped = {f"gw:{i}": {"text": t, "ts": time.time(),
                                     "error": ""}
                         for i, t in enumerate(texts)}


class _FakeMaster:
    """Just the attributes the aggregator reads."""

    class tiering:
        seal_after_idle = 3600.0

    class watchdog:
        max_bytes_per_sec = 0.0

    def __init__(self, texts=()):
        self.federator = _FakeFederator(texts)


# ---------------------------------------------------------------------
# aggregator: ingest, merge, advisors, overrides
# ---------------------------------------------------------------------


class TestAggregator:
    def test_ingest_and_seal_advisor(self):
        agg = WorkloadAggregator(_FakeMaster(), seal_quantile=0.9,
                                 headroom=1.5)
        # two nodes, gap distributions around 100 s
        agg.ingest("n1", _payload(gaps=[100.0] * 90 + [1000.0] * 10))
        agg.ingest("n2", _payload(gaps=[100.0] * 100, vid="2"))
        snap = agg.snapshot()
        assert snap["nodes"]["n1"]["volumes"] == 1
        assert snap["cluster"]["read_gap"]["count"] == 200
        adv = snap["advisors"]["seal"]
        assert adv["current"] == 3600.0
        # p90 of the merged gaps ~ 100 s; × 1.5 headroom
        assert adv["recommended"] == pytest.approx(150.0, rel=0.05)
        assert adv["effective"] == adv["recommended"]
        assert adv["delta"] == pytest.approx(
            adv["recommended"] - 3600.0, abs=0.01)
        assert 0.0 < adv["coverage"] <= 1.0

    def test_per_volume_views_merge_across_nodes(self):
        agg = WorkloadAggregator(_FakeMaster())
        agg.ingest("n1", _payload(sizes=[4096.0] * 50, vid="7"))
        agg.ingest("n2", _payload(sizes=[4096.0] * 30, vid="7"))
        vols = agg.snapshot()["volumes"]
        assert vols["7"]["read_size"]["count"] == 80

    def test_stale_node_excluded_from_merge_but_shown(self):
        agg = WorkloadAggregator(_FakeMaster(), stale_after=5.0)
        agg.ingest("old", _payload(gaps=[10.0] * 20))
        agg._nodes["old"]["at"] = time.time() - 60.0  # age it
        agg.ingest("fresh", _payload(gaps=[99.0] * 20, vid="2"))
        snap = agg.snapshot()
        assert snap["nodes"]["old"]["stale"] is True
        assert snap["nodes"]["fresh"]["stale"] is False
        assert snap["cluster"]["read_gap"]["count"] == 20  # fresh only

    def test_forget_drops_node(self):
        agg = WorkloadAggregator(_FakeMaster())
        agg.ingest("n1", _payload(gaps=[1.0]))
        agg.forget("n1")
        assert agg.snapshot()["nodes"] == {}

    def test_junk_payloads_ignored(self):
        agg = WorkloadAggregator(_FakeMaster())
        agg.ingest("n1", "not a dict")
        agg.ingest("n2", {"volumes": {"1": {"rg": "junk",
                                            "zz": {"a": 0.01}}}})
        snap = agg.snapshot()
        assert "n1" not in snap["nodes"]
        assert snap["nodes"]["n2"]["volumes"] == 0

    def test_repair_advisor_min_slack_across_nodes(self):
        agg = WorkloadAggregator(_FakeMaster())
        agg.ingest("n1", _payload(fg=100.0, peak=1000.0))
        agg.ingest("n2", _payload(fg=700.0, peak=1000.0, vid="2"))
        adv = agg.snapshot()["advisors"]["repair"]
        # n2 is the bottleneck: only 300 B/s of idle headroom
        assert adv["recommended"] == 300.0
        assert adv["node_slack"] == {"n1": 900.0, "n2": 300.0}

    def test_repair_advisor_no_data(self):
        adv = WorkloadAggregator(
            _FakeMaster()).snapshot()["advisors"]["repair"]
        assert adv["recommended"] is None
        assert adv["effective"] is None

    def test_tenant_demand_folds_federated_scrapes(self):
        # rates SUM across gateways; provisioned + quantiles take MAX
        t1 = ('workload_tenant_rate_rps{tenant="acme"} 10\n'
              'workload_tenant_bytes_per_sec{tenant="acme"} 1000\n'
              'workload_tenant_provisioned_rate{tenant="acme"} 500\n'
              'workload_tenant_bytes{tenant="acme",q="0.99"} 4096\n')
        t2 = ('workload_tenant_rate_rps{tenant="acme",'
              'instance="gw:1"} 5\n'
              'workload_tenant_bytes_per_sec{tenant="acme",'
              'instance="gw:1"} 200\n'
              'workload_tenant_provisioned_rate{tenant="acme",'
              'instance="gw:1"} 400\n'
              'workload_tenant_delay_seconds{tenant="acme",'
              'q="0.5"} 0.02\n')
        agg = WorkloadAggregator(_FakeMaster(texts=[t1, t2]),
                                 headroom=2.0)
        demand = agg.tenant_demand()
        assert demand["acme"]["rate_rps"] == 15.0
        assert demand["acme"]["bytes_per_sec"] == 1200.0
        assert demand["acme"]["provisioned_rate"] == 500.0
        assert demand["acme"]["bytes"]["0.99"] == 4096.0
        assert demand["acme"]["delay"]["0.5"] == 0.02
        adv = agg.snapshot()["advisors"]["qos"]
        row = adv["tenants"]["acme"]
        assert row["recommended"] == 2400.0  # 1200 × headroom
        assert row["current"] == 500.0
        assert row["delta"] == 1900.0

    def test_overrides_win_in_effective(self):
        agg = WorkloadAggregator(_FakeMaster())
        agg.ingest("n1", _payload(gaps=[10.0] * 50))
        out = agg.set_override("seal", 7200.0)
        assert out == {"advisor": "seal", "tenant": "",
                       "override": 7200.0}
        adv = agg.snapshot()["advisors"]["seal"]
        assert adv["override"] == 7200.0
        assert adv["effective"] == 7200.0
        assert adv["recommended"] != 7200.0  # recommendation unchanged
        # clear with null: back to recommendation
        agg.set_override("seal", None)
        adv = agg.snapshot()["advisors"]["seal"]
        assert "override" not in adv
        assert adv["effective"] == adv["recommended"]

    def test_per_tenant_qos_override(self):
        t = ('workload_tenant_rate_rps{tenant="acme"} 1\n'
             'workload_tenant_bytes_per_sec{tenant="acme"} 100\n'
             'workload_tenant_provisioned_rate{tenant="acme"} 50\n')
        agg = WorkloadAggregator(_FakeMaster(texts=[t]))
        agg.set_override("qos", 999.0, tenant="acme")
        row = agg.snapshot()["advisors"]["qos"]["tenants"]["acme"]
        assert row["override"] == 999.0 and row["effective"] == 999.0

    def test_override_validation(self):
        agg = WorkloadAggregator(_FakeMaster())
        with pytest.raises(ValueError):
            agg.set_override("bogus", 1.0)
        with pytest.raises(ValueError):
            agg.set_override("seal", 1.0, tenant="acme")  # qos only
        with pytest.raises(ValueError):
            agg.set_override("seal", "not-a-number")
        with pytest.raises(ValueError):
            agg.set_override("seal", -5.0)
        with pytest.raises(ValueError):
            agg.set_override("seal", float("nan"))

    def test_export_gauges_and_status_fold(self):
        agg = WorkloadAggregator(_FakeMaster())
        agg.ingest("n1", _payload(gaps=[10.0] * 50,
                                  sizes=[4096.0] * 50,
                                  fg=10.0, peak=100.0))
        agg.set_override("repair", 42.0)
        agg.export_gauges()
        with metrics._lock:
            g = dict(metrics._gauges)
        assert g[("workload_nodes_reporting", ())] == 1
        assert ("workload_read_gap_seconds",
                (("q", "0.99"),)) in g
        assert ("workload_read_size_bytes", (("q", "0.5"),)) in g
        assert g[("workload_advisor_effective",
                  (("kind", "repair"),))] == 42.0
        fold = agg.status_fold()
        assert fold["NodesReporting"] == 1
        assert fold["Advisors"]["repair"]["Override"] == 42.0
        assert fold["Advisors"]["seal"]["Recommended"] is not None


# ---------------------------------------------------------------------
# gateway tenant demand (utils/qos.py)
# ---------------------------------------------------------------------


class TestTenantDemand:
    @pytest.fixture(autouse=True)
    def _fresh_registry(self):
        _qos._registry.reset()
        yield
        _qos._registry.reset()

    def test_record_and_snapshot(self):
        for _ in range(20):
            _qos.record_demand("AKIDEXAMPLE", 4096, 0.01)
        snap = _qos.demand_snapshot()
        t = snap["tenants"]["AKIDEXAMPLE"]
        assert t["bytes"]["count"] == 20
        assert t["bytes"]["p50"] == pytest.approx(4096, rel=0.02)
        assert t["delay"]["p90"] == pytest.approx(0.01, rel=0.02)
        # provisioned_rate reflects config (0 = unprovisioned default)
        assert t["provisioned_rate"] == 0.0
        assert snap["alpha"] == _sketch.alpha()

    def test_rate_from_mean_gap(self):
        reg = _qos.QosRegistry()
        now = time.time()
        # synthesize steady 10 rps by driving the sketches directly
        d = {"gap": _sketch.windowed(), "bytes": _sketch.windowed(),
             "delay": _sketch.windowed(), "last_at": now}
        for i in range(50):
            d["gap"].record(0.1, now)
            d["bytes"].record(1000, now)
            d["delay"].record(0.0, now)
        reg._demand["t"] = d
        rows = {r[0]: r for r in reg._demand_rows_locked(now)}
        assert rows["t"][1] == pytest.approx(10.0, rel=0.02)
        snap = reg.demand_snapshot(now=now)
        assert snap["tenants"]["t"]["bytes_per_sec"] == pytest.approx(
            10.0 * 1000, rel=0.05)

    def test_disabled_telemetry_records_nothing(self):
        _sketch.configure(enabled=False)
        _qos.record_demand("akid", 100, 0.0)
        assert _qos.demand_snapshot()["tenants"] == {}
        _sketch.configure(enabled=True)

    def test_overflow_tenant_bounds_cardinality(self):
        reg = _qos.QosRegistry()
        reg.max_tenants = 2
        for i in range(5):
            reg.record_demand(f"tenant-{i}", 10, 0.0)
        snap = reg.demand_snapshot()
        assert len(snap["tenants"]) <= 3
        assert _qos.OVERFLOW_TENANT in snap["tenants"]

    def test_export_demand_metrics_gauges(self):
        for _ in range(5):
            _qos.record_demand("acme", 2048, 0.005)
        _qos.export_demand_metrics()
        with metrics._lock:
            g = dict(metrics._gauges)
        assert ("workload_tenant_rate_rps",
                (("tenant", "acme"),)) in g
        key = ("workload_tenant_bytes",
               (("q", "0.99"), ("tenant", "acme")))
        assert g[key] == pytest.approx(2048, rel=0.02)


# ---------------------------------------------------------------------
# volume store taps -> heartbeat payload
# ---------------------------------------------------------------------


class TestStoreTaps:
    def test_reads_and_writes_feed_sketches(self, tmp_path):
        store = Store([str(tmp_path)], ip="127.0.0.1", port=0)
        for _ in range(10):
            store.record_read(1, nbytes=4096)
            store.record_write(1, nbytes=1024)
        p = store.workload_payload()
        assert p["alpha"] == _sketch.alpha()
        v = p["volumes"]["1"]
        assert v["rs"]["n"] == 10
        assert v["ws"]["n"] == 10
        # 9 gaps from 10 accesses of each kind
        assert v["rg"]["n"] == 9 and v["wg"]["n"] == 9
        assert p["peak_bps"] >= p["fg_bps"] >= 0
        hb = store.collect_heartbeat()
        assert hb["workload"]["volumes"]["1"]["rs"]["n"] == 10

    def test_disabled_telemetry_skips_taps_and_heartbeat(self,
                                                         tmp_path):
        _sketch.configure(enabled=False)
        store = Store([str(tmp_path)], ip="127.0.0.1", port=0)
        store.record_read(1, nbytes=4096)
        assert store.workload_payload()["volumes"] == {}
        assert "workload" not in store.collect_heartbeat()
        # heat counters still tick: tiering depends on them
        assert store.volume_heat(1)["read_count"] == 1
        _sketch.configure(enabled=True)

    def test_empty_sketches_not_shipped(self, tmp_path):
        store = Store([str(tmp_path)], ip="127.0.0.1", port=0)
        assert store.workload_payload()["volumes"] == {}


# ---------------------------------------------------------------------
# federation staleness: up gauge + stale-series drop
# ---------------------------------------------------------------------


class TestFederationStaleness:
    def test_up_gauge_and_stale_drop(self):
        fed = MetricsFederator(master=None, stale_after=30.0)
        now = time.time()
        live = ("# TYPE req_total counter\n"
                'req_total{code="200"} 5\n')
        fed._scraped = {
            "live:1": {"text": live, "ts": now, "error": ""},
            "dead:2": {"text": live, "ts": now - 300.0, "error": ""},
        }
        out = fed.merged()
        assert 'up{instance="live:1"} 1' in out
        assert 'up{instance="dead:2"} 0' in out
        # the dead instance's frozen series are dropped, not re-merged
        assert 'req_total{instance="live:1",code="200"} 5' in out
        assert 'instance="dead:2",code="200"' not in out
        # exactly one TYPE line for the synthetic family
        assert out.count("# TYPE up gauge") == 1

    def test_never_scraped_is_down(self):
        fed = MetricsFederator(master=None, stale_after=30.0)
        fed._scraped = {"gone:9": {"text": "", "ts": 0.0,
                                   "error": "boom"}}
        out = fed.merged()
        assert 'up{instance="gone:9"} 0' in out
        obs = fed.observability()
        assert obs["gone:9"]["Up"] is False

    def test_stale_after_defaults_to_3x_interval(self):
        assert MetricsFederator(master=None,
                                interval=20.0).stale_after == 60.0
        # floor of 30 s for fast scrape configs
        assert MetricsFederator(master=None,
                                interval=1.0).stale_after == 30.0


# ---------------------------------------------------------------------
# master endpoints (in-process master)
# ---------------------------------------------------------------------


@pytest.fixture(scope="module")
def master_srv():
    m = MasterServer(pulse_seconds=0.4, scrape_interval=3600.0)
    t = ServerThread(m.app).start()
    yield m, t
    t.stop()


class TestMasterEndpoints:
    def test_debug_index(self, master_srv):
        _, t = master_srv
        body = requests.get(f"{t.url}/debug", timeout=5).json()
        assert body["service"] == "master"
        assert "/debug/workload" in body["endpoints"]
        txt = requests.get(f"{t.url}/debug",
                           params={"format": "text"}, timeout=5)
        assert "/debug/workload" in txt.text

    def test_debug_workload_snapshot(self, master_srv):
        m, t = master_srv
        m.workload.ingest("vol:1", _payload(gaps=[50.0] * 40,
                                            fg=10.0, peak=200.0))
        body = requests.get(f"{t.url}/debug/workload",
                            timeout=5).json()
        assert body["telemetry_enabled"] is True
        assert body["nodes"]["vol:1"]["stale"] is False
        assert set(body["advisors"]) == {"seal", "qos", "repair"}
        assert body["advisors"]["repair"]["recommended"] == 190.0

    def test_workload_override_roundtrip(self, master_srv):
        m, t = master_srv
        url = f"{t.url}/debug/workload"
        r = requests.post(url, json={"advisor": "seal",
                                     "override": 1234.5}, timeout=5)
        assert r.status_code == 200
        assert r.json()["override"] == 1234.5
        adv = requests.get(url, timeout=5).json()["advisors"]["seal"]
        assert adv["override"] == 1234.5
        assert adv["effective"] == 1234.5
        # clear
        r = requests.post(url, json={"advisor": "seal",
                                     "override": None}, timeout=5)
        assert r.status_code == 200
        assert "override" not in \
            requests.get(url, timeout=5).json()["advisors"]["seal"]

    def test_workload_override_rejects_bad_bodies(self, master_srv):
        _, t = master_srv
        url = f"{t.url}/debug/workload"
        assert requests.post(url, data=b"not json",
                             timeout=5).status_code == 400
        assert requests.post(url, json=[1, 2],
                             timeout=5).status_code == 400
        assert requests.post(url, json={"override": 1},
                             timeout=5).status_code == 400
        assert requests.post(url, json={"advisor": "seal"},
                             timeout=5).status_code == 400
        assert requests.post(url, json={"advisor": "nope",
                                        "override": 1},
                             timeout=5).status_code == 400
        assert requests.post(url, json={"advisor": "seal",
                                        "override": -1},
                             timeout=5).status_code == 400

    def test_workload_gauges_in_metrics(self, master_srv):
        m, t = master_srv
        m.workload.ingest("vol:1", _payload(gaps=[50.0] * 40))
        body = requests.get(f"{t.url}/metrics", timeout=5).text
        assert "workload_nodes_reporting" in body
        assert 'workload_read_gap_seconds{q="0.99"}' in body

    def test_workload_in_cluster_status(self, master_srv):
        _, t = master_srv
        wl = requests.get(f"{t.url}/cluster/status",
                          timeout=5).json()["Workload"]
        assert "Advisors" in wl and "NodesReporting" in wl
        assert set(wl["Advisors"]) == {"seal", "qos", "repair"}

    def test_trace_query_alias(self, master_srv):
        m, t = master_srv
        from seaweedfs_tpu.utils import tracing
        tid = tracing.new_trace_id()
        m.collector.add_spans("i", "s3", [{
            "trace_id": tid, "span_id": tracing.new_span_id(),
            "parent_id": "", "service": "s3", "name": "op",
            "kind": "server", "peer": "", "start": time.time(),
            "duration": 0.01, "status": "200"}])
        # ?trace= is an alias for ?trace_id=
        tree = requests.get(f"{t.url}/cluster/traces",
                            params={"trace": tid}, timeout=5).json()
        assert tree["spans"] == 1
        r = requests.get(f"{t.url}/cluster/traces",
                         params={"trace": "f" * 32}, timeout=5)
        assert r.status_code == 404
        assert "error" in r.json()
