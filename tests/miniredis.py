"""In-process mini redis server for exercising the RESP filer store.

Implements just the command subset RedisStore speaks (SET/GET/DEL/
ZADD/ZREM/ZRANGE/ZRANGEBYLEX/AUTH/SELECT/PING/FLUSHALL) with real RESP2
framing, so the store's socket client is tested against an actual wire
protocol rather than a monkeypatch — the same spirit as the
reference's docker-compose redis test variants, minus the container.
"""
from __future__ import annotations

import socket
import threading


class MiniRedis:
    def __init__(self):
        self.kv: dict[bytes, bytes] = {}
        self.zsets: dict[bytes, set[bytes]] = {}
        self.lock = threading.Lock()
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]
        self._stop = False
        self._thread = threading.Thread(target=self._accept, daemon=True)
        self._thread.start()

    def close(self):
        self._stop = True
        try:
            self._srv.close()
        except OSError:
            pass

    # -- plumbing -------------------------------------------------------
    def _accept(self):
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket):
        buf = b""

        def read_line():
            nonlocal buf
            while b"\r\n" not in buf:
                chunk = conn.recv(65536)
                if not chunk:
                    raise ConnectionError
                buf += chunk
            line, buf2 = buf.split(b"\r\n", 1)
            buf = buf2
            return line

        def read_exact(n):
            nonlocal buf
            while len(buf) < n + 2:
                chunk = conn.recv(65536)
                if not chunk:
                    raise ConnectionError
                buf += chunk
            data, buf = buf[:n], buf[n + 2:]
            return data

        try:
            while True:
                line = read_line()
                if not line.startswith(b"*"):
                    conn.sendall(b"-ERR protocol\r\n")
                    return
                argc = int(line[1:])
                args = []
                for _ in range(argc):
                    hdr = read_line()
                    assert hdr.startswith(b"$")
                    args.append(read_exact(int(hdr[1:])))
                conn.sendall(self._dispatch(args))
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    # -- replies --------------------------------------------------------
    @staticmethod
    def _bulk(v: bytes | None) -> bytes:
        return b"$-1\r\n" if v is None else \
            b"$%d\r\n%s\r\n" % (len(v), v)

    @staticmethod
    def _arr(items: list[bytes]) -> bytes:
        return b"*%d\r\n" % len(items) + \
            b"".join(MiniRedis._bulk(i) for i in items)

    # -- commands -------------------------------------------------------
    def _dispatch(self, args: list[bytes]) -> bytes:
        cmd = args[0].upper()
        with self.lock:
            if cmd in (b"PING",):
                return b"+PONG\r\n"
            if cmd in (b"AUTH", b"SELECT", b"FLUSHALL"):
                if cmd == b"FLUSHALL":
                    self.kv.clear()
                    self.zsets.clear()
                return b"+OK\r\n"
            if cmd == b"SET":
                self.kv[args[1]] = args[2]
                return b"+OK\r\n"
            if cmd == b"GET":
                return self._bulk(self.kv.get(args[1]))
            if cmd == b"MGET":
                out = b"*%d\r\n" % (len(args) - 1)
                for k in args[1:]:
                    out += self._bulk(self.kv.get(k))
                return out
            if cmd == b"DEL":
                n = 0
                for k in args[1:]:
                    n += self.kv.pop(k, None) is not None
                    n += self.zsets.pop(k, None) is not None
                return b":%d\r\n" % n
            if cmd == b"ZADD":
                z = self.zsets.setdefault(args[1], set())
                added = 0
                for member in args[3::2]:
                    added += member not in z
                    z.add(member)
                return b":%d\r\n" % added
            if cmd == b"ZREM":
                z = self.zsets.get(args[1], set())
                n = 0
                for member in args[2:]:
                    if member in z:
                        z.discard(member)
                        n += 1
                return b":%d\r\n" % n
            if cmd == b"ZRANGE":
                members = sorted(self.zsets.get(args[1], set()))
                start, stop = int(args[2]), int(args[3])
                if stop == -1:
                    stop = len(members) - 1
                return self._arr(members[start:stop + 1])
            if cmd == b"ZRANGEBYLEX":
                members = sorted(self.zsets.get(args[1], set()))
                lo, hi = args[2], args[3]

                def above_lo(m):
                    if lo == b"-":
                        return True
                    if lo.startswith(b"["):
                        return m >= lo[1:]
                    return m > lo[1:]

                def below_hi(m):
                    if hi == b"+":
                        return True
                    if hi.startswith(b"["):
                        return m <= hi[1:]
                    return m < hi[1:]

                sel = [m for m in members if above_lo(m) and below_hi(m)]
                if len(args) >= 7 and args[4].upper() == b"LIMIT":
                    off, cnt = int(args[5]), int(args[6])
                    sel = sel[off:] if cnt < 0 else sel[off:off + cnt]
                return self._arr(sel)
        return b"-ERR unknown command %s\r\n" % cmd
