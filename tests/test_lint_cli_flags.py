"""Fast tier-1 lint: every robustness CLI knob (-repair.*, -fault.*,
-retry.*, -qos.*, -filer.store.*, -filer.cache.*, -filer.native*,
-tier.*) registered in cli.py carries non-empty help text, and the
documented flag surface has not rotted.

The rule logic (including the EXPECTED flag list) lives in
seaweedfs_tpu/analysis/rules/cli_flags.py; this module keeps the
historical entrypoint as a thin wrapper over the shared engine pass."""
import pytest

from seaweedfs_tpu.analysis import run_cached

pytestmark = pytest.mark.lint


def test_robustness_flags_have_help():
    run = run_cached()
    assert run.stats["cli_flags_checked"] > 0, (
        "no -repair./-fault./-retry./-qos. flags found in cli.py")
    offenders = [f.render() for f in run.by_rule("cli-flag-help")]
    assert not offenders, "\n".join(offenders)
