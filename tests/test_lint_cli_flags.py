"""Fast tier-1 lint: every robustness CLI knob (-repair.*, -fault.*,
-retry.*, -qos.*, -filer.store.*, -filer.cache.*, -filer.native*,
-tier.*) registered in cli.py carries non-empty help text — these
flags gate chaos/repair/overload/metadata-plane/tiering/native-front
behaviour and an undocumented one is effectively invisible to
operators."""
import ast
import os

CLI_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "seaweedfs_tpu", "cli.py")

PREFIXES = ("-repair.", "-fault.", "-retry.", "-qos.",
            "-filer.store.", "-filer.cache.", "-filer.native",
            "-tier.")


def _add_argument_calls(tree):
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            yield node.args[0].value, node


def test_robustness_flags_have_help():
    with open(CLI_PATH, encoding="utf-8") as f:
        tree = ast.parse(f.read())
    flags = {}
    for flag, call in _add_argument_calls(tree):
        if not flag.startswith(PREFIXES):
            continue
        help_text = ""
        for kw in call.keywords:
            if kw.arg == "help" and isinstance(kw.value, ast.Constant):
                help_text = str(kw.value.value)
            elif kw.arg == "help":
                # implicit concatenation of string constants folds to
                # one Constant; anything else is computed — accept it
                help_text = "<computed>"
        flags.setdefault(flag, []).append(help_text.strip())
    assert flags, "no -repair./-fault./-retry./-qos. flags found in " \
        "cli.py"
    undocumented = sorted(f for f, helps in flags.items()
                          if any(not h for h in helps))
    assert not undocumented, (
        f"robustness flags missing help text: {undocumented}")
    # the whole documented surface this PR series promises
    for expected in ("-repair.enabled", "-repair.interval",
                     "-repair.concurrency", "-repair.maxAttempts",
                     "-repair.grace", "-repair.maxBytesPerSec",
                     "-repair.partialEc",
                     "-fault.spec", "-fault.seed",
                     "-qos.enabled", "-qos.rate", "-qos.burst",
                     "-qos.maxTenants", "-qos.maxDelay",
                     "-qos.requestFloor", "-qos.spec",
                     "-filer.store.shards", "-filer.cache.entries",
                     "-filer.cache.pages",
                     "-filer.native", "-filer.native.workers",
                     "-tier.enabled", "-tier.interval",
                     "-tier.concurrency", "-tier.sealAfterIdle",
                     "-tier.offloadAfterIdle", "-tier.recallReads",
                     "-tier.recallWindow", "-tier.maxAttempts",
                     "-tier.maxBytesPerSec", "-tier.remote",
                     "-tier.stateDir"):
        assert expected in flags, f"{expected} flag missing from cli.py"
