"""BtreeNeedleMap: the on-disk third index-persistence strategy
(-index=btree, the reference's needle_map_leveldb.go analog).

Covers the watermark catch-up (reopen replays only the .idx tail),
vacuum-shrink rebuild, metric parity with the memory map, and a full
Volume round trip at kind="btree".
"""
import os

import numpy as np
import pytest

from seaweedfs_tpu.storage import idx as idxmod
from seaweedfs_tpu.storage import needle_map as nmap
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume


def _write_idx(path, entries):
    with open(path, "wb") as f:
        for key, off, size in entries:
            idxmod.append_entry(f, key, off, size)


def test_btree_matches_memory_semantics(tmp_path):
    idx = str(tmp_path / "1.idx")
    entries = [(1, 8, 100), (2, 16, 200), (1, 24, 150),  # overwrite
               (3, 32, 50), (2, 0, t.TOMBSTONE_SIZE)]    # delete
    _write_idx(idx, entries)
    mem = nmap.load_needle_map(idx, kind="memory")
    bt = nmap.load_needle_map(idx, kind="btree")
    try:
        for key in (1, 2, 3, 4):
            assert bt.get(key) == mem.get(key), key
        assert bt.file_count == mem.file_count
        assert bt.deleted_count == mem.deleted_count
        assert bt.file_bytes == mem.file_bytes
        assert bt.deleted_bytes == mem.deleted_bytes
        assert bt.max_key == mem.max_key
        assert sorted(bt.live_items()) == sorted(mem.live_items())
        assert sorted(bt.deleted_keys()) == sorted(mem.deleted_keys())
    finally:
        bt.close()


def test_btree_watermark_tail_replay(tmp_path):
    idx = str(tmp_path / "2.idx")
    _write_idx(idx, [(i, 8 * i, 10) for i in range(1, 101)])
    bt = nmap.load_btree_needle_map(idx)
    bt.set_watermark(os.path.getsize(idx))
    assert bt.file_count == 100
    bt.close()

    # append a tail while "down"; reopen must pick up ONLY the tail
    with open(idx, "ab") as f:
        idxmod.append_entry(f, 200, 800, 42)
        idxmod.append_entry(f, 1, 0, t.TOMBSTONE_SIZE)
    bt2 = nmap.load_btree_needle_map(idx)
    try:
        assert bt2.get(200) == (800, 42)
        assert bt2.get(1) is None
        assert bt2.file_count == 100  # +1 new -1 deleted
        assert bt2.watermark() == os.path.getsize(idx)
    finally:
        bt2.close()


def test_btree_rebuilds_after_idx_shrink(tmp_path):
    idx = str(tmp_path / "3.idx")
    _write_idx(idx, [(i, 8 * i, 10) for i in range(1, 51)])
    bt = nmap.load_btree_needle_map(idx)
    bt.close()
    # vacuum analog: .idx rewritten smaller with different content
    _write_idx(idx, [(7, 8, 10), (9, 16, 20)])
    bt2 = nmap.load_btree_needle_map(idx)
    try:
        assert len(bt2) == 2
        assert bt2.get(7) == (8, 10)
        assert bt2.get(30) is None
        assert bt2.file_count == 2
    finally:
        bt2.close()


def test_volume_round_trip_btree(tmp_path):
    v = Volume(str(tmp_path), "", 7, create=True,
               needle_map_kind="btree")
    rng = np.random.default_rng(3)
    payloads = {}
    for i in range(1, 40):
        data = rng.bytes(int(rng.integers(10, 5000)))
        v.append_needle(Needle(id=i, cookie=0x1234, data=data))
        payloads[i] = data
    v.delete_needle(5)
    v.delete_needle(17)
    for i, data in payloads.items():
        if i in (5, 17):
            with pytest.raises(KeyError):
                v.read_needle(i)
        else:
            assert v.read_needle(i).data == data
    v.close()
    assert os.path.exists(str(tmp_path / "7.idx.bdb"))

    # reopen: state comes back through the watermarked sidecar
    v2 = Volume(str(tmp_path), "", 7, needle_map_kind="btree")
    try:
        assert v2.nm.file_count == 37
        for i, data in payloads.items():
            if i not in (5, 17):
                assert v2.read_needle(i).data == data
        # vacuum compact with the btree map
        v2.compact()
        assert v2.read_needle(3).data == payloads[3]
        assert v2.nm.file_count == 37
    finally:
        v2.close()
