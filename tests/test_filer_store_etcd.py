"""etcd filer store over the real v3 HTTP gateway wire protocol,
against the in-process mini-etcd (tests/minietcd.py) — the same
in-tree-wire-protocol strategy as the redis RESP store tests.
Reference slot: /root/reference/weed/filer/etcd/etcd_store.go.
"""
import time

import pytest

from seaweedfs_tpu.filer.entry import Entry, FileChunk
from seaweedfs_tpu.filer.etcd_store import EtcdStore, _prefix_end
from seaweedfs_tpu.filer.filer import Filer

from .minietcd import MiniEtcd


@pytest.fixture(scope="module")
def etcd_server():
    s = MiniEtcd().start()
    yield s
    s.stop()


@pytest.fixture()
def store(etcd_server):
    etcd_server._kv.clear()
    etcd_server._keys.clear()
    s = EtcdStore(port=etcd_server.port)
    yield s
    s.close()


def ent(path, size=0):
    chunks = [FileChunk(fid="1,ab", offset=0, size=size,
                        mtime_ns=time.time_ns())] if size else []
    return Entry(full_path=path, chunks=chunks)


def test_prefix_end():
    assert _prefix_end(b"abc") == b"abd"
    assert _prefix_end(b"a\xff") == b"b"
    assert _prefix_end(b"\xff\xff") == b"\x00"


def test_insert_find_update_delete(store):
    store.insert_entry(ent("/a/b.txt", 10))
    got = store.find_entry("/a/b.txt")
    assert got is not None and got.file_size == 10
    store.update_entry(ent("/a/b.txt", 20))
    assert store.find_entry("/a/b.txt").file_size == 20
    store.delete_entry("/a/b.txt")
    assert store.find_entry("/a/b.txt") is None


def test_listing_order_pagination_prefix(store):
    for n in ("zeta", "alpha", "beta", "beta2", "gamma"):
        store.insert_entry(ent(f"/dir/{n}"))
    # nested entries must NOT leak into the parent listing
    store.insert_entry(ent("/dir/beta/child"))
    names = [e.name for e in store.list_directory_entries("/dir")]
    assert names == ["alpha", "beta", "beta2", "gamma", "zeta"]
    page = store.list_directory_entries("/dir", limit=2)
    assert [e.name for e in page] == ["alpha", "beta"]
    page = store.list_directory_entries("/dir", start_from="beta",
                                        inclusive=False, limit=2)
    assert [e.name for e in page] == ["beta2", "gamma"]
    pref = store.list_directory_entries("/dir", prefix="beta")
    assert [e.name for e in pref] == ["beta", "beta2"]


def test_delete_folder_children_subtree(store):
    for p in ("/t/a", "/t/sub/x", "/t/sub/deep/y", "/tother/z"):
        store.insert_entry(ent(p))
    store.delete_folder_children("/t")
    assert store.find_entry("/t/a") is None
    assert store.find_entry("/t/sub/x") is None
    assert store.find_entry("/t/sub/deep/y") is None
    # sibling directory with a shared name prefix must survive
    assert store.find_entry("/tother/z") is not None


def test_kv(store):
    store.kv_put("conf", b"\x00\x01binary")
    assert store.kv_get("conf") == b"\x00\x01binary"
    store.kv_delete("conf")
    assert store.kv_get("conf") is None
    assert store.kv_get("never") is None


def test_full_filer_stack(etcd_server):
    etcd_server._kv.clear()
    etcd_server._keys.clear()
    f = Filer("etcd", port=etcd_server.port)
    try:
        f.create_entry(ent("/docs/readme.md", 5))
        assert f.find_entry("/docs/readme.md").file_size == 5
        assert f.find_entry("/docs").is_directory
        names = [e.name for e in f.list_entries("/docs")]
        assert names == ["readme.md"]
        f.delete_entry("/docs", recursive=True)
        assert f.find_entry("/docs/readme.md") is None
    finally:
        f.close()


def test_large_directory_pagination(store):
    # more entries than one gateway range page; exercises the `more`
    # continuation loop
    for i in range(2500):
        store.insert_entry(ent(f"/big/f{i:05d}"))
    names = [e.name for e in
             store.list_directory_entries("/big", limit=2500)]
    assert names == [f"f{i:05d}" for i in range(2500)]


def test_root_recursive_delete(store):
    # review finding: base+"/" built "E//" for root and deleted nothing
    for p in ("/a/b/deep.txt", "/a/top", "/c"):
        store.insert_entry(ent(p))
    store.delete_folder_children("/")
    for p in ("/a/b/deep.txt", "/a/top", "/c"):
        assert store.find_entry(p) is None, p


def test_non_ascii_directory_listing(store):
    # review finding: str-slicing by byte length mangled names under
    # non-ASCII dirs
    store.insert_entry(ent("/café/beta"))
    store.insert_entry(ent("/café/beta2"))
    names = [e.name for e in
             store.list_directory_entries("/café", prefix="beta")]
    assert names == ["beta", "beta2"]
    page = store.list_directory_entries("/café", start_from="beta",
                                        inclusive=False)
    assert [e.name for e in page] == ["beta2"]
