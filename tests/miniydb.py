"""In-process YDB TableService double: a REAL grpc-core server (same
wire class a ydb endpoint exposes) implementing the CreateSession /
ExecuteSchemeQuery / ExecuteDataQuery subset over an in-memory
filemeta/kv model. YQL is dispatched by statement shape (the five
query templates the store emits), parameters decoded with the
independent protobuf helpers from minitikv — client and double
cross-check each other.
"""
from __future__ import annotations

import threading
from concurrent import futures

import grpc

from .minitikv import _by, _decode, _one, _u, _vi

SUCCESS = 400000


def _signed(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def _like_unescape(s: str) -> str:
    """Reverse the store's _like_escape: backslash-prefixed wildcards
    become literals (the double then matches with plain startswith)."""
    out = []
    i = 0
    while i < len(s):
        if s[i] == "\\" and i + 1 < len(s):
            out.append(s[i + 1])
            i += 2
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


def _param_value(tv_raw: bytes):
    """TypedValue bytes -> python scalar."""
    tv = _decode(tv_raw)
    val = _decode(_one(tv, 2, b""))
    if 4 in val:
        return _signed(val[4][0])
    if 5 in val:
        return val[5][0]
    if 8 in val:
        return bytes(val[8][0])
    if 9 in val:
        return bytes(val[9][0]).decode()
    raise ValueError(f"unsupported value fields {sorted(val)}")


def _scalar(v) -> bytes:
    """python scalar -> Ydb.Value bytes."""
    if isinstance(v, bytes):
        return _by(8, v)
    if isinstance(v, str):
        return _by(9, v.encode())
    raise TypeError(type(v))


def _operation(result_msg: bytes | None, type_url: str) -> bytes:
    op = _u(2, 1) + _u(3, SUCCESS)  # ready, status
    if result_msg is not None:
        any_msg = _by(1, type_url.encode()) + _by(2, result_msg)
        op += _by(5, any_msg)
    return _by(1, op)


class MiniYdb(grpc.GenericRpcHandler):
    def __init__(self):
        # filemeta: {(dir_hash, name): (directory, meta)}; kv: {k: v}
        self.filemeta: dict[tuple[int, str], tuple[str, bytes]] = {}
        self.kv: dict[str, bytes] = {}
        self.sessions = 0
        # simulate real YDB's 1000-row result-set cap (truncated=true)
        self.result_cap: int | None = None
        self._lock = threading.Lock()

    def start(self) -> "MiniYdb":
        self.server = grpc.server(futures.ThreadPoolExecutor(4))
        self.server.add_generic_rpc_handlers((self,))
        self.port = self.server.add_insecure_port("127.0.0.1:0")
        self.server.start()
        return self

    def stop(self):
        self.server.stop(0)

    def service(self, details):
        if not details.method.startswith("/Ydb.Table.V1.TableService/"):
            return None
        name = details.method.rsplit("/", 1)[-1]
        fn = getattr(self, f"_{name}", None)
        if fn is None:
            return None
        return grpc.unary_unary_rpc_method_handler(
            lambda req, ctx, fn=fn: fn(_decode(req) if req else {}))

    def _CreateSession(self, req):
        with self._lock:
            self.sessions += 1
            sid = f"session-{self.sessions}"
        return _operation(
            _by(1, sid.encode()),
            "type.googleapis.com/Ydb.Table.CreateSessionResult")

    def _ExecuteSchemeQuery(self, req):
        assert b"CREATE TABLE" in bytes(_one(req, 2, b""))
        return _operation(None, "")

    def _ExecuteDataQuery(self, req):
        yql = bytes(_one(_decode(_one(req, 3, b"")), 1, b"")).decode()
        params = {}
        for entry_raw in req.get(4, []):
            e = _decode(bytes(entry_raw))
            params[bytes(_one(e, 1, b"")).decode()] = \
                _param_value(bytes(_one(e, 2, b"")))
        with self._lock:
            rows = self._run(yql, params)
        truncated = False
        if self.result_cap is not None and len(rows) > self.result_cap:
            rows = rows[:self.result_cap]
            truncated = True
        # ExecuteQueryResult { result_sets=1 }
        out_rows = b""
        for row in rows:
            items = b"".join(_by(12, _scalar(cell)) for cell in row)
            out_rows += _by(2, items)  # ResultSet.rows (Value)
        rs = out_rows + (_u(3, 1) if truncated else b"")
        result = _by(1, rs) if rows or "SELECT" in yql else b""
        return _operation(
            result,
            "type.googleapis.com/Ydb.Table.ExecuteQueryResult")

    def _run(self, yql: str, p: dict) -> list[list]:
        if "UPSERT INTO filemeta" in yql:
            self.filemeta[(p["$dir_hash"], p["$name"])] = \
                (p["$directory"], p["$meta"])
            return []
        if "UPSERT INTO kv" in yql:
            self.kv[p["$k"]] = p["$v"]
            return []
        if "SELECT meta FROM filemeta" in yql:
            hit = self.filemeta.get((p["$dir_hash"], p["$name"]))
            return [[hit[1]]] if hit else []
        if "SELECT v FROM kv" in yql:
            return [[self.kv[p["$k"]]]] if p["$k"] in self.kv else []
        if "DELETE FROM kv" in yql:
            self.kv.pop(p["$k"], None)
            return []
        if "DELETE FROM filemeta" in yql and "$directory" in p:
            doomed = [k for k, (d, _m) in self.filemeta.items()
                      if k[0] == p["$dir_hash"] and d == p["$directory"]]
            for k in doomed:
                del self.filemeta[k]
            return []
        if "DELETE FROM filemeta" in yql:
            self.filemeta.pop((p["$dir_hash"], p["$name"]), None)
            return []
        if "SELECT name, meta FROM filemeta" in yql:
            inclusive = "name >= $start_name" in yql
            assert "ESCAPE" in yql  # the store must escape wildcards
            pfx = p["$prefix"]
            assert pfx.endswith("%"), pfx
            pfx = _like_unescape(pfx[:-1])
            out = []
            for (dh, name), (d, meta) in sorted(self.filemeta.items(),
                                                key=lambda kv: kv[0][1]):
                if dh != p["$dir_hash"] or d != p["$directory"]:
                    continue
                if inclusive and name < p["$start_name"]:
                    continue
                if not inclusive and name <= p["$start_name"]:
                    continue
                if not name.startswith(pfx):
                    continue
                out.append([name, meta])
                if len(out) >= p["$limit"]:
                    break
            return out
        raise AssertionError(f"unrecognized YQL: {yql[:80]}")
