"""Mesh codec (-ec.backend=mesh) tests: bit-for-bit oracle agreement
with the CPU codec on even and uneven shapes, pad_to_mesh round-trips,
and the three-way (cpu / single-chip / mesh) measured-curve router.

All device tests run on the 8-device virtual CPU mesh conftest forces;
they skip themselves (mesh marker) if fewer than 2 devices are visible.
"""
import time as _time

import jax
import numpy as np
import pytest

from seaweedfs_tpu.ec import backend as ecb
from seaweedfs_tpu.ec import probe
from seaweedfs_tpu.ops import codec_numpy, rs_matrix
from seaweedfs_tpu.parallel import mesh as pmesh

needs_devices = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="mesh tests need >= 2 jax devices")

pytestmark = [pytest.mark.mesh, needs_devices]


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(7)


@pytest.fixture(scope="module")
def mesh_codec():
    from seaweedfs_tpu.ops.codec_mesh import MeshCodec

    return MeshCodec()


# ---------------------------------------------------------------------
# oracle agreement
# ---------------------------------------------------------------------

@pytest.mark.parametrize("km", [(10, 4), (28, 4)])
@pytest.mark.parametrize("n", [8192, 5000, 777, 8, 1])
def test_mesh_encode_matches_cpu_oracle(mesh_codec, rng, km, n):
    """Even AND uneven column counts: the mesh pad->shard->trim path is
    bit-identical to the numpy codec for narrow and wide codes."""
    k, m = km
    coef = rs_matrix.parity_rows(k, m)
    data = rng.integers(0, 256, (k, n), dtype=np.uint8)
    got = mesh_codec.coded_matmul(coef, data)
    want = codec_numpy.coded_matmul(coef, data)
    assert got.shape == (m, n)
    assert np.array_equal(got, want), (km, n)


@pytest.mark.parametrize("km", [(10, 4), (28, 4)])
def test_mesh_reconstruct_matches_cpu_oracle(mesh_codec, rng, km):
    k, m = km
    rs_mesh = ecb.ReedSolomon(k, m, backend=mesh_codec)
    rs_cpu = ecb.ReedSolomon(k, m, backend="numpy")
    data = rng.integers(0, 256, (k, 3001), dtype=np.uint8)
    parity = rs_mesh.encode(data)
    assert np.array_equal(parity, rs_cpu.encode(data))
    full = np.concatenate([data, parity], axis=0)
    drop = [0, 3, k + 1, k + 3]
    shards = {i: full[i] for i in range(k + m) if i not in drop}
    rec = rs_mesh.reconstruct(shards)
    assert sorted(rec) == sorted(drop)
    for sid, row in rec.items():
        assert np.array_equal(row, full[sid]), (km, sid)


@pytest.mark.parametrize("depth", [1, 2, 4])
def test_mesh_stream_matches_oracle_all_depths(mesh_codec, rng, depth):
    """Streaming pipeline: order preserved, uneven widths and an empty
    block mid-stream, bit-identical at every depth."""
    coef = rs_matrix.parity_rows(10, 4)
    widths = [4096, 1000, 0, 257, 8192, 3]
    blocks = [rng.integers(0, 256, (10, w), dtype=np.uint8)
              for w in widths]
    outs = list(mesh_codec.coded_matmul_stream(coef, iter(blocks),
                                               depth=depth))
    assert len(outs) == len(blocks)
    for out, blk in zip(outs, blocks):
        assert np.array_equal(out, codec_numpy.coded_matmul(coef, blk))


def test_mesh_registered_and_constructible():
    assert "mesh" in ecb.backend_names()
    assert "mesh" in ecb.available_backend_names()
    codec = ecb.get_backend("mesh")
    geo = codec.describe()
    assert geo["devices"] == geo["vol"] * geo["col"] >= 2


# ---------------------------------------------------------------------
# pad_to_mesh round-trips (satellite: uneven batch/column oracles)
# ---------------------------------------------------------------------

@pytest.mark.parametrize("km", [(10, 4), (28, 4)])
def test_pad_to_mesh_roundtrip_uneven(rng, km):
    """Uneven batch AND uneven columns: sharded encode over the padded
    tensor, sliced back, equals the single-chip encode bit-for-bit."""
    from seaweedfs_tpu.models import ec_pipeline as ep

    k, m = km
    mesh = pmesh.make_mesh()
    vol, col = mesh.devices.shape
    batch, cols = vol + 1, 100 * col + 3  # both indivisible
    stripes = rng.integers(0, 256, (batch, k, cols), dtype=np.uint8)

    padded, orig = pmesh.pad_to_mesh(stripes, mesh)
    assert orig == (batch, cols)
    assert padded.shape[0] % vol == 0 and padded.shape[2] % col == 0

    step, a_bits, data_sh = ep.sharded_encode_scrub(mesh, k, m)
    dev = jax.device_put(padded, data_sh)
    zeros = jax.device_put(
        np.zeros((padded.shape[0], m, padded.shape[2]), np.uint8),
        data_sh)
    parity, _ = step(a_bits, dev, zeros)
    got = np.asarray(parity)[:batch, :, :cols]

    fn, a1 = ep.jitted_encode(k, m)
    want = np.asarray(fn(a1, stripes))
    assert np.array_equal(got, want), km


def test_pad_to_mesh_even_is_identity(rng):
    mesh = pmesh.make_mesh()
    vol, col = mesh.devices.shape
    arr = rng.integers(0, 256, (2 * vol, 10, 64 * col), dtype=np.uint8)
    padded, orig = pmesh.pad_to_mesh(arr, mesh)
    assert padded is arr
    assert orig == (arr.shape[0], arr.shape[2])


def test_make_mesh_divisibility_error():
    n = len(jax.devices())
    with pytest.raises(ValueError):
        pmesh.make_mesh(n, col_parallel=n + 1)
    if n % 3:
        with pytest.raises(ValueError):
            pmesh.make_mesh(n, col_parallel=3)
    with pytest.raises(ValueError):
        pmesh.make_mesh(n + 1)  # more than the host has


def test_mesh_config_env_parsing(monkeypatch):
    monkeypatch.setenv(pmesh.DEVICES_ENV, "4")
    monkeypatch.setenv(pmesh.COL_ENV, "2")
    assert pmesh.mesh_config() == (4, 2)
    monkeypatch.setenv(pmesh.DEVICES_ENV, "garbage")
    monkeypatch.setenv(pmesh.COL_ENV, "-3")
    assert pmesh.mesh_config() == (None, None)  # ignored, not fatal
    monkeypatch.delenv(pmesh.DEVICES_ENV)
    monkeypatch.delenv(pmesh.COL_ENV)
    assert pmesh.mesh_config() == (None, None)


def test_mesh_codec_respects_env_shape(monkeypatch):
    from seaweedfs_tpu.ops.codec_mesh import MeshCodec

    monkeypatch.setenv(pmesh.DEVICES_ENV, "2")
    monkeypatch.setenv(pmesh.COL_ENV, "1")
    codec = MeshCodec()
    assert (codec.n_devices, codec.vol, codec.col) == (2, 2, 1)


# ---------------------------------------------------------------------
# pipelined feed over the mesh
# ---------------------------------------------------------------------

def test_pipelined_encode_stream_mesh_matches_single(rng):
    from seaweedfs_tpu.models import ec_pipeline as ep

    mesh = pmesh.make_mesh()
    blocks = [rng.integers(0, 256, (3, 10, 300 + 17 * i), dtype=np.uint8)
              for i in range(4)]  # uneven batch and columns throughout
    fn, a_bits = ep.jitted_encode()
    refs = [np.asarray(fn(a_bits, b)) for b in blocks]
    for depth in (1, 2):
        outs = list(ep.pipelined_encode_stream(iter(blocks), depth=depth,
                                               mesh=mesh))
        assert len(outs) == len(blocks)
        for out, want in zip(outs, refs):
            assert out.shape == want.shape
            assert np.array_equal(np.asarray(out), want), depth


def test_pipelined_scrub_mesh_counts_mismatches(rng):
    from seaweedfs_tpu.models import ec_pipeline as ep

    mesh = pmesh.make_mesh()
    fn, a_bits = ep.jitted_encode()
    stripes = rng.integers(0, 256, (3, 10, 501), dtype=np.uint8)
    parity = np.asarray(fn(a_bits, stripes))
    clean, n = ep.pipelined_scrub(iter([(stripes, parity)]), mesh=mesh)
    assert (clean, n) == (0, 1)
    bad = parity.copy()
    bad[0, 0, 0] ^= 0xFF
    dirty, n = ep.pipelined_scrub(iter([(stripes, bad)]), mesh=mesh)
    assert n == 1 and dirty == 1  # exactly the byte we flipped


# ---------------------------------------------------------------------
# three-way router + fingerprint invalidation
# ---------------------------------------------------------------------

def _mk_curve(cpu_mbps, rows=(), mesh_rows=(), device=True):
    curve = {
        "fingerprint": probe.host_fingerprint(),
        "measured_at": _time.time(),
        "rows": list(rows),
        "cpu_backend": "numpy",
        "cpu_mbps": cpu_mbps,
        "device": ({"platform": "tpu", "kind": "test", "count": 8}
                   if device else None),
        "device_backend": "jax",
    }
    if mesh_rows:
        curve["mesh_rows"] = list(mesh_rows)
        curve["mesh"] = {"devices": 8, "vol": 4, "col": 2,
                         "platform": "tpu"}
    return curve


def _rows(rates):
    return [{"size": s, "depth": d, "e2e_mbps": r}
            for (s, d), r in rates.items()]


def test_router_picks_mesh_when_fastest(monkeypatch):
    monkeypatch.delenv("SEAWEEDFS_TPU_EC_BACKEND", raising=False)
    curve = _mk_curve(300.0,
                      rows=_rows({(1 << 20, 1): 400.0,
                                  (64 << 20, 2): 900.0}),
                      mesh_rows=_rows({(1 << 20, 1): 100.0,
                                       (64 << 20, 4): 4000.0}))
    # small requests can't amortize the scatter: single-chip wins
    assert ecb._decide(curve, 1 << 20) == "jax"
    # bulk rides the mesh
    assert ecb._decide(curve, 64 << 20) == "mesh"
    monkeypatch.setattr(probe, "_curves", {"": curve})
    assert ecb.choose_backend_for_size(64 << 20) == "mesh"
    # depth for a mesh-routed size comes from the MESH rows
    assert ecb.pipeline_depth_for(64 << 20) == 4
    assert ecb.pipeline_depth_for(1 << 20) == 1


def test_router_never_picks_mesh_below_cpu(monkeypatch):
    monkeypatch.delenv("SEAWEEDFS_TPU_EC_BACKEND", raising=False)
    curve = _mk_curve(500.0,
                      rows=_rows({(64 << 20, 2): 90.0}),
                      mesh_rows=_rows({(64 << 20, 4): 400.0}))
    for size in (1 << 20, 64 << 20, 1 << 30):
        assert ecb._decide(curve, size) == "numpy", size


def test_router_mesh_interpolation_and_buckets():
    curve = _mk_curve(100.0,
                      rows=_rows({(1 << 20, 1): 50.0}),
                      mesh_rows=_rows({(1 << 20, 1): 200.0,
                                       (64 << 20, 4): 800.0}))
    assert probe.mesh_mbps_at(curve, 1 << 20) == 200.0
    assert probe.mesh_mbps_at(curve, 64 << 20) == 800.0
    mid = probe.mesh_mbps_at(curve, 8 << 20)
    assert 200.0 < mid < 800.0
    assert probe.mesh_depth_at(curve, 64 << 20) == 4
    buckets = ecb.router_buckets(curve)
    assert any(b["mesh_e2e_mbps"] for b in buckets)
    assert buckets[-1]["backend"] == "mesh"
    # no mesh rows -> reader degrades to None/default, not a crash
    bare = _mk_curve(100.0, rows=_rows({(1 << 20, 1): 50.0}))
    assert probe.mesh_mbps_at(bare, 4 << 20) is None
    assert probe.mesh_depth_at(bare, 4 << 20) == 2


def test_fingerprint_includes_visible_device_count(monkeypatch):
    """Satellite fix: a curve swept with a different visible device
    set must not be trusted — the fingerprint carries the TOTAL device
    count (any platform) and the mesh shape knobs, so CPU-only hosts
    invalidate too."""
    fp = probe.host_fingerprint()
    assert fp["device_count"] == len(jax.devices())
    assert fp["probe_version"] == probe.PROBE_VERSION >= 2
    assert "mesh_config" in fp

    stale = _mk_curve(100.0, rows=_rows({(1 << 20, 1): 50.0}))
    stale["fingerprint"] = dict(stale["fingerprint"], device_count=1)
    import json as _json
    import os as _os
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        path = _os.path.join(td, "probe.json")
        monkeypatch.setenv("SEAWEEDFS_TPU_EC_PROBE_CACHE", path)
        with open(path, "w", encoding="utf-8") as f:
            _json.dump(stale, f)
        assert probe.load_cached() is None  # stale device set rejected
        fresh = _mk_curve(100.0, rows=_rows({(1 << 20, 1): 50.0}))
        with open(path, "w", encoding="utf-8") as f:
            _json.dump(fresh, f)
        assert probe.load_cached() is not None


def test_fingerprint_changes_with_mesh_knobs(monkeypatch):
    base = probe.host_fingerprint()
    monkeypatch.setenv(pmesh.DEVICES_ENV, "2")
    assert probe.host_fingerprint() != base


def test_mesh_geometry_in_debug_snapshot():
    ecb.get_backend("mesh")  # ensure the instance exists
    snap = ecb.probe_snapshot()
    geo = snap["mesh"]
    assert geo["state"] == "active"
    assert geo["devices"] >= 2
    assert geo["devices"] == geo["vol"] * geo["col"]


def test_summary_includes_mesh_rows():
    curve = _mk_curve(100.0,
                      rows=_rows({(1 << 20, 1): 50.0}),
                      mesh_rows=_rows({(64 << 20, 4): 800.0}))
    s = probe.summary(curve)
    assert s["mesh"]["devices"] == 8
    assert s["mesh_best_by_size_mb"]["64"]["e2e_mbps"] == 800.0
