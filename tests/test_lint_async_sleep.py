"""Fast tier-1 lint: no blocking calls inside ``async def`` bodies in
gateway / edge-QoS code.

The gateways are single event loops: one blocking sleep (or sync HTTP
hop, or unbounded lock acquire) on the loop thread stalls EVERY
in-flight request behind it — which is exactly how an "overload
protection" layer would manufacture the overload it exists to shed.

The rule logic lives in seaweedfs_tpu/analysis/rules/async_hygiene.py
(now generalized from time.sleep to any blocking call); this module
keeps the historical entrypoints as thin wrappers over the shared
engine pass, plus the negative controls."""
import os
import re

import pytest

from seaweedfs_tpu.analysis import run_cached

pytestmark = pytest.mark.lint

PKG_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "seaweedfs_tpu")


def test_no_blocking_sleep_on_the_event_loop():
    run = run_cached()
    assert run.stats["async_functions"] > 50, (
        f"only {run.stats['async_functions']} async functions scanned "
        "— the lint's scope no longer covers the gateways?")
    offenders = [f.render() for f in run.by_rule("async-hygiene")]
    assert not offenders, (
        "blocking calls on gateway event loops:\n" + "\n".join(offenders))


def test_async_delays_exist_and_are_loop_friendly():
    """Negative control: the edge stack genuinely delays (fault
    injection, QoS pacing, async acquisition) — it must do so via
    asyncio.sleep, so if those call sites vanished the lint above
    would be guarding an empty set."""
    found = 0
    for rel in (os.path.join("utils", "faults.py"),
                os.path.join("utils", "qos.py"),
                os.path.join("utils", "ratelimit.py")):
        with open(os.path.join(PKG_DIR, rel), encoding="utf-8") as f:
            if re.search(r"await asyncio\.sleep\(", f.read()):
                found += 1
    assert found == 3, (
        f"only {found}/3 edge modules still await asyncio.sleep — "
        "negative control lost its subject")
