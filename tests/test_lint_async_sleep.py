"""Fast tier-1 lint: no blocking ``time.sleep`` inside ``async def``
bodies in gateway / edge-QoS code.

The gateways are single event loops: one blocking sleep on the loop
thread stalls EVERY in-flight request behind it — which is exactly how
an "overload protection" layer would manufacture the overload it
exists to shed. The ROADMAP calls out the native fault-injection delay
(which sleeps on the IO thread, by design, outside the loop) as the
pattern NOT to reuse; the sanctioned shapes are ``await
asyncio.sleep(...)`` (faults.async_hook, qos middleware pacing) and
the reservation-style ``TokenBucket`` whose quotes async callers await
(utils/ratelimit.py).

AST-based: only calls lexically inside an ``async def`` body count.
A nested *sync* ``def`` (e.g. a worker handed to
``asyncio.to_thread``) legitimately may sleep — it runs off the loop —
so the scan does not descend into nested sync functions.
"""
import ast
import os

PKG_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "seaweedfs_tpu")

# everything that serves requests on an event loop, plus the edge
# stack the gateways compose (deadline/retry, fault injection, QoS,
# rate limiting)
SERVING_DIRS = ("server", "filer", "s3", "mount")
EDGE_MODULES = (os.path.join("utils", "qos.py"),
                os.path.join("utils", "retry.py"),
                os.path.join("utils", "faults.py"),
                os.path.join("utils", "ratelimit.py"))


def _iter_sources():
    seen = set()
    for sub in SERVING_DIRS:
        base = os.path.join(PKG_DIR, sub)
        if not os.path.isdir(base):
            continue
        for root, _dirs, files in os.walk(base):
            for fn in sorted(files):
                if fn.endswith(".py"):
                    path = os.path.join(root, fn)
                    seen.add(path)
                    yield path
    for rel in EDGE_MODULES:
        path = os.path.join(PKG_DIR, rel)
        if os.path.isfile(path) and path not in seen:
            yield path


def _is_time_sleep(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "sleep" and \
            isinstance(f.value, ast.Name) and f.value.id == "time":
        return True
    # `from time import sleep` style
    return isinstance(f, ast.Name) and f.id == "sleep"


def _blocking_sleeps_in_async(fn: ast.AsyncFunctionDef):
    """time.sleep call sites inside this async function's own body —
    NOT inside nested sync defs (those run off-loop via to_thread /
    executors) but INCLUDING nested async defs."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, ast.FunctionDef):
            continue  # sync nested def: off-loop by construction
        if isinstance(node, ast.Call) and _is_time_sleep(node):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def _collect():
    offenders, n_async = [], 0
    for path in _iter_sources():
        rel = os.path.relpath(path, PKG_DIR)
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
        for node in ast.walk(tree):
            if isinstance(node, ast.AsyncFunctionDef):
                n_async += 1
                for call in _blocking_sleeps_in_async(node):
                    offenders.append(
                        f"{rel}:{call.lineno}: time.sleep inside "
                        f"async def {node.name} — blocks the event "
                        "loop; await asyncio.sleep(...) instead")
    return offenders, n_async


def test_no_blocking_sleep_on_the_event_loop():
    offenders, n_async = _collect()
    assert n_async > 50, (
        f"only {n_async} async functions scanned — the lint's scope "
        "no longer covers the gateways?")
    assert not offenders, (
        "blocking sleeps on gateway event loops:\n"
        + "\n".join(offenders))


def test_async_delays_exist_and_are_loop_friendly():
    """Negative control: the edge stack genuinely delays (fault
    injection, QoS pacing, async acquisition) — it must do so via
    asyncio.sleep, so if those call sites vanished the lint above
    would be guarding an empty set."""
    import re

    found = 0
    for rel in (os.path.join("utils", "faults.py"),
                os.path.join("utils", "qos.py"),
                os.path.join("utils", "ratelimit.py")):
        with open(os.path.join(PKG_DIR, rel), encoding="utf-8") as f:
            if re.search(r"await asyncio\.sleep\(", f.read()):
                found += 1
    assert found == 3, (
        f"only {found}/3 edge modules still await asyncio.sleep — "
        "negative control lost its subject")
