"""Master follower: stateless lookup service fed by KeepConnected.

Mirrors the reference's weed/command/master_follower.go contract:
/dir/lookup?volumeId= and ?fileId= answered without touching the
leader once the push stream has warmed the cache.
"""
import time

import pytest
import requests

from seaweedfs_tpu.operation import verbs
from seaweedfs_tpu.rpc.http import ServerThread
from seaweedfs_tpu.server.cluster import Cluster
from seaweedfs_tpu.server.master_follower import MasterFollower


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    c = Cluster(str(tmp_path_factory.mktemp("mfol")),
                n_volume_servers=1, volume_size_limit=8 << 20)
    mf = MasterFollower(c.master_url)
    t = ServerThread(mf.build_app()).start()
    yield c, mf, t
    mf.client.stop()
    c.stop()


def test_lookup_by_volume_and_file_id(setup):
    c, mf, t = setup
    a = verbs.assign(c.master_url)
    verbs.upload(a, b"follower bytes")
    vid = int(a.fid.split(",")[0])
    r = requests.get(f"{t.url}/dir/lookup", params={"volumeId": str(vid)})
    assert r.status_code == 200
    locs = r.json()["locations"]
    assert any(l["url"] == a.url for l in locs)
    r2 = requests.get(f"{t.url}/dir/lookup", params={"fileId": a.fid})
    assert r2.status_code == 200
    assert r2.json()["locations"] == locs


def test_follower_serves_from_stream_cache(setup):
    """After the KeepConnected snapshot lands, lookups hit the local
    cache (no HTTP fallback): verified by the status volume count."""
    c, mf, t = setup
    verbs.assign(c.master_url)
    deadline = time.time() + 15
    while time.time() < deadline:
        n = requests.get(f"{t.url}/status").json()["cachedVolumes"]
        if n > 0:
            break
        time.sleep(0.2)
    assert n > 0


def test_lookup_errors(setup):
    _, _, t = setup
    assert requests.get(f"{t.url}/dir/lookup",
                        params={"volumeId": "999999"}).status_code == 404
    assert requests.get(f"{t.url}/dir/lookup",
                        params={"volumeId": "bogus"}).status_code == 400
