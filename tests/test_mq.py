"""Message-queue broker: topic lifecycle, partitioned publish,
subscribe/replay, durability across broker restarts, shell commands
(reference weed/mq/broker, mq.proto).
"""
import json

import pytest
import requests

from seaweedfs_tpu.server.cluster import Cluster
from seaweedfs_tpu.shell.env import CommandEnv
from seaweedfs_tpu.shell.repl import run_command


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = Cluster(str(tmp_path_factory.mktemp("mq_cluster")),
                n_volume_servers=1, volume_size_limit=16 << 20,
                with_filer=True)
    c.start_broker()
    yield c
    c.stop()


def broker(cluster):
    return cluster.broker_thread.url


def publish(cluster, topic, records, ns="default"):
    r = requests.post(f"{broker(cluster)}/topics/{ns}/{topic}/publish",
                      json={"records": records}, timeout=30)
    assert r.status_code == 200, r.text
    return r.json()["acks"]


def subscribe(cluster, topic, partition, offset=0, ns="default",
              idle=0.3, limit=0):
    r = requests.get(
        f"{broker(cluster)}/topics/{ns}/{topic}/subscribe",
        params={"partition": partition, "offset": offset,
                "idle_timeout": idle, "limit": limit}, timeout=60)
    assert r.status_code == 200, r.text
    return [json.loads(x) for x in r.text.splitlines() if x.strip()]


class TestTopicLifecycle:
    def test_create_list_describe_delete(self, cluster):
        b = broker(cluster)
        r = requests.post(f"{b}/topics/default/events",
                          json={"partitions": 3})
        assert r.status_code == 201
        assert r.json()["partitions"] == 3
        topics = requests.get(f"{b}/topics").json()["topics"]
        assert {"namespace": "default", "name": "events",
                "partitions": 3} in topics
        d = requests.get(f"{b}/topics/default/events").json()
        assert len(d["state"]) == 3
        assert requests.delete(
            f"{b}/topics/default/events").status_code == 204
        assert requests.get(
            f"{b}/topics/default/events").status_code == 404

    def test_cannot_shrink(self, cluster):
        b = broker(cluster)
        requests.post(f"{b}/topics/default/wide",
                      json={"partitions": 4})
        r = requests.post(f"{b}/topics/default/wide",
                          json={"partitions": 2})
        assert r.status_code == 409

    def test_publish_unknown_topic_404(self, cluster):
        r = requests.post(
            f"{broker(cluster)}/topics/default/nope/publish",
            json={"key": "k", "value": "v"})
        assert r.status_code == 404


class TestPubSub:
    def test_same_key_same_partition(self, cluster):
        b = broker(cluster)
        requests.post(f"{b}/topics/default/orders",
                      json={"partitions": 4})
        acks = publish(cluster, "orders",
                       [{"key": "user-1", "value": f"o{i}"}
                        for i in range(5)])
        parts = {a["partition"] for a in acks}
        assert len(parts) == 1
        assert [a["offset"] for a in acks] == list(range(5))

    def test_subscribe_replay_and_follow(self, cluster):
        b = broker(cluster)
        requests.post(f"{b}/topics/default/logs",
                      json={"partitions": 1})
        publish(cluster, "logs",
                [{"key": "a", "value": f"line-{i}"} for i in range(10)])
        got = subscribe(cluster, "logs", 0)
        assert [r["v"] for r in got] == [f"line-{i}" for i in range(10)]
        assert [r["o"] for r in got] == list(range(10))
        # resume from an offset
        got = subscribe(cluster, "logs", 0, offset=7)
        assert [r["v"] for r in got] == ["line-7", "line-8", "line-9"]

    def test_subscribe_after_flush(self, cluster):
        """Records must survive the memory->filer segment flush."""
        import time

        b = broker(cluster)
        requests.post(f"{b}/topics/default/flushy",
                      json={"partitions": 1})
        publish(cluster, "flushy",
                [{"key": "k", "value": f"v{i}"} for i in range(20)])
        time.sleep(1.5)  # > SEG_FLUSH_AGE: records now in the filer
        got = subscribe(cluster, "flushy", 0)
        assert len(got) == 20
        # and new records continue after the flushed ones
        publish(cluster, "flushy", [{"key": "k", "value": "after"}])
        got = subscribe(cluster, "flushy", 0, offset=20)
        assert [r["v"] for r in got] == ["after"]

    def test_binary_value_round_trip(self, cluster):
        import base64

        b = broker(cluster)
        requests.post(f"{b}/topics/default/bin", json={"partitions": 1})
        blob = bytes(range(256))
        r = requests.post(
            f"{b}/topics/default/bin/publish",
            json={"key": "k",
                  "value64": base64.b64encode(blob).decode()})
        assert r.status_code == 200
        got = subscribe(cluster, "bin", 0)
        assert base64.b64decode(got[0]["v64"]) == blob


class TestDurability:
    def test_broker_restart_preserves_offsets(self, cluster):
        import time

        b = broker(cluster)
        requests.post(f"{b}/topics/default/durable",
                      json={"partitions": 2})
        publish(cluster, "durable",
                [{"key": f"k{i}", "value": f"v{i}"} for i in range(12)])
        time.sleep(1.5)  # let segments flush
        before = requests.get(f"{b}/topics/default/durable").json()
        # restart the broker
        cluster.broker_thread.stop()
        new_url = cluster.start_broker()
        after = requests.get(
            f"{new_url}/topics/default/durable").json()
        assert sorted(p["next_offset"] for p in after["state"]) == \
            sorted(p["next_offset"] for p in before["state"])
        # replay still works through the new broker
        total = sum(len(subscribe(cluster, "durable", p))
                    for p in range(2))
        assert total == 12


class TestShell:
    def test_mq_topic_commands(self, cluster):
        env = CommandEnv(cluster.master_url,
                         filer_url=cluster.filer_url)
        out = run_command(
            env, "mq.topic.create -topic=shelltest -partitions=2")
        assert out["partitions"] == 2
        topics = run_command(env, "mq.topic.list")["topics"]
        assert any(t["name"] == "shelltest" for t in topics)
        d = run_command(env, "mq.topic.describe -topic=shelltest")
        assert len(d["state"]) == 2
        assert "deleted" in run_command(
            env, "mq.topic.delete -topic=shelltest")
