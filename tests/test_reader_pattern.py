"""Reader-pattern detection + readahead (VERDICT r3 item 8; reference
weed/filer/reader_pattern.go + reader_cache.go MaybeCache): sequential
readers get whole-chunk caching and one-chunk-ahead prefetch; random
readers get exact ranged fetches with no amplification.
"""
import threading
import time

import pytest

from seaweedfs_tpu.filer import stream as stream_mod
from seaweedfs_tpu.filer.entry import FileChunk
from seaweedfs_tpu.filer.stream import ChunkStreamReader, ReaderPattern


class TestReaderPattern:
    def test_sequential_stays_sequential(self):
        p = ReaderPattern()
        for i in range(10):
            p.monitor(i * 100, 100)
            assert not p.is_random

    def test_random_flips_after_limit(self):
        p = ReaderPattern()
        # one random jump is not enough to flip a fresh reader to
        # random mode permanently... counter goes 0 -> -1 -> random
        p.monitor(0, 10)      # counter 1 (0 == 0 start)
        p.monitor(500, 10)    # jump: counter 0
        assert not p.is_random
        p.monitor(90, 10)     # jump: counter -1
        assert p.is_random
        # sustained sequential reads flip it back (ModeChangeLimit=3
        # saturation means recovery takes a few)
        at = 1000
        for _ in range(3):
            p.monitor(at, 50)
            at += 50
        assert not p.is_random

    def test_counter_saturates(self):
        p = ReaderPattern()
        at = 0
        for _ in range(50):
            p.monitor(at, 10)
            at += 10
        # 50 sequential reads saturate at +3: three jumps flip it
        for off in (9000, 5, 7000, 13):
            p.monitor(off, 4)
        assert p.is_random


class _FakeVolume:
    """In-memory 'volume server' for stream tests: records whether each
    fetch was ranged or whole-chunk."""

    def __init__(self, chunks: dict[str, bytes]):
        self.data = chunks
        self.fetches: list[tuple[str, str]] = []  # (fid, kind)
        self.lock = threading.Lock()

    def lookup(self, fid: str) -> str:
        return f"http://fake/{fid}"

    def read_fid(self, lookup, fid, offset=0, size=None):
        with self.lock:
            self.fetches.append(
                (fid, "whole" if size is None and not offset
                 else "ranged"))
        data = self.data[fid]
        if size is None:
            return data[offset:]
        return data[offset:offset + size]


@pytest.fixture()
def fake(monkeypatch):
    chunks = {f"c{i}": bytes([i]) * 1000 for i in range(5)}
    fv = _FakeVolume(chunks)
    monkeypatch.setattr(stream_mod, "read_fid", fv.read_fid)
    return fv


def _chunks():
    return [FileChunk(fid=f"c{i}", offset=i * 1000, size=1000,
                      mtime_ns=i + 1) for i in range(5)]


def test_sequential_stream_prefetches_next_chunk(fake):
    r = ChunkStreamReader(fake.lookup, _chunks())
    try:
        # read straight through: every chunk fetched WHOLE, and the
        # one-ahead prefetch warms chunk i+1 while i is served
        got = r.read(0, 5000)
        assert got == b"".join(bytes([i]) * 1000 for i in range(5))
        kinds = {k for _f, k in fake.fetches}
        assert kinds == {"whole"}
        # every chunk fetched exactly once (prefetch dedupes with the
        # demand fetch)
        time.sleep(0.05)  # let the last prefetch settle
        fids = sorted(f for f, _k in fake.fetches)
        assert len(fids) == len(set(fids)) or \
            len(fids) <= 6  # at most one wasted tail prefetch
    finally:
        r.close()


def test_random_reads_stay_ranged(fake):
    r = ChunkStreamReader(fake.lookup, _chunks())
    try:
        # jump around: after the mode flips, partial views are ranged
        for off in (4200, 100, 3300, 900, 2500, 1700):
            got = r.read(off, 50)
            assert got == bytes([off // 1000]) * 50
        ranged = [f for f, k in fake.fetches if k == "ranged"]
        assert len(ranged) >= 3  # the post-flip reads
        # and NO chunk was cached from a ranged read
        assert len(r._cache) <= 2
    finally:
        r.close()


def test_warm_sequential_subchunk_reads_cache_whole_chunks(fake):
    """A persistent reader doing small sequential reads: cold reads are
    ranged (no amplification for one-shots), but once the pattern
    saturates (is_streaming) chunks come in whole and later sub-chunk
    reads are served from cache with readahead warming the next."""
    r = ChunkStreamReader(fake.lookup, _chunks())
    try:
        at = 0
        for _ in range(20):  # 50-byte sequential reads over 1KB chunks
            assert r.read(at, 50) == bytes([at // 1000]) * 50
            at += 50
        time.sleep(0.05)
        # after warm-up (3 reads), whole-chunk fetches take over:
        # 20 reads cover chunk 0 fully — FAR fewer than 20 fetches
        assert len(fake.fetches) < 10
        kinds = [k for _f, k in fake.fetches]
        assert "whole" in kinds  # the warmed-up fetches
        assert kinds[0] == "ranged"  # the cold reads stayed ranged
    finally:
        r.close()


def test_mount_random_read_no_amplification():
    """The mount handle's pattern: random 4KB reads of an 8MB-chunk
    file must fetch ranges, not whole chunks into the tiered cache."""
    from seaweedfs_tpu.mount.weedfs import FileHandle

    h = FileHandle(1, "/f", None, None)
    h.pattern.monitor(0, 4096)
    assert not h.pattern.is_random
    h.pattern.monitor(9_000_000, 4096)
    h.pattern.monitor(2_000_000, 4096)
    assert h.pattern.is_random
