"""Cluster membership + distributed lock manager tests.

Covers the reference's weed/cluster/cluster.go membership semantics and
lock_manager/distributed_lock_manager.go:13-93 (consistent-hash home
filer, moved hints, TTL expiry, renewal tokens), plus the shell's
cluster-wide admin lock riding on the DLM.
"""
import time

import pytest
import requests

from seaweedfs_tpu.cluster.lock_manager import (DistributedLockManager,
                                                DlmClient, LockMoved,
                                                LockNotOwned, LockRing)
from seaweedfs_tpu.cluster.membership import ClusterMembership


class TestMembership:
    def test_announce_list_expire(self):
        m = ClusterMembership(ttl_seconds=0.2)
        m.announce("f1:8888", "filer")
        m.announce("f2:8888", "filer")
        m.announce("b1:9999", "broker")
        assert [n.address for n in m.list_nodes("filer")] == \
            ["f1:8888", "f2:8888"]
        assert [n.address for n in m.list_nodes("broker")] == ["b1:9999"]
        time.sleep(0.25)
        m.announce("f1:8888", "filer")  # refresh just one
        assert [n.address for n in m.list_nodes("filer")] == ["f1:8888"]

    def test_leave(self):
        m = ClusterMembership()
        m.announce("f1:8888", "filer")
        m.leave("f1:8888", "filer")
        assert m.list_nodes("filer") == []

    def test_filer_group_filter(self):
        m = ClusterMembership()
        m.announce("f1:8888", "filer", filer_group="g1")
        m.announce("f2:8888", "filer", filer_group="g2")
        assert [n.address for n in m.list_nodes("filer", "g1")] == \
            ["f1:8888"]


class TestLockManagerUnit:
    def test_lock_unlock_roundtrip(self):
        dlm = DistributedLockManager("me")
        dlm.ring.set_servers(["me"])
        token = dlm.lock("job1", owner="alice", ttl=5)
        assert dlm.find_owner("job1") == "alice"
        dlm.unlock("job1", token)
        assert dlm.find_owner("job1") is None

    def test_contention_rejected(self):
        dlm = DistributedLockManager("me")
        dlm.ring.set_servers(["me"])
        dlm.lock("job1", owner="alice", ttl=5)
        with pytest.raises(PermissionError):
            dlm.lock("job1", owner="bob", ttl=5)
        # same owner without token is still refused: token is the proof
        with pytest.raises(PermissionError):
            dlm.lock("job1", owner="alice", ttl=5)

    def test_renewal_extends(self):
        dlm = DistributedLockManager("me")
        dlm.ring.set_servers(["me"])
        token = dlm.lock("job1", owner="alice", ttl=0.15)
        time.sleep(0.1)
        token2 = dlm.lock("job1", owner="alice", ttl=0.15, token=token)
        assert token2 == token
        time.sleep(0.1)
        assert dlm.find_owner("job1") == "alice"  # renewed past first ttl

    def test_ttl_expiry_allows_takeover(self):
        dlm = DistributedLockManager("me")
        dlm.ring.set_servers(["me"])
        dlm.lock("job1", owner="alice", ttl=0.1)
        time.sleep(0.15)
        dlm.lock("job1", owner="bob", ttl=5)  # expired -> takeover ok
        assert dlm.find_owner("job1") == "bob"

    def test_wrong_token_unlock(self):
        dlm = DistributedLockManager("me")
        dlm.ring.set_servers(["me"])
        dlm.lock("job1", owner="alice", ttl=5)
        with pytest.raises(LockNotOwned):
            dlm.unlock("job1", "bogus")

    def test_moved_when_not_home(self):
        ring = LockRing()
        ring.set_servers(["a:1", "b:2"])
        a = DistributedLockManager("a:1", ring)
        b = DistributedLockManager("b:2", ring)
        # find a name homed on b, then ask a for it
        name = next(n for n in (f"lk{i}" for i in range(64))
                    if ring.owner_of(n) == "b:2")
        with pytest.raises(LockMoved) as ei:
            a.lock(name, owner="x")
        assert ei.value.host == "b:2"
        b.lock(name, owner="x")  # home filer accepts

    def test_stale_renewal_cannot_resurrect(self):
        dlm = DistributedLockManager("me")
        dlm.ring.set_servers(["me"])
        token = dlm.lock("job1", owner="alice", ttl=5)
        dlm.unlock("job1", token)
        with pytest.raises(LockNotOwned):
            dlm.lock("job1", owner="alice", ttl=5, token=token)
        # expired lock: renewal is rejected too
        t2 = dlm.lock("job2", owner="bob", ttl=0.05)
        time.sleep(0.1)
        with pytest.raises(LockNotOwned):
            dlm.lock("job2", owner="bob", ttl=5, token=t2)

    def test_empty_ring_refuses_grants(self):
        from seaweedfs_tpu.cluster.lock_manager import RingEmpty

        dlm = DistributedLockManager("me")  # ring never populated
        with pytest.raises(RingEmpty):
            dlm.lock("job1", owner="alice")
        with pytest.raises(RingEmpty):
            dlm.find_owner("job1")

    def test_consistent_hash_stability_on_growth(self):
        ring = LockRing()
        ring.set_servers(["a:1", "b:2", "c:3"])
        before = {f"lk{i}": ring.owner_of(f"lk{i}") for i in range(200)}
        ring.set_servers(["a:1", "b:2", "c:3", "d:4"])
        moved = sum(1 for k, v in before.items()
                    if ring.owner_of(k) != v)
        # consistent hashing moves ~1/N of names, not ~all like mod-N
        assert moved < 120, f"{moved}/200 moved"

    def test_ring_consistency(self):
        ring = LockRing()
        ring.set_servers(["c:3", "a:1", "b:2"])
        homes = {ring.owner_of(f"lock{i}") for i in range(100)}
        assert homes <= {"a:1", "b:2", "c:3"}
        assert len(homes) > 1  # names spread across the ring


@pytest.fixture(scope="module")
def dlm_cluster(tmp_path_factory):
    """Master + 2 filers announcing membership; DLM over both."""
    from seaweedfs_tpu.rpc.http import ServerThread
    from seaweedfs_tpu.server.filer_server import FilerServer
    from seaweedfs_tpu.server.master_server import MasterServer

    master = MasterServer(pulse_seconds=0.4)
    mt = ServerThread(master.app).start()
    filers, threads = [], [mt]
    for _ in range(2):
        f = FilerServer(mt.url, announce_pulse=0.3)
        t = ServerThread(f.app).start()
        f.address = t.address
        filers.append(f)
        threads.append(t)
    # membership loop pulses every 3s; force a fast first ring by
    # waiting for both filers to appear in /cluster/nodes
    deadline = time.time() + 20
    while time.time() < deadline:
        nodes = requests.get(f"{mt.url}/cluster/nodes",
                             params={"type": "filer"}, timeout=3).json()
        if len(nodes["nodes"]) == 2 and \
                all(len(f.dlm.ring.servers()) == 2 for f in filers):
            break
        time.sleep(0.1)
    assert all(len(f.dlm.ring.servers()) == 2 for f in filers)
    yield {"master": mt, "filers": filers,
           "filer_urls": [t.address for t in threads[1:]]}
    for t in threads:
        t.stop()


class TestDlmOverHttp:
    def test_lock_routes_by_ring_and_follows_moved(self, dlm_cluster):
        c = DlmClient(dlm_cluster["filer_urls"], owner="worker-1")
        c.lock("migrate-vol-7")
        assert c.is_held("migrate-vol-7")
        # a second client contends and is refused
        c2 = DlmClient(dlm_cluster["filer_urls"], owner="worker-2")
        with pytest.raises(RuntimeError, match="held by"):
            c2.lock("migrate-vol-7")
        assert c2.find_owner("migrate-vol-7") == "worker-1"
        c.unlock("migrate-vol-7")
        c2.lock("migrate-vol-7")  # now free
        c2.close()
        c.close()

    def test_admin_lock_via_shell_env(self, dlm_cluster):
        from seaweedfs_tpu.shell.env import CommandEnv, ShellError

        env = CommandEnv(dlm_cluster["master"].url,
                         filer_url=dlm_cluster["filer_urls"][0])
        env.acquire_lock()
        env.confirm_locked()
        # second operator cannot take the admin lock concurrently
        env2 = CommandEnv(dlm_cluster["master"].url,
                          filer_url=dlm_cluster["filer_urls"][1])
        with pytest.raises(ShellError):
            env2.acquire_lock()
        env.release_lock()
        env2.acquire_lock()
        env2.release_lock()
