"""Redis Cluster filer store: slot routing, MOVED refresh mid-run,
ASK one-shots, and cross-slot listing pages — driven against the
in-process mini cluster (tests/minirediscluster.py). Reference:
weed/filer/redis/redis_cluster_store.go:35."""
import pytest

from seaweedfs_tpu.filer import Entry, FileChunk
from seaweedfs_tpu.filer.filerstore import make_store
from seaweedfs_tpu.filer.redis_cluster_store import SLOTS, key_slot
from tests.minirediscluster import MiniRedisCluster


@pytest.fixture
def cluster():
    c = MiniRedisCluster(3)
    yield c
    c.close()


@pytest.fixture
def store(cluster):
    s = make_store("redis_cluster", host=cluster.seeds)
    yield s
    s.close()


def _entry(path, size=1):
    return Entry(full_path=path, chunks=[
        FileChunk(fid="1,abc123", offset=0, size=size, mtime_ns=1)])


def test_key_slot_spec_vectors():
    """Published CRC16 slot assignments (redis cluster spec examples)
    + the hash-tag rule."""
    assert key_slot("foo") == 12182
    assert key_slot("bar") == 5061
    assert key_slot("") == 0
    # {user1000}.following and {user1000}.followers share a slot
    assert key_slot("{user1000}.following") == \
        key_slot("{user1000}.followers") == key_slot("user1000")
    # empty/unclosed tags hash the whole key
    assert key_slot("foo{}bar") != key_slot("")
    assert key_slot("foo{bar") == key_slot("foo{bar")


def test_crud_spreads_across_nodes(cluster, store):
    paths = [f"/buckets/rc/k{i:03d}" for i in range(60)]
    for p in paths:
        store.insert_entry(_entry(p))
    # the keyspace genuinely spread over multiple nodes
    populated = sum(1 for nd in cluster.nodes if nd.kv)
    assert populated >= 2
    for p in paths:
        e = store.find_entry(p)
        assert e is not None and e.full_path == p
    # listing pages MGET across slots (per-node pipelines)
    names = [e.name for e in
             store.list_directory_entries("/buckets/rc", limit=100)]
    assert names == sorted(f"k{i:03d}" for i in range(60))
    store.delete_entry(paths[0])
    assert store.find_entry(paths[0]) is None


def test_moved_redirect_mid_run(cluster, store):
    """A live slot migration mid-run: the next command on a moved slot
    gets -MOVED, the client rebuilds its map and follows — no errors
    surface to the store's caller."""
    store.insert_entry(_entry("/buckets/mv/a"))
    store.insert_entry(_entry("/buckets/mv/b"))
    before = cluster.redirects
    # move EVERY slot owned by node 0 to node 1, data included
    cluster.migrate(0, SLOTS // 3 - 1, 1)
    # old map in the client is now stale for those slots
    for i in range(40):
        store.insert_entry(_entry(f"/buckets/mv/post{i:02d}"))
    assert cluster.redirects > before, "migration never exercised MOVED"
    for i in range(40):
        assert store.find_entry(f"/buckets/mv/post{i:02d}") is not None
    assert store.find_entry("/buckets/mv/a") is not None
    names = [e.name for e in
             store.list_directory_entries("/buckets/mv", limit=100)]
    assert len(names) == 42


def test_ask_redirect_one_shot(cluster, store):
    """During an ASK window the source answers -ASK without map
    changes; the client must prefix ASKING on the target and NOT
    remember the redirect."""
    path = "/buckets/ask/victim"
    slot = key_slot(path)
    dst = (cluster.owner[slot] + 1) % 3
    cluster.start_ask_window(slot, dst)
    store.insert_entry(_entry(path))  # -ASK -> ASKING SET on dst
    e = store.find_entry(path)
    assert e is not None
    cluster.end_ask_window(slot, dst)
    assert store.find_entry(path) is not None


def test_dead_node_recovers_after_remap(cluster, store):
    """A node death + slot takeover: the client's dropped connection
    triggers a map refresh against surviving nodes."""
    store.insert_entry(_entry("/buckets/dn/x"))
    # node 2's slots move to node 0, then node 2 dies
    lo = 2 * (SLOTS // 3)
    cluster.migrate(lo, SLOTS - 1, 0)
    cluster.nodes[2].close()
    for i in range(30):
        p = f"/buckets/dn/y{i:02d}"
        store.insert_entry(_entry(p))
        assert store.find_entry(p) is not None


def test_store_registered_with_seed_parsing():
    with pytest.raises(ValueError):
        make_store("redis_cluster", host="")
