"""Real-protobuf goldens for the gRPC-wire filer stores.

The ydb/tikv stores hand-roll their protobuf bytes through grpc_lite;
until now those bytes were validated only against the in-repo mini
servers, written by the same hand from the same public protos — a
misread encoding rule would pass both sides. Here the REAL protobuf
runtime (via protoc-compiled mirrors of the public message subsets,
tests/protos/*.proto) produces the goldens:

- every request the stores emit must match the runtime's encoding
  byte for byte, and
- runtime-encoded responses must decode through the stores' own
  parsing into the right Python values.

This breaks the encoder/decoder circularity. The residual assumption
is the transcription of FIELD NUMBERS from the public protos into the
mirrors — reviewable by diffing tests/protos/ against ydb-api-protos
and kvproto — recorded in PARITY.md alongside the live-server caveat.
"""
from __future__ import annotations

import shutil
import subprocess
import sys

import pytest

PROTOC = shutil.which("protoc")
pytestmark = pytest.mark.skipif(PROTOC is None, reason="no protoc")
pytest.importorskip("google.protobuf", minversion="4.21")

import os

HERE = os.path.dirname(os.path.abspath(__file__))
PROTO_DIR = os.path.join(HERE, "protos")


@pytest.fixture(scope="module")
def msgs(tmp_path_factory):
    """protoc-compile the mirrors, load them into a fresh descriptor
    pool, return a name -> message-class resolver."""
    from google.protobuf import (descriptor_pb2, descriptor_pool,
                                 message_factory)

    out = tmp_path_factory.mktemp("pb") / "mirror.desc"
    proc = subprocess.run(
        [PROTOC, f"-I{PROTO_DIR}", f"--descriptor_set_out={out}",
         "ydb_value_mirror.proto", "ydb_table_mirror.proto",
         "kvrpcpb_mirror.proto"],
        capture_output=True, text=True)
    assert proc.returncode == 0, f"protoc failed:\n{proc.stderr}"
    fds = descriptor_pb2.FileDescriptorSet()
    fds.ParseFromString(out.read_bytes())
    pool = descriptor_pool.DescriptorPool()
    for f in fds.file:
        pool.Add(f)

    def resolve(name: str):
        return message_factory.GetMessageClass(
            pool.FindMessageTypeByName(name))

    return resolve


class _CaptureChannel:
    """GrpcChannel double: records each unary request's raw bytes and
    replays runtime-encoded response bytes."""

    def __init__(self):
        self.calls: list[tuple[str, bytes]] = []
        self.responses: list[bytes] = []

    def unary(self, method: str, req: bytes, metadata=None) -> bytes:
        self.calls.append((method, req))
        return self.responses.pop(0)

    def close(self) -> None:
        pass


# -- ydb ----------------------------------------------------------------

SUCCESS = 400000


def _op_response(msgs, wrapper: str, result_msg=None) -> bytes:
    Any = msgs("Ydb.Table.AnyMirror")
    Op = msgs("Ydb.Table.Operation")
    W = msgs(wrapper)
    op = Op(ready=True, status=SUCCESS)
    if result_msg is not None:
        op.result.CopyFrom(Any(
            type_url="type.googleapis.com/" + result_msg.DESCRIPTOR.full_name,
            value=result_msg.SerializeToString()))
    return W(operation=op).SerializeToString()


class TestYdbGoldens:
    def test_typed_value_params(self, msgs):
        """p_int64/p_uint64/p_utf8/p_string vs the runtime's
        TypedValue encoding — incl. the negative-int64 10-byte varint
        (dir_hash IS frequently negative) and zero inside a oneof
        (which proto3 still serializes)."""
        from seaweedfs_tpu.filer.ydb_store import (T_INT64, T_STRING,
                                                   T_UINT64, T_UTF8,
                                                   p_int64, p_string,
                                                   p_uint64, p_utf8)
        TV = msgs("Ydb.TypedValue")

        def golden(type_id, **value_fields):
            tv = TV()
            tv.type.type_id = type_id
            for k, v in value_fields.items():
                setattr(tv.value, k, v)
            return tv.SerializeToString(deterministic=True)

        for v in (0, 1, 127, 128, 2**31, 2**63 - 1, -1, -2**63,
                  -123456789):
            assert p_int64(v) == golden(T_INT64, int64_value=v), v
        for v in (0, 1, 2**64 - 1, 2**63):
            assert p_uint64(v) == golden(T_UINT64, uint64_value=v), v
        for s in ("", "name.txt", "café ☕", "a" * 300):
            assert p_utf8(s) == golden(T_UTF8, text_value=s), s
        for b in (b"", b"\x00\xff" * 10, bytes(range(256))):
            assert p_string(b) == golden(T_STRING, bytes_value=b)

    def test_execute_request_bytes_and_response_decode(self, msgs):
        """The full ExecuteDataQueryRequest a FIND emits matches the
        runtime encoding; a runtime-encoded response decodes through
        the store's generic parser into the right rows."""
        from seaweedfs_tpu.filer.ydb_store import _Ydb, p_int64, p_utf8

        Req = msgs("Ydb.Table.ExecuteDataQueryRequest")
        TV = msgs("Ydb.TypedValue")
        RS = msgs("Ydb.ResultSet")
        Val = msgs("Ydb.Value")
        ExecResult = msgs("Ydb.Table.ExecuteQueryResult")
        SessResult = msgs("Ydb.Table.CreateSessionResult")

        ch = _CaptureChannel()
        db = _Ydb.__new__(_Ydb)
        db.ch, db.meta, db.database, db.session = ch, [], "/local", ""

        yql = "SELECT meta FROM filemeta WHERE dir_hash = $a;"
        # session mint + data query (params in sorted order so the
        # deterministic map serialization lines up)
        ch.responses.append(_op_response(
            msgs, "Ydb.Table.CreateSessionResponse",
            SessResult(session_id="sess-7")))
        rs = RS(truncated=True)
        row = rs.rows.add()
        row.items.add().text_value = "doc.txt"
        row.items.add().bytes_value = b'{"full_path": "/d/doc.txt"}'
        ch.responses.append(_op_response(
            msgs, "Ydb.Table.ExecuteDataQueryResponse",
            ExecResult(result_sets=[rs])))

        rows, truncated = db.execute(yql, {
            "$dir_hash": p_int64(-5187234712),
            "$name": p_utf8("doc.txt"),
        })

        # request golden
        golden = Req(session_id="sess-7")
        golden.tx_control.begin_tx.serializable_read_write.SetInParent()
        golden.tx_control.commit_tx = True
        golden.query.yql_text = yql
        golden.parameters["$dir_hash"].CopyFrom(
            TV.FromString(p_int64(-5187234712)))
        golden.parameters["$name"].CopyFrom(
            TV.FromString(p_utf8("doc.txt")))
        method, req = ch.calls[1]
        assert method.endswith("/ExecuteDataQuery")
        assert req == golden.SerializeToString(deterministic=True)
        # response decoded through the store's own parser
        assert truncated is True
        assert len(rows) == 1 and len(rows[0]) == 2
        from seaweedfs_tpu.filer.ydb_store import _cell_bytes
        assert _cell_bytes(rows[0][0]) == b"doc.txt"
        assert _cell_bytes(rows[0][1]) == b'{"full_path": "/d/doc.txt"}'

    def test_scheme_request_bytes(self, msgs):
        from seaweedfs_tpu.filer.ydb_store import SCHEME, _Ydb

        Req = msgs("Ydb.Table.ExecuteSchemeQueryRequest")
        SessResult = msgs("Ydb.Table.CreateSessionResult")
        ch = _CaptureChannel()
        db = _Ydb.__new__(_Ydb)
        db.ch, db.meta, db.database, db.session = ch, [], "/local", ""
        ch.responses.append(_op_response(
            msgs, "Ydb.Table.CreateSessionResponse",
            SessResult(session_id="s")))
        # ExecuteSchemeQueryResponse has the same {operation=1} wire
        # shape as every Ydb response wrapper
        ch.responses.append(_op_response(
            msgs, "Ydb.Table.CreateSessionResponse"))
        db.scheme(SCHEME)
        _, req = ch.calls[1]
        assert req == Req(session_id="s", yql_text=SCHEME
                          ).SerializeToString(deterministic=True)


# -- tikv ---------------------------------------------------------------

class TestTikvGoldens:
    def _store(self, msgs):
        from seaweedfs_tpu.filer.tikv_store import TikvStore

        ch = _CaptureChannel()
        store = TikvStore.__new__(TikvStore)
        store.ch = ch
        return store, ch

    def test_raw_verbs_request_bytes(self, msgs):
        store, ch = self._store(msgs)
        GetReq = msgs("kvrpcpb.RawGetRequest")
        GetResp = msgs("kvrpcpb.RawGetResponse")
        PutReq = msgs("kvrpcpb.RawPutRequest")
        DelReq = msgs("kvrpcpb.RawDeleteRequest")
        DelRangeReq = msgs("kvrpcpb.RawDeleteRangeRequest")
        ScanReq = msgs("kvrpcpb.RawScanRequest")
        Empty = msgs("kvrpcpb.RawPutResponse")

        key = b"m" + bytes(range(20)) + "naïve.txt".encode()
        ch.responses = [GetResp(not_found=True).SerializeToString(),
                        Empty().SerializeToString(),
                        Empty().SerializeToString(),
                        Empty().SerializeToString(),
                        msgs("kvrpcpb.RawScanResponse")()
                        .SerializeToString()]
        assert store._raw_get(key) is None
        store._raw_put(key, b"\x00\xffvalue")
        store._raw_delete(key)
        store._raw_delete_range(b"m\x01", b"m\x02")
        assert store._raw_scan(b"maa", b"mzz", 7) == []

        goldens = [
            GetReq(key=key),
            PutReq(key=key, value=b"\x00\xffvalue"),
            DelReq(key=key),
            DelRangeReq(start_key=b"m\x01", end_key=b"m\x02"),
            ScanReq(start_key=b"maa", limit=7, end_key=b"mzz"),
        ]
        for (method, req), g in zip(ch.calls, goldens, strict=True):
            assert req == g.SerializeToString(deterministic=True), method

    def test_response_decoding_and_errors(self, msgs):
        store, ch = self._store(msgs)
        GetResp = msgs("kvrpcpb.RawGetResponse")
        ScanResp = msgs("kvrpcpb.RawScanResponse")

        # value present / empty-but-existing / region error / error
        ch.responses = [GetResp(value=b"data").SerializeToString()]
        assert store._raw_get(b"k1") == b"data"
        # proto3 omits empty bytes: existing key with b"" value is
        # signalled only by not_found staying false
        ch.responses = [GetResp().SerializeToString()]
        assert store._raw_get(b"k2") == b""
        region = GetResp()
        region.region_error.message = "epoch_not_match"
        ch.responses = [region.SerializeToString()]
        with pytest.raises(IOError, match="region error"):
            store._raw_get(b"k3")
        ch.responses = [GetResp(error="key error").SerializeToString()]
        with pytest.raises(IOError, match="key error"):
            store._raw_get(b"k4")

        scan = ScanResp()
        for i in range(3):
            kv = scan.kvs.add()
            kv.key = b"mkey%d" % i
            kv.value = b"val%d" % i
        ch.responses = [scan.SerializeToString()]
        assert store._raw_scan(b"m", b"", 10) == [
            (b"mkey0", b"val0"), (b"mkey1", b"val1"),
            (b"mkey2", b"val2")]

    def test_entry_roundtrip_through_runtime_wire(self, msgs):
        """insert_entry/find_entry end to end over runtime-encoded
        responses: the key layout and the JSON meta both survive."""
        from seaweedfs_tpu.filer.entry import Entry
        from seaweedfs_tpu.filer.tikv_store import _entry_key

        store, ch = self._store(msgs)
        GetResp = msgs("kvrpcpb.RawGetResponse")
        PutReq = msgs("kvrpcpb.RawPutRequest")
        Empty = msgs("kvrpcpb.RawPutResponse")

        e = Entry(full_path="/photos/cat.jpg", mode=0o644)
        ch.responses = [Empty().SerializeToString()]
        store.insert_entry(e)
        _, raw_req = ch.calls[0]
        put = PutReq.FromString(raw_req)
        assert put.key == _entry_key("/photos", "cat.jpg")
        ch.responses = [GetResp(value=put.value).SerializeToString()]
        got = store.find_entry("/photos/cat.jpg")
        assert got is not None and got.full_path == "/photos/cat.jpg"
        assert got.mode == 0o644
