"""Force-device e2e encode/rebuild (VERDICT r2 item 8): the auto
router's DEVICE arm executed through the real production file paths,
golden-bits-checked — not just coded_matmul units.

Under the test conftest (JAX_PLATFORMS=cpu, 8 virtual devices) the
device backend is "jax", which runs the exact same depth-bounded
streaming pipeline (H2D/compute/D2H via JaxCodec slabbing) the pallas
backend shares; on a machine with a real accelerator the same test
rides it with the fused pallas kernel. Either way, write_ec_files and
rebuild_ec_files run their device-streaming arm end to end.
"""
import os

import numpy as np
import pytest

from seaweedfs_tpu.ec import geometry as geo
from seaweedfs_tpu.ec.encoder import (rebuild_ec_files, verify_ec_files,
                                      write_ec_files)


def _device_backend() -> str:
    import jax

    if any(d.platform != "cpu" for d in jax.devices()):
        return "pallas"  # real accelerator: the fused kernel path
    return "jax"  # CPU test mesh: same streaming pipeline, XLA kernel


@pytest.fixture()
def volume(tmp_path):
    base = str(tmp_path / "1")
    rng = np.random.default_rng(42)
    data = rng.integers(0, 256, 3 << 20, dtype=np.uint8).tobytes()
    with open(base + ".dat", "wb") as f:
        f.write(data)
    open(base + ".idx", "wb").close()
    return base, data


def _shard_bytes(base):
    out = {}
    for i in range(geo.TOTAL_SHARDS):
        with open(base + geo.shard_ext(i), "rb") as f:
            out[i] = f.read()
    return out


def test_device_encode_golden_bits(volume, tmp_path):
    base, data = volume
    backend = _device_backend()
    # small chunk: several streaming pipeline iterations, not one
    write_ec_files(base, backend=backend, chunk=1 << 20,
                   small_block=256 << 10)
    dev_shards = _shard_bytes(base)

    # golden: the CPU reference codec over a fresh copy of the volume
    base2 = str(tmp_path / "2")
    with open(base2 + ".dat", "wb") as f:
        f.write(data)
    open(base2 + ".idx", "wb").close()
    write_ec_files(base2, backend="numpy", chunk=1 << 20,
                   small_block=256 << 10)
    for i in range(geo.TOTAL_SHARDS):
        with open(base2 + geo.shard_ext(i), "rb") as f:
            assert f.read() == dev_shards[i], f"shard {i} diverges"


def test_device_rebuild_golden_bits(volume):
    base, _ = volume
    backend = _device_backend()
    write_ec_files(base, backend=backend, chunk=1 << 20,
                   small_block=256 << 10)
    golden = _shard_bytes(base)
    # knock out a data shard and a parity shard, rebuild on device
    for i in (2, 12):
        os.remove(base + geo.shard_ext(i))
    rebuilt = rebuild_ec_files(base, backend=backend, chunk=1 << 20)
    assert sorted(rebuilt) == [2, 12]
    assert _shard_bytes(base) == golden
    assert verify_ec_files(base, backend=backend, chunk=1 << 20)


def test_env_override_routes_auto(volume, monkeypatch):
    """SEAWEEDFS_TPU_EC_BACKEND pins the auto router's choice — the
    production switch the force-device deployment would set."""
    from seaweedfs_tpu.ec import backend as ecb

    base, _ = volume
    backend = _device_backend()
    monkeypatch.setenv("SEAWEEDFS_TPU_EC_BACKEND", backend)
    ecb._auto_choice = None
    try:
        assert ecb.choose_auto_backend() == backend
        write_ec_files(base, backend="auto", chunk=1 << 20,
                       small_block=256 << 10)
        assert verify_ec_files(base, backend="numpy", chunk=1 << 20)
    finally:
        ecb._auto_choice = None
