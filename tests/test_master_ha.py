"""Multi-master HA over real HTTP: raft election, leader proxying of
control verbs, and volume-id agreement across masters.

Mirrors the reference's multi-master mode (weed master -peers=...,
/root/reference/weed/server/raft_hashicorp.go + leader proxy
master_server.go:219).
"""
import os
import socket
import time

import pytest
import requests

from seaweedfs_tpu.rpc.http import ServerThread
from seaweedfs_tpu.server.master_server import MasterServer
from seaweedfs_tpu.server.volume_server import VolumeServer
from seaweedfs_tpu.storage.store import Store


def free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


@pytest.fixture(scope="module")
def ha(tmp_path_factory):
    base = tmp_path_factory.mktemp("ha")
    ports = free_ports(3)
    peers = [f"127.0.0.1:{p}" for p in ports]
    masters, threads = [], []
    for me, port in zip(peers, ports):
        m = MasterServer(pulse_seconds=0.4, me=me, peers=peers,
                         raft_state_dir=str(base), raft_tick=0.6)
        masters.append(m)
        threads.append(ServerThread(m.app, port=port).start())

    # wait for a stable leader
    leader_addr = None
    deadline = time.time() + 20
    while time.time() < deadline:
        states = []
        for p in peers:
            try:
                states.append(requests.get(
                    f"http://{p}/raft/status", timeout=2).json())
            except Exception:
                states.append(None)
        leaders = [s["me"] for s in states if s and s["state"] == "leader"]
        agreed = {s["leader"] for s in states if s and s["leader"]}
        if len(leaders) == 1 and agreed == {leaders[0]}:
            leader_addr = leaders[0]
            break
        time.sleep(0.1)
    assert leader_addr, "no stable leader"

    # one volume server heartbeating at the leader
    vol_dir = os.path.join(str(base), "vol0")
    os.makedirs(vol_dir, exist_ok=True)
    store = Store([vol_dir], ip="127.0.0.1", port=0, ec_backend="numpy")
    vs = VolumeServer(store, f"http://{leader_addr}", pulse_seconds=0.3)
    vt = ServerThread(vs.app).start()
    store.port = vt.port
    store.public_url = vt.address
    deadline = time.time() + 15
    while time.time() < deadline:
        topo = requests.get(f"http://{leader_addr}/dir/status",
                            timeout=2).json()["Topology"]
        n = sum(len(r["nodes"]) for dc in topo["datacenters"]
                for r in dc["racks"])
        if n >= 1:
            break
        time.sleep(0.1)

    yield {"peers": peers, "leader": leader_addr, "masters": masters}
    for t in threads:
        t.stop()
    vt.stop()


def test_one_leader_elected(ha):
    flags = [requests.get(f"http://{p}/cluster/status", timeout=2).json()
             for p in ha["peers"]]
    assert sum(1 for f in flags if f["IsLeader"]) == 1
    assert all(f["Leader"] == ha["leader"] for f in flags if not f["IsLeader"])


def test_follower_redirects_assign_to_leader(ha):
    followers = [p for p in ha["peers"] if p != ha["leader"]]
    r = requests.get(f"http://{followers[0]}/dir/assign", timeout=10)
    assert r.history, "expected a 307 leader redirect"
    assert ha["leader"] in r.url
    assert r.status_code == 200 and "fid" in r.json()


def test_follower_redirects_lookup_to_leader(ha):
    # grow happened via assign; looking up that volume on a follower
    # must redirect to the leader (topology lives on the leader)
    r = requests.get(f"http://{ha['leader']}/dir/assign", timeout=10)
    vid = r.json()["fid"].split(",")[0]
    follower = [p for p in ha["peers"] if p != ha["leader"]][0]
    r = requests.get(f"http://{follower}/dir/lookup",
                     params={"volumeId": vid}, timeout=10)
    assert r.history and ha["leader"] in r.url
    assert r.json()["locations"]


def test_max_volume_id_replicated_to_followers(ha):
    # assign (possibly growing a volume) through the leader...
    r = requests.get(f"http://{ha['leader']}/dir/assign", timeout=10)
    assert r.status_code == 200
    lead_max = requests.get(f"http://{ha['leader']}/raft/status",
                            timeout=2).json()["max_volume_id"]
    assert lead_max >= 1
    # ...and every follower's raft FSM converges to the same mark
    deadline = time.time() + 10
    while time.time() < deadline:
        marks = [requests.get(f"http://{p}/raft/status", timeout=2)
                 .json()["max_volume_id"] for p in ha["peers"]]
        if all(m == lead_max for m in marks):
            break
        time.sleep(0.1)
    assert all(m == lead_max for m in marks)
    # and into each master's topology high-water mark
    for m in ha["masters"]:
        assert m.topo.max_volume_id >= lead_max
