"""Master redundancy watchdog: deficit detection from heartbeat loss,
/debug/repair visibility, and the bounded repair queue that drives
volume re-replication back to full redundancy (PR 4 tentpole)."""
import time

import pytest
from seaweedfs_tpu.operation import verbs
from seaweedfs_tpu.rpc.httpclient import session
from seaweedfs_tpu.server.cluster import Cluster


def _wait(pred, timeout=15, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.1)
    raise TimeoutError(f"{msg} never became true")


def _locations(cluster, vid):
    r = session().get(cluster.master_url + "/dir/lookup",
                      params={"volumeId": str(vid)}, timeout=5).json()
    return [loc["url"] for loc in r.get("locations", [])]


def _repair(cluster):
    return session().get(cluster.master_url + "/debug/repair",
                         timeout=5).json()


def _kill_holder(cluster, vid):
    """Stop the server thread of one replica holder; returns its url."""
    victim = next(i for i, s in enumerate(cluster.stores)
                  if s.find_volume(vid) is not None)
    url = cluster.stores[victim].public_url
    cluster.volume_threads[victim].stop()
    return url


def _write_replicated(cluster, n=5):
    a0 = verbs.assign(cluster.master_url, replication="001")
    vid = int(a0.fid.split(",")[0])
    verbs.upload(a0, b"watchdog-payload-0")
    fids = [a0.fid]
    for i in range(1, n):
        a = verbs.assign(cluster.master_url, replication="001")
        verbs.upload(a, b"watchdog-payload-%d" % i)
        if int(a.fid.split(",")[0]) == vid:
            fids.append(a.fid)
    return vid, fids


class TestDeficitVisibility:
    """Watchdog disabled: deficits are surfaced and tracked as pending
    work, but nothing repairs on its own (volume.fix.replication and
    the chaos e2e rely on manual control)."""

    @pytest.fixture()
    def cluster(self, tmp_path):
        c = Cluster(str(tmp_path), n_volume_servers=3,
                    pulse_seconds=0.3, volume_size_limit=8 << 20,
                    repair_enabled=False, repair_interval=0.5)
        yield c
        c.stop()

    def test_under_replicated_surfaced_and_pending(self, cluster):
        vid, _ = _write_replicated(cluster)
        assert len(_locations(cluster, vid)) == 2
        _kill_holder(cluster, vid)
        _wait(lambda: any(u["volume"] == vid for u in session().get(
            cluster.master_url + "/cluster/status", timeout=5
        ).json()["UnderReplicated"]), msg="deficit in /cluster/status")
        st = session().get(cluster.master_url + "/cluster/status",
                           timeout=5).json()
        row = next(u for u in st["UnderReplicated"]
                   if u["volume"] == vid)
        assert (row["have"], row["want"]) == (1, 2)
        assert st["RepairEnabled"] is False
        rep = _repair(cluster)
        assert rep["enabled"] is False
        assert any(p["volume"] == vid and p["kind"] == "replica"
                   for p in rep["pending"])
        # nothing is being repaired behind the operator's back
        assert rep["queue_depth"] == 0 and rep["in_flight"] == []

    def test_manual_enqueue_validation(self, cluster):
        r = session().post(cluster.master_url + "/debug/repair",
                           json={"volume": 1, "kind": "bogus"},
                           timeout=5)
        assert r.status_code == 400
        r = session().post(cluster.master_url + "/debug/repair",
                           json={"volume": "x", "kind": "replica"},
                           timeout=5)
        assert r.status_code == 400
        # non-JSON body: 400 with a JSON error, not a 500
        r = session().post(cluster.master_url + "/debug/repair",
                           data=b"\x00not json",
                           headers={"Content-Type":
                                    "application/json"},
                           timeout=5)
        assert r.status_code == 400
        assert "error" in r.json()
        # JSON but not an object
        r = session().post(cluster.master_url + "/debug/repair",
                           json=[1, 2, 3], timeout=5)
        assert r.status_code == 400
        assert "error" in r.json()
        # non-positive volume ids are never silently accepted
        for bad_vid in (0, -3):
            r = session().post(cluster.master_url + "/debug/repair",
                               json={"volume": bad_vid,
                                     "kind": "replica"}, timeout=5)
            assert r.status_code == 400, bad_vid
            assert "error" in r.json()
        r = session().post(cluster.master_url + "/debug/repair",
                           json={"volume": 7, "kind": "replica",
                                 "reason": "test"}, timeout=5)
        assert r.status_code == 200
        body = r.json()
        assert body["accepted"] is True and body["enabled"] is False
        assert (7, "replica") in {(p["volume"], p["kind"])
                                  for p in _repair(cluster)["pending"]}


class TestAutoRepair:
    @pytest.fixture()
    def cluster(self, tmp_path):
        c = Cluster(str(tmp_path), n_volume_servers=3,
                    pulse_seconds=0.3, volume_size_limit=8 << 20,
                    repair_enabled=True, repair_interval=0.5)
        yield c
        c.stop()

    def test_replica_restored_within_interval(self, cluster):
        vid, fids = _write_replicated(cluster)
        dead = _kill_holder(cluster, vid)
        # the watchdog notices the loss and re-replicates without any
        # operator involvement
        _wait(lambda: len(_locations(cluster, vid)) == 2
              and dead not in _locations(cluster, vid),
              timeout=20, msg="replica restored")
        rep = _repair(cluster)
        assert rep["enabled"] is True
        oks = [r for r in rep["recent"]
               if r["volume"] == vid and r["ok"]]
        assert oks and oks[-1]["kind"] == "replica"
        # deficit views drained back to clean
        _wait(lambda: session().get(
            cluster.master_url + "/cluster/status", timeout=5
        ).json()["UnderReplicated"] == [], msg="deficit cleared")
        # every payload is served by the healed copy too
        for fid in fids:
            for url in _locations(cluster, vid):
                assert session().get(f"http://{url}/{fid}",
                                     timeout=5).status_code == 200
        # repair metrics surfaced
        text = session().get(cluster.master_url + "/metrics",
                             timeout=5).text
        assert "repair_seconds" in text
        assert "repair_bytes_total" in text
        assert "repair_queue_depth" in text

    def test_snapshot_shape(self, cluster):
        rep = _repair(cluster)
        for key in ("enabled", "interval", "concurrency",
                    "max_attempts", "grace", "queue_depth",
                    "scan_count", "under_replicated", "under_parity",
                    "pending", "in_flight", "recent"):
            assert key in rep, key
        assert rep["interval"] == 0.5 and rep["concurrency"] == 2
