"""5-byte offset variant (the reference's `5BytesOffset` build tag,
offset_5bytes.go): 17-byte index entries, 8TiB volume ceiling.

The mode is process-wide (selected at import via WEED_5BYTES_OFFSET=1,
like a build tag), so the full storage/EC behavior check runs the
existing suites in a subprocess with the env set; in-process tests here
only verify the byte layout contract against hand-built fixtures.
"""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def in_5b_subprocess(code: str) -> str:
    env = dict(os.environ, WEED_5BYTES_OFFSET="1", PYTHONPATH=REPO,
               JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr
    return out.stdout


def test_entry_layout_matches_reference_order():
    """offset_5bytes.go OffsetToBytes: 4 BE lower bytes then high byte."""
    out = in_5b_subprocess("""
from seaweedfs_tpu.storage import types as t
assert t.OFFSET_SIZE == 5 and t.NEEDLE_MAP_ENTRY_SIZE == 17
assert t.MAX_VOLUME_SIZE == 8 * (1 << 40)
v = t.NeedleValue(0x1122334455667788, (7 << 32) | 0xAABBCCDD, 4096)
b = v.to_bytes()
assert len(b) == 17
assert b[:8] == bytes.fromhex("1122334455667788")
assert b[8:12] == bytes.fromhex("AABBCCDD")   # lower 4, big-endian
assert b[12] == 7                             # high byte appended
assert b[13:] == (4096).to_bytes(4, "big")
r = t.NeedleValue.from_bytes(b)
assert (r.key, r.offset, r.size) == (v.key, v.offset, v.size)
print("layout-ok")
""")
    assert "layout-ok" in out


def test_idx_numpy_roundtrip_above_32gb():
    out = in_5b_subprocess("""
import numpy as np, tempfile, os
from seaweedfs_tpu.storage import idx, types as t
arr = np.zeros(3, dtype=idx.IDX_DTYPE)
arr["key"] = [1, 2, 3]
# stored offsets beyond the 4-byte range (volume > 32GB)
arr["offset"] = [10, 1 << 33, (1 << 39) + 5]
arr["size"] = [100, 200, 300]
p = os.path.join(tempfile.mkdtemp(), "x.idx")
idx.write_index(p, arr)
assert os.path.getsize(p) == 3 * 17
back = idx.read_index(p)
assert list(back["offset"]) == [10, 1 << 33, (1 << 39) + 5]
assert list(back["key"]) == [1, 2, 3]
# append_entry agrees with the vectorized writer
with open(p, "ab") as f:
    idx.append_entry(f, 4, (1 << 38) + 1, 400)
back = idx.read_index(p)
assert int(back["offset"][-1]) == (1 << 38) + 1
print("idx-ok")
""")
    assert "idx-ok" in out


def test_default_mode_unchanged():
    from seaweedfs_tpu.storage import types as t
    assert t.OFFSET_SIZE == 4
    assert t.NEEDLE_MAP_ENTRY_SIZE == 16
    assert t.MAX_VOLUME_SIZE == 8 * (1 << 32)


def test_storage_and_ec_suites_under_5bytes():
    """The real check: the whole storage engine + EC golden tests pass
    with 17-byte entries."""
    env = dict(os.environ, WEED_5BYTES_OFFSET="1", PYTHONPATH=REPO)
    out = subprocess.run(
        [sys.executable, "-m", "pytest", "-x", "-q",
         "tests/test_storage.py", "tests/test_ec_files.py",
         "tests/test_needle_map_compact.py",
         "tests/test_crash_recovery.py"],
        env=env, capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stdout[-4000:] + out.stderr[-2000:]
