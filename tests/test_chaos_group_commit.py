"""SIGKILL-mid-group-commit property test (batch durability).

A child process runs concurrent writers through Volume +
CommitScheduler in ``batch`` mode with a sub-millisecond window, and
prints an ack line only after its ticket's covering fsync released.
The parent SIGKILLs it at a seeded random point mid-workload, then
reopens the volume cold — driving check_integrity's torn-batch tail
scan — and asserts the two recovery invariants:

  * zero acked-write loss: every acked id reads back bit-for-bit;
  * the torn batch tail is dropped as a unit: whatever survives (acked
    or unacked-but-landed) is CRC-intact, the .dat ends on the record
    grid, and no torn record is reachable from the index.

20 seeded runs; ``-m chaos`` selects the family (excluded from the
tier-1 gate like the rest of the chaos suite).
"""
import hashlib
import os
import random
import signal
import struct
import subprocess
import sys
import time

import pytest

from seaweedfs_tpu.storage import needle as ndl
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.volume import Volume

pytestmark = [pytest.mark.slow, pytest.mark.chaos,
              pytest.mark.durability]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
COOKIE = 0xC0
BASE_SEED = 20260807


def _payload(seed: int, i: int) -> bytes:
    out, block = bytearray(), b"%d-%d" % (seed, i)
    n = 64 + (i * 37) % 2048
    while len(out) < n:
        block = hashlib.sha256(block).digest()
        out += block
    return bytes(out[:n])


# the child: 4 writer threads appending + submitting batch tickets,
# ack lines ("A <id>") flushed only after the covering fsync released
CHILD = r"""
import hashlib, os, sys, threading
sys.path.insert(0, sys.argv[3])
from seaweedfs_tpu.storage import needle as ndl
from seaweedfs_tpu.storage.commit import CommitScheduler
from seaweedfs_tpu.storage.volume import Volume

seed, d, repo = int(sys.argv[1]), sys.argv[2], sys.argv[3]

def payload(seed, i):
    out, block = bytearray(), b"%d-%d" % (seed, i)
    n = 64 + (i * 37) % 2048
    while len(out) < n:
        block = hashlib.sha256(block).digest()
        out += block
    return bytes(out[:n])

v = Volume(d, "", 1, create=True)
sched = CommitScheduler("batch", max_delay=0.0005)
emit = threading.Lock()
sys.stdout.write("R\n"); sys.stdout.flush()

def writer(base, stride):
    j = base
    while True:
        data = payload(seed, j)
        v.append_needle(ndl.Needle(id=j, cookie=0xC0, data=data))
        t = sched.submit(v, len(data))
        if t.wait(5.0) and t.error is None:
            with emit:
                sys.stdout.write("A %d\n" % j); sys.stdout.flush()
        j += stride

threads = [threading.Thread(target=writer, args=(b + 1, 4), daemon=True)
           for b in range(4)]
for th in threads:
    th.start()
for th in threads:
    th.join()
"""


@pytest.mark.parametrize("seed", range(20))
def test_sigkill_mid_group_commit_loses_no_acked_write(tmp_path, seed):
    rng = random.Random(BASE_SEED + seed)
    vdir = tmp_path / "vol"
    vdir.mkdir()
    proc = subprocess.Popen(
        [sys.executable, "-c", CHILD, str(seed), str(vdir), REPO],
        stdout=subprocess.PIPE, env={**os.environ,
                                     "JAX_PLATFORMS": "cpu"})
    try:
        assert proc.stdout.readline() == b"R\n"  # volume exists
        time.sleep(rng.uniform(0.05, 0.4))  # seeded kill point
    finally:
        os.kill(proc.pid, signal.SIGKILL)
    out, _ = proc.communicate(timeout=10)
    acked = {int(line.split()[1]) for line in out.splitlines()
             if line.startswith(b"A ")}

    # cold reopen drives check_integrity (incl. the torn-batch tail
    # scan); it must come up without manual repair
    v = Volume(str(vdir), "", 1)
    try:
        # invariant 1: zero acked-write loss, bit-for-bit
        for j in sorted(acked):
            n = v.read_needle(j, COOKIE)
            assert n.data == _payload(seed, j), f"acked id {j} corrupt"

        # invariant 2: the torn tail was dropped as a unit — the .dat
        # ends on the record grid and every surviving record (acked or
        # unacked-but-landed) is intact; an unacked write may survive
        # (its batch fsync raced the kill) but never torn
        size = os.path.getsize(vdir / "1.dat")
        assert size % 8 == 0
        survivors = 0
        for key, off, sz in list(v.nm.live_items()):
            n = v.read_needle(key, COOKIE)
            assert n.data == _payload(seed, key), \
                f"surviving id {key} torn"
            survivors += 1
        assert survivors >= len(acked)

        # the recovered tail itself re-parses: walk the grid from the
        # superblock and require every record to round-trip its CRC
        offset = v.super_block.block_size
        with open(vdir / "1.dat", "rb") as f:
            while offset + t.NEEDLE_HEADER_SIZE <= size:
                f.seek(offset)
                head = f.read(t.NEEDLE_HEADER_SIZE)
                _, _nid, size_u32 = struct.unpack(">IQI", head)
                nsize = max(t.u32_to_size(size_u32), 0)
                disk = ndl.disk_size(nsize, v.version)
                assert offset + disk <= size, "torn record survived"
                f.seek(offset)
                ndl.Needle.from_bytes(f.read(disk), v.version)
                offset += disk
        assert offset == size
    finally:
        v.close()
