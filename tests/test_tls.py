"""TLS on the HTTP plane: cert generation, HTTPS listeners, mTLS.

Counterpart of the reference's weed/security/tls.go configuration
(there applied to gRPC channels; here to the aiohttp listeners).
"""
import json
import ssl

import pytest
import requests

from seaweedfs_tpu.rpc.http import ServerThread, json_ok
from seaweedfs_tpu.utils import tls


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    return tls.generate_self_signed(
        str(tmp_path_factory.mktemp("certs")))


@pytest.fixture(scope="module")
def https_server(certs):
    from aiohttp import web

    async def hello(req):
        return json_ok({"ok": True})

    app = web.Application()
    app.add_routes([web.get("/status", hello)])
    ctx = tls.server_ssl_context(certs["cert"], certs["key"])
    t = ServerThread(app, ssl_context=ctx).start()
    yield t
    t.stop()


def test_url_scheme_and_verified_fetch(https_server, certs):
    assert https_server.url.startswith("https://")
    r = requests.get(f"{https_server.url}/status",
                     verify=certs["ca_cert"])
    assert r.status_code == 200 and r.json()["ok"] is True


def test_untrusted_ca_rejected(https_server):
    with pytest.raises(requests.exceptions.SSLError):
        requests.get(f"{https_server.url}/status", verify=True)


def test_plain_http_to_tls_port_fails(https_server):
    with pytest.raises(requests.RequestException):
        requests.get(f"http://127.0.0.1:{https_server.port}/status",
                     timeout=3)


class TestMutualTLS:
    @pytest.fixture(scope="class")
    def mtls_server(self, certs):
        from aiohttp import web

        async def hello(req):
            return json_ok({"mtls": True})

        app = web.Application()
        app.add_routes([web.get("/status", hello)])
        ctx = tls.server_ssl_context(certs["cert"], certs["key"],
                                     ca=certs["ca_cert"],
                                     client_auth=True)
        t = ServerThread(app, ssl_context=ctx).start()
        yield t
        t.stop()

    def test_client_cert_required(self, mtls_server, certs):
        with pytest.raises(requests.RequestException):
            requests.get(f"{mtls_server.url}/status",
                         verify=certs["ca_cert"], timeout=3)
        r = requests.get(f"{mtls_server.url}/status",
                         verify=certs["ca_cert"],
                         cert=(certs["client_cert"],
                               certs["client_key"]))
        assert r.json()["mtls"] is True


def test_context_from_config(certs, tmp_path):
    conf = {"https": {"cert": certs["cert"], "key": certs["key"]}}
    ctx = tls.context_from_config(conf)
    assert isinstance(ctx, ssl.SSLContext)
    assert tls.context_from_config({"https": {}}) is None
    assert tls.context_from_config({}) is None
    p = tmp_path / "sec.json"
    p.write_text(json.dumps(conf))
    assert isinstance(
        tls.context_from_config(tls.load_security_config(str(p))),
        ssl.SSLContext)


def test_cli_master_with_security(certs, tmp_path):
    """End-to-end: a master started with -security serves HTTPS."""
    import os
    import signal
    import subprocess
    import sys
    import time

    sec = tmp_path / "security.json"
    sec.write_text(json.dumps(
        {"https": {"cert": certs["cert"], "key": certs["key"]}}))
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    proc = subprocess.Popen(
        [sys.executable, "-m", "seaweedfs_tpu", "-security", str(sec),
         "master", "-port", str(port)],
        env=dict(os.environ, PYTHONPATH=repo),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.time() + 30
        last = None
        while time.time() < deadline:
            try:
                r = requests.get(
                    f"https://127.0.0.1:{port}/cluster/status",
                    verify=certs["ca_cert"], timeout=2)
                assert r.status_code == 200
                break
            except requests.RequestException as e:
                last = e
                if proc.poll() is not None:
                    raise RuntimeError(proc.stdout.read())
                time.sleep(0.3)
        else:
            raise TimeoutError(str(last))
    finally:
        proc.send_signal(signal.SIGINT)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
