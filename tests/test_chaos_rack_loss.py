"""Whole-rack-kill chaos: the PR-7 acceptance scenario.

Six volume servers across three racks, replication 010 (one replica in
a second rack), a bandwidth-shaped watchdog.  Kill EVERY node in rack B
mid-workload and require datacenter-grade behaviour:

* repair completes and every repaired volume is rack-spread again —
  zero placement violations (the new replica never lands beside the
  survivor while another rack has slots);
* repair traffic stays inside -repair.maxBytesPerSec (token-bucket
  admission measured over the whole outage window);
* zero acked-write loss: every payload acked before the kill reads
  back from every live replica afterwards;
* foreground reads sampled DURING the repair stay inside the SLO.
"""
import time

import numpy as np
import pytest

from seaweedfs_tpu.operation import verbs
from seaweedfs_tpu.rpc.httpclient import session
from seaweedfs_tpu.server.cluster import Cluster
from seaweedfs_tpu.utils import metrics, ratelimit

pytestmark = [pytest.mark.chaos, pytest.mark.rackloss]

CAP = 400_000.0  # repair bytes/s per node bucket
TOPOLOGY = [("dc1", "rA"), ("dc1", "rA"),
            ("dc1", "rB"), ("dc1", "rB"),
            ("dc1", "rC"), ("dc1", "rC")]
DEAD = (2, 3)  # rack B
FOREGROUND_P99_SLO = 2.0  # generous: in-process servers on shared CPU


@pytest.fixture()
def cluster(tmp_path):
    ratelimit.reset()
    c = Cluster(str(tmp_path), n_volume_servers=6,
                pulse_seconds=0.3, volume_size_limit=8 << 20,
                default_replication="010", topology=TOPOLOGY,
                repair_enabled=True, repair_interval=0.5,
                repair_max_bytes_per_sec=CAP)
    yield c
    c.stop()


def _status(cluster):
    return session().get(cluster.master_url + "/cluster/status",
                         timeout=5).json()


def _locations(cluster, vid):
    r = session().get(cluster.master_url + "/dir/lookup",
                      params={"volumeId": str(vid)}, timeout=5).json()
    return [loc["url"] for loc in r.get("locations", [])]


def _bw_total():
    return metrics._counters.get(("repair_bw_bytes_total", ()), 0.0)


def test_rack_kill_repairs_shaped_spread_and_lossless(cluster):
    rack_of = {cluster.stores[i].public_url: TOPOLOGY[i][1]
               for i in range(6)}
    dead_urls = {cluster.stores[i].public_url for i in DEAD}
    rng = np.random.default_rng(5)
    payloads = {}
    # one volume per collection; keep writing until rack B holds a
    # replica of at least 3 volumes so the kill forces real repair
    affected = set()
    for ci in range(15):
        col = f"rackloss{ci}"
        for _ in range(4):
            a = verbs.assign(cluster.master_url, collection=col)
            data = rng.bytes(int(rng.integers(10_000, 40_000)))
            verbs.upload(a, data)
            payloads[a.fid] = data
        vid = int(a.fid.split(",")[0])
        if set(_locations(cluster, vid)) & dead_urls:
            affected.add(vid)
        if len(affected) >= 3:
            break
    assert len(affected) >= 3, "rack B never got replicas"
    vids = sorted({int(f.split(",")[0]) for f in payloads})
    for vid in vids:  # the write path already spread every volume
        assert len({rack_of[u] for u in _locations(cluster, vid)}) == 2

    bw0 = _bw_total()
    assert _status(cluster)["RepairPlacementViolations"] == 0
    t0 = time.monotonic()
    for i in DEAD:
        cluster.volume_threads[i].stop()

    # poll for full recovery while running a foreground read workload
    fids = list(payloads)
    lat = []
    t_done = None
    deadline = time.monotonic() + 90
    while time.monotonic() < deadline:
        fid = fids[len(lat) % len(fids)]
        vid = int(fid.split(",")[0])
        live = [u for u in _locations(cluster, vid)
                if u not in dead_urls]
        if live:
            t = time.monotonic()
            r = session().get(f"http://{live[0]}/{fid}", timeout=10)
            lat.append(time.monotonic() - t)
            assert r.status_code == 200, fid
        healed = all(
            len(set(_locations(cluster, v)) - dead_urls) == 2
            for v in vids)
        if healed and not _status(cluster)["UnderReplicated"]:
            t_done = time.monotonic()
            break
        time.sleep(0.05)
    assert t_done is not None, "rack-B repair never completed"
    elapsed = t_done - t0

    # bandwidth cap: all shaped bytes over the outage window respect
    # rate*w + burst (+ one in-flight chunk per side of the copy)
    moved = _bw_total() - bw0
    assert moved > 0, "repair moved no bytes through the shaper"
    burst = max(64 << 10, CAP / 8)
    assert moved <= CAP * elapsed + 2 * burst + 2 * (1 << 20), \
        f"{moved} repair bytes in {elapsed:.2f}s exceeds the cap"

    # placement: every volume rack-spread again, nothing left on the
    # dead rack, and the master counted zero violations
    st = _status(cluster)
    assert st["RepairPlacementViolations"] == 0
    assert st["RepairMaxBytesPerSec"] == CAP
    assert st["RepairBandwidth"], "no node published repair_bw state"
    for vid in vids:
        locs = _locations(cluster, vid)
        assert not set(locs) & dead_urls
        assert len(locs) == 2
        assert len({rack_of[u] for u in locs}) == 2, \
            f"volume {vid} healed co-located: {locs}"

    # zero acked-write loss: every payload from every live replica
    for fid, data in payloads.items():
        for u in _locations(cluster, int(fid.split(",")[0])):
            assert session().get(f"http://{u}/{fid}",
                                 timeout=10).content == data

    # foreground SLO during the repair
    assert len(lat) >= 20, "foreground workload barely ran"
    p99 = float(np.percentile(lat, 99))
    assert p99 <= FOREGROUND_P99_SLO, f"foreground p99 {p99:.3f}s"
