"""Native S3 front (dataplane.cc ROLE_S3 + s3/native_front.py).

The conformance sweep (test_s3_conformance.py) runs identically against
this front; here we prove the NATIVE paths actually engage (counters),
that the in-C++ SigV4/MD5 agree with the python implementations, and
the cache-coherency contract: any mutation path — native PUT, relayed
python write, delete, rename — leaves reads correct immediately
(read-after-write, like AWS). Reference:
s3api_object_handlers_put.go, auth_signature_v4.go.
"""
import hashlib
import time

import pytest

from seaweedfs_tpu.native import dataplane as dpmod
from seaweedfs_tpu.server.cluster import Cluster
from tests.s3v4client import S3V4Client

pytestmark = pytest.mark.skipif(not dpmod.available(),
                                reason="native dataplane unavailable")

AK, SK = "NFAK", "NFSECRET"
RAK, RSK = "NFRD", "NFRDSECRET"


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    cfg = {"identities": [
        {"name": "admin", "credentials": [
            {"accessKey": AK, "secretKey": SK}], "actions": ["Admin"]},
        {"name": "scoped", "credentials": [
            {"accessKey": RAK, "secretKey": RSK}],
         "actions": ["Read:nf", "Write:nf"]},
    ]}
    c = Cluster(str(tmp_path_factory.mktemp("s3native")),
                n_volume_servers=1, volume_size_limit=64 << 20,
                with_s3=True, s3_native=True, s3_config=cfg)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def s3(cluster) -> S3V4Client:
    c = S3V4Client(cluster.s3_url, AK, SK)
    assert c.put("/nf").status in (200, 409)
    # wait for the refill thread to pool fids for the new bucket —
    # until then PUTs relay (correct, but these tests assert the
    # native counters move)
    deadline = time.time() + 10
    while time.time() < deadline:
        if cluster.s3_front.front.pool_level("nf") > 0:
            break
        time.sleep(0.05)
    return c


def test_md5_matches_hashlib():
    """The C++ MD5 (ETag hash) against hashlib across block-boundary
    sizes (55/56/57 straddle the length-padding edge)."""
    for n in (0, 1, 55, 56, 57, 63, 64, 65, 1000, 1 << 16):
        blob = bytes((i * 131 + 7) % 256 for i in range(n))
        assert dpmod.md5_hex(blob) == hashlib.md5(blob).hexdigest(), n


def test_native_put_get_counters(cluster, s3):
    before = cluster.s3_front.stats()
    body = b"native front payload" * 10
    r = s3.put("/nf/counter.bin", body)
    assert r.status == 200
    assert r.header("etag") == f'"{hashlib.md5(body).hexdigest()}"'
    g = s3.get("/nf/counter.bin")
    assert g.status == 200 and g.body == body
    after = cluster.s3_front.stats()
    assert after["fast_put"] == before["fast_put"] + 1
    assert after["fast_get"] == before["fast_get"] + 1
    assert after["chan_fail"] == 0


def test_meta_roundtrip_native(cluster, s3):
    r = s3.put("/nf/meta.bin", b"m",
               headers={"Content-Type": "text/weird",
                        "x-amz-meta-kind": "native-test",
                        "x-amz-meta-promo": "50% off + tax"})
    assert r.status == 200
    g = s3.get("/nf/meta.bin")
    assert g.header("content-type") == "text/weird"
    assert g.header("x-amz-meta-kind") == "native-test"
    assert g.header("x-amz-meta-promo") == "50% off + tax"
    h = s3.head("/nf/meta.bin")
    assert h.status == 200 and h.body == b""
    assert h.header("x-amz-meta-kind") == "native-test"
    assert int(h.header("content-length")) == 1


def test_overwrite_read_after_write(cluster, s3):
    for i in range(5):
        body = f"version {i}".encode()
        assert s3.put("/nf/rw.bin", body).status == 200
        g = s3.get("/nf/rw.bin")  # immediately: zero staleness window
        assert g.body == body, i


def test_delete_invalidates_native_cache(cluster, s3):
    assert s3.put("/nf/gone.bin", b"x").status == 200
    assert s3.get("/nf/gone.bin").status == 200
    assert s3.delete("/nf/gone.bin").status == 204  # native
    assert s3.get("/nf/gone.bin").status == 404  # no stale cache hit


def test_python_path_write_updates_cache(cluster, s3):
    """A write through the RELAY path (python filer) must be served
    correctly by subsequent native GETs — the meta-event listener is
    the single cache maintainer for every mutation source."""
    import requests

    # write through the filer HTTP API directly (not the S3 front)
    url = f"{cluster.filer_url}/buckets/nf/via-python.bin"
    r = requests.post(url, data=b"python wrote this",
                      headers={"Content-Type":
                               "application/octet-stream"})
    assert r.status_code == 201
    g = s3.get("/nf/via-python.bin")
    assert g.status == 200 and g.body == b"python wrote this"


def test_tampered_signature_rejected_natively(cluster, s3):
    before = cluster.s3_front.stats()["rejected"]
    bad = S3V4Client(cluster.s3_url, AK, "WRONG")
    r = bad.put("/nf/bad.bin", b"x")
    assert r.status == 403 and b"SignatureDoesNotMatch" in r.body
    assert cluster.s3_front.stats()["rejected"] >= before + 1
    assert s3.get("/nf/bad.bin").status == 404


def test_scoped_identity_native(cluster, s3):
    scoped = S3V4Client(cluster.s3_url, RAK, RSK)
    assert scoped.put("/nf/scoped.bin", b"ok").status == 200
    assert scoped.get("/nf/scoped.bin").status == 200
    # same identity against another bucket: denied (Write:nf only)
    assert s3.put("/other").status in (200, 409)
    r = scoped.put("/other/x.bin", b"no")
    assert r.status == 403 and b"AccessDenied" in r.body


def test_pool_dry_relays_correctly(cluster, s3):
    """An empty fid pool must not fail writes — they relay through the
    python path and read back fine."""
    front = cluster.s3_front.front
    # drain the pool by force: push nothing and consume what's there
    lvl = front.pool_level("nf")
    drained = 0
    while front.pool_level("nf") > 0 and drained < lvl + 10:
        s3.put(f"/nf/drain-{drained:05d}", b"d")
        drained += 1
    assert s3.put("/nf/after-dry.bin", b"still works").status == 200
    assert s3.get("/nf/after-dry.bin").body == b"still works"


def test_rename_through_filer_invalidates(cluster, s3):
    assert s3.put("/nf/old-name.bin", b"renamed").status == 200
    assert s3.get("/nf/old-name.bin").status == 200  # cached
    import requests

    r = requests.put(
        f"{cluster.filer_url}/buckets/nf/new-name.bin"
        f"?mv.from=/buckets/nf/old-name.bin")
    assert r.status_code == 200
    assert s3.get("/nf/old-name.bin").status == 404
    assert s3.get("/nf/new-name.bin").body == b"renamed"


def test_native_delete_fast_path(cluster, s3):
    before = cluster.s3_front.stats()
    assert s3.put("/nf/todelete.bin", b"bye").status == 200
    r = s3.delete("/nf/todelete.bin")
    assert r.status == 204 and r.body == b""
    assert s3.get("/nf/todelete.bin").status == 404
    # S3 semantics: deleting a missing key is still 204
    assert s3.delete("/nf/todelete.bin").status == 204
    after = cluster.s3_front.stats()
    assert after["fast_del"] >= before["fast_del"] + 2
    # chunk reclamation rode the normal filer path: the entry is gone
    import requests

    f = requests.get(f"{cluster.filer_url}/buckets/nf/todelete.bin")
    assert f.status_code == 404


def test_native_range_get(cluster, s3):
    body = bytes(range(256)) * 16  # 4KB, position-identifiable
    assert s3.put("/nf/ranged.bin", body).status == 200
    before = cluster.s3_front.stats()["fast_get"]
    r = s3.get("/nf/ranged.bin", headers={"Range": "bytes=100-199"})
    assert r.status == 206
    assert r.body == body[100:200]
    assert r.header("content-range") == f"bytes 100-199/{len(body)}"
    # open-ended and suffix forms
    r = s3.get("/nf/ranged.bin", headers={"Range": "bytes=4000-"})
    assert r.status == 206 and r.body == body[4000:]
    r = s3.get("/nf/ranged.bin", headers={"Range": "bytes=-64"})
    assert r.status == 206 and r.body == body[-64:]
    # end past EOF clamps (RFC 7233)
    r = s3.get("/nf/ranged.bin", headers={"Range": "bytes=4090-9999"})
    assert r.status == 206 and r.body == body[4090:]
    assert cluster.s3_front.stats()["fast_get"] >= before + 4
    # unsatisfiable starts relay to python for exact 416 semantics
    r = s3.get("/nf/ranged.bin", headers={"Range": "bytes=99999-"})
    assert r.status == 416


def test_native_multipart_part_upload(cluster, s3):
    """Part PUTs ride the native path (the one hot verb of a multipart
    upload): initiate/complete stay python, but every part between them
    is appended and recorded in C++ — and the assembled object must be
    what python would have built."""
    import xml.etree.ElementTree as ET

    r = s3.post("/nf/mpu.bin", **{"uploads": ""})
    assert r.status == 200
    ns = "{http://s3.amazonaws.com/doc/2006-03-01/}"
    upload_id = ET.fromstring(r.body).find(f"{ns}UploadId").text
    before = cluster.s3_front.stats()
    payloads = [b"P" * (5 << 20), b"Q" * (5 << 20), b"R" * 333]
    parts = []
    for i, data in enumerate(payloads, start=1):
        pr = s3.put("/nf/mpu.bin", data,
                    **{"partNumber": str(i), "uploadId": upload_id})
        assert pr.status == 200
        assert pr.header("etag") == \
            f'"{hashlib.md5(data).hexdigest()}"'
        parts.append((i, pr.header("etag")))
    after = cluster.s3_front.stats()
    assert after["fast_part"] >= before["fast_part"] + 3
    assert after["chan_fail"] == 0
    doc = "<CompleteMultipartUpload>" + "".join(
        f"<Part><PartNumber>{n}</PartNumber><ETag>{e}</ETag></Part>"
        for n, e in parts) + "</CompleteMultipartUpload>"
    cr = s3.post("/nf/mpu.bin", doc.encode(), **{"uploadId": upload_id})
    assert cr.status == 200
    g = s3.get("/nf/mpu.bin")
    assert g.status == 200 and g.body == b"".join(payloads)
    # the upload id is retired with the marker dir: a straggler part
    # relays to python's NoSuchUpload instead of appending blindly
    late = s3.put("/nf/mpu.bin", b"late",
                  **{"partNumber": "4", "uploadId": upload_id})
    assert late.status == 404 and b"NoSuchUpload" in late.body
    assert cluster.s3_front.stats()["fast_part"] == after["fast_part"]


def test_native_part_abort_discards(cluster, s3):
    import xml.etree.ElementTree as ET

    r = s3.post("/nf/mpab.bin", **{"uploads": ""})
    ns = "{http://s3.amazonaws.com/doc/2006-03-01/}"
    upload_id = ET.fromstring(r.body).find(f"{ns}UploadId").text
    before = cluster.s3_front.stats()["fast_part"]
    pr = s3.put("/nf/mpab.bin", b"x" * 2048,
                **{"partNumber": "1", "uploadId": upload_id})
    assert pr.status == 200
    assert cluster.s3_front.stats()["fast_part"] == before + 1
    assert s3.delete("/nf/mpab.bin",
                     **{"uploadId": upload_id}).status == 204
    assert s3.get("/nf/mpab.bin").status == 404


def test_range_overflow_is_safe(cluster, s3):
    """64-bit-overflowing range numbers must behave like python's
    unbounded ints (saturate, then the bounds rules apply) — a wrapped
    negative start once slipped past the bounds checks into an
    out-of-bounds buffer read."""
    assert s3.put("/nf/ovf.bin", b"abcdef").status == 200
    # start > INT64_MAX: unsatisfiable -> python path's 416
    r = s3.get("/nf/ovf.bin",
               headers={"Range": "bytes=99999999999999999999-"})
    assert r.status == 416
    # end > INT64_MAX: clamps to EOF like python
    r = s3.get("/nf/ovf.bin",
               headers={"Range": "bytes=2-99999999999999999999"})
    assert r.status == 206 and r.body == b"cdef"
    # huge suffix: whole body
    r = s3.get("/nf/ovf.bin",
               headers={"Range": "bytes=-99999999999999999999"})
    assert r.status == 206 and r.body == b"abcdef"
    # multi-range relays to python, which now answers the reference's
    # multipart/byteranges (common.go:348); junk specs relay to the
    # gateway's InvalidRange 416
    r = s3.get("/nf/ovf.bin", headers={"Range": "bytes=0-1,4-5"})
    assert r.status == 206
    assert r.header("content-type").startswith("multipart/byteranges")
    assert b"ab" in r.body and b"ef" in r.body  # parts 0-1 and 4-5
    r = s3.get("/nf/ovf.bin", headers={"Range": "bytes=abc-2"})
    assert r.status == 416
