"""Offline volume tools (fix/compact/export) and filer.cat/filer.copy
CLI verbs (reference weed/command/fix.go, compact.go, export.go,
filer_cat.go, filer_copy.go).
"""
import io
import json
import os
import subprocess
import sys
import tarfile

import pytest
import requests

from seaweedfs_tpu.operation import tools
from seaweedfs_tpu.server.cluster import Cluster
from seaweedfs_tpu.storage import needle as ndl
from seaweedfs_tpu.storage.volume import Volume


def mkvol(d, vid=3):
    os.makedirs(str(d), exist_ok=True)
    v = Volume(str(d), "", vid, create=True)
    v.append_needle(ndl.Needle(id=1, cookie=5, data=b"alpha" * 20,
                               name=b"a.txt",
                               flags=ndl.FLAG_HAS_NAME))
    v.append_needle(ndl.Needle(id=2, cookie=5, data=b"beta" * 20,
                               name=b"b.txt",
                               flags=ndl.FLAG_HAS_NAME))
    v.append_needle(ndl.Needle(id=3, cookie=5, data=b"gamma"))
    v.delete_needle(2)
    return v


class TestOfflineTools:
    def test_fix_rebuilds_idx(self, tmp_path):
        v = mkvol(tmp_path)
        v.close()
        idx = tmp_path / "3.idx"
        os.remove(idx)
        open(idx, "wb").close()  # empty (as after corruption wipe)
        out = tools.fix_volume(str(tmp_path), 3)
        assert out["records"] == 2  # needle 2 deleted
        again = Volume(str(tmp_path), "", 3)
        assert again.read_needle(1).data == b"alpha" * 20
        with pytest.raises(KeyError):
            again.read_needle(2)
        again.close()

    def test_compact_drops_garbage(self, tmp_path):
        v = mkvol(tmp_path)
        v.close()
        out = tools.compact_volume(str(tmp_path), 3)
        assert out["after_bytes"] < out["before_bytes"]
        assert out["records"] == 2
        again = Volume(str(tmp_path), "", 3)
        assert again.read_needle(3).data == b"gamma"
        again.close()

    def test_export_to_tar(self, tmp_path):
        v = mkvol(tmp_path)
        v.close()
        out_tar = str(tmp_path / "dump.tar")
        out = tools.export_volume(str(tmp_path), 3, out_tar)
        assert out["files"] == 2
        with tarfile.open(out_tar) as tar:
            names = sorted(tar.getnames())
            assert names == ["vol3/3", "vol3/a.txt"]
            data = tar.extractfile("vol3/a.txt").read()
            assert data == b"alpha" * 20

    def test_export_skips_overwritten(self, tmp_path):
        v = mkvol(tmp_path)
        v.append_needle(ndl.Needle(id=1, cookie=5, data=b"alpha-v2",
                                   name=b"a.txt",
                                   flags=ndl.FLAG_HAS_NAME))
        v.close()
        out_tar = str(tmp_path / "dump2.tar")
        tools.export_volume(str(tmp_path), 3, out_tar)
        with tarfile.open(out_tar) as tar:
            assert tar.extractfile("vol3/a.txt").read() == b"alpha-v2"
            assert len([n for n in tar.getnames()
                        if n == "vol3/a.txt"]) == 1


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = Cluster(str(tmp_path_factory.mktemp("tools_cluster")),
                n_volume_servers=1, volume_size_limit=16 << 20,
                with_filer=True)
    yield c
    c.stop()


def run_cli(*argv, timeout=90):
    env = dict(os.environ, PYTHONPATH=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return subprocess.run([sys.executable, "-m", "seaweedfs_tpu",
                           *argv], capture_output=True, text=True,
                          env=env, timeout=timeout)


class TestFilerCliVerbs:
    def test_filer_copy_and_cat(self, cluster, tmp_path):
        tree = tmp_path / "tree"
        (tree / "sub").mkdir(parents=True)
        (tree / "top.txt").write_text("top content")
        (tree / "sub" / "leaf.txt").write_text("leaf content")
        out = run_cli("filer.copy", "-filer", cluster.filer_url,
                      str(tree), "dropzone")
        assert out.returncode == 0, out.stderr
        assert "copied 2 files" in out.stdout

        r = requests.get(f"{cluster.filer_url}/dropzone/tree/top.txt")
        assert r.content == b"top content"
        r = requests.get(
            f"{cluster.filer_url}/dropzone/tree/sub/leaf.txt")
        assert r.content == b"leaf content"

        out = run_cli("filer.cat", "-filer", cluster.filer_url,
                      "/dropzone/tree/sub/leaf.txt")
        assert out.returncode == 0
        assert out.stdout == "leaf content"

    def test_filer_copy_single_file(self, cluster, tmp_path):
        f = tmp_path / "single.bin"
        f.write_bytes(b"\x00\x01\x02")
        out = run_cli("filer.copy", "-filer", cluster.filer_url,
                      str(f), "/files")
        assert out.returncode == 0, out.stderr
        r = requests.get(f"{cluster.filer_url}/files/single.bin")
        assert r.content == b"\x00\x01\x02"

    def test_tools_refuse_missing_volume(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            tools.fix_volume(str(tmp_path), 99)
        with pytest.raises(FileNotFoundError):
            tools.compact_volume(str(tmp_path), 99)
        with pytest.raises(FileNotFoundError):
            tools.export_volume(str(tmp_path), 99,
                                str(tmp_path / "x.tar"))
        assert not os.path.exists(tmp_path / "99.dat")


class TestSeeTools:
    def test_see_dat_and_idx(self, tmp_path):
        from seaweedfs_tpu.operation import tools
        from seaweedfs_tpu.storage import needle as ndl
        from seaweedfs_tpu.storage.volume import Volume

        v = Volume(str(tmp_path), "", 9, create=True)
        v.append_needle(ndl.Needle(id=1, cookie=7, data=b"abc",
                                   name=b"a.txt", mime=b"text/plain"))
        v.append_needle(ndl.Needle(id=2, cookie=8, data=b"defg"))
        v.delete_needle(1)
        v.close()

        recs = list(tools.see_dat(str(tmp_path), 9))
        live = [r for r in recs if not r["deleted"]]
        assert {r["id"] for r in live} == {1, 2}
        a = next(r for r in live if r["id"] == 1)
        assert a["name"] == "a.txt" and a["mime"] == "text/plain"
        assert a["crc_ok"] and a["data_bytes"] == 3
        # the tombstone append shows up as a deleted record
        assert any(r["deleted"] for r in recs)

        entries = list(tools.see_idx(str(tmp_path), 9))
        assert entries[0]["key"] == 1 and not entries[0]["deleted"]
        assert entries[-1]["deleted"]  # trailing tombstone

    def test_see_missing_volume(self, tmp_path):
        from seaweedfs_tpu.operation import tools
        with pytest.raises(FileNotFoundError):
            list(tools.see_dat(str(tmp_path), 404))
        with pytest.raises(FileNotFoundError):
            list(tools.see_idx(str(tmp_path), 404))

    def test_cli_see_dat(self, tmp_path):
        import json as _json
        import os
        import subprocess
        import sys

        from seaweedfs_tpu.storage import needle as ndl
        from seaweedfs_tpu.storage.volume import Volume

        v = Volume(str(tmp_path), "", 5, create=True)
        v.append_needle(ndl.Needle(id=11, cookie=1, data=b"x" * 10))
        v.close()
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        out = subprocess.run(
            [sys.executable, "-m", "seaweedfs_tpu", "see.dat",
             "-dir", str(tmp_path), "-volumeId", "5"],
            env=dict(os.environ, PYTHONPATH=repo),
            capture_output=True, text=True)
        assert out.returncode == 0, out.stderr
        recs = [_json.loads(l) for l in out.stdout.splitlines()]
        assert recs[0]["id"] == 11 and recs[0]["data_bytes"] == 10
