"""MySQL filer store over the real client/server wire, against the
in-process mini-mysqld (tests/minimysql.py) — the abstract_sql mysql
dialect driven by the in-tree wire client (filer/mysql_lite.py)
instead of an SDK. Reference slot:
/root/reference/weed/filer/mysql/mysql_store.go +
abstract_sql/abstract_sql_store.go:36.
"""
import time

import pytest

from seaweedfs_tpu.filer.entry import Entry, FileChunk
from seaweedfs_tpu.filer.filer import Filer
from seaweedfs_tpu.filer.mysql_lite import (MysqlConnection, MysqlError,
                                            escape_literal,
                                            native_password_token)

from .minimysql import MiniMysql, de_interpolate


@pytest.fixture(scope="module")
def mysqld():
    s = MiniMysql(user="weed", password="s3cret")
    yield s
    s.close()


@pytest.fixture()
def store(mysqld):
    from seaweedfs_tpu.filer.abstract_sql import MysqlStore

    with mysqld.lock:
        mysqld.db.execute("DROP TABLE IF EXISTS filemeta")
        mysqld.db.execute("DROP TABLE IF EXISTS kv")
    s = MysqlStore(port=mysqld.port, user="weed", password="s3cret",
                   database="")
    yield s
    s.close()


def ent(path, size=0):
    chunks = [FileChunk(fid="1,ab", offset=0, size=size,
                        mtime_ns=time.time_ns())] if size else []
    return Entry(full_path=path, chunks=chunks)


# -- wire-level spec checks --------------------------------------------

def test_native_password_scramble_known_vector():
    # independently computed: SHA1(p) XOR SHA1(nonce + SHA1(SHA1(p)))
    import hashlib

    nonce = bytes(range(20))
    tok = native_password_token("secret", nonce)
    h1 = hashlib.sha1(b"secret").digest()
    h3 = hashlib.sha1(nonce + hashlib.sha1(h1).digest()).digest()
    assert tok == bytes(a ^ b for a, b in zip(h1, h3))
    assert native_password_token("", nonce) == b""


def test_auth_rejected(mysqld):
    with pytest.raises(MysqlError) as ei:
        MysqlConnection("127.0.0.1", mysqld.port, user="weed",
                        password="wrong")
    assert ei.value.errno == 1045


def test_escaping_round_trips():
    evil = "it's a \\ tricky\nvalue\x00 with \"quotes\" and ''"
    sql = "INSERT INTO t VALUES(%s,%s)" % (
        escape_literal(evil), escape_literal(b"\x00\xff\x27bin"))
    psql, params = de_interpolate(sql)
    assert psql == "INSERT INTO t VALUES(?,?)"
    assert params == [evil, b"\x00\xff\x27bin"]


def test_query_errors_surface(mysqld, store):
    with pytest.raises(MysqlError):
        store._exec("SELECT * FROM no_such_table")


# -- store behavior through the wire ------------------------------------

def test_insert_find_update_delete(store):
    store.insert_entry(ent("/a/b.txt", 10))
    assert store.find_entry("/a/b.txt").file_size == 10
    store.update_entry(ent("/a/b.txt", 20))  # exercises the upsert
    assert store.find_entry("/a/b.txt").file_size == 20
    store.delete_entry("/a/b.txt")
    assert store.find_entry("/a/b.txt") is None


def test_listing_order_pagination_prefix(store):
    for n in ("zeta", "alpha", "beta", "beta2", "gamma", "100%"):
        store.insert_entry(ent(f"/dir/{n}"))
    store.insert_entry(ent("/dir/beta/child"))
    names = [e.name for e in store.list_directory_entries("/dir")]
    assert names == ["100%", "alpha", "beta", "beta2", "gamma", "zeta"]
    page = store.list_directory_entries("/dir", start_from="beta",
                                        inclusive=False, limit=2)
    assert [e.name for e in page] == ["beta2", "gamma"]
    pref = store.list_directory_entries("/dir", prefix="beta")
    assert [e.name for e in pref] == ["beta", "beta2"]
    # LIKE metacharacters in the prefix must be literal (ESCAPE path)
    pct = store.list_directory_entries("/dir", prefix="100%")
    assert [e.name for e in pct] == ["100%"]


def test_delete_folder_children_subtree(store):
    for p in ("/t/a", "/t/sub/x", "/t/sub/deep/y", "/tother/z"):
        store.insert_entry(ent(p))
    store.delete_folder_children("/t")
    for p in ("/t/a", "/t/sub/x", "/t/sub/deep/y"):
        assert store.find_entry(p) is None, p
    assert store.find_entry("/tother/z") is not None


def test_kv_binary(store):
    store.kv_put("conf", b"\x00\x01\xffbinary'quote")
    assert store.kv_get("conf") == b"\x00\x01\xffbinary'quote"
    store.kv_delete("conf")
    assert store.kv_get("conf") is None


def test_full_filer_stack(mysqld):
    with mysqld.lock:
        mysqld.db.execute("DELETE FROM filemeta")
    f = Filer("mysql", port=mysqld.port, user="weed",
              password="s3cret", database="")
    try:
        f.create_entry(ent("/docs/readme.md", 5))
        assert f.find_entry("/docs/readme.md").file_size == 5
        assert [e.name for e in f.list_entries("/docs")] == ["readme.md"]
        f.delete_entry("/docs", recursive=True)
        assert f.find_entry("/docs/readme.md") is None
    finally:
        f.close()


def test_reconnect_after_idle_close(mysqld, store):
    store.insert_entry(ent("/r/x"))
    # the server idle-closing the socket (wait_timeout) must not wedge
    # the store: next op reconnects and succeeds
    store._conn._sock.close()
    assert store.find_entry("/r/x") is not None


def test_dirhash_rides_every_statement(mysqld, store):
    from seaweedfs_tpu.filer.abstract_sql import dir_hash

    store.insert_entry(ent("/dh/file"))
    assert store.find_entry("/dh/file") is not None
    with mysqld.lock:
        row = mysqld.db.execute(
            "SELECT dirhash, directory FROM filemeta "
            "WHERE name='file'").fetchone()
    assert row == (dir_hash("/dh"), "/dh")
    # signed-int64 range (BIGINT can't hold unsigned md5 high bit)
    assert -(1 << 63) <= dir_hash("/dh") < (1 << 63)


def test_large_packet_continuation(mysqld, store):
    # >16MB payload forces 0xFFFFFF packet splitting on send; the
    # echo back exercises multi-packet receive
    blob = bytes(range(256)) * (68 << 10)  # ~17MB
    store.kv_put("big", blob)
    assert store.kv_get("big") == blob
    store.kv_delete("big")


# -- mysql2: per-bucket tables (mysql2_store.go:60,88) -----------------

@pytest.fixture()
def store2(mysqld):
    from seaweedfs_tpu.filer.abstract_sql import Mysql2Store

    with mysqld.lock:
        for (name,) in mysqld.db.execute(
                "SELECT name FROM sqlite_master WHERE type='table'"
        ).fetchall():
            mysqld.db.execute(f"DROP TABLE IF EXISTS `{name}`")
    s = Mysql2Store(port=mysqld.port, user="weed", password="s3cret",
                    database="")
    yield s
    s.close()


def test_mysql2_bucket_rows_land_in_bucket_table(mysqld, store2):
    store2.insert_entry(ent("/buckets/photos/a.jpg", size=1))
    store2.insert_entry(ent("/buckets/photos/sub/b.jpg", size=1))
    store2.insert_entry(ent("/topics/other.txt", size=1))
    with mysqld.lock:
        tables = {r[0] for r in mysqld.db.execute(
            "SELECT name FROM sqlite_master WHERE type='table'")}
        n_bucket = mysqld.db.execute(
            "SELECT COUNT(*) FROM `bucket_photos`").fetchone()[0]
        n_default = mysqld.db.execute(
            "SELECT COUNT(*) FROM filemeta").fetchone()[0]
    assert "bucket_photos" in tables
    assert n_bucket == 2          # both bucket entries, nested incl.
    assert n_default == 1         # the non-bucket entry only
    # reads route to the right table
    assert store2.find_entry("/buckets/photos/a.jpg") is not None
    assert store2.find_entry("/topics/other.txt") is not None
    names = [e.name for e in
             store2.list_directory_entries("/buckets/photos")]
    assert names == ["a.jpg"]


def test_mysql2_bucket_delete_is_drop_table(mysqld, store2):
    for i in range(50):
        store2.insert_entry(ent(f"/buckets/big/k{i:03d}", size=1))
    q_before = len(mysqld.queries)
    store2.delete_folder_children("/buckets/big")
    drops = [q for q in mysqld.queries[q_before:]
             if q.upper().startswith("DROP TABLE")]
    assert drops, "bucket delete must be DROP TABLE, not a row scan"
    assert store2.find_entry("/buckets/big/k000") is None
    # the bucket can be recreated afterwards (lazy table recreation)
    store2.insert_entry(ent("/buckets/big/reborn", size=1))
    assert store2.find_entry("/buckets/big/reborn") is not None


def test_mysql2_fresh_store_sees_existing_bucket_tables(mysqld, store2):
    from seaweedfs_tpu.filer.abstract_sql import Mysql2Store

    store2.insert_entry(ent("/buckets/persist/x", size=1))
    again = Mysql2Store(port=mysqld.port, user="weed",
                        password="s3cret", database="")
    try:
        assert again.find_entry("/buckets/persist/x") is not None
    finally:
        again.close()


def test_mysql2_quote_injection_rejected(store2):
    with pytest.raises(ValueError):
        store2.insert_entry(ent("/buckets/evil`name/x", size=1))
