"""End-to-end EC workflow over a live cluster: the reference's
ec.encode/ec.rebuild/ec.decode shell flows (SURVEY.md section 3.5) plus
degraded reads through on-the-fly reconstruction (store_ec.go:339)."""
import numpy as np
import pytest
import requests

from seaweedfs_tpu.ec import geometry as geo
from seaweedfs_tpu.operation import verbs
from seaweedfs_tpu.server.cluster import Cluster
from seaweedfs_tpu.shell import commands_ec, commands_volume
from seaweedfs_tpu.shell.env import CommandEnv, ShellError


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = Cluster(str(tmp_path_factory.mktemp("ec_cluster")),
                n_volume_servers=3, volume_size_limit=4 << 20,
                max_volumes=40)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def env(cluster):
    e = CommandEnv(cluster.master_url)
    e.acquire_lock()
    return e


@pytest.fixture()
def sealed_volume(cluster):
    """Upload objects into a fresh collection; return (vid, {fid: data})."""
    import secrets

    col = "seal" + secrets.token_hex(3)
    rng = np.random.default_rng(0)
    payloads = {}
    a0 = verbs.assign(cluster.master_url, collection=col)
    vid = int(a0.fid.split(",")[0])
    verbs.upload(a0, rng.bytes(1000))
    payloads[a0.fid] = None  # replaced below
    payloads = {}
    for i in range(30):
        a = verbs.assign(cluster.master_url, collection=col)
        if int(a.fid.split(",")[0]) != vid:
            continue
        data = rng.bytes(int(rng.integers(100, 50000)))
        verbs.upload(a, data)
        payloads[a.fid] = data
    return vid, payloads


class TestEcEncode:
    def test_encode_spread_read(self, cluster, env, sealed_volume):
        vid, payloads = sealed_volume
        placement = commands_ec.ec_encode(env, vid)
        assert len(placement) == geo.TOTAL_SHARDS
        # original volume is gone from all stores
        assert all(not s.has_volume(vid) for s in cluster.stores)
        # shards spread across all 3 servers
        servers = set(placement.values())
        assert len(servers) == 3
        # every object readable through the EC read path
        for fid, data in payloads.items():
            holders = env.ec_shard_locations(vid)
            any_holder = holders[0][0]
            resp = requests.get(f"http://{any_holder}/{fid}")
            assert resp.status_code == 200, fid
            assert resp.content == data

    def test_degraded_read_after_losing_parity_and_data(
            self, cluster, env, sealed_volume):
        vid, payloads = sealed_volume
        commands_ec.ec_encode(env, vid)
        locs = env.ec_shard_locations(vid)
        # delete 2 data shards + 2 parity shards (max tolerable)
        for sid in (1, 4, 10, 13):
            for url in locs.get(sid, []):
                env.vs_post(url, "/admin/ec/delete",
                            {"volume": vid, "shard_ids": [sid]})
        locs2 = env.ec_shard_locations(vid)
        remaining = {sid for sid, urls in locs2.items() if urls}
        assert len(remaining) == 10
        fid, data = next(iter(payloads.items()))
        holder = locs2[sorted(remaining)[0]][0]
        resp = requests.get(f"http://{holder}/{fid}")
        assert resp.status_code == 200
        assert resp.content == data

    def test_rebuild_restores_full_set(self, cluster, env, sealed_volume):
        vid, payloads = sealed_volume
        commands_ec.ec_encode(env, vid)
        locs = env.ec_shard_locations(vid)
        for sid in (0, 7, 12):
            for url in locs.get(sid, []):
                env.vs_post(url, "/admin/ec/delete",
                            {"volume": vid, "shard_ids": [sid]})
        result = commands_ec.ec_rebuild(env, vid)
        assert sorted(result["rebuilt"]) == [0, 7, 12]
        locs2 = env.ec_shard_locations(vid)
        assert sum(1 for urls in locs2.values() if urls) == 14
        # reads still fine
        for fid, data in list(payloads.items())[:3]:
            holder = locs2[0][0]
            resp = requests.get(f"http://{holder}/{fid}")
            assert resp.content == data

    def test_decode_back_to_volume(self, cluster, env, sealed_volume):
        vid, payloads = sealed_volume
        commands_ec.ec_encode(env, vid)
        out = commands_ec.ec_decode(env, vid)
        server = out["server"]
        # normal volume reads again
        for fid, data in list(payloads.items())[:3]:
            resp = requests.get(f"http://{server}/{fid}")
            assert resp.status_code == 200
            assert resp.content == data

    def test_encode_requires_lock(self, cluster, sealed_volume):
        vid, _ = sealed_volume
        env2 = CommandEnv(cluster.master_url)
        with pytest.raises(ShellError, match="lock"):
            commands_ec.ec_encode(env2, vid)

    def test_encode_missing_volume(self, env):
        with pytest.raises(ShellError, match="not found"):
            commands_ec.ec_encode(env, 424242)


class TestPartialRepairTraffic:
    """Acceptance: rebuilding ONE lost shard through the partial-stripe
    path must demonstrably move fewer bytes than the classic
    borrow-every-shard full rebuild — asserted on the
    repair_read_bytes_total{mode} counters both paths feed."""

    @staticmethod
    def _read_bytes(mode):
        from seaweedfs_tpu.utils import metrics
        return metrics._counters.get(
            ("repair_read_bytes_total", (("mode", mode),)), 0.0)

    def _drop_shard(self, env, vid, sid):
        for url in env.ec_shard_locations(vid).get(sid, []):
            env.vs_post(url, "/admin/ec/delete",
                        {"volume": vid, "shard_ids": [sid]})

    def test_partial_moves_fewer_bytes_than_full(self, cluster, env,
                                                 sealed_volume):
        vid, payloads = sealed_volume
        commands_ec.ec_encode(env, vid)
        # leg 1: lose shard 3, repair through the partial path
        self._drop_shard(env, vid, 3)
        p0, f0 = self._read_bytes("partial"), self._read_bytes("full")
        out = commands_ec.ec_rebuild(env, vid, partial=True)
        assert out["mode"] == "partial"
        assert out["rebuilt"] == [3]
        partial_bytes = self._read_bytes("partial") - p0
        assert partial_bytes > 0
        assert partial_bytes == out["read_bytes"]
        assert self._read_bytes("full") == f0, \
            "partial repair leaked full-path traffic"
        # leg 2: the SAME single-shard loss repaired the classic way
        self._drop_shard(env, vid, 3)
        f1 = self._read_bytes("full")
        out2 = commands_ec.ec_rebuild(env, vid, partial=False)
        assert out2["mode"] == "full"
        assert 3 in out2["rebuilt"]
        full_bytes = self._read_bytes("full") - f1
        assert full_bytes > 0
        # the headline claim: partial-stripe reads strictly fewer bytes
        assert partial_bytes < full_bytes, \
            f"partial={partial_bytes} full={full_bytes}"
        # the healed volume still serves every object
        locs = env.ec_shard_locations(vid)
        assert sum(1 for urls in locs.values() if urls) == 14
        holder = locs[3][0]
        for fid, data in list(payloads.items())[:3]:
            assert requests.get(f"http://{holder}/{fid}").content == data

    def test_partial_rebuild_survives_dark_planned_shard(
            self, cluster, env, sealed_volume):
        """Regression: a planned remote shard that never answers must
        not abort a structured-code partial rebuild. The server marks
        it dead, re-plans around it (LRCs carry substitutable shards),
        and still heals the lost shard bit-for-bit."""
        vid, payloads = sealed_volume
        commands_ec.ec_encode(env, vid, codec="lrc-10.2.2")
        col, reg_code, locs = env.ec_full_info(vid)
        assert reg_code.spec == "lrc-10.2.2"
        # golden copy of data shard 1 before losing it everywhere
        holder = next(s for s in cluster.volume_servers
                      if f"{s.store.ip}:{s.store.port}" == locs[1][0])
        shard = holder.store.ec_volumes[vid].shards[1]
        golden = shard.read_at(0, shard.size)
        self._drop_shard(env, vid, 1)
        plan = reg_code.repair_plan(
            [1], [s for s in range(reg_code.total) if s != 1])
        assert plan is not None and plan.kind == "local"
        # pick a rebuilder that must fetch >= 1 planned shard remotely,
        # then black that shard out at its fan-out layer
        rebuilder = dark = None
        for srv in cluster.volume_servers:
            ecv = srv.store.ec_volumes.get(vid)
            mine = set(ecv.shards) if ecv is not None else set()
            short = [s for s in plan.reads if s not in mine]
            if short:
                rebuilder, dark = srv, short[0]
                break
        assert rebuilder is not None
        orig = rebuilder._remote_shards_fetch_sync
        darkened = []

        def no_answer_from_dark(vid_, sids, offset, size, need,
                                deadline, bps=0.0):
            live = [s for s in sids if s != dark]
            if len(live) != len(sids):
                darkened.append(dark)
            if not live:
                return {}
            return orig(vid_, live, offset, size,
                        need=min(need, len(live)), deadline=deadline,
                        bps=bps)

        rebuilder._remote_shards_fetch_sync = no_answer_from_dark
        try:
            out = env.vs_post(
                f"{rebuilder.store.ip}:{rebuilder.store.port}",
                "/admin/ec/rebuild_partial",
                {"volume": vid, "collection": col, "shard_ids": [1]})
        finally:
            rebuilder._remote_shards_fetch_sync = orig
        assert out["rebuilt_shards"] == [1]
        assert darkened, "the dark shard never entered a plan"
        healed = rebuilder.store.ec_volumes[vid].shards[1]
        assert healed.read_at(0, healed.size) == golden
        # the healed volume still serves reads
        locs2 = env.ec_shard_locations(vid)
        fid, data = next(iter(payloads.items()))
        assert requests.get(
            f"http://{locs2[1][0]}/{fid}").content == data

    def test_partial_rebuild_rejects_garbage(self, cluster, env,
                                             sealed_volume):
        vid, _ = sealed_volume
        commands_ec.ec_encode(env, vid)
        locs = env.ec_shard_locations(vid)
        url = locs[0][0]
        with pytest.raises(ShellError):
            env.vs_post(url, "/admin/ec/rebuild_partial",
                        {"volume": vid, "shard_ids": []})
        with pytest.raises(ShellError):
            env.vs_post(url, "/admin/ec/rebuild_partial",
                        {"volume": vid, "shard_ids": [0], "chunk": 0})


class TestEcBalance:
    def test_balance_evens_counts(self, cluster, env, sealed_volume):
        vid, _ = sealed_volume
        commands_ec.ec_encode(env, vid)
        moves = commands_ec.ec_balance(env)
        counts = []
        for n in env.data_nodes():
            counts.append(sum(bin(b).count("1")
                              for b in n["ec_volumes"].values()))
        assert max(counts) - min(counts) <= geo.TOTAL_SHARDS // 3 + 2


class TestVolumeMaintenance:
    def test_volume_list_and_cluster_check(self, cluster, env):
        check = commands_volume.cluster_check(env)
        assert check["nodes"] == 3

    def test_fix_replication(self, cluster, env):
        a = verbs.assign(cluster.master_url, collection="fixrep",
                         replication="001")
        verbs.upload(a, b"fix me")
        vid = int(a.fid.split(",")[0])
        # drop one replica
        locs = env.volume_locations(vid)
        assert len(locs) == 2
        env.vs_post(locs[1], "/admin/delete_volume", {"volume": vid})
        import time

        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                len(env.volume_locations(vid)) != 1:
            time.sleep(0.1)
        fixes = commands_volume.volume_fix_replication(env)
        assert any(f["volume"] == vid for f in fixes)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                len(env.volume_locations(vid)) != 2:
            time.sleep(0.1)
        locs2 = env.volume_locations(vid)
        assert len(locs2) == 2
        for url in locs2:
            assert requests.get(
                f"http://{url}/{a.fid}").content == b"fix me"
