"""End-to-end EC workflow over a live cluster: the reference's
ec.encode/ec.rebuild/ec.decode shell flows (SURVEY.md section 3.5) plus
degraded reads through on-the-fly reconstruction (store_ec.go:339)."""
import numpy as np
import pytest
import requests

from seaweedfs_tpu.ec import geometry as geo
from seaweedfs_tpu.operation import verbs
from seaweedfs_tpu.server.cluster import Cluster
from seaweedfs_tpu.shell import commands_ec, commands_volume
from seaweedfs_tpu.shell.env import CommandEnv, ShellError


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = Cluster(str(tmp_path_factory.mktemp("ec_cluster")),
                n_volume_servers=3, volume_size_limit=4 << 20,
                max_volumes=40)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def env(cluster):
    e = CommandEnv(cluster.master_url)
    e.acquire_lock()
    return e


@pytest.fixture()
def sealed_volume(cluster):
    """Upload objects into a fresh collection; return (vid, {fid: data})."""
    import secrets

    col = "seal" + secrets.token_hex(3)
    rng = np.random.default_rng(0)
    payloads = {}
    a0 = verbs.assign(cluster.master_url, collection=col)
    vid = int(a0.fid.split(",")[0])
    verbs.upload(a0, rng.bytes(1000))
    payloads[a0.fid] = None  # replaced below
    payloads = {}
    for i in range(30):
        a = verbs.assign(cluster.master_url, collection=col)
        if int(a.fid.split(",")[0]) != vid:
            continue
        data = rng.bytes(int(rng.integers(100, 50000)))
        verbs.upload(a, data)
        payloads[a.fid] = data
    return vid, payloads


class TestEcEncode:
    def test_encode_spread_read(self, cluster, env, sealed_volume):
        vid, payloads = sealed_volume
        placement = commands_ec.ec_encode(env, vid)
        assert len(placement) == geo.TOTAL_SHARDS
        # original volume is gone from all stores
        assert all(not s.has_volume(vid) for s in cluster.stores)
        # shards spread across all 3 servers
        servers = set(placement.values())
        assert len(servers) == 3
        # every object readable through the EC read path
        for fid, data in payloads.items():
            holders = env.ec_shard_locations(vid)
            any_holder = holders[0][0]
            resp = requests.get(f"http://{any_holder}/{fid}")
            assert resp.status_code == 200, fid
            assert resp.content == data

    def test_degraded_read_after_losing_parity_and_data(
            self, cluster, env, sealed_volume):
        vid, payloads = sealed_volume
        commands_ec.ec_encode(env, vid)
        locs = env.ec_shard_locations(vid)
        # delete 2 data shards + 2 parity shards (max tolerable)
        for sid in (1, 4, 10, 13):
            for url in locs.get(sid, []):
                env.vs_post(url, "/admin/ec/delete",
                            {"volume": vid, "shard_ids": [sid]})
        locs2 = env.ec_shard_locations(vid)
        remaining = {sid for sid, urls in locs2.items() if urls}
        assert len(remaining) == 10
        fid, data = next(iter(payloads.items()))
        holder = locs2[sorted(remaining)[0]][0]
        resp = requests.get(f"http://{holder}/{fid}")
        assert resp.status_code == 200
        assert resp.content == data

    def test_rebuild_restores_full_set(self, cluster, env, sealed_volume):
        vid, payloads = sealed_volume
        commands_ec.ec_encode(env, vid)
        locs = env.ec_shard_locations(vid)
        for sid in (0, 7, 12):
            for url in locs.get(sid, []):
                env.vs_post(url, "/admin/ec/delete",
                            {"volume": vid, "shard_ids": [sid]})
        result = commands_ec.ec_rebuild(env, vid)
        assert sorted(result["rebuilt"]) == [0, 7, 12]
        locs2 = env.ec_shard_locations(vid)
        assert sum(1 for urls in locs2.values() if urls) == 14
        # reads still fine
        for fid, data in list(payloads.items())[:3]:
            holder = locs2[0][0]
            resp = requests.get(f"http://{holder}/{fid}")
            assert resp.content == data

    def test_decode_back_to_volume(self, cluster, env, sealed_volume):
        vid, payloads = sealed_volume
        commands_ec.ec_encode(env, vid)
        out = commands_ec.ec_decode(env, vid)
        server = out["server"]
        # normal volume reads again
        for fid, data in list(payloads.items())[:3]:
            resp = requests.get(f"http://{server}/{fid}")
            assert resp.status_code == 200
            assert resp.content == data

    def test_encode_requires_lock(self, cluster, sealed_volume):
        vid, _ = sealed_volume
        env2 = CommandEnv(cluster.master_url)
        with pytest.raises(ShellError, match="lock"):
            commands_ec.ec_encode(env2, vid)

    def test_encode_missing_volume(self, env):
        with pytest.raises(ShellError, match="not found"):
            commands_ec.ec_encode(env, 424242)


class TestEcBalance:
    def test_balance_evens_counts(self, cluster, env, sealed_volume):
        vid, _ = sealed_volume
        commands_ec.ec_encode(env, vid)
        moves = commands_ec.ec_balance(env)
        counts = []
        for n in env.data_nodes():
            counts.append(sum(bin(b).count("1")
                              for b in n["ec_volumes"].values()))
        assert max(counts) - min(counts) <= geo.TOTAL_SHARDS // 3 + 2


class TestVolumeMaintenance:
    def test_volume_list_and_cluster_check(self, cluster, env):
        check = commands_volume.cluster_check(env)
        assert check["nodes"] == 3

    def test_fix_replication(self, cluster, env):
        a = verbs.assign(cluster.master_url, collection="fixrep",
                         replication="001")
        verbs.upload(a, b"fix me")
        vid = int(a.fid.split(",")[0])
        # drop one replica
        locs = env.volume_locations(vid)
        assert len(locs) == 2
        env.vs_post(locs[1], "/admin/delete_volume", {"volume": vid})
        import time

        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                len(env.volume_locations(vid)) != 1:
            time.sleep(0.1)
        fixes = commands_volume.volume_fix_replication(env)
        assert any(f["volume"] == vid for f in fixes)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                len(env.volume_locations(vid)) != 2:
            time.sleep(0.1)
        locs2 = env.volume_locations(vid)
        assert len(locs2) == 2
        for url in locs2:
            assert requests.get(
                f"http://{url}/{a.fid}").content == b"fix me"
