"""Native filer front (dataplane.cc ROLE_FILER + filer/native_front.py).

The python filer suites (test_filer_server.py etc.) exercise the HTTP
API; here we prove the NATIVE hot path actually engages (counters) and
— the PR's contract — that it is BYTE-IDENTICAL to the python handlers
it replaces: every hot verb (GET/PUT/HEAD/DELETE, conditional GET,
range reads) is fired at both the native front and the demoted python
backend over the SAME entries and the responses compared header by
header. Fallback verbs (listings, renames, queries) must relay and
match too. Zero-staleness: a mutation through either channel is
visible on the other immediately, no sleeps.
"""
import hashlib

import pytest
import requests

from seaweedfs_tpu.native import dataplane as dpmod
from seaweedfs_tpu.server.cluster import Cluster

pytestmark = pytest.mark.skipif(not dpmod.available(),
                                reason="native dataplane unavailable")

# hop-by-hop / per-response noise that legitimately differs between two
# independent HTTP stacks; everything else must match exactly
IGNORED_HEADERS = {"date", "server", "connection", "keep-alive",
                   "transfer-encoding"}


def _norm(resp) -> tuple:
    headers = {k.lower(): v for k, v in resp.headers.items()
               if k.lower() not in IGNORED_HEADERS}
    body = resp.content
    ctype = headers.get("content-type", "")
    if ctype.startswith("multipart/byteranges; boundary="):
        # the boundary is random per response — the one legitimate
        # non-determinism; normalize it away, keep the frame structure
        boundary = ctype.split("boundary=", 1)[1]
        headers["content-type"] = ctype.replace(boundary, "B")
        body = body.replace(boundary.encode(), b"B")
        headers["content-length"] = str(len(body))
    return resp.status_code, headers, body


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    import time

    c = Cluster(str(tmp_path_factory.mktemp("filernative")),
                n_volume_servers=1, volume_size_limit=64 << 20,
                with_filer=True, filer_native=True)
    # wait for the refill thread to pool fids — until then PUTs relay
    # (correct, but these tests assert the native counters move)
    deadline = time.time() + 10
    while time.time() < deadline and c.filer_front.front.pool_level() == 0:
        time.sleep(0.05)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def native(cluster) -> str:
    return cluster.filer_url  # the C++ front


@pytest.fixture(scope="module")
def backend(cluster) -> str:
    return cluster.filer_thread.url  # the python app, direct


def _parity(native, backend, method, path, **kw):
    """Fire the same request at both stacks, demand identical
    (status, headers, body)."""
    n = requests.request(method, native + path, **kw)
    p = requests.request(method, backend + path, **kw)
    assert _norm(n) == _norm(p), f"{method} {path} diverged"
    return n


def test_native_counters_move(cluster, native):
    before = cluster.filer_front.stats()
    body = b"native filer payload" * 9
    r = requests.put(f"{native}/hot/counters.bin", data=body)
    assert r.status_code == 201
    assert r.json() == {"name": "counters.bin", "size": len(body),
                        "etag": hashlib.md5(body).hexdigest()}
    g = requests.get(f"{native}/hot/counters.bin")
    assert g.status_code == 200 and g.content == body
    h = requests.head(f"{native}/hot/counters.bin")
    assert h.status_code == 200
    d = requests.delete(f"{native}/hot/counters.bin")
    assert d.status_code == 204
    after = cluster.filer_front.stats()
    assert after["fast_put"] == before["fast_put"] + 1
    assert after["fast_get"] >= before["fast_get"] + 2  # GET + HEAD
    assert after["fast_del"] == before["fast_del"] + 1
    assert after["chan_fail"] == 0


def test_put_response_parity(native, backend):
    """Same body, same filename, one via each stack: the 201 JSON and
    headers must be indistinguishable."""
    body = b"parity put body"
    n = requests.put(f"{native}/pn/same.bin", data=body)
    p = requests.put(f"{backend}/pp/same.bin", data=body)
    assert n.status_code == p.status_code == 201
    assert n.json() == p.json()
    nh = {k.lower() for k in n.headers} - IGNORED_HEADERS
    ph = {k.lower() for k in p.headers} - IGNORED_HEADERS
    assert nh == ph


def test_get_head_parity(cluster, native, backend):
    """GET/HEAD of the same entry through both stacks: identical down
    to ETag, Content-Type, Last-Modified and Accept-Ranges."""
    body = bytes(range(256)) * 8
    assert requests.put(f"{native}/par/blob.dat", data=body,
                        headers={"Content-Type": "application/x-blob"}
                        ).status_code == 201
    _parity(native, backend, "GET", "/par/blob.dat")
    _parity(native, backend, "HEAD", "/par/blob.dat")
    # mime sniffed from the extension when the PUT didn't name one
    assert requests.put(f"{native}/par/page.html",
                        data=b"<html></html>").status_code == 201
    g = _parity(native, backend, "GET", "/par/page.html")
    assert g.headers["Content-Type"].startswith("text/html")
    # missing entry: both 404
    n = requests.get(f"{native}/par/absent.bin")
    p = requests.get(f"{backend}/par/absent.bin")
    assert n.status_code == p.status_code == 404


def test_conditional_get_parity(native, backend):
    body = b"conditional body"
    r = requests.put(f"{native}/par/cond.bin", data=body)
    etag = f'"{hashlib.md5(body).hexdigest()}"'
    assert r.status_code == 201
    # matching If-None-Match: 304, empty body, same headers
    n = _parity(native, backend, "GET", "/par/cond.bin",
                headers={"If-None-Match": etag})
    assert n.status_code == 304 and n.content == b""
    # non-matching: full 200
    n = _parity(native, backend, "GET", "/par/cond.bin",
                headers={"If-None-Match": '"deadbeef"'})
    assert n.status_code == 200 and n.content == body
    # If-None-Match wins over Range (RFC 7232 6.)
    n = _parity(native, backend, "GET", "/par/cond.bin",
                headers={"If-None-Match": etag, "Range": "bytes=0-3"})
    assert n.status_code == 304


def test_range_parity(native, backend):
    body = bytes(range(256)) * 16  # 4KB, position-identifiable
    assert requests.put(f"{native}/par/ranged.bin",
                        data=body).status_code == 201
    cases = ["bytes=100-199",        # plain
             "bytes=0-0",            # single byte
             "bytes=4000-",          # open-ended
             "bytes=-64",            # suffix
             "bytes=4090-9999",      # end past EOF clamps
             "bytes=99999-",         # unsatisfiable -> 416
             "bytes=-0",             # zero suffix -> 416
             "bytes=abc-2",          # malformed
             "bytes=0-1,4-5"]        # multi-range (python path)
    for spec in cases:
        n = _parity(native, backend, "GET", "/par/ranged.bin",
                    headers={"Range": spec})
        if spec == "bytes=100-199":
            assert n.status_code == 206 and n.content == body[100:200]
        # HEAD with the same Range: same status + headers, no body
        h = _parity(native, backend, "HEAD", "/par/ranged.bin",
                    headers={"Range": spec})
        assert h.content == b""


def test_delete_parity(native, backend):
    assert requests.put(f"{native}/par/die.bin",
                        data=b"x").status_code == 201
    n = requests.delete(f"{native}/par/die.bin")
    assert n.status_code == 204
    assert requests.get(f"{native}/par/die.bin").status_code == 404
    # deleting a missing path: both answer 204 (native relays — no
    # cache proof the path is a plain file)
    _parity(native, backend, "DELETE", "/par/die.bin")


def test_fallback_verbs_byte_identical(native, backend):
    """Verbs the front does NOT serve natively (listings, renames,
    queried reads) relay to python and must come back identical."""
    for i in range(3):
        assert requests.put(f"{native}/ls/f{i}.txt",
                            data=f"file {i}".encode()).status_code == 201
    # JSON listing (query + trailing slash: relays)
    _parity(native, backend, "GET", "/ls/?limit=10",
            headers={"Accept": "application/json"})
    # rename rides the python path on either socket
    r = requests.put(f"{native}/ls/renamed.txt?mv.from=/ls/f0.txt")
    assert r.status_code == 200
    assert requests.get(f"{native}/ls/f0.txt").status_code == 404
    assert requests.get(f"{native}/ls/renamed.txt").content == b"file 0"
    # queried read (metadata view) relays
    _parity(native, backend, "GET", "/ls/f1.txt?metadata=true",
            headers={"Accept": "application/json"})


def test_post_is_put(native):
    """python routes POST and PUT to the same handler; the front must
    treat POST as a hot write too."""
    r = requests.post(f"{native}/par/posted.bin", data=b"posted")
    assert r.status_code == 201
    assert requests.get(f"{native}/par/posted.bin").content == b"posted"


def test_zero_staleness_native_to_python(cluster, native, backend):
    """A native-channel mutation is durable and visible through the
    python API the moment the response lands — no sleeps anywhere."""
    before = cluster.filer_front.stats()["fast_put"]
    for i in range(5):
        body = f"native wrote v{i}".encode()
        assert requests.put(f"{native}/zs/obj.bin",
                            data=body).status_code == 201
        g = requests.get(f"{backend}/zs/obj.bin")  # python, immediately
        assert g.status_code == 200 and g.content == body, i
    assert cluster.filer_front.stats()["fast_put"] == before + 5
    assert requests.delete(f"{native}/zs/obj.bin").status_code == 204
    assert requests.get(f"{backend}/zs/obj.bin").status_code == 404


def test_zero_staleness_python_to_native(cluster, native, backend):
    """The reverse channel: python-API writes are served by the native
    cache immediately (the sync meta listener is the one maintainer)."""
    for i in range(5):
        body = f"python wrote v{i}".encode()
        assert requests.put(f"{backend}/zs/rev.bin",
                            data=body).status_code == 201
        g = requests.get(f"{native}/zs/rev.bin")  # native, immediately
        assert g.status_code == 200 and g.content == body, i
    assert requests.delete(f"{backend}/zs/rev.bin").status_code == 204
    assert requests.get(f"{native}/zs/rev.bin").status_code == 404


def test_writes_gate_follows_server_config(cluster, native):
    """Flip a condition the python write path special-cases (inline
    threshold): the gate must close within a refill tick, PUTs keep
    working through the relay, and reopen when restored."""
    import time

    fs = cluster.filer
    front = cluster.filer_front
    fs.save_to_filer_limit = 1024
    deadline = time.time() + 5
    while time.time() < deadline and front._writes_on:
        time.sleep(0.02)
    assert not front._writes_on
    before = front.stats()["fast_put"]
    r = requests.put(f"{native}/gate/inline.bin", data=b"tiny")
    assert r.status_code == 201  # relayed, python inlined it
    assert front.stats()["fast_put"] == before
    assert requests.get(f"{native}/gate/inline.bin").content == b"tiny"
    fs.save_to_filer_limit = 0
    deadline = time.time() + 5
    while time.time() < deadline and not front._writes_on:
        time.sleep(0.02)
    assert front._writes_on


def test_reserved_and_odd_paths_relay(cluster, native, backend):
    """Control-plane paths and shapes outside the hot grammar must
    reach python untouched."""
    n = requests.get(f"{native}/healthz")
    p = requests.get(f"{backend}/healthz")
    assert n.status_code == p.status_code
    _parity(native, backend, "GET", "/status")
    # percent-encoded names fall outside the unreserved grammar: relay,
    # but stay correct end to end
    r = requests.put(f"{native}/odd/sp%20ace.txt", data=b"spaced")
    assert r.status_code == 201
    assert requests.get(f"{native}/odd/sp%20ace.txt").content == b"spaced"


def test_fault_spec_gates_native_front(cluster, native):
    """The filer front takes its own share of -fault.spec: a filer
    read-error rule fires on natively served GETs, counted in the
    front's own 5xx class. Driven through the same dp_role_faults ABI
    the spawn mirror (faults.native_params('filer')) pushes; the front
    is a process-global, so the rule is set on the live one."""
    from seaweedfs_tpu.utils import faults

    # what a `-fault.spec filer:read:error=1.0` spawn would have pushed
    spec = faults.parse_spec("filer:read:error=1.0")
    assert spec[0].matches("filer", "read")
    front = cluster.filer_front.front
    front.set_faults(read_err=1.0, seed=11)
    try:
        # writes are untouched by a read rule
        assert requests.put(f"{native}/f/x.bin",
                            data=b"ok").status_code == 201
        dp = cluster.volume_servers[0].dp
        before = dp.role_front_stats(dpmod.ROLE_FILER)["5xx"]
        r = requests.get(f"{native}/f/x.bin")
        assert r.status_code >= 500
        after = dp.role_front_stats(dpmod.ROLE_FILER)["5xx"]
        assert after == before + 1  # injected IN the front
    finally:
        front.set_faults()  # clear
    assert requests.get(f"{native}/f/x.bin").content == b"ok"


def test_big_body_single_chunk_roundtrip(cluster, native, backend):
    """A body over the pump's fast-path gate (1MB) relays to python and
    chunks; reads of it must stay correct (cache rejects multi-chunk,
    so the GET relays too) and byte-identical."""
    import numpy as np

    body = np.random.default_rng(7).bytes(3 << 20)
    assert requests.put(f"{native}/big/blob.bin",
                        data=body).status_code == 201
    n = requests.get(f"{native}/big/blob.bin")
    assert n.status_code == 200 and n.content == body
    _parity(native, backend, "HEAD", "/big/blob.bin")
