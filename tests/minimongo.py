"""Minimal mongod double speaking OP_MSG for wire-protocol tests.

Implements the command subset the mongodb filer store issues — ping,
createIndexes (accepted, unindexed), update (upsert by q equality),
find (equality + $gt/$gte/$lt[e] conditions, sort by one key, limit,
batchSize + getMore cursors), delete (limit 0/1) — the miniredis /
minietcd role for the OP_MSG wire. Uses the store's own bson_lite
codec for framing (the codec itself is spec-tested separately).
"""
from __future__ import annotations

import socket
import struct
import threading

from seaweedfs_tpu.filer.bson_lite import OP_MSG, decode_doc, encode_doc


def _match(row: dict, q: dict) -> bool:
    for k, cond in q.items():
        v = row.get(k)
        if isinstance(cond, dict):
            for op, rhs in cond.items():
                if v is None:
                    return False
                if op == "$gt" and not v > rhs:
                    return False
                if op == "$gte" and not v >= rhs:
                    return False
                if op == "$lt" and not v < rhs:
                    return False
                if op == "$lte" and not v <= rhs:
                    return False
        elif v != cond:
            return False
    return True


class MiniMongo:
    def __init__(self):
        # db -> collection -> _id -> row
        self._data: dict[str, dict[str, dict]] = {}
        self._cursors: dict[int, list[dict]] = {}
        self._next_cursor = [1]
        self._lock = threading.Lock()
        self._srv: socket.socket | None = None
        self.port = 0

    def _coll(self, db: str, name: str) -> dict:
        return self._data.setdefault(db, {}).setdefault(name, {})

    def _handle(self, cmd: dict) -> dict:
        db = cmd.get("$db", "test")
        with self._lock:
            if "ping" in cmd or "hello" in cmd or "ismaster" in cmd:
                return {"ok": 1}
            if "createIndexes" in cmd:
                return {"ok": 1, "numIndexesAfter": 2}
            if "update" in cmd:
                coll = self._coll(db, cmd["update"])
                n = 0
                for u in cmd["updates"]:
                    hits = [r for r in coll.values()
                            if _match(r, u["q"])]
                    if hits:
                        for r in hits:
                            r.clear()
                            r.update(u["u"])
                            n += 1
                    elif u.get("upsert"):
                        coll[u["u"]["_id"]] = dict(u["u"])
                        n += 1
                return {"ok": 1, "n": n}
            if "delete" in cmd:
                coll = self._coll(db, cmd["delete"])
                n = 0
                for d in cmd["deletes"]:
                    hits = [rid for rid, r in coll.items()
                            if _match(r, d["q"])]
                    if d.get("limit") == 1:
                        hits = hits[:1]
                    for rid in hits:
                        del coll[rid]
                        n += 1
                return {"ok": 1, "n": n}
            if "find" in cmd:
                coll = self._coll(db, cmd["find"])
                rows = [r for r in coll.values()
                        if _match(r, cmd.get("filter", {}))]
                for key, direction in reversed(
                        list(cmd.get("sort", {}).items())):
                    rows.sort(key=lambda r: r.get(key),
                              reverse=direction < 0)
                limit = cmd.get("limit", 0)
                if limit:
                    rows = rows[:limit]
                batch = cmd.get("batchSize", 101)
                first, rest = rows[:batch], rows[batch:]
                cid = 0
                if rest:
                    cid = self._next_cursor[0]
                    self._next_cursor[0] += 1
                    self._cursors[cid] = rest
                return {"ok": 1, "cursor": {
                    "id": cid, "ns": f"{db}.{cmd['find']}",
                    "firstBatch": first}}
            if "getMore" in cmd:
                cid = cmd["getMore"]
                rows = self._cursors.get(cid, [])
                batch = cmd.get("batchSize", 101)
                out, rest = rows[:batch], rows[batch:]
                if rest:
                    self._cursors[cid] = rest
                    nxt = cid
                else:
                    self._cursors.pop(cid, None)
                    nxt = 0
                return {"ok": 1, "cursor": {
                    "id": nxt, "ns": f"{db}.{cmd['collection']}",
                    "nextBatch": out}}
        return {"ok": 0, "errmsg": f"unsupported command {cmd}"}

    # -- wire loop ------------------------------------------------------
    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while True:
                head = b""
                while len(head) < 16:
                    piece = conn.recv(16 - len(head))
                    if not piece:
                        return
                    head += piece
                length, rid, _, opcode = struct.unpack("<iiii", head)
                body = b""
                while len(body) < length - 16:
                    piece = conn.recv(length - 16 - len(body))
                    if not piece:  # half-closed: exit, don't spin
                        return
                    body += piece
                if opcode != OP_MSG or body[4] != 0:
                    return
                reply = self._handle(decode_doc(body[5:]))
                payload = b"\x00\x00\x00\x00\x00" + encode_doc(reply)
                conn.sendall(struct.pack(
                    "<iiii", 16 + len(payload), 0, rid, OP_MSG)
                    + payload)
        except OSError:
            pass
        finally:
            conn.close()

    def start(self) -> "MiniMongo":
        self._srv = socket.create_server(("127.0.0.1", 0))
        self.port = self._srv.getsockname()[1]

        def loop():
            while True:
                try:
                    conn, _ = self._srv.accept()
                except OSError:
                    return
                threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True).start()

        threading.Thread(target=loop, daemon=True).start()
        return self

    def stop(self) -> None:
        if self._srv is not None:
            self._srv.close()
