"""fio-style verified random I/O over the mount filesystem core.

The reference's e2e gate runs fio randwrite/randrw at 4k/128k/1M block
sizes with crc32c verification over a real FUSE mount
(.github/workflows/e2e.yml:44-83). This is the same workload at
library level: a shadow buffer tracks every byte we wrote; reads —
through the dirty pages, after flush, and after a fresh remount — must
match the shadow exactly.
"""
import hashlib
import random

import pytest

from seaweedfs_tpu.mount.weedfs import WeedFS
from seaweedfs_tpu.server.cluster import Cluster


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = Cluster(str(tmp_path_factory.mktemp("torture")),
                n_volume_servers=2, volume_size_limit=64 << 20,
                with_filer=True)
    yield c
    c.stop()


@pytest.fixture
def fs(cluster):
    f = WeedFS(cluster.filer_url, cluster.master_url)
    yield f
    f.destroy()


def torture(fs, path, file_size, block_sizes, ops, seed,
            reads_every=4):
    rng = random.Random(seed)
    shadow = bytearray(file_size)
    fh = fs.create(path)
    # lay down a base extent so random-offset reads are defined
    base = bytes(rng.getrandbits(8) for _ in range(file_size))
    fs.write(fh, 0, base)
    shadow[:] = base
    for i in range(ops):
        bs = rng.choice(block_sizes)
        off = rng.randrange(0, max(1, file_size - bs))
        blob = rng.getrandbits(8 * bs).to_bytes(bs, "little")
        fs.write(fh, off, blob)
        shadow[off:off + bs] = blob
        if i % reads_every == 0:
            roff = rng.randrange(0, max(1, file_size - bs))
            got = fs.read(fh, roff, bs)
            assert got == bytes(shadow[roff:roff + bs]), \
                f"dirty-read mismatch at op {i} off {roff}"
        if i % 11 == 0:
            fs.flush(fh)
    fs.flush(fh)
    got = fs.read(fh, 0, file_size)
    assert hashlib.sha256(got).hexdigest() == \
        hashlib.sha256(bytes(shadow)).hexdigest(), "post-flush mismatch"
    fs.release(fh)
    return bytes(shadow)


class TestVerifiedRandomIO:
    def test_randrw_4k(self, cluster, fs):
        shadow = torture(fs, "/t/rand4k.bin", 256 << 10,
                         [4 << 10], ops=60, seed=41)
        self._verify_remount(cluster, "/t/rand4k.bin", shadow)

    def test_randrw_mixed_128k_1m(self, cluster, fs):
        shadow = torture(fs, "/t/randmix.bin", 4 << 20,
                         [128 << 10, 1 << 20], ops=25, seed=42)
        self._verify_remount(cluster, "/t/randmix.bin", shadow)

    def test_unaligned_small_writes(self, cluster, fs):
        shadow = torture(fs, "/t/unaligned.bin", 128 << 10,
                         [1, 17, 511, 4097], ops=80, seed=43)
        self._verify_remount(cluster, "/t/unaligned.bin", shadow)

    @staticmethod
    def _verify_remount(cluster, path, shadow):
        """Fresh mount (no warm caches): bytes must come back from the
        cluster itself."""
        fs2 = WeedFS(cluster.filer_url, cluster.master_url)
        try:
            fh = fs2.open(path)
            got = fs2.read(fh, 0, len(shadow))
            assert hashlib.sha256(got).hexdigest() == \
                hashlib.sha256(shadow).hexdigest(), "remount mismatch"
            fs2.release(fh)
        finally:
            fs2.destroy()

    def test_truncate_then_extend(self, cluster, fs):
        fh = fs.create("/t/trunc.bin")
        fs.write(fh, 0, b"A" * 100000)
        fs.flush(fh)
        fs.truncate("/t/trunc.bin", 1000, fh)
        fs.write(fh, 5000, b"B" * 100)
        fs.flush(fh)
        got = fs.read(fh, 0, 5100)
        assert got[:1000] == b"A" * 1000
        assert got[1000:5000] == b"\x00" * 4000  # hole reads zeros
        assert got[5000:5100] == b"B" * 100
        fs.release(fh)
