"""grpc_lite (in-tree HTTP/2 + HPACK + gRPC unary client) against a
REAL grpc-core server — the framing, HPACK dynamic table, Huffman
strings, flow control and trailers come from the canonical C
implementation, so the client is validated against the same stack the
reference's gRPC services run on, not a hand-rolled double.
"""
import struct
from concurrent import futures

import grpc
import pytest

from seaweedfs_tpu.utils import grpc_lite as g

LONG_MSG = "the requested entity was not found anywhere at all"


class _Handlers(grpc.GenericRpcHandler):
    """Raw-bytes services (identity serializers)."""

    def service(self, details):
        m = details.method
        if m == "/test.Echo/Unary":
            return grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: b"echo:" + req)
        if m == "/test.Echo/Meta":
            def meta(req, ctx):
                md = dict(ctx.invocation_metadata())
                return md.get("x-tag", "").encode()
            return grpc.unary_unary_rpc_method_handler(meta)
        if m == "/test.Echo/Fail":
            def fail(req, ctx):
                ctx.abort(grpc.StatusCode.NOT_FOUND, LONG_MSG)
            return grpc.unary_unary_rpc_method_handler(fail)
        if m == "/test.Echo/Big":
            return grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: req[::-1])
        return None


@pytest.fixture(scope="module")
def server():
    srv = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    srv.add_generic_rpc_handlers((_Handlers(),))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    yield port
    srv.stop(0)


@pytest.fixture()
def ch(server):
    c = g.GrpcChannel("127.0.0.1", server)
    yield c
    c.close()


def test_unary_roundtrip(ch):
    assert ch.unary("/test.Echo/Unary", b"hello") == b"echo:hello"
    assert ch.unary("/test.Echo/Unary", b"") == b"echo:"


def test_sequential_calls_one_connection(ch):
    for i in range(20):
        body = f"msg{i}".encode()
        assert ch.unary("/test.Echo/Unary", body) == b"echo:" + body
    assert ch._next_stream == 41  # 20 streams: ids 1,3,...,39


def test_metadata(ch):
    assert ch.unary("/test.Echo/Meta", b"",
                    metadata=[("x-tag", "v-123")]) == b"v-123"


def test_error_status_and_huffman_message(ch):
    """NOT_FOUND with a long ASCII message: grpc-core Huffman-encodes
    compressible header values, so this exercises the RFC 7541
    Appendix B decode end to end."""
    with pytest.raises(g.GrpcError) as ei:
        ch.unary("/test.Echo/Fail", b"x")
    assert ei.value.code == 5  # NOT_FOUND
    assert LONG_MSG in ei.value.message


def test_large_messages_flow_control(ch):
    """1MB each way: many DATA frames, our WINDOW_UPDATEs on receive,
    the server's on send — both beyond the 65535 initial windows."""
    blob = bytes(range(256)) * 4096  # 1MB
    got = ch.unary("/test.Echo/Big", blob)
    assert got == blob[::-1]


def test_reconnect_after_dead_connection(ch):
    import socket as _s

    assert ch.unary("/test.Echo/Unary", b"a") == b"echo:a"
    ch._sock.shutdown(_s.SHUT_RDWR)
    assert ch.unary("/test.Echo/Unary", b"b") == b"echo:b"


def test_protobuf_golden_bytes():
    """The wire helpers against hand-derived spec bytes (protobuf
    encoding docs), independent of any server."""
    assert g.pb_varint(0) == b"\x00"
    assert g.pb_varint(300) == b"\xac\x02"
    assert g.pb_varint(-1) == b"\xff" * 9 + b"\x01"
    assert g.pb_bytes(2, b"hi") == b"\x12\x02hi"
    assert g.pb_uint(3, 150) == b"\x18\x96\x01"
    assert g.pb_uint(1, 0) == b""
    msg = g.pb_bytes(1, b"ab") + g.pb_uint(2, 7) + g.pb_bytes(1, b"c")
    dec = g.pb_decode(msg)
    assert dec == {1: [b"ab", b"c"], 2: [7]}
    assert g.pb_first(dec, 2) == 7
    with pytest.raises(ValueError):
        g.pb_decode(b"\x0a\x05ab")  # truncated length-delimited


def test_huffman_golden():
    """RFC 7541 Appendix C.4.1 example: 'www.example.com' huffman
    encodes to f1e3 c2e5 f23a 6ba0 ab90 f4ff."""
    enc = bytes.fromhex("f1e3c2e5f23a6ba0ab90f4ff")
    assert g.huffman_decode(enc) == b"www.example.com"
    # C.6.1: response value "private"
    assert g.huffman_decode(bytes.fromhex("aec3771a4b")) == b"private"


def test_hpack_decoder_rfc_examples():
    """RFC 7541 C.3.1: first request, full literal-with-indexing set."""
    d = g.HpackDecoder()
    block = bytes.fromhex("828684410f7777772e6578616d706c652e636f6d")
    assert d.decode(block) == [
        (":method", "GET"), (":scheme", "http"), (":path", "/"),
        (":authority", "www.example.com")]
    # C.3.2 second request: indexed dynamic entry (62) + new literal
    block2 = bytes.fromhex("828684be58086e6f2d6361636865")
    assert d.decode(block2) == [
        (":method", "GET"), (":scheme", "http"), (":path", "/"),
        (":authority", "www.example.com"),
        ("cache-control", "no-cache")]
