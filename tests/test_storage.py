"""Storage engine unit tests: needle format, idx, needle map, volume."""
import os

import numpy as np
import pytest

from seaweedfs_tpu.storage import idx as idxmod
from seaweedfs_tpu.storage import needle as ndl
from seaweedfs_tpu.storage import needle_map as nmap
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.super_block import ReplicaPlacement, SuperBlock
from seaweedfs_tpu.storage.volume import Volume


class TestNeedleFormat:
    def test_roundtrip_simple(self):
        n = ndl.Needle(id=0x1234, cookie=0xDEADBEEF, data=b"hello world")
        blob = n.to_bytes()
        assert len(blob) % t.NEEDLE_PADDING == 0
        m = ndl.Needle.from_bytes(blob)
        assert m.id == n.id and m.cookie == n.cookie and m.data == n.data

    def test_roundtrip_all_fields(self):
        n = ndl.Needle(id=7, cookie=9, data=b"x" * 100, name=b"a.txt",
                       mime=b"text/plain", pairs=b'{"k":"v"}',
                       last_modified=1700000000, ttl=b"\x05\x02")
        m = ndl.Needle.from_bytes(n.to_bytes())
        assert m.name == b"a.txt" and m.mime == b"text/plain"
        assert m.pairs == b'{"k":"v"}'
        assert m.last_modified == 1700000000
        assert m.ttl == b"\x05\x02"

    def test_crc_detects_corruption(self):
        n = ndl.Needle(id=1, cookie=2, data=b"payload bytes")
        blob = bytearray(n.to_bytes())
        blob[t.NEEDLE_HEADER_SIZE + 5] ^= 0xFF  # flip a data byte
        with pytest.raises(ValueError, match="CRC"):
            ndl.Needle.from_bytes(bytes(blob))

    def test_legacy_crc_accepted(self):
        n = ndl.Needle(id=1, cookie=2, data=b"data")
        blob = bytearray(n.to_bytes())
        import struct
        actual = ndl.crc32c(b"data")
        struct.pack_into(">I", blob, t.NEEDLE_HEADER_SIZE + n.size,
                         ndl.legacy_crc_value(actual))
        m = ndl.Needle.from_bytes(bytes(blob))
        assert m.data == b"data"

    def test_disk_size_alignment(self):
        for size in (0, 1, 7, 8, 100, 4096):
            assert ndl.disk_size(size, 2) % 8 == 0
            assert ndl.disk_size(size, 3) % 8 == 0
        # reference quirk: aligned sizes still get a full 8-byte pad
        assert ndl.padding_length(0, 2) in range(1, 9)

    def test_empty_tombstone_needle(self):
        n = ndl.Needle(id=42)
        m = ndl.Needle.from_bytes(n.to_bytes())
        assert m.id == 42 and m.size == 0 and m.data == b""

    def test_v2_layout(self):
        n = ndl.Needle(id=3, cookie=4, data=b"v2 data")
        m = ndl.Needle.from_bytes(n.to_bytes(ndl.VERSION2), ndl.VERSION2)
        assert m.data == b"v2 data"


class TestFileId:
    def test_roundtrip(self):
        fid = t.format_file_id(3, 0x1637037D6, 0x12345678)
        vid, key, cookie = t.parse_file_id(fid)
        assert (vid, key, cookie) == (3, 0x1637037D6, 0x12345678)

    def test_bad(self):
        with pytest.raises(ValueError):
            t.parse_file_id("3,123")


class TestIdx:
    def test_write_read(self, tmp_path):
        p = str(tmp_path / "v.idx")
        arr = np.zeros(3, dtype=idxmod.IDX_DTYPE)
        arr[0] = (1, 1, 100)
        arr[1] = (2, 20, 200)
        arr[2] = (1, 0, t.size_to_u32(t.TOMBSTONE_SIZE))
        idxmod.write_index(p, arr)
        assert os.path.getsize(p) == 3 * t.NEEDLE_MAP_ENTRY_SIZE
        back = idxmod.read_index(p)
        assert list(back["key"]) == [1, 2, 1]
        entries = list(idxmod.iter_entries(p))
        assert entries[2].size == t.TOMBSTONE_SIZE

    def test_needle_value_bytes(self):
        v = t.NeedleValue(0xAABBCCDD, 7, -1)
        assert t.NeedleValue.from_bytes(v.to_bytes()) == v


class TestNeedleMap:
    def test_put_get_delete_accounting(self):
        nm = nmap.NeedleMap()
        nm.put(1, 10, 100)
        nm.put(2, 20, 200)
        assert nm.file_count == 2 and nm.file_bytes == 300
        nm.put(1, 30, 150)  # overwrite
        assert nm.file_count == 2 and nm.file_bytes == 350
        assert nm.deleted_count == 1 and nm.deleted_bytes == 100
        assert nm.delete(2) == 200
        assert nm.get(2) is None
        assert nm.delete(2) == 0

    def test_memdb_sorted_visit(self, tmp_path):
        db = nmap.MemDb()
        for k in (5, 1, 9, 3):
            db.set(k, k * 10, k * 100)
        seen = []
        db.ascending_visit(lambda k, o, s: seen.append(k))
        assert seen == [1, 3, 5, 9]
        p = str(tmp_path / "sorted.idx")
        db.save_to_idx(p)
        keys = [e.key for e in idxmod.iter_entries(p)]
        assert keys == [1, 3, 5, 9]


class TestSuperBlock:
    def test_roundtrip(self):
        sb = SuperBlock(version=3,
                        replica_placement=ReplicaPlacement.parse("012"),
                        ttl=b"\x03\x01", compaction_revision=7)
        back = SuperBlock.from_bytes(sb.to_bytes())
        assert back.version == 3
        assert str(back.replica_placement) == "012"
        assert back.ttl == b"\x03\x01"
        assert back.compaction_revision == 7

    def test_replica_placement(self):
        rp = ReplicaPlacement.parse("112")
        assert rp.copy_count == 5
        assert ReplicaPlacement.from_byte(rp.to_byte()) == rp
        with pytest.raises(ValueError):
            ReplicaPlacement.parse("9")


class TestVolume:
    def test_write_read_delete(self, tmp_path):
        v = Volume(str(tmp_path), "", 1, create=True)
        n = ndl.Needle(id=101, cookie=0xAB, data=b"the quick brown fox")
        off, size = v.append_needle(n)
        assert off == 8  # right after super block
        got = v.read_needle(101, cookie=0xAB)
        assert got.data == b"the quick brown fox"
        with pytest.raises(PermissionError):
            v.read_needle(101, cookie=0xFF)
        assert v.delete_needle(101) > 0
        with pytest.raises(KeyError):
            v.read_needle(101)
        v.close()

    def test_reload_from_disk(self, tmp_path):
        v = Volume(str(tmp_path), "col", 2, create=True)
        for i in range(10):
            v.append_needle(ndl.Needle(id=i + 1, cookie=i, data=bytes([i]) * 50))
        v.delete_needle(3)
        v.close()

        v2 = Volume(str(tmp_path), "col", 2)
        assert v2.nm.file_count == 9
        assert v2.read_needle(5).data == bytes([4]) * 50
        with pytest.raises(KeyError):
            v2.read_needle(3)
        v2.close()

    def test_compact_reclaims_space(self, tmp_path):
        v = Volume(str(tmp_path), "", 3, create=True)
        for i in range(20):
            v.append_needle(ndl.Needle(id=i + 1, cookie=1, data=b"z" * 1000))
        for i in range(10):
            v.delete_needle(i + 1)
        size_before = v.content_size()
        assert v.garbage_ratio() > 0.4
        v.compact()
        assert v.content_size() < size_before
        assert v.garbage_ratio() == 0.0
        # survivors still readable, deleted still gone
        assert v.read_needle(15).data == b"z" * 1000
        with pytest.raises(KeyError):
            v.read_needle(5)
        assert v.super_block.compaction_revision == 1
        v.close()

    def test_read_only(self, tmp_path):
        v = Volume(str(tmp_path), "", 4, create=True)
        v.read_only = True
        with pytest.raises(PermissionError):
            v.append_needle(ndl.Needle(id=1, data=b"x"))
        v.close()


class TestMmapBackend:
    """memory_map backend parity (storage/backend/memory_map/):
    the same volume lifecycle over an mmap-backed .dat."""

    def test_volume_lifecycle_on_mmap(self, tmp_path):
        v = Volume(str(tmp_path), "", 7, create=True,
                   backend_kind="mmap")
        for i in range(20):
            v.append_needle(ndl.Needle(id=i + 1, cookie=i,
                                       data=bytes([i]) * 100))
        assert v.read_needle(5, cookie=4).data == bytes([4]) * 100
        v.delete_needle(9)
        v.close()
        # reload from disk on the plain backend: bytes are identical
        v2 = Volume(str(tmp_path), "", 7)
        assert v2.nm.file_count == 19
        assert v2.read_needle(12).data == bytes([11]) * 100
        with pytest.raises(KeyError):
            v2.read_needle(9)
        v2.close()

    def test_mmap_file_grows_and_syncs(self, tmp_path):
        from seaweedfs_tpu.storage import backend as bk
        f = bk.create("mmap", str(tmp_path / "x.dat"), create=True)
        off = f.append(b"A" * 10)
        assert off == 0 and f.size() == 10
        f.write_at(b"BB", 4)
        assert f.read_at(10, 0) == b"AAAABBAAAA"
        f.append(b"C" * (3 << 20))  # forces remap growth
        assert f.size() == 10 + (3 << 20)
        assert f.read_at(2, 10) == b"CC"
        f.sync()
        f.close()

    def test_rclone_gated(self):
        from seaweedfs_tpu.storage import backend as bk
        with pytest.raises((RuntimeError, NotImplementedError)):
            bk.create("rclone", "remote:path")


class TestFidCountSuffix:
    """`assign?count=N` batch addressing: fid_1..fid_{N-1} add to the
    key (needle.go ParsePath)."""

    def test_suffix_parses_as_key_delta(self):
        base_vid, base_key, base_cookie = t.parse_file_id("3,01637037d6")
        for i in (1, 2, 15):
            vid, key, cookie = t.parse_file_id(f"3,01637037d6_{i}")
            assert (vid, key - base_key, cookie) == \
                (base_vid, i, base_cookie)

    def test_bad_suffix_rejected(self):
        with pytest.raises(ValueError):
            t.parse_file_id("3,01637037d6_x")
