"""GF(256) field + matrix algebra unit tests."""
import numpy as np
import pytest

from seaweedfs_tpu.ops import gf256, rs_matrix


def test_field_axioms_spot():
    rng = np.random.default_rng(0)
    for _ in range(200):
        a, b, c = (int(x) for x in rng.integers(0, 256, 3))
        assert gf256.gf_mul(a, b) == gf256.gf_mul(b, a)
        assert gf256.gf_mul(a, gf256.gf_mul(b, c)) == gf256.gf_mul(
            gf256.gf_mul(a, b), c)
        # distributive over XOR (field addition)
        assert gf256.gf_mul(a, b ^ c) == gf256.gf_mul(a, b) ^ gf256.gf_mul(a, c)
        assert gf256.gf_mul(a, 1) == a
        assert gf256.gf_mul(a, 0) == 0


def test_known_products_poly_0x11d():
    # 2*128 = 0x100 -> reduced by 0x11d -> 0x1d
    assert gf256.gf_mul(2, 128) == 0x1D
    # generator powers: exp[1]=2, exp[2]=4, exp[8]=0x1d^... spot known values
    assert int(gf256.EXP[0]) == 1 and int(gf256.EXP[1]) == 2
    assert int(gf256.EXP[8]) == 0x1D  # 2^8 reduced by 0x11d
    assert gf256.gf_mul(0x53, gf256.gf_inv(0x53)) == 1


def test_inverse_table():
    for a in range(1, 256):
        assert gf256.gf_mul(a, gf256.gf_inv(a)) == 1


def test_div_pow():
    rng = np.random.default_rng(1)
    for _ in range(100):
        a = int(rng.integers(1, 256))
        b = int(rng.integers(1, 256))
        assert gf256.gf_mul(gf256.gf_div(a, b), b) == a
    assert gf256.gf_pow(2, 8) == 0x1D
    assert gf256.gf_pow(3, 0) == 1
    assert gf256.gf_pow(0, 5) == 0
    with pytest.raises(ZeroDivisionError):
        gf256.gf_div(1, 0)


def test_mat_inv_roundtrip():
    rng = np.random.default_rng(2)
    for n in (1, 2, 5, 10, 14):
        # random matrices are invertible w.h.p.; retry until one is
        for _ in range(20):
            m = rng.integers(0, 256, (n, n)).astype(np.uint8)
            try:
                inv = gf256.mat_inv(m)
            except ValueError:
                continue
            prod = gf256.mat_mul(m, inv)
            assert np.array_equal(prod, np.eye(n, dtype=np.uint8))
            break
        else:
            pytest.fail("no invertible matrix found")


def test_mat_inv_singular_raises():
    m = np.array([[1, 2], [1, 2]], dtype=np.uint8)
    with pytest.raises(ValueError):
        gf256.mat_inv(m)


def test_bitmat_matches_scalar_mul():
    rng = np.random.default_rng(3)
    for _ in range(50):
        c = int(rng.integers(0, 256))
        x = int(rng.integers(0, 256))
        xb = np.array([(x >> t) & 1 for t in range(8)], dtype=np.uint8)
        yb = (gf256.BITMAT[c] @ xb) % 2
        y = int(sum(int(b) << s for s, b in enumerate(yb)))
        assert y == gf256.gf_mul(c, x)


def test_expand_pack_unpack_roundtrip():
    rng = np.random.default_rng(4)
    data = rng.integers(0, 256, (5, 33)).astype(np.uint8)
    assert np.array_equal(gf256.pack_bits(gf256.unpack_bits(data)), data)


def test_bit_matrix_matmul_equals_byte_matmul():
    rng = np.random.default_rng(5)
    m, k, n = 4, 10, 57
    coef = rng.integers(0, 256, (m, k)).astype(np.uint8)
    data = rng.integers(0, 256, (k, n)).astype(np.uint8)
    byte_out = np.zeros((m, n), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            byte_out[i] ^= gf256.MUL_TABLE[coef[i, j]][data[j]]
    a_bits = gf256.expand_to_bits(coef)
    bit_out = gf256.pack_bits(
        (a_bits.astype(np.int32) @ gf256.unpack_bits(data).astype(np.int32)) % 2)
    assert np.array_equal(bit_out, byte_out)


def test_encode_matrix_systematic():
    enc = rs_matrix.encode_matrix(10, 4)
    assert enc.shape == (14, 10)
    assert np.array_equal(enc[:10], np.eye(10, dtype=np.uint8))
    # any k rows must be invertible (MDS property) — spot-check a few subsets
    rng = np.random.default_rng(6)
    for _ in range(10):
        rows = sorted(rng.choice(14, size=10, replace=False).tolist())
        gf256.mat_inv(enc[rows, :])  # must not raise
