"""S3 gateway integration tests against a live master+volume+filer+s3
stack — the in-process analogue of the reference's ceph/s3-tests +
test/s3/ suites (SURVEY.md section 4).
"""
import xml.etree.ElementTree as ET

import pytest
import requests

from seaweedfs_tpu.s3.auth import presign_url, sign_request
from seaweedfs_tpu.server.cluster import Cluster

NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = Cluster(str(tmp_path_factory.mktemp("s3_cluster")),
                n_volume_servers=2, volume_size_limit=16 << 20,
                with_s3=True)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def s3(cluster):
    return cluster.s3_url


def put_bucket(s3, name):
    return requests.put(f"{s3}/{name}")


class TestBuckets:
    def test_create_head_list_delete(self, s3):
        assert put_bucket(s3, "b1").status_code == 200
        assert requests.head(f"{s3}/b1").status_code == 200
        body = requests.get(f"{s3}/").text
        root = ET.fromstring(body)
        names = [b.find(f"{NS}Name").text
                 for b in root.iter(f"{NS}Bucket")]
        assert "b1" in names
        assert requests.delete(f"{s3}/b1").status_code == 204
        assert requests.head(f"{s3}/b1").status_code == 404

    def test_duplicate_create_conflicts(self, s3):
        put_bucket(s3, "dup")
        r = put_bucket(s3, "dup")
        assert r.status_code == 409
        assert "BucketAlreadyExists" in r.text

    def test_delete_nonempty_conflicts(self, s3):
        put_bucket(s3, "full")
        requests.put(f"{s3}/full/x.txt", data=b"x")
        r = requests.delete(f"{s3}/full")
        assert r.status_code == 409
        assert "BucketNotEmpty" in r.text


class TestObjects:
    def test_put_get_round_trip(self, s3):
        put_bucket(s3, "obj")
        r = requests.put(f"{s3}/obj/hello.txt", data=b"hello s3",
                         headers={"Content-Type": "text/plain"})
        assert r.status_code == 200
        assert r.headers["ETag"]
        got = requests.get(f"{s3}/obj/hello.txt")
        assert got.content == b"hello s3"
        head = requests.head(f"{s3}/obj/hello.txt")
        assert head.status_code == 200
        assert int(head.headers["Content-Length"]) == 8

    def test_nested_key_and_range(self, s3):
        put_bucket(s3, "obj2")
        requests.put(f"{s3}/obj2/a/b/c/deep.bin", data=bytes(range(100)))
        r = requests.get(f"{s3}/obj2/a/b/c/deep.bin",
                         headers={"Range": "bytes=10-19"})
        assert r.status_code == 206
        assert r.content == bytes(range(10, 20))

    def test_missing_key_xml_error(self, s3):
        put_bucket(s3, "obj3")
        r = requests.get(f"{s3}/obj3/ghost")
        assert r.status_code == 404
        assert "NoSuchKey" in r.text

    def test_delete_object(self, s3):
        put_bucket(s3, "obj4")
        requests.put(f"{s3}/obj4/gone", data=b"bye")
        assert requests.delete(f"{s3}/obj4/gone").status_code == 204
        assert requests.get(f"{s3}/obj4/gone").status_code == 404

    def test_copy_object(self, s3):
        put_bucket(s3, "src")
        put_bucket(s3, "dst")
        requests.put(f"{s3}/src/orig.bin", data=b"copy me")
        r = requests.put(f"{s3}/dst/copied.bin",
                         headers={"x-amz-copy-source": "/src/orig.bin"})
        assert r.status_code == 200
        assert "CopyObjectResult" in r.text
        assert requests.get(f"{s3}/dst/copied.bin").content == b"copy me"

    def test_batch_delete(self, s3):
        put_bucket(s3, "batch")
        for k in ("one", "two", "three"):
            requests.put(f"{s3}/batch/{k}", data=b"x")
        body = (b"<Delete><Object><Key>one</Key></Object>"
                b"<Object><Key>two</Key></Object></Delete>")
        r = requests.post(f"{s3}/batch?delete", data=body)
        assert r.status_code == 200
        assert r.text.count("<Deleted>") == 2
        assert requests.get(f"{s3}/batch/one").status_code == 404
        assert requests.get(f"{s3}/batch/three").status_code == 200


class TestListing:
    @pytest.fixture(scope="class", autouse=True)
    def keys(self, s3):
        put_bucket(s3, "ls")
        for k in ("a.txt", "b.txt", "dir1/x.txt", "dir1/y.txt",
                  "dir2/z.txt"):
            requests.put(f"{s3}/ls/{k}", data=b"1")

    def parse(self, text):
        root = ET.fromstring(text)
        keys = [c.find(f"{NS}Key").text
                for c in root.iter(f"{NS}Contents")]
        prefixes = [p.find(f"{NS}Prefix").text
                    for p in root.iter(f"{NS}CommonPrefixes")]
        return root, keys, prefixes

    def test_flat_list_v2(self, s3):
        _, keys, _ = self.parse(requests.get(
            f"{s3}/ls", params={"list-type": "2"}).text)
        assert keys == ["a.txt", "b.txt", "dir1/x.txt", "dir1/y.txt",
                        "dir2/z.txt"]

    def test_delimiter_groups(self, s3):
        _, keys, prefixes = self.parse(requests.get(
            f"{s3}/ls", params={"list-type": "2", "delimiter": "/"}
        ).text)
        assert keys == ["a.txt", "b.txt"]
        assert prefixes == ["dir1/", "dir2/"]

    def test_prefix_within_dir(self, s3):
        _, keys, _ = self.parse(requests.get(
            f"{s3}/ls", params={"list-type": "2", "prefix": "dir1/"}
        ).text)
        assert keys == ["dir1/x.txt", "dir1/y.txt"]

    def test_pagination(self, s3):
        root, keys, _ = self.parse(requests.get(
            f"{s3}/ls", params={"list-type": "2", "max-keys": "2"}).text)
        assert keys == ["a.txt", "b.txt"]
        assert root.find(f"{NS}IsTruncated").text == "true"
        token = root.find(f"{NS}NextContinuationToken").text
        _, keys2, _ = self.parse(requests.get(
            f"{s3}/ls", params={"list-type": "2", "max-keys": "10",
                                "continuation-token": token}).text)
        assert keys2 == ["dir1/x.txt", "dir1/y.txt", "dir2/z.txt"]


class TestListingEdgeCases:
    def test_prefix_with_delimiter_navigates_folder(self, s3):
        """aws s3 ls s3://b/dir1/ — prefix ending in '/' + delimiter."""
        put_bucket(s3, "nav")
        for k in ("dir1/x.txt", "dir1/sub/deep.txt", "top.txt"):
            requests.put(f"{s3}/nav/{k}", data=b"1")
        root = ET.fromstring(requests.get(
            f"{s3}/nav", params={"list-type": "2", "prefix": "dir1/",
                                 "delimiter": "/"}).text)
        keys = [c.find(f"{NS}Key").text
                for c in root.iter(f"{NS}Contents")]
        prefixes = [p.find(f"{NS}Prefix").text
                    for p in root.iter(f"{NS}CommonPrefixes")]
        assert keys == ["dir1/x.txt"]
        assert prefixes == ["dir1/sub/"]

    def test_get_prefix_key_is_404(self, s3):
        put_bucket(s3, "pfx")
        requests.put(f"{s3}/pfx/d/inner.txt", data=b"1")
        r = requests.get(f"{s3}/pfx/d")
        assert r.status_code == 404
        assert "NoSuchKey" in r.text

    def test_delete_prefix_key_keeps_children(self, s3):
        put_bucket(s3, "safe")
        requests.put(f"{s3}/safe/d/keep.txt", data=b"1")
        assert requests.delete(f"{s3}/safe/d").status_code == 204
        assert requests.get(f"{s3}/safe/d/keep.txt").status_code == 200

    def test_delete_bucket_with_upload_and_object(self, s3):
        put_bucket(s3, "mixed")
        requests.post(f"{s3}/mixed/f.bin?uploads")  # creates .uploads
        requests.put(f"{s3}/mixed/real.txt", data=b"1")
        r = requests.delete(f"{s3}/mixed")
        assert r.status_code == 409
        assert requests.get(f"{s3}/mixed/real.txt").status_code == 200


class TestPaginationWithPrefixes:
    def test_no_duplicate_prefixes_across_pages(self, s3):
        """Prefixes count toward max-keys; concatenated pages must not
        repeat a CommonPrefix."""
        put_bucket(s3, "pgx")
        for k in ("a1", "a2", "a3", "zdir/f.txt"):
            requests.put(f"{s3}/pgx/{k}", data=b"1")
        seen_keys, seen_prefixes, token = [], [], ""
        for _ in range(10):
            params = {"list-type": "2", "max-keys": "2",
                      "delimiter": "/"}
            if token:
                params["continuation-token"] = token
            root = ET.fromstring(requests.get(f"{s3}/pgx",
                                              params=params).text)
            seen_keys += [c.find(f"{NS}Key").text
                          for c in root.iter(f"{NS}Contents")]
            seen_prefixes += [p.find(f"{NS}Prefix").text
                              for p in root.iter(f"{NS}CommonPrefixes")]
            if root.find(f"{NS}IsTruncated").text != "true":
                break
            token = root.find(f"{NS}NextContinuationToken").text
        assert seen_keys == ["a1", "a2", "a3"]
        assert seen_prefixes == ["zdir/"]


class TestContentIntegrity:
    def test_tampered_body_rejected(self, tmp_path_factory):
        cfg = {"identities": [{"name": "w", "credentials": [
            {"accessKey": "WK", "secretKey": "WS"}],
            "actions": ["Admin"]}]}
        c = Cluster(str(tmp_path_factory.mktemp("s3_integrity")),
                    n_volume_servers=1, with_s3=True, s3_config=cfg)
        try:
            s3 = c.s3_url
            h = sign_request("PUT", f"{s3}/ib", "WK", "WS")
            assert requests.put(f"{s3}/ib",
                                headers=h).status_code == 200
            h = sign_request("PUT", f"{s3}/ib/f", "WK", "WS",
                             payload=b"original")
            # replay the captured signature with a substituted body
            r = requests.put(f"{s3}/ib/f", data=b"TAMPERED", headers=h)
            assert r.status_code == 400
            assert "XAmzContentSHA256Mismatch" in r.text
        finally:
            c.stop()


class TestMultipart:
    def test_full_flow(self, s3):
        put_bucket(s3, "mp")
        r = requests.post(f"{s3}/mp/large.bin?uploads")
        upload_id = ET.fromstring(r.text).find(f"{NS}UploadId").text
        part1 = b"A" * (1 << 20)
        part2 = b"B" * 100
        for i, part in ((1, part1), (2, part2)):
            pr = requests.put(
                f"{s3}/mp/large.bin",
                params={"partNumber": str(i), "uploadId": upload_id},
                data=part)
            assert pr.status_code == 200, pr.text
        lp = requests.get(f"{s3}/mp/large.bin",
                          params={"uploadId": upload_id})
        assert lp.text.count("<Part>") == 2
        body = ("<CompleteMultipartUpload>"
                "<Part><PartNumber>1</PartNumber></Part>"
                "<Part><PartNumber>2</PartNumber></Part>"
                "</CompleteMultipartUpload>").encode()
        cr = requests.post(f"{s3}/mp/large.bin",
                           params={"uploadId": upload_id}, data=body)
        assert cr.status_code == 200, cr.text
        etag = ET.fromstring(cr.text).find(f"{NS}ETag").text
        assert etag.endswith('-2"') or etag.endswith("-2")
        got = requests.get(f"{s3}/mp/large.bin")
        assert got.content == part1 + part2
        # ranged read across the part boundary
        rng = requests.get(
            f"{s3}/mp/large.bin",
            headers={"Range": f"bytes={(1 << 20) - 2}-{(1 << 20) + 1}"})
        assert rng.content == b"AABB"

    def test_abort(self, s3):
        put_bucket(s3, "mp2")
        r = requests.post(f"{s3}/mp2/x.bin?uploads")
        upload_id = ET.fromstring(r.text).find(f"{NS}UploadId").text
        requests.put(f"{s3}/mp2/x.bin",
                     params={"partNumber": "1", "uploadId": upload_id},
                     data=b"junk")
        assert requests.delete(
            f"{s3}/mp2/x.bin",
            params={"uploadId": upload_id}).status_code == 204
        cr = requests.post(f"{s3}/mp2/x.bin",
                           params={"uploadId": upload_id})
        assert cr.status_code == 404


class TestTagging:
    def test_put_get_delete(self, s3):
        put_bucket(s3, "tags")
        requests.put(f"{s3}/tags/t.txt", data=b"x")
        body = (b"<Tagging><TagSet><Tag><Key>env</Key>"
                b"<Value>prod</Value></Tag></TagSet></Tagging>")
        assert requests.put(f"{s3}/tags/t.txt?tagging",
                            data=body).status_code == 200
        got = requests.get(f"{s3}/tags/t.txt?tagging").text
        assert "env" in got and "prod" in got
        assert requests.delete(
            f"{s3}/tags/t.txt?tagging").status_code == 204
        got2 = requests.get(f"{s3}/tags/t.txt?tagging").text
        assert "env" not in got2


class TestSigV4:
    @pytest.fixture(scope="class")
    def auth_cluster(self, tmp_path_factory):
        cfg = {"identities": [
            {"name": "admin",
             "credentials": [{"accessKey": "AKID", "secretKey": "SK"}],
             "actions": ["Admin"]},
            {"name": "reader",
             "credentials": [{"accessKey": "RKID", "secretKey": "RS"}],
             "actions": ["Read", "List"]},
        ]}
        c = Cluster(str(tmp_path_factory.mktemp("s3_auth")),
                    n_volume_servers=1, volume_size_limit=16 << 20,
                    with_s3=True, s3_config=cfg)
        yield c
        c.stop()

    def test_anonymous_denied(self, auth_cluster):
        r = requests.put(f"{auth_cluster.s3_url}/priv")
        assert r.status_code == 403
        assert "AccessDenied" in r.text

    def test_signed_round_trip(self, auth_cluster):
        s3 = auth_cluster.s3_url
        h = sign_request("PUT", f"{s3}/priv", "AKID", "SK")
        assert requests.put(f"{s3}/priv", headers=h).status_code == 200
        h = sign_request("PUT", f"{s3}/priv/f.txt", "AKID", "SK",
                         payload=b"secret")
        assert requests.put(f"{s3}/priv/f.txt", data=b"secret",
                            headers=h).status_code == 200
        h = sign_request("GET", f"{s3}/priv/f.txt", "AKID", "SK")
        assert requests.get(f"{s3}/priv/f.txt",
                            headers=h).content == b"secret"

    def test_bad_signature_rejected(self, auth_cluster):
        s3 = auth_cluster.s3_url
        h = sign_request("GET", f"{s3}/priv/f.txt", "AKID", "WRONG")
        r = requests.get(f"{s3}/priv/f.txt", headers=h)
        assert r.status_code == 403
        assert "SignatureDoesNotMatch" in r.text

    def test_reader_cannot_write(self, auth_cluster):
        s3 = auth_cluster.s3_url
        h = sign_request("PUT", f"{s3}/priv/no.txt", "RKID", "RS",
                         payload=b"nope")
        r = requests.put(f"{s3}/priv/no.txt", data=b"nope", headers=h)
        assert r.status_code == 403
        h = sign_request("GET", f"{s3}/priv/f.txt", "RKID", "RS")
        assert requests.get(f"{s3}/priv/f.txt",
                            headers=h).status_code == 200

    def test_presigned_url(self, auth_cluster):
        s3 = auth_cluster.s3_url
        url = presign_url("GET", f"{s3}/priv/f.txt", "AKID", "SK")
        assert requests.get(url).content == b"secret"
        bad = url.replace("X-Amz-Signature=", "X-Amz-Signature=0")
        assert requests.get(bad).status_code == 403
