"""MongoDB filer store over the real OP_MSG/BSON wire, against the
in-process mini-mongod (tests/minimongo.py) — third in-tree wire
protocol after redis RESP and the etcd v3 gateway. Reference slot:
/root/reference/weed/filer/mongodb/mongodb_store.go.
"""
import time

import pytest

from seaweedfs_tpu.filer import bson_lite
from seaweedfs_tpu.filer.entry import Entry, FileChunk
from seaweedfs_tpu.filer.filer import Filer
from seaweedfs_tpu.filer.mongodb_store import MongodbStore

from .minimongo import MiniMongo


# -- BSON codec spec checks --------------------------------------------

def test_bson_round_trip():
    doc = {"s": "héllo", "i32": 42, "i64": 1 << 40, "f": 2.5,
           "b": True, "none": None, "bin": b"\x00\x01\xff",
           "sub": {"k": "v"}, "arr": ["a", 1, {"x": b"y"}]}
    assert bson_lite.decode_doc(bson_lite.encode_doc(doc)) == doc


def test_bson_known_bytes():
    # {"hello": "world"} — the canonical example from bsonspec.org:
    # \x16\x00\x00\x00 \x02 hello\x00 \x06\x00\x00\x00 world\x00 \x00
    assert bson_lite.encode_doc({"hello": "world"}) == (
        b"\x16\x00\x00\x00\x02hello\x00\x06\x00\x00\x00world\x00\x00")


# -- store over the wire ------------------------------------------------

@pytest.fixture(scope="module")
def mongo_server():
    s = MiniMongo().start()
    yield s
    s.stop()


@pytest.fixture()
def store(mongo_server):
    mongo_server._data.clear()
    s = MongodbStore(port=mongo_server.port)
    yield s
    s.close()


def ent(path, size=0):
    chunks = [FileChunk(fid="1,ab", offset=0, size=size,
                        mtime_ns=time.time_ns())] if size else []
    return Entry(full_path=path, chunks=chunks)


def test_insert_find_update_delete(store):
    store.insert_entry(ent("/a/b.txt", 10))
    assert store.find_entry("/a/b.txt").file_size == 10
    store.update_entry(ent("/a/b.txt", 20))
    assert store.find_entry("/a/b.txt").file_size == 20
    store.delete_entry("/a/b.txt")
    assert store.find_entry("/a/b.txt") is None


def test_listing_order_pagination_prefix(store):
    for n in ("zeta", "alpha", "beta", "beta2", "gamma"):
        store.insert_entry(ent(f"/dir/{n}"))
    store.insert_entry(ent("/dir/beta/child"))  # nested: must not leak
    names = [e.name for e in store.list_directory_entries("/dir")]
    assert names == ["alpha", "beta", "beta2", "gamma", "zeta"]
    page = store.list_directory_entries("/dir", start_from="beta",
                                        inclusive=False, limit=2)
    assert [e.name for e in page] == ["beta2", "gamma"]
    pref = store.list_directory_entries("/dir", prefix="beta")
    assert [e.name for e in pref] == ["beta", "beta2"]


def test_getmore_cursor_pagination(store):
    for i in range(300):
        store.insert_entry(ent(f"/big/f{i:04d}"))
    # batchSize < limit forces the getMore path in the store
    got = store._cmd({"find": "filemeta",
                      "filter": {"dir": "/big"},
                      "sort": {"name": 1}, "limit": 300,
                      "batchSize": 50})
    assert len(got["cursor"]["firstBatch"]) == 50
    names = [e.name for e in
             store.list_directory_entries("/big", limit=300)]
    assert names == [f"f{i:04d}" for i in range(300)]


def test_delete_folder_children_subtree(store):
    for p in ("/t/a", "/t/sub/x", "/t/sub/deep/y", "/tother/z"):
        store.insert_entry(ent(p))
    store.delete_folder_children("/t")
    for p in ("/t/a", "/t/sub/x", "/t/sub/deep/y"):
        assert store.find_entry(p) is None, p
    assert store.find_entry("/tother/z") is not None


def test_root_recursive_delete(store):
    for p in ("/a/b/deep.txt", "/a/top", "/c"):
        store.insert_entry(ent(p))
    store.delete_folder_children("/")
    for p in ("/a/b/deep.txt", "/a/top", "/c"):
        assert store.find_entry(p) is None, p


def test_kv(store):
    store.kv_put("conf", b"\x00\x01binary")
    assert store.kv_get("conf") == b"\x00\x01binary"
    store.kv_delete("conf")
    assert store.kv_get("conf") is None


def test_full_filer_stack(mongo_server):
    mongo_server._data.clear()
    f = Filer("mongodb", port=mongo_server.port)
    try:
        f.create_entry(ent("/docs/readme.md", 5))
        assert f.find_entry("/docs/readme.md").file_size == 5
        assert f.find_entry("/docs").is_directory
        assert [e.name for e in f.list_entries("/docs")] == ["readme.md"]
        f.delete_entry("/docs", recursive=True)
        assert f.find_entry("/docs/readme.md") is None
    finally:
        f.close()


def test_exclusive_start_equal_to_prefix(store):
    # review finding: start_from == prefix (exclusive) must not repeat
    # the boundary entry on the next page
    for n in ("beta", "beta2", "beta3"):
        store.insert_entry(ent(f"/pg/{n}"))
    page1 = store.list_directory_entries("/pg", prefix="beta", limit=1)
    assert [e.name for e in page1] == ["beta"]
    page2 = store.list_directory_entries("/pg", prefix="beta",
                                         start_from="beta",
                                         inclusive=False, limit=2)
    assert [e.name for e in page2] == ["beta2", "beta3"]
