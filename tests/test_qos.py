"""Edge QoS (utils/qos.py): per-tenant admission, deadline-aware
shedding, bounded tenant cardinality — unit tests plus live-gateway
integration and the overload chaos scenario (10x provisioned burst
mid-workload: zero acked-write loss, shed counters account for the
excess, queue delay stays bounded).
"""
import json
import threading
import time

import pytest
import requests

from seaweedfs_tpu.server.cluster import Cluster
from seaweedfs_tpu.utils import metrics, qos, retry
from seaweedfs_tpu.utils.qos import OVERFLOW_TENANT


@pytest.fixture(autouse=True)
def _clean_qos():
    qos.reset()
    yield
    qos.reset()


def _counter(name: str, **labels) -> float:
    want = tuple(sorted(labels.items()))
    with metrics._lock:
        return sum(v for (n, lab), v in metrics._counters.items()
                   if n == name and set(want) <= set(lab))


class TestAdmission:
    def test_disabled_admits_everything_free(self):
        a = qos.admit("anyone", 1 << 30, 0.001)
        assert a.admitted and a.wait == 0.0

    def test_zero_rate_tenant_is_unshaped(self):
        qos.configure(enabled=True, rate=0)
        a = qos.admit("t", 1 << 20, 10.0)
        assert a.admitted and a.wait == 0.0

    def test_paced_then_shed_on_rate(self):
        qos.configure(enabled=True, rate=100_000, max_delay=0.5)
        first = qos.admit("greedy", 4096, None)
        assert first.admitted
        a = qos.admit("greedy", 1 << 20, None)  # ~10s quote
        assert not a.admitted
        assert a.shed_reason == "rate"
        assert a.retry_after > 0.5

    def test_shed_cancels_reservation(self):
        qos.configure(enabled=True, rate=100_000, max_delay=0.5)
        qos.admit("t", 1 << 20, None)  # shed; must owe nothing
        a = qos.admit("t", 4096, None)
        assert a.admitted and a.wait < 0.5

    def test_deadline_shed_beats_doomed_504(self):
        qos.configure(enabled=True, rate=100_000, max_delay=30.0)
        a = qos.admit("t", 200_000, 0.5)  # ~2s quote, 0.5s budget
        assert not a.admitted
        assert a.shed_reason == "deadline"

    def test_priority_divides_the_charge(self):
        qos.configure(enabled=True, rate=100_000, max_delay=30.0)
        qos.load_spec({"tenants": {"gold": {"priority": 4}}})
        w_gold = qos.admit("gold", 100_000, None).wait
        w_base = qos.admit("base", 100_000, None).wait
        assert w_gold < w_base / 2

    def test_tenant_cardinality_is_bounded(self):
        qos.configure(enabled=True, rate=1e9, max_tenants=4)
        for i in range(10):
            a = qos.admit(f"spray-{i}", 1, None)
            assert a.admitted
        snap = qos.snapshot()
        assert len(snap["tenants"]) <= 5  # 4 named + __overflow__
        assert OVERFLOW_TENANT in snap["tenants"]

    def test_tenant_label_value_is_sanitized(self):
        qos.configure(enabled=True, rate=1e9)
        a = qos.admit('evil"} tenant\n{x', 1, None)
        assert '"' not in a.tenant and "\n" not in a.tenant
        assert qos.admit("", 1, None).tenant == "anonymous"

    def test_spec_hot_reload_on_mtime(self, tmp_path):
        spec = tmp_path / "qos.json"
        spec.write_text(json.dumps(
            {"default": {"rate": 50_000}}))
        qos.configure(enabled=True, rate=1000, spec=str(spec))
        assert qos.snapshot()["default_rate"] == 50_000
        # rewrite with a bumped mtime: next admit must re-rate
        spec.write_text(json.dumps(
            {"default": {"rate": 75_000},
             "tenants": {"a": {"rate": 10_000}}}))
        import os
        os.utime(spec, (time.time() + 5, time.time() + 5))
        time.sleep(qos.SPEC_CHECK_INTERVAL + 0.1)
        qos.admit("a", 1, None)
        snap = qos.snapshot()
        assert snap["default_rate"] == 75_000
        assert snap["tenants"]["a"]["rate"] == 10_000

    def test_malformed_spec_keeps_previous_config(self, tmp_path):
        spec = tmp_path / "qos.json"
        spec.write_text(json.dumps({"default": {"rate": 9_000}}))
        qos.configure(enabled=True, spec=str(spec))
        assert qos.snapshot()["default_rate"] == 9_000
        spec.write_text("{not json")
        import os
        os.utime(spec, (time.time() + 5, time.time() + 5))
        time.sleep(qos.SPEC_CHECK_INTERVAL + 0.1)
        qos.admit("a", 1, None)
        assert qos.snapshot()["default_rate"] == 9_000

    def test_shed_and_admit_counters(self):
        qos.configure(enabled=True, rate=100_000, max_delay=0.2)
        s0 = _counter("qos_shed_total", tenant="ctr")
        a0 = _counter("qos_admitted_total", tenant="ctr")
        qos.admit("ctr", 4096, None)
        qos.admit("ctr", 1 << 20, None)
        assert _counter("qos_admitted_total", tenant="ctr") == a0 + 1
        assert _counter("qos_shed_total", tenant="ctr") == s0 + 1


class TestTenantExtraction:
    class _Req:
        def __init__(self, headers=None, query=None, path="/"):
            self.headers = headers or {}
            self.query = query or {}
            self.path = path

    def test_sigv4_authorization_header(self):
        r = self._Req(headers={"Authorization":
                               "AWS4-HMAC-SHA256 Credential=AKIDX/2023"
                               "0101/us-east-1/s3/aws4_request, Signed"
                               "Headers=host, Signature=abc"})
        assert qos.s3_tenant(r) == "AKIDX"

    def test_sigv2_authorization_header(self):
        r = self._Req(headers={"Authorization": "AWS AKIDV2:sig=="})
        assert qos.s3_tenant(r) == "AKIDV2"

    def test_presigned_query_credential(self):
        r = self._Req(query={"X-Amz-Credential":
                             "AKIDQ/20230101/us-east-1/s3/aws4_request"})
        assert qos.s3_tenant(r) == "AKIDQ"
        assert qos.s3_tenant(
            self._Req(query={"AWSAccessKeyId": "AKIDOLD"})) == "AKIDOLD"

    def test_anonymous_fallback(self):
        assert qos.s3_tenant(self._Req()) == "anonymous"

    def test_filer_tenant_is_first_segment(self):
        assert qos.filer_tenant(self._Req(path="/teamA/x/y.bin")) \
            == "teamA"
        assert qos.filer_tenant(self._Req(path="/")) == "_root"


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = Cluster(str(tmp_path_factory.mktemp("qos_cluster")),
                n_volume_servers=1, volume_size_limit=16 << 20,
                with_s3=True)
    yield c
    c.stop()


class TestGatewayIntegration:
    def test_filer_shed_carries_retryable_attestation(self, cluster):
        qos.configure(enabled=True, rate=50_000, max_delay=0.2)
        url = f"{cluster.filer_url}/shedme/obj.bin"
        r = requests.put(url, data=b"x" * (1 << 20), timeout=30)
        assert r.status_code == 503, r.text
        assert r.headers.get(retry.RETRYABLE_HEADER) == "1"
        assert int(r.headers["Retry-After"]) >= 1
        body = r.json()
        assert body["reason"] == "rate"
        assert body["tenant"] == "shedme"

    def test_filer_deadline_shed(self, cluster):
        qos.configure(enabled=True, rate=50_000, max_delay=30.0)
        url = f"{cluster.filer_url}/dlshed/obj.bin"
        hdr = {retry.DEADLINE_HEADER: str(time.time() + 0.3)}
        r = requests.put(url, data=b"x" * 200_000, headers=hdr,
                         timeout=30)
        assert r.status_code == 503, r.text
        assert r.json()["reason"] == "deadline"
        assert r.headers.get(retry.RETRYABLE_HEADER) == "1"

    def test_filer_tame_tenant_unaffected_by_greedy(self, cluster):
        qos.configure(enabled=True, rate=200_000, max_delay=0.3)
        greedy = f"{cluster.filer_url}/hog/big.bin"
        requests.put(greedy, data=b"x" * (1 << 20), timeout=30)
        # greedy's own bucket is now deep in debt; tame's is fresh
        r = requests.put(f"{cluster.filer_url}/tame/ok.bin",
                         data=b"ok", timeout=30)
        assert r.status_code == 201, r.text
        assert requests.get(f"{cluster.filer_url}/tame/ok.bin",
                            timeout=30).content == b"ok"

    def test_control_plane_paths_never_shaped(self, cluster):
        qos.configure(enabled=True, rate=1, max_delay=0.0)
        assert requests.get(f"{cluster.filer_url}/status",
                            timeout=10).status_code == 200
        assert requests.get(f"{cluster.filer_url}/metrics",
                            timeout=10).status_code == 200
        r = requests.put(f"{cluster.filer_url}/kv/qos/test",
                         data=b"v", timeout=10)
        assert r.status_code in (200, 201, 204)

    def test_debug_qos_on_both_gateways(self, cluster):
        qos.configure(enabled=True, rate=100_000)
        requests.put(f"{cluster.filer_url}/dbg/x", data=b"1",
                     timeout=30)
        for base in (cluster.filer_url, cluster.s3_url):
            snap = requests.get(f"{base}/debug/qos", timeout=10).json()
            assert snap["enabled"] is True
            assert "tenants" in snap
        snap = requests.get(f"{cluster.filer_url}/debug/qos",
                            timeout=10).json()
        assert "dbg" in snap["tenants"]

    def test_s3_tenant_attribution_by_access_key(self, cluster):
        qos.configure(enabled=True, rate=40_000, max_delay=0.2)
        # open gateway: a bare X-Amz-Credential attributes without
        # tripping signature verification
        q = "?X-Amz-Credential=AKIDGREEDY/20230101/us-east-1/s3/x"
        requests.put(f"{cluster.s3_url}/qosb{q}", timeout=30)
        r = requests.put(f"{cluster.s3_url}/qosb/big.bin{q}",
                         data=b"x" * (1 << 20), timeout=30)
        assert r.status_code == 503
        assert r.json()["tenant"] == "AKIDGREEDY"
        assert r.headers.get(retry.RETRYABLE_HEADER) == "1"
        snap = requests.get(f"{cluster.s3_url}/debug/qos",
                            timeout=10).json()
        assert "AKIDGREEDY" in snap["tenants"]

    def test_cluster_status_carries_qos_summary(self, cluster):
        qos.configure(enabled=True, rate=50_000, max_delay=0.2)
        requests.put(f"{cluster.filer_url}/statq/big.bin",
                     data=b"x" * (1 << 20), timeout=30)  # shed
        # force a federation sweep so the master's summary is fresh
        cluster.master.federator.scrape_once()
        st = requests.get(f"{cluster.master_url}/cluster/status",
                          timeout=10).json()
        assert "Qos" in st
        assert set(st["Qos"]) == {"Admitted", "Shed"}
        # the shed above happened in THIS process, whose /metrics the
        # federator scraped via the filer's membership registration
        shed = st["Qos"]["Shed"].get("statq", {})
        assert sum(shed.values()) >= 1, st["Qos"]


@pytest.mark.chaos
class TestOverloadChaos:
    def test_10x_burst_zero_acked_loss_and_accounted_shed(self, cluster):
        """Overload chaos: a tenant provisioned for ~50 req/s bursts
        10x that mid-workload. The gateway must (a) never lose an
        acked write, (b) keep every admitted request's queue delay
        bounded by -qos.maxDelay, (c) account for the whole excess in
        qos_shed_total, and (d) keep a concurrent tame tenant at 100%
        success — all without a blocking sleep on the event loop (the
        tame tenant's latency IS that assertion: a blocked loop would
        stall it behind the burst)."""
        floor = 4096
        body = 16 << 10  # each burst PUT charges its 16KiB body
        rate = 50 * floor  # ~200KB/s provisioned for the burster
        max_delay = 0.3
        qos.configure(enabled=True, rate=rate, max_delay=max_delay,
                      request_floor=floor)

        s0 = _counter("qos_shed_total", tenant="burst")
        a0 = _counter("qos_admitted_total", tenant="burst")

        results = []
        res_lock = threading.Lock()
        tame_fail = []
        tame_lat = []
        stop_tame = threading.Event()

        def tame_loop():
            i = 0
            while not stop_tame.is_set():
                t0 = time.perf_counter()
                try:
                    r = requests.put(
                        f"{cluster.filer_url}/tamebg/o{i}",
                        data=b"t" * 512, timeout=30)
                    if r.status_code != 201:
                        tame_fail.append(r.status_code)
                except requests.RequestException as e:
                    tame_fail.append(repr(e))
                tame_lat.append(time.perf_counter() - t0)
                i += 1
                time.sleep(0.05)  # well under its own rate

        def burst_worker(ids):
            for i in ids:
                t0 = time.perf_counter()
                try:
                    r = requests.put(
                        f"{cluster.filer_url}/burst/o{i}",
                        data=b"b" * body, timeout=30)
                    code = r.status_code
                except requests.RequestException:
                    code = -1
                with res_lock:
                    results.append(
                        (i, code, time.perf_counter() - t0))

        tame = threading.Thread(target=tame_loop)
        tame.start()
        time.sleep(0.3)  # mid-workload: the tame flow is established
        n_burst, n_threads = 160, 16
        threads = [threading.Thread(
            target=burst_worker,
            args=(range(w, n_burst, n_threads),))
            for w in range(n_threads)]
        t_start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        burst_wall = time.perf_counter() - t_start
        stop_tame.set()
        tame.join()

        acked = [i for i, code, _ in results if code == 201]
        shed = [i for i, code, _ in results if code == 503]
        errors = [(i, c) for i, c, _ in results
                  if c not in (201, 503)]
        assert not errors, f"unexpected outcomes: {errors[:5]}"
        assert shed, "a 10x burst must shed"

        # freeze the burst's counters, then turn shaping off so the
        # verification reads below don't re-enter the QoS layer
        shed_ctr = _counter("qos_shed_total", tenant="burst") - s0
        admitted_ctr = _counter("qos_admitted_total",
                                tenant="burst") - a0
        qos.configure(enabled=False)

        # (a) zero acked-write loss: every 201 is readable, intact
        for i in acked:
            r = requests.get(f"{cluster.filer_url}/burst/o{i}",
                             timeout=30)
            assert r.status_code == 200, (i, r.status_code)
            assert r.content == b"b" * body, i
        # (b) bounded queue delay: an admitted request paid at most
        # max_delay of pacing (+ service time under contention)
        acked_lats = sorted(lat for i, code, lat in results
                            if code == 201)
        assert acked_lats[-1] <= max_delay + 5.0
        # (c) the shed counter accounts for the excess exactly
        assert shed_ctr == len(shed)
        assert admitted_ctr == len(acked)
        # admitted volume respects the provisioned rate over the
        # burst window (+ burst allowance + in-flight slack)
        budget = rate * max(burst_wall, 0.1) + rate / 8 \
            + n_threads * body + rate * max_delay
        assert len(acked) * body <= budget, \
            (len(acked), burst_wall, budget)
        # (d) the tame tenant sailed through the whole burst
        assert not tame_fail, tame_fail[:5]
        assert tame_lat and max(tame_lat) < 5.0
