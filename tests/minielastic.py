"""Mini Elasticsearch 7 double: the REST subset the elastic filer
store issues — index create/delete/HEAD, _doc CRUD with refresh,
_search with bool-filter (term / range / prefix on Name) + sort +
size, and basic auth. The fake-gcs / minimongo role for the ES wire.
"""
from __future__ import annotations

import base64
import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class MiniElastic:
    def __init__(self, username: str = "", password: str = ""):
        self.username = username
        self.password = password
        # index -> {doc_id: source_dict}
        self.indexes: dict[str, dict[str, dict]] = {}
        self.lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _json(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if self.command != "HEAD":
                    self.wfile.write(body)

            def _auth_ok(self) -> bool:
                if not outer.username:
                    return True
                got = self.headers.get("Authorization", "")
                want = "Basic " + base64.b64encode(
                    f"{outer.username}:{outer.password}".encode()
                ).decode()
                return got == want

            def _route(self):
                if not self._auth_ok():
                    return self._json(401, {"error": "unauthorized"})
                u = urllib.parse.urlsplit(self.path)
                parts = [urllib.parse.unquote(p)
                         for p in u.path.strip("/").split("/")]
                n = int(self.headers.get("Content-Length", 0) or 0)
                body = json.loads(self.rfile.read(n)) if n else {}
                with outer.lock:
                    return self._dispatch(parts, body)

            do_GET = do_PUT = do_POST = do_DELETE = do_HEAD = _route

            def _dispatch(self, parts, body):
                ix = outer.indexes
                if len(parts) == 1:  # index-level
                    name = parts[0]
                    if self.command == "HEAD":
                        return self._json(
                            200 if name in ix else 404, {})
                    if self.command == "PUT":
                        ix.setdefault(name, {})
                        return self._json(200, {"acknowledged": True})
                    if self.command == "DELETE":
                        if ix.pop(name, None) is None:
                            return self._json(404, {"error": "no index"})
                        return self._json(200, {"acknowledged": True})
                if len(parts) == 2 and parts[1] == "_search":
                    return self._search(ix.get(parts[0]), body)
                if len(parts) == 3 and parts[1] == "_doc":
                    index, _, doc_id = parts
                    if self.command == "PUT":
                        ix.setdefault(index, {})[doc_id] = body
                        return self._json(201, {"result": "created"})
                    docs = ix.get(index, {})
                    if self.command == "GET":
                        if doc_id not in docs:
                            return self._json(404, {"found": False})
                        return self._json(200, {"found": True,
                                                "_id": doc_id,
                                                "_source": docs[doc_id]})
                    if self.command == "DELETE":
                        if docs.pop(doc_id, None) is None:
                            return self._json(404,
                                              {"result": "not_found"})
                        return self._json(200, {"result": "deleted"})
                return self._json(400, {"error": f"bad route {parts}"})

            def _search(self, docs, body):
                if docs is None:
                    return self._json(404, {"error": "no such index"})
                filt = body.get("query", {}).get("bool", {}) \
                    .get("filter", [])
                out = []
                for doc_id, src in docs.items():
                    ok = True
                    for f in filt:
                        if "term" in f:
                            ((k, v),) = f["term"].items()
                            ok &= src.get(k) == v
                        elif "range" in f:
                            ((k, cond),) = f["range"].items()
                            val = src.get(k, "")
                            for op, rhs in cond.items():
                                ok &= {"gt": val > rhs,
                                       "gte": val >= rhs,
                                       "lt": val < rhs,
                                       "lte": val <= rhs}[op]
                        elif "prefix" in f:
                            ((k, v),) = f["prefix"].items()
                            ok &= str(src.get(k, "")).startswith(v)
                        else:
                            return self._json(
                                400, {"error": f"bad filter {f}"})
                    if ok:
                        out.append({"_id": doc_id, "_source": src})
                for s in reversed(body.get("sort", [])):
                    ((k, order),) = s.items() if isinstance(s, dict) \
                        else ((s, "asc"),)
                    out.sort(key=lambda h: h["_source"].get(k, ""),
                             reverse=order == "desc")
                out = out[:body.get("size", 10)]
                return self._json(200, {"hits": {"hits": out}})

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._srv.server_port
        threading.Thread(target=self._srv.serve_forever,
                         daemon=True).start()

    def close(self):
        self._srv.shutdown()
