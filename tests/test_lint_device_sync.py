"""Fast tier-1 lint: no bare device synchronization in serving code.

Serving packages (server/, filer/, s3/, mount/) must never touch the
accelerator directly: a bare ``jax.device_get``/``.block_until_ready()``
stalls a request thread behind the (possibly relayed) link for the
whole transfer, and an argless ``jax.device_put(x)`` uploads to an
UNCOMMITTED default device — XLA is then free to re-copy the array per
executable, silently doubling link traffic.

The rule logic lives in seaweedfs_tpu/analysis/rules/device_sync.py;
this module keeps the historical entrypoints as thin wrappers over the
shared engine pass. The negative control now rides the jax-hygiene
rule's stats: the pipeline layer's drain sites are where sync lives."""
import pytest

from seaweedfs_tpu.analysis import run_cached

pytestmark = pytest.mark.lint


def test_no_bare_device_sync_in_serving_code():
    offenders = [f.render() for f in run_cached().by_rule("device-sync")]
    assert not offenders, "\n".join(offenders)


def test_pipeline_layer_is_where_sync_lives():
    """Negative control: the staged pipeline genuinely synchronizes at
    its drain sites (that's its contract) — if those call sites
    vanished, the serving-side lint would be guarding an empty set."""
    assert run_cached().stats["feed_sync_sites"] > 0
