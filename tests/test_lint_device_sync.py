"""Fast tier-1 lint: no bare device synchronization in serving code.

Serving packages (server/, filer/, s3/, mount/) must never touch the
accelerator directly: a bare ``jax.device_get``/``.block_until_ready()``
stalls a request thread behind the (possibly relayed) link for the
whole transfer, and an argless ``jax.device_put(x)`` uploads to an
UNCOMMITTED default device — XLA is then free to re-copy the array per
executable, silently doubling link traffic. All device traffic belongs
in the staged pipeline (ops/codec_jax.py) behind the measured router
(ec/backend.py), which uses committed shardings and overlapped
transfers and reports per-stage timings.
"""
import os
import re

PKG_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "seaweedfs_tpu")

# request-serving packages: anything here runs inside an event loop or
# a per-request worker thread
SERVING_DIRS = ("server", "filer", "s3", "mount")

_DEVICE_GET_RE = re.compile(r"\bjax\.device_get\s*\(")
_BLOCK_RE = re.compile(r"\.block_until_ready\s*\(")
_DEVICE_PUT_RE = re.compile(r"\bdevice_put\s*\(")


def _iter_serving_sources():
    for sub in SERVING_DIRS:
        base = os.path.join(PKG_DIR, sub)
        if not os.path.isdir(base):
            continue
        for root, _dirs, files in os.walk(base):
            for fn in files:
                if fn.endswith(".py"):
                    path = os.path.join(root, fn)
                    with open(path, encoding="utf-8") as f:
                        yield os.path.relpath(path, PKG_DIR), f.read()


def _call_args(src: str, open_paren: int) -> str:
    """Argument text of the call whose '(' is at ``open_paren``
    (balanced-paren scan, lint-grade)."""
    depth = 0
    for i in range(open_paren, min(len(src), open_paren + 4000)):
        c = src[i]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                return src[open_paren + 1:i]
    return src[open_paren + 1:open_paren + 4000]


def _has_top_level_comma(args: str) -> bool:
    depth = 0
    for c in args:
        if c in "([{":
            depth += 1
        elif c in ")]}":
            depth -= 1
        elif c == "," and depth == 0:
            return True
    return False


def _line(src: str, pos: int) -> int:
    return src.count("\n", 0, pos) + 1


def test_no_bare_device_sync_in_serving_code():
    offenders = []
    for rel, src in _iter_serving_sources():
        for m in _DEVICE_GET_RE.finditer(src):
            offenders.append(
                f"{rel}:{_line(src, m.start())}: jax.device_get — "
                "synchronous D2H in a request thread")
        for m in _BLOCK_RE.finditer(src):
            offenders.append(
                f"{rel}:{_line(src, m.start())}: .block_until_ready() "
                "— blocks the request thread on the device")
        for m in _DEVICE_PUT_RE.finditer(src):
            args = _call_args(src, m.end() - 1)
            if not _has_top_level_comma(args):
                offenders.append(
                    f"{rel}:{_line(src, m.start())}: device_put with "
                    "no placement — uncommitted upload, XLA may "
                    "re-copy per executable")
    assert not offenders, (
        "bare device synchronization in serving code; route through "
        "the staged pipeline (ops/codec_jax.py) via the EC router "
        "(ec/backend.py):\n" + "\n".join(offenders))


def test_pipeline_layer_is_where_sync_lives():
    """Negative control: the fence is about placement, not the
    primitives — the staged pipeline layer itself MUST wait on the
    device (that is its job), so the lint would be vacuous if these
    calls existed nowhere."""
    path = os.path.join(PKG_DIR, "ops", "codec_jax.py")
    with open(path, encoding="utf-8") as f:
        src = f.read()
    assert _BLOCK_RE.search(src), "pipeline no longer waits on device?"
    assert _DEVICE_PUT_RE.search(src)
