"""Property test: the sharded store is observationally identical to a
single store.

Random namespaces (buckets, nested dirs, skewed name distributions)
are inserted into a ShardedStore and a MemoryStore oracle; every
listing — full scans, random pagination seams, prefix windows,
inclusive edges — must come back byte-identical (same names, same
order, same page boundaries), including after random deletes and
delete_folder_children. This is the acceptance bar for the routing +
k-way-merge design: if any directory's children ever straddled shards
without the merge reconstructing the exact single-store page, a seam
would show here.
"""
import random

import pytest

from seaweedfs_tpu.filer import make_store
from seaweedfs_tpu.filer.entry import Entry

SEEDS = [7, 42, 1337]


def _entry(path, is_dir):
    # fixed timestamps so the sharded copy and the oracle copy carry
    # identical bytes (Entry defaults stamp time.time() per object)
    return Entry(full_path=path, mode=0o40755 if is_dir else 0o644,
                 mtime=1000.0, crtime=1000.0)


def _build_namespace(rng):
    """-> (paths, dirs): a random tree with fan-out hot spots."""
    dirs = ["/", "/buckets"]
    paths = [("/buckets", True)]
    # buckets: the realistic hot namespace
    for b in range(rng.randint(2, 5)):
        bpath = f"/buckets/bkt{b}"
        dirs.append(bpath)
        paths.append((bpath, True))
        for k in range(rng.randint(5, 40)):
            paths.append((f"{bpath}/obj{k:04d}", False))
    # non-bucket top-level trees (single-shard subtrees)
    for t in ("etc", "srv", "var"):
        tpath = f"/{t}"
        dirs.append(tpath)
        paths.append((tpath, True))
        for d in range(rng.randint(1, 4)):
            dpath = f"{tpath}/d{d}"
            dirs.append(dpath)
            paths.append((dpath, True))
            for f in range(rng.randint(0, 25)):
                paths.append((f"{dpath}/f{f:03d}", False))
    return paths, dirs


def _paged(store, dirpath, limit, prefix=""):
    """Walk a directory page by page; -> list of page name-lists."""
    pages, cursor = [], ""
    while True:
        page = store.list_directory_entries(dirpath, start_from=cursor,
                                            limit=limit, prefix=prefix)
        pages.append([e.name for e in page])
        if len(page) < limit:
            break
        cursor = page[-1].name
    return pages


def _assert_identical(sharded, oracle, dirs, rng):
    for d in dirs:
        a = [e.name for e in sharded.list_directory_entries(d,
                                                            limit=10_000)]
        b = [e.name for e in oracle.list_directory_entries(d,
                                                           limit=10_000)]
        assert a == b, f"full listing diverged in {d}"
        # page seams at random limits must match page-for-page
        for limit in (1, 2, 3, rng.randint(4, 16)):
            assert _paged(sharded, d, limit) == _paged(oracle, d, limit), \
                f"page seams diverged in {d} at limit={limit}"
        # prefix windows and inclusive edges
        if b:
            pivot = rng.choice(b)
            for inc in (False, True):
                got = [e.name for e in sharded.list_directory_entries(
                    d, start_from=pivot, inclusive=inc, limit=10_000)]
                want = [e.name for e in oracle.list_directory_entries(
                    d, start_from=pivot, inclusive=inc, limit=10_000)]
                assert got == want, \
                    f"start_from={pivot!r} inclusive={inc} diverged in {d}"
            pfx = pivot[:rng.randint(1, len(pivot))]
            got = [e.name for e in sharded.list_directory_entries(
                d, prefix=pfx, limit=10_000)]
            want = [e.name for e in oracle.list_directory_entries(
                d, prefix=pfx, limit=10_000)]
            assert got == want, f"prefix={pfx!r} diverged in {d}"


@pytest.mark.parametrize("seed", SEEDS)
def test_sharded_matches_single_store_oracle(seed, tmp_path):
    rng = random.Random(seed)
    sharded = make_store("sharded", path=str(tmp_path / "db"),
                         shards=rng.choice([2, 3, 4, 7]), child="leveldb")
    oracle = make_store("memory")
    try:
        paths, dirs = _build_namespace(rng)
        for path, is_dir in paths:
            e = _entry(path, is_dir)
            sharded.insert_entry(e)
            oracle.insert_entry(_entry(path, is_dir))
        _assert_identical(sharded, oracle, dirs, rng)

        # random point deletes keep them in lockstep
        files = [p for p, d in paths if not d]
        rng.shuffle(files)
        for path in files[:len(files) // 3]:
            sharded.delete_entry(path)
            oracle.delete_entry(path)
            assert sharded.find_entry(path) is None
        _assert_identical(sharded, oracle, dirs, rng)

        # subtree deletes too — including a fan-out directory's child
        victims = [d for d in dirs if d not in ("/", "/buckets")]
        for victim in rng.sample(victims, min(3, len(victims))):
            sharded.delete_folder_children(victim)
            oracle.delete_folder_children(victim)
        _assert_identical(sharded, oracle, dirs, rng)

        # point lookups agree everywhere after all the churn
        for path, _ in paths:
            a, b = sharded.find_entry(path), oracle.find_entry(path)
            assert (a is None) == (b is None), f"find diverged at {path}"
            if a is not None:
                assert a.to_dict() == b.to_dict(), \
                    f"entry bytes diverged at {path}"
    finally:
        sharded.close()
        oracle.close()
