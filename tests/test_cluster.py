"""Cluster integration tests: master + volume servers in one process.

Covers the reference's core call stacks (SURVEY.md section 3): assign ->
upload -> direct read; replication fan-out; delete; vacuum; heartbeat
registration and node death; lookup/redirect.
"""
import time

import pytest
import requests

from seaweedfs_tpu.operation import verbs
from seaweedfs_tpu.server.cluster import Cluster
from seaweedfs_tpu.wdclient.client import MasterClient


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = Cluster(str(tmp_path_factory.mktemp("cluster")),
                n_volume_servers=2, volume_size_limit=8 << 20)
    yield c
    c.stop()


class TestWriteReadDelete:
    def test_assign_upload_read(self, cluster):
        a = verbs.assign(cluster.master_url)
        assert "," in a.fid
        verbs.upload(a, b"hello object store", name="greet.txt",
                     mime="text/plain")
        data = verbs.download(f"http://{a.url}/{a.fid}")
        assert data == b"hello object store"

    def test_upload_via_helper_and_headers(self, cluster):
        fid = verbs.upload_data(cluster.master_url, b"x" * 1000,
                                name="x.bin")
        mc = MasterClient(cluster.master_url)
        url = mc.lookup_file_id(fid)
        resp = requests.get(url)
        assert resp.status_code == 200
        assert resp.content == b"x" * 1000
        assert resp.headers["Etag"]

    def test_range_read(self, cluster):
        a = verbs.assign(cluster.master_url)
        verbs.upload(a, bytes(range(200)))
        resp = requests.get(f"http://{a.url}/{a.fid}",
                            headers={"Range": "bytes=10-19"})
        assert resp.status_code == 206
        assert resp.content == bytes(range(10, 20))

    def test_delete_then_404(self, cluster):
        a = verbs.assign(cluster.master_url)
        verbs.upload(a, b"to be deleted")
        verbs.delete(f"http://{a.url}/{a.fid}")
        resp = requests.get(f"http://{a.url}/{a.fid}")
        assert resp.status_code == 404

    def test_wrong_cookie_forbidden(self, cluster):
        a = verbs.assign(cluster.master_url)
        verbs.upload(a, b"cookie test")
        vid_key = a.fid.rsplit(",", 1)[0] if False else a.fid
        # flip last cookie hex digit
        bad = a.fid[:-1] + ("0" if a.fid[-1] != "0" else "1")
        resp = requests.get(f"http://{a.url}/{bad}")
        assert resp.status_code in (403, 404)

    def test_bad_fid_400(self, cluster):
        resp = requests.get(f"{cluster.volume_url(0)}/abc,zz")
        assert resp.status_code in (400, 404)


class TestReplication:
    def test_replicated_write_lands_on_both(self, cluster):
        a = verbs.assign(cluster.master_url, replication="001")
        verbs.upload(a, b"replicated payload")
        vid = int(a.fid.split(",")[0])
        nodes = cluster.master.topo.lookup(vid)
        assert len(nodes) == 2
        # read directly from each server without redirect
        for node in nodes:
            resp = requests.get(f"http://{node.url}/{a.fid}")
            assert resp.status_code == 200, node.url
            assert resp.content == b"replicated payload"

    def test_replicated_delete(self, cluster):
        a = verbs.assign(cluster.master_url, replication="001")
        verbs.upload(a, b"replicated delete")
        vid = int(a.fid.split(",")[0])
        nodes = cluster.master.topo.lookup(vid)
        verbs.delete(f"http://{nodes[0].url}/{a.fid}")
        for node in nodes:
            assert requests.get(
                f"http://{node.url}/{a.fid}").status_code == 404


class TestMasterBehavior:
    def test_lookup(self, cluster):
        a = verbs.assign(cluster.master_url)
        vid = a.fid.split(",")[0]
        resp = requests.get(f"{cluster.master_url}/dir/lookup",
                            params={"volumeId": vid})
        locs = resp.json()["locations"]
        assert any(l["url"] == a.url for l in locs)

    def test_lookup_missing_volume(self, cluster):
        resp = requests.get(f"{cluster.master_url}/dir/lookup",
                            params={"volumeId": "99999"})
        assert resp.status_code == 404

    def test_cluster_status(self, cluster):
        body = requests.get(f"{cluster.master_url}/cluster/status").json()
        assert body["IsLeader"] is True
        n_nodes = sum(len(r["nodes"])
                      for dc in body["Topology"]["datacenters"]
                      for r in dc["racks"])
        assert n_nodes == 2
        # EC router state rides along for operators: either measured
        # (curve + buckets) or an explicit "unprobed" — never missing
        router = body["EcRouter"]
        assert router["cpu_backend"] in ("native", "numpy")
        assert router["probe"]["state"] in ("measured", "unprobed")
        if router["probe"]["state"] == "measured":
            assert isinstance(router["buckets"], list)

    def test_debug_ec(self, cluster):
        """/debug/ec exposes the probe curve, cache age and the chosen
        backend per size bucket without ever triggering a sweep."""
        body = requests.get(f"{cluster.master_url}/debug/ec").json()
        assert body["cache_path"]
        assert body["cache_ttl_s"] > 0
        assert body["probe"]["state"] in ("measured", "unprobed")
        if body["probe"]["state"] == "measured":
            for b in body["buckets"]:
                assert set(b) >= {"size_mb", "backend", "depth",
                                  "device_e2e_mbps", "cpu_mbps"}

    def test_grow(self, cluster):
        before = cluster.master.topo.max_volume_id
        resp = requests.get(f"{cluster.master_url}/vol/grow",
                            params={"count": "2"})
        assert resp.status_code == 200
        assert cluster.master.topo.max_volume_id >= before + 2

    def test_collection_isolation(self, cluster):
        a1 = verbs.assign(cluster.master_url, collection="pics")
        a2 = verbs.assign(cluster.master_url)
        assert a1.fid.split(",")[0] != a2.fid.split(",")[0]

    def test_metrics_endpoint(self, cluster):
        resp = requests.get(f"{cluster.master_url}/metrics")
        assert resp.status_code == 200


class TestVacuum:
    def test_vacuum_compact_via_admin(self, cluster):
        a = verbs.assign(cluster.master_url, collection="vac")
        verbs.upload(a, b"a" * 10000)
        vid = int(a.fid.split(",")[0])
        # write + delete more needles on same volume to create garbage
        server_i = next(i for i, s in enumerate(cluster.stores)
                        if s.has_volume(vid))
        for j in range(5):
            a2 = verbs.assign(cluster.master_url, collection="vac")
            if int(a2.fid.split(",")[0]) == vid:
                verbs.upload(a2, b"b" * 20000)
                verbs.delete(f"http://{a2.url}/{a2.fid}")
        check = cluster.admin(server_i, "/admin/vacuum_check",
                              {"volume": vid})
        ratio = check["garbage_ratio"]
        cluster.admin(server_i, "/admin/vacuum_compact", {"volume": vid})
        check2 = cluster.admin(server_i, "/admin/vacuum_check",
                               {"volume": vid})
        assert check2["garbage_ratio"] <= ratio
        # original still readable after compaction
        assert verbs.download(f"http://{a.url}/{a.fid}") == b"a" * 10000


class TestKeepConnected:
    def test_client_receives_updates(self, cluster):
        mc = MasterClient(cluster.master_url, subscribe=True)
        try:
            a = verbs.assign(cluster.master_url, collection="kc")
            vid = int(a.fid.split(",")[0])
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                with mc._lock:
                    if vid in mc._vid_cache:
                        break
                time.sleep(0.05)
            with mc._lock:
                assert vid in mc._vid_cache
        finally:
            mc.stop()


class TestKeepConnectedInvalidation:
    def test_node_death_invalidates_subscribed_clients(self, tmp_path):
        """The subtle KeepConnected case (topology.go:303,330): a dead
        node's locations must vanish from SUBSCRIBED client caches via
        the push stream (snapshot replace), without any client-side
        lookup or TTL expiry."""
        c = Cluster(str(tmp_path), n_volume_servers=2,
                    volume_size_limit=8 << 20, pulse_seconds=0.2)
        mc = MasterClient(c.master_url, subscribe=True)
        try:
            a = verbs.assign(c.master_url)
            verbs.upload(a, b"x")
            vid = int(a.fid.split(",")[0])
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                with mc._lock:
                    if mc._vid_cache.get(vid):
                        break
                time.sleep(0.05)
            with mc._lock:
                assert mc._vid_cache.get(vid), "push never arrived"
            owner = next(i for i, s in enumerate(c.stores)
                         if s.has_volume(vid))
            c.volume_threads[owner].stop()
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                with mc._lock:
                    locs = mc._vid_cache.get(vid, [])
                if not locs:
                    break
                time.sleep(0.1)
            assert not locs, f"stale locations survived: {locs}"
        finally:
            mc.stop()
            c.stop()


class TestNodeDeath:
    def test_unregister_on_disconnect(self, tmp_path):
        c = Cluster(str(tmp_path), n_volume_servers=2,
                    volume_size_limit=8 << 20, pulse_seconds=0.2)
        try:
            a = verbs.assign(c.master_url)
            verbs.upload(a, b"data before death")
            vid = int(a.fid.split(",")[0])
            owner = next(i for i, s in enumerate(c.stores)
                         if s.has_volume(vid))
            c.volume_threads[owner].stop()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if len(c.master.topo.nodes) == 1:
                    break
                time.sleep(0.1)
            assert len(c.master.topo.nodes) == 1
            assert c.master.topo.lookup(vid) == []
        finally:
            c.stop()


class TestAssignCountBatch:
    def test_upload_to_suffix_fids(self, cluster):
        a = verbs.assign(cluster.master_url, count=3)
        assert a.count == 3
        for i, payload in enumerate((b"zero", b"one", b"two")):
            fid = a.fid if i == 0 else f"{a.fid}_{i}"
            verbs.upload(f"http://{a.url}/{fid}", payload)
        for i, payload in enumerate((b"zero", b"one", b"two")):
            fid = a.fid if i == 0 else f"{a.fid}_{i}"
            assert verbs.download(f"http://{a.url}/{fid}") == payload


def test_master_vacuum_endpoint(tmp_path_factory):
    """/vol/vacuum?garbageThreshold= triggers the on-demand cluster
    vacuum over HTTP (master_server.go:141 volumeVacuumHandler)."""
    import requests

    c = Cluster(str(tmp_path_factory.mktemp("vacnow")),
                n_volume_servers=1, volume_size_limit=32 << 20)
    try:
        a = requests.get(f"{c.master_url}/dir/assign").json()
        url = f"http://{a['publicUrl']}/{a['fid']}"
        body = b"g" * 4096
        assert requests.post(url, data=body, headers={
            "Content-Type": "application/octet-stream"}
        ).status_code == 201
        assert requests.delete(url).status_code == 202  # 100% garbage
        r = requests.post(f"{c.master_url}/vol/vacuum",
                          params={"garbageThreshold": "0.0"})
        assert r.status_code == 200, r.text
        out = r.json()
        assert out["garbageThreshold"] == 0.0
        # the deleted needle's volume was compacted
        vid = int(a["fid"].split(",")[0])
        assert any(d.get("volume") == vid and d.get("replicas")
                   for d in out["results"]), out
        assert requests.get(url).status_code == 404
        # bad threshold -> 406 like the reference
        assert requests.post(f"{c.master_url}/vol/vacuum",
                             params={"garbageThreshold": "zz"}
                             ).status_code == 406
    finally:
        c.stop()


def test_grow_rack_and_node_pins(tmp_path_factory):
    """/vol/grow?rack= / ?dataNode= pin where the main copy lands
    (volume_growth.go option.Rack/DataNode)."""
    import requests

    c = Cluster(str(tmp_path_factory.mktemp("growpin")),
                n_volume_servers=2, volume_size_limit=16 << 20,
                topology=[("dc1", "rA"), ("dc1", "rB")])
    try:
        node_b = None
        for s, (_dc, r) in zip(c.stores, [("dc1", "rA"), ("dc1", "rB")]):
            if r == "rB":
                node_b = s
        g = requests.post(f"{c.master_url}/vol/grow",
                          params={"rack": "rB", "count": "1"})
        assert g.status_code == 200, g.text
        # the new volume exists on the rB node (heartbeat registers it)
        deadline = time.monotonic() + 5
        found = []
        while time.monotonic() < deadline and not found:
            st = requests.get(f"{c.master_url}/dir/status").json()
            for dc in st["Topology"]["datacenters"]:
                for rk in dc["racks"]:
                    if rk["id"] != "rB":
                        continue
                    for n in rk["nodes"]:
                        if n["volumes"]:
                            found.append(n)
            time.sleep(0.1)
        assert found, st
        assert node_b is not None
        # unknown rack: no free slots -> error, not silent misplace
        bad = requests.post(f"{c.master_url}/vol/grow",
                            params={"rack": "nope", "count": "1"})
        assert bad.status_code == 500
        # dataNode pin: the main copy lands on the NAMED server
        st0 = requests.get(f"{c.master_url}/dir/status").json()
        all_nodes = [n for dc in st0["Topology"]["datacenters"]
                     for rk in dc["racks"] for n in rk["nodes"]]
        target = all_nodes[0]["id"]
        vols_before = set(all_nodes[0]["volumes"])
        g2 = requests.post(f"{c.master_url}/vol/grow",
                           params={"dataNode": target, "count": "1"})
        assert g2.status_code == 200, g2.text
        deadline = time.monotonic() + 5
        new_vols = set()
        while time.monotonic() < deadline and not new_vols:
            st1 = requests.get(f"{c.master_url}/dir/status").json()
            for dc in st1["Topology"]["datacenters"]:
                for rk in dc["racks"]:
                    for n in rk["nodes"]:
                        if n["id"] == target:
                            new_vols = set(n["volumes"]) - vols_before
            time.sleep(0.1)
        assert new_vols, st1
        # unknown node: loud error
        assert requests.post(
            f"{c.master_url}/vol/grow",
            params={"dataNode": "nosuch:1", "count": "1"}
        ).status_code == 500
    finally:
        c.stop()
