"""Wide-code EC tier: RS(28,4) volumes end-to-end (beyond-reference,
BASELINE config #4 / VERDICT round-2 item 8).

The reference hard-codes RS(10,4); here `ec.encode -codec=28.4` encodes
cold volumes at 1/7th the parity overhead, with the same geometry math
parameterized by stripe width and the codec recorded in the .vif
sidecar so every consumer (mount, rebuild, degraded read, decode)
agrees.
"""
import os
import secrets

import numpy as np
import pytest
import requests

from seaweedfs_tpu.ec import geometry as geo
from seaweedfs_tpu.ec.backend import ReedSolomon
from seaweedfs_tpu.ec.encoder import (codec_of, rebuild_ec_files,
                                      verify_ec_files, write_ec_files)
from seaweedfs_tpu.operation import verbs
from seaweedfs_tpu.server.cluster import Cluster
from seaweedfs_tpu.shell import commands_ec
from seaweedfs_tpu.shell.env import CommandEnv
from seaweedfs_tpu.shell.repl import run_command


# ---------------------------------------------------------------------
# geometry + file level
# ---------------------------------------------------------------------

def test_parse_codec():
    assert geo.parse_codec("") == (10, 4)
    assert geo.parse_codec("28.4") == (28, 4)
    with pytest.raises(ValueError):
        geo.parse_codec("30.4")  # > uint32 shard mask
    with pytest.raises(ValueError):
        geo.parse_codec("0.4")


def test_wide_locate_round_trip():
    # every byte of a 28-wide stripe maps to the right shard/offset
    k = 28
    dat_size = k * (1 << 14) * 3 + 12345
    small = 1 << 14
    for off in (0, small - 1, small * k, dat_size - 1):
        ivs = geo.locate(dat_size, off, 1, large_block=1 << 20,
                         small_block=small, data_shards=k)
        assert len(ivs) == 1
        sid, shard_off = ivs[0].to_shard_and_offset(
            large_block=1 << 20, small_block=small)
        assert 0 <= sid < k
        # block b of row r belongs to shard b%k at row-offset r*small
        row, block = divmod(off, small * k)
        assert sid == block // small
        assert shard_off == row * small + off % small


def test_wide_write_rebuild_verify_files(tmp_path):
    rng = np.random.default_rng(21)
    base = str(tmp_path / "9")
    payload = rng.bytes(3 << 20)
    (tmp_path / "9.dat").write_bytes(payload)
    write_ec_files(base, backend="numpy", codec="28.4",
                   large_block=1 << 20, small_block=1 << 14,
                   chunk=1 << 18)
    assert codec_of(base) == (28, 4)
    assert all(os.path.exists(base + geo.shard_ext(i)) for i in range(32))
    assert not os.path.exists(base + geo.shard_ext(32))
    # drop 4 shards (max tolerable) and rebuild bit-exact
    golden = {i: open(base + geo.shard_ext(i), "rb").read()
              for i in (0, 13, 29, 31)}
    for i in golden:
        os.unlink(base + geo.shard_ext(i))
    assert sorted(rebuild_ec_files(base, backend="numpy",
                                   chunk=1 << 18)) == [0, 13, 29, 31]
    for i, want in golden.items():
        assert open(base + geo.shard_ext(i), "rb").read() == want
    assert verify_ec_files(base, backend="numpy", chunk=1 << 18)

    # data shards concatenate back to the original bytes
    k = 28
    n_large, n_small = geo.row_layout(len(payload), 1 << 20, 1 << 14, k)
    out = bytearray()
    for r in range(n_small):
        for i in range(k):
            shard = open(base + geo.shard_ext(i), "rb").read()
            out += shard[r << 14:(r + 1) << 14]
    assert bytes(out[:len(payload)]) == payload


def test_wide_code_parity_matches_reed_solomon(tmp_path):
    # the shard files ARE RS(28,4) codewords column-by-column
    rng = np.random.default_rng(22)
    base = str(tmp_path / "5")
    (tmp_path / "5.dat").write_bytes(rng.bytes(1 << 20))
    write_ec_files(base, backend="numpy", codec="28.4",
                   large_block=1 << 20, small_block=1 << 14)
    shards = np.stack([np.frombuffer(
        open(base + geo.shard_ext(i), "rb").read(), dtype=np.uint8)
        for i in range(32)])
    assert ReedSolomon(28, 4, backend="numpy").verify(shards)


# ---------------------------------------------------------------------
# cluster e2e: encode -> spread -> degraded read -> rebuild
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = Cluster(str(tmp_path_factory.mktemp("wide_ec")),
                n_volume_servers=3, volume_size_limit=4 << 20,
                max_volumes=60)
    yield c
    c.stop()


def test_wide_encode_spread_degraded_read(cluster):
    env = CommandEnv(cluster.master_url)
    env.acquire_lock()
    try:
        col = "wide" + secrets.token_hex(3)
        rng = np.random.default_rng(23)
        a = verbs.assign(cluster.master_url, collection=col)
        vid = int(a.fid.split(",")[0])
        payloads = {a.fid: rng.bytes(120_000)}
        verbs.upload(a, payloads[a.fid])
        for _ in range(10):
            b = verbs.assign(cluster.master_url, collection=col)
            if int(b.fid.split(",")[0]) != vid:
                continue
            payloads[b.fid] = rng.bytes(int(rng.integers(500, 60_000)))
            verbs.upload(b, payloads[b.fid])

        placement = run_command(
            env, f"ec.encode -volumeId={vid} -codec=28.4")
        assert len(placement) == 32
        # master learned the codec from the heartbeat
        assert env.ec_codec(vid) == (28, 4)

        # the codec record survives the source-volume delete ON DISK:
        # a restarted server must re-derive (28, 4), not the default
        # (round-2 review: Volume.destroy used to unlink the .vif)
        from seaweedfs_tpu.ec.volume import EcVolume

        srv_ecv = next(s.store.ec_volumes[vid]
                       for s in cluster.volume_servers
                       if vid in s.store.ec_volumes)
        fresh = EcVolume(srv_ecv.dir, srv_ecv.collection, vid)
        assert (fresh.k, fresh.m) == (28, 4)
        fresh.close()

        # reads through any holder (local + remote shard fetch)
        locs = env.ec_shard_locations(vid)
        holder = locs[0][0]
        for fid, data in payloads.items():
            r = requests.get(f"http://{holder}/{fid}", timeout=30)
            assert r.status_code == 200, (fid, r.text)
            assert r.content == data

        # lose 4 shards (max tolerable for m=4) -> degraded reads OK
        for sid in (2, 11, 28, 31):
            for url in locs.get(sid, []):
                env.vs_post(url, "/admin/ec/delete",
                            {"volume": vid, "shard_ids": [sid]})
        for fid, data in payloads.items():
            r = requests.get(f"http://{holder}/{fid}", timeout=60)
            assert r.status_code == 200, (fid, r.text)
            assert r.content == data

        # ec.rebuild restores the full 32-shard set
        out = commands_ec.ec_rebuild(env, vid)
        assert sorted(out["rebuilt"]) == [2, 11, 28, 31]
        assert commands_ec.ec_verify(env, vid)["verified"]
    finally:
        env.close()


def test_reencode_default_clears_stale_codec(tmp_path):
    # encode wide -> wipe shards (decode analog) -> re-encode default:
    # the stale .vif marker must be cleared (round-2 review finding)
    rng = np.random.default_rng(24)
    base = str(tmp_path / "4")
    (tmp_path / "4.dat").write_bytes(rng.bytes(1 << 20))
    write_ec_files(base, backend="numpy", codec="28.4",
                   large_block=1 << 20, small_block=1 << 14)
    assert codec_of(base) == (28, 4)
    for i in range(32):
        os.unlink(base + geo.shard_ext(i))
    write_ec_files(base, backend="numpy",
                   large_block=1 << 20, small_block=1 << 14)
    assert codec_of(base) == (10, 4)
    assert verify_ec_files(base, backend="numpy")
