"""Elasticsearch filer store over raw REST, against the in-process
mini-ES (tests/minielastic.py) — wire/REST store family #7. Reference
slot: /root/reference/weed/filer/elastic/v7/elastic_store.go:30.
"""
import time

import pytest

from seaweedfs_tpu.filer.elastic_store import INDEX_PREFIX, ElasticStore
from seaweedfs_tpu.filer.entry import Entry, FileChunk
from seaweedfs_tpu.filer.filer import Filer

from .minielastic import MiniElastic


@pytest.fixture(scope="module")
def es():
    s = MiniElastic()
    yield s
    s.close()


@pytest.fixture()
def store(es):
    with es.lock:
        es.indexes.clear()
    s = ElasticStore(port=es.port)
    yield s
    s.close()


def ent(path, size=0):
    chunks = [FileChunk(fid="1,ab", offset=0, size=size,
                        mtime_ns=time.time_ns())] if size else []
    return Entry(full_path=path, chunks=chunks)


def test_insert_find_update_delete(store, es):
    store.insert_entry(ent("/bkt/a/b.txt", 10))
    # documents of /bkt/** land in the bucket's index
    assert INDEX_PREFIX + "bkt" in es.indexes
    assert store.find_entry("/bkt/a/b.txt").file_size == 10
    store.update_entry(ent("/bkt/a/b.txt", 20))
    assert store.find_entry("/bkt/a/b.txt").file_size == 20
    store.delete_entry("/bkt/a/b.txt")
    assert store.find_entry("/bkt/a/b.txt") is None


def test_listing_order_pagination_prefix(store):
    for n in ("zeta", "alpha", "beta", "beta2", "gamma"):
        store.insert_entry(ent(f"/bkt/dir/{n}"))
    store.insert_entry(ent("/bkt/dir/beta/child"))  # other parent
    names = [e.name for e in
             store.list_directory_entries("/bkt/dir")]
    assert names == ["alpha", "beta", "beta2", "gamma", "zeta"]
    page = store.list_directory_entries("/bkt/dir", start_from="beta",
                                        inclusive=False, limit=2)
    assert [e.name for e in page] == ["beta2", "gamma"]
    pref = store.list_directory_entries("/bkt/dir", prefix="beta")
    assert [e.name for e in pref] == ["beta", "beta2"]


def test_bucket_delete_drops_index(store, es):
    store.insert_entry(ent("/vol1/x"))
    store.insert_entry(ent("/vol1/deep/y"))
    assert INDEX_PREFIX + "vol1" in es.indexes
    store.delete_entry("/vol1")  # bucket level: whole index goes
    assert INDEX_PREFIX + "vol1" not in es.indexes
    assert store.find_entry("/vol1/x") is None


def test_delete_folder_children_subtree(store):
    store.insert_entry(Entry(full_path="/b/t", mode=0o40755))
    store.insert_entry(Entry(full_path="/b/t/sub", mode=0o40755))
    for p in ("/b/t/a", "/b/t/sub/x", "/b/other"):
        store.insert_entry(ent(p))
    store.delete_folder_children("/b/t")
    for p in ("/b/t/a", "/b/t/sub", "/b/t/sub/x"):
        assert store.find_entry(p) is None, p
    assert store.find_entry("/b/other") is not None


def test_kv(store, es):
    store.kv_put("conf", b"\x00\x01binary")
    assert store.kv_get("conf") == b"\x00\x01binary"
    store.kv_delete("conf")
    assert store.kv_get("conf") is None
    assert ".seaweedfs_kv_entries" in es.indexes


def test_basic_auth():
    s = MiniElastic(username="weed", password="pw")
    try:
        store = ElasticStore(port=s.port, user="weed", password="pw")
        store.kv_put("k", b"v")
        assert store.kv_get("k") == b"v"
        store.close()
        import requests

        with pytest.raises(requests.HTTPError):
            bad = ElasticStore(port=s.port, user="weed",
                               password="wrong")
            bad.kv_put("k", b"v")
    finally:
        s.close()


def test_full_filer_stack(es):
    with es.lock:
        es.indexes.clear()
    f = Filer("elastic", port=es.port)
    try:
        f.create_entry(ent("/docs/readme.md", 5))
        assert f.find_entry("/docs/readme.md").file_size == 5
        assert [e.name for e in f.list_entries("/docs")] == ["readme.md"]
        f.delete_entry("/docs", recursive=True)
        assert f.find_entry("/docs/readme.md") is None
    finally:
        f.close()
