"""TracingSession retry-loop hygiene: an abandoned 502/503/504
response must be closed before the next attempt — stream=True call
sites (ftpd, backup, s3 client) otherwise leak one pooled urllib3
connection per retried attempt, exactly under the degraded conditions
retries fire."""
import io

import requests

from seaweedfs_tpu.rpc import httpclient
from seaweedfs_tpu.utils import retry


def _fake_response(status: int, headers: dict | None = None):
    r = requests.Response()
    r.status_code = status
    r.raw = io.BytesIO(b"")
    r.headers.update(headers or {})
    return r


def test_status_retry_closes_abandoned_response(monkeypatch):
    retry.reset_breakers()
    closed = []
    served = []

    def fake_request(self, method, url, **kw):
        r = _fake_response(503 if not served else 200)
        served.append(r)
        orig_close = r.close
        r.close = lambda: (closed.append(r), orig_close())[-1]
        return r

    monkeypatch.setattr(requests.Session, "request", fake_request)
    try:
        sess = httpclient.TracingSession()
        resp = sess.request("GET", "http://peer-leak:1234/x")
        assert resp.status_code == 200
        assert len(served) == 2
        assert closed == [served[0]], \
            "the abandoned 503 must be drained back to the pool"
        assert resp not in closed, "the returned response stays open"
    finally:
        retry.reset_breakers()


def test_exhausted_status_retries_return_last_response_open(monkeypatch):
    """When every attempt yields a retryable status, the final response
    is returned (not closed) so the caller can read the error body."""
    retry.reset_breakers()
    served = []

    def fake_request(self, method, url, **kw):
        r = _fake_response(503)
        served.append(r)
        return r

    monkeypatch.setattr(requests.Session, "request", fake_request)
    try:
        sess = httpclient.TracingSession()
        resp = sess.request("GET", "http://peer-exhaust:1234/x")
        assert resp.status_code == 503
        assert resp is served[-1]
        assert len(served) == retry.policy().max_attempts
    finally:
        retry.reset_breakers()
