"""Fast tier-1 lint: all sync HTTP in the package flows through
rpc/httpclient.py's session() — the one place that enforces timeouts,
deadline propagation, retries, and circuit breaking — and every
outbound call passes an explicit timeout.

A raw ``requests.get(...)`` bypasses the whole robustness layer; a
call without ``timeout=`` can hang a worker thread forever on one
dead peer (requests has no default timeout)."""
import os
import re

PKG_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "seaweedfs_tpu")

_VERBS = r"(?:get|post|put|delete|head|patch|options|request)"
# module-level requests verb calls — the bypass the lint exists to stop
_RAW_RE = re.compile(rf"\brequests\.{_VERBS}\s*\(")
# outbound calls through the pooled adapter
_SESSION_RE = re.compile(rf"\bsession\(\)\s*\.\s*{_VERBS}\s*\(")

_ALLOWED_RAW = {os.path.join("rpc", "httpclient.py")}


def _iter_sources():
    for root, _dirs, files in os.walk(PKG_DIR):
        for fn in files:
            if fn.endswith(".py"):
                path = os.path.join(root, fn)
                with open(path, encoding="utf-8") as f:
                    yield os.path.relpath(path, PKG_DIR), f.read()


def _call_span(src: str, open_paren: int) -> str:
    """The argument text of the call whose '(' is at ``open_paren``
    (balanced-paren scan; good enough for lint-grade extraction)."""
    depth = 0
    for i in range(open_paren, min(len(src), open_paren + 4000)):
        c = src[i]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                return src[open_paren:i + 1]
    return src[open_paren:open_paren + 4000]


def test_no_raw_requests_calls_outside_httpclient():
    offenders = []
    for rel, src in _iter_sources():
        if rel in _ALLOWED_RAW:
            continue
        for m in _RAW_RE.finditer(src):
            line = src.count("\n", 0, m.start()) + 1
            offenders.append(f"{rel}:{line}: {m.group(0)}...")
    assert not offenders, (
        "raw requests.<verb>() bypasses the retry/deadline/breaker "
        "layer; use rpc.httpclient.session() instead:\n"
        + "\n".join(offenders))


def test_every_session_call_has_explicit_timeout():
    offenders = []
    for rel, src in _iter_sources():
        for m in _SESSION_RE.finditer(src):
            span = _call_span(src, src.index("(", m.end() - 1))
            if "timeout" not in span:
                line = src.count("\n", 0, m.start()) + 1
                offenders.append(f"{rel}:{line}")
    assert not offenders, (
        "session() calls without an explicit timeout= (a hung peer "
        "would pin the worker forever):\n" + "\n".join(offenders))


def test_session_is_actually_used():
    # the lint is vacuous if nothing routes through the adapter
    n = sum(len(_SESSION_RE.findall(src)) for _rel, src in _iter_sources())
    assert n > 30, f"only {n} session() call sites found"
