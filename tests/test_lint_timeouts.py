"""Fast tier-1 lint: all sync HTTP in the package flows through
rpc/httpclient.py's session() — the one place that enforces timeouts,
deadline propagation, retries, and circuit breaking — and every
outbound call passes an explicit timeout.

The rule logic lives in seaweedfs_tpu/analysis/rules/http_discipline.py;
this module keeps the historical entrypoints as thin wrappers over the
shared engine pass, plus the negative control that proves the rules
still guard a non-empty surface."""
import pytest

from seaweedfs_tpu.analysis import run_cached

pytestmark = pytest.mark.lint


def test_no_raw_requests_calls_outside_httpclient():
    offenders = [f.render() for f in run_cached().by_rule("raw-requests")]
    assert not offenders, "\n".join(offenders)


def test_every_session_call_has_explicit_timeout():
    offenders = [f.render()
                 for f in run_cached().by_rule("session-timeout")]
    assert not offenders, "\n".join(offenders)


def test_session_is_actually_used():
    """Negative control: the pooled session is the package's actual
    HTTP surface — if its call sites vanished, the lints above would
    be guarding an empty set."""
    assert run_cached().stats["session_calls"] > 30
