"""Leveled logging (utils/glog.py — the weed/glog equivalent)."""
import io
import re

import pytest

from seaweedfs_tpu.utils import glog


@pytest.fixture(autouse=True)
def capture():
    buf = io.StringIO()
    glog.set_output(buf)
    glog.set_verbosity(0)
    glog.set_vmodule("")
    yield buf
    glog.set_output(__import__("sys").stderr)
    glog.set_verbosity(0)
    glog.set_vmodule("")


def test_line_format(capture):
    glog.info("hello %s", "world")
    line = capture.getvalue()
    # I0730 12:00:00.000000 <tid> test_glog.py:<line>] hello world
    assert re.match(
        r"I\d{4} \d\d:\d\d:\d\d\.\d{6} \d+ test_glog\.py:\d+\] "
        r"hello world\n", line), line


def test_severities(capture):
    glog.warning("w")
    glog.error("e")
    out = capture.getvalue()
    assert out.startswith("W") and "\nE" in out


def test_v_gated_by_verbosity(capture):
    glog.v(2, "hidden")
    assert capture.getvalue() == ""
    glog.set_verbosity(2)
    glog.v(2, "shown %d", 42)
    assert "shown 42" in capture.getvalue()


def test_vmodule_overrides_per_file(capture):
    glog.set_verbosity(0)
    glog.set_vmodule("test_glog=3,other=1")
    glog.v(3, "module-level visible")
    assert "module-level visible" in capture.getvalue()
    glog.set_vmodule("other=5")
    glog.v(1, "not ours")
    assert "not ours" not in capture.getvalue()


def test_fatal_exits(capture):
    with pytest.raises(SystemExit):
        glog.fatal("boom")
    assert capture.getvalue().startswith("F")
