"""S3 conformance sweep driven ONLY by the vendored independent SigV4
client (tests/s3v4client.py) — the stand-in for the reference's ceph
s3-tests run (docker/compose/local-s3tests-compose.yml), since no
external S3 SDK exists in this image. Round-2 VERDICT item 10.

Covers the s3-tests greatest hits: auth acceptance/rejection, object
CRUD + metadata + ranges, V1/V2 listing edge cases (prefix, delimiter,
marker, continuation), multipart incl. UploadPartCopy and abort,
presigned URLs, aws-chunked streaming uploads, copy, and error XML
shapes.
"""
import xml.etree.ElementTree as ET

import pytest

from seaweedfs_tpu.server.cluster import Cluster
from tests.s3v4client import S3V4Client

NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"
AK, SK = "CONFAK", "CONFSECRET"


def _native_available():
    from seaweedfs_tpu.native import dataplane as dpmod

    return dpmod.available()


@pytest.fixture(scope="module",
                params=["python",
                        pytest.param("native", marks=pytest.mark.skipif(
                            not _native_available(),
                            reason="native dataplane unavailable"))])
def cluster(request, tmp_path_factory):
    """The whole sweep runs twice: against the pure-python gateway and
    against the native C++ S3 front (fast paths + relay) — conformance
    must be indistinguishable between the two."""
    cfg = {"identities": [{"name": "conf", "credentials": [
        {"accessKey": AK, "secretKey": SK}], "actions": ["Admin"]}]}
    native = request.param == "native"
    c = Cluster(str(tmp_path_factory.mktemp("s3conf")),
                n_volume_servers=1 if native else 2,
                volume_size_limit=16 << 20,
                with_s3=True, s3_native=native, s3_config=cfg)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def s3(cluster) -> S3V4Client:
    c = S3V4Client(cluster.s3_url, AK, SK)
    assert c.put("/conf").status in (200, 409)
    return c


def _xml(body: bytes) -> ET.Element:
    return ET.fromstring(body)


# -- auth --------------------------------------------------------------

def test_wrong_secret_rejected(cluster):
    bad = S3V4Client(cluster.s3_url, AK, "WRONG")
    r = bad.get("/")
    assert r.status == 403
    assert b"SignatureDoesNotMatch" in r.body


def test_unsigned_rejected(cluster, s3):
    r = s3.request("GET", "/", sign=False)
    assert r.status == 403


def test_unknown_access_key(cluster):
    bad = S3V4Client(cluster.s3_url, "NOPE", SK)
    r = bad.get("/")
    assert r.status == 403
    assert b"InvalidAccessKeyId" in r.body


# -- objects -----------------------------------------------------------

def test_put_get_head_delete_roundtrip(s3):
    body = b"conformance payload \x00\x01\xff" * 100
    r = s3.put("/conf/obj1.bin", body,
               headers={"Content-Type": "application/x-conf",
                        "x-amz-meta-color": "teal",
                        # % and + stress the internal header armor —
                        # a double-encode or missed decode corrupts them
                        "x-amz-meta-promo": "50% off + tax"})
    assert r.status == 200
    assert r.header("etag")
    g = s3.get("/conf/obj1.bin")
    assert g.status == 200 and g.body == body
    assert g.header("content-type") == "application/x-conf"
    assert g.header("x-amz-meta-color") == "teal"
    assert g.header("x-amz-meta-promo") == "50% off + tax"
    h = s3.head("/conf/obj1.bin")
    assert h.status == 200
    assert int(h.header("content-length")) == len(body)
    assert s3.delete("/conf/obj1.bin").status == 204
    assert s3.get("/conf/obj1.bin").status == 404


def test_nosuchkey_error_xml(s3):
    r = s3.get("/conf/definitely-missing")
    assert r.status == 404
    root = _xml(r.body)
    assert root.tag == "Error"
    assert root.find("Code").text == "NoSuchKey"


def test_nosuchbucket(s3):
    r = s3.get("/nobucket-xyz/obj")
    assert r.status == 404
    assert b"NoSuchBucket" in r.body


def test_range_get(s3):
    body = bytes(range(256)) * 64
    s3.put("/conf/range.bin", body)
    r = s3.get("/conf/range.bin", headers={"Range": "bytes=100-299"})
    assert r.status == 206
    assert r.body == body[100:300]
    assert r.header("content-range") == \
        f"bytes 100-299/{len(body)}"
    # suffix range
    r = s3.get("/conf/range.bin", headers={"Range": "bytes=-50"})
    assert r.status == 206 and r.body == body[-50:]


def test_copy_object(s3):
    s3.put("/conf/src.txt", b"copy me")
    r = s3.put("/conf/dst.txt",
               headers={"x-amz-copy-source": "/conf/src.txt"})
    assert r.status == 200
    assert b"CopyObjectResult" in r.body
    assert s3.get("/conf/dst.txt").body == b"copy me"


def test_special_key_characters(s3):
    key = "/conf/dir with space/uni-é中.txt"
    assert s3.put(key, b"special").status == 200
    assert s3.get(key).body == b"special"
    assert s3.delete(key).status == 204


# -- listing -----------------------------------------------------------

@pytest.fixture(scope="module")
def listing_keys(s3):
    keys = ([f"list/a/{i:02d}.txt" for i in range(5)] +
            [f"list/b/{i:02d}.txt" for i in range(5)] +
            ["list/top.txt"])
    for k in keys:
        s3.put(f"/conf/{k}", b"x")
    return keys


def test_list_v1_prefix_delimiter(s3, listing_keys):
    r = s3.get("/conf", **{"prefix": "list/", "delimiter": "/"})
    root = _xml(r.body)
    prefixes = sorted(p.find(f"{NS}Prefix").text for p in
                      root.iter(f"{NS}CommonPrefixes"))
    assert prefixes == ["list/a/", "list/b/"]
    keys = [k.find(f"{NS}Key").text for k in root.iter(f"{NS}Contents")]
    assert keys == ["list/top.txt"]


def test_list_v1_marker_pagination(s3, listing_keys):
    seen = []
    marker = ""
    while True:
        params = {"prefix": "list/a/", "max-keys": "2"}
        if marker:
            params["marker"] = marker
        root = _xml(s3.get("/conf", **params).body)
        batch = [k.find(f"{NS}Key").text
                 for k in root.iter(f"{NS}Contents")]
        seen += batch
        if root.find(f"{NS}IsTruncated").text != "true":
            break
        marker = batch[-1]
    assert seen == [f"list/a/{i:02d}.txt" for i in range(5)]


def test_list_v2_continuation(s3, listing_keys):
    seen, token = [], ""
    while True:
        params = {"list-type": "2", "prefix": "list/b/",
                  "max-keys": "2"}
        if token:
            params["continuation-token"] = token
        root = _xml(s3.get("/conf", **params).body)
        seen += [k.find(f"{NS}Key").text
                 for k in root.iter(f"{NS}Contents")]
        if root.find(f"{NS}IsTruncated").text != "true":
            break
        token = root.find(f"{NS}NextContinuationToken").text
    assert seen == [f"list/b/{i:02d}.txt" for i in range(5)]


def test_list_v2_url_encoding(s3):
    s3.put("/conf/enc/a b+c.txt", b"x")
    root = _xml(s3.get("/conf", **{"list-type": "2",
                                   "prefix": "enc/",
                                   "encoding-type": "url"}).body)
    keys = [k.find(f"{NS}Key").text for k in root.iter(f"{NS}Contents")]
    assert keys == ["enc/a%20b%2Bc.txt"]


# -- multipart ---------------------------------------------------------

def test_multipart_upload_complete(s3):
    r = s3.post("/conf/mp.bin", **{"uploads": ""})
    assert r.status == 200
    upload_id = _xml(r.body).find(f"{NS}UploadId").text
    parts = []
    payloads = [b"A" * (5 << 20), b"B" * (5 << 20), b"C" * 1234]
    for i, data in enumerate(payloads, start=1):
        pr = s3.put("/conf/mp.bin", data,
                    **{"partNumber": str(i), "uploadId": upload_id})
        assert pr.status == 200
        parts.append((i, pr.header("etag")))
    # list parts
    lp = _xml(s3.get("/conf/mp.bin", **{"uploadId": upload_id}).body)
    nums = [int(p.find(f"{NS}PartNumber").text)
            for p in lp.iter(f"{NS}Part")]
    assert nums == [1, 2, 3]
    doc = "<CompleteMultipartUpload>" + "".join(
        f"<Part><PartNumber>{n}</PartNumber><ETag>{e}</ETag></Part>"
        for n, e in parts) + "</CompleteMultipartUpload>"
    cr = s3.post("/conf/mp.bin", doc.encode(),
                 **{"uploadId": upload_id})
    assert cr.status == 200
    etag = _xml(cr.body).find(f"{NS}ETag").text
    assert etag.strip('"').endswith("-3")  # multipart etag form
    g = s3.get("/conf/mp.bin")
    assert g.status == 200
    assert g.body == b"".join(payloads)


def test_multipart_abort(s3):
    r = s3.post("/conf/ab.bin", **{"uploads": ""})
    upload_id = _xml(r.body).find(f"{NS}UploadId").text
    s3.put("/conf/ab.bin", b"x" * 1024,
           **{"partNumber": "1", "uploadId": upload_id})
    assert s3.delete("/conf/ab.bin",
                     **{"uploadId": upload_id}).status == 204
    assert s3.get("/conf/ab.bin").status == 404


def test_upload_part_copy(s3):
    src = bytes(range(256)) * 40
    s3.put("/conf/upc-src.bin", src)
    r = s3.post("/conf/upc.bin", **{"uploads": ""})
    upload_id = _xml(r.body).find(f"{NS}UploadId").text
    pr = s3.put("/conf/upc.bin",
                headers={"x-amz-copy-source": "/conf/upc-src.bin",
                         "x-amz-copy-source-range": "bytes=0-5119"},
                **{"partNumber": "1", "uploadId": upload_id})
    assert pr.status == 200
    etag1 = _xml(pr.body).find(f"{NS}ETag").text
    pr2 = s3.put("/conf/upc.bin", b"tail-part",
                 **{"partNumber": "2", "uploadId": upload_id})
    doc = ("<CompleteMultipartUpload>"
           f"<Part><PartNumber>1</PartNumber><ETag>{etag1}</ETag></Part>"
           f"<Part><PartNumber>2</PartNumber>"
           f"<ETag>{pr2.header('etag')}</ETag></Part>"
           "</CompleteMultipartUpload>")
    assert s3.post("/conf/upc.bin", doc.encode(),
                   **{"uploadId": upload_id}).status == 200
    assert s3.get("/conf/upc.bin").body == src[:5120] + b"tail-part"


# -- presigned + chunked --------------------------------------------------

def test_presigned_get_and_put(cluster, s3):
    import urllib.request

    s3.put("/conf/pre.txt", b"presigned!")
    url = s3.presign("GET", "/conf/pre.txt")
    with urllib.request.urlopen(url, timeout=30) as resp:
        assert resp.read() == b"presigned!"

    put_url = s3.presign("PUT", "/conf/pre-up.txt")
    req = urllib.request.Request(put_url, data=b"uploaded via presign",
                                 method="PUT")
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert resp.status == 200
    assert s3.get("/conf/pre-up.txt").body == b"uploaded via presign"

    # expired-style tamper: breaking the signature must 403
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(url[:-4] + "beef", timeout=30)
    assert ei.value.code == 403


def test_chunked_streaming_upload(s3):
    chunks = [b"x" * 65536, b"y" * 65536, b"z" * 321]
    r = s3.put_chunked("/conf/chunked.bin", chunks)
    assert r.status == 200, r.body
    g = s3.get("/conf/chunked.bin")
    assert g.body == b"".join(chunks)


def test_chunked_tampered_chunk_rejected(cluster, s3):
    # flip a payload byte after signing: the per-chunk signature must
    # catch it
    import hashlib

    class Tampering(S3V4Client):
        def request(self, method, path, params=None, headers=None,
                    body=b"", sign=True):
            if body and b"chunk-signature=" in body:
                idx = body.find(b"\r\nx")
                if idx > 0:
                    body = body[:idx + 2] + b"T" + body[idx + 3:]
            return super().request(method, path, params, headers,
                                   body, sign)

    t = Tampering(cluster.s3_url, AK, SK)
    r = t.put_chunked("/conf/tampered.bin", [b"x" * 4096])
    assert r.status in (400, 403)


# -- copy-object + metadata directive (round-3 sweep growth) -----------

def test_copy_preserves_metadata_by_default(s3):
    s3.put("/conf/md-src.txt", b"meta-src",
           headers={"x-amz-meta-color": "teal",
                    "x-amz-meta-rank": "7"})
    r = s3.put("/conf/md-dst.txt",
               headers={"x-amz-copy-source": "/conf/md-src.txt"})
    assert r.status == 200
    h = s3.head("/conf/md-dst.txt")
    assert h.header("x-amz-meta-color") == "teal"
    assert h.header("x-amz-meta-rank") == "7"
    assert s3.get("/conf/md-dst.txt").body == b"meta-src"


def test_copy_replace_directive(s3):
    s3.put("/conf/md2-src.txt", b"v", headers={"x-amz-meta-old": "yes"})
    r = s3.put("/conf/md2-dst.txt",
               headers={"x-amz-copy-source": "/conf/md2-src.txt",
                        "x-amz-metadata-directive": "REPLACE",
                        "x-amz-meta-new": "fresh"})
    assert r.status == 200
    h = s3.head("/conf/md2-dst.txt")
    assert h.header("x-amz-meta-new") == "fresh"
    assert h.header("x-amz-meta-old") == ""


def test_copy_to_self_requires_replace(s3):
    s3.put("/conf/self.txt", b"self", headers={"x-amz-meta-a": "1"})
    r = s3.put("/conf/self.txt",
               headers={"x-amz-copy-source": "/conf/self.txt"})
    assert r.status == 400
    assert b"InvalidRequest" in r.body
    r = s3.put("/conf/self.txt",
               headers={"x-amz-copy-source": "/conf/self.txt",
                        "x-amz-metadata-directive": "REPLACE",
                        "x-amz-meta-a": "2"})
    assert r.status == 200
    assert s3.head("/conf/self.txt").header("x-amz-meta-a") == "2"


def test_copy_missing_source(s3):
    r = s3.put("/conf/never.txt",
               headers={"x-amz-copy-source": "/conf/ghost-src.txt"})
    assert r.status == 404
    assert b"NoSuchKey" in r.body


# -- multipart edge cases ----------------------------------------------

def _start_upload(s3, key):
    r = s3.post(key, **{"uploads": ""})
    assert r.status == 200
    return _xml(r.body).find(f"{NS}UploadId").text


def test_list_multipart_uploads_lifecycle(s3):
    uid = _start_upload(s3, "/conf/lmu.bin")
    lst = s3.get("/conf", **{"uploads": ""})
    assert lst.status == 200
    ids = [u.text for u in _xml(lst.body).iter(f"{NS}UploadId")]
    assert uid in ids
    assert s3.delete("/conf/lmu.bin", **{"uploadId": uid}).status == 204
    lst = s3.get("/conf", **{"uploads": ""})
    assert uid not in [u.text
                       for u in _xml(lst.body).iter(f"{NS}UploadId")]


def test_complete_with_missing_part_number(s3):
    uid = _start_upload(s3, "/conf/badmp.bin")
    s3.put("/conf/badmp.bin", b"data",
           **{"partNumber": "1", "uploadId": uid})
    doc = ("<CompleteMultipartUpload>"
           "<Part><PartNumber>1</PartNumber><ETag>x</ETag></Part>"
           "<Part><PartNumber>9</PartNumber><ETag>y</ETag></Part>"
           "</CompleteMultipartUpload>")
    r = s3.post("/conf/badmp.bin", doc.encode(), **{"uploadId": uid})
    assert r.status == 400
    assert b"InvalidPart" in r.body
    s3.delete("/conf/badmp.bin", **{"uploadId": uid})


def test_operations_on_aborted_upload(s3):
    uid = _start_upload(s3, "/conf/gone.bin")
    assert s3.delete("/conf/gone.bin", **{"uploadId": uid}).status == 204
    # part upload, list-parts, and complete must all answer NoSuchUpload
    pr = s3.put("/conf/gone.bin", b"x",
                **{"partNumber": "1", "uploadId": uid})
    assert pr.status == 404 and b"NoSuchUpload" in pr.body
    lp = s3.get("/conf/gone.bin", **{"uploadId": uid})
    assert lp.status == 404
    doc = b"<CompleteMultipartUpload></CompleteMultipartUpload>"
    cr = s3.post("/conf/gone.bin", doc, **{"uploadId": uid})
    assert cr.status == 404


def test_list_parts_reports_sizes_and_etags(s3):
    import hashlib as _hl

    uid = _start_upload(s3, "/conf/lp.bin")
    p1, p2 = b"a" * 1000, b"b" * 2000
    s3.put("/conf/lp.bin", p1, **{"partNumber": "1", "uploadId": uid})
    s3.put("/conf/lp.bin", p2, **{"partNumber": "2", "uploadId": uid})
    lp = _xml(s3.get("/conf/lp.bin", **{"uploadId": uid}).body)
    parts = {int(p.find(f"{NS}PartNumber").text):
             (int(p.find(f"{NS}Size").text),
              p.find(f"{NS}ETag").text.strip('"'))
             for p in lp.iter(f"{NS}Part")}
    assert parts[1] == (1000, _hl.md5(p1).hexdigest())
    assert parts[2] == (2000, _hl.md5(p2).hexdigest())
    s3.delete("/conf/lp.bin", **{"uploadId": uid})


# -- batch delete -------------------------------------------------------

def test_multi_object_delete(s3):
    for i in range(3):
        s3.put(f"/conf/del{i}.txt", b"x")
    doc = ("<Delete>" +
           "".join(f"<Object><Key>del{i}.txt</Key></Object>"
                   for i in range(3)) +
           "<Object><Key>not-there.txt</Key></Object></Delete>")
    r = s3.post("/conf", doc.encode(), **{"delete": ""})
    assert r.status == 200
    deleted = [k.text for k in _xml(r.body).iter(f"{NS}Key")]
    assert set(deleted) >= {"del0.txt", "del1.txt", "del2.txt"}
    for i in range(3):
        assert s3.get(f"/conf/del{i}.txt").status == 404


# -- presigned POST (browser form upload) -------------------------------

def _post_form(url: str, fields: dict, file_bytes: bytes):
    import urllib.error
    import urllib.request
    import uuid

    boundary = uuid.uuid4().hex
    body = b""
    for k, v in fields.items():
        body += (f"--{boundary}\r\nContent-Disposition: form-data; "
                 f'name="{k}"\r\n\r\n{v}\r\n').encode()
    body += (f"--{boundary}\r\nContent-Disposition: form-data; "
             f'name="file"; filename="up.bin"\r\n'
             "Content-Type: application/octet-stream\r\n\r\n").encode()
    body += file_bytes + f"\r\n--{boundary}--\r\n".encode()
    req = urllib.request.Request(
        url, data=body, method="POST",
        headers={"Content-Type":
                 f"multipart/form-data; boundary={boundary}"})
    try:
        r = urllib.request.urlopen(req, timeout=10)
        return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _signed_policy_fields(s3, key: str, expires_s: int = 300):
    import base64
    import datetime
    import hashlib as _hl
    import hmac as _hm
    import json as _json

    now = datetime.datetime.now(datetime.timezone.utc)
    date = now.strftime("%Y%m%d")
    cred = f"{s3.access_key}/{s3._scope(date)}"
    policy = base64.b64encode(_json.dumps({
        "expiration": (now + datetime.timedelta(seconds=expires_s))
        .strftime("%Y-%m-%dT%H:%M:%SZ"),
        "conditions": [{"bucket": "conf"}, ["eq", "$key", key],
                       ["content-length-range", 0, 10 << 20]],
    }).encode()).decode()
    sig = _hm.new(s3._signing_key(date), policy.encode(),
                  _hl.sha256).hexdigest()
    return {"key": key, "policy": policy, "x-amz-credential": cred,
            "x-amz-signature": sig}


def test_presigned_post_policy_upload(cluster, s3):
    fields = _signed_policy_fields(s3, "posted/form.bin")
    fields["success_action_status"] = "201"
    code, body = _post_form(f"{cluster.s3_url}/conf", fields,
                            b"form-bytes")
    assert code == 201, body
    assert b"<Key>posted/form.bin</Key>" in body
    assert s3.get("/conf/posted/form.bin").body == b"form-bytes"


def test_presigned_post_bad_signature_rejected(cluster, s3):
    fields = _signed_policy_fields(s3, "posted/evil.bin")
    fields["x-amz-signature"] = "0" * 64
    code, body = _post_form(f"{cluster.s3_url}/conf", fields, b"nope")
    assert code == 403
    assert s3.get("/conf/posted/evil.bin").status == 404


def test_presigned_post_key_condition_enforced(cluster, s3):
    fields = _signed_policy_fields(s3, "posted/allowed.bin")
    fields["key"] = "posted/other.bin"  # violates the eq condition
    code, _ = _post_form(f"{cluster.s3_url}/conf", fields, b"x")
    assert code == 403


def test_presigned_url_expiry(cluster, s3):
    import time as _time
    import urllib.error
    import urllib.request

    s3.put("/conf/exp.txt", b"short-lived")
    url = s3.presign("GET", "/conf/exp.txt", expires=1)
    assert urllib.request.urlopen(url, timeout=10).read() == \
        b"short-lived"
    _time.sleep(2)
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(url, timeout=10)
    assert ei.value.code == 403


# -- metadata, overwrite, listing, bucket edges ------------------------

def test_user_metadata_roundtrip(s3):
    s3.put("/conf/meta.txt", b"m",
           headers={"x-amz-meta-owner": "conformance",
                    "Content-Type": "text/x-custom"})
    h = s3.head("/conf/meta.txt")
    assert h.header("x-amz-meta-owner") == "conformance"
    g = s3.get("/conf/meta.txt")
    assert g.header("x-amz-meta-owner") == "conformance"


def test_overwrite_replaces_content_and_etag(s3):
    import hashlib as _hl

    s3.put("/conf/ow.txt", b"first")
    e1 = s3.head("/conf/ow.txt").header("etag")
    s3.put("/conf/ow.txt", b"second!")
    h = s3.head("/conf/ow.txt")
    assert h.header("etag") != e1
    assert h.header("etag").strip('"') == _hl.md5(b"second!").hexdigest()
    assert s3.get("/conf/ow.txt").body == b"second!"


def test_list_v2_start_after(s3, listing_keys):
    r = _xml(s3.get("/conf", **{"list-type": "2",
                                "start-after": "list/b/01.txt",
                                "prefix": "list/"}).body)
    keys = [k.text for k in r.iter(f"{NS}Key")]
    assert keys and all(k > "list/b/01.txt" for k in keys)
    assert "list/b/02.txt" in keys and "list/top.txt" in keys


def test_nested_common_prefixes(s3, listing_keys):
    r = _xml(s3.get("/conf", **{"prefix": "list/b/",
                                "delimiter": "/"}).body)
    keys = [k.text for k in r.iter(f"{NS}Key")]
    assert keys
    assert all(k.startswith("list/b/") and "/" not in
               k[len("list/b/"):] for k in keys)


def test_delete_nonempty_bucket_rejected(s3):
    s3.put("/convict", b"")
    s3.put("/convict/keeper.txt", b"x")
    r = s3.delete("/convict")
    assert r.status == 409
    assert b"BucketNotEmpty" in r.body
    s3.delete("/convict/keeper.txt")
    assert s3.delete("/convict").status == 204
    assert s3.get("/convict").status == 404


def test_copy_replace_changes_content_type(s3):
    s3.put("/conf/ct.bin", b"<h1>hi</h1>",
           headers={"Content-Type": "application/octet-stream"})
    r = s3.put("/conf/ct.bin",
               headers={"x-amz-copy-source": "/conf/ct.bin",
                        "x-amz-metadata-directive": "REPLACE",
                        "Content-Type": "text/html"})
    assert r.status == 200
    g = s3.get("/conf/ct.bin")
    assert g.header("content-type").startswith("text/html")
    assert g.body == b"<h1>hi</h1>"


# -- legacy Signature V2 (auth_signature_v2.go) -------------------------

def _v2_headers(method, resource, headers=None):
    """Independent V2 signer: AWS <ak>:<b64 hmac-sha1(string-to-sign)>."""
    import base64 as _b64
    import hashlib as _hl
    import hmac as _hm
    from email.utils import formatdate

    h = dict(headers or {})
    h.setdefault("Date", formatdate(usegmt=True))
    low = {k.lower(): v for k, v in h.items()}
    amz = "".join(f"{k}:{low[k].strip()}\n" for k in sorted(low)
                  if k.startswith("x-amz-"))
    sts = (f"{method}\n{low.get('content-md5', '')}\n"
           f"{low.get('content-type', '')}\n{h['Date']}\n"
           f"{amz}{resource}")
    sig = _b64.b64encode(_hm.new(SK.encode(), sts.encode(),
                                 _hl.sha1).digest()).decode()
    h["Authorization"] = f"AWS {AK}:{sig}"
    return h


def _raw(cluster, method, resource, headers, body=b""):
    import urllib.error
    import urllib.request

    req = urllib.request.Request(f"{cluster.s3_url}{resource}",
                                 data=body or None, method=method,
                                 headers=headers)
    try:
        r = urllib.request.urlopen(req, timeout=10)
        return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def test_v2_header_roundtrip(cluster, s3):
    # x-amz-* headers (incl. x-amz-date) ride the canonicalized amz
    # block of the string-to-sign (canonicalizedAmzHeadersV2)
    code, _ = _raw(cluster, "PUT", "/conf/v2.txt",
                   _v2_headers("PUT", "/conf/v2.txt",
                               {"Content-Type": "text/plain",
                                "x-amz-meta-via": "v2",
                                "x-amz-date":
                                "Thu, 30 Jul 2026 12:00:00 GMT"}),
                   b"signed-with-v2")
    assert code == 200
    code, body = _raw(cluster, "GET", "/conf/v2.txt",
                      _v2_headers("GET", "/conf/v2.txt"))
    assert (code, body) == (200, b"signed-with-v2")
    code, _ = _raw(cluster, "DELETE", "/conf/v2.txt",
                   _v2_headers("DELETE", "/conf/v2.txt"))
    assert code == 204


def test_v2_wrong_secret_rejected(cluster):
    import base64 as _b64

    headers = _v2_headers("GET", "/conf/anything")
    # corrupt the signature
    ak, sig = headers["Authorization"][4:].split(":")
    headers["Authorization"] = \
        f"AWS {ak}:{_b64.b64encode(b'wrong-sig-bytes').decode()}"
    code, body = _raw(cluster, "GET", "/conf/anything", headers)
    assert code == 403 and b"SignatureDoesNotMatch" in body


def test_v2_presigned_get(cluster, s3):
    import base64 as _b64
    import hashlib as _hl
    import hmac as _hm
    import time as _time
    import urllib.parse

    s3.put("/conf/v2p.txt", b"presigned-v2")
    expires = str(int(_time.time()) + 60)
    sts = f"GET\n\n\n{expires}\n/conf/v2p.txt"
    sig = _b64.b64encode(_hm.new(SK.encode(), sts.encode(),
                                 _hl.sha1).digest()).decode()
    q = urllib.parse.urlencode({"AWSAccessKeyId": AK,
                                "Expires": expires, "Signature": sig})
    code, body = _raw(cluster, "GET", f"/conf/v2p.txt?{q}", {})
    assert (code, body) == (200, b"presigned-v2")
    # expired
    old = str(int(_time.time()) - 10)
    sts = f"GET\n\n\n{old}\n/conf/v2p.txt"
    sig = _b64.b64encode(_hm.new(SK.encode(), sts.encode(),
                                 _hl.sha1).digest()).decode()
    q = urllib.parse.urlencode({"AWSAccessKeyId": AK,
                                "Expires": old, "Signature": sig})
    code, _ = _raw(cluster, "GET", f"/conf/v2p.txt?{q}", {})
    assert code == 403
