"""Crash-recovery tests: torn writes, index-ahead-of-data, stale index
after a torn compact commit, and scan-based index rebuild (`weed fix`)."""
import os
import struct

import pytest

from seaweedfs_tpu.storage import needle as ndl
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.volume import Volume


def _fill(v, n=10, size=100):
    for i in range(n):
        v.append_needle(ndl.Needle(id=i + 1, cookie=7,
                                   data=bytes([i % 251]) * size))


class TestRecovery:
    def test_index_ahead_of_data(self, tmp_path):
        """Simulate: idx entry flushed, .dat record lost in the crash."""
        v = Volume(str(tmp_path), "", 1, create=True)
        _fill(v)
        v.close()
        # append a bogus idx entry pointing past EOF
        dat_size = os.path.getsize(tmp_path / "1.dat")
        with open(tmp_path / "1.idx", "ab") as f:
            f.write(t.NeedleValue(
                999, t.actual_to_offset(dat_size), 100).to_bytes())
        v2 = Volume(str(tmp_path), "", 1)
        with pytest.raises(KeyError):
            v2.read_needle(999)
        assert v2.read_needle(5).data == bytes([4]) * 100
        v2.close()

    def test_torn_dat_tail(self, tmp_path):
        v = Volume(str(tmp_path), "", 2, create=True)
        _fill(v)
        v.close()
        with open(tmp_path / "2.dat", "ab") as f:
            f.write(b"TORN!")
        v2 = Volume(str(tmp_path), "", 2)
        assert os.path.getsize(tmp_path / "2.dat") % 8 == 0
        assert v2.read_needle(10).data == bytes([9]) * 100
        v2.close()

    def test_stale_index_rebuilt_from_dat(self, tmp_path):
        """Torn compact commit: new .dat + old .idx. The last-entry spot
        check fails and the index is rebuilt by scanning."""
        v = Volume(str(tmp_path), "", 3, create=True)
        _fill(v, n=20, size=500)
        for i in range(10):
            v.delete_needle(i + 1)
        old_idx = open(tmp_path / "3.idx", "rb").read()
        v.compact()
        v.close()
        # restore the PRE-compact index: offsets now point at wrong records
        with open(tmp_path / "3.idx", "wb") as f:
            f.write(old_idx)
        v2 = Volume(str(tmp_path), "", 3)
        # live set must match post-compact reality
        assert v2.nm.file_count == 10
        for i in range(10, 20):
            assert v2.read_needle(i + 1).data == bytes([i % 251]) * 500
        for i in range(10):
            with pytest.raises(KeyError):
                v2.read_needle(i + 1)
        v2.close()

    def test_rebuild_index_directly(self, tmp_path):
        """`weed fix` equivalent: delete .idx entirely, rebuild by scan."""
        v = Volume(str(tmp_path), "", 4, create=True)
        _fill(v, n=15)
        v.delete_needle(3)
        v.close()
        os.remove(tmp_path / "4.idx")
        v2 = Volume(str(tmp_path), "", 4)
        # missing idx is detected on load and rebuilt by scanning .dat
        assert v2.nm.file_count == 14
        assert v2.read_needle(15).data == bytes([14 % 251]) * 100
        with pytest.raises(KeyError):
            v2.read_needle(3)
        v2.close()


class TestNeedleValidation:
    def test_long_mime_clear_error(self):
        n = ndl.Needle(id=1, data=b"x", mime=b"a" * 300)
        with pytest.raises(ValueError, match="mime too long"):
            n.to_bytes()

    def test_long_pairs_clear_error(self):
        n = ndl.Needle(id=1, data=b"x", pairs=b"p" * 70000)
        with pytest.raises(ValueError, match="pairs too long"):
            n.to_bytes()

    def test_long_name_truncated(self):
        n = ndl.Needle(id=1, data=b"x", name=b"n" * 300)
        m = ndl.Needle.from_bytes(n.to_bytes())
        assert len(m.name) == 255


class TestCompactDuringWrites:
    """CommitCompact makeupDiff (volume_vacuum.go:200): writes and
    deletes landing DURING compaction must survive the swap."""

    def test_concurrent_appends_survive_compact(self, tmp_path):
        import threading
        import time as _t

        from seaweedfs_tpu.storage import needle as ndl
        from seaweedfs_tpu.storage.volume import Volume

        v = Volume(str(tmp_path), "", 21, create=True)
        for i in range(200):
            v.append_needle(ndl.Needle(id=i + 1, cookie=1,
                                       data=b"a" * 500))
        for i in range(100):
            v.delete_needle(i + 1)

        stop = threading.Event()
        written = []
        errors = []

        def writer():
            nid = 10_000
            while not stop.is_set():
                nid += 1
                try:
                    v.append_needle(ndl.Needle(id=nid, cookie=7,
                                               data=b"mid" * 30))
                    written.append(nid)
                except Exception as e:  # pragma: no cover
                    errors.append(e)
                    return
                _t.sleep(0)

        th = threading.Thread(target=writer)
        th.start()
        _t.sleep(0.01)
        v.compact()
        stop.set()
        th.join(timeout=10)
        assert not errors, errors
        assert written, "writer thread never ran"
        # every acknowledged write — before and during compact — reads back
        for nid in written:
            assert v.read_needle(nid, cookie=7).data == b"mid" * 30
        for i in range(100, 200):
            assert v.read_needle(i + 1).data == b"a" * 500
        for i in range(100):
            with pytest.raises(KeyError):
                v.read_needle(i + 1)
        # reload from disk: the swapped files carry the makeup records
        v.close()
        v2 = Volume(str(tmp_path), "", 21)
        for nid in written:
            assert v2.read_needle(nid, cookie=7).data == b"mid" * 30
        v2.close()

    def test_concurrent_delete_survives_compact(self, tmp_path):
        """A tombstone landing during compaction must not be resurrected
        by the swap."""
        import threading

        from seaweedfs_tpu.storage import needle as ndl
        from seaweedfs_tpu.storage.volume import Volume

        v = Volume(str(tmp_path), "", 22, create=True)
        for i in range(50):
            v.append_needle(ndl.Needle(id=i + 1, cookie=1,
                                       data=b"z" * 100))
        # grab the snapshot, then delete before the commit phase by
        # deleting from a hook inside the copy loop via a short thread
        deleted = {"done": False}

        orig_commit = v._commit_compact

        def delayed_commit(cpd, cpx, snap):
            v.delete_needle(25)
            deleted["done"] = True
            return orig_commit(cpd, cpx, snap)

        v._commit_compact = delayed_commit
        v.compact()
        assert deleted["done"]
        with pytest.raises(KeyError):
            v.read_needle(25)
        v.close()
        v2 = Volume(str(tmp_path), "", 22)
        with pytest.raises(KeyError):
            v2.read_needle(25)
        v2.close()
