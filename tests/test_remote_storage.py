"""Remote-storage tiering tests: mount a cloud path onto a filer dir,
sync metadata, read through, cache/uncache, and push writes back.

In-process analogue of the reference's remote-mount flow
(weed/shell/command_remote_*.go + weed/command/filer_remote_sync.go),
using the local-directory client for determinism plus one S3 round-trip
against the framework's own gateway.
"""
import json
import os
import time

import pytest
import requests

from seaweedfs_tpu.remote_storage import (LocalRemoteClient,
                                          S3RemoteClient, make_client)
from seaweedfs_tpu.remote_storage.sync import RemoteSyncWorker
from seaweedfs_tpu.server.cluster import Cluster
from seaweedfs_tpu.shell.env import CommandEnv
from seaweedfs_tpu.shell.repl import run_command


class TestClients:
    def test_local_roundtrip(self, tmp_path):
        c = LocalRemoteClient(root=str(tmp_path / "r"))
        c.write_file("a/b.txt", b"hello")
        assert c.read_file("a/b.txt") == b"hello"
        assert c.read_file("a/b.txt", offset=1, size=3) == b"ell"
        keys = [e.key for e in c.traverse()]
        assert keys == ["a/b.txt"]
        assert c.head("a/b.txt").size == 5
        assert c.head("missing") is None
        c.delete_file("a/b.txt")
        assert c.head("a/b.txt") is None

    def test_local_escape_forbidden(self, tmp_path):
        c = LocalRemoteClient(root=str(tmp_path / "r"))
        with pytest.raises(PermissionError):
            c.read_file("../../etc/passwd")

    def test_make_client_errors(self):
        with pytest.raises(KeyError, match="unknown"):
            make_client({"type": "nope"})
        with pytest.raises(KeyError, match="cloud SDK"):
            make_client({"type": "hdfs"})
        # gcs/azure are real in-tree REST clients now: they fail on
        # missing required config, not on a missing SDK
        with pytest.raises(ValueError, match="bucket"):
            make_client({"type": "gcs"})
        with pytest.raises(ValueError, match="account"):
            make_client({"type": "azure"})


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = Cluster(str(tmp_path_factory.mktemp("remote_cluster")),
                n_volume_servers=1, volume_size_limit=8 << 20,
                with_s3=True)
    yield c
    c.stop()


@pytest.fixture()
def env(cluster):
    e = CommandEnv(cluster.master_url, filer_url=cluster.filer_url)
    e.acquire_lock()
    yield e
    e.close()


@pytest.fixture(scope="module")
def remote_dir(tmp_path_factory):
    root = tmp_path_factory.mktemp("cloud")
    (root / "photos").mkdir()
    (root / "photos" / "a.jpg").write_bytes(b"JPEG" * 100)
    (root / "photos" / "b.jpg").write_bytes(b"PNG" * 200)
    (root / "readme.txt").write_bytes(b"top-level")
    return str(root)


class TestMountFlow:
    def test_configure_mount_sync_read_cache(self, cluster, env,
                                             remote_dir):
        out = run_command(
            env, f"remote.configure -name=cloud1 -type=local "
                 f"-root={remote_dir}")
        assert out == {"cloud1": "local"}
        out = run_command(env,
                          "remote.mount -dir=/clouddata -remote=cloud1")
        assert out["mounted"] == "/clouddata"
        assert out["created"] == 3

        # placeholders: metadata only, no chunks
        meta = requests.get(f"{cluster.filer_url}/clouddata/photos/a.jpg",
                            params={"meta": "1"}).json()
        assert "chunks" not in meta or not meta["chunks"]
        assert json.loads(meta["extended"]["remote"])["size"] == 400

        # read-through GET serves the cloud bytes
        r = requests.get(f"{cluster.filer_url}/clouddata/photos/a.jpg")
        assert r.status_code == 200 and r.content == b"JPEG" * 100
        # ranged read-through
        r = requests.get(f"{cluster.filer_url}/clouddata/readme.txt",
                         headers={"Range": "bytes=4-8"})
        assert r.status_code == 206 and r.content == b"level"

        # cache: bytes become cluster chunks
        out = run_command(env, "remote.cache -dir=/clouddata")
        assert out["cached"] == 3
        meta = requests.get(f"{cluster.filer_url}/clouddata/photos/a.jpg",
                            params={"meta": "1"}).json()
        assert meta["chunks"]
        r = requests.get(f"{cluster.filer_url}/clouddata/photos/a.jpg")
        assert r.content == b"JPEG" * 100

        # uncache: chunks dropped, read-through again
        out = run_command(env, "remote.uncache -dir=/clouddata")
        assert out["uncached"] == 3
        meta = requests.get(f"{cluster.filer_url}/clouddata/photos/b.jpg",
                            params={"meta": "1"}).json()
        assert not meta.get("chunks")
        r = requests.get(f"{cluster.filer_url}/clouddata/photos/b.jpg")
        assert r.content == b"PNG" * 200

    def test_meta_sync_detects_changes(self, cluster, env, remote_dir):
        # new + changed + deleted upstream
        with open(os.path.join(remote_dir, "new.bin"), "wb") as f:
            f.write(b"fresh")
        with open(os.path.join(remote_dir, "readme.txt"), "wb") as f:
            f.write(b"rewritten!")
        os.remove(os.path.join(remote_dir, "photos", "b.jpg"))
        out = run_command(env, "remote.meta.sync -dir=/clouddata")
        assert out["created"] == 1
        assert out["updated"] >= 1
        assert out["removed"] == 1
        r = requests.get(f"{cluster.filer_url}/clouddata/readme.txt")
        assert r.content == b"rewritten!"
        assert requests.get(
            f"{cluster.filer_url}/clouddata/photos/b.jpg").status_code \
            == 404

    def test_unmount(self, cluster, env, remote_dir):
        out = run_command(env, "remote.unmount -dir=/clouddata")
        assert out == {"unmounted": "/clouddata"}
        assert run_command(env, "remote.mount") == {}


class TestRemoteSyncBack:
    def test_local_writes_pushed(self, cluster, env, tmp_path):
        root = tmp_path / "push-cloud"
        root.mkdir()
        run_command(env, f"remote.configure -name=pc -type=local "
                         f"-root={root}")
        run_command(env, "remote.mount -dir=/pushed -remote=pc")
        w = RemoteSyncWorker(cluster.filer_url, "/pushed")
        w.start()
        try:
            requests.put(f"{cluster.filer_url}/pushed/doc.txt",
                         data=b"written locally").raise_for_status()
            deadline = time.monotonic() + 10
            target = root / "doc.txt"
            while time.monotonic() < deadline and not target.exists():
                time.sleep(0.05)
            assert target.read_bytes() == b"written locally"

            requests.delete(
                f"{cluster.filer_url}/pushed/doc.txt").raise_for_status()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and target.exists():
                time.sleep(0.05)
            assert not target.exists()
        finally:
            w.stop()
            run_command(env, "remote.unmount -dir=/pushed")


class TestEdgeCases:
    def test_empty_remote_file_read_through(self, cluster, env,
                                            tmp_path):
        root = tmp_path / "empty-cloud"
        root.mkdir()
        (root / "zero.bin").write_bytes(b"")
        run_command(env, f"remote.configure -name=ec -type=local "
                         f"-root={root}")
        run_command(env, "remote.mount -dir=/emptymnt -remote=ec")
        r = requests.get(f"{cluster.filer_url}/emptymnt/zero.bin")
        assert r.status_code == 200 and r.content == b""
        run_command(env, "remote.unmount -dir=/emptymnt")

    def test_rename_of_uncached_placeholder_keeps_bytes(self, cluster,
                                                        env, tmp_path):
        """Renaming an uncached placeholder must copy the remote object
        to the new key before removing the old one."""
        root = tmp_path / "ren-cloud"
        root.mkdir()
        (root / "orig.txt").write_bytes(b"remote-only bytes")
        run_command(env, f"remote.configure -name=rn -type=local "
                         f"-root={root}")
        run_command(env, "remote.mount -dir=/renmnt -remote=rn")
        w = RemoteSyncWorker(cluster.filer_url, "/renmnt")
        w.start()
        try:
            requests.put(f"{cluster.filer_url}/renmnt/moved.txt",
                         params={"mv.from": "/renmnt/orig.txt"},
                         ).raise_for_status()
            deadline = time.monotonic() + 10
            target = root / "moved.txt"
            while time.monotonic() < deadline and not target.exists():
                time.sleep(0.05)
            assert target.read_bytes() == b"remote-only bytes"
            assert not (root / "orig.txt").exists()
        finally:
            w.stop()
            run_command(env, "remote.unmount -dir=/renmnt")


class TestS3RemoteClient:
    def test_s3_roundtrip_against_gateway(self, cluster):
        requests.put(f"{cluster.s3_url}/rsc").raise_for_status()
        c = S3RemoteClient(endpoint=cluster.s3_url, bucket="rsc")
        c.write_file("x/one.bin", b"payload-1")
        c.write_file("x/two.bin", b"payload-22")
        assert c.read_file("x/one.bin") == b"payload-1"
        assert c.read_file("x/two.bin", offset=8, size=2) == b"22"
        keys = sorted(e.key for e in c.traverse(prefix="x/"))
        assert keys == ["x/one.bin", "x/two.bin"]
        sizes = {e.key: e.size for e in c.traverse()}
        assert sizes["x/two.bin"] == 10
        assert c.head("x/one.bin").size == 9
        c.delete_file("x/one.bin")
        assert c.head("x/one.bin") is None
