"""Fast tier-1 lint: every label dict passed to the metrics registry
uses only allowlisted, bounded-cardinality label keys.

Prometheus memory and the federated /cluster/metrics corpus scale with
the number of distinct label values; a per-request key (path, volume
id, trace id...) turns one family into millions of series. The sibling
lint (test_lint_metrics_names.py) guards family *names*; this one
guards label *keys* via the AST: label dicts must be literal — either
inline or a simple ``lab = {...}`` assignment in the same module — so
their keys are statically checkable, and every key must come from the
allowlist below. Adding a key here is a deliberate cardinality
decision, reviewed like one.
"""
import ast
import os

PKG_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "seaweedfs_tpu")

_FUNCS = {"counter_add", "gauge_set", "histogram_observe"}

# Every key is bounded by construction: enum-like (kind, op, stage,
# outcome, method, direction, mode — repair read mode is exactly
# {partial, full}; reason is the QoS shed verdict, exactly {rate,
# deadline}), a fixed deployment set (backend, service, handler,
# collection, instance), HTTP classes (code), the histogram-internal
# bucket bound (le), or capped by a registry (tenant: at most
# -qos.maxTenants distinct values plus __overflow__ — utils/qos.py
# folds every later tenant into that one bucket precisely so this
# label stays bounded; shard: exactly -filer.store.shards values,
# fixed at store construction in filer/sharded_store.py; from/to/tier
# are drawn from the fixed tier-state enum in master/tiering.py
# (TIERS/TRANSITIONS) and dir is exactly {offload, recall}).
ALLOWED = {
    "backend", "code", "collection", "dir", "direction", "from",
    "handler", "instance", "kind", "le", "method", "mode", "op",
    "outcome", "reason", "service", "shard", "stage", "tenant",
    "tier", "to",
}


def _iter_modules():
    for root, _dirs, files in os.walk(PKG_DIR):
        for fn in sorted(files):
            if fn.endswith(".py"):
                path = os.path.join(root, fn)
                with open(path, encoding="utf-8") as f:
                    yield path, ast.parse(f.read(), filename=path)


def _labels_node(call: ast.Call) -> ast.expr | None:
    """The labels argument of one registry call, if present."""
    for kw in call.keywords:
        if kw.arg == "labels":
            return kw.value
    if len(call.args) >= 3:
        return call.args[2]
    return None


def _called_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _collect_label_sites():
    """-> (sites, used_keys): each site is (where, keys|None, problem)."""
    sites = []
    used = set()
    for path, tree in _iter_modules():
        rel = os.path.relpath(path, PKG_DIR)
        # simple local resolution: Name -> every dict literal assigned
        # to it anywhere in the module (call sites use `lab = {...}`
        # immediately above the calls, so this is exact in practice)
        assigned: dict[str, list[ast.Dict]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Dict):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        assigned.setdefault(tgt.id, []).append(node.value)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if _called_name(node) not in _FUNCS:
                continue
            lab = _labels_node(node)
            if lab is None or (isinstance(lab, ast.Constant)
                               and lab.value is None):
                continue
            where = f"{rel}:{node.lineno}"
            dicts: list[ast.Dict]
            if isinstance(lab, ast.Dict):
                dicts = [lab]
            elif isinstance(lab, ast.Name) and lab.id in assigned:
                dicts = assigned[lab.id]
            else:
                sites.append((where, None,
                              "labels must be a literal dict (inline "
                              "or a plain `name = {...}` assignment)"))
                continue
            for d in dicts:
                for k in d.keys:
                    if k is None:  # dict unpacking: keys unknowable
                        sites.append((where, None,
                                      "**-unpacking hides label keys"))
                    elif not (isinstance(k, ast.Constant)
                              and isinstance(k.value, str)):
                        sites.append((where, None,
                                      "label keys must be string "
                                      "literals"))
                    else:
                        used.add(k.value)
                        sites.append((where, k.value, ""))
    return sites, used


def test_label_dicts_are_statically_resolvable():
    sites, _used = _collect_label_sites()
    assert sites, "no labeled metric call sites found"
    bad = [(w, msg) for w, _k, msg in sites if msg]
    assert not bad, f"unresolvable label dicts: {bad}"


def test_label_keys_are_allowlisted():
    sites, used = _collect_label_sites()
    offenders = sorted({(w, k) for w, k, msg in sites
                        if not msg and k not in ALLOWED})
    assert not offenders, (
        f"label keys outside the cardinality allowlist: {offenders} — "
        "if the key is genuinely bounded, add it to ALLOWED in "
        "tests/test_lint_label_cardinality.py with a justification")
    # the allowlist must not rot: `le` is emitted by the histogram
    # renderer itself and `direction` by the volume server's manually
    # rendered native_front exposition, so neither appears at a
    # registry call site — everything else must
    unused = ALLOWED - used
    assert unused <= {"le", "direction"}, \
        f"allowlisted label keys no longer used anywhere: {unused}"
