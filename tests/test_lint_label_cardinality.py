"""Fast tier-1 lint: metric label keys come from a fixed allowlist and
label dicts are statically resolvable.

Prometheus memory and the federated /cluster/metrics corpus scale with
the number of distinct label values; a per-request key (path, volume
id, trace id...) turns one family into millions of series.

The rule logic (including the ALLOWED key set) lives in
seaweedfs_tpu/analysis/rules/label_cardinality.py; this module keeps
the historical entrypoints as thin wrappers over the shared engine
pass, including the rot check that every allowlisted key is still used
somewhere."""
import pytest

from seaweedfs_tpu.analysis import run_cached

pytestmark = pytest.mark.lint


def test_label_dicts_are_statically_resolvable():
    run = run_cached()
    assert run.stats["label_sites"] > 0, "no labeled metric call sites"
    offenders = [f.render() for f in run.by_rule("label-cardinality")
                 if "allowlist" not in f.message]
    assert not offenders, "\n".join(offenders)


def test_label_keys_are_allowlisted():
    run = run_cached()
    offenders = [f.render() for f in run.by_rule("label-cardinality")
                 if "allowlist" in f.message]
    assert not offenders, "\n".join(offenders)
    # the allowlist must not rot: renderer-emitted keys (le,
    # direction) never appear at a registry call site — everything
    # else must
    assert run.stats["label_keys_unused"] == [], (
        "allowlisted label keys no longer used anywhere: "
        f"{run.stats['label_keys_unused']}")
