"""Chunk-interval algebra unit tests.

Mirrors the reference's filer/filechunks_test.go scenarios: overlap
resolution by mtime, remnant splitting, views over ranges, garbage
separation, manifest round-trip.
"""
import hashlib

from seaweedfs_tpu.filer import (Entry, FileChunk, compact_file_chunks,
                                 etag_chunks, maybe_manifestize,
                                 non_overlapping_visible_intervals,
                                 resolve_chunk_manifest, total_size,
                                 view_from_chunks)


def C(fid, offset, size, ts):
    return FileChunk(fid=fid, offset=offset, size=size, mtime_ns=ts)


class TestVisibleIntervals:
    def test_single_chunk(self):
        v = non_overlapping_visible_intervals([C("1,a", 0, 100, 1)])
        assert [(x.start, x.stop, x.fid) for x in v] == [(0, 100, "1,a")]

    def test_non_overlapping(self):
        v = non_overlapping_visible_intervals(
            [C("1,a", 0, 100, 1), C("2,b", 100, 50, 2)])
        assert [(x.start, x.stop) for x in v] == [(0, 100), (100, 150)]

    def test_later_fully_covers(self):
        v = non_overlapping_visible_intervals(
            [C("1,a", 0, 100, 1), C("2,b", 0, 100, 2)])
        assert [(x.start, x.stop, x.fid) for x in v] == [(0, 100, "2,b")]

    def test_earlier_write_does_not_shadow(self):
        v = non_overlapping_visible_intervals(
            [C("2,b", 0, 100, 2), C("1,a", 0, 100, 1)])
        assert [x.fid for x in v] == ["2,b"]

    def test_middle_overwrite_splits(self):
        v = non_overlapping_visible_intervals(
            [C("1,a", 0, 100, 1), C("2,b", 30, 20, 2)])
        assert [(x.start, x.stop, x.fid, x.offset_in_chunk) for x in v] \
            == [(0, 30, "1,a", 0), (30, 50, "2,b", 0),
                (50, 100, "1,a", 50)]

    def test_staircase(self):
        v = non_overlapping_visible_intervals(
            [C("1,a", 0, 70, 1), C("2,b", 50, 70, 2), C("3,c", 100, 70, 3)])
        assert [(x.start, x.stop, x.fid) for x in v] == \
            [(0, 50, "1,a"), (50, 100, "2,b"), (100, 170, "3,c")]

    def test_total_size_and_sparse(self):
        chunks = [C("1,a", 100, 50, 1)]
        assert total_size(chunks) == 150


class TestChunkViews:
    def test_view_subrange(self):
        views = view_from_chunks(
            [C("1,a", 0, 100, 1), C("2,b", 100, 100, 2)], 50, 100)
        assert [(v.fid, v.offset_in_chunk, v.view_size, v.view_offset)
                for v in views] == [("1,a", 50, 50, 50), ("2,b", 0, 50, 100)]

    def test_view_with_overwrite_offsets(self):
        views = view_from_chunks(
            [C("1,a", 0, 100, 1), C("2,b", 30, 20, 2)], 40, 30)
        assert [(v.fid, v.offset_in_chunk, v.view_size) for v in views] \
            == [("2,b", 10, 10), ("1,a", 50, 20)]


class TestGarbage:
    def test_fully_shadowed_is_garbage(self):
        live, garbage = compact_file_chunks(
            [C("1,a", 0, 100, 1), C("2,b", 0, 100, 2)])
        assert [c.fid for c in live] == ["2,b"]
        assert [c.fid for c in garbage] == ["1,a"]

    def test_partial_overlap_not_garbage(self):
        live, garbage = compact_file_chunks(
            [C("1,a", 0, 100, 1), C("2,b", 50, 100, 2)])
        assert {c.fid for c in live} == {"1,a", "2,b"}
        assert garbage == []


class TestEtag:
    def test_single_chunk_etag(self):
        c = C("1,a", 0, 3, 1)
        c.etag = hashlib.md5(b"abc").hexdigest()
        assert etag_chunks([c]) == c.etag

    def test_multi_chunk_etag_has_count_suffix(self):
        cs = [C("1,a", 0, 3, 1), C("2,b", 3, 3, 2)]
        for c in cs:
            c.etag = hashlib.md5(c.fid.encode()).hexdigest()
        assert etag_chunks(cs).endswith("-2")


class TestManifest:
    def test_round_trip(self):
        blobs = {}

        def save(data: bytes) -> str:
            fid = f"9,{len(blobs):x}"
            blobs[fid] = data
            return fid

        chunks = [C(f"1,{i:x}", i * 10, 10, i) for i in range(25)]
        folded = maybe_manifestize(save, chunks, batch=10)
        manifests = [c for c in folded if c.is_chunk_manifest]
        assert len(manifests) == 2  # 25 = 10 + 10 + 5 plain
        assert len(folded) == 2 + 5
        back = resolve_chunk_manifest(lambda fid: blobs[fid], folded)
        assert sorted(c.fid for c in back) == sorted(c.fid for c in chunks)

    def test_below_batch_untouched(self):
        chunks = [C("1,a", 0, 10, 1)]
        assert maybe_manifestize(lambda b: "x", chunks, batch=10) == chunks


class TestEntryModel:
    def test_round_trip(self):
        e = Entry(full_path="/a/b/c.txt", mime="text/plain", ttl_sec=60,
                  chunks=[C("1,a", 0, 10, 1)], extended={"k": "v"})
        e2 = Entry.from_dict(e.to_dict())
        assert e2.full_path == "/a/b/c.txt"
        assert e2.chunks[0].fid == "1,a"
        assert e2.extended == {"k": "v"}
        assert not e2.is_directory
        assert e2.dir_and_name == ("/a/b", "c.txt")
