"""Disk-class support: volume layouts keyed (collection, rp, ttl,
diskType) (SURVEY.md section 2.4; volume_layout.go:107), ?disk= on
assign/grow, filer.conf disk routing, and volume.tier.move."""
import pytest
import requests

from seaweedfs_tpu.server.cluster import Cluster
from seaweedfs_tpu.shell import commands_volume
from seaweedfs_tpu.shell.env import CommandEnv


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = Cluster(str(tmp_path_factory.mktemp("disks")),
                n_volume_servers=2, volume_size_limit=4 << 20,
                max_volumes=20, with_filer=True,
                disk_types=["hdd", "ssd"])
    yield c
    c.stop()


@pytest.fixture(scope="module")
def env(cluster):
    e = CommandEnv(cluster.master_url, filer_url=cluster.filer_url)
    e.acquire_lock()
    yield e
    e.close()


def server_of(cluster, disk):
    return next(t.address for vs, t in
                zip(cluster.volume_servers, cluster.volume_threads)
                if vs.disk_type == disk)


class TestDiskAssign:
    def test_topology_reports_disk_types(self, cluster, env):
        types = {n["url"]: n["disk_type"] for n in env.data_nodes()}
        assert sorted(types.values()) == ["hdd", "ssd"]

    def test_assign_targets_disk_class(self, cluster, env):
        ssd_server = server_of(cluster, "ssd")
        hdd_server = server_of(cluster, "hdd")
        for disk, want in (("ssd", ssd_server), ("hdd", hdd_server)):
            r = requests.get(f"{cluster.master_url}/dir/assign",
                             params={"disk": disk,
                                     "collection": f"c{disk}"})
            body = r.json()
            assert r.status_code == 200, body
            assert body["url"] == want, (disk, body)

    def test_default_assign_is_hdd(self, cluster, env):
        r = requests.get(f"{cluster.master_url}/dir/assign",
                         params={"collection": "plain"})
        assert r.json()["url"] == server_of(cluster, "hdd")

    def test_grow_with_disk(self, cluster, env):
        out = commands_volume.volume_grow(env, count=1,
                                          collection="growssd",
                                          disk_type="ssd")
        assert out["count"] == 1
        ssd_server = server_of(cluster, "ssd")
        nodes = {n["url"]: n for n in env.data_nodes()}
        grown = [v for v, col in nodes[ssd_server]
                 .get("collections", {}).items() if col == "growssd"]
        assert grown

    def test_unknown_disk_class_errors(self, cluster):
        r = requests.get(f"{cluster.master_url}/dir/assign",
                         params={"disk": "tape", "collection": "nope"})
        assert r.status_code == 500
        assert "tape" in r.json().get("error", "")


class TestFilerDiskRouting:
    def test_filer_conf_disk_rule_routes_uploads(self, cluster, env):
        import json as _json
        conf = {"rules": [{"location_prefix": "/fast/",
                           "disk_type": "ssd",
                           "collection": "fastcol"}]}
        requests.put(f"{cluster.filer_url}/kv/filer.conf",
                     data=_json.dumps(conf))
        r = requests.post(f"{cluster.filer_url}/fast/f.bin",
                          data=b"ssd bytes")
        assert r.status_code < 300
        # the chunk must live on the ssd server
        meta = requests.get(f"{cluster.filer_url}/fast/f.bin",
                            params={"meta": "1"}).json()
        vid = int(meta["chunks"][0]["fid"].partition(",")[0])
        locs = requests.get(f"{cluster.master_url}/dir/lookup",
                            params={"volumeId": str(vid)}).json()
        urls = {l["url"] for l in locs["locations"]}
        assert urls == {server_of(cluster, "ssd")}


class TestTierMove:
    def test_tier_move_hdd_to_ssd(self, cluster, env):
        # land a volume on the hdd server
        r = requests.get(f"{cluster.master_url}/dir/assign",
                         params={"disk": "hdd",
                                 "collection": "movecol"})
        body = r.json()
        requests.post(f"http://{body['url']}/{body['fid']}",
                      files={"file": b"move these bytes"},
                      params={"auth": body.get("auth", "")})
        moved = commands_volume.volume_tier_move(
            env, "ssd", collection="movecol")
        assert moved, "nothing moved"
        assert all(m["to"] == server_of(cluster, "ssd")
                   for m in moved)
        # data still readable after the move
        got = requests.get(
            f"http://{server_of(cluster, 'ssd')}/{body['fid']}")
        assert got.content == b"move these bytes"
