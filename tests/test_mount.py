"""Mount layer tests: dirty-page pipeline units, inode registry, chunk
cache, and the WeedFS core against a live in-process cluster.

Mirrors the concerns of /root/reference/weed/mount/: page_writer
seal/upload/flush semantics (upload_pipeline.go), inode stability
across rename (inode_to_path.go), tiered chunk cache, POSIX-shaped op
behavior over the filer (weedfs_*.go), including the e2e write/read
verification the reference gets from fio over a real mount
(.github/workflows/e2e.yml) at library level.
"""
import hashlib
import threading

import numpy as np
import pytest

from seaweedfs_tpu.mount.chunk_cache import (MemoryChunkCache,
                                             TieredChunkCache)
from seaweedfs_tpu.mount.inode_registry import InodeRegistry
from seaweedfs_tpu.mount.page_writer import DirtyPages


class TestDirtyPages:
    def _mk(self, chunk_size=64):
        uploads = {}
        counter = [0]
        lock = threading.Lock()

        def upload(data: bytes) -> str:
            with lock:
                counter[0] += 1
                fid = f"f{counter[0]}"
                uploads[fid] = data
            return fid

        return DirtyPages(upload, chunk_size=chunk_size), uploads

    def test_sequential_write_seals_full_chunks(self):
        dp, uploads = self._mk(chunk_size=64)
        dp.write(0, b"a" * 64)
        dp.write(64, b"b" * 64)
        dp.write(128, b"c" * 10)  # cursor past slots 0 and 1 -> sealed
        chunks = dp.flush()
        got = bytearray(138)
        for c in chunks:
            got[c.offset:c.offset + c.size] = uploads[c.fid]
        assert bytes(got) == b"a" * 64 + b"b" * 64 + b"c" * 10
        # mtimes strictly increase so overlap resolution is stable
        mtimes = [c.mtime_ns for c in chunks]
        assert mtimes == sorted(mtimes) and len(set(mtimes)) == len(mtimes)

    def test_random_write_within_open_slot_mutates(self):
        dp, uploads = self._mk(chunk_size=64)
        dp.write(0, b"x" * 32)
        dp.write(8, b"y" * 8)  # overwrite inside the moving slot
        chunks = dp.flush()
        assert len(chunks) == 1
        data = uploads[chunks[0].fid]
        assert data == b"x" * 8 + b"y" * 8 + b"x" * 16

    def test_sparse_write_uploads_spans_separately(self):
        dp, uploads = self._mk(chunk_size=64)
        dp.write(0, b"a" * 8)
        dp.write(32, b"b" * 8)  # same slot, disjoint span
        chunks = dp.flush()
        assert sorted((c.offset, c.size) for c in chunks) == \
            [(0, 8), (32, 8)]

    def test_overlay_read_sees_unflushed_bytes(self):
        dp, _ = self._mk(chunk_size=64)
        dp.write(0, b"a" * 64)     # full slot
        dp.write(64, b"b" * 100)   # seals slot 0, slot 1 moving
        out = bytearray(200)
        covered = dp.read_overlay(0, 200, out)
        assert covered and covered[0][0] == 0
        assert bytes(out[:164]) == b"a" * 64 + b"b" * 100

    def test_write_after_seal_wins_by_mtime(self):
        dp, uploads = self._mk(chunk_size=64)
        dp.write(0, b"1" * 64)
        dp.write(64, b"2" * 64)
        dp.write(128, b"3" * 8)   # slots 0,1 sealed
        dp.write(0, b"9" * 16)    # rewrite into sealed region
        chunks = dp.flush()
        from seaweedfs_tpu.filer.filechunks import view_from_chunks

        views = view_from_chunks(chunks, 0, 136)
        got = bytearray(136)
        for v in views:
            data = uploads[v.fid]
            got[v.view_offset:v.view_offset + v.view_size] = \
                data[v.offset_in_chunk:v.offset_in_chunk + v.view_size]
        assert bytes(got) == b"9" * 16 + b"1" * 48 + b"2" * 64 + b"3" * 8

    def test_flush_empty_is_noop(self):
        dp, _ = self._mk()
        assert dp.flush() == []
        assert not dp.has_dirty()


class TestInodeRegistry:
    def test_stable_and_unique(self):
        reg = InodeRegistry()
        a = reg.lookup("/a")
        b = reg.lookup("/b")
        assert a != b
        assert reg.lookup("/a") == a

    def test_rename_moves_inode_tree(self):
        reg = InodeRegistry()
        d = reg.lookup("/dir")
        f = reg.lookup("/dir/file")
        reg.replace_path("/dir", "/renamed")
        assert reg.inode_of("/renamed") == d
        assert reg.inode_of("/renamed/file") == f
        assert reg.inode_of("/dir") is None

    def test_forget(self):
        reg = InodeRegistry()
        i = reg.lookup("/x")
        reg.forget("/x")
        assert reg.inode_of("/x") is None
        assert reg.path_of(i) is None


class TestChunkCache:
    def test_memory_lru_eviction(self):
        c = MemoryChunkCache(capacity_bytes=100)
        c.put("a", b"x" * 40)
        c.put("b", b"y" * 40)
        c.get("a")  # touch a so b is LRU
        c.put("c", b"z" * 40)  # evicts b
        assert c.get("a") is not None
        assert c.get("b") is None
        assert c.get("c") is not None

    def test_disk_tier_promote(self, tmp_path):
        c = TieredChunkCache(memory_bytes=1 << 20,
                             disk_dir=str(tmp_path), disk_bytes=1 << 20)
        c.put("fid1", b"hello")
        c.mem._data.clear()
        c.mem._used = 0
        assert c.get("fid1") == b"hello"  # from disk, promoted
        assert c.mem.get("fid1") == b"hello"


@pytest.fixture(scope="module")
def mount_fs(tmp_path_factory):
    from seaweedfs_tpu.mount.weedfs import WeedFS
    from seaweedfs_tpu.server.cluster import Cluster

    base = tmp_path_factory.mktemp("mountfs")
    cluster = Cluster(str(base), n_volume_servers=1, with_filer=True)
    cluster.wait_for_nodes(1)
    fs = WeedFS(cluster.filer_url, master_url=cluster.master_url,
                root="/mnt-root", chunk_size=256,  # small for test io
                cache_dir=str(base / "cache"),
                upload_workers=4, subscribe=True, meta_ttl=30)
    yield fs
    fs.destroy()
    cluster.stop()


class TestWeedFS:
    def test_create_write_read_roundtrip(self, mount_fs):
        fs = mount_fs
        fh = fs.create("/hello.txt")
        fs.write(fh, 0, b"hello mount world")
        # read-your-writes before flush (dirty overlay)
        assert fs.read(fh, 0, 100) == b"hello mount world"
        fs.release(fh)
        fh2 = fs.open("/hello.txt")
        assert fs.read(fh2, 0, 100) == b"hello mount world"
        assert fs.read(fh2, 6, 5) == b"mount"
        fs.release(fh2)

    def test_large_file_multi_chunk(self, mount_fs):
        fs = mount_fs
        rng = np.random.default_rng(7)
        payload = rng.integers(0, 256, 256 * 5 + 37, dtype=np.uint8) \
            .tobytes()
        fh = fs.create("/big.bin")
        # write in odd-sized pieces to cross chunk boundaries
        pos = 0
        for sz in (100, 300, 256, 511, 1):
            fs.write(fh, pos, payload[pos:pos + sz])
            pos += sz
        fs.write(fh, pos, payload[pos:])
        fs.release(fh)
        fh = fs.open("/big.bin")
        got = fs.read(fh, 0, len(payload) + 64)
        fs.release(fh)
        assert hashlib.md5(got).hexdigest() == \
            hashlib.md5(payload).hexdigest()
        assert fs.getattr("/big.bin")["st_size"] == len(payload)

    def test_random_overwrite_visible(self, mount_fs):
        fs = mount_fs
        fh = fs.create("/rw.bin")
        fs.write(fh, 0, b"A" * 1000)
        fs.release(fh)
        fh = fs.open("/rw.bin")
        fs.write(fh, 100, b"B" * 50)  # overwrite committed range
        assert fs.read(fh, 90, 70) == b"A" * 10 + b"B" * 50 + b"A" * 10
        fs.release(fh)
        fh = fs.open("/rw.bin")
        data = fs.read(fh, 0, 1000)
        fs.release(fh)
        assert data[100:150] == b"B" * 50
        assert data[:100] == b"A" * 100

    def test_mkdir_readdir_rmdir(self, mount_fs):
        fs = mount_fs
        fs.mkdir("/subdir")
        fh = fs.create("/subdir/f1")
        fs.write(fh, 0, b"x")
        fs.release(fh)
        names = fs.readdir("/subdir")
        assert "f1" in names
        with pytest.raises(OSError):  # ENOTEMPTY
            fs.rmdir("/subdir")
        fs.unlink("/subdir/f1")
        fs.rmdir("/subdir")
        with pytest.raises(OSError):
            fs.getattr("/subdir")

    def test_rename_keeps_inode_and_content(self, mount_fs):
        fs = mount_fs
        fh = fs.create("/old-name")
        fs.write(fh, 0, b"payload")
        fs.release(fh)
        ino = fs.getattr("/old-name")["st_ino"]
        fs.rename("/old-name", "/new-name")
        assert fs.getattr("/new-name")["st_ino"] == ino
        with pytest.raises(OSError):
            fs.getattr("/old-name")
        fh = fs.open("/new-name")
        assert fs.read(fh, 0, 10) == b"payload"
        fs.release(fh)

    def test_truncate(self, mount_fs):
        fs = mount_fs
        fh = fs.create("/trunc.bin")
        fs.write(fh, 0, b"0123456789" * 100)
        fs.release(fh)
        fs.truncate("/trunc.bin", 5)
        assert fs.getattr("/trunc.bin")["st_size"] == 5
        fh = fs.open("/trunc.bin")
        assert fs.read(fh, 0, 100) == b"01234"
        fs.release(fh)
        fs.truncate("/trunc.bin", 0)
        assert fs.getattr("/trunc.bin")["st_size"] == 0

    def test_chmod_chown_utimens(self, mount_fs):
        fs = mount_fs
        fh = fs.create("/attrs", mode=0o644)
        fs.release(fh)
        fs.chmod("/attrs", 0o600)
        assert fs.getattr("/attrs")["st_mode"] & 0o777 == 0o600
        fs.chown("/attrs", 1000, 1000)
        at = fs.getattr("/attrs")
        assert (at["st_uid"], at["st_gid"]) == (1000, 1000)
        fs.utimens("/attrs", 12345.0)
        assert fs.getattr("/attrs")["st_mtime"] == 12345.0

    def test_symlink_readlink(self, mount_fs):
        fs = mount_fs
        fs.symlink("/new-name", "/link-to-file")
        assert fs.readlink("/link-to-file") == "/new-name"

    def test_open_truncate_flag(self, mount_fs):
        fs = mount_fs
        fh = fs.create("/otrunc")
        fs.write(fh, 0, b"long old content")
        fs.release(fh)
        fh = fs.open("/otrunc", truncate=True)
        fs.write(fh, 0, b"new")
        fs.release(fh)
        fh = fs.open("/otrunc")
        assert fs.read(fh, 0, 100) == b"new"
        fs.release(fh)

    def test_getattr_sees_unflushed_size(self, mount_fs):
        fs = mount_fs
        fh = fs.create("/growing")
        fs.write(fh, 0, b"z" * 700)  # > 2 chunks sealed, rest dirty
        assert fs.getattr("/growing")["st_size"] == 700
        fs.release(fh)
        assert fs.getattr("/growing")["st_size"] == 700

    def test_readdir_cache_fresh_after_create(self, mount_fs):
        fs = mount_fs
        fs.mkdir("/cachedir")
        assert fs.readdir("/cachedir") == [".", ".."]  # caches listing
        fh = fs.create("/cachedir/newfile")
        fs.release(fh)
        assert "newfile" in fs.readdir("/cachedir")
        fs.unlink("/cachedir/newfile")
        assert "newfile" not in fs.readdir("/cachedir")
        fs.rmdir("/cachedir")

    def test_truncate_discards_dirty_pages(self, mount_fs):
        fs = mount_fs
        fh = fs.create("/trunc-dirty")
        fs.write(fh, 0, b"x" * 100)
        fs.truncate("/trunc-dirty", 10)  # path-based, no fh
        fs.release(fh)
        assert fs.getattr("/trunc-dirty")["st_size"] == 10

    def test_xattr_roundtrip_and_flags(self, mount_fs):
        """get/set/list/remove xattr stored as xattr- entry extras
        (weedfs_xattr.go:22-181), with proper setxattr(2) flag
        semantics and the VFS size caps."""
        import errno

        from seaweedfs_tpu.mount.weedfs import (
            MAX_XATTR_NAME_SIZE, MAX_XATTR_VALUE_SIZE, XATTR_CREATE,
            XATTR_REPLACE, FuseError)

        fs = mount_fs
        fh = fs.create("/xa.txt")
        fs.release(fh)
        fs.setxattr("/xa.txt", "user.color", b"teal")
        fs.setxattr("/xa.txt", "user.blob", bytes(range(256)))
        assert fs.getxattr("/xa.txt", "user.color") == b"teal"
        assert fs.getxattr("/xa.txt", "user.blob") == bytes(range(256))
        assert sorted(fs.listxattr("/xa.txt")) == \
            ["user.blob", "user.color"]
        # flags: CREATE on existing = EEXIST, REPLACE on missing = ENODATA
        with pytest.raises(FuseError) as ei:
            fs.setxattr("/xa.txt", "user.color", b"x", XATTR_CREATE)
        assert ei.value.errno == errno.EEXIST
        with pytest.raises(FuseError) as ei:
            fs.setxattr("/xa.txt", "user.nope", b"x", XATTR_REPLACE)
        assert ei.value.errno == errno.ENODATA
        fs.setxattr("/xa.txt", "user.color", b"red", XATTR_REPLACE)
        assert fs.getxattr("/xa.txt", "user.color") == b"red"
        # missing attr / removed attr = ENODATA
        fs.removexattr("/xa.txt", "user.blob")
        for op in (lambda: fs.getxattr("/xa.txt", "user.blob"),
                   lambda: fs.removexattr("/xa.txt", "user.blob")):
            with pytest.raises(FuseError) as ei:
                op()
            assert ei.value.errno == errno.ENODATA
        # size caps -> ERANGE; empty name -> EINVAL
        with pytest.raises(FuseError) as ei:
            fs.setxattr("/xa.txt", "n" * (MAX_XATTR_NAME_SIZE + 1), b"v")
        assert ei.value.errno == errno.ERANGE
        with pytest.raises(FuseError) as ei:
            fs.setxattr("/xa.txt", "user.big",
                        b"v" * (MAX_XATTR_VALUE_SIZE + 1))
        assert ei.value.errno == errno.ERANGE
        with pytest.raises(FuseError) as ei:
            fs.getxattr("/xa.txt", "")
        assert ei.value.errno == errno.EINVAL
        # persists through the filer (fresh core, no shared caches)
        from seaweedfs_tpu.mount.weedfs import WeedFS

        fs2 = WeedFS(fs.client.filer_url, root="/mnt-root",
                     subscribe=False)
        try:
            assert fs2.getxattr("/xa.txt", "user.color") == b"red"
            assert fs2.listxattr("/xa.txt") == ["user.color"]
        finally:
            fs2.destroy()

    def test_xattr_disabled(self, mount_fs):
        import errno

        from seaweedfs_tpu.mount.weedfs import FuseError, WeedFS
        fs = WeedFS(mount_fs.client.filer_url, root="/mnt-root",
                    subscribe=False, disable_xattr=True)
        try:
            with pytest.raises(FuseError) as ei:
                fs.getxattr("/any", "user.x")
            assert ei.value.errno == errno.ENOTSUP
            with pytest.raises(FuseError):
                fs.setxattr("/any", "user.x", b"v")
            with pytest.raises(FuseError):
                fs.listxattr("/any")
            with pytest.raises(FuseError):
                fs.removexattr("/any", "user.x")
        finally:
            fs.destroy()

    def test_xattr_survives_open_handle_flush(self, mount_fs):
        """A set on a path with an open write handle must not be
        clobbered when that handle flushes its own entry object."""
        fs = mount_fs
        fh = fs.create("/xa-open.txt")
        fs.write(fh, 0, b"before")
        fs.flush(fh)
        fs.setxattr("/xa-open.txt", "user.tag", b"keep")
        fs.write(fh, 6, b" after")
        fs.release(fh)  # flush saves the handle's entry
        assert fs.getxattr("/xa-open.txt", "user.tag") == b"keep"
        fh = fs.open("/xa-open.txt")
        assert fs.read(fh, 0, 100) == b"before after"
        fs.release(fh)

    def test_fio_style_verified_randwrite(self, mount_fs):
        """Random-offset writes then full verify — the library-level
        equivalent of the reference's fio randwrite + crc32c gate."""
        fs = mount_fs
        rng = np.random.default_rng(11)
        size = 256 * 8
        model = bytearray(size)
        fh = fs.create("/fio.bin")
        fs.write(fh, 0, bytes(size))  # preallocate
        for _ in range(60):
            off = int(rng.integers(0, size - 64))
            blk = rng.integers(0, 256, 64, dtype=np.uint8).tobytes()
            model[off:off + 64] = blk
            fs.write(fh, off, blk)
        fs.flush(fh)
        got = fs.read(fh, 0, size)
        fs.release(fh)
        assert hashlib.md5(got).hexdigest() == \
            hashlib.md5(bytes(model)).hexdigest()


class TestDirtySpill:
    """Dirty-memory bound + swap-file spill (page_writer.go
    MemoryChunkPages / swapfile_chunk_pages; round-2 VERDICT item 5)."""

    def _mk(self, chunk_size=1024, memory_limit=4096, tmp=None):
        uploads = {}
        counter = [0]
        lock = threading.Lock()

        def upload(data: bytes) -> str:
            with lock:
                counter[0] += 1
                fid = f"f{counter[0]}"
                uploads[fid] = data
            return fid

        dp = DirtyPages(upload, chunk_size=chunk_size,
                        memory_limit=memory_limit, swap_dir=tmp)
        return dp, uploads

    def test_random_writes_bounded_ram(self, tmp_path):
        # write 64 distinct 1KB slots with a 4KB cap: without the bound
        # this holds 64KB of slot buffers (+ payloads); with it, RAM
        # stays O(cap) and the data still round-trips bit-exact
        rng = np.random.default_rng(4)
        dp, uploads = self._mk(chunk_size=1024, memory_limit=4096,
                               tmp=str(tmp_path))
        golden = {}
        order = rng.permutation(64)
        for idx in order:
            payload = rng.bytes(1024)
            golden[int(idx)] = payload
            dp.write(int(idx) * 1024, payload)
            assert dp.dirty_ram_bytes <= 4096 + 1024, \
                f"dirty RAM {dp.dirty_ram_bytes} exceeds cap"
        assert dp.swap_bytes > 0, "cap this tight must have spilled"
        chunks = dp.flush()
        got = bytearray(64 * 1024)
        for c in sorted(chunks, key=lambda c: c.mtime_ns):
            got[c.offset:c.offset + c.size] = uploads[c.fid]
        want = bytearray(64 * 1024)
        for idx, payload in golden.items():
            want[idx * 1024:(idx + 1) * 1024] = payload
        assert got == want
        # swap space recycled once everything committed
        assert dp.swap_bytes == 0
        dp.close()

    def test_overlay_reads_from_swap(self, tmp_path):
        dp, _uploads = self._mk(chunk_size=1024, memory_limit=2048,
                                tmp=str(tmp_path))
        a, b, c = b"A" * 1024, b"B" * 1024, b"C" * 1024
        dp.write(0, a)
        dp.write(4096, b)   # forces seal+spill of older slots
        dp.write(8192, c)
        out = bytearray(1024)
        covered = dp.read_overlay(0, 1024, out)
        assert covered and bytes(out) == a
        out = bytearray(1024)
        covered = dp.read_overlay(4096, 1024, out)
        assert covered and bytes(out) == b
        # partial window inside a spilled payload
        out = bytearray(100)
        covered = dp.read_overlay(4096 + 200, 100, out)
        assert covered and bytes(out) == b[200:300]
        dp.flush()
        dp.close()

    def test_spilled_upload_failure_retries(self, tmp_path):
        fail = [True]
        uploads = {}

        def upload(data: bytes) -> str:
            if fail[0]:
                raise IOError("volume server down")
            fid = f"f{len(uploads)}"
            uploads[fid] = data
            return fid

        dp = DirtyPages(upload, chunk_size=1024, memory_limit=1024,
                        swap_dir=str(tmp_path))
        dp.write(0, b"x" * 1024)
        dp.write(2048, b"y" * 1024)  # spills slot 0
        with pytest.raises(IOError):
            dp.flush()
        fail[0] = False
        # the failed flush may itself have resubmitted an upload while
        # fail was still set; like the kernel, retry flush until clean
        for _ in range(3):
            try:
                chunks = dp.flush()  # retried from swap-resident refs
                break
            except IOError:
                continue
        else:
            raise AssertionError("flush never recovered")
        assert {uploads[c.fid] for c in chunks} == \
            {b"x" * 1024, b"y" * 1024}
        dp.close()
