"""Postgres filer store over protocol v3, against the in-process
mini-postgres (tests/minipg.py) — the abstract_sql postgres dialect
driven by the in-tree wire client (filer/pg_lite.py). Reference slot:
/root/reference/weed/filer/postgres/postgres_store.go.
"""
import time

import pytest

from seaweedfs_tpu.filer.entry import Entry, FileChunk
from seaweedfs_tpu.filer.filer import Filer
from seaweedfs_tpu.filer.pg_lite import (PgConnection, PgError,
                                         escape_literal)

from .minipg import MiniPg, de_interpolate


@pytest.fixture(scope="module")
def pg():
    s = MiniPg(user="weed", password="s3cret")
    yield s
    s.close()


@pytest.fixture()
def store(pg):
    from seaweedfs_tpu.filer.abstract_sql import PostgresStore

    with pg.lock:
        pg.db.execute("DROP TABLE IF EXISTS filemeta")
        pg.db.execute("DROP TABLE IF EXISTS kv")
    s = PostgresStore(port=pg.port, user="weed", password="s3cret",
                      database="weeddb")
    yield s
    s.close()


def ent(path, size=0):
    chunks = [FileChunk(fid="1,ab", offset=0, size=size,
                        mtime_ns=time.time_ns())] if size else []
    return Entry(full_path=path, chunks=chunks)


def test_md5_auth_rejected(pg):
    with pytest.raises(PgError) as ei:
        PgConnection("127.0.0.1", pg.port, user="weed",
                     password="wrong")
    assert ei.value.fields["C"] == "28P01"


def test_escaping_round_trips():
    evil = "it's ''doubled'' and a \\ backslash"
    sql = "INSERT INTO t VALUES(%s,%s)" % (
        escape_literal(evil), escape_literal(b"\x00\xffbin'"))
    psql, params = de_interpolate(sql)
    assert psql == "INSERT INTO t VALUES(?,?)"
    assert params == [evil, b"\x00\xffbin'"]


def test_query_errors_surface(store):
    with pytest.raises(PgError):
        store._exec("SELECT * FROM no_such_table")


def test_insert_find_update_delete(store):
    store.insert_entry(ent("/a/b.txt", 10))
    assert store.find_entry("/a/b.txt").file_size == 10
    store.update_entry(ent("/a/b.txt", 20))  # ON CONFLICT upsert
    assert store.find_entry("/a/b.txt").file_size == 20
    store.delete_entry("/a/b.txt")
    assert store.find_entry("/a/b.txt") is None


def test_listing_order_pagination_prefix(store):
    for n in ("zeta", "alpha", "beta", "beta2", "gamma"):
        store.insert_entry(ent(f"/dir/{n}"))
    names = [e.name for e in store.list_directory_entries("/dir")]
    assert names == ["alpha", "beta", "beta2", "gamma", "zeta"]
    page = store.list_directory_entries("/dir", start_from="beta",
                                        inclusive=True, limit=2)
    assert [e.name for e in page] == ["beta", "beta2"]
    pref = store.list_directory_entries("/dir", prefix="beta")
    assert [e.name for e in pref] == ["beta", "beta2"]


def test_delete_folder_children_subtree(store):
    for p in ("/t/a", "/t/sub/x", "/t/sub/deep/y", "/tother/z"):
        store.insert_entry(ent(p))
    store.delete_folder_children("/t")
    for p in ("/t/a", "/t/sub/x", "/t/sub/deep/y"):
        assert store.find_entry(p) is None, p
    assert store.find_entry("/tother/z") is not None


def test_kv_bytea_round_trip(store):
    blob = b"\x00\x01\xffbinary'quote\\x"
    store.kv_put("conf", blob)
    assert store.kv_get("conf") == blob
    store.kv_delete("conf")
    assert store.kv_get("conf") is None


def test_full_filer_stack(pg):
    with pg.lock:
        pg.db.execute("DELETE FROM filemeta")
    f = Filer("postgres", port=pg.port, user="weed",
              password="s3cret", database="weeddb")
    try:
        f.create_entry(ent("/docs/readme.md", 5))
        assert f.find_entry("/docs/readme.md").file_size == 5
        assert [e.name for e in f.list_entries("/docs")] == ["readme.md"]
        f.delete_entry("/docs", recursive=True)
        assert f.find_entry("/docs/readme.md") is None
    finally:
        f.close()


# -- postgres2: per-bucket tables (postgres2_store.go) -----------------

def test_postgres2_bucket_tables_and_drop(pg):
    from seaweedfs_tpu.filer.abstract_sql import Postgres2Store

    with pg.lock:
        for (name,) in pg.db.execute(
                "SELECT name FROM sqlite_master WHERE type='table'"
        ).fetchall():
            pg.db.execute(f'DROP TABLE IF EXISTS "{name}"')
    s = Postgres2Store(port=pg.port, user="weed", password="s3cret",
                       database="weeddb")
    try:
        s.insert_entry(ent("/buckets/pics/a.png", size=1))
        s.insert_entry(ent("/outside.txt", size=1))
        with pg.lock:
            tables = {r[0] for r in pg.db.execute(
                "SELECT name FROM sqlite_master WHERE type='table'")}
        assert "bucket_pics" in tables
        assert s.find_entry("/buckets/pics/a.png") is not None
        s.delete_folder_children("/buckets/pics")
        with pg.lock:
            tables = {r[0] for r in pg.db.execute(
                "SELECT name FROM sqlite_master WHERE type='table'")}
        assert "bucket_pics" not in tables  # dropped, not scanned
        assert s.find_entry("/outside.txt") is not None
    finally:
        s.close()
