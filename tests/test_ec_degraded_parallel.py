"""Degraded-read fan-out: concurrent first-k-wins shard fetch under a
per-read deadline (store_ec.go:349-393 goroutine fan-out equivalent;
round-2 VERDICT item 3 — the serial walk paid >= 10 sequential RTTs and
a single hung peer stalled the read forever).
"""
import threading
import time

import numpy as np
import pytest
import requests

from seaweedfs_tpu.ec import geometry as geo
from seaweedfs_tpu.operation import verbs
from seaweedfs_tpu.server.cluster import Cluster
from seaweedfs_tpu.shell import commands_ec
from seaweedfs_tpu.shell.env import CommandEnv


# ---------------------------------------------------------------------
# Store-level: the reconstruct ladder uses the fan-out fetcher contract
# ---------------------------------------------------------------------

def _make_ec_store(tmp_path, n_local=4):
    """A Store holding only `n_local` shards of a 14-shard volume, plus
    the golden shard bytes for the rest."""
    from seaweedfs_tpu.ec.encoder import write_ec_files, write_sorted_ecx
    from seaweedfs_tpu.storage.store import Store

    rng = np.random.default_rng(5)
    base = tmp_path / "77"
    # a tiny needle-shaped volume is unnecessary: reconstruct operates
    # on raw intervals, so raw shard ranges are enough for this layer
    (tmp_path / "77.dat").write_bytes(rng.bytes(geo.SMALL_BLOCK * 10 * 3))
    (tmp_path / "77.idx").write_bytes(b"")  # no needles needed here
    write_ec_files(str(base), backend="numpy")
    write_sorted_ecx(str(base))
    shards = {i: (tmp_path / ("77" + geo.shard_ext(i))).read_bytes()
              for i in range(geo.TOTAL_SHARDS)}
    for i in range(geo.TOTAL_SHARDS):
        if i >= n_local:
            (tmp_path / ("77" + geo.shard_ext(i))).unlink()
    store = Store([str(tmp_path)])
    assert 77 in store.ec_volumes
    return store, shards


def test_reconstruct_uses_fanout_fetcher(tmp_path):
    store, shards = _make_ec_store(tmp_path, n_local=4)
    calls = []

    def fetcher(vid, sids, offset, size, need, deadline):
        calls.append((vid, tuple(sids), need))
        # return exactly `need` shards, as a concurrent fan-out would
        out = {}
        for sid in sids[:need]:
            out[sid] = shards[sid][offset:offset + size]
        return out

    store.remote_shards_fetcher = fetcher
    ecv = store.ec_volumes[77]
    got = store._reconstruct_interval(ecv, 12, 100, 5000)
    assert got == shards[12][100:5100]
    (vid, sids, need) = calls[0]
    assert vid == 77 and need == geo.DATA_SHARDS - 4  # shards 0-3 local
    assert 12 not in sids  # never asks for the shard being rebuilt
    assert all(s >= 4 for s in sids)  # locals aren't re-fetched


def test_reconstruct_fails_cleanly_when_short(tmp_path):
    store, shards = _make_ec_store(tmp_path, n_local=4)
    store.remote_shards_fetcher = \
        lambda vid, sids, off, size, need, dl: {}  # all peers dark
    ecv = store.ec_volumes[77]
    with pytest.raises(IOError, match="only 4 shards reachable"):
        store._reconstruct_interval(ecv, 12, 0, 100)


# ---------------------------------------------------------------------
# Server-level e2e: one hung peer must not stall the degraded read
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = Cluster(str(tmp_path_factory.mktemp("ec_par")),
                n_volume_servers=3, volume_size_limit=4 << 20,
                max_volumes=40)
    yield c
    c.stop()


def test_degraded_read_with_hung_peer(cluster):
    import secrets

    env = CommandEnv(cluster.master_url)
    env.acquire_lock()
    try:
        col = "hung" + secrets.token_hex(3)
        rng = np.random.default_rng(1)
        a = verbs.assign(cluster.master_url, collection=col)
        vid = int(a.fid.split(",")[0])
        data = rng.bytes(200_000)
        verbs.upload(a, data)
        commands_ec.ec_encode(env, vid)
        locs = env.ec_shard_locations(vid)

        # which shard does this needle's read actually need?
        from seaweedfs_tpu.storage.types import parse_file_id

        _, nid, _ = parse_file_id(a.fid)
        any_srv = cluster.volume_servers[0]
        intervals, _size = \
            any_srv.store.ec_volumes[vid].needle_intervals(nid)
        sid_x, _ = intervals[0].to_shard_and_offset()

        # wedge ONLY that shard on its holder (a wedged-but-connected
        # peer); everything else stays healthy, so reconstruction from
        # the other 13 shards remains possible
        hung_url = locs[sid_x][0]
        hung_srv = next(
            s for s in cluster.volume_servers
            if f"{s.store.ip}:{s.store.port}" == hung_url)
        ecv = hung_srv.store.ec_volumes[vid]
        release = threading.Event()

        class HungShard:
            def __init__(self, inner):
                self._inner = inner

            def read_at(self, *a, **kw):
                release.wait(30)  # wedged until the test releases it
                return self._inner.read_at(*a, **kw)

            def __getattr__(self, name):
                return getattr(self._inner, name)

        saved = dict(ecv.shards)
        patched = dict(saved)
        patched[sid_x] = HungShard(saved[sid_x])
        ecv.shards = patched
        try:
            # read through a DIFFERENT server: the direct fetch of the
            # wedged shard must give up after its small budget slice,
            # and the reconstruction fan-out must win well inside the
            # read deadline
            reader = next(u for urls in locs.values() for u in urls
                          if u != hung_url)
            deadline = 8.0
            for s in cluster.volume_servers:
                s.store.ec_read_deadline = deadline
            t0 = time.monotonic()
            resp = requests.get(f"http://{reader}/{a.fid}", timeout=25)
            dt = time.monotonic() - t0
            assert resp.status_code == 200, resp.text
            assert resp.content == data
            # p50 bound: well under the hung peer's 30s wedge — the
            # direct hop costs <= 2s, the fan-out single-digit seconds
            assert dt < deadline, f"degraded read took {dt:.1f}s"
        finally:
            release.set()
            ecv.shards = saved
    finally:
        env.close()


def test_client_ec_cache_follows_shard_move(cluster):
    """EC per-shard locations live in the client vid cache and the
    KeepConnected ec_updates push invalidates them on a shard move
    (vid_map.go:169-236; VERDICT round-2 item 7)."""
    import secrets

    from seaweedfs_tpu.wdclient.client import MasterClient

    env = CommandEnv(cluster.master_url)
    env.acquire_lock()
    mc = MasterClient(cluster.master_url, subscribe=True)
    try:
        col = "mv" + secrets.token_hex(3)
        rng = np.random.default_rng(2)
        a = verbs.assign(cluster.master_url, collection=col)
        vid = int(a.fid.split(",")[0])
        data = rng.bytes(50_000)
        verbs.upload(a, data)
        commands_ec.ec_encode(env, vid)

        # cache warm: per-shard map served without re-polling
        shards = mc.lookup_ec(vid)
        assert shards and all(urls for urls in shards.values())
        src = shards[0][0]
        dst = next(u for urls in shards.values() for u in urls
                   if u != src)

        # move shard 0: copy to dst, mount there, unmount+delete at src
        env.vs_post(dst, "/admin/ec/copy",
                    {"volume": vid, "collection": col, "shard_ids": [0],
                     "source": src})
        env.vs_post(dst, "/admin/ec/mount",
                    {"volume": vid, "collection": col, "shard_ids": [0]})
        env.vs_post(src, "/admin/ec/unmount",
                    {"volume": vid, "shard_ids": [0]})

        # the push stream must update the SUBSCRIBED cache (no manual
        # invalidation, max_age large so polling can't mask a miss)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            now_shards = mc.lookup_ec(vid, max_age=3600)
            holders = now_shards.get(0, [])
            if dst in holders and src not in holders:
                break
            time.sleep(0.2)
        else:
            raise AssertionError(
                f"ec cache still stale after move: {now_shards.get(0)}")

        # and a degraded read through any holder still round-trips
        reader = now_shards[0][0]
        resp = requests.get(f"http://{reader}/{a.fid}", timeout=25)
        assert resp.status_code == 200 and resp.content == data
    finally:
        mc.stop()
        env.close()


def test_single_interval_reconstruct_latency_budget():
    """Degraded-read latency budget (VERDICT r2 item 7): recovering ONE
    1MB interval from k=10 shards through the Store's synchronous codec
    must stay in single-digit-milliseconds territory on the CPU path —
    the p50 the bench records (bench.py bench_degraded_read_p50). The
    budget is deliberately loose (CI VMs share cores) but tight enough
    to catch an accidental O(n^2) or a fallen-off fast path."""
    import time

    import numpy as np

    from seaweedfs_tpu.ec.backend import ReedSolomon
    from seaweedfs_tpu.ops import rs_matrix

    rs = ReedSolomon(10, 4, backend="auto")
    present = [i for i in range(14) if i != 0]
    rows, inputs = rs_matrix.recovery_rows(10, 4, present[:10], [0])
    shards = np.random.default_rng(0).integers(
        0, 256, (10, 1 << 20), dtype=np.uint8)
    rs.backend.coded_matmul(rows, shards)  # warm
    lats = []
    for _ in range(7):
        t0 = time.perf_counter()
        rs.backend.coded_matmul(rows, shards)
        lats.append(time.perf_counter() - t0)
    p50_ms = sorted(lats)[len(lats) // 2] * 1000
    assert p50_ms < 50, f"1MB reconstruct p50 {p50_ms:.1f}ms over budget"
