"""EC file-level golden tests — the port of the reference's
TestEncodingDecoding semantics (/root/reference/weed/storage/
erasure_coding/ec_test.go:21): encode a real volume fixture, validate
shard-interval reads against whole-file reads, rebuild lost shards
bit-for-bit, and round-trip decode. Uses small block sizes so the
large/small region transition is exercised without GB files.
"""
import os

import numpy as np
import pytest

from seaweedfs_tpu.ec import geometry as geo
from seaweedfs_tpu.ec.backend import ReedSolomon
from seaweedfs_tpu.ec.decoder import write_dat_file
from seaweedfs_tpu.ec.encoder import (rebuild_ec_files, verify_ec_files,
                                      write_ec_files, write_sorted_ecx)
from seaweedfs_tpu.storage import idx as idxmod
from seaweedfs_tpu.storage import needle as ndl
from seaweedfs_tpu.storage.volume import Volume

LB = 4096   # test large block
SB = 512    # test small block


@pytest.fixture()
def fixture_volume(tmp_path):
    """A real volume with a few hundred needles, as the golden input."""
    v = Volume(str(tmp_path), "", 7, create=True)
    rng = np.random.default_rng(1234)
    for i in range(300):
        size = int(rng.integers(1, 400))
        v.append_needle(ndl.Needle(id=i + 1, cookie=int(rng.integers(0, 2**32)),
                                   data=rng.bytes(size)))
    v.close()
    return str(tmp_path / "7")


def _encode(base, backend="numpy"):
    write_ec_files(base, backend=backend, large_block=LB, small_block=SB,
                   chunk=2048)


class TestRowLayout:
    def test_small_only(self):
        n_large, n_small = geo.row_layout(100, LB, SB)
        assert n_large == 0 and n_small == 1

    def test_exact_small_row(self):
        assert geo.row_layout(SB * 10, LB, SB) == (0, 1)
        assert geo.row_layout(SB * 10 + 1, LB, SB) == (0, 2)

    def test_large_transition(self):
        # == one large row stays small (reference's strict >)
        assert geo.row_layout(LB * 10, LB, SB)[0] == 0
        assert geo.row_layout(LB * 10 + 1, LB, SB)[0] == 1

    def test_shard_size(self):
        dat = LB * 10 + SB * 3 + 17
        n_large, n_small = geo.row_layout(dat, LB, SB)
        assert geo.shard_file_size(dat, LB, SB) == n_large * LB + n_small * SB


class TestLocate:
    """Interval math vs a brute-force shard-layout simulation
    (reference TestLocateData, ec_test.go:192)."""

    @pytest.mark.parametrize("dat_size", [1, 100, SB * 10, SB * 10 + 1,
                                          LB * 10 + 1, LB * 10 + SB * 7 + 99,
                                          LB * 20 + 5])
    def test_locate_against_simulation(self, dat_size):
        rng = np.random.default_rng(dat_size)
        dat = rng.integers(0, 256, dat_size).astype(np.uint8)
        shards = _simulate_shards(dat, LB, SB)
        for _ in range(20):
            off = int(rng.integers(0, dat_size))
            size = int(rng.integers(1, min(3 * SB, dat_size - off) + 1))
            got = bytearray()
            for iv in geo.locate(dat_size, off, size, LB, SB):
                sid, s_off = iv.to_shard_and_offset(LB, SB)
                got += shards[sid][s_off:s_off + iv.size].tobytes()
            assert bytes(got) == dat[off:off + size].tobytes(), (off, size)


def _simulate_shards(dat: np.ndarray, lb: int, sb: int) -> list[np.ndarray]:
    """Brute-force the encode layout: walk rows exactly like the encoder
    and slice blocks into shard buffers."""
    n_large, n_small = geo.row_layout(len(dat), lb, sb)
    shard_len = n_large * lb + n_small * sb
    shards = [np.zeros(shard_len, dtype=np.uint8) for _ in range(10)]
    pos = 0
    out_off = 0
    for block, rows in ((lb, n_large), (sb, n_small)):
        for _ in range(rows):
            for i in range(10):
                chunk = dat[pos:pos + block]
                shards[i][out_off:out_off + len(chunk)] = chunk
                pos += block
            out_off += block
    return shards


class TestEncodeRebuildDecode:
    def test_shard_reads_match_dat(self, fixture_volume):
        base = fixture_volume
        _encode(base)
        dat_size = os.path.getsize(base + ".dat")
        dat = np.fromfile(base + ".dat", dtype=np.uint8)
        shards = [np.fromfile(base + geo.shard_ext(i), dtype=np.uint8)
                  for i in range(10)]
        assert all(len(s) == geo.shard_file_size(dat_size, LB, SB)
                   for s in shards)
        rng = np.random.default_rng(0)
        for _ in range(50):
            off = int(rng.integers(0, dat_size))
            size = int(rng.integers(1, min(2000, dat_size - off) + 1))
            got = bytearray()
            for iv in geo.locate(dat_size, off, size, LB, SB):
                sid, s_off = iv.to_shard_and_offset(LB, SB)
                got += shards[sid][s_off:s_off + iv.size].tobytes()
            assert bytes(got) == dat[off:off + size].tobytes()

    def test_parity_verifies(self, fixture_volume):
        _encode(fixture_volume)
        assert verify_ec_files(fixture_volume, chunk=2048)

    def test_rebuild_bit_for_bit(self, fixture_volume):
        base = fixture_volume
        _encode(base)
        originals = {i: open(base + geo.shard_ext(i), "rb").read()
                     for i in range(14)}
        # destroy 4 shards (2 data, 2 parity)
        for i in (0, 7, 10, 13):
            os.remove(base + geo.shard_ext(i))
        rebuilt = rebuild_ec_files(base, chunk=1536)
        assert sorted(rebuilt) == [0, 7, 10, 13]
        for i in (0, 7, 10, 13):
            assert open(base + geo.shard_ext(i), "rb").read() == originals[i], i

    def test_rebuild_too_many_missing(self, fixture_volume):
        base = fixture_volume
        _encode(base)
        for i in range(5):
            os.remove(base + geo.shard_ext(i))
        # 9 shards left < 10
        with pytest.raises(ValueError):
            rebuild_ec_files(base)

    def test_decode_back_to_dat(self, fixture_volume):
        base = fixture_volume
        _encode(base)
        original = open(base + ".dat", "rb").read()
        os.remove(base + ".dat")
        os.remove(base + geo.shard_ext(3))  # also exercise rebuild-on-decode
        write_dat_file(base, len(original), LB, SB)
        assert open(base + ".dat", "rb").read() == original

    def test_needle_reads_through_shards(self, fixture_volume):
        """End-to-end: locate each indexed needle in the shards and parse
        it — the EC read path's core loop (store_ec.go:136)."""
        base = fixture_volume
        _encode(base)
        write_sorted_ecx(base)
        dat_size = os.path.getsize(base + ".dat")
        shards = [np.fromfile(base + geo.shard_ext(i), dtype=np.uint8)
                  for i in range(10)]
        from seaweedfs_tpu.storage import types as t
        count = 0
        for e in idxmod.iter_entries(base + ".ecx"):
            if not t.size_is_valid(e.size):
                continue
            disk = ndl.disk_size(e.size)
            got = bytearray()
            for iv in geo.locate(dat_size, t.offset_to_actual(e.offset),
                                 disk, LB, SB):
                sid, s_off = iv.to_shard_and_offset(LB, SB)
                got += shards[sid][s_off:s_off + iv.size].tobytes()
            n = ndl.Needle.from_bytes(bytes(got))
            assert n.id == e.key
            count += 1
        assert count == 300

    def test_jax_backend_encode_identical(self, fixture_volume, tmp_path):
        """CPU and TPU(jax) backends must produce byte-identical shards."""
        base = fixture_volume
        _encode(base, backend="numpy")
        cpu_shards = {i: open(base + geo.shard_ext(i), "rb").read()
                      for i in range(14)}
        for i in range(14):
            os.remove(base + geo.shard_ext(i))
        _encode(base, backend="jax")
        for i in range(14):
            assert open(base + geo.shard_ext(i), "rb").read() == \
                cpu_shards[i], i
