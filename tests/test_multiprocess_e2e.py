"""Multi-process end-to-end: real OS processes via the CLI — the
single-host analogue of the reference's docker-compose cluster tests
(docker/compose/local-cluster-compose.yml, e2e.yml): master + two
volume servers + filer + s3 as separate processes, exercised through
their public interfaces only, then torn down.
"""
import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest
import requests

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def wait_http(url, timeout=30):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            requests.get(url, timeout=1)
            return
        except requests.RequestException as e:
            last = e
            time.sleep(0.15)
    raise TimeoutError(f"{url} never came up: {last}")


class Procs:
    def __init__(self):
        self.procs: list[subprocess.Popen] = []
        self.env = dict(os.environ, PYTHONPATH=REPO)

    def spawn(self, *argv) -> subprocess.Popen:
        p = subprocess.Popen(
            [sys.executable, "-m", "seaweedfs_tpu", *argv],
            env=self.env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        self.procs.append(p)
        return p

    def stop_all(self):
        for p in reversed(self.procs):
            if p.poll() is None:
                p.send_signal(signal.SIGINT)
        for p in reversed(self.procs):
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    base = tmp_path_factory.mktemp("mp")
    procs = Procs()
    mport, f_port, s_port = free_port(), free_port(), free_port()
    vports = [free_port(), free_port()]
    master = f"http://127.0.0.1:{mport}"
    filer = f"http://127.0.0.1:{f_port}"
    s3 = f"http://127.0.0.1:{s_port}"
    procs.spawn("master", "-port", str(mport),
                "-volumeSizeLimitMB", "64")
    wait_http(f"{master}/cluster/status")
    for i, vp in enumerate(vports):
        d = base / f"vol{i}"
        d.mkdir()
        # python dataplane: the native C++ front serves the data path
        # without recording spans (ROADMAP gap), which would make the
        # volume hop invisible to the trace-collector e2e below
        procs.spawn("volume", "-port", str(vp), "-dir", str(d),
                    "-mserver", f"127.0.0.1:{mport}",
                    "-index", "compact" if i else "memory",
                    "-dataplane", "python")
        wait_http(f"http://127.0.0.1:{vp}/status")
    procs.spawn("filer", "-port", str(f_port), "-master", master,
                "-store", "leveldb",
                "-store.path", str(base / "filerdb"))
    wait_http(f"{filer}/status")
    procs.spawn("s3", "-port", str(s_port), "-filer", filer)
    wait_http(f"{s3}/status")
    # volume servers registered?
    deadline = time.time() + 20
    while time.time() < deadline:
        topo = requests.get(f"{master}/cluster/status").json()["Topology"]
        n = sum(len(r["nodes"]) for dc in topo["datacenters"]
                for r in dc["racks"])
        if n >= 2:
            break
        time.sleep(0.2)
    else:
        raise TimeoutError("volume servers never registered")
    yield {"master": master, "filer": filer, "s3": s3, "procs": procs}
    procs.stop_all()


def test_object_write_read_delete(cluster):
    m = cluster["master"]
    a = requests.get(f"{m}/dir/assign").json()
    url = f"http://{a['url']}/{a['fid']}"
    assert requests.post(url, data=b"cross-process bytes",
                         ).status_code == 201
    assert requests.get(url).content == b"cross-process bytes"
    assert requests.delete(url).status_code in (200, 202, 204)
    assert requests.get(url).status_code == 404


def test_filer_and_s3_roundtrip(cluster):
    f, s3 = cluster["filer"], cluster["s3"]
    body = b"filer through real processes\n" * 100
    assert requests.post(f"{f}/proj/readme.txt", data=body,
                         headers={"Content-Type": "text/plain"},
                         ).status_code == 201
    assert requests.get(f"{f}/proj/readme.txt").content == body
    requests.put(f"{s3}/artifacts")
    requests.put(f"{s3}/artifacts/build.log", data=b"ok\n" * 500)
    got = requests.get(f"{s3}/artifacts/build.log")
    assert got.content == b"ok\n" * 500
    listing = requests.get(f"{s3}/artifacts").text
    assert "build.log" in listing


def test_shell_against_real_cluster(cluster):
    env = dict(os.environ, PYTHONPATH=REPO)
    script = (
        "import sys; sys.path.insert(0, %r)\n"
        "from seaweedfs_tpu.shell.env import CommandEnv\n"
        "from seaweedfs_tpu.shell.repl import run_command\n"
        "env = CommandEnv(%r, filer_url=%r)\n"
        "print(len(run_command(env, 'volume.list')))\n"
        "print(run_command(env, 'cluster.check')['nodes'] >= 2)\n"
    ) % (REPO, cluster["master"], cluster["filer"])
    out = subprocess.run([sys.executable, "-c", script],
                         capture_output=True, text=True, env=env,
                         timeout=60)
    assert out.returncode == 0, out.stderr
    lines = out.stdout.strip().splitlines()
    assert int(lines[0]) >= 1
    assert lines[1] == "True"


def test_observability_plane_collects_cross_process_trace(cluster):
    """One S3 PUT through real processes -> a single stitched trace on
    the master with spans from >= 3 distinct processes, zero span-push
    drops at the default sample rate, a valid OTLP rendering, and a
    federated /cluster/metrics exposition labeled per instance."""
    m, s3 = cluster["master"], cluster["s3"]
    requests.put(f"{s3}/tracebkt")
    requests.put(f"{s3}/tracebkt/obj.bin", data=b"observe me" * 256)
    requests.get(f"{s3}/tracebkt/obj.bin")

    # span pushers flush every ~2s; wait for a trace that crossed the
    # gateway, the filer and a volume server
    hit = None
    deadline = time.time() + 30
    while time.time() < deadline and hit is None:
        body = requests.get(f"{m}/cluster/traces",
                            params={"limit": 100}, timeout=5).json()
        for t in body["traces"]:
            if {"s3", "filer", "volume"} <= set(t["services"]):
                hit = t
                break
        if hit is None:
            time.sleep(0.3)
    assert hit is not None, body["traces"]
    assert len(hit["instances"]) >= 3  # distinct OS processes

    # the stitched tree shares one trace id and chains across hops
    tree = requests.get(f"{m}/cluster/traces",
                        params={"trace_id": hit["trace_id"]},
                        timeout=5).json()
    assert tree["spans"] == hit["spans"]

    def walk(nodes):
        for n in nodes:
            yield n
            yield from walk(n.get("children", []))

    flat = list(walk(tree["tree"]))
    assert {s["trace_id"] for s in flat} == {hit["trace_id"]}
    # at least one hop actually nested under a parent
    assert any(n.get("children") for n in flat)

    # default sample rate keeps everything: real loss must be zero
    obs = body["observability"]
    assert obs["Pushers"], obs
    for inst, st in obs["Pushers"].items():
        assert st["SpansDropped"] == 0, (inst, st)
        assert st["SpansReceived"] > 0

    # OTLP/JSON rendering of the same trace
    otlp = requests.get(f"{m}/cluster/traces",
                        params={"format": "otlp",
                                "trace_id": hit["trace_id"]},
                        timeout=5).json()
    spans = [s for rs in otlp["resourceSpans"]
             for ss in rs["scopeSpans"] for s in ss["spans"]]
    assert len(spans) == hit["spans"]
    svc = set()
    for rs in otlp["resourceSpans"]:
        attrs = {a["key"]: a["value"]["stringValue"]
                 for a in rs["resource"]["attributes"]}
        svc.add(attrs["service.name"])
    assert {"s3", "filer", "volume"} <= svc
    for s in spans:
        assert s["traceId"] == hit["trace_id"]
        assert s["startTimeUnixNano"].isdigit()  # uint64 as string
        assert s["kind"] in (1, 2, 3)

    # federated metrics: merged series from every registered process
    text = requests.get(f"{m}/cluster/metrics", timeout=15).text
    instances = set()
    for line in text.splitlines():
        # skip the master's own federation gauges: they carry instance
        # labels for *other* nodes and would mask a failed scrape
        if line.startswith("#") or line.startswith("cluster_"):
            continue
        if 'instance="' in line:
            instances.add(line.split('instance="', 1)[1].split('"')[0])
    # master + 2 volume servers + filer + s3 gateway
    assert len(instances) >= 5, instances
    fams = [ln.split()[2] for ln in text.splitlines()
            if ln.startswith("# TYPE ")]
    assert len(fams) == len(set(fams))  # one TYPE line per family


def test_benchmark_cli(cluster):
    env = dict(os.environ, PYTHONPATH=REPO)
    out = subprocess.run(
        [sys.executable, "-m", "seaweedfs_tpu", "benchmark",
         "-master", cluster["master"], "-n", "50", "-size", "512",
         "-c", "4"],
        capture_output=True, text=True, env=env, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "req/s" in out.stdout or "write" in out.stdout.lower()
