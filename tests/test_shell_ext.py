"""Shell commands closing the registry gap with the reference:
fs.cd/pwd, fs.meta.cat/changeVolumeId/notify, mount.configure,
volume.configure.replication / deleteEmpty / server.leave / tier.move /
vacuum.disable, cluster.raft.add/remove, s3.bucket.quota(.enforce),
s3.clean.uploads, remote.mount.buckets (weed/shell command registry,
SURVEY.md section 2.9).
"""
import json
import time

import pytest
import requests

from seaweedfs_tpu.server.cluster import Cluster
from seaweedfs_tpu.shell import (commands_fs, commands_remote,
                                 commands_s3, commands_volume, repl)
from seaweedfs_tpu.shell.env import CommandEnv, ShellError


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = Cluster(str(tmp_path_factory.mktemp("shell_ext")),
                n_volume_servers=2, volume_size_limit=4 << 20,
                max_volumes=40, with_filer=True)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def env(cluster):
    e = CommandEnv(cluster.master_url, filer_url=cluster.filer_url)
    e.acquire_lock()
    yield e
    e.close()


def put(cluster, path: str, data: bytes) -> None:
    r = requests.post(f"{cluster.filer_url}{path}", data=data)
    assert r.status_code < 300, (path, r.status_code)


class TestFsCdPwd:
    def test_cd_pwd_relative_resolution(self, cluster, env):
        put(cluster, "/wd/a/f.txt", b"rel")
        assert commands_fs.fs_pwd(env) == "/"
        assert commands_fs.fs_cd(env, "/wd") == "/wd"
        assert commands_fs.fs_pwd(env) == "/wd"
        # relative paths resolve under the cwd through the repl
        out = repl.run_command(env, "fs.cat a/f.txt")
        assert out == "rel"
        assert repl.run_command(env, "fs.pwd") == "/wd"
        repl.run_command(env, "fs.cd a")
        assert env.cwd == "/wd/a"
        repl.run_command(env, "fs.cd ..")
        assert env.cwd == "/wd"
        repl.run_command(env, "fs.cd /")
        assert env.cwd == "/"

    def test_cd_to_file_fails(self, cluster, env):
        with pytest.raises(ShellError):
            commands_fs.fs_cd(env, "/wd/a/f.txt")


class TestFsMetaExt:
    def test_meta_cat(self, cluster, env):
        put(cluster, "/mc/x.bin", b"y" * 100)
        meta = commands_fs.fs_meta_cat(env, "/mc/x.bin")
        assert meta["chunks"] and meta["chunks"][0]["size"] == 100

    def test_change_volume_id_dry_and_apply(self, cluster, env):
        put(cluster, "/cv/f.bin", b"data here")
        meta = commands_fs.fs_meta_cat(env, "/cv/f.bin")
        old_vid = int(meta["chunks"][0]["fid"].partition(",")[0])
        new_vid = old_vid + 100
        dry = commands_fs.fs_meta_change_volume_id(
            env, "/cv", f"{old_vid}:{new_vid}")
        assert dry["entries_rewritten"] == 1 and not dry["applied"]
        # dry run didn't touch anything
        meta2 = commands_fs.fs_meta_cat(env, "/cv/f.bin")
        assert meta2["chunks"][0]["fid"].startswith(f"{old_vid},")
        commands_fs.fs_meta_change_volume_id(
            env, "/cv", f"{old_vid}:{new_vid}", apply=True)
        meta3 = commands_fs.fs_meta_cat(env, "/cv/f.bin")
        assert meta3["chunks"][0]["fid"].startswith(f"{new_vid},")
        # map back so the file stays readable for other tests
        commands_fs.fs_meta_change_volume_id(
            env, "/cv", f"{new_vid}:{old_vid}", apply=True)

    def test_bad_mapping_rejected(self, env):
        with pytest.raises(ShellError):
            commands_fs.fs_meta_change_volume_id(env, "/", "abc")

    def test_meta_notify_to_log_queue(self, cluster, env, tmp_path):
        log_path = str(tmp_path / "events.jsonl")
        requests.put(f"{cluster.filer_url}/kv/notification.conf",
                     data=json.dumps({"kind": "log", "path": log_path}))
        put(cluster, "/nt/one.txt", b"1")
        put(cluster, "/nt/two.txt", b"2")
        out = commands_fs.fs_meta_notify(env, "/nt")
        assert out["notified"] == 2
        lines = [json.loads(l) for l in open(log_path)]
        keys = {l["key"] for l in lines}
        assert keys == {"/nt/one.txt", "/nt/two.txt"}


class TestMountConfigure:
    def test_quota_round_trip(self, cluster, env):
        conf = commands_fs.mount_configure(env, dir="/wd", quota_mb=5)
        assert conf["/wd"]["quota_bytes"] == 5 << 20
        assert commands_fs.mount_configure(env)["/wd"]
        conf = commands_fs.mount_configure(env, dir="/wd", quota_mb=0)
        assert "/wd" not in conf


class TestVolumeExt:
    def test_configure_replication(self, cluster, env):
        put(cluster, "/vr/f.txt", b"x" * 50)
        meta = commands_fs.fs_meta_cat(env, "/vr/f.txt")
        vid = int(meta["chunks"][0]["fid"].partition(",")[0])
        out = commands_volume.volume_configure_replication(env, vid,
                                                           "001")
        assert all(r["replication"] == "001" for r in out)
        # survives a reload: verify via the volume server status page
        out2 = commands_volume.volume_configure_replication(env, vid,
                                                            "000")
        assert all(r["replication"] == "000" for r in out2)

    def test_bad_replication_rejected(self, env):
        with pytest.raises(ValueError):
            commands_volume.volume_configure_replication(env, 1, "9z")

    def test_delete_empty(self, cluster, env):
        # grow a fresh collection volume, never write to it
        commands_volume.volume_grow(env, count=1, collection="emptycol")
        before = {v["volume"] for v in commands_volume.volume_list(env)
                  if v.get("server")}
        deleted = commands_volume.volume_delete_empty(env, force=True)
        assert deleted  # at least the fresh empty volume went away
        for d in deleted:
            assert d["volume"] in before

    def test_vacuum_toggle(self, cluster, env):
        out = commands_volume.volume_vacuum_toggle(env, disable=True)
        assert out["vacuum_disabled"] is True
        status = env.master_get("/cluster/status")
        assert status["VacuumDisabled"] is True
        # manual vacuum honors the switch too
        with pytest.raises(ShellError, match="disabled"):
            commands_volume.volume_vacuum(env)
        out = commands_volume.volume_vacuum_toggle(env, disable=False)
        assert out["vacuum_disabled"] is False
        commands_volume.volume_vacuum(env)  # runs again

    def test_dispatch_new_commands(self, cluster, env):
        assert repl.run_command(env, "volume.vacuum.enable")[
            "vacuum_disabled"] is False
        assert isinstance(
            repl.run_command(env, "volume.deleteEmpty -force"), list)


class TestServerLeave:
    def test_leave_removes_from_topology(self, tmp_path_factory):
        c = Cluster(str(tmp_path_factory.mktemp("leave")),
                    n_volume_servers=2, volume_size_limit=4 << 20,
                    with_filer=False)
        try:
            e = CommandEnv(c.master_url)
            e.acquire_lock()
            nodes = e.data_nodes()
            assert len(nodes) == 2
            victim = nodes[0]["url"]
            out = commands_volume.volume_server_leave(e, victim)
            assert out.get("left")
            deadline = time.time() + 10
            while time.time() < deadline:
                left = {n["url"] for n in e.data_nodes()}
                if victim not in left:
                    break
                time.sleep(0.2)
            assert victim not in {n["url"] for n in e.data_nodes()}
        finally:
            c.stop()


class TestS3QuotaAndUploads:
    def test_bucket_quota_set_and_enforce(self, cluster, env):
        requests.post(f"{cluster.filer_url}/buckets/qb/",
                      params={"mkdir": "1"})
        # objects in collection "qb"
        r = requests.post(f"{cluster.filer_url}/buckets/qb/big.bin",
                          params={"collection": "qb"},
                          data=b"z" * (1 << 20))
        assert r.status_code < 300
        out = commands_s3.s3_bucket_quota(env, "qb", quota_mb=0)
        commands_s3.s3_bucket_quota(env, "qb", quota_mb=1)
        info = commands_s3.s3_bucket_quota(env, "qb")
        assert info["quota_bytes"] == 1 << 20
        assert info["used_bytes"] == 1 << 20

        # push over quota and enforce -> volumes readonly
        requests.post(f"{cluster.filer_url}/buckets/qb/more.bin",
                      params={"collection": "qb"}, data=b"z" * 4096)
        res = commands_s3.s3_bucket_quota_enforce(env)
        rec = next(r for r in res if r["bucket"] == "qb")
        assert rec["over"] and rec["volumes"]

        # drop quota -> writable again
        commands_s3.s3_bucket_quota(env, "qb", quota_mb=100)
        res = commands_s3.s3_bucket_quota_enforce(env)
        rec = next(r for r in res if r["bucket"] == "qb")
        assert not rec["over"]

    def test_clearing_quota_releases_readonly_latch(self, cluster,
                                                    env):
        requests.post(f"{cluster.filer_url}/buckets/latch/",
                      params={"mkdir": "1"})
        requests.post(f"{cluster.filer_url}/buckets/latch/f.bin",
                      params={"collection": "latch"}, data=b"q" * 8192)
        commands_s3.s3_bucket_quota(env, "latch", quota_mb=1)
        # force over-quota by shrinking the quota below usage: 8KB used
        from seaweedfs_tpu.shell.commands_fs import _stat
        meta = _stat(env, "/buckets/latch")
        ext = dict(meta.get("extended", {}))
        ext["s3_quota_bytes"] = "4096"
        meta["extended"] = ext
        meta.pop("full_path", None)
        requests.put(f"{cluster.filer_url}/buckets/latch?meta=1",
                     json=meta)
        res = commands_s3.s3_bucket_quota_enforce(env)
        rec = next(r for r in res if r["bucket"] == "latch")
        assert rec["over"] and rec["volumes"]
        vids = rec["volumes"]
        # REMOVE the quota entirely: enforce must release the volumes
        commands_s3.s3_bucket_quota(env, "latch", quota_mb=0)
        res = commands_s3.s3_bucket_quota_enforce(env)
        rec = next(r for r in res if r["bucket"] == "latch")
        assert not rec["over"] and set(rec["volumes"]) == set(vids)
        # latch cleared: bucket drops out of future enforce passes
        res = commands_s3.s3_bucket_quota_enforce(env)
        assert not any(r["bucket"] == "latch" for r in res)

    def test_clean_uploads(self, cluster, env):
        requests.post(f"{cluster.filer_url}/buckets/ub/",
                      params={"mkdir": "1"})
        requests.post(
            f"{cluster.filer_url}/buckets/ub/.uploads/stale123/",
            params={"mkdir": "1"})
        removed = commands_s3.s3_clean_uploads(env, time_ago_seconds=-5)
        assert any("stale123" in p for p in removed)
        listing = requests.get(
            f"{cluster.filer_url}/buckets/ub/.uploads/",
            headers={"Accept": "application/json"})
        names = [e["full_path"] for e in
                 (listing.json().get("entries", [])
                  if listing.status_code == 200 else [])]
        assert not any("stale123" in n for n in names)


class TestRemoteMountBuckets:
    def test_mount_all_buckets(self, cluster, env, tmp_path):
        root = tmp_path / "remote_root"
        for b in ("alpha", "beta", "gamma"):
            (root / b).mkdir(parents=True)
            (root / b / "obj.txt").write_text(f"in {b}")
        commands_remote.remote_configure(env, "store1", type="local",
                                         root=str(root))
        out = commands_remote.remote_mount_buckets(env, "store1")
        assert set(out["mounted"]) == {"alpha", "beta", "gamma"}
        # mounted buckets are browsable through the filer
        got = commands_fs.fs_cat(env, "/buckets/alpha/obj.txt")
        assert got == b"in alpha"

    def test_pattern_filter(self, cluster, env, tmp_path):
        root = tmp_path / "remote_root2"
        for b in ("red", "green", "greed"):
            (root / b).mkdir(parents=True)
        commands_remote.remote_configure(env, "store2", type="local",
                                         root=str(root))
        out = commands_remote.remote_mount_buckets(
            env, "store2", bucket_pattern="gre*")
        assert set(out["mounted"]) == {"green", "greed"}


class TestRaftMembership:
    def test_add_remove_peer_round_trip(self, tmp_path_factory):
        import socket

        from seaweedfs_tpu.server.cluster import ServerThread
        from seaweedfs_tpu.server.master_server import MasterServer
        from seaweedfs_tpu.shell import commands_cluster

        base = tmp_path_factory.mktemp("raft_m")
        socks, ports = [], []
        for _ in range(3):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
        for s in socks:
            s.close()
        peers = [f"127.0.0.1:{p}" for p in ports]
        masters = [MasterServer(pulse_seconds=0.4, me=me, peers=peers,
                                raft_state_dir=str(base), raft_tick=0.6)
                   for me in peers]
        threads = [ServerThread(m.app, port=p).start()
                   for m, p in zip(masters, ports)]
        try:
            leader = None
            deadline = time.time() + 20
            while time.time() < deadline and leader is None:
                for p in peers:
                    try:
                        st = requests.get(f"http://{p}/raft/status",
                                          timeout=2).json()
                        if st["state"] == "leader":
                            leader = p
                    except Exception:
                        pass
                time.sleep(0.1)
            assert leader, "no leader elected"
            e = CommandEnv(f"http://{leader}")
            e.locked = True  # no filer DLM in this fixture
            out = commands_cluster.cluster_raft_change(
                e, "127.0.0.1:59999", add=True)
            assert "127.0.0.1:59999" in out["peers"]
            # the change replicated to followers
            follower = next(p for p in peers if p != leader)
            deadline = time.time() + 10
            while time.time() < deadline:
                st = requests.get(f"http://{follower}/raft/status",
                                  timeout=2).json()
                if "127.0.0.1:59999" in st["peers"]:
                    break
                time.sleep(0.1)
            assert "127.0.0.1:59999" in st["peers"]
            out = commands_cluster.cluster_raft_change(
                e, "127.0.0.1:59999", add=False)
            assert "127.0.0.1:59999" not in out["peers"]

            # the vacuum switch rides the raft log: disabling via the
            # leader must be visible in every follower's status
            r = requests.post(
                f"http://{leader}/vol/vacuum/disable", timeout=10)
            assert r.json()["vacuum_disabled"] is True
            follower = next(p for p in peers if p != leader)
            deadline = time.time() + 10
            seen = False
            while time.time() < deadline and not seen:
                seen = requests.get(
                    f"http://{follower}/cluster/status",
                    timeout=2).json().get("VacuumDisabled", False)
                time.sleep(0.1)
            assert seen, "follower never saw VacuumDisabled"
            requests.post(f"http://{leader}/vol/vacuum/enable",
                          timeout=10)
        finally:
            for t in threads:
                t.stop()
