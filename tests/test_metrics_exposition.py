"""utils/metrics.py renders valid Prometheus text exposition format:
`# TYPE` lines per family, escaped label values, histogram _sum/_count
adjacent to their _bucket series, and a push loop that stops cleanly
and can restart."""
import threading

import pytest

from seaweedfs_tpu.utils import metrics


@pytest.fixture
def clean_registry():
    """Run against an empty registry, restoring whatever other tests
    accumulated (the registry is process-global)."""
    with metrics._lock:
        counters = dict(metrics._counters)
        gauges = dict(metrics._gauges)
        hists = {k: list(v) for k, v in metrics._histograms.items()}
    metrics.reset()
    yield
    with metrics._lock:
        metrics._counters.clear()
        metrics._counters.update(counters)
        metrics._gauges.clear()
        metrics._gauges.update(gauges)
        metrics._histograms.clear()
        metrics._histograms.update(hists)


class TestExpositionFormat:
    def test_golden_render(self, clean_registry):
        metrics.counter_add("demo_requests_total", 2,
                            {"method": "GET"})
        metrics.counter_add("demo_requests_total", 1,
                            {"method": "PUT"})
        metrics.gauge_set("demo_temperature", 36.6)
        metrics.histogram_observe("demo_seconds", 0.0005)
        metrics.histogram_observe("demo_seconds", 0.75)
        metrics.histogram_observe("demo_seconds", 99.0)
        out = metrics.render()
        assert out == (
            "# TYPE demo_requests_total counter\n"
            'demo_requests_total{method="GET"} 2.0\n'
            'demo_requests_total{method="PUT"} 1.0\n'
            "# TYPE demo_temperature gauge\n"
            "demo_temperature 36.6\n"
            "# TYPE demo_seconds histogram\n"
            'demo_seconds_bucket{le="0.001"} 1\n'
            'demo_seconds_bucket{le="0.005"} 1\n'
            'demo_seconds_bucket{le="0.01"} 1\n'
            'demo_seconds_bucket{le="0.05"} 1\n'
            'demo_seconds_bucket{le="0.1"} 1\n'
            'demo_seconds_bucket{le="0.5"} 1\n'
            'demo_seconds_bucket{le="1"} 2\n'
            'demo_seconds_bucket{le="5"} 2\n'
            'demo_seconds_bucket{le="10"} 2\n'
            'demo_seconds_bucket{le="+Inf"} 3\n'
            "demo_seconds_sum 99.7505\n"
            "demo_seconds_count 3.0\n")

    def test_label_value_escaping(self, clean_registry):
        metrics.counter_add("esc_total", 1,
                            {"path": 'a"quoted"\\back\nnl'})
        out = metrics.render()
        assert ('esc_total{path="a\\"quoted\\"\\\\back\\nnl"} 1.0'
                in out)

    def test_type_line_precedes_every_family(self, clean_registry):
        metrics.counter_add("aa_total", 1)
        metrics.gauge_set("bb_gauge", 5)
        metrics.histogram_observe("cc_seconds", 0.2)
        lines = metrics.render().splitlines()
        for family, kind in (("aa_total", "counter"),
                             ("bb_gauge", "gauge"),
                             ("cc_seconds", "histogram")):
            first = min(i for i, ln in enumerate(lines)
                        if ln.startswith(family))
            assert lines[first - 1] == f"# TYPE {family} {kind}"

    def test_histogram_sum_count_adjacent(self, clean_registry):
        # interleaving regression: a counter sorting between
        # "<name>_bucket" and "<name>_sum" must not split the family
        metrics.histogram_observe("h_seconds", 0.002,
                                  {"method": "GET"})
        metrics.histogram_observe("h_seconds", 0.002,
                                  {"method": "PUT"})
        metrics.counter_add("h_seconds_extra_total", 1)
        lines = metrics.render().splitlines()
        for method in ("GET", "PUT"):
            inf = lines.index(
                f'h_seconds_bucket{{le="+Inf",method="{method}"}} 1')
            assert lines[inf + 1].startswith(
                f'h_seconds_sum{{method="{method}"}}')
            assert lines[inf + 2] == \
                f'h_seconds_count{{method="{method}"}} 1.0'
        # the histogram's own _sum/_count never also render as
        # standalone counter families
        assert "# TYPE h_seconds_sum" not in "\n".join(lines)
        assert "# TYPE h_seconds_count" not in "\n".join(lines)

    def test_existing_metric_shapes_survive(self, clean_registry):
        # the substrings the rest of the test-suite greps for
        metrics.counter_add("s3_requests_total", 1,
                            {"method": "PUT", "code": "200"})
        metrics.histogram_observe("s3_request_seconds", 0.01,
                                  {"method": "PUT"})
        out = metrics.render()
        assert 's3_requests_total{code="200",method="PUT"}' in out
        assert "s3_request_seconds_count" in out


class TestPushLifecycle:
    def test_stop_joins_and_restart_works(self):
        before = threading.active_count()
        # unroutable port: the loop's PUT fails fast and is swallowed
        metrics.start_push("127.0.0.1:1", job="t",
                           interval_seconds=0.05)
        t1 = metrics._push_thread
        assert t1 is not None and t1.is_alive()
        metrics.stop_push()
        assert metrics._push_thread is None
        assert not t1.is_alive()  # joined, not leaked
        # a second start after stop must spin up a fresh pusher
        metrics.start_push("127.0.0.1:1", job="t",
                           interval_seconds=0.05)
        t2 = metrics._push_thread
        assert t2 is not None and t2.is_alive() and t2 is not t1
        metrics.stop_push()
        assert not t2.is_alive()
        assert threading.active_count() <= before + 1

    def test_double_start_is_noop_while_running(self):
        metrics.start_push("127.0.0.1:1", job="t",
                           interval_seconds=0.05)
        t1 = metrics._push_thread
        metrics.start_push("127.0.0.1:1", job="t",
                           interval_seconds=0.05)
        assert metrics._push_thread is t1
        metrics.stop_push()
        assert not t1.is_alive()
