"""Cluster observability plane: deterministic head sampling, the span
pusher, the master's trace collector + tail-based retention, OTLP/JSON
rendering, and metrics federation (master/collector.py,
rpc/trace_push.py, utils/tracing.py)."""
import random
import time

import pytest
import requests

from seaweedfs_tpu.master.collector import (MAX_SPANS_PER_TRACE,
                                            OTLP_SCOPE, MetricsFederator,
                                            SpanCollector, _family_of,
                                            _inject_instance)
from seaweedfs_tpu.rpc.http import ServerThread
from seaweedfs_tpu.rpc.trace_push import SpanPusher
from seaweedfs_tpu.server.master_server import MasterServer
from seaweedfs_tpu.utils import metrics, tracing


def _rec(trace_id=None, span_id=None, parent_id="", service="s3",
         name="op", kind="server", status="200", start=None,
         duration=0.01, peer=""):
    return {
        "trace_id": trace_id or tracing.new_trace_id(),
        "span_id": span_id or tracing.new_span_id(),
        "parent_id": parent_id,
        "service": service,
        "name": name,
        "kind": kind,
        "peer": peer,
        "start": time.time() if start is None else start,
        "duration": duration,
        "status": status,
    }


def _counter(name: str) -> float:
    with metrics._lock:
        return sum(v for (n, _), v in metrics._counters.items()
                   if n == name)


@pytest.fixture
def sample_config():
    """Snapshot/restore the global head-sampling rate."""
    rate = tracing.sample_rate()
    yield
    tracing.configure(sample_rate=rate)


# ---------------------------------------------------------------------
# head sampling
# ---------------------------------------------------------------------


class TestSampler:
    def test_deterministic_across_calls(self):
        rng = random.Random(7)
        ids = ["%032x" % rng.getrandbits(128) for _ in range(64)]
        first = [tracing.sample_decision(t, 0.5) for t in ids]
        again = [tracing.sample_decision(t, 0.5) for t in ids]
        assert first == again

    def test_kept_at_low_rate_kept_at_higher_rate(self):
        # the verdict is a threshold on the id's low bits, so the kept
        # set only grows with the rate — a trace sampled at one hop is
        # sampled at every hop even if rates are skewed upward
        rng = random.Random(11)
        ids = ["%032x" % rng.getrandbits(128) for _ in range(256)]
        low = {t for t in ids if tracing.sample_decision(t, 0.2)}
        high = {t for t in ids if tracing.sample_decision(t, 0.7)}
        assert low <= high

    def test_rate_extremes(self):
        tid = tracing.new_trace_id()
        assert tracing.sample_decision(tid, 1.0) is True
        assert tracing.sample_decision(tid, 0.0) is False

    def test_malformed_id_is_kept(self):
        # losing malformed ids would hide bugs, not traffic
        assert tracing.sample_decision("not-hex-at-all", 0.001) is True
        assert tracing.sample_decision("", 0.001) is True

    def test_fraction_tracks_rate(self):
        rng = random.Random(3)
        ids = ["%032x" % rng.getrandbits(128) for _ in range(4000)]
        kept = sum(tracing.sample_decision(t, 0.5) for t in ids)
        assert 0.42 < kept / len(ids) < 0.58

    def test_configure_clamps(self, sample_config):
        tracing.configure(sample_rate=7.0)
        assert tracing.sample_rate() == 1.0
        tracing.configure(sample_rate=-3.0)
        assert tracing.sample_rate() == 0.0


# ---------------------------------------------------------------------
# collector: stitching + tail-based retention
# ---------------------------------------------------------------------


class TestCollector:
    def test_cross_instance_stitching(self):
        c = SpanCollector(max_traces=64)
        tid = tracing.new_trace_id()
        root = _rec(trace_id=tid, service="s3", name="put_object")
        child = _rec(trace_id=tid, parent_id=root["span_id"],
                     service="filer", name="write", kind="server")
        grand = _rec(trace_id=tid, parent_id=child["span_id"],
                     service="volume", name="needle_write")
        c.add_spans("s3:8333", "s3", [root])
        c.add_spans("vol:8080", "volume", [grand])  # out of order
        c.add_spans("filer:8888", "filer", [child])
        got = c.get_trace(tid)
        assert got is not None and got["spans"] == 3
        assert len(got["tree"]) == 1
        r = got["tree"][0]
        assert r["name"] == "put_object" and r["instance"] == "s3:8333"
        assert r["children"][0]["name"] == "write"
        assert r["children"][0]["children"][0]["name"] == "needle_write"

        summaries = c.list_traces()
        assert summaries[0]["trace_id"] == tid
        assert summaries[0]["services"] == ["filer", "s3", "volume"]
        assert set(summaries[0]["instances"]) == \
            {"s3:8333", "filer:8888", "vol:8080"}
        assert summaries[0]["error"] is False

    def test_tail_retention_pins_error_and_slow(self):
        c = SpanCollector(max_traces=16, slow_threshold=1.0)
        bad = _rec(status="error")
        slow = _rec(duration=5.0)
        c.add_spans("i", "s3", [bad, slow])
        for _ in range(40):
            c.add_spans("i", "s3", [_rec()])
        assert len(c._traces) <= 16
        assert c.get_trace(bad["trace_id"]) is not None
        assert c.get_trace(slow["trace_id"]) is not None
        assert c._evicted > 0
        pinned = [s for s in c.list_traces(limit=16) if s["pinned"]]
        assert {p["trace_id"] for p in pinned} >= \
            {bad["trace_id"], slow["trace_id"]}

    def test_all_pinned_still_bounded(self):
        c = SpanCollector(max_traces=16)
        for _ in range(25):
            c.add_spans("i", "s3", [_rec(status="error")])
        assert len(c._traces) == 16

    def test_runaway_trace_span_cap(self):
        c = SpanCollector(max_traces=64)
        tid = tracing.new_trace_id()
        for _ in range(MAX_SPANS_PER_TRACE + 20):
            c.add_spans("i", "s3", [_rec(trace_id=tid)])
        assert c.get_trace(tid)["spans"] == MAX_SPANS_PER_TRACE

    def test_ignores_junk_spans(self):
        c = SpanCollector(max_traces=64)
        assert c.add_spans("i", "s3", [{"no": "trace_id"},
                                       {"trace_id": ""},
                                       {"trace_id": 42}]) == 0
        assert len(c._traces) == 0

    def test_drain_otlp_pending_waits_for_idle(self):
        c = SpanCollector(max_traces=64)
        r = _rec()
        c.add_spans("i", "s3", [r])
        # freshly-touched traces are deferred so late spans still land
        assert c.drain_otlp_pending(min_idle=60.0) == []
        assert c.drain_otlp_pending(min_idle=0.0) == [r["trace_id"]]
        # drained ids do not come back
        assert c.drain_otlp_pending(min_idle=0.0) == []

    def test_observability_block(self):
        c = SpanCollector(max_traces=64)
        c.add_spans("vol:8080", "volume", [_rec()], dropped=3)
        obs = c.observability()
        assert obs["TraceStoreTraces"] == 1
        assert obs["TraceStoreSpans"] == 1
        st = obs["Pushers"]["vol:8080"]
        assert st["Service"] == "volume"
        assert st["SpansReceived"] == 1 and st["SpansDropped"] == 3
        assert st["PushLagSeconds"] is not None


# ---------------------------------------------------------------------
# OTLP rendering
# ---------------------------------------------------------------------


class TestOtlp:
    def test_shape_and_field_encoding(self):
        c = SpanCollector(max_traces=64)
        tid = tracing.new_trace_id()
        root = _rec(trace_id=tid, service="s3", name="put", start=100.0,
                    duration=0.25, status="201", peer="10.0.0.9")
        child = _rec(trace_id=tid, parent_id=root["span_id"],
                     service="filer", kind="client", status="error")
        c.add_spans("s3:1", "s3", [root])
        c.add_spans("filer:2", "filer", [child])
        doc = c.to_otlp(trace_ids=[tid])
        rs = doc["resourceSpans"]
        assert len(rs) == 2  # one per (service, instance)
        by_service = {}
        for entry in rs:
            attrs = {a["key"]: a["value"]["stringValue"]
                     for a in entry["resource"]["attributes"]}
            assert "service.instance.id" in attrs
            scope = entry["scopeSpans"][0]
            assert scope["scope"]["name"] == OTLP_SCOPE
            by_service[attrs["service.name"]] = scope["spans"]
        s = by_service["s3"][0]
        assert s["traceId"] == tid and len(s["spanId"]) == 16
        assert s["kind"] == 2  # server
        # uint64 nanos are strings per the proto3 JSON mapping
        assert s["startTimeUnixNano"] == str(int(100.0 * 1e9))
        assert int(s["endTimeUnixNano"]) - int(s["startTimeUnixNano"]) \
            == int(0.25 * 1e9)
        assert s["status"] == {"code": 0}
        assert "parentSpanId" not in s
        attrs = {a["key"]: a["value"]["stringValue"]
                 for a in s["attributes"]}
        assert attrs["http.response.status_code"] == "201"
        assert attrs["net.peer.name"] == "10.0.0.9"
        f = by_service["filer"][0]
        assert f["kind"] == 3  # client
        assert f["status"] == {"code": 2}  # error
        assert f["parentSpanId"] == root["span_id"]

    def test_unknown_kind_maps_internal(self):
        c = SpanCollector(max_traces=64)
        r = _rec(kind="mystery")
        c.add_spans("i", "s3", [r])
        doc = c.to_otlp(trace_ids=[r["trace_id"]])
        assert doc["resourceSpans"][0]["scopeSpans"][0]["spans"][0][
            "kind"] == 1

    def test_limit_and_unknown_ids(self):
        c = SpanCollector(max_traces=64)
        for _ in range(5):
            c.add_spans("i", "s3", [_rec()])
        assert len(c.to_otlp(limit=2)["resourceSpans"][0]["scopeSpans"]
                   [0]["spans"]) == 2
        assert c.to_otlp(trace_ids=["f" * 32]) == {"resourceSpans": []}


# ---------------------------------------------------------------------
# metrics federation
# ---------------------------------------------------------------------


class TestFederation:
    def test_inject_instance(self):
        assert _inject_instance('up 1', 'a:1') == 'up{instance="a:1"} 1'
        assert _inject_instance('req_total{code="200"} 5', 'a:1') == \
            'req_total{instance="a:1",code="200"} 5'
        # nested federation: already-labeled series pass through
        line = 'up{instance="b:2"} 1'
        assert _inject_instance(line, 'a:1') == line
        assert _inject_instance('junk{unterminated 1', 'a:1') is None
        assert _inject_instance('lonely', 'a:1') is None

    def test_family_of_folds_histogram_components(self):
        assert _family_of('lat_seconds_bucket{le="1"} 3') == "lat_seconds"
        assert _family_of("lat_seconds_sum 1.5") == "lat_seconds"
        assert _family_of("lat_seconds_count 3") == "lat_seconds"
        assert _family_of('req_total{code="200"} 5') == "req_total"

    def test_merged_dedupes_type_lines(self):
        fed = MetricsFederator(master=None)
        text = ("# TYPE req_total counter\n"
                'req_total{code="200"} 5\n')
        now = time.time()
        fed._scraped = {
            "a:1": {"text": text, "ts": now, "error": ""},
            "b:2": {"text": text, "ts": now, "error": ""},
        }
        out = fed.merged()
        assert out.count("# TYPE req_total counter") == 1
        assert 'req_total{instance="a:1",code="200"} 5' in out
        assert 'req_total{instance="b:2",code="200"} 5' in out
        # staleness gauges land in the live registry per instance
        with metrics._lock:
            keys = {k for k in metrics._gauges
                    if k[0] == "cluster_scrape_staleness_seconds"}
        assert (("cluster_scrape_staleness_seconds",
                 (("instance", "a:1"),)) in keys)

    def test_merged_never_scraped_is_negative_staleness(self):
        fed = MetricsFederator(master=None)
        fed._scraped = {"gone:9": {"text": "", "ts": 0.0,
                                   "error": "boom"}}
        fed.merged()
        with metrics._lock:
            v = metrics._gauges.get(
                ("cluster_scrape_staleness_seconds",
                 (("instance", "gone:9"),)))
        assert v == -1
        assert fed.observability()["gone:9"]["Error"] == "boom"


# ---------------------------------------------------------------------
# pusher + master endpoints (in-process master)
# ---------------------------------------------------------------------


@pytest.fixture(scope="module")
def master_srv():
    m = MasterServer(pulse_seconds=0.4, scrape_interval=3600.0)
    t = ServerThread(m.app).start()
    yield m, t
    t.stop()


class TestMasterEndpoints:
    def test_push_then_query(self, master_srv):
        m, t = master_srv
        tid = tracing.new_trace_id()
        root = _rec(trace_id=tid, service="s3", name="edge")
        child = _rec(trace_id=tid, parent_id=root["span_id"],
                     service="filer")
        r = requests.post(f"{t.url}/cluster/traces/push", json={
            "instance": "push:1", "service": "s3",
            "spans": [root, child], "dropped": 2}, timeout=5)
        assert r.status_code == 200 and r.json()["accepted"] == 2

        body = requests.get(f"{t.url}/cluster/traces", timeout=5).json()
        assert any(s["trace_id"] == tid for s in body["traces"])
        assert body["observability"]["Pushers"]["push:1"][
            "SpansDropped"] == 2

        tree = requests.get(f"{t.url}/cluster/traces",
                            params={"trace_id": tid}, timeout=5).json()
        assert tree["spans"] == 2
        assert tree["tree"][0]["children"][0]["service"] == "filer"

        otlp = requests.get(f"{t.url}/cluster/traces",
                            params={"format": "otlp",
                                    "trace_id": tid}, timeout=5).json()
        spans = [s for rs in otlp["resourceSpans"]
                 for ss in rs["scopeSpans"] for s in ss["spans"]]
        assert {s["traceId"] for s in spans} == {tid}

    def test_push_rejects_bad_bodies(self, master_srv):
        _, t = master_srv
        url = f"{t.url}/cluster/traces/push"
        assert requests.post(url, data=b"not json",
                             timeout=5).status_code == 400
        assert requests.post(url, json={"spans": "nope"},
                             timeout=5).status_code == 400
        assert requests.get(f"{t.url}/cluster/traces",
                            params={"trace_id": "f" * 32},
                            timeout=5).status_code == 404

    def test_cluster_status_observability_block(self, master_srv):
        _, t = master_srv
        obs = requests.get(f"{t.url}/cluster/status",
                           timeout=5).json()["Observability"]
        assert "TraceStoreTraces" in obs
        assert "Pushers" in obs and "Federation" in obs

    def test_cluster_metrics_merged(self, master_srv):
        _, t = master_srv
        body = requests.get(f"{t.url}/cluster/metrics", timeout=10).text
        # the master's own registry rides along, instance-labeled
        assert 'instance="master"' in body
        # one # TYPE line per family even with self + scrapes merged
        fams = [ln.split()[2] for ln in body.splitlines()
                if ln.startswith("# TYPE ")]
        assert len(fams) == len(set(fams))

    def test_master_own_spans_reach_collector(self, master_srv):
        m, t = master_srv
        # any traced master endpoint feeds the in-process sink
        requests.get(f"{t.url}/dir/status", timeout=5)
        deadline = time.time() + 5
        while time.time() < deadline:
            if any("master" in s["services"]
                   for s in m.collector.list_traces(limit=50)):
                break
            time.sleep(0.05)
        assert any("master" in s["services"]
                   for s in m.collector.list_traces(limit=50))


class TestSpanPusher:
    def test_end_to_end_push(self, master_srv, sample_config):
        m, t = master_srv
        tracing.configure(sample_rate=1.0)
        sp = SpanPusher(t.url, "unittest", "unit:1", interval=0.2)
        sp.start()
        try:
            pushed0 = _counter("trace_spans_pushed_total")
            with tracing.span("unit-root", service="unittest",
                              kind="server") as rec:
                pass
            tid = rec["trace_id"]
            # the master's in-process sink sees the span immediately;
            # wait for the HTTP push specifically
            deadline = time.time() + 10
            while time.time() < deadline:
                if "unit:1" in m.collector.observability()["Pushers"]:
                    break
                time.sleep(0.05)
            assert m.collector.get_trace(tid) is not None
            assert _counter("trace_spans_pushed_total") > pushed0
            st = m.collector.observability()["Pushers"]["unit:1"]
            assert st["Service"] == "unittest"
            assert st["SpansDropped"] == 0
        finally:
            sp.stop()

    def test_queue_overflow_counts_drops_and_recovers(self, master_srv,
                                                      sample_config):
        m, t = master_srv
        tracing.configure(sample_rate=1.0)
        url = {"u": "http://127.0.0.1:1"}  # unreachable
        sp = SpanPusher(lambda: url["u"], "droptest", "drop:1",
                        batch_size=4, queue_max=4)
        dropped0 = _counter("trace_spans_dropped_total")
        for _ in range(10):
            sp._enqueue(_rec(service="droptest"))
        assert len(sp._q) == 4
        assert _counter("trace_spans_dropped_total") - dropped0 == 6
        assert sp.flush() is False  # master away: batch requeues
        assert len(sp._q) == 4
        url["u"] = t.url  # master is back
        assert sp.flush() is True
        assert len(sp._q) == 0
        st = m.collector.observability()["Pushers"]["drop:1"]
        assert st["SpansReceived"] == 4
        assert st["SpansDropped"] == 6  # loss is reported, not hidden

    def test_sampled_out_is_skipped_not_dropped(self, sample_config):
        tracing.configure(sample_rate=0.0)
        sp = SpanPusher("http://127.0.0.1:1", "s", "i")
        dropped0 = _counter("trace_spans_dropped_total")
        sp._enqueue(_rec())
        assert len(sp._q) == 0
        assert _counter("trace_spans_dropped_total") == dropped0

    def test_slow_span_tail_kept_despite_sampling(self, sample_config):
        """Keep-if-slow tail pass: with head sampling at 0, a span over
        -trace.slowThreshold is still enqueued and counted."""
        thresh = tracing.slow_threshold()
        tracing.configure(sample_rate=0.0, slow_threshold=0.5)
        try:
            sp = SpanPusher("http://127.0.0.1:1", "s", "i")
            kept0 = _counter("trace_push_tail_kept_total")
            sp._enqueue(_rec(duration=0.1))    # fast: sampled out
            assert len(sp._q) == 0
            sp._enqueue(_rec(duration=0.7))    # slow: tail-kept
            assert len(sp._q) == 1
            assert _counter("trace_push_tail_kept_total") == kept0 + 1
            # a disabled threshold (<= 0) disables the tail pass too
            tracing.configure(slow_threshold=0.0)
            sp._enqueue(_rec(duration=99.0))
            assert len(sp._q) == 1
        finally:
            tracing.configure(slow_threshold=thresh)

    def test_tail_keep_not_counted_when_head_sampled(self, sample_config):
        """A slow span whose trace IS head-sampled rides the normal
        path — the tail counter only counts rescues."""
        thresh = tracing.slow_threshold()
        tracing.configure(sample_rate=1.0, slow_threshold=0.5)
        try:
            sp = SpanPusher("http://127.0.0.1:1", "s", "i")
            kept0 = _counter("trace_push_tail_kept_total")
            sp._enqueue(_rec(duration=0.7))
            assert len(sp._q) == 1
            assert _counter("trace_push_tail_kept_total") == kept0
        finally:
            tracing.configure(slow_threshold=thresh)

    def test_stop_before_start_is_safe(self):
        SpanPusher("http://127.0.0.1:1", "s", "i").stop()


# ---------------------------------------------------------------------
# metrics pushgateway thread lifecycle (satellite fix)
# ---------------------------------------------------------------------


class TestMetricsPushThread:
    def test_stop_before_start_is_noop(self):
        metrics.stop_push()
        metrics.stop_push()

    def test_start_stop_start_cycle(self):
        metrics.start_push("127.0.0.1:1", "t", interval_seconds=3600)
        first = metrics._push_thread
        assert first is not None and first.is_alive()
        # idempotent while alive
        metrics.start_push("127.0.0.1:1", "t", interval_seconds=3600)
        assert metrics._push_thread is first
        metrics.stop_push()
        assert metrics._push_thread is None
        assert not first.is_alive()
        metrics.start_push("127.0.0.1:1", "t2", interval_seconds=3600)
        second = metrics._push_thread
        assert second is not None and second is not first
        metrics.stop_push()
        assert not second.is_alive()
