"""ArangoDB filer store over the raw HTTP API, against the in-process
mini-arango (tests/miniarango.py) — REST store family #8. Reference
slot: /root/reference/weed/filer/arangodb/arangodb_store.go:23.
"""
import time

import pytest

from seaweedfs_tpu.filer.arangodb_store import (DEFAULT_COLLECTION,
                                                ArangodbStore)
from seaweedfs_tpu.filer.entry import Entry, FileChunk
from seaweedfs_tpu.filer.filer import Filer

from .miniarango import MiniArango


@pytest.fixture(scope="module")
def arango():
    s = MiniArango()
    yield s
    s.close()


@pytest.fixture()
def store(arango):
    with arango.lock:
        arango.collections.clear()
    s = ArangodbStore(port=arango.port)
    yield s
    s.close()


def ent(path, size=0):
    chunks = [FileChunk(fid="1,ab", offset=0, size=size,
                        mtime_ns=time.time_ns())] if size else []
    return Entry(full_path=path, chunks=chunks)


def test_insert_find_update_delete(store):
    store.insert_entry(ent("/a/b.txt", 10))
    assert store.find_entry("/a/b.txt").file_size == 10
    store.update_entry(ent("/a/b.txt", 20))  # overwriteMode=replace
    assert store.find_entry("/a/b.txt").file_size == 20
    store.delete_entry("/a/b.txt")
    assert store.find_entry("/a/b.txt") is None


def test_bucket_paths_get_own_collection(store, arango):
    store.insert_entry(ent("/buckets/photos/cat.jpg", 3))
    store.insert_entry(ent("/plain/file.txt"))
    assert "seaweedfs_photos" in arango.collections
    assert store.find_entry("/buckets/photos/cat.jpg").file_size == 3
    # non-bucket paths share the default collection
    assert any(d.get("name") == "file.txt" for d in
               arango.collections[DEFAULT_COLLECTION].values())


def test_listing_order_pagination_prefix(store):
    for n in ("zeta", "alpha", "beta", "beta2", "gamma"):
        store.insert_entry(ent(f"/dir/{n}"))
    store.insert_entry(ent("/dir/beta/child"))
    names = [e.name for e in store.list_directory_entries("/dir")]
    assert names == ["alpha", "beta", "beta2", "gamma", "zeta"]
    page = store.list_directory_entries("/dir", start_from="beta",
                                        inclusive=False, limit=2)
    assert [e.name for e in page] == ["beta2", "gamma"]
    pref = store.list_directory_entries("/dir", prefix="beta")
    assert [e.name for e in pref] == ["beta", "beta2"]


def test_cursor_batching(store, arango):
    arango.batch = 10  # force hasMore continuation PUTs
    try:
        for i in range(35):
            store.insert_entry(ent(f"/big/f{i:03d}"))
        names = [e.name for e in
                 store.list_directory_entries("/big", limit=100)]
        assert names == [f"f{i:03d}" for i in range(35)]
    finally:
        arango.batch = 1000


def test_delete_folder_children_subtree(store):
    for p in ("/t/a", "/t/sub/x", "/t/sub/deep/y", "/tother/z"):
        store.insert_entry(ent(p))
    store.delete_folder_children("/t")
    for p in ("/t/a", "/t/sub/x", "/t/sub/deep/y"):
        assert store.find_entry(p) is None, p
    assert store.find_entry("/tother/z") is not None


def test_subtree_delete_spans_bucket_collections(store):
    store.insert_entry(ent("/buckets/b1/x"))
    store.insert_entry(ent("/buckets/b2/y"))
    store.delete_folder_children("/buckets")
    assert store.find_entry("/buckets/b1/x") is None
    assert store.find_entry("/buckets/b2/y") is None


def test_kv(store):
    store.kv_put("conf", b"\x00\x01binary")
    assert store.kv_get("conf") == b"\x00\x01binary"
    store.kv_delete("conf")
    assert store.kv_get("conf") is None


def test_basic_auth():
    s = MiniArango(username="weed", password="pw")
    try:
        st = ArangodbStore(port=s.port, user="weed", password="pw")
        st.kv_put("k", b"v")
        assert st.kv_get("k") == b"v"
        st.close()
        import requests

        with pytest.raises(requests.HTTPError):
            ArangodbStore(port=s.port, user="weed", password="wrong")
    finally:
        s.close()


def test_full_filer_stack(arango):
    with arango.lock:
        arango.collections.clear()
    f = Filer("arangodb", port=arango.port)
    try:
        f.create_entry(ent("/docs/readme.md", 5))
        assert f.find_entry("/docs/readme.md").file_size == 5
        assert [e.name for e in f.list_entries("/docs")] == ["readme.md"]
        f.delete_entry("/docs", recursive=True)
        assert f.find_entry("/docs/readme.md") is None
    finally:
        f.close()


def test_dashed_bucket_names(store, arango):
    # '-' is an AQL operator: collection names must be backtick-quoted
    # in every query (arangodb_store.go:299 does the same)
    store.insert_entry(ent("/buckets/my-bucket/obj.bin", 7))
    assert "seaweedfs_my-bucket" in arango.collections
    got = store.list_directory_entries("/buckets/my-bucket")
    assert [e.name for e in got] == ["obj.bin"]
    store.delete_folder_children("/buckets/my-bucket")
    assert store.find_entry("/buckets/my-bucket/obj.bin") is None


def test_bucket_dir_entry_lists_and_drops_collection(store, arango):
    # the bucket DIR entry lives in the default collection so that
    # listing /buckets works (helpers.go extractBucket >= 3 slashes)
    store.insert_entry(Entry(full_path="/buckets/pix", mode=0o40755))
    store.insert_entry(ent("/buckets/pix/a.jpg"))
    assert [e.name for e in
            store.list_directory_entries("/buckets")] == ["pix"]
    # deleting the bucket dir drops its collection (OnBucketDeletion)
    store.delete_folder_children("/buckets/pix")
    store.delete_entry("/buckets/pix")
    assert "seaweedfs_pix" not in arango.collections
    assert store.list_directory_entries("/buckets") == []
