"""In-flight byte accounting + cond-var backpressure on the volume
server (volume_server.go:24-28 inFlightUpload/DownloadDataSize).
"""
import asyncio
import threading

import pytest
import requests

from seaweedfs_tpu.operation import verbs
from seaweedfs_tpu.server.cluster import Cluster
from seaweedfs_tpu.server.volume_server import InFlightLimiter


class TestLimiter:
    def _run(self, coro):
        return asyncio.new_event_loop().run_until_complete(coro)

    def test_admits_under_limit(self):
        async def go():
            lim = InFlightLimiter(100)
            assert await lim.wait_admit()
            lim.add(80)
            assert await lim.wait_admit()  # 80 <= 100
            lim.add(80)
            # now over limit: next waiter times out
            lim.timeout = 0.2
            assert not await lim.wait_admit()
            await lim.release(80)
            assert await lim.wait_admit()
        self._run(go())

    def test_waiter_wakes_on_release(self):
        async def go():
            lim = InFlightLimiter(10, timeout=5)
            lim.add(50)
            results = []

            async def waiter():
                results.append(await lim.wait_admit())

            task = asyncio.ensure_future(waiter())
            await asyncio.sleep(0.05)
            assert not task.done()  # parked on the condition
            await lim.release(50)
            await asyncio.wait_for(task, 2)
            assert results == [True]
        self._run(go())

    def test_unlimited_mode_accounts_only(self):
        async def go():
            lim = InFlightLimiter(0)
            lim.add(1 << 40)
            assert await lim.wait_admit()  # never blocks
            await lim.release(1 << 40)
            assert lim.value == 0
        self._run(go())


class TestEndToEnd:
    @pytest.fixture(scope="class")
    def cluster(self, tmp_path_factory):
        c = Cluster(str(tmp_path_factory.mktemp("ifl")),
                    n_volume_servers=1, volume_size_limit=16 << 20)
        yield c
        c.stop()

    def test_normal_traffic_unaffected(self, cluster):
        a = verbs.assign(cluster.master_url)
        verbs.upload(a, b"x" * 100_000)
        assert verbs.download(f"http://{a.url}/{a.fid}") == b"x" * 100_000
        vs = cluster.volume_servers[0]
        assert vs._upload_flight.value == 0
        assert vs._download_flight.value == 0

    def test_over_limit_upload_rejected_after_timeout(self, cluster):
        vs = cluster.volume_servers[0]
        vs._upload_flight.limit = 10
        vs._upload_flight.timeout = 0.3
        vs._upload_flight.add(1000)  # simulate a huge in-flight body
        try:
            a = verbs.assign(cluster.master_url)
            r = requests.post(f"http://{a.url}/{a.fid}",
                              files={"file": ("x.bin", b"y" * 100)},
                              timeout=10)
            assert r.status_code == 429
        finally:
            vs._upload_flight.value -= 1000
            vs._upload_flight.limit = 256 << 20
            vs._upload_flight.timeout = 30.0

    def test_metrics_exported(self, cluster):
        m = requests.get(cluster.volume_url(0) + "/metrics").text
        assert "volume_server_in_flight_upload_bytes" in m
        assert "volume_server_in_flight_download_bytes" in m
