"""Race/recovery hardening for the round-5 native surfaces.

1. S3 front cache coherency under CONCURRENT mixed-path mutations:
   native PUTs racing python-path overwrites and deletes of the same
   keys must never serve stale or torn reads (the sync meta-listener
   contract of s3/native_front.py).
2. SWRP replica-channel recovery: a peer volume server killed and
   RESTARTED mid-load — the fan-out must fail loudly while the peer
   is down, then return to the native path (fresh connection, fresh
   upgrade handshake) once the control plane re-pushes peers.
"""
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import pytest
import requests

from seaweedfs_tpu.native import dataplane as dpmod
from seaweedfs_tpu.server.cluster import Cluster
from tests.s3v4client import S3V4Client

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(
    not dpmod.available(), reason="no g++ / prebuilt dataplane library")

AK, SK = "RACEAK", "RACESECRET"


def test_s3_front_concurrent_mixed_path_mutations(tmp_path):
    cfg = {"identities": [{"name": "race", "credentials": [
        {"accessKey": AK, "secretKey": SK}], "actions": ["Admin"]}]}
    c = Cluster(str(tmp_path), n_volume_servers=1,
                volume_size_limit=64 << 20, with_s3=True,
                s3_native=True, s3_config=cfg)
    try:
        s3 = S3V4Client(c.s3_url, AK, SK)
        assert s3.put("/race").status in (200, 409)
        deadline = time.time() + 10
        while time.time() < deadline and \
                c.s3_front.front.pool_level("race") == 0:
            time.sleep(0.05)
        assert c.s3_front.front.pool_level("race") > 0, \
            "fid pool never filled — warm-up/setup problem, not a race"
        errors: list[str] = []
        stop = threading.Event()
        KEYS = 8

        def s3_writer(tid):
            cli = S3V4Client(c.s3_url, AK, SK)
            i = 0
            while not stop.is_set():
                k = i % KEYS
                body = f"s3-{tid}-{i}".encode()
                try:
                    r = cli.put(f"/race/k{k}", body)
                except Exception as e:  # a dead thread = vacuous pass
                    errors.append(f"put exc {e!r}")
                    return
                if r.status != 200:
                    errors.append(f"put {r.status}")
                i += 1

        def filer_mutator():
            # overwrites + deletes through the PYTHON filer path: the
            # meta listener is the only thing keeping the C++ cache
            # honest about these. Statuses are CHECKED — if this arm
            # silently 4xx'd, the test would stress nothing
            sess = requests.Session()
            i = 0
            while not stop.is_set():
                k = i % KEYS
                try:
                    if i % 3 == 2:
                        r = sess.delete(
                            f"{c.filer_url}/buckets/race/k{k}",
                            timeout=20)
                        if r.status_code not in (200, 204, 404):
                            errors.append(
                                f"filer delete {r.status_code}")
                    else:
                        r = sess.post(
                            f"{c.filer_url}/buckets/race/k{k}",
                            data=f"py-{i}".encode(),
                            headers={"Content-Type":
                                     "application/octet-stream"},
                            timeout=20)
                        if r.status_code != 201:
                            errors.append(f"filer post {r.status_code}")
                except Exception as e:
                    errors.append(f"filer exc {e!r}")
                    return
                i += 1

        def reader():
            cli = S3V4Client(c.s3_url, AK, SK)
            while not stop.is_set():
                k = int(time.time() * 997) % KEYS
                try:
                    r = cli.get(f"/race/k{k}")
                except Exception as e:
                    errors.append(f"get exc {e!r}")
                    return
                if r.status == 200:
                    body = r.body
                    # every observable value must be a COMPLETE write
                    # from one of the two paths — torn/garbage bytes
                    # mean the cache served something no writer wrote
                    if not (body.startswith(b"s3-")
                            or body.startswith(b"py-")):
                        errors.append(f"torn read: {body[:40]!r}")
                elif r.status != 404:
                    errors.append(f"get {r.status}")

        threads = [threading.Thread(target=s3_writer, args=(t,))
                   for t in range(2)]
        threads += [threading.Thread(target=filer_mutator),
                    threading.Thread(target=reader),
                    threading.Thread(target=reader)]
        for t in threads:
            t.start()
        time.sleep(6)
        stop.set()
        for t in threads:
            t.join(timeout=20)
        assert not errors, errors[:5]
        # quiesce, then FINAL COHERENCY: for every key the native GET
        # must agree byte-for-byte with the filer (the store of record)
        time.sleep(0.5)
        for k in range(KEYS):
            f = requests.get(f"{c.filer_url}/buckets/race/k{k}")
            g = s3.get(f"/race/k{k}")
            if f.status_code == 404:
                assert g.status == 404, f"stale cache hit on k{k}"
            else:
                assert g.status == 200 and g.body == f.content, \
                    f"k{k}: cache {g.body[:30]!r} != filer " \
                    f"{f.content[:30]!r}"
        st = c.s3_front.stats()
        assert st["fast_put"] > 0 and st["fast_get"] > 0
        assert st["chan_fail"] == 0
    finally:
        c.stop()


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_http(url, timeout=30):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            requests.get(url, timeout=1)
            return
        except requests.RequestException:
            time.sleep(0.15)
    raise TimeoutError(url)


def test_swrp_peer_restart_recovers_native_path(tmp_path):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    procs = []

    def spawn(*argv):
        p = subprocess.Popen(
            [sys.executable, "-m", "seaweedfs_tpu", *argv], env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
        procs.append(p)
        return p

    try:
        mport, v1, v2 = _free_port(), _free_port(), _free_port()
        master = f"http://127.0.0.1:{mport}"
        (tmp_path / "v1").mkdir()
        (tmp_path / "v2").mkdir()
        spawn("master", "-port", str(mport), "-volumeSizeLimitMB", "64",
              "-defaultReplication", "001")
        _wait_http(f"{master}/cluster/status")
        spawn("volume", "-port", str(v1), "-dir", str(tmp_path / "v1"),
              "-mserver", f"127.0.0.1:{mport}", "-dataplane", "native")
        peer = spawn("volume", "-port", str(v2), "-dir",
                     str(tmp_path / "v2"),
                     "-mserver", f"127.0.0.1:{mport}",
                     "-dataplane", "native")
        _wait_http(f"http://127.0.0.1:{v1}/status")
        _wait_http(f"http://127.0.0.1:{v2}/status")

        def stats(port):
            return requests.get(f"http://127.0.0.1:{port}/status",
                                timeout=5).json()["native_dataplane"]

        def write_one(payload):
            a = requests.get(f"{master}/dir/assign?replication=001",
                             timeout=5).json()
            if "fid" not in a:
                return None, None  # master refused (peer fenced)
            try:
                r = requests.post(f"http://{a['url']}/{a['fid']}",
                                  data=payload, timeout=10)
            except requests.RequestException:
                return a, None  # primary itself unreachable
            return a, r

        # phase 1: wait until the native fan-out engages (SWRP upgrade)
        deadline = time.time() + 30
        while time.time() < deadline:
            write_one(b"warm")
            if stats(v1)["repl_post"] + stats(v2)["repl_post"] > 0:
                break
            time.sleep(0.3)
        else:
            pytest.fail("native fan-out never engaged")

        # phase 2: kill the peer hard mid-load. The contract: NO write
        # may be acked 2xx while its replica target is down — every
        # outcome must be loud (5xx from the primary's failed fan-out,
        # an unreachable primary, or the master fencing the dead node
        # and refusing the assign). Which one depends on how fast the
        # heartbeat notices; all are correct, a 201 is never.
        peer.kill()
        peer.wait(timeout=10)
        time.sleep(0.3)
        outcomes = set()
        deadline = time.time() + 10
        while time.time() < deadline:
            a, r = write_one(b"doomed")
            if a is None:
                outcomes.add("assign-refused")
            elif r is None:
                outcomes.add("primary-unreachable")
            elif r.status_code >= 500:
                outcomes.add("fanout-5xx")
            else:
                assert r.status_code != 201, \
                    "write acked 201 with its replica peer dead"
            time.sleep(0.2)
        assert outcomes, "no writes attempted while the peer was down"

        # phase 3: restart the peer on the SAME port+dir; the channel
        # must renegotiate (fresh conn + fresh .swrp upgrade) and the
        # native path must take over again
        spawn("volume", "-port", str(v2), "-dir", str(tmp_path / "v2"),
              "-mserver", f"127.0.0.1:{mport}", "-dataplane", "native")
        _wait_http(f"http://127.0.0.1:{v2}/status")
        base = stats(v1)["repl_post"] + stats(v2)["repl_post"]
        recovered = None
        deadline = time.time() + 40
        while time.time() < deadline:
            a, r = write_one(b"recovered-bytes")
            if r is not None and r.status_code == 201 and \
                    stats(v1)["repl_post"] + stats(v2)["repl_post"] > base:
                recovered = a
                break
            time.sleep(0.3)
        assert recovered, "native fan-out never re-engaged after restart"
        # both copies of the post-recovery write are readable
        for port in (v1, v2):
            g = requests.get(f"http://127.0.0.1:{port}/{recovered['fid']}",
                             timeout=5)
            assert g.status_code == 200 and g.content == b"recovered-bytes"
    finally:
        for p in reversed(procs):
            if p.poll() is None:
                p.send_signal(signal.SIGINT)
        for p in reversed(procs):
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
