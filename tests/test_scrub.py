"""Cluster scrub: volume.scrub full-read CRC verification and
ec.verify parity checking of spread shards (the two arms of BASELINE
config #5 as operator verbs).
"""
import secrets

import numpy as np
import pytest

from seaweedfs_tpu.operation import verbs
from seaweedfs_tpu.rpc.httpclient import session
from seaweedfs_tpu.server.cluster import Cluster
from seaweedfs_tpu.shell import commands_ec, commands_volume
from seaweedfs_tpu.shell.env import CommandEnv


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = Cluster(str(tmp_path_factory.mktemp("scrub")),
                n_volume_servers=3, volume_size_limit=4 << 20,
                max_volumes=40)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def env(cluster):
    e = CommandEnv(cluster.master_url)
    e.acquire_lock()
    return e


def fill_volume(cluster, col, n=20, size=4096, replication=""):
    rng = np.random.default_rng(1)
    a0 = verbs.assign(cluster.master_url, collection=col,
                      replication=replication)
    vid = int(a0.fid.split(",")[0])
    verbs.upload(a0, rng.bytes(size))
    for _ in range(n - 1):
        a = verbs.assign(cluster.master_url, collection=col,
                         replication=replication)
        verbs.upload(a, rng.bytes(size))
    return vid


def repair_pending(cluster) -> set:
    r = session().get(cluster.master_url + "/debug/repair",
                     timeout=30).json()
    return {(p["volume"], p["kind"]) for p in r["pending"]}


class TestVolumeScrub:
    def test_clean_volume_scrubs_clean(self, cluster, env):
        col = "sc" + secrets.token_hex(3)
        vid = fill_volume(cluster, col)
        out = commands_volume.volume_scrub(env, volume_id=vid)
        assert out and all(r["bad"] == [] for r in out)
        assert sum(r["checked"] for r in out) >= 1

    def test_corruption_detected(self, cluster, env):
        col = "bad" + secrets.token_hex(3)
        vid = fill_volume(cluster, col, n=8)
        # flip a data byte on the primary's .dat behind the server's back
        store = next(s for s in cluster.stores
                     if s.find_volume(vid) is not None)
        v = store.find_volume(vid)
        key, off, size = next(v.nm.live_items())
        from seaweedfs_tpu.storage import types as t
        byte_off = t.offset_to_actual(off) + t.NEEDLE_HEADER_SIZE + 2
        orig = v.dat.read_at(1, byte_off)
        v.dat.write_at(bytes([orig[0] ^ 0xFF]), byte_off)
        out = commands_volume.volume_scrub(env, volume_id=vid)
        bad = [b for r in out for b in r["bad"]]
        assert any(b["id"] == key for b in bad)
        # single replica: quarantine can only freeze it (readonly) —
        # dropping the last copy would lose the healthy needles too
        q = [r["quarantine"] for r in out if r.get("bad")]
        assert q and q[0]["action"] == "readonly"
        assert not q[0]["repair_enqueued"]
        # restore so other tests aren't poisoned
        v.dat.write_at(orig, byte_off)

    def test_corrupt_replica_quarantined_and_repair_enqueued(
            self, cluster, env):
        col = "qr" + secrets.token_hex(3)
        vid = fill_volume(cluster, col, n=6, replication="001")
        locs = set(env.volume_locations(vid))
        assert len(locs) == 2
        store = next(s for s in cluster.stores
                     if s.find_volume(vid) is not None)
        corrupt_url = store.public_url
        v = store.find_volume(vid)
        key, off, size = next(v.nm.live_items())
        from seaweedfs_tpu.storage import types as t
        byte_off = t.offset_to_actual(off) + t.NEEDLE_HEADER_SIZE + 2
        orig = v.dat.read_at(1, byte_off)
        v.dat.write_at(bytes([orig[0] ^ 0xFF]), byte_off)
        out = commands_volume.volume_scrub(env, volume_id=vid)
        q = [r for r in out if r.get("bad")]
        assert len(q) == 1 and q[0]["server"] == corrupt_url
        assert q[0]["quarantine"]["action"] == "unmounted"
        assert q[0]["quarantine"]["repair_enqueued"] is True
        # the corrupt replica left the topology; the healthy one serves
        import time
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if corrupt_url not in env.volume_locations(vid):
                break
            time.sleep(0.1)
        assert corrupt_url not in env.volume_locations(vid)
        # and the loss is on the master's repair queue as pending work
        assert (vid, "replica") in repair_pending(cluster)

    def test_scrub_report_only_mode(self, cluster, env):
        col = "ro" + secrets.token_hex(3)
        vid = fill_volume(cluster, col, n=4)
        store = next(s for s in cluster.stores
                     if s.find_volume(vid) is not None)
        v = store.find_volume(vid)
        key, off, size = next(v.nm.live_items())
        from seaweedfs_tpu.storage import types as t
        byte_off = t.offset_to_actual(off) + t.NEEDLE_HEADER_SIZE + 2
        orig = v.dat.read_at(1, byte_off)
        v.dat.write_at(bytes([orig[0] ^ 0xFF]), byte_off)
        try:
            out = commands_volume.volume_scrub(env, volume_id=vid,
                                               quarantine=False)
            assert any(r["bad"] for r in out)
            assert all("quarantine" not in r for r in out)
        finally:
            v.dat.write_at(orig, byte_off)

    def test_scrub_all_with_limit(self, cluster, env):
        out = commands_volume.volume_scrub(env, limit=3)
        assert all(r["checked"] <= 3 for r in out)


class TestEcVerify:
    def test_verify_after_encode(self, cluster, env):
        col = "ev" + secrets.token_hex(3)
        vid = fill_volume(cluster, col, n=12, size=8192)
        commands_ec.ec_encode(env, vid)
        out = commands_ec.ec_verify(env, vid, sample_mb=1)
        assert out["verified"] is True
        assert out["bytes_checked_per_shard"] > 0

    def test_verify_detects_shard_corruption(self, cluster, env):
        col = "evc" + secrets.token_hex(3)
        vid = fill_volume(cluster, col, n=12, size=8192)
        commands_ec.ec_encode(env, vid)
        # corrupt one mounted shard's bytes directly
        ecv = next(s.ec_volumes[vid] for s in cluster.stores
                   if vid in s.ec_volumes)
        sid, shard = next(iter(ecv.shards.items()))
        orig = shard.read_at(10, 1)
        with open(shard.path, "r+b") as f:
            f.seek(10)
            f.write(bytes([orig[0] ^ 0x5A]))
        try:
            out = commands_ec.ec_verify(env, vid, sample_mb=1,
                                        quarantine=False)
            assert out["verified"] is False
        finally:
            with open(shard.path, "r+b") as f:
                f.seek(10)
                f.write(orig)

    def test_corrupt_shard_quarantined_and_rebuild_enqueued(
            self, cluster, env):
        col = "evq" + secrets.token_hex(3)
        vid = fill_volume(cluster, col, n=12, size=8192)
        commands_ec.ec_encode(env, vid)
        ecv = next(s.ec_volumes[vid] for s in cluster.stores
                   if vid in s.ec_volumes)
        sid, shard = next(iter(ecv.shards.items()))
        orig = shard.read_at(10, 1)
        with open(shard.path, "r+b") as f:
            f.seek(10)
            f.write(bytes([orig[0] ^ 0x5A]))
        out = commands_ec.ec_verify(env, vid, sample_mb=1)
        assert out["verified"] is False
        assert out["corrupt_shard"] == sid
        assert out["quarantined"] is True
        assert out["repair_enqueued"] is True
        # the corrupt shard is gone from its holder and the rebuild is
        # pending on the master's repair queue
        import time
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if sid not in env.ec_shard_locations(vid):
                break
            time.sleep(0.1)
        assert sid not in env.ec_shard_locations(vid)
        assert (vid, "ec") in repair_pending(cluster)
        # still recoverable: 13 of 14 shards live
        live = sum(len(u) for u in env.ec_shard_locations(vid).values())
        assert live == 13

    def test_missing_shards_reported(self, env):
        out = commands_ec.ec_verify(env, 999_999)
        assert out["verified"] is False and out["missing_shards"]
