"""Cluster scrub: volume.scrub full-read CRC verification and
ec.verify parity checking of spread shards (the two arms of BASELINE
config #5 as operator verbs).
"""
import secrets

import numpy as np
import pytest

from seaweedfs_tpu.operation import verbs
from seaweedfs_tpu.server.cluster import Cluster
from seaweedfs_tpu.shell import commands_ec, commands_volume
from seaweedfs_tpu.shell.env import CommandEnv


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = Cluster(str(tmp_path_factory.mktemp("scrub")),
                n_volume_servers=3, volume_size_limit=4 << 20,
                max_volumes=40)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def env(cluster):
    e = CommandEnv(cluster.master_url)
    e.acquire_lock()
    return e


def fill_volume(cluster, col, n=20, size=4096):
    rng = np.random.default_rng(1)
    a0 = verbs.assign(cluster.master_url, collection=col)
    vid = int(a0.fid.split(",")[0])
    verbs.upload(a0, rng.bytes(size))
    for _ in range(n - 1):
        a = verbs.assign(cluster.master_url, collection=col)
        verbs.upload(a, rng.bytes(size))
    return vid


class TestVolumeScrub:
    def test_clean_volume_scrubs_clean(self, cluster, env):
        col = "sc" + secrets.token_hex(3)
        vid = fill_volume(cluster, col)
        out = commands_volume.volume_scrub(env, volume_id=vid)
        assert out and all(r["bad"] == [] for r in out)
        assert sum(r["checked"] for r in out) >= 1

    def test_corruption_detected(self, cluster, env):
        col = "bad" + secrets.token_hex(3)
        vid = fill_volume(cluster, col, n=8)
        # flip a data byte on the primary's .dat behind the server's back
        store = next(s for s in cluster.stores
                     if s.find_volume(vid) is not None)
        v = store.find_volume(vid)
        key, off, size = next(v.nm.live_items())
        from seaweedfs_tpu.storage import types as t
        byte_off = t.offset_to_actual(off) + t.NEEDLE_HEADER_SIZE + 2
        orig = v.dat.read_at(1, byte_off)
        v.dat.write_at(bytes([orig[0] ^ 0xFF]), byte_off)
        out = commands_volume.volume_scrub(env, volume_id=vid)
        bad = [b for r in out for b in r["bad"]]
        assert any(b["id"] == key for b in bad)
        # restore so other tests aren't poisoned
        v.dat.write_at(orig, byte_off)

    def test_scrub_all_with_limit(self, cluster, env):
        out = commands_volume.volume_scrub(env, limit=3)
        assert all(r["checked"] <= 3 for r in out)


class TestEcVerify:
    def test_verify_after_encode(self, cluster, env):
        col = "ev" + secrets.token_hex(3)
        vid = fill_volume(cluster, col, n=12, size=8192)
        commands_ec.ec_encode(env, vid)
        out = commands_ec.ec_verify(env, vid, sample_mb=1)
        assert out["verified"] is True
        assert out["bytes_checked_per_shard"] > 0

    def test_verify_detects_shard_corruption(self, cluster, env):
        col = "evc" + secrets.token_hex(3)
        vid = fill_volume(cluster, col, n=12, size=8192)
        commands_ec.ec_encode(env, vid)
        # corrupt one mounted shard's bytes directly
        ecv = next(s.ec_volumes[vid] for s in cluster.stores
                   if vid in s.ec_volumes)
        sid, shard = next(iter(ecv.shards.items()))
        orig = shard.read_at(10, 1)
        with open(shard.path, "r+b") as f:
            f.seek(10)
            f.write(bytes([orig[0] ^ 0x5A]))
        try:
            out = commands_ec.ec_verify(env, vid, sample_mb=1)
            assert out["verified"] is False
        finally:
            with open(shard.path, "r+b") as f:
                f.seek(10)
                f.write(orig)

    def test_missing_shards_reported(self, env):
        out = commands_ec.ec_verify(env, 999_999)
        assert out["verified"] is False and out["missing_shards"]
