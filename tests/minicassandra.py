"""Minimal cassandra double speaking CQL binary protocol v4.

Implements exactly the statement shapes the cassandra filer store
issues — USE, INSERT ... USING TTL, point SELECT, range SELECT with
LIMIT, partition/point DELETE — over real v4 frames (STARTUP, optional
PLAIN auth, PREPARE/EXECUTE, RESULT rows with global_tables_spec
metadata). The miniredis / minietcd / minimongo role for the CQL wire.
Row TTLs expire like the real server's (checked lazily on read).
"""
from __future__ import annotations

import hashlib
import re
import socket
import struct
import threading
import time

from seaweedfs_tpu.filer import cql_lite as cql

VARCHAR, BLOB, INT = 0x0D, 0x03, 0x09


class MiniCassandra:
    def __init__(self, username: str = "", password: str = ""):
        self.username = username
        self.password = password
        # {directory: {name: (meta bytes, expire_at or None)}}
        self.data: dict[str, dict[str, tuple[bytes, float | None]]] = {}
        self.prepared: dict[bytes, str] = {}
        self.lock = threading.Lock()
        self.queries: list[str] = []
        self.warn_with: list[str] = []  # attach v4 warnings to replies
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]
        self._stop = False
        threading.Thread(target=self._accept, daemon=True).start()

    def close(self) -> None:
        self._stop = True
        try:
            self._srv.close()
        except OSError:
            pass

    # -- plumbing -------------------------------------------------------
    def _accept(self) -> None:
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            while True:
                hdr = self._recv_exact(conn, 9)
                if hdr is None:
                    return
                _ver, _fl, stream, opcode, length = struct.unpack(
                    ">BBhBI", hdr)
                body = self._recv_exact(conn, length) or b""
                resp_op, resp_body = self._handle(conn, stream, opcode,
                                                  body)
                if resp_op is not None:
                    self._send(conn, stream, resp_op, resp_body)
        except (OSError, IOError):
            pass
        finally:
            conn.close()

    @staticmethod
    def _recv_exact(conn, n):
        out = b""
        while len(out) < n:
            piece = conn.recv(n - len(out))
            if not piece:
                return None
            out += piece
        return out

    def _send(self, conn, stream, opcode, body):
        flags = 0
        if self.warn_with:
            # v4 warning flag: [string list] of warnings prefixes the
            # body (real cassandra does this for tombstone scans)
            warns = struct.pack(">H", len(self.warn_with))
            for w in self.warn_with:
                wb = w.encode()
                warns += struct.pack(">H", len(wb)) + wb
            body = warns + body
            flags |= 0x08
        conn.sendall(struct.pack(">BBhBI", 0x84, flags, stream, opcode,
                                 len(body)) + body)

    # -- protocol -------------------------------------------------------
    def _handle(self, conn, stream, opcode, body):
        if opcode == cql.OP_OPTIONS:
            return cql.OP_SUPPORTED, struct.pack(">H", 0)
        if opcode == cql.OP_STARTUP:
            if self.username:
                return (cql.OP_AUTHENTICATE, cql.enc_string(
                    "org.apache.cassandra.auth.PasswordAuthenticator"))
            return cql.OP_READY, b""
        if opcode == cql.OP_AUTH_RESPONSE:
            r = cql._Reader(body)
            token = r.bytes_() or b""
            parts = token.split(b"\x00")
            if len(parts) == 3 and parts[1].decode() == self.username \
                    and parts[2].decode() == self.password:
                return cql.OP_AUTH_SUCCESS, struct.pack(">i", -1)
            return cql.OP_ERROR, (struct.pack(">i", 0x0100) +
                                  cql.enc_string("bad credentials"))
        if opcode == cql.OP_PREPARE:
            r = cql._Reader(body)
            q = r.take(r.i32()).decode()
            stmt_id = hashlib.md5(q.encode()).digest()
            with self.lock:
                self.prepared[stmt_id] = q
            # RESULT kind=prepared: id + v4 metadata (flags, cols, pk)
            meta = struct.pack(">iii", 0, q.count("?"), 0)
            return cql.OP_RESULT, (struct.pack(">i", cql.RESULT_PREPARED)
                                   + struct.pack(">H", 16) + stmt_id
                                   + meta + struct.pack(">ii", 0x0004, 0))
        if opcode in (cql.OP_QUERY, cql.OP_EXECUTE):
            r = cql._Reader(body)
            if opcode == cql.OP_QUERY:
                q = r.take(r.i32()).decode()
            else:
                stmt_id = r.short_bytes()
                with self.lock:
                    q = self.prepared.get(stmt_id, "")
                if not q:
                    return cql.OP_ERROR, (struct.pack(">i", 0x2500) +
                                          cql.enc_string("unprepared"))
            _consistency = r.u16()
            flags = r.u8()
            values: list[bytes | None] = []
            if flags & 0x01:
                for _ in range(r.u16()):
                    values.append(r.bytes_())
            try:
                return self._run(q, values)
            except Exception as e:  # malformed statement = server error
                return cql.OP_ERROR, (struct.pack(">i", 0x0000) +
                                      cql.enc_string(str(e)))
        return cql.OP_ERROR, (struct.pack(">i", 0x000A) +
                              cql.enc_string(f"bad opcode {opcode}"))

    # -- statement engine ----------------------------------------------
    @staticmethod
    def _rows(names_types, rows):
        out = struct.pack(">i", cql.RESULT_ROWS)
        out += struct.pack(">ii", 0x0001, len(names_types))  # global spec
        out += cql.enc_string("ks") + cql.enc_string("filemeta")
        for name, tid in names_types:
            out += cql.enc_string(name) + struct.pack(">H", tid)
        out += struct.pack(">i", len(rows))
        for row in rows:
            for cell in row:
                out += cql.enc_bytes(cell)
        return cql.OP_RESULT, out

    VOID = struct.pack(">i", cql.RESULT_VOID)

    def _live(self, d: str):
        now = time.time()
        part = self.data.get(d, {})
        return {n: m for n, (m, exp) in part.items()
                if exp is None or exp > now}

    def _run(self, q: str, values):
        self.queries.append(q)
        qs = q.strip().rstrip(";").strip()
        with self.lock:
            if re.fullmatch(r'USE\s+"?\w+"?', qs, re.I):
                return cql.OP_RESULT, (
                    struct.pack(">i", cql.RESULT_SET_KEYSPACE) +
                    cql.enc_string("ks"))
            if qs.upper().startswith("INSERT INTO FILEMETA"):
                d = (values[0] or b"").decode()
                n = (values[1] or b"").decode()
                meta = values[2] or b""
                ttl = struct.unpack(">i", values[3])[0] if values[3] \
                    else 0
                exp = time.time() + ttl if ttl > 0 else None
                self.data.setdefault(d, {})[n] = (meta, exp)
                return cql.OP_RESULT, self.VOID
            m = re.fullmatch(
                r"SELECT meta FROM filemeta WHERE directory=\? "
                r"AND name=\?", qs, re.I)
            if m:
                d = (values[0] or b"").decode()
                n = (values[1] or b"").decode()
                live = self._live(d)
                rows = [[live[n]]] if n in live else []
                return self._rows([("meta", BLOB)], rows)
            m = re.fullmatch(
                r"SELECT name, meta FROM filemeta WHERE directory=\? "
                r"AND name(>=|>)\? LIMIT \?", qs, re.I)
            if m:
                op = m.group(1)
                d = (values[0] or b"").decode()
                start = (values[1] or b"").decode()
                limit = struct.unpack(">i", values[2])[0]
                live = self._live(d)
                names = sorted(n for n in live
                               if (n >= start if op == ">=" else
                                   n > start))
                rows = [[n.encode(), live[n]] for n in names[:limit]]
                return self._rows([("name", VARCHAR), ("meta", BLOB)],
                                  rows)
            if re.fullmatch(r"DELETE FROM filemeta WHERE directory=\? "
                            r"AND name=\?", qs, re.I):
                d = (values[0] or b"").decode()
                n = (values[1] or b"").decode()
                self.data.get(d, {}).pop(n, None)
                return cql.OP_RESULT, self.VOID
            if re.fullmatch(r"DELETE FROM filemeta WHERE directory=\?",
                            qs, re.I):
                self.data.pop((values[0] or b"").decode(), None)
                return cql.OP_RESULT, self.VOID
        raise ValueError(f"mini-cassandra: unsupported statement {q!r}")
