"""On-read image resizing (reference weed/images/resizing.go + the
volume read hook at volume_server_handlers_read.go:294).
"""
import io

import pytest
import requests

from seaweedfs_tpu import images
from seaweedfs_tpu.operation import verbs
from seaweedfs_tpu.server.cluster import Cluster

PIL = pytest.importorskip("PIL")
from PIL import Image  # noqa: E402


def png_bytes(w, h, color=(200, 30, 30)):
    buf = io.BytesIO()
    Image.new("RGB", (w, h), color).save(buf, format="PNG")
    return buf.getvalue()


class TestResized:
    def test_exact_resize(self):
        out = images.resized(png_bytes(100, 50), "image/png", 40, 40)
        assert Image.open(io.BytesIO(out)).size == (40, 40)

    def test_single_dim_keeps_ratio(self):
        out = images.resized(png_bytes(100, 50), "image/png", width=50)
        assert Image.open(io.BytesIO(out)).size == (50, 25)

    def test_fit_mode(self):
        out = images.resized(png_bytes(100, 50), "image/png", 40, 40,
                             "fit")
        assert Image.open(io.BytesIO(out)).size == (40, 20)

    def test_fill_mode_crops(self):
        out = images.resized(png_bytes(100, 50), "image/png", 40, 40,
                             "fill")
        assert Image.open(io.BytesIO(out)).size == (40, 40)

    def test_non_image_mime_passthrough(self):
        data = b"plain text"
        assert images.resized(data, "text/plain", 10, 10) is data

    def test_undecodable_passthrough(self):
        data = b"not a png"
        assert images.resized(data, "image/png", 10, 10) is data

    def test_no_dims_passthrough(self):
        data = png_bytes(10, 10)
        assert images.resized(data, "image/png") is data


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = Cluster(str(tmp_path_factory.mktemp("img_cluster")),
                n_volume_servers=1, volume_size_limit=16 << 20)
    yield c
    c.stop()


class TestReadHook:
    def test_resized_on_get(self, cluster):
        a = verbs.assign(cluster.master_url)
        verbs.upload(a, png_bytes(120, 80), name="pic.png",
                     mime="image/png")
        r = requests.get(f"http://{a.url}/{a.fid}",
                         params={"width": 30, "height": 30,
                                 "mode": "fill"})
        assert r.status_code == 200
        assert Image.open(io.BytesIO(r.content)).size == (30, 30)
        # original untouched
        r2 = requests.get(f"http://{a.url}/{a.fid}")
        assert Image.open(io.BytesIO(r2.content)).size == (120, 80)


class TestCrop:
    def test_cropped_unit(self):
        data = png_bytes(40, 30)
        out = images.cropped(data, "image/png", 5, 5, 25, 20)
        assert Image.open(io.BytesIO(out)).size == (20, 15)
        # out-of-bounds rectangle: original bytes (cropping.go:24)
        assert images.cropped(data, "image/png", 0, 0, 400, 300) is data
        # non-croppable mime (reference crops png/jpeg/gif only)
        assert images.cropped(data, "image/webp", 0, 0, 10, 10) is data

    def test_crop_then_resize_on_get(self, cluster):
        a = verbs.assign(cluster.master_url)
        verbs.upload(a, png_bytes(100, 60), name="crop.png",
                     mime="image/png")
        r = requests.get(f"http://{a.url}/{a.fid}",
                         params={"crop_x1": 10, "crop_y1": 10,
                                 "crop_x2": 50, "crop_y2": 40})
        assert r.status_code == 200
        assert Image.open(io.BytesIO(r.content)).size == (40, 30)
        # chained with resize: crop first, then scale (reference order)
        r2 = requests.get(f"http://{a.url}/{a.fid}",
                          params={"crop_x1": 0, "crop_y1": 0,
                                  "crop_x2": 50, "crop_y2": 30,
                                  "width": 25, "height": 15})
        assert Image.open(io.BytesIO(r2.content)).size == (25, 15)

    def test_negative_origin_clamped(self):
        data = png_bytes(40, 30)
        out = images.cropped(data, "image/png", -10, -5, 20, 20)
        # origin clamps to (0,0): no black padding is fabricated
        assert Image.open(io.BytesIO(out)).size == (20, 20)
